"""Autograd tests (ref: tests/python/unittest/test_autograd.py)."""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, autograd


def test_simple_backward():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 2 * x.asnumpy())


def test_chain_and_broadcast():
    x = nd.array(np.random.RandomState(0).rand(3, 4).astype("float32"))
    w = nd.array(np.random.RandomState(1).rand(4, 2).astype("float32"))
    x.attach_grad()
    w.attach_grad()
    with autograd.record():
        y = nd.dot(x, w)
        z = nd.relu(y - 0.5).sum()
    z.backward()
    mask = (np.dot(x.asnumpy(), w.asnumpy()) - 0.5 > 0).astype("float32")
    np.testing.assert_allclose(x.grad.asnumpy(),
                               np.dot(mask, w.asnumpy().T), rtol=1e-5)
    np.testing.assert_allclose(w.grad.asnumpy(),
                               np.dot(x.asnumpy().T, mask), rtol=1e-5)


def test_grad_req_add():
    x = nd.array([1.0, 2.0])
    grad = nd.zeros((2,))
    autograd.mark_variables([x], [grad], "add")
    for _ in range(3):
        with autograd.record():
            y = (x * x).sum()
        y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 3 * 2 * x.asnumpy())


def test_record_pause():
    x = nd.array([1.0])
    x.attach_grad()
    with autograd.record():
        assert autograd.is_recording()
        assert autograd.is_training()
        with autograd.pause():
            assert not autograd.is_recording()
        y = x * 2
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [2.0])


def test_train_predict_mode():
    with autograd.record(train_mode=False):
        assert not autograd.is_training()
        with autograd.train_mode():
            assert autograd.is_training()
    with autograd.predict_mode():
        assert not autograd.is_training()


def test_head_gradient():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = x * 4
    y.backward(nd.array([1.0, 0.5, 0.25]))
    np.testing.assert_allclose(x.grad.asnumpy(), [4.0, 2.0, 1.0])


def test_autograd_grad_api():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x * x
    (g,) = autograd.grad(y, [x])
    np.testing.assert_allclose(g.asnumpy(), [12.0])


def test_multi_output_op_backward():
    x = nd.array(np.arange(8, dtype="float32").reshape(2, 4))
    x.attach_grad()
    with autograd.record():
        a, b = nd.split(x, num_outputs=2, axis=1)
        y = (a * 2 + b * 3).sum()
    y.backward()
    expect = np.concatenate([2 * np.ones((2, 2)), 3 * np.ones((2, 2))], 1)
    np.testing.assert_allclose(x.grad.asnumpy(), expect)


def test_detach_and_stop_gradient():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        z = nd.BlockGrad(y) + x
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [1.0])


def test_dropout_modes():
    x = nd.ones((100, 100))
    with autograd.record(train_mode=True):
        y = nd.Dropout(x, p=0.5)
    frac = (y.asnumpy() == 0).mean()
    assert 0.3 < frac < 0.7
    with autograd.predict_mode():
        y = nd.Dropout(x, p=0.5)
    assert (y.asnumpy() == 1).all()


def test_custom_function():
    class Sigmoid(autograd.Function):
        def forward(self, x):
            y = nd.sigmoid(x)
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            (y,) = self.saved_tensors
            return dy * y * (1 - y)

    x = nd.array([0.5, -1.0])
    x.attach_grad()
    f = Sigmoid()
    with autograd.record():
        y = f(x)
    y.backward()
    s = 1 / (1 + np.exp(-x.asnumpy()))
    np.testing.assert_allclose(x.grad.asnumpy(), s * (1 - s), rtol=1e-5)


def test_second_order_unsupported_path():
    # higher-order via composition still works through jax directly;
    # here we just assert grad() with create_graph=False returns values
    x = nd.array([1.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    (g,) = autograd.grad(y, [x], retain_graph=True)
    np.testing.assert_allclose(g.asnumpy(), [2.0])


def test_get_symbol_retrace():
    # imperative history -> Symbol -> executor must reproduce forward
    import numpy as np
    from incubator_mxnet_tpu import sym as sym_mod
    a = mx.nd.array(np.random.RandomState(0).rand(2, 3).astype("float32"))
    w = mx.nd.array(np.random.RandomState(1).rand(4, 3).astype("float32"))
    b = mx.nd.array(np.zeros(4, "float32"))
    with autograd.record():
        y = mx.nd.FullyConnected(a, w, b, num_hidden=4)
        z = mx.nd.Activation(y, act_type="relu") * 2.0
    s = autograd.get_symbol(z)
    args = s.list_arguments()
    assert len(args) == 3, args
    ex = s.simple_bind(mx.cpu(), **{args[0]: (2, 3), args[1]: (4, 3),
                                    args[2]: (4,)})
    ex.arg_dict[args[0]][:] = a
    ex.arg_dict[args[1]][:] = w
    ex.arg_dict[args[2]][:] = b
    np.testing.assert_allclose(ex.forward()[0].asnumpy(),
                               z.asnumpy(), rtol=1e-5, atol=1e-6)


def test_get_symbol_leaf_and_opaque():
    import numpy as np
    import pytest
    from incubator_mxnet_tpu import gluon
    # un-recorded array -> a bare Variable
    leafsym = autograd.get_symbol(mx.nd.ones((2,)))
    assert leafsym.list_arguments() == ["var0"]
    # CachedOp history is opaque and must say so
    net = gluon.nn.Dense(2)
    net.initialize()
    net.hybridize()
    x = mx.nd.ones((1, 3))
    net(x)  # build+cache
    with autograd.record():
        out = net(x)
    with pytest.raises(ValueError, match="opaque"):
        autograd.get_symbol(out)


def test_get_symbol_rejects_inlined_constants():
    import numpy as np
    import pytest
    x = mx.nd.ones((3,))
    with autograd.record():
        y = mx.nd.broadcast_add(x, np.ones(3, "float32"))
    with pytest.raises(ValueError, match="constant"):
        autograd.get_symbol(y)
