"""Elastic training worker: checkpoint every epoch, crash rank 1
mid-train on the first attempt, resume from the newest checkpoint
after tools/launch.py --max-restarts relaunches the job (the
reference's scheduler-restart failure model; SURVEY §5 failure
detection).  Spawned by tests/test_dist_launch.py — not a pytest
module."""
import glob
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import incubator_mxnet_tpu as mx

TOTAL_EPOCHS = 14
CRASH_AFTER_EPOCH = 2      # rank 1 dies once this epoch is saved


def main():
    ckdir = os.environ["MXTPU_ELASTIC_DIR"]
    attempt = int(os.environ.get("MXTPU_RESTART_ATTEMPT", "0"))
    kv = mx.kvstore.create("dist_sync")
    r = kv.rank
    prefix = os.path.join(ckdir, "model")

    # learnable synthetic problem, data sharded by rank
    rs = np.random.RandomState(0)
    x = rs.rand(256, 10).astype(np.float32)
    w = rs.rand(10, 5).astype(np.float32)
    y = (x @ w).argmax(1).astype(np.float32)
    it = mx.io.NDArrayIter(x[r::2], y[r::2], batch_size=16,
                           label_name="softmax_label")

    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc1", num_hidden=32)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=5)
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    # resume: newest checkpoint wins (both ranks read shared disk)
    begin, arg_params, aux_params = 0, None, None
    saved = glob.glob(prefix + "-*.params")
    if saved:
        begin = max(int(p.rsplit("-", 1)[1].split(".")[0])
                    for p in saved)
        _, arg_params, aux_params = mx.model.load_checkpoint(
            prefix, begin)
        print(f"RESUMED_FROM {begin} rank {r}", flush=True)

    def epoch_cb(epoch, symbol, arg_p, aux_p):
        if r == 0:
            mx.model.save_checkpoint(prefix, epoch + 1, symbol,
                                     arg_p, aux_p)
        kv.barrier()          # checkpoint visible to all ranks
        if attempt == 0 and r == 1 and epoch + 1 == CRASH_AFTER_EPOCH:
            print(f"CRASHING rank {r} after epoch {epoch}",
                  flush=True)
            os._exit(7)       # hard death, no teardown

    mx.random.seed(42)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, begin_epoch=begin, num_epoch=TOTAL_EPOCHS,
            kvstore=kv, optimizer="sgd",
            optimizer_params=dict(learning_rate=1.0),
            initializer=mx.initializer.Xavier(),
            arg_params=arg_params, aux_params=aux_params,
            epoch_end_callback=epoch_cb)

    acc = mod.score(it, "acc")[0][1]
    assert acc > 0.88, f"rank {r} did not converge: acc={acc}"
    print(f"ELASTIC_OK rank {r} attempt {attempt} acc {acc:.3f}",
          flush=True)


if __name__ == "__main__":
    main()
