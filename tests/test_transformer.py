"""TransformerLM model family (gluon/model_zoo/transformer.py):
causal attention semantics, convergence through the mesh train step,
and bf16 mixed-precision."""
import numpy as np

import jax
import jax.numpy as jnp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import parallel
from incubator_mxnet_tpu.gluon.model_zoo.transformer import (
    TransformerLM, transformer_lm)


def _tiny(vocab=37, **kw):
    cfg = dict(d_model=32, n_layers=2, n_heads=4, max_len=16)
    cfg.update(kw)
    mx.random.seed(0)
    net = TransformerLM(vocab, **cfg)
    net.initialize(mx.initializer.Xavier())
    return net


def _lm_loss(outputs, labels):
    logits = outputs[0].astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(
        jnp.take_along_axis(logp, labels[..., None], axis=-1))


def test_forward_shape_and_determinism():
    net = _tiny()
    toks = mx.nd.array(np.random.RandomState(0)
                       .randint(0, 37, (2, 8)).astype("int32"))
    out = net(toks)
    assert out.shape == (2, 8, 37)
    np.testing.assert_allclose(out.asnumpy(), net(toks).asnumpy())


def test_causality():
    # changing a future token must not change earlier logits
    net = _tiny()
    rs = np.random.RandomState(1)
    a = rs.randint(0, 37, (1, 8)).astype("int32")
    b = a.copy()
    b[0, 5:] = (b[0, 5:] + 7) % 37
    oa = net(mx.nd.array(a)).asnumpy()
    ob = net(mx.nd.array(b)).asnumpy()
    np.testing.assert_allclose(oa[0, :5], ob[0, :5], atol=1e-5)
    assert np.abs(oa[0, 5:] - ob[0, 5:]).max() > 1e-4


def test_trains_on_mesh():
    net = _tiny()
    rs = np.random.RandomState(0)
    toks = jnp.asarray(rs.randint(0, 37, (8, 8)), jnp.int32)
    labels = jnp.asarray(rs.randint(0, 37, (8, 8)), jnp.int32)
    step = parallel.ShardedTrainStep(
        net, optimizer="adam",
        optimizer_params=dict(learning_rate=1e-2), loss_fn=_lm_loss,
        example_args=[mx.nd.array(np.zeros((2, 8), "int32"))])
    losses = [float(step(toks, labels)) for _ in range(25)]
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_bf16_compute_path():
    net = _tiny()
    rs = np.random.RandomState(0)
    toks = jnp.asarray(rs.randint(0, 37, (8, 8)), jnp.int32)
    labels = jnp.asarray(rs.randint(0, 37, (8, 8)), jnp.int32)
    step = parallel.ShardedTrainStep(
        net, optimizer="sgd",
        optimizer_params=dict(learning_rate=0.1), loss_fn=_lm_loss,
        example_args=[mx.nd.array(np.zeros((2, 8), "int32"))],
        compute_dtype=jnp.bfloat16)
    l0 = float(step(toks, labels))
    l1 = float(step(toks, labels))
    assert np.isfinite(l0) and np.isfinite(l1)
    # masters stay fp32
    assert all(v.dtype == jnp.float32 for v in step.params.values())


def test_factory_presets():
    net = transformer_lm(vocab_size=100, size="small", n_layers=1,
                        max_len=8)
    assert net.n_layers == 1 and net._d == 768


def test_max_len_guard():
    net = _tiny(max_len=8)
    import pytest
    with pytest.raises(ValueError, match="max_len"):
        net(mx.nd.array(np.zeros((1, 9), "int32")))
