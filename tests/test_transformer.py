"""TransformerLM model family (gluon/model_zoo/transformer.py):
causal attention semantics, convergence through the mesh train step,
and bf16 mixed-precision."""
import numpy as np

import jax
import jax.numpy as jnp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import parallel
from incubator_mxnet_tpu.gluon.model_zoo.transformer import (
    TransformerLM, transformer_lm)


def _tiny(vocab=37, **kw):
    cfg = dict(d_model=32, n_layers=2, n_heads=4, max_len=16)
    cfg.update(kw)
    mx.random.seed(0)
    net = TransformerLM(vocab, **cfg)
    net.initialize(mx.initializer.Xavier())
    return net


def _lm_loss(outputs, labels):
    logits = outputs[0].astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(
        jnp.take_along_axis(logp, labels[..., None], axis=-1))


def test_forward_shape_and_determinism():
    net = _tiny()
    toks = mx.nd.array(np.random.RandomState(0)
                       .randint(0, 37, (2, 8)).astype("int32"))
    out = net(toks)
    assert out.shape == (2, 8, 37)
    np.testing.assert_allclose(out.asnumpy(), net(toks).asnumpy())


def test_causality():
    # changing a future token must not change earlier logits
    net = _tiny()
    rs = np.random.RandomState(1)
    a = rs.randint(0, 37, (1, 8)).astype("int32")
    b = a.copy()
    b[0, 5:] = (b[0, 5:] + 7) % 37
    oa = net(mx.nd.array(a)).asnumpy()
    ob = net(mx.nd.array(b)).asnumpy()
    np.testing.assert_allclose(oa[0, :5], ob[0, :5], atol=1e-5)
    assert np.abs(oa[0, 5:] - ob[0, 5:]).max() > 1e-4


def test_trains_on_mesh():
    net = _tiny()
    rs = np.random.RandomState(0)
    toks = jnp.asarray(rs.randint(0, 37, (8, 8)), jnp.int32)
    labels = jnp.asarray(rs.randint(0, 37, (8, 8)), jnp.int32)
    step = parallel.ShardedTrainStep(
        net, optimizer="adam",
        optimizer_params=dict(learning_rate=1e-2), loss_fn=_lm_loss,
        example_args=[mx.nd.array(np.zeros((2, 8), "int32"))])
    losses = [float(step(toks, labels)) for _ in range(25)]
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_bf16_compute_path():
    net = _tiny()
    rs = np.random.RandomState(0)
    toks = jnp.asarray(rs.randint(0, 37, (8, 8)), jnp.int32)
    labels = jnp.asarray(rs.randint(0, 37, (8, 8)), jnp.int32)
    step = parallel.ShardedTrainStep(
        net, optimizer="sgd",
        optimizer_params=dict(learning_rate=0.1), loss_fn=_lm_loss,
        example_args=[mx.nd.array(np.zeros((2, 8), "int32"))],
        compute_dtype=jnp.bfloat16)
    l0 = float(step(toks, labels))
    l1 = float(step(toks, labels))
    assert np.isfinite(l0) and np.isfinite(l1)
    # masters stay fp32
    assert all(v.dtype == jnp.float32 for v in step.params.values())


def test_factory_presets():
    net = transformer_lm(vocab_size=100, size="small", n_layers=1,
                        max_len=8)
    assert net.n_layers == 1 and net._d == 768


def test_max_len_guard():
    net = _tiny(max_len=8)
    import pytest
    with pytest.raises(ValueError, match="max_len"):
        net(mx.nd.array(np.zeros((1, 9), "int32")))


def test_generate_greedy_matches_naive():
    # the scan+KV-cache decoder must agree exactly with re-running
    # the full forward and taking argmax of the last position
    net = _tiny(max_len=16)
    rs = np.random.RandomState(3)
    prompt = rs.randint(0, 37, (2, 4)).astype("int32")
    out = net.generate(mx.nd.array(prompt), max_new_tokens=5)
    assert out.shape == (2, 9)
    got = out.asnumpy()
    np.testing.assert_array_equal(got[:, :4], prompt)

    cur = prompt.copy()
    for _ in range(5):
        logits = net(mx.nd.array(cur)).asnumpy()
        nxt = logits[:, -1].argmax(-1).astype("int32")
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(got, cur)


def test_generate_sampled_and_guard():
    import pytest
    net = _tiny(max_len=16)
    prompt = mx.nd.array(np.zeros((1, 4), "int32"))
    s1 = net.generate(prompt, 4, temperature=1.0,
                      rng=jax.random.PRNGKey(1)).asnumpy()
    s2 = net.generate(prompt, 4, temperature=1.0,
                      rng=jax.random.PRNGKey(1)).asnumpy()
    np.testing.assert_array_equal(s1, s2)   # same key -> same sample
    with pytest.raises(ValueError, match="max_len"):
        net.generate(prompt, 100)


def test_seq_parallel_ring_attention_matches_local(tmp_path):
    # seq_parallel=True under a mesh with sp>1 must compute the SAME
    # values as local attention (ring attention is exact)
    from incubator_mxnet_tpu.parallel import make_mesh, use_mesh
    net_sp = TransformerLM(37, d_model=32, n_layers=2, n_heads=4,
                           max_len=16, seq_parallel=True)
    net_sp.initialize(mx.initializer.Xavier())
    net_local = TransformerLM(37, d_model=32, n_layers=2, n_heads=4,
                              max_len=16)
    net_local.initialize(mx.initializer.Xavier())

    toks = mx.nd.array(np.random.RandomState(0)
                       .randint(0, 37, (2, 8)).astype("int32"))
    ref = net_local(toks).asnumpy()
    # share the exact same weights across both attention impls
    f = str(tmp_path / "w.params")
    net_local.save_params(f)
    net_sp(toks)          # settle deferred shapes before loading
    net_sp.load_params(f)
    np.testing.assert_allclose(net_sp(toks).asnumpy(), ref,
                               rtol=1e-4, atol=1e-4)
    mesh = make_mesh(dp=2, sp=4)
    with use_mesh(mesh):
        got = net_sp(toks).asnumpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)
    # off-mesh it falls back to local attention and still agrees
    np.testing.assert_allclose(net_sp(toks).asnumpy(), ref,
                               rtol=1e-4, atol=1e-4)


def test_seq_parallel_trains_on_mesh():
    from incubator_mxnet_tpu.parallel import make_mesh, use_mesh
    net = _tiny(seq_parallel=True)
    rs = np.random.RandomState(0)
    toks = jnp.asarray(rs.randint(0, 37, (2, 8)), jnp.int32)
    labels = jnp.asarray(rs.randint(0, 37, (2, 8)), jnp.int32)
    mesh = make_mesh(dp=2, sp=4)
    with use_mesh(mesh):
        step = parallel.ShardedTrainStep(
            net, optimizer="adam",
            optimizer_params=dict(learning_rate=1e-2),
            loss_fn=_lm_loss, mesh=mesh, seq_axis=1,
            example_args=[mx.nd.array(np.zeros((2, 8), "int32"))])
        losses = [float(step(toks, labels)) for _ in range(15)]
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


def test_seq_parallel_eager_autograd_gets_gradients():
    # eager record()/backward() must take the registry-op attention
    # path (the raw-jax ring call is invisible to the tape), so qkv
    # weights receive real gradients
    from incubator_mxnet_tpu import autograd
    from incubator_mxnet_tpu.parallel import make_mesh, use_mesh
    net = _tiny(seq_parallel=True)
    toks = mx.nd.array(np.random.RandomState(0)
                       .randint(0, 37, (2, 8)).astype("int32"))
    labels = mx.nd.array(np.random.RandomState(1)
                         .randint(0, 37, (2, 8)).astype("float32"))
    net(toks)            # settle deferred shapes
    for p in net.collect_params().values():
        p.data().attach_grad()
    lossf = mx.gluon.loss.SoftmaxCrossEntropyLoss(axis=-1)
    with use_mesh(make_mesh(dp=2, sp=4)):
        with autograd.record():
            L = lossf(net(toks), labels).mean()
        L.backward()
    g = net.blocks[0].attn.qkv.weight.data().grad
    assert g is not None and float(np.abs(g.asnumpy()).max()) > 0


def test_seq_parallel_non_divisible_seq_falls_back():
    from incubator_mxnet_tpu.parallel import make_mesh, use_mesh
    net = _tiny(seq_parallel=True)
    toks = mx.nd.array(np.random.RandomState(0)
                       .randint(0, 37, (2, 6)).astype("int32"))
    ref = net(toks).asnumpy()          # off-mesh local path
    with use_mesh(make_mesh(dp=2, sp=4)):
        got = net(toks).asnumpy()      # L=6 % sp=4 != 0 -> local
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_sharded_step_traces_with_own_mesh_outside_scope():
    # first call outside use_mesh() must still trace the ring path
    # with the step's own mesh ambient (not bake in local attention)
    from incubator_mxnet_tpu.parallel import make_mesh, use_mesh
    net = _tiny(seq_parallel=True)
    mesh = make_mesh(dp=2, sp=4)
    with use_mesh(mesh):
        step = parallel.ShardedTrainStep(
            net, optimizer="sgd",
            optimizer_params=dict(learning_rate=0.1),
            loss_fn=_lm_loss, mesh=mesh, seq_axis=1,
            example_args=[mx.nd.array(np.zeros((2, 8), "int32"))])
    rs = np.random.RandomState(0)
    toks = jnp.asarray(rs.randint(0, 37, (2, 8)), jnp.int32)
    labels = jnp.asarray(rs.randint(0, 37, (2, 8)), jnp.int32)
    # called OUTSIDE the with-block: ambient mesh is None here
    ring_calls = []
    import incubator_mxnet_tpu.gluon.model_zoo.transformer as tf_mod
    orig = tf_mod.CausalSelfAttention._ring_mesh
    def spy(self, seq_len):
        m = orig(self, seq_len)
        ring_calls.append(m is not None)
        return m
    tf_mod.CausalSelfAttention._ring_mesh = spy
    try:
        loss = float(step(toks, labels))
    finally:
        tf_mod.CausalSelfAttention._ring_mesh = orig
    assert np.isfinite(loss)
    assert any(ring_calls), "ring path never engaged during trace"


def test_generate_top_k_restricts_support():
    # every sampled continuation token must be in the per-step top-2
    # of the same model's full-forward logits
    net = _tiny(max_len=16)
    prompt = np.random.RandomState(5).randint(0, 37, (1, 4)) \
        .astype("int32")
    out = net.generate(mx.nd.array(prompt), max_new_tokens=5,
                       temperature=1.0, top_k=2,
                       rng=jax.random.PRNGKey(3)).asnumpy()
    cur = prompt.copy()
    for t in range(5):
        logits = net(mx.nd.array(cur)).asnumpy()[:, -1]
        top2 = set(np.argsort(logits[0])[-2:].tolist())
        assert int(out[0, 4 + t]) in top2, (t, out, top2)
        cur = np.concatenate(
            [cur, out[:, 4 + t:5 + t].astype("int32")], axis=1)


def test_generate_top_p_one_keeps_all_and_top_k1_is_greedy():
    net = _tiny(max_len=16)
    prompt = mx.nd.array(np.zeros((1, 4), "int32"))
    greedy = net.generate(prompt, 5).asnumpy()
    k1 = net.generate(prompt, 5, temperature=1.0, top_k=1,
                      rng=jax.random.PRNGKey(0)).asnumpy()
    np.testing.assert_array_equal(k1, greedy)
    # nucleus sampling is deterministic for a fixed key, and valid
    s1 = net.generate(prompt, 5, temperature=1.0, top_p=0.3,
                      rng=jax.random.PRNGKey(0)).asnumpy()
    s2 = net.generate(prompt, 5, temperature=1.0, top_p=0.3,
                      rng=jax.random.PRNGKey(0)).asnumpy()
    np.testing.assert_array_equal(s1, s2)
    assert ((s1 >= 0) & (s1 < 37)).all()


def test_generate_sampling_arg_validation():
    import pytest
    net = _tiny(max_len=16)
    prompt = mx.nd.array(np.zeros((1, 4), "int32"))
    with pytest.raises(ValueError, match="top_k"):
        net.generate(prompt, 2, temperature=1.0, top_k=-1)
    with pytest.raises(ValueError, match="top_p"):
        net.generate(prompt, 2, temperature=1.0, top_p=0.0)
    # greedy ignores the filters and shares one executable
    net._gen_cache = {}
    net.generate(prompt, 2)
    net.generate(prompt, 2, top_k=50, top_p=0.9)
    assert len(net._gen_cache) == 1


def test_gen_cache_is_lru_not_fifo(monkeypatch):
    """Regression: the decode-executable cache must evict the LEAST
    RECENTLY USED signature, not the oldest inserted — an
    alternating pair of hot signatures at capacity used to thrash
    recompiles under FIFO."""
    net = _tiny(max_len=16)
    builds = []
    real_build = net._build_decode

    def counting_build(b, p, max_new, sample, top_k=0, top_p=1.0):
        builds.append((b, p, max_new))
        return real_build(b, p, max_new, sample, top_k=top_k,
                          top_p=top_p)

    monkeypatch.setattr(net, "_build_decode", counting_build)
    monkeypatch.setattr(TransformerLM, "_GEN_CACHE_MAX", 2)
    prompt_a = mx.nd.array(np.zeros((1, 4), "int32"))
    prompt_b = mx.nd.array(np.zeros((1, 5), "int32"))
    prompt_c = mx.nd.array(np.zeros((1, 6), "int32"))
    net.generate(prompt_a, 2)          # build A
    net.generate(prompt_b, 2)          # build B (cache full)
    net.generate(prompt_a, 2)          # hit A -> A becomes MRU
    net.generate(prompt_c, 2)          # build C, evicts B (LRU)
    assert len(builds) == 3
    net.generate(prompt_a, 2)          # FIFO would have evicted A
    assert len(builds) == 3, \
        "hot signature was evicted despite a recent hit (FIFO)"
    # the pair (A, C) now alternates at capacity with no rebuilds
    for _ in range(3):
        net.generate(prompt_a, 2)
        net.generate(prompt_c, 2)
    assert len(builds) == 3


def test_seq_parallel_ulysses_matches_local(tmp_path):
    """seq_parallel='ulysses' under an sp>1 mesh computes the SAME
    values as local attention (all-to-all resharding is exact)."""
    from incubator_mxnet_tpu.parallel import make_mesh, use_mesh
    net_sp = TransformerLM(37, d_model=32, n_layers=2, n_heads=4,
                           max_len=16, seq_parallel="ulysses")
    net_sp.initialize(mx.initializer.Xavier())
    net_local = TransformerLM(37, d_model=32, n_layers=2, n_heads=4,
                              max_len=16)
    net_local.initialize(mx.initializer.Xavier())
    toks = mx.nd.array(np.random.RandomState(0)
                       .randint(0, 37, (2, 8)).astype("int32"))
    ref = net_local(toks).asnumpy()
    f = str(tmp_path / "w.params")
    net_local.save_params(f)
    net_sp(toks)
    net_sp.load_params(f)
    mesh = make_mesh(dp=2, sp=4)
    with use_mesh(mesh):
        got = net_sp(toks).asnumpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_seq_parallel_ulysses_trains_on_mesh():
    from incubator_mxnet_tpu.parallel import make_mesh, use_mesh
    net = _tiny(seq_parallel="ulysses")
    rs = np.random.RandomState(0)
    toks = jnp.asarray(rs.randint(0, 37, (2, 8)), jnp.int32)
    labels = jnp.asarray(rs.randint(0, 37, (2, 8)), jnp.int32)
    mesh = make_mesh(dp=2, sp=4)
    with use_mesh(mesh):
        step = parallel.ShardedTrainStep(
            net, optimizer="adam",
            optimizer_params=dict(learning_rate=1e-2),
            loss_fn=_lm_loss, mesh=mesh, seq_axis=1,
            example_args=[mx.nd.array(np.zeros((2, 8), "int32"))])
        losses = [float(step(toks, labels)) for _ in range(15)]
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


def test_rope_position_scheme():
    """pos='rope': rotary embeddings — trains, decodes consistently
    with the forward pass through the KV cache, and needs no learned
    position table (no pos embedding parameter)."""
    mx.random.seed(0)
    net = TransformerLM(37, d_model=32, n_layers=2, n_heads=4,
                        max_len=64, pos="rope")
    net.initialize(mx.initializer.Xavier())
    assert not any("embedding1" in n or n.endswith("pos_weight")
                   for n in net.collect_params()), \
        list(net.collect_params())[:6]

    toks = mx.nd.array(np.random.RandomState(0)
                       .randint(0, 37, (2, 16)).astype("int32"))
    out = net.generate(toks, max_new_tokens=4)
    nxt = net(toks).asnumpy()[:, -1].argmax(-1)
    assert (out.asnumpy()[:, 16] == nxt).all()

    # trains through the compiled mesh step
    step = parallel.ShardedTrainStep(
        net, optimizer="adam",
        optimizer_params=dict(learning_rate=1e-2),
        loss_fn=_lm_loss,
        example_args=[mx.nd.array(np.zeros((2, 16), "int32"))])
    rs = np.random.RandomState(0)
    t = jnp.asarray(rs.randint(0, 37, (8, 16)), jnp.int32)
    y = jnp.asarray(rs.randint(0, 37, (8, 16)), jnp.int32)
    losses = [float(step(t, y)) for _ in range(12)]
    assert losses[-1] < losses[0] * 0.9, losses

    import pytest
    with pytest.raises(ValueError, match="pos"):
        TransformerLM(37, pos="sinusoidal")
    # odd head dim: loud error at first use, not a reshape crash
    odd = TransformerLM(37, d_model=24, n_heads=8, pos="rope")
    odd.initialize(mx.initializer.Xavier())
    with pytest.raises(ValueError, match="even"):
        odd(mx.nd.array(np.zeros((1, 4), "int32")))


def test_rope_with_ring_attention_matches_local(tmp_path):
    """rope rotates q/k BEFORE sequence sharding, so ring attention
    over the mesh must equal the local forward exactly."""
    from incubator_mxnet_tpu.parallel import make_mesh, use_mesh
    net_sp = TransformerLM(37, d_model=32, n_layers=2, n_heads=4,
                           max_len=16, pos="rope",
                           seq_parallel=True)
    net_sp.initialize(mx.initializer.Xavier())
    net_local = TransformerLM(37, d_model=32, n_layers=2, n_heads=4,
                              max_len=16, pos="rope")
    net_local.initialize(mx.initializer.Xavier())
    toks = mx.nd.array(np.random.RandomState(0)
                       .randint(0, 37, (2, 8)).astype("int32"))
    ref = net_local(toks).asnumpy()
    f = str(tmp_path / "w.params")
    net_local.save_params(f)
    net_sp(toks)
    net_sp.load_params(f)
    with use_mesh(make_mesh(dp=2, sp=4)):
        got = net_sp(toks).asnumpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_grouped_query_attention():
    """n_kv_heads < n_heads (GQA): the k/v projections and the decode
    cache shrink to kv head groups while attention math matches the
    full decode <-> forward consistency contract; n_kv_heads ==
    n_heads is exactly MHA."""
    mx.random.seed(0)
    net = TransformerLM(64, d_model=32, n_layers=2, n_heads=8,
                        max_len=64, n_kv_heads=2)
    net.initialize(mx.initializer.Xavier())
    # qkv projection rows: d + 2 * kv * dh = 32 + 2*2*4 = 48
    qkv_w = [p for n, p in net.collect_params().items()
             if "dense0_weight" in n][0]
    assert qkv_w.shape[0] == 48, qkv_w.shape

    toks = mx.nd.array(np.random.RandomState(0)
                       .randint(0, 64, (2, 16)).astype("int32"))
    out = net.generate(toks, max_new_tokens=4)
    nxt = net(toks).asnumpy()[:, -1].argmax(-1)
    assert (out.asnumpy()[:, 16] == nxt).all()

    # trains through the compiled step
    step = parallel.ShardedTrainStep(
        net, optimizer="adam",
        optimizer_params=dict(learning_rate=1e-2),
        loss_fn=_lm_loss,
        example_args=[mx.nd.array(np.zeros((2, 16), "int32"))])
    rs = np.random.RandomState(0)
    t = jnp.asarray(rs.randint(0, 64, (8, 16)), jnp.int32)
    y = jnp.asarray(rs.randint(0, 64, (8, 16)), jnp.int32)
    losses = [float(step(t, y)) for _ in range(12)]
    assert losses[-1] < losses[0] * 0.9, losses

    import pytest
    for bad in (3, 0, -2):
        with pytest.raises(ValueError, match="multiple"):
            TransformerLM(64, d_model=32, n_heads=8, n_kv_heads=bad)

    # flops accounting shrinks with the kv projections
    full = TransformerLM(64, d_model=32, n_layers=2, n_heads=8)
    assert net.train_flops_per_token(16) < \
        full.train_flops_per_token(16)


def test_factory_modern_preset():
    """transformer_lm(size='modern'): rope + grouped-query — the
    configuration current decoder LMs ship with."""
    net = transformer_lm(128, size="modern", max_len=32, n_layers=2)
    net.initialize(mx.initializer.Xavier())
    assert net.n_kv_heads == 4 and net._pos_kind == "rope"
    out = net(mx.nd.array(np.zeros((1, 8), "int32")))
    assert out.shape == (1, 8, 128)
    import pytest
    with pytest.raises(ValueError, match="unknown size"):
        transformer_lm(128, size="modem")   # typo must not silently
    # build a default model


def test_attn_window_model():
    """TransformerLM(attn_window=N): sliding-window attention — the
    flash (banded-kernel) and exact masked paths agree on the same
    weights, and the combination trains."""
    import os

    mx.random.seed(0)
    net = TransformerLM(64, d_model=32, n_layers=2, n_heads=4,
                        max_len=256, attn_window=128, pos="rope")
    net.initialize(mx.initializer.Xavier())
    toks = mx.nd.array(np.random.RandomState(0)
                       .randint(0, 64, (1, 256)).astype("int32"))
    prev = os.environ.get("MXTPU_FLASH")
    try:
        os.environ["MXTPU_FLASH"] = "1"
        out_flash = net(toks).asnumpy()
        os.environ["MXTPU_FLASH"] = "0"
        out_exact = net(toks).asnumpy()
    finally:
        if prev is None:
            os.environ.pop("MXTPU_FLASH", None)
        else:
            os.environ["MXTPU_FLASH"] = prev
    np.testing.assert_allclose(out_flash, out_exact, rtol=2e-4,
                               atol=2e-4)

    step = parallel.ShardedTrainStep(
        net, optimizer="adam",
        optimizer_params=dict(learning_rate=1e-2),
        loss_fn=_lm_loss,
        example_args=[mx.nd.array(np.zeros((1, 256), "int32"))])
    rs = np.random.RandomState(0)
    t = jnp.asarray(rs.randint(0, 64, (8, 256)), jnp.int32)
    y = jnp.asarray(rs.randint(0, 64, (8, 256)), jnp.int32)
    losses = [float(step(t, y)) for _ in range(6)]
    assert losses[-1] < losses[0], losses

    import pytest
    with pytest.raises(ValueError, match="seq_parallel"):
        TransformerLM(64, attn_window=64, seq_parallel=True)
    with pytest.raises(ValueError, match=">= 0"):
        TransformerLM(64, attn_window=-64)

    # decode honors the window even when context exceeds it
    mx.random.seed(1)
    netw = TransformerLM(64, d_model=32, n_layers=2, n_heads=4,
                         max_len=300, attn_window=64, pos="rope")
    netw.initialize(mx.initializer.Xavier())
    toks2 = mx.nd.array(np.random.RandomState(2)
                        .randint(0, 64, (2, 200)).astype("int32"))
    out = netw.generate(toks2, max_new_tokens=4)
    nxt = netw(toks2).asnumpy()[:, -1].argmax(-1)
    assert (out.asnumpy()[:, 200] == nxt).all()

    # FLOPs honor the band
    assert netw.train_flops_per_token(300) < \
        TransformerLM(64, d_model=32, n_layers=2, n_heads=4,
                      max_len=300).train_flops_per_token(300)
