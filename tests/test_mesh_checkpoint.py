"""ShardedTrainStep checkpoint/resume via orbax: bitwise resume on
the same mesh, and restore onto a different mesh shape."""
import numpy as np

import jax
import jax.numpy as jnp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import parallel
from incubator_mxnet_tpu.parallel import make_mesh


def _net():
    # explicit prefix: checkpoint keys are parameter names, so the
    # restoring net must use the same names (reference semantics)
    mx.random.seed(0)
    net = mx.gluon.nn.HybridSequential(prefix="ck_")
    with net.name_scope():
        net.add(mx.gluon.nn.Dense(16, activation="relu"),
                mx.gluon.nn.Dense(4))
    net.initialize(mx.initializer.Xavier())
    return net


def _step(mesh=None):
    return parallel.ShardedTrainStep(
        _net(), optimizer="adam",
        optimizer_params=dict(learning_rate=1e-2),
        mesh=mesh or make_mesh(),
        example_args=[jnp.zeros((2, 8), jnp.float32)])


def _batches(n):
    rs = np.random.RandomState(0)
    return [(jnp.asarray(rs.rand(16, 8), jnp.float32),
             jnp.asarray(rs.randint(0, 4, (16,)), jnp.int32))
            for _ in range(n)]


def test_checkpoint_resume_bitwise(tmp_path):
    batches = _batches(8)
    # uninterrupted run
    ref = _step()
    ref_losses = [float(ref(x, y)) for x, y in batches]

    # run 4, checkpoint, resume in a FRESH step, run the rest
    a = _step()
    for x, y in batches[:4]:
        a(x, y)
    a.save_checkpoint(str(tmp_path / "ck"))

    b = _step()
    b.load_checkpoint(str(tmp_path / "ck"))
    resumed = [float(b(x, y)) for x, y in batches[4:]]
    np.testing.assert_allclose(resumed, ref_losses[4:], rtol=1e-6)


def test_checkpoint_restores_onto_different_mesh(tmp_path):
    batches = _batches(6)
    a = _step(make_mesh(dp=8))
    for x, y in batches[:3]:
        a(x, y)
    a.save_checkpoint(str(tmp_path / "ck"))

    devs = jax.devices("cpu")[:4]
    b = _step(make_mesh(dp=4, devices=devs))
    b.load_checkpoint(str(tmp_path / "ck"))
    # values land in THIS step's layout
    for v in b.params.values():
        assert v.sharding.mesh.shape["dp"] == 4
    l_b = [float(b(x, y)) for x, y in batches[3:]]
    # and the continuation matches the dp=8 continuation (same math)
    c = _step(make_mesh(dp=8))
    c.load_checkpoint(str(tmp_path / "ck"))
    l_c = [float(c(x, y)) for x, y in batches[3:]]
    np.testing.assert_allclose(l_b, l_c, rtol=1e-5)
