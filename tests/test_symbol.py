"""Symbol tests (ref: tests/python/unittest/test_symbol.py,
test_infer_shape.py)."""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import sym


def _mlp():
    data = sym.Variable("data")
    fc1 = sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = sym.FullyConnected(act, num_hidden=10, name="fc2")
    return sym.SoftmaxOutput(fc2, sym.Variable("label"), name="softmax")


def test_compose_and_listing():
    net = _mlp()
    args = net.list_arguments()
    assert args == ["data", "fc1_weight", "fc1_bias", "fc2_weight",
                    "fc2_bias", "label"]
    assert net.list_outputs() == ["softmax_output"]
    assert net.list_auxiliary_states() == []


def test_auto_variable_creation_and_naming():
    sym.NameManager.reset()
    x = sym.Variable("x")
    f = sym.FullyConnected(x, num_hidden=4)
    assert f.name.startswith("fullyconnected")
    assert f.list_arguments() == ["x", f.name + "_weight",
                                  f.name + "_bias"]


def test_infer_shape_mlp():
    net = _mlp()
    arg_shapes, out_shapes, aux_shapes = net.infer_shape(data=(32, 100),
                                                         label=(32,))
    d = dict(zip(net.list_arguments(), arg_shapes))
    assert d["fc1_weight"] == (16, 100)
    assert d["fc1_bias"] == (16,)
    assert d["fc2_weight"] == (10, 16)
    assert out_shapes == [(32, 10)]


def test_infer_shape_conv():
    data = sym.Variable("data")
    c = sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                        name="conv1")
    b = sym.BatchNorm(c, name="bn1")
    p = sym.Pooling(b, kernel=(2, 2), stride=(2, 2), pool_type="max")
    arg_shapes, out_shapes, aux_shapes = p.infer_shape(
        data=(4, 3, 16, 16))
    d = dict(zip(p.list_arguments(), arg_shapes))
    assert d["conv1_weight"] == (8, 3, 3, 3)
    assert d["conv1_bias"] == (8,)
    assert d["bn1_gamma"] == (8,)
    assert out_shapes == [(4, 8, 8, 8)]
    ax = dict(zip(p.list_auxiliary_states(), aux_shapes))
    assert ax["bn1_moving_mean"] == (8,)
    assert ax["bn1_moving_var"] == (8,)


def test_batchnorm_aux_listing():
    data = sym.Variable("data")
    b = sym.BatchNorm(data, name="bn")
    assert b.list_arguments() == ["data", "bn_gamma", "bn_beta"]
    assert b.list_auxiliary_states() == ["bn_moving_mean",
                                         "bn_moving_var"]


def test_arithmetic_composition():
    a = sym.Variable("a")
    b = sym.Variable("b")
    c = (a + b) * 2 - a / b
    args = c.list_arguments()
    assert set(args) == {"a", "b"}
    ex = c.bind(mx.cpu(), {"a": mx.nd.array([2.0]),
                           "b": mx.nd.array([4.0])})
    out = ex.forward()
    np.testing.assert_allclose(out[0].asnumpy(), [(2 + 4) * 2 - 2 / 4])


def test_group_and_internals():
    a = sym.Variable("a")
    x = sym.relu(a, name="r")
    y = sym.sigmoid(a, name="s")
    g = sym.Group([x, y])
    assert g.list_outputs() == ["r_output", "s_output"]
    internals = x.get_internals()
    assert "a" in internals.list_outputs()


def test_multi_output_indexing():
    a = sym.Variable("a")
    parts = sym.SliceChannel(a, num_outputs=3, axis=1, name="split")
    assert len(parts) == 3
    assert parts.list_outputs() == ["split_output0", "split_output1",
                                    "split_output2"]
    p1 = parts[1]
    assert p1.list_outputs() == ["split_output1"]


def test_json_roundtrip():
    net = _mlp()
    js = net.tojson()
    net2 = sym.load_json(js)
    assert net2.list_arguments() == net.list_arguments()
    assert net2.list_outputs() == net.list_outputs()
    arg_shapes, out_shapes, _ = net2.infer_shape(data=(8, 20), label=(8,))
    assert out_shapes == [(8, 10)]


def test_save_load_file(tmp_path):
    net = _mlp()
    fname = str(tmp_path / "net.json")
    net.save(fname)
    net2 = sym.load(fname)
    assert net2.list_arguments() == net.list_arguments()


def test_method_style_ops():
    a = sym.Variable("a")
    out = a.reshape(shape=(2, 2)).sum()
    ex = out.bind(mx.cpu(), {"a": mx.nd.array([1.0, 2.0, 3.0, 4.0])})
    np.testing.assert_allclose(ex.forward()[0].asnumpy(), 10.0)


def test_variable_attrs():
    v = sym.Variable("w", shape=(3, 4), lr_mult=2.0)
    assert v.attr("__shape__") == "(3, 4)"
    assert v.attr("__lr_mult__") == "2.0"


def test_keyword_input_after_gap():
    # bias given by keyword with weight omitted must land in the bias
    # slot (weight auto-created), not be silently dropped
    data = sym.Variable("data")
    my_bias = sym.Variable("my_bias")
    fc = sym.FullyConnected(data, bias=my_bias, num_hidden=4,
                            name="fc")
    args = fc.list_arguments()
    assert "my_bias" in args, args
    assert "fc_bias" not in args, args
    assert "fc_weight" in args, args
    # executor actually uses the provided bias
    import incubator_mxnet_tpu as mx2
    ex = fc.simple_bind(mx2.cpu(), data=(2, 3))
    ex.arg_dict["data"][:] = 0
    ex.arg_dict["my_bias"][:] = mx2.nd.array(
        np.arange(4, dtype="float32"))
    out = ex.forward()[0].asnumpy()
    np.testing.assert_allclose(out, np.tile(np.arange(4.0), (2, 1)))


def test_unknown_symbol_kwarg_raises():
    data = sym.Variable("data")
    stray = sym.Variable("stray")
    try:
        sym.FullyConnected(data, bogus_input=stray, num_hidden=4)
    except TypeError as e:
        assert "bogus_input" in str(e)
    else:
        raise AssertionError("unknown Symbol kwarg accepted")


def test_nd_missing_input_raises():
    import incubator_mxnet_tpu as mx2
    x = mx2.nd.array(np.zeros((2, 3), "float32"))
    b = mx2.nd.array(np.zeros(4, "float32"))
    try:
        mx2.nd.FullyConnected(x, bias=b, num_hidden=4)
    except TypeError as e:
        assert "bias" in str(e)
    else:
        raise AssertionError("gap before keyword array accepted")
