"""Serving survival layer (ISSUE 11; docs/serving.md "SLOs,
shedding, and drain"): per-request deadlines, client cancellation,
admission control / overload shedding, graceful drain, atomic
snapshot + token-identical crash-resume, the decode-step watchdog,
the serve:step/serve:deadline/serve:queue fault scopes, terminal-
event parity, chaos interleavings under a tiny pool, and the
monotonic-clock lint rule."""
import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import resilience, telemetry, tracing
from incubator_mxnet_tpu.gluon.model_zoo.transformer import (
    TransformerLM)
from incubator_mxnet_tpu.serving import (
    CANCELLED, EXPIRED, FAILED, FINISHED, RequestTooLargeError,
    ServeRejectedError, ServingEngine, ServingError)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

VOCAB = 37

TERMINAL_EVENTS = ("serve_retire", "serve_evict", "serve_expire",
                   "serve_cancel")


def _tiny(vocab=VOCAB, **kw):
    cfg = dict(d_model=32, n_layers=2, n_heads=4, max_len=64)
    cfg.update(kw)
    mx.random.seed(0)
    net = TransformerLM(vocab, **cfg)
    net.initialize(mx.initializer.Xavier())
    return net


_NET = None


def _shared_net():
    """One tiny LM for the whole module: weights are deterministic
    (seeded init), engines never mutate the model, and sharing it
    keeps the generate()-reference compile cache warm across tests."""
    global _NET
    if _NET is None:
        _NET = _tiny()
    return _NET


def _gen_ref(net, prompt, max_new):
    out = net.generate(
        mx.nd.array(np.asarray([prompt], np.int32)), max_new)
    return [int(t) for t in out.asnumpy()[0]]


def _counter(name):
    return telemetry.get_registry().counter(name).value


def _terminal_events(eng, req):
    evs = tracing.events(rid=req.id, engine=eng.engine_id)
    return [e for e in evs if e["event"] in TERMINAL_EVENTS]


# -------------------------------------------------------- deadlines
def test_env_default_deadlines_arm_requests(monkeypatch):
    monkeypatch.setenv("MXTPU_SERVE_DEADLINE", "5.0")
    monkeypatch.setenv("MXTPU_SERVE_TTFT_DEADLINE", "2.0")
    net = _shared_net()
    eng = ServingEngine(net, max_batch=1, block_size=4,
                        num_blocks=32)
    req = eng.submit([1, 2, 3], 2)
    assert req.deadline_ts is not None
    assert req.ttft_deadline_ts is not None
    # explicit override beats the env default; 0 disables
    r2 = eng.submit([1, 2, 3], 2, deadline=0, ttft_deadline=0)
    assert r2.deadline_ts is None and r2.ttft_deadline_ts is None
    eng.run()


def test_ttft_deadline_expires_queued_request_without_leaks():
    net = _shared_net()
    rs = np.random.RandomState(41)
    prompts = [list(rs.randint(0, VOCAB, n)) for n in (8, 6)]
    ref0 = _gen_ref(net, prompts[0], 10)
    exp0 = _counter("serving_expired_total")
    eng = ServingEngine(net, max_batch=1, block_size=4,
                        num_blocks=64, prefix_cache=False)
    r1 = eng.submit(prompts[0], 10)
    # queued behind r1 in the only slot; a millisecond TTFT budget
    # cannot survive even one decode iteration
    r2 = eng.submit(prompts[1], 10, ttft_deadline=1e-3)
    out = eng.run()
    assert r1.state == FINISHED
    assert [int(t) for t in r1.tokens] == ref0
    assert r2.state == EXPIRED
    assert isinstance(r2.error, resilience.DeadlineExceededError)
    assert r2.generated == []
    assert r2.id in out                 # partial output reported
    assert eng.pool.num_allocated == 0
    assert _counter("serving_expired_total") - exp0 == 1
    terms = _terminal_events(eng, r2)
    assert [e["event"] for e in terms] == ["serve_expire"]
    assert terms[0]["why"] == "ttft"
    assert terms[0]["queue_wait_s"] >= 0
    assert eng.stats()["terminal_counts"][EXPIRED] == 1


def test_total_deadline_expires_midstream_retaining_output():
    net = _shared_net()
    eng = ServingEngine(net, max_batch=1, block_size=4,
                        num_blocks=64, prefix_cache=False)
    req = eng.submit([3, 1, 4, 1, 5], 20, deadline=60.0)
    for _ in range(4):
        eng.step()
    n_before = len(req.generated)
    assert 0 < n_before < 20
    # force the breach: rewind the stamp AND the engine's earliest-
    # deadline gate (deadlines are submit-time API; mutating the
    # stamp directly is test-only surgery the gate can't see)
    req.deadline_ts = time.monotonic() - 1.0
    eng._deadline_next = 0.0
    eng.run()
    assert req.state == EXPIRED
    # partial output retained; blocks freed the same iteration
    assert len(req.generated) == n_before
    assert eng.pool.num_allocated == 0
    terms = _terminal_events(eng, req)
    assert [e["event"] for e in terms] == ["serve_expire"]
    assert terms[0]["why"] == "total"


def test_injected_deadline_breach_expires_nth_submission(monkeypatch):
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "serve:deadline:2:error")
    resilience.reset_faults()
    try:
        net = _shared_net()
        eng = ServingEngine(net, max_batch=2, block_size=4,
                            num_blocks=64, prefix_cache=False)
        r1 = eng.submit([1, 2, 3], 3)
        r2 = eng.submit([4, 5, 6], 3)       # the poisoned one
        eng.run()
    finally:
        monkeypatch.setenv("MXTPU_FAULT_SPEC", "")
        resilience.reset_faults()
    assert r1.state == FINISHED
    assert r2.state == EXPIRED
    assert eng.pool.num_allocated == 0


# ----------------------------------------------------- cancellation
def test_cancel_queued_and_running_requests():
    net = _shared_net()
    rs = np.random.RandomState(43)
    prompts = [list(rs.randint(0, VOCAB, n)) for n in (7, 5, 9)]
    ref0 = _gen_ref(net, prompts[0], 8)
    can0 = _counter("serving_cancelled_total")
    eng = ServingEngine(net, max_batch=1, block_size=4,
                        num_blocks=64, prefix_cache=False)
    r1 = eng.submit(prompts[0], 8)
    r2 = eng.submit(prompts[1], 8)          # stays queued behind r1
    assert eng.cancel(r2.id) is True
    assert eng.cancel(r2.id) is False       # already marked
    assert eng.cancel(999) is False         # unknown id
    eng.step()                              # r1 admitted; r2 reaped
    assert r2.state == CANCELLED
    # cancel a RUNNING request mid-stream
    eng.step()
    assert eng.cancel(r1.id) is True
    eng.run()
    assert r1.state == CANCELLED
    assert 0 < len(r1.generated) < 8        # partial retained
    assert r1.tokens == _gen_ref(net, prompts[0], len(
        r1.generated)) == ref0[:len(r1.tokens)]
    assert eng.pool.num_allocated == 0
    assert _counter("serving_cancelled_total") - can0 == 2
    for r in (r1, r2):
        assert [e["event"] for e in _terminal_events(eng, r)] \
            == ["serve_cancel"]
        assert eng.cancel(r.id) is False    # terminal: not live
    counts = eng.stats()["terminal_counts"]
    assert counts[CANCELLED] == 2


def test_stream_abandon_cancels_request():
    net = _shared_net()
    eng = ServingEngine(net, max_batch=1, block_size=4,
                        num_blocks=64, prefix_cache=False)
    req = eng.submit([2, 7, 1, 8], 12)
    got = []
    for tok in eng.stream_request(req):
        got.append(tok)
        if len(got) == 3:
            break                       # client hangs up
    # the abandon path only FLAGS (a GC finalizer may run it in
    # contexts where locks/mutation would deadlock); the next
    # iteration finalizes and frees the blocks
    assert req.cancel_requested and not req.done
    eng.step()
    assert req.state == CANCELLED
    assert req.generated[:3] == got
    assert eng.pool.num_allocated == 0
    assert not eng.has_work()
    # a full consumption does NOT cancel
    r2 = eng.submit([5, 5, 6], 4)
    toks = list(eng.stream_request(r2))
    assert r2.state == FINISHED and len(toks) == 4


# ------------------------------------------- admission control/shed
def test_queue_limit_sheds_with_typed_error():
    net = _shared_net()
    rej0 = _counter("serving_rejected_total")
    eng = ServingEngine(net, max_batch=1, block_size=4,
                        num_blocks=64, queue_limit=2,
                        prefix_cache=False)
    reqs = [eng.submit([1, 2, 3], 2) for _ in range(2)]
    with pytest.raises(ServeRejectedError, match="queue_limit"):
        eng.submit([1, 2, 3], 2)
    assert isinstance(ServeRejectedError("x"), ServingError)
    assert _counter("serving_rejected_total") - rej0 == 1
    rejects = tracing.events("serve_reject", engine=eng.engine_id)
    assert len(rejects) == 1            # exactly one terminal event
    assert rejects[0]["reason"] == "queue_limit"
    assert rejects[0]["queue_depth"] == 2
    assert eng.stats()["terminal_counts"]["rejected"] == 1
    eng.run()
    assert all(r.state == FINISHED for r in reqs)


def test_queued_token_budget_sheds():
    net = _shared_net()
    eng = ServingEngine(net, max_batch=1, block_size=4,
                        num_blocks=64, queue_tokens=10,
                        prefix_cache=False)
    eng.submit(list(range(1, 9)), 2)            # 8 queued tokens
    with pytest.raises(ServeRejectedError, match="queue_tokens"):
        eng.submit(list(range(1, 9)), 2)        # would make 16
    eng.submit([1, 2], 2)                       # 10: still fits
    eng.run()
    assert eng._sched.queued_tokens == 0


def test_injected_queue_rejection(monkeypatch):
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "serve:queue:2:error")
    resilience.reset_faults()
    try:
        net = _shared_net()
        eng = ServingEngine(net, max_batch=1, block_size=4,
                            num_blocks=64, prefix_cache=False)
        eng.submit([1, 2, 3], 2)
        with pytest.raises(ServeRejectedError, match="injected"):
            eng.submit([4, 5, 6], 2)
        eng.run()
    finally:
        monkeypatch.setenv("MXTPU_FAULT_SPEC", "")
        resilience.reset_faults()


def test_queue_gauges_reported():
    net = _shared_net()
    eng = ServingEngine(net, max_batch=1, block_size=4,
                        num_blocks=64, prefix_cache=False)
    eng.submit([1, 2, 3, 4], 2)
    eng.submit([5, 6, 7], 2)
    reg = telemetry.get_registry()
    assert reg.gauge("serving_queue_depth").value == 2
    assert reg.gauge("serving_queued_prompt_tokens").value == 7
    eng.run()
    assert reg.gauge("serving_queue_depth").value == 0
    assert reg.gauge("serving_queued_prompt_tokens").value == 0


# ------------------------------------- impossible requests: typed
def test_impossible_requests_fail_loudly_not_hang():
    net = _shared_net()
    eng = ServingEngine(net, max_batch=1, block_size=4,
                        num_blocks=4, prefix_cache=False)
    # needs more blocks than the whole pool holds
    with pytest.raises(RequestTooLargeError, match="blocks"):
        eng.submit(list(range(10)), 8)
    # max_new_tokens can never be satisfied inside the context
    with pytest.raises(RequestTooLargeError, match="max_len"):
        eng.submit(list(range(30)), 40)
    # both are ValueErrors too (legacy handlers) and ServingErrors
    assert issubclass(RequestTooLargeError, ValueError)
    assert issubclass(RequestTooLargeError, ServingError)
    assert not eng.has_work()           # nothing queued: no hang


def test_restored_request_too_big_for_new_pool_fails_typed():
    """Satellite regression: a queued request the pool can never
    serve must terminate loudly (typed, per-request) instead of
    hanging run()/step() forever."""
    net = _shared_net()
    eng = ServingEngine(net, max_batch=1, block_size=4,
                        num_blocks=64, prefix_cache=False)
    big = eng.submit(list(range(1, 21)), 12)    # 8 blocks total
    small = eng.submit([1, 2, 3], 4)
    snap = eng.snapshot()
    eng.run()
    # restore into a pool that can never hold the big request
    eng2 = ServingEngine.restore(net, snap, num_blocks=6)
    t0 = time.monotonic()
    out = eng2.run()                    # must terminate, not hang
    assert time.monotonic() - t0 < 60
    restored = {s["id"]: s for s in eng2.stats()["requests"]}
    assert restored[big.id]["state"] == FAILED
    assert "blocks" in restored[big.id]["error"]
    assert restored[small.id]["state"] == FINISHED
    assert out[small.id] == _gen_ref(net, [1, 2, 3], 4)
    assert eng2.pool.num_allocated == 0


# --------------------------------------------------- drain/snapshot
def test_drain_stops_admission_finishes_running():
    net = _shared_net()
    rs = np.random.RandomState(47)
    prompts = [list(rs.randint(0, VOCAB, n)) for n in (6, 9, 5)]
    refs = [_gen_ref(net, p, 7) for p in prompts]
    dr0 = _counter("serving_drains_total")
    eng = ServingEngine(net, max_batch=1, block_size=4,
                        num_blocks=64, prefix_cache=False)
    reqs = [eng.submit(p, 7) for p in prompts]
    eng.step()                          # admit + first token for r0
    done = eng.drain()
    assert _counter("serving_drains_total") - dr0 == 1
    assert eng.drain() == {}            # idempotent, no double count
    assert _counter("serving_drains_total") - dr0 == 1
    # the running request finished exactly; queued ones stayed
    assert reqs[0].state == FINISHED and reqs[0].id in done
    assert [int(t) for t in reqs[0].tokens] == refs[0]
    assert [r.state for r in reqs[1:]] == ["queued", "queued"]
    with pytest.raises(ServeRejectedError, match="draining"):
        eng.submit([1, 2], 2)
    # stream()/run() return instead of spinning on the queue — and
    # has_work() agrees, so a manual `while eng.has_work():
    # eng.step()` driver exits instead of livelocking on requests
    # admission will never start
    assert eng.run() == {}
    assert not eng.has_work()
    # the queued requests land in the snapshot and complete
    # token-identically in a fresh engine
    snap = eng.snapshot()
    assert sorted(e["id"] for e in snap["requests"]) == \
        sorted(r.id for r in reqs[1:])
    eng2 = ServingEngine.restore(net, snap)
    out = eng2.run()
    assert out[reqs[1].id] == refs[1]
    assert out[reqs[2].id] == refs[2]
    assert eng2.pool.num_allocated == 0


def test_snapshot_restore_mid_decode_token_identical(tmp_path):
    net = _shared_net()
    rs = np.random.RandomState(53)
    prompts = [list(rs.randint(0, VOCAB, n)) for n in (4, 11, 7)]
    refs = [_gen_ref(net, p, 13) for p in prompts]
    eng = ServingEngine(net, max_batch=2, block_size=4,
                        num_blocks=64, prefix_cache=False)
    reqs = [eng.submit(p, 13, deadline=600.0) for p in prompts]
    for _ in range(5):
        eng.step()                      # mid-decode state
    path = str(tmp_path / "serve.snap")
    snap = eng.snapshot(path)
    assert os.path.exists(path)
    assert os.path.exists(path + ".crc32")      # atomic + CRC
    assert [e["id"] for e in snap["requests"]] == \
        [r.id for r in reqs]            # running first, then queue
    assert all(e["deadline_remaining_s"] > 0
               for e in snap["requests"])
    eng2 = ServingEngine.restore(net, path)
    out = eng2.run()
    for req, ref in zip(reqs, refs):
        assert out[req.id] == ref       # bitwise continuation
    assert eng2.pool.num_allocated == 0
    assert eng2._next_id == eng._next_id
    # restored lifecycles are complete in the ring under the NEW
    # engine id: enqueue(restored) ... exactly one terminal
    for req in reqs:
        evs = tracing.events(rid=req.id, engine=eng2.engine_id)
        assert evs[0]["event"] == "serve_enqueue"
        assert evs[0]["restored"] is True
        assert [e["event"] for e in evs
                if e["event"] in TERMINAL_EVENTS] == ["serve_retire"]


def test_corrupt_snapshot_raises_typed(tmp_path):
    net = _shared_net()
    eng = ServingEngine(net, max_batch=1, block_size=4,
                        num_blocks=32)
    eng.submit([1, 2, 3], 2)
    path = str(tmp_path / "serve.snap")
    eng.snapshot(path)
    with open(path, "r+b") as f:        # flip a byte
        b = f.read(1)
        f.seek(0)
        f.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(resilience.CheckpointCorruptError):
        ServingEngine.restore(net, path)
    eng.run()


def test_snapshot_excludes_cancelled_requests():
    net = _shared_net()
    eng = ServingEngine(net, max_batch=1, block_size=4,
                        num_blocks=64, prefix_cache=False)
    keep = eng.submit([1, 2, 3], 2)
    gone = eng.submit([4, 5, 6], 2)
    eng.cancel(gone.id)
    snap = eng.snapshot()       # the client already hung up
    assert [e["id"] for e in snap["requests"]] == [keep.id]
    eng.run()


def test_snapshot_rearms_remaining_deadline(tmp_path):
    net = _shared_net()
    eng = ServingEngine(net, max_batch=1, block_size=4,
                        num_blocks=64, prefix_cache=False)
    live = eng.submit([1, 2, 3], 3, deadline=600.0)
    doomed = eng.submit([4, 5, 6], 3, deadline=1e-4)
    time.sleep(0.01)                    # let the tiny deadline pass
    snap = eng.snapshot()
    by_id = {e["id"]: e for e in snap["requests"]}
    assert by_id[doomed.id]["deadline_remaining_s"] < 0
    eng2 = ServingEngine.restore(net, snap)
    eng2.run()
    summaries = {s["id"]: s for s in eng2.stats()["requests"]}
    assert summaries[doomed.id]["state"] == EXPIRED
    assert summaries[live.id]["state"] == FINISHED
    eng.run()


def test_restore_does_not_reemit_first_token():
    """A request whose first token shipped pre-crash must not emit
    a second serve_first_token after restore (lifecycle parity: one
    first token per request, ever) nor observe a second TTFT
    histogram sample."""
    net = _shared_net()
    eng = ServingEngine(net, max_batch=1, block_size=4,
                        num_blocks=64, prefix_cache=False)
    req = eng.submit([1, 2, 3, 4, 5], 6)
    while req.first_token_ts is None:
        eng.step()
    snap = eng.snapshot()
    assert snap["requests"][0]["ttft_done"] is True
    eng2 = ServingEngine.restore(net, snap)
    out = eng2.run()
    assert len(out[req.id]) == 5 + 6
    assert [e["event"]
            for e in tracing.events(rid=req.id,
                                    engine=eng2.engine_id)
            if e["event"] == "serve_first_token"] == []
    eng.cancel(req.id)
    eng.run()


def test_restore_already_complete_request_does_not_overrun():
    """A snapshot can catch a request BETWEEN its last generated
    token and its same-iteration retirement (req.done not yet
    latched): restore must retire it, not re-queue it — a re-
    admission would decode one token past the budget."""
    net = _shared_net()
    eng = ServingEngine(net, max_batch=1, block_size=4,
                        num_blocks=64, prefix_cache=False)
    req = eng.submit([1, 2, 3], 4)
    full = eng.run()[req.id]
    snap = eng.snapshot()               # craft the racy capture
    snap["requests"] = [{
        "id": 9, "prompt": [1, 2, 3], "generated": full[3:],
        "max_new_tokens": 4, "eos_id": None,
        "queue_wait_s": 0.0, "prefill_s": 0.0, "preemptions": 0,
        "ttft_done": True, "ttft_remaining_s": None,
        "deadline_remaining_s": None,
    }]
    snap["next_id"] = 10
    eng2 = ServingEngine.restore(net, snap)
    out2 = eng2.run()
    assert out2[9] == full              # complete — not budget + 1
    assert eng2.stats()["terminal_counts"] == {FINISHED: 1}
    assert eng2.pool.num_allocated == 0
    terms = [e for e in tracing.events(rid=9, engine=eng2.engine_id)
             if e["event"] in TERMINAL_EVENTS]
    assert [e["event"] for e in terms] == ["serve_retire"]


def test_sigterm_latch_counts_drain(tmp_path):
    """The SIGTERM handler's drain latch must land in
    serving_drains_total and leave the serve_drain event
    (docs/observability.md: 'SIGTERM wiring counts here') — and a
    later explicit drain() must not double-count."""
    net = _shared_net()
    eng = ServingEngine(net, max_batch=1, block_size=4,
                        num_blocks=64, prefix_cache=False)
    eng.submit([1, 2, 3], 2)
    path = str(tmp_path / "sig.snap")
    before = _counter("serving_drains_total")
    prev = signal.getsignal(signal.SIGTERM)
    try:
        # pin a benign baseline: install_sigterm(drain=True) chains
        # whatever Python handler is ambient (e.g. the tracing dump
        # handler another test left installed), and an ambient chain
        # ending in SIG_DFL would kill this very process
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        assert eng.install_sigterm(path, drain=True)
        signal.raise_signal(signal.SIGTERM)
    finally:
        signal.signal(signal.SIGTERM, prev)
    assert eng._draining
    assert os.path.exists(path)
    assert _counter("serving_drains_total") == before + 1
    assert [e for e in tracing.events(engine=eng.engine_id)
            if e["event"] == "serve_drain"] != []
    eng.drain()                 # idempotent: latched once, not twice
    assert _counter("serving_drains_total") == before + 1


def test_stream_request_sees_tokens_from_other_drivers():
    """Continuous batching decodes every running request whichever
    driver steps the engine: a stream_request consumer must receive
    tokens produced by run()/another stream's steps, not only those
    of its own step() calls."""
    net = _shared_net()
    eng = ServingEngine(net, max_batch=2, block_size=4,
                        num_blocks=64, prefix_cache=False)
    r1 = eng.submit([1, 2, 3], 4)
    r2 = eng.submit([4, 5, 6], 4)
    g1 = eng.stream_request(r1)
    g2 = eng.stream_request(r2)
    assert list(g1) == r1.generated     # drives r2 alongside r1
    assert r2.state == FINISHED         # finished by g1's steps
    assert list(g2) == r2.generated     # no token lost
    r3 = eng.submit([7, 8], 3)
    g3 = eng.stream_request(r3)
    eng.run()                           # an entirely different driver
    assert list(g3) == r3.generated


def test_abandon_cancel_not_counted():
    """A stream-abandon cancellation never bumped _cancels_pending,
    so its finalize must not decrement it either — an uncounted
    decrement would starve another request's real cancel() behind
    the reap gate."""
    net = _shared_net()
    eng = ServingEngine(net, max_batch=2, block_size=4,
                        num_blocks=64, prefix_cache=False)
    a = eng.submit([1, 2, 3], 8)
    b = eng.submit([4, 5, 6], 8)
    gen = eng.stream_request(a)
    next(gen)
    gen.close()                     # abandon: flagged, NOT counted
    assert a.cancel_requested and not a.cancel_counted
    assert eng.cancel(b.id)         # real cancel: counted
    assert b.cancel_counted and eng._cancels_pending == 1
    eng.step()
    assert a.state == CANCELLED and b.state == CANCELLED
    assert eng._cancels_pending == 0    # exactly b's count released
    assert eng.pool.num_allocated == 0


def test_stream_abandon_before_first_next_cancels():
    """Dropping a stream_request generator that was NEVER started
    must still cancel: an unstarted generator's close()/GC runs no
    body code, so the finalizer on the generator object flags it."""
    import gc
    net = _shared_net()
    eng = ServingEngine(net, max_batch=1, block_size=4,
                        num_blocks=64, prefix_cache=False)
    req = eng.submit([9, 8, 7], 5)
    gen = eng.stream_request(req)
    del gen
    gc.collect()
    assert req.cancel_requested
    eng.step()
    assert req.state == CANCELLED
    assert eng.pool.num_allocated == 0


def test_stream_request_drain_exit_does_not_cancel():
    """A stream_request loop that exits because drain latched (not
    because the client hung up) must NOT cancel a still-queued
    request — it belongs to snapshot()/restore()."""
    net = _shared_net()
    eng = ServingEngine(net, max_batch=1, block_size=4,
                        num_blocks=64, prefix_cache=False)
    first = eng.submit([1, 2, 3], 2)
    queued = eng.submit([4, 5, 6], 2)
    gen = eng.stream_request(queued)    # queued behind the first
    eng.step()                          # admit `first` into the slot
    eng.drain()                         # finishes the running batch
    assert first.state == FINISHED
    assert list(gen) == []              # normal exit: out of work
    assert not queued.cancel_requested
    assert queued.state == "queued"
    snap = eng.snapshot()
    assert [e["id"] for e in snap["requests"]] == [queued.id]
    out = ServingEngine.restore(net, snap).run()
    assert len(out[queued.id]) == 3 + 2


def test_snapshot_captures_in_transit_request():
    """A SIGTERM snapshot landing inside _admit's pop->place window
    (or _preempt's clear->requeue) must still see the request — it
    is in neither the waiting queue nor a slot right then — and
    must not double-count it once it lands."""
    net = _shared_net()
    eng = ServingEngine(net, max_batch=1, block_size=4,
                        num_blocks=64, prefix_cache=False)
    req = eng.submit([1, 2, 3], 2)
    popped = eng._sched.pop_waiting()   # simulate mid-_admit
    eng._in_transit = popped
    snap = eng.snapshot()
    assert [e["id"] for e in snap["requests"]] == [req.id]
    eng._sched.push_front(popped)       # landed back: no dup entry
    snap2 = eng.snapshot()
    assert [e["id"] for e in snap2["requests"]] == [req.id]
    eng._in_transit = None
    eng.run()


def test_block_leak_audit_by_id_with_live_cache():
    """BlockPool.live() against PrefixCache.block_refs(): after an
    engine drains with the prefix cache ON, every live pool block's
    holders must be exactly the cache's refs — a terminal path
    leaking a request's hold on a shared block is caught by id."""
    net = _shared_net()
    eng = ServingEngine(net, max_batch=2, block_size=4,
                        num_blocks=64, prefix_cache=True)
    prompt = [1, 2, 3, 4, 5, 6, 7, 8, 9]
    eng.submit(prompt, 3)
    eng.submit(prompt, 3)       # shares the cached prompt blocks
    eng.run()
    refs = eng.cache.block_refs()
    assert refs                 # the cache genuinely holds blocks
    assert eng.pool.live() == refs
    assert eng.pool.num_allocated == sum(refs.values())


def test_sigterm_chains_python_handlers_on_drain(tmp_path):
    """install_sigterm(drain=True) must still run the previously
    installed Python handler — another engine's snapshot hook, the
    tracing post-mortem — before consuming the signal: the last
    installer must not silence the first."""
    net = _shared_net()
    e1 = ServingEngine(net, max_batch=1, block_size=4,
                       num_blocks=64, prefix_cache=False)
    e2 = ServingEngine(net, max_batch=1, block_size=4,
                       num_blocks=64, prefix_cache=False)
    e1.submit([1, 2], 2)
    e2.submit([3, 4], 2)
    p1 = str(tmp_path / "e1.snap")
    p2 = str(tmp_path / "e2.snap")
    hits = []
    prev = signal.getsignal(signal.SIGTERM)
    try:
        # benign baseline below the chain (see latch test): also
        # proves the chain runs all the way through both engines
        signal.signal(signal.SIGTERM,
                      lambda num, frame: hits.append(num))
        assert e1.install_sigterm(p1, drain=True)
        assert e2.install_sigterm(p2, drain=True)
        signal.raise_signal(signal.SIGTERM)
    finally:
        signal.signal(signal.SIGTERM, prev)
    assert os.path.exists(p2)           # last installer ran...
    assert os.path.exists(p1)           # ...and chained to the first
    assert hits == [signal.SIGTERM]     # ...down to the baseline
    assert e1._draining and e2._draining


def test_sigterm_falls_through_after_engine_gc(tmp_path):
    """The SIGTERM handler holds only a weakref: once the engine is
    gone it must chain to the previous disposition instead of
    silently consuming every SIGTERM (an unkillable process)."""
    import gc
    net = _shared_net()
    hits = []
    prev = signal.getsignal(signal.SIGTERM)
    try:
        signal.signal(signal.SIGTERM,
                      lambda num, frame: hits.append(num))
        eng = ServingEngine(net, max_batch=1, block_size=4,
                            num_blocks=64, prefix_cache=False)
        assert eng.install_sigterm(str(tmp_path / "gone.snap"),
                                   drain=True)
        del eng
        gc.collect()
        signal.raise_signal(signal.SIGTERM)
    finally:
        signal.signal(signal.SIGTERM, prev)
    assert hits == [signal.SIGTERM]
    assert not os.path.exists(str(tmp_path / "gone.snap"))


def test_stream_abandon_off_thread_only_flags():
    """Closing an abandoned stream_request generator on a NON-
    driving thread (the GC-finalizer case) must only flag the
    cancellation — scheduler/pool mutation is engine-loop territory
    — and the next iteration finalizes it without leaks."""
    net = _shared_net()
    eng = ServingEngine(net, max_batch=1, block_size=4,
                        num_blocks=64, prefix_cache=False)
    req = eng.submit([1, 2, 3], 4)
    gen = eng.stream_request(req)
    next(gen)
    t = threading.Thread(target=gen.close)
    t.start()
    t.join()
    assert req.cancel_requested and not req.done    # flagged only
    eng.step()                          # the engine loop reaps it
    assert req.state == CANCELLED
    assert eng.pool.num_allocated == 0


# ----------------------------------------------- kill-and-restore
def test_sigterm_kill_and_restore_e2e(tmp_path):
    """Acceptance: SIGTERM mid-decode snapshots the in-flight
    requests; a fresh engine in a NEW process restores them and
    every completed output is token-identical to an uninterrupted
    run, with zero leaked blocks."""
    snap = str(tmp_path / "serve.snap")
    child = textwrap.dedent(f"""
        import os, signal
        import numpy as np
        import incubator_mxnet_tpu as mx
        from incubator_mxnet_tpu.gluon.model_zoo.transformer import \\
            TransformerLM
        from incubator_mxnet_tpu.serving import ServingEngine

        mx.random.seed(0)
        net = TransformerLM(37, d_model=32, n_layers=2, n_heads=4,
                            max_len=64)
        net.initialize(mx.initializer.Xavier())
        rs = np.random.RandomState(3)
        prompts = [list(rs.randint(0, 37, n)) for n in (4, 9, 6)]
        eng = ServingEngine(net, max_batch=2, block_size=4,
                            num_blocks=64, prefix_cache=False)
        reqs = [eng.submit(p, 12) for p in prompts]
        assert eng.install_sigterm({snap!r}, drain=False)
        for _ in range(4):
            eng.step()                  # mid-decode: partial output
        assert any(r.generated for r in reqs)
        os.kill(os.getpid(), signal.SIGTERM)
        raise SystemExit("unreachable: SIGTERM must terminate")
    """)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", child], cwd=REPO,
                          env=env, capture_output=True, text=True,
                          timeout=240)
    assert proc.returncode == -signal.SIGTERM, proc.stderr
    assert os.path.exists(snap)
    with open(snap + ".crc32"):
        pass                            # CRC sidecar landed too
    net = _shared_net()
    rs = np.random.RandomState(3)
    prompts = [list(rs.randint(0, 37, n)) for n in (4, 9, 6)]
    refs = [_gen_ref(net, p, 12) for p in prompts]
    eng = ServingEngine.restore(net, snap)
    st = eng.stats()
    assert len(st["live"]) == 3         # N in-flight restored
    assert any(s["tokens_generated"] > 0 for s in st["live"])
    out = eng.run()
    for rid, ref in enumerate(refs):
        assert out[rid] == ref          # token-identical completion
    assert eng.pool.num_allocated == 0


def test_sigterm_drain_mode_finishes_running(tmp_path):
    net = _shared_net()
    eng = ServingEngine(net, max_batch=1, block_size=4,
                        num_blocks=64, prefix_cache=False)
    r1 = eng.submit([1, 2, 3, 4], 6)
    r2 = eng.submit([5, 6, 7], 6)
    path = str(tmp_path / "serve.snap")
    prev = signal.getsignal(signal.SIGTERM)
    try:
        # benign baseline: drain=True chains ambient Python
        # handlers (e.g. the tracing dump handler), and a chain
        # ending in SIG_DFL would kill this very process
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        assert eng.install_sigterm(path, drain=True)
        eng.step()
        os.kill(os.getpid(), signal.SIGTERM)    # consumed: no death
        eng.run()                       # drains the running request
    finally:
        signal.signal(signal.SIGTERM, prev)
    assert r1.state == FINISHED
    assert r2.state == "queued"         # left for the snapshot
    assert os.path.exists(path)
    assert eng._draining
    eng2 = ServingEngine.restore(net, path)
    out = eng2.run()
    assert out[r2.id] == _gen_ref(net, [5, 6, 7], 6)


def test_install_sigterm_rejected_off_main_thread(tmp_path):
    import threading
    net = _shared_net()
    eng = ServingEngine(net, max_batch=1, block_size=4,
                        num_blocks=32)
    results = []
    t = threading.Thread(target=lambda: results.append(
        eng.install_sigterm(str(tmp_path / "s"))))
    t.start()
    t.join()
    assert results == [False]


# --------------------------------------------------- step watchdog
def test_step_watchdog_dumps_flight_recorder(tmp_path, monkeypatch):
    path = str(tmp_path / "flight.jsonl")
    monkeypatch.setenv("MXTPU_TRACE_DUMP", path)
    net = _shared_net()
    eng = ServingEngine(net, max_batch=1, block_size=4,
                        num_blocks=32, prefix_cache=False,
                        step_timeout=1e-9)      # every step overruns
    eng.submit([1, 2, 3], 2)
    eng.run()
    assert tracing.events("serve_step_overrun",
                          engine=eng.engine_id)
    lines = [json.loads(line)
             for line in open(path).read().splitlines()]
    assert lines[0]["reason"] == "serve_step_overrun"
    assert any(e.get("event") == "serve_step_overrun"
               for e in lines[1:])


def test_injected_step_hang_trips_watchdog(monkeypatch):
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "serve:step:1:hang")
    monkeypatch.setenv("MXTPU_FAULT_HANG_S", "0.05")
    resilience.reset_faults()
    try:
        net = _shared_net()
        eng = ServingEngine(net, max_batch=1, block_size=4,
                            num_blocks=32, prefix_cache=False,
                            step_timeout=0.01)
        req = eng.submit([1, 2, 3], 2)
        eng.run()
        assert req.state == FINISHED    # overrun detected, not fatal
        evs = tracing.events("serve_step_overrun",
                             engine=eng.engine_id)
        assert evs and evs[0]["seconds"] >= 0.05
    finally:
        monkeypatch.setenv("MXTPU_FAULT_SPEC", "")
        resilience.reset_faults()


def test_injected_step_error_is_loud(monkeypatch):
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "serve:step:1:error")
    resilience.reset_faults()
    try:
        net = _shared_net()
        eng = ServingEngine(net, max_batch=1, block_size=4,
                            num_blocks=32, prefix_cache=False)
        eng.submit([1, 2, 3], 2)
        with pytest.raises(resilience.TransientError):
            eng.run()
    finally:
        monkeypatch.setenv("MXTPU_FAULT_SPEC", "")
        resilience.reset_faults()


# ------------------------------------------------------ chaos sweep
@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_chaos_interleavings_no_leaks_no_stuck(seed, monkeypatch):
    """Randomized submit/cancel/expiry/preemption/fault-eviction
    interleavings under a tiny block pool: the engine must terminate
    (no stuck requests), leak zero blocks, close every lifecycle
    with exactly one terminal event, and every FINISHED survivor's
    output must be bitwise-identical to sequential generate()."""
    net = _shared_net()
    rs = np.random.RandomState(100 + seed)
    n, max_new = 6, 6
    prompts = [list(rs.randint(0, VOCAB, int(rs.randint(3, 14))))
               for _ in range(n)]
    refs = [_gen_ref(net, p, max_new) for p in prompts]
    monkeypatch.setenv("MXTPU_FAULT_SPEC",
                       f"serve:request:{rs.randint(1, 5)}:error")
    resilience.reset_faults()
    try:
        # capacity 9 blocks @ bs 4 vs up to 19-token streams:
        # concurrent requests force preemption cycles
        eng = ServingEngine(net, max_batch=2, block_size=4,
                            num_blocks=10, prefix_cache=False)
        reqs, cancel_at = [], {}
        i = steps = 0
        while i < n or eng.has_work():
            assert steps < 500, "engine stuck"
            for _ in range(min(n - i, int(rs.randint(0, 3)))):
                dl = 1e-9 if rs.random() < 0.2 else None
                r = eng.submit(prompts[i], max_new, deadline=dl)
                if rs.random() < 0.25:
                    cancel_at[r.id] = steps + int(rs.randint(0, 5))
                reqs.append(r)
                i += 1
            for rid, at in cancel_at.items():
                if at == steps:
                    eng.cancel(rid)
            eng.step()
            steps += 1
    finally:
        monkeypatch.setenv("MXTPU_FAULT_SPEC", "")
        resilience.reset_faults()
    assert eng.pool.num_allocated == 0, \
        f"leaked blocks: {eng.pool.live()}"
    # per-block-id audit: every live hold must be the cache's
    # (none here — cache off), so a leaking terminal path names
    # the exact block it forgot
    assert eng.pool.live() == eng.cache.block_refs()
    assert all(r.done for r in reqs)
    for idx, r in enumerate(reqs):
        if r.state == FINISHED:
            assert [int(t) for t in r.tokens] == refs[idx]
        terms = _terminal_events(eng, r)
        assert len(terms) == 1, (r, terms)
        assert terms[0]["queue_wait_s"] >= 0
    counts = eng.stats()["terminal_counts"]
    assert sum(counts.values()) == len(reqs)


# ------------------------------------------- launch.py aggregation
def test_launch_aggregates_serving_slo_signals():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "launch", os.path.join(REPO, "tools", "launch.py"))
    launch = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(launch)
    for name in ("serving_rejected_total", "serving_expired_total",
                 "serving_cancelled_total", "serving_drains_total"):
        assert name in launch._ERROR_COUNTERS
    snaps = {0: {"counters": {"serving_rejected_total": 3,
                              "serving_expired_total": 1},
                 "gauges": {"serving_queue_depth": 2,
                            "serving_queued_prompt_tokens": 40}},
             1: {"counters": {"serving_rejected_total": 2},
                 "gauges": {"serving_queue_depth": 1,
                            "serving_queued_prompt_tokens": 9}}}
    agg = launch._aggregate_telemetry(snaps)
    assert agg["counters"]["serving_rejected_total"] == 5
    assert agg["serve_queue"] == 3
    assert agg["serve_queued_tokens"] == 49
    status = launch._format_status(agg)
    assert "serve queue: 3 req (49 tok)" in status
    assert "serving_rejected_total=5" in status
    report = launch._format_report(snaps)
    assert "serving queue at exit: 3 req (49 tok)" in report


# -------------------------------------------------------- lint rule
def _load_lint():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "lint", os.path.join(REPO, "ci", "lint.py"))
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    return lint


def test_lint_forbids_wallclock_deadline_arithmetic(tmp_path):
    lint = _load_lint()
    d = tmp_path / "incubator_mxnet_tpu" / "serving"
    d.mkdir(parents=True)
    f = d / "x.py"
    f.write_text("import time\ndeadline = time.time() + 5\n")
    assert any("time.time()" in p for p in lint.check_file(f))
    f.write_text("import time\n"
                 "stamp = time.time()  # wallclock-ok: log stamp\n")
    assert not any("time.time()" in p for p in lint.check_file(f))
    # resilience.py is covered too; monotonic is always fine
    r = tmp_path / "incubator_mxnet_tpu" / "resilience.py"
    r.write_text("import time\nt = time.time()\n"
                 "m = time.monotonic()\n")
    probs = [p for p in lint.check_file(r) if "time.time()" in p]
    assert len(probs) == 1
    # outside the deadline modules the rule does not fire
    o = tmp_path / "incubator_mxnet_tpu" / "other.py"
    o.write_text("import time\nt = time.time()\n")
    assert not any("time.time()" in p for p in lint.check_file(o))


def test_lint_hot_sync_covers_survival_paths(tmp_path):
    lint = _load_lint()
    d = tmp_path / "incubator_mxnet_tpu" / "serving"
    d.mkdir(parents=True)
    eng = d / "engine.py"
    eng.write_text(
        "import numpy as np\n\n\n"
        "class E:\n"
        "    def _reap(self, x):\n"
        "        return np.asarray(x)\n\n"
        "    def snapshot(self, x):\n"
        "        return x.asnumpy()\n")
    probs = lint.check_file(eng)
    assert sum("host sync" in p for p in probs) == 2
