"""C++ frontend (ref role: cpp-package/include/mxnet-cpp/MxNetCpp.h —
native code COMPOSING models, not just running exported JSON).

Compiles a real C++ program against src/cpp_package/mxtpu_cpp.hpp
that builds a 2-layer MLP forward pass, computes its gradients with
hand-written backprop from registry ops, and trains through KVStore —
demonstrating the compose-train-read loop entirely from C++."""
import os
import subprocess

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CAPI = os.path.join(REPO, "src", "c_api")
CPP = os.path.join(REPO, "src", "cpp_package")


def _build_capi():
    # unconditional: make's timestamp tracking makes the fresh case a
    # no-op, and a stale cached .so (predating the glue this test
    # needs) would otherwise fail spuriously
    subprocess.run(["make", "-C", CAPI], check=True,
                   capture_output=True, timeout=300)
    return os.path.join(CAPI, "libmxtpu_capi.so")


DEMO_CPP = r"""
// Linear regression composed and trained in pure C++: forward from
// registry ops, analytic gradient, KVStore SGD updates store-side.
#include <cstdio>
#include <vector>
#include "mxtpu_cpp.hpp"

using mxtpu::NDArray;
using mxtpu::Context;

int main() {
    const int N = 64, D = 4;
    // synthetic y = X w*  with fixed pseudo-random X
    std::vector<float> xv(N * D), yv(N);
    unsigned s = 123456789u;
    auto rnd = [&s]() {
        s = s * 1103515245u + 12345u;
        return float((s >> 16) & 0x7fff) / 32768.0f - 0.5f;
    };
    float wstar[D] = {1.5f, -2.0f, 0.5f, 3.0f};
    for (int i = 0; i < N; ++i) {
        float t = 0;
        for (int j = 0; j < D; ++j) {
            xv[i * D + j] = rnd();
            t += xv[i * D + j] * wstar[j];
        }
        yv[i] = t;
    }
    Context ctx = Context::Cpu();
    NDArray X(xv, {N, D}, ctx);
    NDArray y(yv, {N, 1}, ctx);
    NDArray w({D, 1}, ctx);             // zeros

    mxtpu::KVStore kv("local");
    kv.Init("w", w);
    kv.SetOptimizer("sgd", 0.5f);

    float first = -1, last = -1;
    for (int it = 0; it < 60; ++it) {
        kv.Pull("w", &w);
        NDArray pred = mxtpu::dot(X, w);
        NDArray resid = pred - y;                     // (N,1)
        NDArray loss = mxtpu::mean(resid * resid);
        float l = loss.CopyTo()[0];
        if (it == 0) first = l;
        last = l;
        // dL/dw = 2/N * X^T resid
        NDArray grad = mxtpu::dot(X, resid, /*transpose_a=*/true)
                       * (2.0f / N);
        kv.Push("w", grad);
    }
    kv.Pull("w", &w);
    std::vector<float> wf = w.CopyTo();
    printf("LOSS %.6f %.6f\n", first, last);
    for (int j = 0; j < D; ++j) printf("W %.6f\n", wf[j]);

    // operator-builder path with parameters: FullyConnected
    NDArray fcw({3, (mx_uint)D}, ctx);
    std::vector<float> fwv(3 * D, 0.25f);
    fcw.CopyFrom(fwv);
    NDArray fcb({3}, ctx);
    auto fc = mxtpu::Operator("FullyConnected")
                  .AddInput(X).AddInput(fcw).AddInput(fcb)
                  .SetParam("num_hidden", 3)
                  .Invoke();
    auto shp = fc[0].Shape();
    printf("FC %u %u\n", shp[0], shp[1]);
    NDArray::WaitAll();
    return 0;
}
"""


def test_cpp_package_compose_and_train(tmp_path):
    _build_capi()
    demo_cpp = tmp_path / "demo.cpp"
    demo_cpp.write_text(DEMO_CPP)
    demo = str(tmp_path / "demo")
    subprocess.run(
        ["g++", "-O2", "-std=c++17", "-I", CAPI, "-I", CPP,
         str(demo_cpp), "-o", demo, "-L", CAPI,
         f"-Wl,-rpath,{CAPI}", "-lmxtpu_capi"],
        check=True, capture_output=True, timeout=180)

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["MXTPU_FORCE_CPU"] = "1"
    env.pop("XLA_FLAGS", None)
    r = subprocess.run([demo], capture_output=True, text=True,
                       timeout=600, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = r.stdout.strip().splitlines()
    first, last = map(float, lines[0].split()[1:])
    assert last < 0.01 * first, (first, last)   # trained hard
    w = np.array([float(l.split()[1]) for l in lines[1:5]])
    np.testing.assert_allclose(w, [1.5, -2.0, 0.5, 3.0], atol=0.05)
    assert lines[5].split()[1:] == ["64", "3"]
