"""DLPack interchange (ref role: dmlc/dlpack submodule in
.gitmodules — zero-copy tensor exchange with other frameworks)."""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd


def test_dlpack_protocol_to_torch():
    import torch
    a = nd.array(np.arange(6, dtype="float32").reshape(2, 3))
    t = torch.from_dlpack(a)          # consumes __dlpack__
    assert t.shape == (2, 3) and t.dtype == torch.float32
    np.testing.assert_allclose(t.numpy(), a.asnumpy())


def test_from_dlpack_torch_and_numpy():
    import torch
    t = torch.arange(8).float().reshape(2, 4)
    a = nd.from_dlpack(t)
    assert isinstance(a, nd.NDArray) and a.shape == (2, 4)
    np.testing.assert_allclose(a.asnumpy(), t.numpy())
    # interchange result is usable as a normal operand
    np.testing.assert_allclose((a + 1).asnumpy(), t.numpy() + 1)


def test_capsule_roundtrip():
    a = nd.array(np.eye(3, dtype="float32"))
    cap = nd.to_dlpack_for_read(a)
    b = nd.from_dlpack(cap)
    np.testing.assert_allclose(b.asnumpy(), np.eye(3))
    cap2 = nd.to_dlpack_for_write(a)
    c = nd.from_dlpack(cap2)
    np.testing.assert_allclose(c.asnumpy(), np.eye(3))


def test_dlpack_device():
    a = nd.array(np.zeros(2, "float32"))
    dev_type, dev_id = a.__dlpack_device__()
    assert isinstance(dev_type, int) and isinstance(dev_id, int)
