"""Flash attention Pallas kernel (ops/flash.py): forward numerics
against the XLA oracle, gradients through the custom VJP, registry
integration with the autograd tape, and model integration.

Runs in Pallas interpret mode on the CPU mesh; the same kernel
compiles via Mosaic on TPU.
"""
import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, nd
from incubator_mxnet_tpu.ops.flash import (_reference_attention,
                                           flash_attention)


def _rand(bh, l, d, seed=0):
    rs = np.random.RandomState(seed)
    return [jnp.asarray(rs.normal(0, 1, (bh, l, d)), jnp.float32)
            for _ in range(3)]


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("l", [128, 256])
def test_forward_matches_reference(causal, l):
    q, k, v = _rand(2, l, 64)
    got = flash_attention(q, k, v, causal=causal, interpret=True)
    ref = _reference_attention(q, k, v, causal, 1.0 / 8.0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_multiple_key_tiles_online_softmax():
    # L=256 with 128-tiles forces >1 inner iteration: the running
    # max/denominator rescaling is actually exercised
    q, k, v = _rand(1, 256, 32, seed=3)
    got = flash_attention(q, k, v, causal=True, interpret=True)
    ref = _reference_attention(q, k, v, True, 1.0 / math.sqrt(32))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_gradients_match_reference():
    q, k, v = _rand(2, 128, 32, seed=1)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_reference_attention(
            q, k, v, True, 1.0 / math.sqrt(32)) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_unsupported_shape_falls_back():
    # L=200 is untileable (200 % 128 != 0): must take the XLA
    # reference fallback, preserving causal flag and scale
    from incubator_mxnet_tpu.ops import flash as flash_mod
    q, k, v = _rand(1, 200, 16)
    assert not flash_mod._supported(q, k)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = _reference_attention(q, k, v, True, 0.25)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_registry_op_and_tape():
    # the op is on the nd namespace and records on the autograd tape
    rs = np.random.RandomState(0)
    q = nd.array(rs.normal(0, 1, (2, 128, 16)).astype("float32"))
    k = nd.array(rs.normal(0, 1, (2, 128, 16)).astype("float32"))
    v = nd.array(rs.normal(0, 1, (2, 128, 16)).astype("float32"))
    for t in (q, k, v):
        t.attach_grad()
    with autograd.record():
        out = nd._internal._flash_attention(q, k, v, causal=True,
                                            interpret=True)
        s = (out * out).sum()
    s.backward()
    ref = _reference_attention(q._data, k._data, v._data, True,
                               0.25)
    np.testing.assert_allclose(out.asnumpy(), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    assert float(np.abs(q.grad.asnumpy()).max()) > 0
    assert float(np.abs(k.grad.asnumpy()).max()) > 0


def test_model_uses_flash(monkeypatch):
    # MXTPU_FLASH=1 routes CausalSelfAttention through the kernel and
    # must reproduce the default path's logits
    from incubator_mxnet_tpu.gluon.model_zoo.transformer import \
        TransformerLM
    mx.random.seed(0)
    net = TransformerLM(37, d_model=32, n_layers=2, n_heads=4,
                        max_len=128)
    net.initialize(mx.initializer.Xavier())
    toks = mx.nd.array(np.random.RandomState(0)
                       .randint(0, 37, (2, 128)).astype("int32"))
    ref = net(toks).asnumpy()
    monkeypatch.setenv("MXTPU_FLASH", "1")
    from incubator_mxnet_tpu.ops import registry
    calls = []
    op = registry.OPS["_flash_attention"]
    orig_fn = op.fn

    def spy(*a, **kw):
        calls.append(1)
        return orig_fn(*a, **kw)

    monkeypatch.setattr(op, "fn", spy)
    got = net(toks).asnumpy()
    assert calls, "flash path never engaged despite MXTPU_FLASH=1"
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_flash_backward_matches_reference_vjp():
    """The tiled backward kernels (dq/dk/dv from lse residuals, no
    L x L tensor) must match autodiff through the XLA oracle."""
    import jax
    import jax.numpy as jnp

    from incubator_mxnet_tpu.ops.flash import (_flash,
                                               _reference_attention)

    rs = np.random.RandomState(0)
    for causal in (True, False):
        for bh, l, d in [(2, 128, 32), (3, 256, 16)]:
            q = jnp.asarray(rs.randn(bh, l, d), jnp.float32)
            k = jnp.asarray(rs.randn(bh, l, d), jnp.float32)
            v = jnp.asarray(rs.randn(bh, l, d), jnp.float32)
            g = jnp.asarray(rs.randn(bh, l, d), jnp.float32)
            scale = 1.0 / np.sqrt(d)
            out, vjp = jax.vjp(
                lambda a, b, c: _flash(a, b, c, causal, scale, True, 0),
                q, k, v)
            ref_out, ref_vjp = jax.vjp(
                lambda a, b, c: _reference_attention(
                    a, b, c, causal, scale), q, k, v)
            np.testing.assert_allclose(np.asarray(out),
                                       np.asarray(ref_out),
                                       rtol=2e-4, atol=2e-4)
            for got, want, name in zip(vjp(g), ref_vjp(g),
                                       ("dq", "dk", "dv")):
                np.testing.assert_allclose(
                    np.asarray(got), np.asarray(want), rtol=2e-3,
                    atol=2e-3, err_msg=f"{name} causal={causal} "
                    f"shape={(bh, l, d)}")


def test_long_sequence_stays_on_pallas_path():
    """The r5 streaming kernels hold VMEM at O(block) regardless of
    sequence length, so long-context shapes stay on the Pallas path
    (the r4 whole-sequence staging fell back past L*D ~ 2^20) and
    must match the reference end to end, gradients included."""
    from incubator_mxnet_tpu.ops import flash as flash_mod

    # L*D here is deliberately above any per-block budget story:
    # 2048*16 tiles into 16x16 blocks of the 128-grid
    q, k, v = _rand(1, 2048, 16)
    assert flash_mod._supported(q, k)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = _reference_attention(q, k, v, True, 1.0 / np.sqrt(16))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    def loss_f(fq, fk, fv):
        return (flash_attention(fq, fk, fv, causal=True,
                                interpret=True) ** 2).sum()

    def loss_r(fq, fk, fv):
        return (_reference_attention(fq, fk, fv, True,
                                     1.0 / np.sqrt(16)) ** 2).sum()

    gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3)


def test_sliding_window_attention():
    """window > 0: sliding-window causal (Mistral-style local
    attention) on the streaming kernels — numerics match the masked
    reference, all three gradients included, and out-of-band blocks
    are skipped (compute O(L * window))."""
    rs = np.random.RandomState(4)
    q, k, v = _rand(2, 512, 16)
    for w in (64, 200):
        out = flash_attention(q, k, v, causal=True, interpret=True,
                              window=w)
        ref = _reference_attention(q, k, v, True,
                                   1.0 / np.sqrt(16), window=w)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def loss_f(fq, fk, fv):
        return (flash_attention(fq, fk, fv, causal=True,
                                interpret=True, window=128) ** 2) \
            .sum()

    def loss_r(fq, fk, fv):
        return (_reference_attention(fq, fk, fv, True,
                                     1.0 / np.sqrt(16),
                                     window=128) ** 2).sum()

    gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3)

    # contract errors
    import pytest
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, k, v, causal=False, window=64)
    with pytest.raises(ValueError, match=">= 0"):
        flash_attention(q, k, v, window=-1)

    # window wider than the sequence == plain causal
    full = flash_attention(q, k, v, causal=True, interpret=True)
    wide = flash_attention(q, k, v, causal=True, interpret=True,
                           window=4096)
    np.testing.assert_allclose(np.asarray(wide), np.asarray(full),
                               rtol=1e-6)


def test_sliding_window_banded_grid_math():
    """The banded inner grid covers only in-window tiles (compute
    AND DMA are O(L * window)): grid-length and index/validity
    bookkeeping checked directly."""
    from incubator_mxnet_tpu.ops.flash import (_band_k_index,
                                               _band_nj,
                                               _band_q_index)
    bq = bk = 128
    nk = 64                      # L = 8192
    # window 256 -> at most (128+256-2)//128 + 2 = 4 k-tiles per
    # q-tile, NOT 64
    nj = _band_nj(256, bq, bk, nk)
    assert nj == 4, nj
    assert _band_nj(256, bq, bk, 2) == 2     # capped at full count

    # q-tile 32 with window 256 sees k positions 3841..4223 ->
    # tiles 30..32
    iqs, valids = [], []
    for j in range(nj):
        jk, valid = _band_k_index(32, j, bq, bk, nk, 256)
        iqs.append(int(jk)), valids.append(bool(valid))
    assert iqs[:3] == [30, 31, 32], iqs
    assert valids[:3] == [True, True, True]
    assert not valids[3]          # clamp duplicate excluded

    # dkv: k-tile 30 is seen by q tiles 30..32 (window 256)
    got = [(int(_band_q_index(30, j, bq, bk, nk, 256)[0]),
            bool(_band_q_index(30, j, bq, bk, nk, 256)[1]))
           for j in range(nj)]
    assert [g for g, ok in got if ok] == [30, 31, 32], got

    # cross-length window rejected
    import pytest
    q = jnp.zeros((1, 256, 16), jnp.float32)
    k = jnp.zeros((1, 128, 16), jnp.float32)
    with pytest.raises(ValueError, match="lq == lk"):
        flash_attention(q, k, k, causal=True, window=64)
