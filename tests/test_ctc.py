"""ctc_loss vs brute-force path-enumeration oracle + grad checks
(ref semantics: src/operator/contrib/ctc_loss.cc / warp-ctc)."""
import itertools

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, autograd


def _collapse(path, blank):
    out = []
    prev = None
    for p in path:
        if p != prev and p != blank:
            out.append(p)
        prev = p
    return tuple(out)


def np_ctc_brute(acts, label, blank):
    """Exact -log p(label) by enumerating all alphabet^T paths."""
    T, C = acts.shape
    e = np.exp(acts - acts.max(axis=1, keepdims=True))
    probs = e / e.sum(axis=1, keepdims=True)
    total = 0.0
    for path in itertools.product(range(C), repeat=T):
        if _collapse(path, blank) == tuple(label):
            p = 1.0
            for t, c in enumerate(path):
                p *= probs[t, c]
            total += p
    return -np.log(total)


@pytest.mark.parametrize("blank_label", ["first", "last"])
def test_ctc_loss_brute_force(blank_label):
    rs = np.random.RandomState(0)
    T, B, C = 4, 3, 3
    acts = rs.randn(T, B, C).astype(np.float32)
    if blank_label == "first":
        labels = np.array([[1, 2], [2, 0], [1, 0]], np.float32)
        seqs = [(1, 2), (2,), (1,)]
        blank = 0
    else:
        labels = np.array([[0, 1], [1, -1], [0, -1]], np.float32)
        seqs = [(0, 1), (1,), (0,)]
        blank = C - 1
    costs = nd._internal._contrib_CTCLoss(
        nd.array(acts), nd.array(labels),
        blank_label=blank_label).asnumpy()
    for b in range(B):
        want = np_ctc_brute(acts[:, b], seqs[b], blank)
        np.testing.assert_allclose(costs[b], want, rtol=1e-4)


def test_ctc_loss_lengths():
    rs = np.random.RandomState(1)
    T, B, C = 6, 2, 4
    acts = rs.randn(T, B, C).astype(np.float32)
    labels = np.array([[1, 2, 3], [3, 1, 1]], np.float32)
    dl = np.array([4, 6], np.float32)
    ll = np.array([2, 3], np.float32)
    costs = nd._internal._contrib_CTCLoss(
        nd.array(acts), nd.array(labels), nd.array(dl), nd.array(ll),
        use_data_lengths=True, use_label_lengths=True).asnumpy()
    want0 = np_ctc_brute(acts[:4, 0], (1, 2), 0)
    want1 = np_ctc_brute(acts[:, 1], (3, 1, 1), 0)
    np.testing.assert_allclose(costs, [want0, want1], rtol=1e-4)


def test_ctc_loss_grad_finite_diff():
    rs = np.random.RandomState(2)
    T, B, C = 3, 1, 3
    acts = rs.randn(T, B, C).astype(np.float32)
    labels = np.array([[1, 2]], np.float32)

    x = nd.array(acts)
    x.attach_grad()
    with autograd.record():
        loss = nd._internal._contrib_CTCLoss(x, nd.array(labels))
    loss.backward()
    g = x.grad.asnumpy()

    eps = 1e-3
    for t in range(T):
        for c in range(C):
            ap = acts.copy(); ap[t, 0, c] += eps
            am = acts.copy(); am[t, 0, c] -= eps
            fp = np_ctc_brute(ap[:, 0], (1, 2), 0)
            fm = np_ctc_brute(am[:, 0], (1, 2), 0)
            np.testing.assert_allclose(g[t, 0, c], (fp - fm) / (2 * eps),
                                       atol=2e-3)


def test_gluon_ctc_loss():
    """The shipped gluon CTCLoss must execute (round-1 bug) and use the
    gluon 'blank last' convention: classes 0..C-2, padding -1 (ref:
    gluon/loss.py:439-446)."""
    rs = np.random.RandomState(3)
    loss_fn = mx.gluon.loss.CTCLoss(layout="NTC")
    T, C = 5, 4
    pred_np = rs.randn(2, T, C).astype(np.float32)  # (N, T, C)
    label = nd.array(np.array([[1, 2], [0, -1]], np.float32))
    out = loss_fn(nd.array(pred_np), label).asnumpy()
    assert out.shape == (2,)
    # class 0 is a REAL token and -1 is padding; blank is channel C-1
    want0 = np_ctc_brute(pred_np[0], (1, 2), C - 1)
    want1 = np_ctc_brute(pred_np[1], (0,), C - 1)
    np.testing.assert_allclose(out, [want0, want1], rtol=1e-4)


def test_gluon_ctc_loss_grad():
    rs = np.random.RandomState(4)
    loss_fn = mx.gluon.loss.CTCLoss()
    pred = nd.array(rs.randn(2, 5, 4).astype(np.float32))
    label = nd.array(np.array([[1, 2], [0, -1]], np.float32))
    pred.attach_grad()
    with autograd.record():
        loss = loss_fn(pred, label).sum()
    loss.backward()
    g = pred.grad.asnumpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_ctc_loss_grad_finite_padded_lengths():
    """Regression: with per-sample label/data lengths, the extended
    states past 2*L_len+1 keep alpha == NEG on BOTH logaddexp inputs;
    the dead branch's vjp is then 0/0 and where-grad turned the whole
    backward NaN (caught by examples/speech_ctc.py: adam NaN-poisoned
    the weights after one step while the forward loss looked fine)."""
    rs = np.random.RandomState(7)
    T, B, C, L = 26, 8, 9, 6
    loss_fn = mx.gluon.loss.CTCLoss(layout="NTC", label_layout="NT")
    pred = nd.array(rs.randn(B, T, C).astype(np.float32))
    label = np.full((B, L), -1, np.float32)
    lab_len = rs.randint(3, L + 1, B)
    for i in range(B):
        label[i, :lab_len[i]] = rs.randint(0, C - 1, lab_len[i])
    dat_len = rs.randint(2 * L, T + 1, B).astype(np.float32)
    pred.attach_grad()
    with autograd.record():
        loss = loss_fn(pred, nd.array(label), nd.array(dat_len),
                       nd.array(lab_len.astype(np.float32))).mean()
    loss.backward()
    g = pred.grad.asnumpy()
    assert np.isfinite(loss.asnumpy()).all()
    assert np.isfinite(g).all(), "NaN/inf in CTC backward"
    assert np.abs(g).sum() > 0
