"""Gluon tests (ref: tests/python/unittest/test_gluon.py)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, nd
from incubator_mxnet_tpu.gluon import nn


def test_parameter_basic():
    p = gluon.Parameter("weight", shape=(3, 4))
    p.initialize(init=mx.init.One())
    assert p.data().shape == (3, 4)
    assert (p.data().asnumpy() == 1).all()
    assert p.grad().shape == (3, 4)
    p.zero_grad()
    assert (p.grad().asnumpy() == 0).all()


def test_parameter_dict_prefix_and_sharing():
    pd = gluon.ParameterDict("block_")
    w = pd.get("weight", shape=(2, 2))
    assert w.name == "block_weight"
    # sharing adopts the shared dict's prefix (reference: _BlockScope
    # creates the new dict with params.prefix when params= is passed)
    shared = gluon.ParameterDict("block_", shared=pd)
    w2 = shared.get("weight")
    assert w2 is w


def test_dense_forward_and_grad():
    net = nn.Dense(3, in_units=4, use_bias=True)
    net.initialize(mx.init.One())
    x = nd.ones((2, 4))
    with autograd.record():
        y = net(x)
        loss = y.sum()
    loss.backward()
    np.testing.assert_allclose(y.asnumpy(), np.full((2, 3), 4.0))
    np.testing.assert_allclose(net.weight.grad().asnumpy(),
                               np.full((3, 4), 2.0))


def test_dense_deferred_init():
    net = nn.Dense(5)
    net.initialize(mx.init.One())
    y = net(nd.ones((2, 7)))
    assert net.weight.shape == (5, 7)
    assert y.shape == (2, 5)


def test_sequential_and_trainer_training():
    rs = np.random.RandomState(0)
    centers = rs.randn(3, 10).astype("float32") * 3
    labels = rs.randint(0, 3, 300)
    data = (centers[labels] + rs.randn(300, 10)).astype("float32")

    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"))
    net.add(nn.Dense(3))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.2, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    ds = gluon.data.ArrayDataset(data, labels.astype("float32"))
    loader = gluon.data.DataLoader(ds, batch_size=50, shuffle=True)
    for epoch in range(5):
        for x, y in loader:
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            trainer.step(50)
    preds = net(nd.array(data)).asnumpy().argmax(1)
    acc = (preds == labels).mean()
    assert acc > 0.9, acc


def test_hybridize_matches_eager():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"))
    net.add(nn.Dense(4))
    net.initialize(mx.init.Xavier())
    x = nd.array(np.random.RandomState(1).rand(3, 8).astype("float32"))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    np.testing.assert_allclose(eager, hybrid, rtol=1e-5)


def test_hybridize_gradients():
    net = nn.Dense(2, in_units=3)
    net.initialize(mx.init.One())
    net.hybridize()
    x = nd.array([[1.0, 2.0, 3.0]])
    with autograd.record():
        y = net(x).sum()
    y.backward()
    np.testing.assert_allclose(net.weight.grad().asnumpy(),
                               np.tile([[1, 2, 3]], (2, 1)), rtol=1e-6)


def test_hybridized_batchnorm_updates_stats():
    net = nn.HybridSequential()
    net.add(nn.BatchNorm(in_channels=3))
    net.initialize()
    net.hybridize()
    bn = net[0]
    x = nd.array(np.random.RandomState(0).rand(8, 3).astype("float32")
                 * 5)
    before = bn.running_mean.data().asnumpy().copy()
    with autograd.record():
        y = net(x)
    after = bn.running_mean.data().asnumpy()
    assert not np.allclose(before, after)
    # eval mode leaves stats untouched
    y2 = net(x)
    np.testing.assert_allclose(bn.running_mean.data().asnumpy(), after)


def test_conv_layers():
    x = nd.ones((1, 3, 8, 8))
    conv = nn.Conv2D(6, 3, padding=1, in_channels=3)
    conv.initialize()
    assert conv(x).shape == (1, 6, 8, 8)
    convT = nn.Conv2DTranspose(4, 2, strides=2, in_channels=3)
    convT.initialize()
    assert convT(x).shape == (1, 4, 16, 16)
    pool = nn.MaxPool2D(2, 2)
    assert pool(x).shape == (1, 3, 4, 4)
    g = nn.GlobalAvgPool2D()
    assert g(x).shape == (1, 3, 1, 1)
    c1 = nn.Conv1D(4, 3, in_channels=3)
    c1.initialize()
    assert c1(nd.ones((2, 3, 10))).shape == (2, 4, 8)


def test_losses():
    pred = nd.array(np.array([[1.0, -1.0], [0.5, 0.5]], "float32"))
    label = nd.array(np.array([0, 1], "float32"))
    l = gluon.loss.SoftmaxCrossEntropyLoss()(pred, label)
    ref = -np.log([
        np.exp(1) / (np.exp(1) + np.exp(-1)),
        0.5])
    np.testing.assert_allclose(l.asnumpy(), ref, rtol=1e-5)
    l2 = gluon.loss.L2Loss()(pred, nd.zeros((2, 2)))
    np.testing.assert_allclose(
        l2.asnumpy(), [0.5 * (1 + 1) / 2, 0.5 * 0.25], rtol=1e-5)
    l1 = gluon.loss.L1Loss()(pred, nd.zeros((2, 2)))
    np.testing.assert_allclose(l1.asnumpy(), [1.0, 0.5], rtol=1e-5)
    hb = gluon.loss.HuberLoss()(pred, nd.zeros((2, 2)))
    assert hb.shape == (2,)
    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss()(
        pred, nd.ones((2, 2)))
    ref_bce = -np.log(1 / (1 + np.exp(-pred.asnumpy())))
    np.testing.assert_allclose(bce.asnumpy(), ref_bce.mean(1),
                               rtol=1e-4)


def test_save_load_params(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=3))
    net.add(nn.Dense(2, in_units=4))
    net.initialize(mx.init.Xavier())
    f = str(tmp_path / "net.params")
    net.save_params(f)
    net2 = nn.HybridSequential()
    net2.add(nn.Dense(4, in_units=3))
    net2.add(nn.Dense(2, in_units=4))
    net2.initialize()
    # fresh nets have different prefixes; reference requires matching
    # structure, so load via collect_params with prefix stripping
    x = nd.ones((1, 3))
    try:
        net2.load_params(f)
        np.testing.assert_allclose(net(x).asnumpy(), net2(x).asnumpy(),
                                   rtol=1e-6)
    except IOError:
        pytest.skip("prefix mismatch across instances (reference "
                    "behavior: construct with same prefix)")


def test_dataset_dataloader():
    data = np.arange(20, dtype="float32").reshape(10, 2)
    labels = np.arange(10, dtype="float32")
    ds = gluon.data.ArrayDataset(data, labels)
    assert len(ds) == 10
    x, y = ds[3]
    np.testing.assert_allclose(x.asnumpy(), [6, 7])
    loader = gluon.data.DataLoader(ds, batch_size=4, last_batch="keep")
    batches = list(loader)
    assert len(batches) == 3
    assert batches[0][0].shape == (4, 2)
    loader2 = gluon.data.DataLoader(ds, batch_size=4,
                                    last_batch="discard",
                                    num_workers=2)
    assert len(list(loader2)) == 2


def test_split_and_load():
    data = nd.arange(0, 12).reshape((6, 2))
    parts = gluon.utils.split_data(data, 3)
    assert len(parts) == 3 and parts[0].shape == (2, 2)
    loaded = gluon.utils.split_and_load(data, [mx.cpu(0), mx.cpu(1)])
    assert loaded[0].context == mx.cpu(0)
    assert loaded[1].context == mx.cpu(1)


def test_clip_global_norm():
    arrays = [nd.ones((2, 2)) * 3, nd.ones((2,)) * 4]
    total = gluon.utils.clip_global_norm(arrays, 1.0)
    new_total = np.sqrt(sum((a.asnumpy() ** 2).sum() for a in arrays))
    assert abs(new_total - 1.0) < 1e-4


def test_model_zoo_constructs():
    for name in ["resnet18_v1", "resnet50_v2", "alexnet", "vgg11",
                 "squeezenet1_0", "mobilenet0_25", "densenet121"]:
        net = gluon.model_zoo.get_model(name, classes=10)
        assert net is not None


def test_resnet18_forward():
    net = gluon.model_zoo.vision.resnet18_v1(classes=10)
    net.initialize(mx.init.Xavier())
    out = net(nd.ones((1, 3, 32, 32)))
    assert out.shape == (1, 10)


def test_resnet_hybridized_forward_and_train():
    net = gluon.model_zoo.vision.resnet18_v1(classes=4, thumbnail=True)
    net.initialize(mx.init.Xavier())
    x = nd.array(np.random.RandomState(0).rand(2, 3, 16, 16)
                 .astype("float32"))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    np.testing.assert_allclose(eager, hybrid, rtol=1e-4, atol=1e-5)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    with autograd.record():
        loss = loss_fn(net(x), nd.array([0.0, 1.0]))
    loss.backward()
    trainer.step(2)
    hybrid2 = net(x).asnumpy()
    assert not np.allclose(hybrid, hybrid2)  # weights moved


def test_block_repr_and_collect():
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=2), nn.Dense(2, in_units=4))
    params = net.collect_params()
    assert len(list(params.keys())) == 4
    sel = net.collect_params(".*weight")
    assert all(k.endswith("weight") for k in sel.keys())
