"""HybridBlock.export -> symbol JSON + params -> Predictor / Module
round-trip (VERDICT r2 task 8; ref: python/mxnet/gluon/block.py
HybridBlock.export, include/mxnet/c_predict_api.h)."""
import numpy as np

import incubator_mxnet_tpu as mx


def _build_net():
    mx.random.seed(0)
    net = mx.gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(mx.gluon.nn.Dense(16, activation="relu"),
                mx.gluon.nn.BatchNorm(),
                mx.gluon.nn.Dense(4))
    net.initialize(mx.initializer.Xavier())
    return net


def test_export_predict_roundtrip(tmp_path):
    net = _build_net()
    rs = np.random.RandomState(0)
    x = rs.rand(8, 12).astype(np.float32)
    want = net(mx.nd.array(x)).asnumpy()  # also settles shapes
    prefix = str(tmp_path / "model")
    net.export(prefix)

    pred = mx.Predictor(prefix + "-symbol.json",
                        prefix + "-0000.params",
                        {"data": (8, 12)})
    got = pred.predict(x)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    # C-api style set_input/forward/get_output
    pred.set_input("data", x)
    pred.forward()
    np.testing.assert_allclose(pred.get_output(0).asnumpy(), want,
                               rtol=1e-5, atol=1e-6)


def test_export_conv_net_and_reshape(tmp_path):
    mx.random.seed(1)
    net = mx.gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(mx.gluon.nn.Conv2D(8, 3, padding=1),
                mx.gluon.nn.Activation("relu"),
                mx.gluon.nn.MaxPool2D(2),
                mx.gluon.nn.Flatten(),
                mx.gluon.nn.Dense(5))
    net.initialize(mx.initializer.Xavier())
    rs = np.random.RandomState(1)
    x = rs.rand(2, 3, 8, 8).astype(np.float32)
    want = net(mx.nd.array(x)).asnumpy()
    prefix = str(tmp_path / "conv")
    net.export(prefix)
    pred = mx.Predictor(prefix + "-symbol.json",
                        prefix + "-0000.params",
                        {"data": (2, 3, 8, 8)})
    np.testing.assert_allclose(pred.predict(x), want, rtol=1e-5,
                               atol=1e-6)
    # MXPredReshape analog: new batch size
    pred2 = pred.reshape({"data": (4, 3, 8, 8)})
    x4 = rs.rand(4, 3, 8, 8).astype(np.float32)
    want4 = net(mx.nd.array(x4)).asnumpy()
    np.testing.assert_allclose(pred2.predict(x4), want4, rtol=1e-5,
                               atol=1e-6)


def test_export_served_by_module(tmp_path):
    """The exported artifact is a valid Module checkpoint too."""
    net = _build_net()
    rs = np.random.RandomState(2)
    x = rs.rand(8, 12).astype(np.float32)
    want = net(mx.nd.array(x)).asnumpy()
    prefix = str(tmp_path / "m")
    net.export(prefix)
    mod = mx.mod.Module.load(prefix, 0, data_names=("data",),
                             label_names=None)
    mod.bind(data_shapes=[("data", (8, 12))], for_training=False)
    mod.forward(mx.io.DataBatch([mx.nd.array(x)], None))
    np.testing.assert_allclose(mod.get_outputs()[0].asnumpy(), want,
                               rtol=1e-5, atol=1e-6)


def test_model_zoo_export(tmp_path):
    """A model-zoo resnet exports and serves (the deployment story
    for config-2 models)."""
    mx.random.seed(3)
    net = mx.gluon.model_zoo.vision.resnet18_v1(classes=10)
    net.initialize(mx.initializer.Xavier())
    rs = np.random.RandomState(3)
    x = rs.rand(2, 3, 32, 32).astype(np.float32)
    want = net(mx.nd.array(x)).asnumpy()
    prefix = str(tmp_path / "resnet")
    net.export(prefix)
    pred = mx.Predictor(prefix + "-symbol.json",
                        prefix + "-0000.params",
                        {"data": (2, 3, 32, 32)})
    np.testing.assert_allclose(pred.predict(x), want, rtol=1e-4,
                               atol=1e-5)


def test_export_tags_aux_states(tmp_path):
    """BatchNorm moving stats must export as aux:, not arg:
    (round-3 review regression)."""
    net = _build_net()
    x = np.random.RandomState(4).rand(4, 12).astype(np.float32)
    net(mx.nd.array(x))
    prefix = str(tmp_path / "auxcheck")
    sym = net.export(prefix)
    aux = sym.list_auxiliary_states()
    assert any("running_mean" in n for n in aux), aux
    assert any("running_var" in n for n in aux), aux
    from incubator_mxnet_tpu.predictor import load_params
    arg_params, aux_params = load_params(prefix + "-0000.params")
    assert any("running_mean" in n for n in aux_params), aux_params
    assert not any("running" in n for n in arg_params)


def test_predictor_positional_order_and_arity(tmp_path):
    """predict() binds positionals in the declared input order and
    rejects wrong arity (round-3 review regression)."""

    class TwoIn(mx.gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.weight = self.params.get("weight", shape=(3, 4))

        def shape_from_input(self, *a):
            pass

        def hybrid_forward(self, F, a, b, weight):
            # first op consumes the SECOND input
            return F.FullyConnected(b, weight, no_bias=True,
                                    num_hidden=3) + a

    net = TwoIn()
    net.initialize(mx.initializer.Xavier())
    rs = np.random.RandomState(5)
    a = rs.rand(2, 3).astype(np.float32)
    b = rs.rand(2, 4).astype(np.float32)
    want = net(mx.nd.array(a), mx.nd.array(b)).asnumpy()
    prefix = str(tmp_path / "two")
    net.export(prefix)
    pred = mx.Predictor(prefix + "-symbol.json",
                        prefix + "-0000.params",
                        {"data0": (2, 3), "data1": (2, 4)})
    np.testing.assert_allclose(pred.predict(a, b), want, rtol=1e-5,
                               atol=1e-6)
    import pytest as _pytest
    with _pytest.raises(ValueError, match="expected 2 inputs"):
        pred.predict(a)


def test_bundle_roundtrip(tmp_path):
    """tools/bundle.py (amalgamation-role deploy artifact): export a
    model, build the bundle, and serve it from the bundle's own
    loader in a fresh process with only the bundle dir."""
    import json
    import os
    import subprocess
    import sys as _sys

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon

    mx.random.seed(5)
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(6, activation="relu"),
                gluon.nn.Dense(2))
    net.initialize(mx.initializer.Xavier())
    net.hybridize()
    x = mx.nd.array(np.random.RandomState(1).rand(3, 4)
                    .astype("float32"))
    ref = net(x).asnumpy()
    prefix = str(tmp_path / "bnet")
    net.export(prefix)

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    _sys.path.insert(0, os.path.join(repo, "tools"))
    import bundle
    out = bundle.build_bundle(prefix, {"data": (3, 4)},
                              str(tmp_path / "bundle"))
    man = json.load(open(os.path.join(out, "MANIFEST.json")))
    assert man["inputs"] == ["data"]

    # fresh process, bundle dir only (forced-CPU embedded runtime)
    code = (
        "import sys, numpy as np\n"
        f"sys.path.insert(0, {out!r})\n"
        "import predict\n"
        "p = predict.load()\n"
        "x = np.load(sys.argv[1])\n"
        "np.save(sys.argv[2], p(data=x))\n")
    xin = tmp_path / "x.npy"
    xout = tmp_path / "y.npy"
    np.save(xin, x.asnumpy())
    env = dict(os.environ)
    env["MXTPU_FORCE_CPU"] = "1"
    env["PYTHONPATH"] = repo
    r = subprocess.run([_sys.executable, "-c", code, str(xin),
                       str(xout)], capture_output=True, text=True,
                      timeout=300, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    got = np.load(xout)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
