"""Live introspection plane (ISSUE 20; docs/observability.md
"Introspection plane"): the per-process debugz endpoint (all six ops
over the CRC-framed rpc transport), the online AnomalyWatch (3x
data_wait inflation detected within 20 steps, attributed to the
right component, exactly one episode), the fleet CLI fan-out with a
deliberately SIGSTOPped replica (bounded, never hung), launch.py's
live-over-mtime freshness/snapshot preference, the emitter's atexit
final flush, the Prometheus HELP/TYPE/quantile exposition, live
tracez payloads through stitch_dumps, and the ci/lint.py
debugz-catalog satellites."""
import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

import incubator_mxnet_tpu as mx  # noqa: F401  (package init wiring)
from incubator_mxnet_tpu import debugz, rpc, telemetry, tracing
from incubator_mxnet_tpu import resilience as rz

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.setenv("MXTPU_TELEMETRY", "1")
    for var in ("MXTPU_DEBUGZ", "MXTPU_DEBUGZ_PORT",
                "MXTPU_DEBUGZ_PORTFILE", "MXTPU_ANOMALY_WINDOW",
                "MXTPU_ANOMALY_THRESHOLD", "MXTPU_ANOMALY_MIN_STEPS",
                "MXTPU_ANOMALY_COOLDOWN", "MXTPU_TELEMETRY_FILE"):
        monkeypatch.delenv(var, raising=False)
    # NOTE: never get_registry().reset() here — rpc.py/router.py
    # cache their Counter objects at import time (the registry's
    # documented process-lifetime contract), so a reset would orphan
    # them for every later test in the session
    debugz.stop()
    telemetry.reset_anomaly_for_tests()
    tracing.get_recorder().clear()
    yield
    debugz.stop()
    telemetry.reset_anomaly_for_tests()
    tracing.get_recorder().clear()


def _counter(name):
    return telemetry.get_registry().counter(name).value


# ------------------------------------------------- anomaly watchdog
BASELINE = {"data_wait": 0.010, "forward_backward": 0.030,
            "optimizer": 0.005, "host_sync": 0.002}


def _jittered(rs, scale=1.0):
    return {k: v * scale * (1.0 + 0.02 * rs.random())
            if k == "data_wait" else v * (1.0 + 0.02 * rs.random())
            for k, v in BASELINE.items()}


def test_anomaly_watch_detects_3x_data_wait_within_20_steps():
    import random
    rs = random.Random(7)
    watch = telemetry.AnomalyWatch(group="t", window=32,
                                   threshold=6.0, min_samples=8,
                                   cooldown=4)
    c0 = _counter("anomaly_detections_total")
    for _ in range(16):                         # calm baseline
        assert watch.observe(_jittered(rs)) is None
    assert _counter("anomaly_detections_total") == c0

    detect_step, episode = None, None
    for step in range(1, 21):                   # inject 3x data_wait
        ep = watch.observe(_jittered(rs, scale=3.0))
        if ep is not None:
            detect_step, episode = step, ep
            break
    assert detect_step is not None and detect_step <= 20
    assert episode["component"] == "data_wait"  # right attribution
    assert episode["episode"] == 1
    # one emission per episode: counter bumped once, one trace event
    assert _counter("anomaly_detections_total") == c0 + 1
    evs = tracing.events("anomaly")
    assert len(evs) == 1 and evs[0]["component"] == "data_wait"
    assert evs[0]["group"] == "t"

    # healthz-facing verdicts while the episode is open
    v = watch.verdicts()
    assert v["anomalous"] and v["episodes"] == 1
    assert v["open"]["component"] == "data_wait"

    # sustained inflation: still exactly one episode, and the shift
    # becomes the new baseline so the episode closes on its own
    for _ in range(64):
        assert watch.observe(_jittered(rs, scale=3.0)) is None
    assert watch.episodes == 1
    assert _counter("anomaly_detections_total") == c0 + 1
    assert not watch.verdicts()["anomalous"]    # hysteresis closed it


def test_anomaly_watch_warmup_and_disabled_paths(monkeypatch):
    watch = telemetry.AnomalyWatch(group="w", window=8,
                                   threshold=6.0, min_samples=50,
                                   cooldown=2)
    for _ in range(20):                 # under min_samples: no score
        assert watch.observe({"data_wait": 0.01}) is None
    assert watch.observe({"data_wait": 10.0}) is None   # still warmup
    assert watch.episodes == 0
    monkeypatch.setenv("MXTPU_TELEMETRY", "0")
    w2 = telemetry.AnomalyWatch(group="off", window=4, threshold=1.0,
                                min_samples=1, cooldown=1)
    for _ in range(10):
        assert w2.observe({"x": 1.0}) is None
    assert w2.observe({"x": 99.0}) is None      # disabled: inert
    assert w2.episodes == 0


def test_anomaly_watch_registry_and_env_defaults(monkeypatch):
    monkeypatch.setenv("MXTPU_ANOMALY_WINDOW", "17")
    monkeypatch.setenv("MXTPU_ANOMALY_THRESHOLD", "4.5")
    w = telemetry.anomaly_watch("train")
    assert w is telemetry.anomaly_watch("train")    # get-or-create
    assert w.window == 17 and w.threshold == 4.5
    assert telemetry.anomaly_watch("serving") is not w
    verdicts = telemetry.anomaly_verdicts()
    assert set(verdicts) == {"train", "serving"}
    assert not verdicts["train"]["anomalous"]


# ------------------------------------------------- endpoint ops
def _ops_server():
    return debugz.DebugzServer("test").start()


def _call(srv, msg, timeout=5.0):
    reply, _ = rpc.call_once(srv.host, srv.port, msg, timeout=timeout)
    return reply


def test_debugz_varz_statusz_publish_and_providers():
    srv = _ops_server()
    try:
        s0 = _counter("steps_total")
        telemetry.counter("steps_total").inc(3)
        debugz.publish("train", step=7, epoch=1)
        debugz.publish("train", step=8)             # merge, not replace
        unreg = debugz.register_provider("engine", lambda: {"q": 2})
        debugz.register_provider("broken", lambda: 1 / 0)

        varz = _call(srv, {"op": "varz"})
        assert varz["op"] == "varz" and varz["role"] == "test"
        assert varz["telemetry"]["counters"]["steps_total"] == s0 + 3
        assert varz["uptime_s"] >= 0

        status = _call(srv, {"op": "statusz"})["status"]
        assert status["train"] == {"step": 8, "epoch": 1}
        assert status["engine"] == {"q": 2}
        assert "error" in status["broken"]      # one broken source
        unreg()                                 # must not take statusz
        assert "engine" not in _call(srv, {"op": "statusz"})["status"]
    finally:
        srv.close()


def test_debugz_tracez_memz_healthz_and_unknown_op():
    srv = _ops_server()
    try:
        tracing.trace_event("submit", rid="r1")
        tracing.trace_event("finish", rid="r1")
        tracing.trace_event("submit", rid="r2")

        t = _call(srv, {"op": "tracez", "event": "submit"})
        assert [e["rid"] for e in t["events"]] == ["r1", "r2"]
        t = _call(srv, {"op": "tracez", "rid": "r1"})
        assert [e["event"] for e in t["events"]] == ["submit",
                                                     "finish"]
        t = _call(srv, {"op": "tracez", "limit": 1})
        assert len(t["events"]) == 1 and "dropped" in t

        tracing.set_memory_plan(12345, {"params": 12000.0})
        m = _call(srv, {"op": "memz"})
        assert m["plan"]["predicted_bytes"] == 12345
        assert "memory" in m

        h = _call(srv, {"op": "healthz"})
        assert h["ok"] and not h["anomalous"]
        assert "heartbeat_age_s" in h and "anomaly" in h

        bad = _call(srv, {"op": "nope"})
        assert bad["op"] == "error" and "unknown" in bad["error"]
        assert bad["ops"] == list(debugz.OPS)
    finally:
        srv.close()


def test_debugz_profilez_bounded_dump():
    srv = _ops_server()
    try:
        p = _call(srv, {"op": "profilez", "seconds": 0.05},
                  timeout=10.0)
        assert p["op"] == "profilez" and p["seconds"] <= 0.05
        dump = json.loads(p["profile"])
        assert "traceEvents" in dump
    finally:
        srv.close()


def test_maybe_start_gating_portfile_and_idempotence(tmp_path,
                                                     monkeypatch):
    monkeypatch.setenv("MXTPU_DEBUGZ", "0")
    assert debugz.maybe_start("test") is None       # gated off
    assert debugz.server() is None

    pf = tmp_path / "dz.port"
    monkeypatch.setenv("MXTPU_DEBUGZ", "1")
    monkeypatch.setenv("MXTPU_DEBUGZ_PORTFILE", str(pf))
    srv = debugz.maybe_start("test")
    assert srv is not None
    assert debugz.maybe_start("test") is srv        # idempotent
    assert debugz.port() == srv.port
    host, port = pf.read_text().strip().rsplit(":", 1)
    assert int(port) == srv.port                    # atomic handshake
    reply, _ = rpc.call_once(host, int(port), {"op": "healthz"})
    assert reply["ok"]
    debugz.stop()
    assert debugz.server() is None


def test_rpc_call_once_and_heartbeat_age(tmp_path):
    assert rz.heartbeat_age() is None               # no beat yet
    rz.start_heartbeat(path=str(tmp_path / "hb"), interval=0.05)
    try:
        deadline = time.monotonic() + 10
        while rz.heartbeat_age() is None:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        assert rz.heartbeat_age() < 5.0
    finally:
        rz.stop_heartbeat()
    # call_once against a dead port: bounded failure, not a hang
    t0 = time.monotonic()
    with pytest.raises(rpc.RpcError):
        rpc.call_once("127.0.0.1", 1, {"op": "healthz"}, timeout=1.0)
    assert time.monotonic() - t0 < 5.0


# ------------------------------------------------- stitcher (live)
def test_stitch_dumps_accepts_live_tracez_payloads(tmp_path):
    dump = tmp_path / "rank0.jsonl"
    dump.write_text(
        json.dumps({"reason": "test"}) + "\n"
        + json.dumps({"event": "a", "ts": 1.0, "seq": 0}) + "\n")
    live = {"op": "tracez", "rank": 7,
            "events": [{"event": "b", "ts": 2.0, "seq": 0},
                       {"event": "c", "ts": 0.5, "seq": 1}]}
    merged = tracing.stitch_dumps([str(dump), live,
                                   [{"event": "d", "ts": 3.0}]])
    assert [e["event"] for e in merged] == ["c", "a", "b", "d"]
    srcs = {e["event"]: e["src"] for e in merged}
    assert srcs["b"] == "live:rank7" and srcs["c"] == "live:rank7"
    assert srcs["d"] == "live" and srcs["a"].endswith("rank0.jsonl")
    role_live = {"role": "router", "events": [{"event": "e",
                                               "ts": 9.0}]}
    assert tracing.stitch_dumps([role_live])[0]["src"] == \
        "live:router"


# ------------------------------------------------- emitter / prom
def test_emitter_atexit_final_flush(tmp_path):
    out = tmp_path / "tele.jsonl"
    code = (
        "from incubator_mxnet_tpu import telemetry\n"
        # huge interval: only the atexit final flush can write
        f"e = telemetry.TelemetryEmitter(path={str(out)!r}, "
        "interval=3600)\n"
        "e.start()\n"
        "telemetry.counter('steps_total').inc(5)\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu", MXTPU_TELEMETRY="1")
    env.pop("MXTPU_TELEMETRY_FILE", None)
    subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                   check=True, timeout=180)
    lines = out.read_text().splitlines()
    assert lines                    # short-lived run still flushed
    last = json.loads(lines[-1])    # ...and the record is complete
    assert last["counters"]["steps_total"] == 5
    prom = (str(out) + ".prom")
    assert os.path.exists(prom)     # textfile replaced atomically too


def test_prometheus_text_help_type_and_quantiles():
    telemetry.counter("anomaly_detections_total").inc(0)
    telemetry.gauge("engine_queue_depth").set(3)
    # a name unique to this test so the quantiles are exactly ours
    # even in a full-suite run (histograms persist for the process)
    h = telemetry.histogram("debugz_probe_seconds")
    for v in (0.1, 0.2, 0.3, 0.4):
        h.observe(v)
    text = telemetry.prometheus_text()
    assert "# TYPE mxtpu_anomaly_detections_total counter" in text
    assert "# TYPE mxtpu_engine_queue_depth gauge" in text
    assert "# TYPE mxtpu_debugz_probe_seconds summary" in text
    # catalogued metrics carry HELP from docs/observability.md
    assert any(line.startswith(
        "# HELP mxtpu_anomaly_detections_total ")
        for line in text.splitlines())
    # quantile gauges derived from the histogram window
    assert "# TYPE mxtpu_debugz_probe_seconds_p50 gauge" in text
    assert "mxtpu_debugz_probe_seconds_p50 0.2" in text
    assert "# TYPE mxtpu_debugz_probe_seconds_p99 gauge" in text
    assert "mxtpu_debugz_probe_seconds_count 4" in text


# ------------------------------------------------- launch.py helpers
def _load_launch():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "launch", os.path.join(REPO, "tools", "launch.py"))
    launch = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(launch)
    return launch


def test_launch_prefers_live_snapshots_with_file_fallback(
        tmp_path, monkeypatch):
    launch = _load_launch()
    hb0 = str(tmp_path / "hb-0")
    hb1 = str(tmp_path / "hb-1")
    assert launch._dz_portfile(hb0) == hb0 + ".debugz"
    file_snap = {"counters": {"file_only": 1.0}}
    for hb in (hb0, hb1):
        with open(hb, "w") as f:
            f.write("123.0\n" + json.dumps(file_snap) + "\n")

    telemetry.counter("live_marker").inc(7)
    srv = debugz.DebugzServer("train").start()
    try:
        with open(launch._dz_portfile(hb0), "w") as f:
            f.write(f"{srv.host}:{srv.port}\n")
        snaps = launch._collect_snapshots({0: hb0, 1: hb1})
        # rank 0 live (current counters), rank 1 heartbeat ride-along
        assert snaps[0]["counters"]["live_marker"] == 7
        assert "file_only" not in snaps[0]["counters"]
        assert snaps[1]["counters"]["file_only"] == 1.0
        assert launch._live_fresh(hb0)
    finally:
        srv.close()
    # dead endpoint: bounded False, callers fall back to mtimes
    with open(launch._dz_portfile(hb1), "w") as f:
        f.write("127.0.0.1:1\n")
    t0 = time.monotonic()
    assert not launch._live_fresh(hb1)
    assert time.monotonic() - t0 < 5.0


# ------------------------------------------------- fleet e2e (procs)
def _spawn_replica_proc(tmp_path, idx):
    port_file = tmp_path / f"port{idx}"
    dz_file = tmp_path / f"hb{idx}.debugz"
    log = open(tmp_path / f"replica{idx}.log", "wb")
    env = dict(os.environ, JAX_PLATFORMS="cpu", MXTPU_DEBUGZ="1",
               MXTPU_DEBUGZ_PORTFILE=str(dz_file))
    env.pop("MXTPU_FAULT_SPEC", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "incubator_mxnet_tpu.serving.replica",
         "--port-file", str(port_file), "--name", f"dz{idx}",
         "--max-batch", "2", "--block-size", "4",
         "--num-blocks", "64", "--prefix-cache", "0"],
        cwd=REPO, env=env, stdout=log, stderr=log)
    return proc, dz_file, log


def _wait_files(files, timeout=180):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(f.exists() for f in files):
            return
        time.sleep(0.1)
    raise AssertionError("debugz port files never appeared")


def test_fleet_fanout_with_sigstopped_replica(tmp_path):
    """Acceptance: a SIGSTOPped rank cannot hang the fan-out CLI or
    launch.py's liveness probe; the healthy rank's payload arrives
    complete and the hung one is reported within the deadline."""
    procs = [_spawn_replica_proc(tmp_path, i) for i in range(2)]
    try:
        _wait_files([dz for _, dz, _ in procs])
        # sanity: both endpoints answer before the wedge
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "debugz.py"),
             str(procs[0][1]), str(procs[1][1]),
             "--op", "healthz", "--deadline", "5"],
            cwd=REPO, capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stdout + out.stderr
        assert all(r.get("ok") for r in
                   json.loads(out.stdout).values())

        os.kill(procs[0][0].pid, signal.SIGSTOP)    # wedge rank 0
        time.sleep(0.2)
        t0 = time.monotonic()
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "debugz.py"),
             str(procs[0][1]), str(procs[1][1]),
             "--op", "statusz", "--deadline", "2"],
            cwd=REPO, capture_output=True, text=True, timeout=60)
        elapsed = time.monotonic() - t0
        assert elapsed < 15.0           # bounded, never hung
        assert out.returncode == 1      # ...and the wedge is reported
        replies = json.loads(out.stdout)
        assert len(replies) == 2
        errs = [r for r in replies.values()
                if "error" in r and "op" not in r]
        good = [r for r in replies.values() if r.get("op") ==
                "statusz"]
        assert len(errs) == 1 and len(good) == 1
        assert good[0]["role"] == "replica"     # healthy payload whole
        assert "engine" in good[0]["status"]

        # launch.py's probe: live rank fresh, wedged rank bounded-dead
        launch = _load_launch()
        hb_live = str(procs[1][1])[:-len(".debugz")]
        hb_hung = str(procs[0][1])[:-len(".debugz")]
        assert launch._live_fresh(hb_live)
        t0 = time.monotonic()
        assert not launch._live_fresh(hb_hung, deadline=1.0)
        assert time.monotonic() - t0 < 5.0
    finally:
        for proc, _, log in procs:
            try:
                os.kill(proc.pid, signal.SIGCONT)
            except OSError:
                pass
            proc.terminate()
        for proc, _, log in procs:
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=30)
            log.close()


# ------------------------------------------------- lint satellites
def _load_lint():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "lint", os.path.join(REPO, "ci", "lint.py"))
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    return lint


def test_lint_debugz_catalog_and_socket_rules(tmp_path, monkeypatch):
    monkeypatch.chdir(REPO)     # catalog checks read docs/ relatively
    lint = _load_lint()
    d = tmp_path / "incubator_mxnet_tpu"
    d.mkdir(parents=True)
    f = d / "debugz.py"
    # documented ops pass; an undocumented op is flagged
    f.write_text('OPS = ("varz", "healthz")\n')
    assert not lint.check_debugz_catalog([f])
    f.write_text('OPS = ("varz", "coffeez")\n')
    probs = lint.check_debugz_catalog([f])
    assert any("coffeez" in p for p in probs)
    # anomaly metric/event names must stay catalogued too
    assert not any("anomaly" in p for p in probs)
    # unbounded socket waits inside a debugz module are flagged
    f.write_text('OPS = ("varz",)\nimport socket\n'
                 "s = socket.socket()\nc = s.recv(4)\n")
    assert any("recv" in p for p in lint.check_file(f))
    # ...and a deadline-ok annotation clears it
    f.write_text('OPS = ("varz",)\nimport socket\n'
                 "s = socket.socket()\n"
                 "c = s.recv(4)  # deadline-ok: settimeout armed\n")
    assert not any("recv" in p for p in lint.check_file(f))
    # the real repo files pass the full catalog check
    from pathlib import Path
    real = [Path("incubator_mxnet_tpu/debugz.py"),
            Path("tools/debugz.py")]
    assert not lint.check_debugz_catalog(real)
