"""Unified telemetry (docs/observability.md): registry semantics,
disabled-mode zero side effects, emitter flush/rotation, heartbeat
ride-along, step-timeline spans for eager + fused Trainer and
Module.fit, counters wired from fault-injected resilience / data /
sentinel runs, launch.py multi-rank aggregation, profiler dump
hardening, and the transfer-budget proof that telemetry adds no
device->host reads beyond the sentinel's guard-interval baseline."""
import json
import os
import sys
import threading
import time
import warnings

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, nd
from incubator_mxnet_tpu import optimizer as opt_mod
from incubator_mxnet_tpu import recordio as rio
from incubator_mxnet_tpu import resilience as rz
from incubator_mxnet_tpu import telemetry as tel
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.model import BatchEndParam

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")


def _load_tool(name):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import importlib
        return importlib.import_module(name)
    finally:
        sys.path.pop(0)


def _load_lint():
    sys.path.insert(0, os.path.join(REPO, "ci"))
    try:
        import lint
        return lint
    finally:
        sys.path.pop(0)


@pytest.fixture(autouse=True)
def _fresh_telemetry(monkeypatch):
    monkeypatch.delenv("MXTPU_TELEMETRY", raising=False)
    monkeypatch.delenv("MXTPU_TELEMETRY_FILE", raising=False)
    tel.stop_emitter()
    tel.get_registry().reset()
    rz.reset_faults()
    yield
    tel.stop_emitter()
    tel.get_registry().reset()
    rz.reset_faults()


# ------------------------------------------------------------ registry
def test_counter_thread_safety_under_concurrent_increments():
    c = tel.get_registry().counter("train_steps_total")
    threads = [threading.Thread(
        target=lambda: [c.inc() for _ in range(500)])
        for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8 * 500


def test_registry_type_conflict_raises():
    tel.get_registry().gauge("loss_scale").set(2.0)
    with pytest.raises(TypeError, match="already registered"):
        tel.get_registry().counter("loss_scale")


def test_histogram_reservoir_is_bounded():
    h = tel.get_registry().histogram("prefetch_queue_wait_seconds",
                                     max_samples=64)
    for i in range(5000):
        h.observe(float(i))
    st = h.stats()
    assert st["count"] == 5000          # exact over the whole run
    assert st["min"] == 0.0 and st["max"] == 4999.0
    assert len(h._samples) == 64        # reservoir stays bounded
    # percentiles come from the most recent window
    assert st["p50"] >= 4936


def test_snapshot_shape_and_rank(monkeypatch):
    monkeypatch.setenv("MXTPU_WORKER_RANK", "3")
    tel.counter("train_steps_total").inc(7)
    tel.gauge("loss_scale").set(4.0)
    with tel.span("data_wait"):
        pass
    snap = tel.snapshot()
    assert snap["rank"] == 3
    assert snap["counters"]["train_steps_total"] == 7
    assert snap["gauges"]["loss_scale"] == 4.0
    assert snap["histograms"]["span_data_wait_seconds"]["count"] == 1
    text = tel.prometheus_text()
    assert "mxtpu_train_steps_total 7" in text
    assert "mxtpu_span_data_wait_seconds_count 1" in text


def test_disabled_mode_has_zero_side_effects(monkeypatch, tmp_path):
    monkeypatch.setenv("MXTPU_TELEMETRY", "0")
    assert tel.counter("train_steps_total") is tel.NULL_METRIC
    assert tel.gauge("loss_scale") is tel.NULL_METRIC
    assert tel.histogram("prefetch_queue_wait_seconds") \
        is tel.NULL_METRIC
    assert tel.span("data_wait") is tel.NULL_SPAN
    tel.counter("train_steps_total").inc()
    with tel.span("data_wait"):
        pass
    snap = tel.snapshot()
    assert not snap["counters"] and not snap["histograms"]
    assert tel.heartbeat_payload() == ""
    monkeypatch.setenv("MXTPU_TELEMETRY_FILE", str(tmp_path / "t"))
    assert tel.start_emitter() is None          # no emitter thread
    assert tel.maybe_start_emitter() is None
    # instrumented hot paths run clean with everything off
    up = opt_mod.GuardedUpdater(
        opt_mod.create("sgd"),
        guard=rz.NumericGuard(policy="skip", max_bad_steps=0))
    g = mx.nd.array(np.ones((2,), np.float32))
    w = mx.nd.array(np.ones((2,), np.float32))
    assert up.begin_step([g])
    up(0, g, w)
    assert not tel.snapshot()["counters"]


# ------------------------------------------------------------- emitter
def test_emitter_flush_writes_jsonl_and_atomic_prom(tmp_path):
    tel.counter("train_steps_total").inc(5)
    path = str(tmp_path / "telemetry.jsonl")
    em = tel.TelemetryEmitter(path=path, interval=999)
    em.flush()
    tel.counter("train_steps_total").inc(5)
    em.flush()
    lines = [json.loads(s) for s in
             open(path).read().splitlines()]
    assert [s["counters"]["train_steps_total"]
            for s in lines] == [5, 10]
    prom = open(path + ".prom").read()
    assert "mxtpu_train_steps_total 10" in prom
    assert prom.startswith("# TYPE")
    assert not os.path.exists(path + ".prom.tmp")  # atomic replace


def test_emitter_rotation(tmp_path):
    path = str(tmp_path / "telemetry.jsonl")
    em = tel.TelemetryEmitter(path=path, interval=999, max_bytes=300)
    tel.counter("train_steps_total").inc()
    for _ in range(8):
        em.flush()
    assert os.path.exists(path + ".1")
    # both generations hold parseable JSONL
    for p in (path, path + ".1"):
        for line in open(p).read().splitlines():
            json.loads(line)


def test_emitter_background_thread_and_retarget(tmp_path,
                                                monkeypatch):
    path = str(tmp_path / "t1.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY_FILE", path)
    monkeypatch.setenv("MXTPU_TELEMETRY_INTERVAL", "0.05")
    em = tel.maybe_start_emitter()
    assert em is not None and em.running
    assert tel.maybe_start_emitter() is em      # idempotent
    deadline = time.time() + 5
    while em.flushes == 0 and time.time() < deadline:
        time.sleep(0.02)
    assert em.flushes > 0
    # re-target to a new path stops the old emitter
    path2 = str(tmp_path / "t2.jsonl")
    em2 = tel.start_emitter(path=path2, interval=999)
    assert em2 is not em and not em.running
    tel.stop_emitter()                          # final flush
    assert not em2.running
    assert os.path.exists(path2)


def test_emitter_final_flush_at_process_exit(tmp_path):
    """A run shorter than MXTPU_TELEMETRY_INTERVAL must still leave
    a complete record: start_emitter registers an atexit final
    flush."""
    import subprocess
    path = str(tmp_path / "exit.jsonl")
    env = dict(os.environ, MXTPU_TELEMETRY="1",
               MXTPU_TELEMETRY_FILE=path,
               MXTPU_TELEMETRY_INTERVAL="600")
    env.pop("MXTPU_WORKER_RANK", None)
    code = ("import sys; sys.path.insert(0, %r); "
            "from incubator_mxnet_tpu import telemetry as tel; "
            "tel.counter('train_steps_total').inc(4); "
            "tel.maybe_start_emitter()" % REPO)
    subprocess.run([sys.executable, "-c", code], env=env, check=True,
                   timeout=120)
    lines = [json.loads(s) for s in open(path).read().splitlines()]
    assert lines[-1]["counters"]["train_steps_total"] == 4
    assert os.path.exists(path + ".prom")


def test_emitter_rank_suffix_avoids_shared_path_collision(
        tmp_path, monkeypatch):
    """The launcher exports ONE MXTPU_TELEMETRY_FILE to every
    worker; nonzero ranks must suffix it or two emitters would race
    the rotation and tear each other's textfile."""
    base = str(tmp_path / "t.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY_FILE", base)
    monkeypatch.setenv("MXTPU_WORKER_RANK", "1")
    em = tel.start_emitter(interval=999)
    assert em.path == base + ".rank1"
    assert tel.maybe_start_emitter() is em      # path stays stable
    em.flush()
    assert os.path.exists(base + ".rank1")
    assert not os.path.exists(base)
    tel.stop_emitter()
    monkeypatch.setenv("MXTPU_WORKER_RANK", "0")
    em = tel.start_emitter(interval=999)
    assert em.path == base                      # rank 0: bare path


def test_snapshots_ride_heartbeat_file(tmp_path):
    tel.counter("train_steps_total").inc(9)
    hb = str(tmp_path / "hb")
    try:
        rz.start_heartbeat(hb, interval=0.05)
        deadline = time.time() + 5
        snap = None
        while snap is None and time.time() < deadline:
            if os.path.exists(hb):
                lines = open(hb).read().splitlines()
                if len(lines) > 1:
                    snap = json.loads(lines[-1])
                    float(lines[0])     # line 1: bare timestamp
            time.sleep(0.02)
    finally:
        rz.stop_heartbeat()
    assert snap is not None
    assert snap["counters"]["train_steps_total"] == 9


# --------------------------------------------------------- speedometer
class _FakeTime:
    def __init__(self):
        self.now = 1000.0

    def time(self):
        return self.now


def test_speedometer_publishes_and_first_window_not_inflated(
        monkeypatch):
    import incubator_mxnet_tpu.callback as cb
    clk = _FakeTime()
    monkeypatch.setattr(cb, "time", clk)
    speedo = cb.Speedometer(batch_size=10, frequent=4,
                            auto_reset=False)
    # first callback lands mid-epoch at nbatch=2 (resumed stream):
    # the first measured window holds only 2 batches, not `frequent`
    speedo(BatchEndParam(0, 2, None, {}))
    clk.now += 2.0
    speedo(BatchEndParam(0, 4, None, {}))
    speed = tel.snapshot()["gauges"]["throughput_samples_per_sec"]
    assert speed == pytest.approx(2 * 10 / 2.0)   # not 4 * 10 / 2.0
    assert tel.snapshot()["gauges"]["nbatch"] == 4
    # steady state: full window over the full elapsed time
    clk.now += 4.0
    speedo(BatchEndParam(0, 8, None, {}))
    speed = tel.snapshot()["gauges"]["throughput_samples_per_sec"]
    assert speed == pytest.approx(4 * 10 / 4.0)


# ------------------------------------------------------------- monitor
def test_monitor_armed_interval_emits_span_and_row_counts():
    mon = mx.monitor.Monitor(interval=2)
    mon.install()
    try:
        for _ in range(4):
            mon.tic()
            if mon.activated:
                mx.monitor.observe_op(
                    "fc", [nd.array(np.ones((2, 2), np.float32))])
            mon.toc()
    finally:
        mon.uninstall()
    snap = tel.snapshot()
    assert snap["counters"]["monitor_armed_batches_total"] == 2
    assert snap["counters"]["monitor_stat_rows_total"] == 2
    assert snap["histograms"]["span_monitor_armed_seconds"][
        "count"] == 2


def test_monitor_span_closed_when_batch_aborts_before_toc():
    """An exception between tic() and toc() (sentinel raise mid
    forward/update) must not leak the armed span open: the next
    re-arm — or uninstall — closes it, so the armed section still
    lands in the timeline."""
    mon = mx.monitor.Monitor(interval=1)
    mon.install()
    try:
        mon.tic()            # armed; batch "aborts": no toc()
        mon.tic()            # re-arm closes the stale span
        mon.toc()
    finally:
        mon.uninstall()
    h = tel.snapshot()["histograms"]["span_monitor_armed_seconds"]
    assert h["count"] == 2
    mon2 = mx.monitor.Monitor(interval=1)
    mon2.install()
    mon2.tic()               # armed, aborted, never re-armed
    mon2.uninstall()         # closes the open span
    h = tel.snapshot()["histograms"]["span_monitor_armed_seconds"]
    assert h["count"] == 3
    assert mon2._span is None


# --------------------------------------------------------- tensorboard
def test_tensorboard_log_telemetry_writes_scalars():
    from incubator_mxnet_tpu.contrib import tensorboard as tb

    class W:
        def __init__(self):
            self.rows = []

        def add_scalar(self, tag, value, step):
            self.rows.append((tag, value, step))

    tel.counter("train_steps_total").inc(12)
    tel.gauge("throughput_samples_per_sec").set(640.0)
    w = W()
    n = tb.log_telemetry(w)
    assert n == len(w.rows) == 2
    rows = dict((t, (v, s)) for t, v, s in w.rows)
    assert rows["telemetry/throughput_samples_per_sec"] == \
        (640.0, 12)
    assert rows["telemetry/train_steps_total"] == (12, 12)


# ------------------------------------------------------------ profiler
def test_profiler_dump_metadata_and_counter_events(tmp_path):
    prof = mx.profiler._profiler
    prof.set_config(filename=str(tmp_path / "trace.json"))
    prof.set_state("run")
    try:
        t = time.perf_counter()
        prof.add_event("op_a", t, t + 0.001)
        tel.counter("train_steps_total").inc(3)
        with tel.span("data_wait"):
            pass                # spans land in the profiler stream
        out = mx.profiler.dump_profile()
    finally:
        prof.set_state("stop")
        prof.set_config(filename="profile.json")
    events = json.load(open(out))["traceEvents"]
    phases = {}
    for e in events:
        phases.setdefault(e["ph"], []).append(e)
    assert any(e["name"] == "process_name" for e in phases["M"])
    assert any(e["name"] == "thread_name" for e in phases["M"])
    names = {e["name"] for e in phases["X"]}
    assert {"op_a", "data_wait"} <= names
    counter_events = {e["name"]: e["args"] for e in phases["C"]}
    assert counter_events["train_steps_total"] == \
        {"train_steps_total": 3}


def test_profiler_concurrent_dump_loses_no_events(tmp_path):
    prof = mx.profiler._profiler
    prof.set_config(filename=str(tmp_path / "trace.json"))
    prof.set_state("stop")
    with prof._lock:
        prof._events = []
    total = 800
    stop_adding = threading.Event()

    def add():
        for i in range(total // 4):
            t = time.perf_counter()
            prof.add_event("op", t, t)
        stop_adding.set()

    adders = [threading.Thread(target=add) for _ in range(4)]
    seen = 0
    for t in adders:
        t.start()
    try:
        while not all(stop_adding.is_set()
                      for _ in adders) or any(t.is_alive()
                                              for t in adders):
            out = prof.dump(finished=True)
            seen += sum(e["ph"] == "X"
                        for e in json.load(open(out))["traceEvents"])
            if all(not t.is_alive() for t in adders):
                break
    finally:
        for t in adders:
            t.join()
    out = prof.dump(finished=True)
    seen += sum(e["ph"] == "X"
                for e in json.load(open(out))["traceEvents"])
    prof.set_config(filename="profile.json")
    assert seen == 4 * (total // 4)


# ------------------------------------------------- fit-loop timelines
def _toy_module_problem(n=64, dim=10, classes=5, batch=16, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.rand(n, dim).astype(np.float32)
    w = rs.rand(dim, classes).astype(np.float32)
    y = (x @ w).argmax(1).astype(np.float32)
    it = mx.io.NDArrayIter(x, y, batch_size=batch,
                           label_name="softmax_label")
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc", num_hidden=classes)
    return it, mx.sym.SoftmaxOutput(net, name="softmax")


def _make_image_rec(tmp_path, n=12, bad=()):
    rec = str(tmp_path / "d.rec")
    idx = str(tmp_path / "d.idx")
    w = rio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(n):
        if i in bad:
            w.write_idx(i, rio.pack(rio.IRHeader(0, i, i, 0),
                                    b"not-an-image"))
        else:
            img = np.full((16, 16, 3), (i * 9) % 255, np.uint8)
            w.write_idx(i, rio.pack_img(rio.IRHeader(0, i, i, 0),
                                        img))
    w.close()
    return rec


def test_module_fit_full_telemetry_stream(tmp_path, monkeypatch):
    """Acceptance path: a CPU Module.fit run under fault injection
    produces a JSONL stream holding the per-step timeline breakdown
    plus non-zero counters from all three prior subsystems, and
    launch.py renders an aggregated final run report from it."""
    jsonl = str(tmp_path / "telemetry.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY", "1")
    monkeypatch.setenv("MXTPU_TELEMETRY_FILE", jsonl)
    monkeypatch.setenv("MXTPU_TELEMETRY_INTERVAL", "600")
    monkeypatch.setenv("MXTPU_NONFINITE_POLICY", "skip")
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "grad:nonfinite:2:nan")
    rz.reset_faults()
    # sentinel subsystem: one injected bad step gets skipped
    it, sym = _toy_module_problem()
    mod = mx.mod.Module(sym, context=mx.cpu())
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        mod.fit(it, num_epoch=2, optimizer="sgd",
                initializer=mx.initializer.Xavier())
    # data-pipeline subsystem: corrupt records quarantined in-budget
    monkeypatch.setenv("MXTPU_MAX_BAD_RECORDS", "5")
    rec_it = mx.image.ImageRecordIter(
        path_imgrec=_make_image_rec(tmp_path, bad={3}),
        data_shape=(3, 16, 16), batch_size=4, preprocess_threads=2)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        for _ in rec_it:
            pass
    # resilience subsystem: one transient failure retried
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 1:
            raise rz.TransientError("transient")
        return "ok"

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        assert rz.retry_call(
            flaky, policy=rz.RetryPolicy(
                max_retries=2, base_delay=0.001, jitter=0)) == "ok"

    tel.stop_emitter()      # final flush
    lines = [json.loads(s) for s in open(jsonl).read().splitlines()]
    assert lines
    snap = lines[-1]
    hists = snap["histograms"]
    for phase in ("data_wait", "forward_backward", "optimizer",
                  "host_sync"):
        assert hists[f"span_{phase}_seconds"]["count"] >= 8, phase
    counters = snap["counters"]
    assert counters["sentinel_skipped_steps_total"] >= 1
    assert counters["sentinel_bad_steps_total"] >= 1
    assert counters["data_quarantined_records_total"] == 1
    assert counters["retry_attempts_total"] == 1
    assert counters["train_steps_total"] == 8
    assert counters["prefetch_batches_total"] >= 1
    # launch.py renders a final run report from this snapshot
    launch = _load_tool("launch")
    report = launch._format_report({0: snap})
    assert "rank 0: steps=8" in report
    assert "sentinel_skipped_steps_total" in report


@pytest.mark.parametrize("optimizer", ["sgd", "test"])
def test_trainer_step_timeline_fused_and_eager(optimizer,
                                               monkeypatch):
    """Both Trainer update paths — fused in-jit ('sgd') and the
    eager per-param fallback ('test', no functional counterpart) —
    emit the optimizer span, the guard-interval host_sync span, and
    the step counter."""
    monkeypatch.setenv("MXTPU_NONFINITE_POLICY", "skip")
    mx.random.seed(42)
    rs = np.random.RandomState(0)
    data = rs.randn(60, 10).astype("float32")
    labels = rs.randint(0, 3, 60).astype("float32")
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"))
    net.add(nn.Dense(3))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), optimizer,
                            {"learning_rate": 0.01})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    steps, batch = 6, 10
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        for step in range(steps):
            lo = (step * batch) % len(data)
            x = nd.array(data[lo:lo + batch])
            y = nd.array(labels[lo:lo + batch])
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(batch)
    fused = trainer._fused_active()
    assert fused == (optimizer == "sgd")
    snap = tel.snapshot()
    assert snap["counters"]["train_steps_total"] == steps
    assert snap["histograms"]["span_optimizer_seconds"][
        "count"] == steps
    # guard interval 1: every step pays exactly one host read
    assert snap["histograms"]["span_host_sync_seconds"][
        "count"] == steps
    assert trainer.guard.checks == steps


def test_transfer_budget_unchanged_with_telemetry_on(monkeypatch,
                                                     tmp_path):
    """Telemetry adds NO device->host reads: with the sentinel at
    interval 4 and telemetry fully armed (registry + emitter), the
    sole transfer point (read_window_bad) still fires exactly once
    per interval — the same count as the telemetry-off baseline in
    test_sentinel.py."""
    monkeypatch.setenv("MXTPU_TELEMETRY", "1")
    monkeypatch.setenv("MXTPU_TELEMETRY_FILE",
                       str(tmp_path / "t.jsonl"))
    monkeypatch.setenv("MXTPU_NONFINITE_POLICY", "skip")
    monkeypatch.setenv("MXTPU_GUARD_INTERVAL", "4")
    reads = []
    orig = opt_mod.read_window_bad
    monkeypatch.setattr(opt_mod, "read_window_bad",
                        lambda g: reads.append(1) or orig(g))
    it, sym = _toy_module_problem()
    mod = mx.mod.Module(sym, context=mx.cpu())
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        mod.fit(it, num_epoch=2, optimizer="sgd",
                initializer=mx.initializer.Xavier())
    # 8 update steps, interval 4 -> exactly 2 host reads, telemetry on
    assert mod._guard.steps == 8
    assert len(reads) == 2
    assert mod._guard.checks == 2
    snap = tel.snapshot()
    assert snap["histograms"]["span_data_wait_seconds"]["count"] >= 8


# ----------------------------------------------- launch.py aggregation
def _fake_worker_files(tmp_path, snaps):
    files = {}
    for rank, snap in snaps.items():
        p = str(tmp_path / f"hb-0-{rank}")
        with open(p, "w") as f:
            f.write(f"{time.time():.3f}\n")
            f.write(json.dumps(snap) + "\n")
        files[rank] = p
    return files


def test_launch_aggregates_two_worker_heartbeats(tmp_path):
    launch = _load_tool("launch")
    snaps = {
        0: {"ts": 1.0, "rank": 0,
            "counters": {"train_steps_total": 100,
                         "retry_attempts_total": 2},
            "gauges": {"throughput_samples_per_sec": 500.0},
            "histograms": {}},
        1: {"ts": 1.0, "rank": 1,
            "counters": {"train_steps_total": 90,
                         "sentinel_skipped_steps_total": 3},
            "gauges": {"throughput_samples_per_sec": 450.0},
            "histograms": {}},
    }
    files = _fake_worker_files(tmp_path, snaps)
    ts, snap0 = launch._read_heartbeat(files[0])
    assert ts is not None and snap0["counters"][
        "train_steps_total"] == 100
    collected = launch._collect_snapshots(files)
    assert set(collected) == {0, 1}
    agg = launch._aggregate_telemetry(collected)
    assert agg["counters"]["train_steps_total"] == 190
    assert agg["counters"]["retry_attempts_total"] == 2
    assert agg["throughput"] == pytest.approx(950.0)
    assert agg["straggler"] == (1, 90, 100)
    status = launch._format_status(agg)
    assert "steps=190" in status
    assert "950.0 samples/s" in status
    assert "sentinel_skipped_steps_total=3" in status
    assert "straggler: rank 1 at step 90/100" in status
    report = launch._format_report(collected)
    assert "rank 0: steps=100" in report
    assert "rank 1: steps=90" in report
    assert "retry_attempts_total = 2" in report
    # malformed / telemetry-less heartbeat files degrade gracefully
    bare = str(tmp_path / "hb-0-9")
    open(bare, "w").write("123.0\n")
    assert launch._read_heartbeat(bare) == (123.0, None)
    torn = str(tmp_path / "hb-0-8")
    open(torn, "w").write("123.0\n{\"cut")
    assert launch._read_heartbeat(torn)[1] is None
    assert "no worker telemetry" in launch._format_report({})


# ---------------------------------------------------------------- lint
def test_lint_metric_catalog_and_perf_counter_rules(tmp_path):
    lint = _load_lint()
    d = tmp_path / "incubator_mxnet_tpu"
    d.mkdir(parents=True)
    f = d / "somemod.py"
    f.write_text("from . import telemetry\n"
                 "telemetry.counter('train_steps_total').inc()\n"
                 "telemetry.span('data_wait')\n")
    assert lint.check_metric_catalog([f]) == []
    f.write_text("from . import telemetry\n"
                 "telemetry.counter('undocumented_metric_xyz')\n")
    problems = lint.check_metric_catalog([f])
    assert any("undocumented_metric_xyz" in p for p in problems)
    # raw perf_counter section timing is forbidden in instrumented
    # hot-path modules (telemetry.span is the sanctioned tool)
    hot = tmp_path / "incubator_mxnet_tpu" / "module"
    hot.mkdir(parents=True)
    g = hot / "base_module.py"
    g.write_text("import time\n"
                 "def fit(self):\n"
                 "    t0 = time.perf_counter()\n"
                 "    return t0\n")
    problems = lint.check_file(g)
    assert any("perf_counter" in p for p in problems), problems
    g.write_text("import time\n"
                 "def fit(self):\n"
                 "    t0 = time.perf_counter()  # timing-ok: bench\n"
                 "    return t0\n")
    assert not any("perf_counter" in p for p in lint.check_file(g))
