"""Deep operator test matrix (round-3 verdict task 7): the reference's
test_operator.py discipline (ref: tests/python/unittest/
test_operator.py:1 — dtype matrices, conv stride/dilate/group sweeps,
degenerate shapes) applied registry-wide.

Three axes beyond tests/test_op_sweep.py's one-shape fp32 pass:

1. bf16: every swept op's forward must agree with its fp32 forward to
   bf16 tolerance (the TPU compute dtype; a hard-coded float32 or an
   accumulation bug shows up here).
2. structured-op parameter matrices: conv stride/dilate/groups/pad/1x1
   and 1d/3d, pooling type/pad/overlap/global/full-convention — each
   numeric-gradient checked.
3. degenerate shapes: size-0 and size-1 dims through elemwise,
   broadcast, reduction, and concat ops against the numpy oracle.
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu import test_utils as tu
from incubator_mxnet_tpu.ops.registry import OPS

from test_op_sweep import CASES, P

RS = np.random.RandomState(11)


# ---------------------------------------------------------------- bf16
# ops whose spec'd inputs leave the bf16-safe domain or that are
# numerically dtype-bound (linalg factorizations need fp32 pivots;
# erfinv/digamma-family curvature amplifies bf16 input rounding)
BF16_SKIP = {
    "linalg_potrf", "linalg_potri", "linalg_trsm", "linalg_gelqf",
    "linalg_syevd", "linalg_sumlogdiag", "linalg_extractdiag",
    "linalg_makediag", "linalg_extracttrian", "linalg_maketrian",
    "erfinv", "_power_scalar", "_rpower_scalar", "reciprocal",
    "rcbrt", "rsqrt", "_rdiv_scalar", "gammaln", "gamma",
    "GridGenerator", "BilinearSampler", "SpatialTransformer",
    "Correlation", "_flash_attention",
    # index-valued outputs: bf16 input rounding creates ties, so the
    # winning index can legitimately differ from fp32's
    "topk", "argmax", "argmin", "argmax_channel", "argsort",
}


def _float_outputs(res):
    # bfloat16's numpy dtype (ml_dtypes) is not np.floating; anything
    # that is not int/uint/bool counts as float here
    outs = res if isinstance(res, list) else [res]
    return [o for o in outs if np.dtype(o.dtype).kind not in "iub"]


@pytest.mark.parametrize("name", sorted(CASES))
def test_bf16_forward_matches_fp32(name):
    if name in BF16_SKIP:
        pytest.skip("documented bf16-unsafe domain")
    spec = CASES[name]
    fn = getattr(nd, name, None) or getattr(nd._internal, name)
    params = spec.get("params", {})
    ins32 = [nd.array(v) for v in spec["inputs"]]
    ins16 = [x.astype("bfloat16")
             if np.issubdtype(x.dtype, np.floating) else x
             for x in ins32]
    out32 = _float_outputs(fn(*ins32, **params))
    out16 = _float_outputs(fn(*ins16, **params))
    assert len(out32) == len(out16)
    for a32, a16 in zip(out32, out16):
        ref = a32.asnumpy().astype(np.float64)
        got = a16.astype("float32").asnumpy().astype(np.float64)
        # bf16 mantissa is 8 bits: 1/128 per-op relative error, with
        # headroom for accumulation across a reduced axis
        scale = np.maximum(np.abs(ref), 1e-2)
        assert np.all(np.abs(got - ref) <= 0.06 * scale + 0.02), (
            name, float(np.max(np.abs(got - ref) / scale)))


def test_bf16_coverage_not_vacuous():
    covered = [n for n in CASES if n not in BF16_SKIP]
    assert len(covered) >= 150, len(covered)


# ------------------------------------------------- conv/pool matrices
CONV_CONFIGS = {
    "stride2": dict(kernel=(3, 3), stride=(2, 2), num_filter=3),
    "dilate2": dict(kernel=(3, 3), dilate=(2, 2), num_filter=3),
    "pad1": dict(kernel=(3, 3), pad=(1, 1), num_filter=3),
    "groups2": dict(kernel=(3, 3), num_group=2, num_filter=4),
    "k1x1": dict(kernel=(1, 1), num_filter=3),
    "rect": dict(kernel=(3, 1), stride=(2, 1), num_filter=3),
    "full": dict(kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                 dilate=(2, 2), num_filter=3),
}


@pytest.mark.parametrize("cfg", sorted(CONV_CONFIGS))
def test_conv2d_matrix(cfg):
    params = dict(CONV_CONFIGS[cfg])
    cin = 4 if params.get("num_group") else 2
    cout = params["num_filter"]
    kh, kw = params["kernel"]
    wshape = (cout, cin // params.get("num_group", 1), kh, kw)
    data, w, b = mx.sym.Variable("data"), mx.sym.Variable("w"), \
        mx.sym.Variable("b")
    sym = mx.sym.Convolution(data, w, b, **params)
    loc = {"data": P(2, cin, 7, 7), "w": P(*wshape), "b": P(cout)}
    tu.check_numeric_gradient(sym, loc, numeric_eps=1e-3, rtol=0.08,
                              atol=5e-3)


@pytest.mark.parametrize("ndim", [1, 3])
def test_conv_1d_3d(ndim):
    sp = (5,) * ndim
    k = (3,) * ndim
    data, w = mx.sym.Variable("data"), mx.sym.Variable("w")
    sym = mx.sym.Convolution(data, w, kernel=k, num_filter=2,
                             stride=(2,) * ndim, no_bias=True)
    loc = {"data": P(1, 2, *sp), "w": P(2, 2, *k)}
    tu.check_numeric_gradient(sym, loc, numeric_eps=1e-3, rtol=0.08,
                              atol=5e-3)


POOL_CONFIGS = {
    "max_basic": dict(kernel=(2, 2), stride=(2, 2), pool_type="max"),
    "avg_pad": dict(kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                    pool_type="avg"),
    "max_overlap": dict(kernel=(3, 3), stride=(1, 1),
                        pool_type="max"),
    "avg_global": dict(kernel=(2, 2), global_pool=True,
                       pool_type="avg"),
    "max_full_conv": dict(kernel=(3, 3), stride=(2, 2),
                          pooling_convention="full",
                          pool_type="max"),
    "sum_pool": dict(kernel=(2, 2), stride=(2, 2), pool_type="sum"),
}


@pytest.mark.parametrize("cfg", sorted(POOL_CONFIGS))
def test_pooling_matrix(cfg):
    params = dict(POOL_CONFIGS[cfg])
    data = mx.sym.Variable("data")
    sym = mx.sym.Pooling(data, **params)
    # distinct values keep max-pool numeric grads off ties
    x = np.linspace(0.1, 0.9, 2 * 2 * 6 * 6, dtype=np.float32) \
        .reshape(2, 2, 6, 6)
    x += RS.rand(2, 2, 6, 6).astype(np.float32) * 1e-3
    tu.check_numeric_gradient(sym, {"data": x}, numeric_eps=1e-4,
                              rtol=0.08, atol=5e-3)


# --------------------------------------------- degenerate-shape oracle
BROADCAST_SHAPES = [
    ((1, 1), (3, 4)),
    ((2, 1, 4), (1, 3, 1)),
    ((1,), (2, 3)),
    ((0, 3), (0, 3)),
    ((1, 3), (0, 1, 3)),
    ((5, 1), (1, 1)),
]
BROADCAST_OPS = {
    "broadcast_add": np.add,
    "broadcast_sub": np.subtract,
    "broadcast_mul": np.multiply,
    "broadcast_div": np.divide,
    "broadcast_maximum": np.maximum,
    "broadcast_minimum": np.minimum,
    "broadcast_power": np.power,
    "broadcast_hypot": np.hypot,
}


@pytest.mark.parametrize("opname", sorted(BROADCAST_OPS))
def test_broadcast_edge_shapes(opname):
    fn = getattr(nd, opname)
    ref = BROADCAST_OPS[opname]
    for sa, sb in BROADCAST_SHAPES:
        a = (RS.rand(*sa) * 0.8 + 0.1).astype(np.float32)
        b = (RS.rand(*sb) * 0.8 + 0.1).astype(np.float32)
        got = fn(nd.array(a), nd.array(b)).asnumpy()
        want = ref(a, b)
        assert got.shape == want.shape, (opname, sa, sb, got.shape)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6,
                                   err_msg=f"{opname} {sa}x{sb}")


UNARY_EMPTY = ["exp", "log1p", "relu", "sigmoid", "negative", "abs",
               "square", "tanh", "floor"]


@pytest.mark.parametrize("opname", UNARY_EMPTY)
def test_unary_on_empty_and_singleton(opname):
    fn = getattr(nd, opname)
    for shape in [(0, 3), (1, 1), (4, 0, 2)]:
        x = RS.rand(*shape).astype(np.float32)
        out = fn(nd.array(x)).asnumpy()
        assert out.shape == shape


def test_reductions_degenerate_axes():
    # sum over a 0-length axis is 0; over size-1 axes is identity
    x0 = np.zeros((2, 0, 3), np.float32)
    np.testing.assert_array_equal(
        nd.sum(nd.array(x0), axis=1).asnumpy(),
        np.zeros((2, 3), np.float32))
    x1 = RS.rand(2, 1, 3).astype(np.float32)
    np.testing.assert_allclose(
        nd.sum(nd.array(x1), axis=1).asnumpy(), x1[:, 0, :],
        rtol=1e-6)
    np.testing.assert_allclose(
        nd.prod(nd.array(x0), axis=1).asnumpy(),
        np.ones((2, 3), np.float32))
    np.testing.assert_allclose(
        nd.max(nd.array(x1), axis=1).asnumpy(), x1[:, 0, :],
        rtol=1e-6)


def test_concat_with_empty_part():
    a = RS.rand(2, 3).astype(np.float32)
    e = np.zeros((0, 3), np.float32)
    got = nd.concat(nd.array(a), nd.array(e), dim=0).asnumpy()
    np.testing.assert_allclose(got, a, rtol=1e-6)


def test_reshape_zero_size():
    x = nd.array(np.zeros((0, 4), np.float32))
    assert nd.reshape(x, shape=(0, 2, 2)).shape == (0, 2, 2)
    assert nd.Flatten(x).shape == (0, 4)


def test_norm_stats_accumulate_fp32():
    """LayerNorm/BatchNorm on bf16 inputs must compute statistics in
    fp32 (found by this sweep: bf16 statistics put LayerNorm ~2e-2
    off the fp32 oracle; with fp32 stats it lands within bf16 output
    rounding) while keeping the activation stream bf16."""
    x = (RS.rand(4, 64).astype(np.float32) - 0.3) * 3
    g = np.ones(64, np.float32)
    b = np.zeros(64, np.float32)
    ref = nd.LayerNorm(nd.array(x), nd.array(g),
                       nd.array(b)).asnumpy()
    out = nd.LayerNorm(nd.array(x).astype("bfloat16"),
                       nd.array(g).astype("bfloat16"),
                       nd.array(b).astype("bfloat16"))
    assert str(out.dtype) == "bfloat16"
    got = out.astype("float32").asnumpy()
    # bf16 OUTPUT rounding only: 1/128 relative
    np.testing.assert_allclose(got, ref, rtol=0.02, atol=0.02)


def test_block_cast_bf16_with_deferred_init():
    """net.cast('bfloat16') before the first forward: deferred-init
    params must materialize bf16 (found by this sweep: the out=
    rebind in imperative_invoke dropped the target dtype, so Xavier
    refilled cast weights as fp32 and conv raised a dtype mismatch)."""
    from incubator_mxnet_tpu import autograd, gluon
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Conv2D(4, 3), gluon.nn.BatchNorm(),
                gluon.nn.Dense(3))
    net.initialize(mx.initializer.Xavier())
    net.cast("bfloat16")
    x = nd.array(RS.rand(2, 3, 8, 8).astype(np.float32)) \
        .astype("bfloat16")
    with autograd.record():
        out = net(x)
        loss = out.astype("float32").square().mean()
    loss.backward()
    assert str(out.dtype) == "bfloat16"
    for name, p in net.collect_params().items():
        assert str(p.data().dtype) == "bfloat16", name


def test_batchnorm_variance_stable_at_large_mean():
    """Single-pass BN variance must not cancel catastrophically
    (review regression: raw E[x^2]-E[x]^2 gave 4x variance error at
    mean/std ~ 3000; the shifted form is exact)."""
    import jax.numpy as jnp
    from incubator_mxnet_tpu.ops.nn import batch_norm
    rs = np.random.RandomState(3)
    x = (rs.rand(8, 4, 5, 5).astype(np.float32) * 2.0 + 300.0)
    g = np.ones(4, np.float32)
    b = np.zeros(4, np.float32)
    out, nm, nv = batch_norm(
        jnp.asarray(x), jnp.asarray(g), jnp.asarray(b),
        jnp.zeros(4), jnp.ones(4), fix_gamma=False,
        _training=True, momentum=0.0)
    true_var = x.astype(np.float64).var(axis=(0, 2, 3))
    np.testing.assert_allclose(np.asarray(nv), true_var, rtol=5e-3)
    # normalized output really is ~N(0,1), not rsqrt(eps)-blown
    o = np.asarray(out)
    assert abs(o.mean()) < 0.05 and 0.8 < o.std() < 1.2
