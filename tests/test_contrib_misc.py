"""Contrib op tail (VERDICT r2 task 9): fft/ifft, count_sketch,
quantize/dequantize, Correlation, DeformablePSROIPooling, MakeLoss,
IdentityAttachKLSparseReg, cast_storage/reshape_like/_sparse_retain/
_square_sum.  Oracles are independent numpy implementations of the
documented reference semantics."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, nd

RS = np.random.RandomState(0)


# ---------------------------------------------------------------- fft

def test_fft_matches_numpy():
    x = RS.rand(3, 8).astype(np.float32)
    out = nd.contrib.fft(nd.array(x)).asnumpy()
    ref = np.fft.fft(x, axis=-1)
    inter = np.empty((3, 16), np.float32)
    inter[:, 0::2] = ref.real
    inter[:, 1::2] = ref.imag
    np.testing.assert_allclose(out, inter, rtol=1e-4, atol=1e-4)


def test_ifft_unnormalized_roundtrip():
    """MXNet's ifft is the unnormalized cuFFT inverse: ifft(fft(x))
    == n * x (ref: contrib/ifft-inl.h)."""
    x = RS.rand(2, 16).astype(np.float32)
    rt = nd.contrib.ifft(nd.contrib.fft(nd.array(x))).asnumpy()
    np.testing.assert_allclose(rt, 16 * x, rtol=1e-4, atol=1e-4)


def test_fft_gradient():
    x = nd.array(RS.rand(2, 8).astype(np.float32))
    x.attach_grad()
    with autograd.record():
        y = (nd.contrib.fft(x) ** 2).sum()
    y.backward()
    g = x.grad.asnumpy()
    assert np.all(np.isfinite(g)) and np.abs(g).max() > 0


# ------------------------------------------------------- count sketch

def test_count_sketch_oracle():
    n, d, od = 4, 10, 6
    x = RS.rand(n, d).astype(np.float32)
    h = RS.randint(0, od, d).astype(np.float32)
    s = np.where(RS.rand(d) < 0.5, -1.0, 1.0).astype(np.float32)
    out = nd.contrib.count_sketch(
        nd.array(x), nd.array(h[None]), nd.array(s[None]),
        out_dim=od).asnumpy()
    want = np.zeros((n, od), np.float32)
    for j in range(d):
        want[:, int(h[j])] += s[j] * x[:, j]
    np.testing.assert_allclose(out, want, rtol=1e-5)


# --------------------------------------------------------- quantize

def test_quantize_dequantize_roundtrip():
    x = RS.uniform(-3, 7, (4, 5)).astype(np.float32)
    lo, hi = nd.array([-3.0]), nd.array([7.0])
    q, qlo, qhi = nd.contrib.quantize(nd.array(x), lo, hi)
    assert q.asnumpy().dtype == np.uint8
    back = nd.contrib.dequantize(q, qlo, qhi).asnumpy()
    np.testing.assert_allclose(back, x, atol=10.0 / 255 + 1e-6)


# ------------------------------------------------------- correlation

def _corr_oracle(a, b, md, pad):
    """kernel 1, strides 1: out[dy,dx] = mean_c a[y,x] b[y+dy,x+dx]."""
    B, C, H, W = a.shape
    ap = np.pad(a, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    bp = np.pad(b, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    D = 2 * md + 1
    out = np.zeros((B, D * D, H, W), np.float32)
    k = 0
    for dy in range(-md, md + 1):
        for dx in range(-md, md + 1):
            for y in range(H):
                for x in range(W):
                    yy, xx = y + md + dy, x + md + dx
                    out[:, k, y, x] = (
                        ap[:, :, y + md, x + md]
                        * bp[:, :, yy, xx]).mean(axis=1)
            k += 1
    return out


def test_correlation_oracle():
    a = RS.rand(2, 3, 5, 5).astype(np.float32)
    b = RS.rand(2, 3, 5, 5).astype(np.float32)
    out = nd.Correlation(nd.array(a), nd.array(b), kernel_size=1,
                         max_displacement=2, stride1=1, stride2=1,
                         pad_size=2).asnumpy()
    np.testing.assert_allclose(out, _corr_oracle(a, b, 2, 2),
                               rtol=1e-4, atol=1e-5)


def test_correlation_subtract_mode_and_grad():
    a = nd.array(RS.rand(1, 2, 4, 4).astype(np.float32))
    b = nd.array(RS.rand(1, 2, 4, 4).astype(np.float32))
    a.attach_grad()
    with autograd.record():
        y = nd.Correlation(a, b, kernel_size=1, max_displacement=1,
                           pad_size=1, is_multiply=False).sum()
    y.backward()
    assert np.all(np.isfinite(a.grad.asnumpy()))


# ---------------------------------------- deformable PS-ROI pooling

def _dpsroi_oracle(data, roi, odim, g, p, spp, scale, trans,
                   trans_std):
    """Loop oracle of deformable_psroi_pooling-inl.h for one roi."""
    _, C, H, W = data.shape
    b = int(roi[0])
    x0 = roi[1] * scale - 0.5
    y0 = roi[2] * scale - 0.5
    x1 = roi[3] * scale + 0.5
    y1 = roi[4] * scale + 0.5
    rw, rh = max(x1 - x0, 0.1), max(y1 - y0, 0.1)
    bw, bh = rw / p, rh / p
    sub, sbh = bw / (spp + 1.0), bh / (spp + 1.0)
    out = np.zeros((odim, p, p), np.float32)
    for py in range(p):
        for px in range(p):
            dx = dy = 0.0
            gy, gx = min(py * g // p, g - 1), min(px * g // p, g - 1)
            n_cls = 1 if trans is None else trans.shape[0] // 2
            cec = max(odim // max(n_cls, 1), 1)
            for od in range(odim):
                ch = (od * g + gy) * g + gx  # ctop-major, like PSROI
                if trans is not None:
                    cls = od // cec
                    pt_y = min(py * trans.shape[-2] // p,
                               trans.shape[-2] - 1)
                    pt_x = min(px * trans.shape[-1] // p,
                               trans.shape[-1] - 1)
                    dx = trans[cls * 2, pt_y, pt_x] * trans_std * rw
                    dy = trans[cls * 2 + 1, pt_y, pt_x] \
                        * trans_std * rh
                acc = 0.0
                for iy in range(1, spp + 1):
                    for ix in range(1, spp + 1):
                        sy = y0 + py * bh + iy * sbh + dy
                        sx = x0 + px * bw + ix * sub + dx
                        if not (-1 < sy < H and -1 < sx < W):
                            continue
                        syc = min(max(sy, 0.0), H - 1.0)
                        sxc = min(max(sx, 0.0), W - 1.0)
                        yl, xl = int(syc), int(sxc)
                        yh, xh = min(yl + 1, H - 1), min(xl + 1, W - 1)
                        wy, wx = syc - yl, sxc - xl
                        v = ((1 - wy) * (1 - wx) * data[b, ch, yl, xl]
                             + (1 - wy) * wx * data[b, ch, yl, xh]
                             + wy * (1 - wx) * data[b, ch, yh, xl]
                             + wy * wx * data[b, ch, yh, xh])
                        acc += v
                out[od, py, px] = acc / (spp * spp)
    return out


@pytest.mark.parametrize("with_trans", [False, True])
def test_deformable_psroi_oracle(with_trans):
    odim, g, p, spp = 2, 2, 2, 2
    data = RS.rand(1, odim * g * g, 9, 9).astype(np.float32)
    rois = np.array([[0, 1, 1, 7, 7]], np.float32)
    if with_trans:
        trans = (RS.rand(1, 2, p, p).astype(np.float32) - 0.5)
        out = nd.contrib.DeformablePSROIPooling(
            nd.array(data), nd.array(rois), nd.array(trans),
            spatial_scale=1.0, output_dim=odim, group_size=g,
            pooled_size=p, sample_per_part=spp, trans_std=0.1)
        want = _dpsroi_oracle(data, rois[0], odim, g, p, spp, 1.0,
                              trans[0], 0.1)
    else:
        out = nd.contrib.DeformablePSROIPooling(
            nd.array(data), nd.array(rois), spatial_scale=1.0,
            output_dim=odim, group_size=g, pooled_size=p,
            sample_per_part=spp, no_trans=True)
        want = _dpsroi_oracle(data, rois[0], odim, g, p, spp, 1.0,
                              None, 0.0)
    np.testing.assert_allclose(out.asnumpy()[0], want, rtol=1e-4,
                               atol=1e-5)


# ------------------------------------------------------- loss heads

def test_make_loss_grad_scale_and_normalization():
    x = nd.array(RS.rand(4, 3).astype(np.float32))
    x.attach_grad()
    with autograd.record():
        y = nd.MakeLoss(x, grad_scale=2.0, normalization="batch")
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(),
                               np.full((4, 3), 0.5), rtol=1e-6)


def test_identity_attach_kl_sparse_reg_grad():
    x = nd.array(RS.uniform(0.2, 0.8, (6, 4)).astype(np.float32))
    x.attach_grad()
    with autograd.record():
        y = nd.IdentityAttachKLSparseReg(x, sparseness_target=0.2,
                                         penalty=0.1)
    y.backward()
    rho = x.asnumpy().mean(0)
    kl = (-0.2 / rho + 0.8 / (1 - rho)) / 6
    want = 1.0 + 0.1 * np.broadcast_to(kl, (6, 4))
    np.testing.assert_allclose(x.grad.asnumpy(), want, rtol=1e-4)


# ----------------------------------------------- storage / shapes

def test_cast_storage_graph_and_imperative():
    x = nd.array(RS.rand(2, 3).astype(np.float32))
    out = nd.cast_storage(x, stype="csr")  # imperative: storage-aware
    assert out.stype == "csr"
    sym_x = mx.sym.Variable("x")
    s = mx.sym.cast_storage(sym_x, stype="default")
    exe = s.simple_bind(mx.cpu(), grad_req="null", x=(2, 3))
    np.testing.assert_allclose(
        exe.forward(x=x)[0].asnumpy(), x.asnumpy())


def test_reshape_like_and_grad():
    a = nd.array(np.arange(6, dtype=np.float32))
    b = nd.array(np.zeros((2, 3), np.float32))
    a.attach_grad()
    with autograd.record():
        y = (nd.reshape_like(a, b) * 2).sum()
    y.backward()
    np.testing.assert_allclose(a.grad.asnumpy(), np.full(6, 2.0))


def test_square_sum_and_scatter_aliases():
    x = RS.rand(3, 4).astype(np.float32)
    out = nd._internal._square_sum(nd.array(x), axis=1).asnumpy()
    np.testing.assert_allclose(out, (x ** 2).sum(1), rtol=1e-5)
    d = nd._internal._scatter_elemwise_div(nd.array(x),
                                           nd.array(x + 1))
    np.testing.assert_allclose(d.asnumpy(), x / (x + 1), rtol=1e-6)


def test_plugin_hooks_raise_helpfully():
    with pytest.raises(NotImplementedError, match="Custom"):
        nd._internal._Native(nd.array(np.zeros(2, np.float32)))


# -------------------------------------------- appendix-A coverage

def test_appendix_a_coverage():
    """Every Appendix-A name the round-2 verdict listed as missing is
    now registered."""
    from incubator_mxnet_tpu.ops.registry import OPS
    for name in ["_contrib_fft", "_contrib_ifft",
                 "_contrib_count_sketch", "_contrib_quantize",
                 "_contrib_dequantize", "Correlation",
                 "_contrib_DeformablePSROIPooling", "MakeLoss",
                 "IdentityAttachKLSparseReg", "cast_storage",
                 "reshape_like", "_sparse_retain", "_square_sum",
                 "_scatter_elemwise_div", "_scatter_plus_scalar",
                 "_scatter_minus_scalar", "_NDArray", "_Native",
                 "_sparse_cast_storage"]:
        assert name in OPS, name


def test_appendix_a_full_parity():
    """Every op name in SURVEY.md Appendix A resolves in the registry
    (plugin ops excluded as out of scope: Caffe/Torch/WarpCTC)."""
    import os
    import re
    from incubator_mxnet_tpu.ops.registry import OPS
    survey = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "SURVEY.md")
    txt = open(survey).read()
    sec = txt[txt.index("## Appendix A"):txt.index("## Appendix B")]

    def expand(tok):
        m = re.match(r"(.*)\{([^}]*)\}(.*)", tok)
        if not m:
            return [tok]
        out = []
        for mid in m.group(2).split(","):
            out.extend(expand(m.group(1) + mid.strip() + m.group(3)))
        return out

    names = set()
    for block in re.findall(r"`([^`]+)`", sec):
        for tok in block.replace("\n", " ").split():
            for n in expand(tok):
                if re.fullmatch(r"[A-Za-z_][A-Za-z0-9_]*", n):
                    names.add(n)
    names -= {"CaffeLoss", "CaffeOp", "TorchCriterion", "TorchModule",
              "WarpCTC"}                       # plugins: out of scope
    names -= {"MXNET_REGISTER_OP_PROPERTY", "NNVM_REGISTER_OP",
              "add_alias"}                     # macro tokens, not ops
    missing = sorted(n for n in names if n not in OPS)
    assert not missing, missing
    assert len(names) >= 188  # the round-2 verdict's bar


def test_deformable_psroi_multiclass_trans():
    """Per-class offset channels are honored (round-3 review
    regression: class_id = ctop // channels_each_class)."""
    odim, g, p, spp = 2, 1, 2, 1
    data = RS.rand(1, odim * g * g, 8, 8).astype(np.float32)
    rois = np.array([[0, 1, 1, 6, 6]], np.float32)
    trans = (RS.rand(1, 4, p, p).astype(np.float32) - 0.5)  # 2 classes
    out = nd.contrib.DeformablePSROIPooling(
        nd.array(data), nd.array(rois), nd.array(trans),
        spatial_scale=1.0, output_dim=odim, group_size=g,
        pooled_size=p, sample_per_part=spp, trans_std=0.2)
    want = _dpsroi_oracle(data, rois[0], odim, g, p, spp, 1.0,
                          trans[0], 0.2)
    np.testing.assert_allclose(out.asnumpy()[0], want, rtol=1e-4,
                               atol=1e-5)


def test_cast_storage_dense_is_differentiable():
    """nd.cast_storage must stay on the autograd tape for dense
    arrays (round-3 review regression)."""
    x = nd.array(RS.rand(2, 3).astype(np.float32))
    x.attach_grad()
    with autograd.record():
        y = (nd.cast_storage(x, "default") * 2).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), np.full((2, 3), 2.0))
