"""Multiprocess DataLoader: forked workers + shared-memory batches.

Ref: python/mxnet/gluon/data/dataloader.py:23-73 — the reference's
process workers return batches through CPUSharedStorageManager; here
workers write numpy batches into multiprocessing.shared_memory and the
parent maps them out of /dev/shm.
"""
import glob
import os
import time
import warnings

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon


class _PidDataset(gluon.data.Dataset):
    """Sample = (deterministic array, pid of the producing process)."""

    def __len__(self):
        return 24

    def __getitem__(self, idx):
        return (np.full((3, 4), idx, dtype="float32"),
                np.int64(os.getpid()))


_PREEXISTING = set(glob.glob("/dev/shm/mxtpu_dl_*"))


def _leaked_segments():
    return set(glob.glob("/dev/shm/mxtpu_dl_*")) - _PREEXISTING


def test_mp_loader_matches_sync():
    ds = _PidDataset()
    sync = gluon.data.DataLoader(ds, batch_size=4)
    mp = gluon.data.DataLoader(ds, batch_size=4, num_workers=2)
    sync_batches = [b[0].asnumpy() for b in sync]
    mp_batches = [b[0].asnumpy() for b in mp]
    assert len(mp_batches) == len(sync_batches) == 6
    for a, b in zip(sync_batches, mp_batches):
        np.testing.assert_array_equal(a, b)
    assert not _leaked_segments()


def test_mp_loader_runs_in_child_processes():
    loader = gluon.data.DataLoader(_PidDataset(), batch_size=4,
                                   num_workers=2)
    pids = set()
    for _, pid_batch in loader:
        pids.update(int(p) for p in pid_batch.asnumpy())
    assert os.getpid() not in pids, "samples were produced in-parent"
    assert 1 <= len(pids) <= 2


def test_mp_loader_tuple_structure_and_types():
    loader = gluon.data.DataLoader(_PidDataset(), batch_size=3,
                                   num_workers=2)
    batch = next(iter(loader))
    assert isinstance(batch, list) and len(batch) == 2
    assert isinstance(batch[0], mx.nd.NDArray)
    assert batch[0].shape == (3, 3, 4)
    assert batch[1].shape == (3,)


def test_mp_loader_custom_batchify():
    def batchify(samples):
        return np.stack([s[0] for s in samples]).sum(axis=0)

    loader = gluon.data.DataLoader(_PidDataset(), batch_size=4,
                                   num_workers=2,
                                   batchify_fn=batchify)
    first = next(iter(loader))
    np.testing.assert_allclose(
        np.asarray(first), np.full((3, 4), 0 + 1 + 2 + 3, "float32"))
    assert not _leaked_segments()


def test_mp_loader_early_abandon_cleans_up():
    loader = gluon.data.DataLoader(_PidDataset(), batch_size=2,
                                   num_workers=2)
    it = iter(loader)
    next(it)
    it.close()
    deadline = time.time() + 5
    while _leaked_segments() and time.time() < deadline:
        time.sleep(0.05)
    assert not _leaked_segments()


class _TimedDataset(gluon.data.Dataset):
    """Sample = (pid, start, end) of its own production interval."""

    def __len__(self):
        return 8

    def __getitem__(self, idx):
        start = time.monotonic()
        time.sleep(0.25)
        return np.array([os.getpid(), start, time.monotonic()],
                        dtype="float64")


def test_mp_loader_workers_run_concurrently():
    # two workers' production intervals must overlap — proving the
    # batches are built in parallel, not serialized through the
    # parent (wall-clock-robust version of a speedup assertion)
    loader = gluon.data.DataLoader(_TimedDataset(), batch_size=2,
                                   num_workers=2)
    spans = {}
    for batch in loader:
        for pid, start, end in batch.asnumpy():
            s, e = spans.get(pid, (start, end))
            spans[pid] = (min(s, start), max(e, end))
    if len(spans) < 2:
        pytest.skip("pool scheduled every batch on one worker")
    (s1, e1), (s2, e2) = list(spans.values())[:2]
    assert max(s1, s2) < min(e1, e2), spans


class _Bf16Dataset(gluon.data.Dataset):
    def __len__(self):
        return 4

    def __getitem__(self, idx):
        import ml_dtypes
        return np.full((3,), idx, dtype=ml_dtypes.bfloat16)


def test_mp_loader_extension_dtype_roundtrip():
    loader = gluon.data.DataLoader(_Bf16Dataset(), batch_size=2,
                                   num_workers=2)
    batches = list(loader)
    assert str(batches[0].dtype) == "bfloat16"
    np.testing.assert_allclose(
        batches[1].astype("float32").asnumpy(),
        [[2, 2, 2], [3, 3, 3]])


def test_mp_loader_backpressure_bounds_shm():
    # slow consumer: workers must not run ahead more than
    # 2*num_workers batches (each batch = 2 arrays in shm)
    loader = gluon.data.DataLoader(_PidDataset(), batch_size=1,
                                   num_workers=2)
    it = iter(loader)
    next(it)
    time.sleep(1.0)          # give workers time to (over)produce
    live = len(_leaked_segments())
    assert live <= (2 * 2 + 2) * 2, live
    for _ in it:
        pass
    assert not _leaked_segments()


def test_mp_loader_explicit_default_batchify_is_safe():
    # passing the exported default_batchify_fn explicitly must take
    # the same numpy-in-worker path as batchify_fn=None (building
    # NDArrays inside the forked child can deadlock jax)
    from incubator_mxnet_tpu.gluon.data.dataloader import \
        default_batchify_fn
    loader = gluon.data.DataLoader(_PidDataset(), batch_size=4,
                                   num_workers=2,
                                   batchify_fn=default_batchify_fn)
    batch = next(iter(loader))
    assert isinstance(batch[0], mx.nd.NDArray)
    assert batch[0].shape == (4, 3, 4)


class _DyingDataset(gluon.data.Dataset):
    def __len__(self):
        return 8

    def __getitem__(self, idx):
        if idx == 3:
            os._exit(1)        # simulate a native crash / OOM kill
        return np.zeros(2, "float32")


def test_mp_loader_dead_worker_raises_typed_not_hangs(monkeypatch):
    from incubator_mxnet_tpu.resilience import DataPipelineError
    monkeypatch.setenv("MXTPU_DL_DEAD_GRACE", "2")
    monkeypatch.setenv("MXTPU_DATA_WORKER_RESTARTS", "0")
    loader = gluon.data.DataLoader(_DyingDataset(), batch_size=2,
                                   num_workers=2)
    start = time.monotonic()
    # also a plain RuntimeError (legacy guards keep working)
    with pytest.raises(DataPipelineError, match="worker died"):
        for _ in loader:
            pass
    assert time.monotonic() - start < 60
    deadline = time.time() + 5
    while _leaked_segments() and time.time() < deadline:
        time.sleep(0.05)
    assert not _leaked_segments()   # dead worker's shm swept


class _KillOnceDataset(gluon.data.Dataset):
    """idx 3 SIGKILLs its worker — but only the first time (a marker
    file records the casualty), so the re-dispatched batch succeeds:
    the recovery path, not just the give-up path."""

    def __init__(self, marker):
        self._marker = marker

    def __len__(self):
        return 12

    def __getitem__(self, idx):
        if idx == 3 and not os.path.exists(self._marker):
            with open(self._marker, "w") as f:
                f.write(str(os.getpid()))
            import signal
            os.kill(os.getpid(), signal.SIGKILL)
        return np.full((2,), idx, dtype="float32")


def test_mp_loader_sigkilled_worker_recovers_via_redispatch(
        tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_DL_DEAD_GRACE", "1")
    monkeypatch.setenv("MXTPU_DATA_WORKER_RESTARTS", "2")
    marker = str(tmp_path / "died")
    loader = gluon.data.DataLoader(_KillOnceDataset(marker),
                                   batch_size=2, num_workers=2)
    start = time.monotonic()
    with warnings.catch_warnings(record=True) as wl:
        warnings.simplefilter("always")
        batches = [b.asnumpy() for b in loader]
    assert time.monotonic() - start < 60
    assert os.path.exists(marker)               # a worker did die
    assert any("re-dispatching" in str(x.message) for x in wl)
    # every batch arrived, in order, despite the mid-epoch SIGKILL
    got = sorted(v for b in batches for v in b[:, 0].tolist())
    assert got == [float(i) for i in range(12)]
    deadline = time.time() + 5
    while _leaked_segments() and time.time() < deadline:
        time.sleep(0.05)
    assert not _leaked_segments()   # no orphan /dev/shm segments


def test_short_data_timeout_does_not_preempt_redispatch(
        tmp_path, monkeypatch):
    # MXTPU_DATA_TIMEOUT below the dead-worker grace must not raise
    # "pool is stalled" while the re-dispatch path is pursuing an
    # observed respawn (review regression)
    monkeypatch.setenv("MXTPU_DATA_TIMEOUT", "2")
    monkeypatch.setenv("MXTPU_DL_DEAD_GRACE", "4")
    monkeypatch.setenv("MXTPU_DATA_WORKER_RESTARTS", "2")
    marker = str(tmp_path / "died")
    loader = gluon.data.DataLoader(_KillOnceDataset(marker),
                                   batch_size=2, num_workers=2)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        batches = [b.asnumpy() for b in loader]
    got = sorted(v for b in batches for v in b[:, 0].tolist())
    assert got == [float(i) for i in range(12)]


class _RaisingDataset(gluon.data.Dataset):
    def __len__(self):
        return 8

    def __getitem__(self, idx):
        if idx == 5:
            raise ValueError("bad sample 5")
        return np.zeros(2, "float32")


def test_mp_loader_worker_exception_is_typed():
    from incubator_mxnet_tpu.resilience import DataPipelineError
    loader = gluon.data.DataLoader(_RaisingDataset(), batch_size=2,
                                   num_workers=2)
    with pytest.raises(DataPipelineError, match="bad sample 5"):
        for _ in loader:
            pass


def test_thread_pool_mode_still_works():
    loader = gluon.data.DataLoader(_PidDataset(), batch_size=4,
                                   num_workers=2, thread_pool=True)
    pids = set()
    for _, pid_batch in loader:
        pids.update(int(p) for p in pid_batch.asnumpy())
    assert pids == {os.getpid()}


class _CrashDataset(gluon.data.Dataset):
    """idx 3 kills its worker; idx 0/1 are slow enough that their
    batch completes only after the pool has respawned the dead
    worker — the masking scenario the respawn-generation logic
    exists for (a global pid re-snapshot would hang forever)."""

    def __len__(self):
        return 12

    def __getitem__(self, idx):
        if idx in (0, 1):
            time.sleep(2.0)
        if idx == 3:
            time.sleep(0.5)
            os._exit(1)
        return np.full((2,), idx, dtype="float32")


def test_mp_loader_dead_worker_raises_not_hangs(monkeypatch):
    monkeypatch.setenv("MXTPU_DL_DEAD_GRACE", "6")
    monkeypatch.setenv("MXTPU_DATA_WORKER_RESTARTS", "0")
    loader = gluon.data.DataLoader(_CrashDataset(), batch_size=2,
                                   num_workers=2)
    with pytest.raises(RuntimeError, match="worker died"):
        for _ in loader:
            pass


class _WedgedDataset(gluon.data.Dataset):
    def __len__(self):
        return 4

    def __getitem__(self, idx):
        if idx == 1:
            time.sleep(3600)     # wedged in native code / NFS stall
        return np.zeros(2, "float32")


def test_mp_loader_wedged_worker_bounded_by_data_timeout(monkeypatch):
    from incubator_mxnet_tpu.resilience import DataPipelineError
    monkeypatch.setenv("MXTPU_DATA_TIMEOUT", "3")
    loader = gluon.data.DataLoader(_WedgedDataset(), batch_size=2,
                                   num_workers=1)
    start = time.monotonic()
    with pytest.raises(DataPipelineError, match="MXTPU_DATA_TIMEOUT"):
        for _ in loader:
            pass
    assert time.monotonic() - start < 30


def test_loader_state_dict_of_armed_resume_is_not_lost():
    # checkpointing between load_state_dict() and the first batch
    # must re-emit the armed state, not batches_served=0 (review
    # regression)
    loader = gluon.data.DataLoader(_PidDataset(), batch_size=4)
    loader.load_state_dict({"type": "DataLoader",
                            "batches_served": 3,
                            "epoch_rng": np.random.get_state()})
    state = loader.state_dict()
    assert state["batches_served"] == 3
    assert len(list(loader)) == 3    # 6 batches - 3 served


def test_mp_loader_state_dict_resumes_at_batch():
    ds = _PidDataset()
    np.random.seed(9)
    loader = gluon.data.DataLoader(ds, batch_size=4, shuffle=True,
                                   num_workers=2)
    it = iter(loader)
    for _ in range(2):
        next(it)
    state = loader.state_dict()
    want = [b[0].asnumpy() for b in it]

    np.random.seed(1)
    loader2 = gluon.data.DataLoader(ds, batch_size=4, shuffle=True,
                                    num_workers=2)
    loader2.load_state_dict(state)
    got = [b[0].asnumpy() for b in loader2]
    assert len(got) == len(want) == 4
    for a, b in zip(want, got):
        np.testing.assert_array_equal(a, b)
    assert not _leaked_segments()
