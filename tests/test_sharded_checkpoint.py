"""Sharded reshardable checkpoints (parallel/checkpoint.py,
docs/elastic.md): property test over every mesh factorization of
1/2/4/8 devices (bitwise round-trip of a randomized pytree including
nested optimizer state), generation fallback past corrupt shards,
zero half-written manifests under fault injection, train-step
integration, and the elastic restart plumbing."""
import os
import pickle

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import resilience as rz
from incubator_mxnet_tpu import telemetry
from incubator_mxnet_tpu.parallel import (make_mesh, checkpoint as ck,
                                          ShardedTrainStep)
from incubator_mxnet_tpu.parallel.sharding import (
    intersect_bounds, shard_bounds, spec_from_json, spec_to_json)


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("MXTPU_FAULT_SPEC", raising=False)
    rz.reset_faults()
    yield
    rz.reset_faults()


def _mesh_configs():
    """Every (n_devices, dp, tp) factorization of 1/2/4/8 devices."""
    out = []
    for n in (1, 2, 4, 8):
        for tp in (1, 2, 4, 8):
            if n % tp == 0:
                out.append((n, n // tp, tp))
    return out


def _mesh(n, dp, tp):
    return make_mesh(dp=dp, tp=tp, devices=jax.devices("cpu")[:n])


def _rand_tree(mesh, seed=0):
    """Randomized params + nested optimizer-state pytree with specs
    drawn per leaf from what divides its dims on this mesh."""
    rs = np.random.RandomState(seed)
    dp, tp = mesh.shape["dp"], mesh.shape["tp"]

    def spec_for(shape):
        cands = [P()]
        if shape and shape[0] % tp == 0:
            cands.append(P("tp"))
        if shape and shape[0] % dp == 0:
            cands.append(P("dp"))
        if len(shape) > 1 and shape[1] % tp == 0:
            cands.append(P(None, "tp"))
        if len(shape) > 1 and shape[0] % dp == 0 \
                and shape[1] % tp == 0:
            cands.append(P("dp", "tp"))
        return cands[rs.randint(len(cands))]

    shapes = {"w_up": (16, 8), "w_down": (8, 16), "bias": (16,),
              "conv": (8, 8, 3), "odd": (7, 5)}
    params = {}
    for name, shape in shapes.items():
        arr = rs.rand(*shape).astype(np.float32)
        params[name] = jax.device_put(
            jnp.asarray(arr), NamedSharding(mesh, spec_for(shape)))
    opt = {"mean": {n: jax.device_put(
               jnp.asarray(rs.rand(*v.shape).astype(np.float32)),
               v.sharding) for n, v in params.items()},
           "t": jnp.asarray(rs.randint(100), jnp.int32)}
    return {"params": params, "opt_state": opt}


def _assert_tree_bitwise(got, want):
    gl = jax.tree_util.tree_flatten_with_path(got)[0]
    wl = dict(jax.tree_util.tree_flatten_with_path(want)[0])
    for path, leaf in gl:
        ref = wl[path]
        assert np.array_equal(np.asarray(leaf), np.asarray(ref)), \
            jax.tree_util.keystr(path)


# -------------------------------------------------------- slice math
def test_spec_json_roundtrip():
    for spec in (P(), P("tp"), P(None, "tp"), P(("dp", "tp"), None),
                 P("dp", None, "tp")):
        assert spec_from_json(spec_to_json(spec)) == spec


def test_shard_bounds_partition_exact():
    mesh = _mesh(8, 4, 2)
    sh = NamedSharding(mesh, P("dp", "tp"))
    bounds = shard_bounds(sh, (8, 4))
    assert len(bounds) == 8                       # 4x2 unique slices
    total = sum((hi0 - lo0) * (hi1 - lo1)
                for (lo0, hi0), (lo1, hi1) in bounds)
    assert total == 32                            # exact cover
    # replication: a 'tp'-only spec on the same mesh replicates over
    # dp -> each unique slice held by 4 devices, owner = min id
    sh2 = NamedSharding(mesh, P(None, "tp"))
    b2 = shard_bounds(sh2, (8, 4))
    assert len(b2) == 2
    for devs in b2.values():
        assert len(devs) == 4
        assert devs[0].id == min(d.id for d in devs)


def test_intersect_bounds():
    assert intersect_bounds(((0, 4),), ((2, 8),)) == ((2, 4),)
    assert intersect_bounds(((0, 4),), ((4, 8),)) is None
    assert intersect_bounds((), ()) == ()


# ---------------------------------------------------- property test
@pytest.mark.parametrize("src", _mesh_configs())
def test_reshard_roundtrip_bitwise_all_factorizations(src, tmp_path):
    """Save on mesh A, restore onto every mesh B (worlds 1/2/4/8,
    every dp×tp split, random destination specs): params AND nested
    optimizer state come back bitwise identical, laid out in B's
    shardings."""
    n, dp, tp = src
    meshA = _mesh(n, dp, tp)
    tree = _rand_tree(meshA, seed=n * 100 + dp)
    ck.save_sharded(str(tmp_path / "ck"), tree, meshA, step=1)
    for (n2, dp2, tp2) in _mesh_configs():
        meshB = _mesh(n2, dp2, tp2)
        target = _rand_tree(meshB, seed=n2 * 7 + tp2)   # other layout
        restored, manifest, _ = ck.load_latest(
            str(tmp_path / "ck"), target, meshB)
        _assert_tree_bitwise(restored, tree)
        # values landed in the DESTINATION's shardings
        for name, leaf in restored["params"].items():
            assert leaf.sharding == target["params"][name].sharding


def test_save_cost_is_sharded_not_replicated(tmp_path):
    """Each rank file holds only the slices its device owns: for a
    dp-sharded leaf the per-file payload is 1/dp of the array, and a
    replicated leaf appears in exactly ONE file (the canonical
    owner's), not every rank's."""
    mesh = _mesh(8, 8, 1)
    big = jax.device_put(jnp.arange(64, dtype=jnp.float32),
                         NamedSharding(mesh, P("dp")))
    rep = jax.device_put(jnp.arange(16, dtype=jnp.float32),
                         NamedSharding(mesh, P()))
    gen = ck.save_sharded(str(tmp_path / "ck"),
                          {"big": big, "rep": rep}, mesh, step=0)
    shard_files = sorted(f for f in os.listdir(gen)
                         if f.startswith("shard-")
                         and not f.endswith(".crc32"))
    assert len(shard_files) == 8
    holders = []
    for f in shard_files:
        with open(os.path.join(gen, f), "rb") as fh:
            payload = pickle.loads(fh.read())
        for key, arr in payload.items():
            if "big" in key:
                assert arr.size == 8          # 64 / dp=8
            if "rep" in key:
                holders.append(f)
    assert len(holders) == 1                  # one owner, not 8


# ------------------------------------------------- generations/chaos
def test_generation_fallback_past_corrupt_shard(tmp_path,
                                                monkeypatch):
    """checkpoint:shard injection corrupts one shard file of the
    newest generation; load falls back to the newest fully-valid one
    with telemetry + a trace event, and no tmp files survive."""
    mesh = _mesh(4, 4, 1)
    tree = _rand_tree(mesh, seed=3)
    d = str(tmp_path / "ck")
    ck.save_sharded(d, tree, mesh, step=1)
    tree2 = _rand_tree(mesh, seed=4)
    monkeypatch.setenv("MXTPU_FAULT_SPEC",
                       "checkpoint:shard:2:corrupt")
    rz.reset_faults()
    before = telemetry.counter(
        "checkpoint_shard_corrupt_total").value
    ck.save_sharded(d, tree2, mesh, step=2)
    monkeypatch.delenv("MXTPU_FAULT_SPEC")
    rz.reset_faults()
    with pytest.warns(RuntimeWarning, match="falling back"):
        restored, manifest, _ = ck.load_latest(d, tree, mesh)
    assert manifest["step"] == 1
    _assert_tree_bitwise(restored, tree)
    assert telemetry.counter(
        "checkpoint_shard_corrupt_total").value > before
    orphans = [f for _, _, fs in os.walk(d) for f in fs
               if ".tmp." in f]
    assert orphans == []


def test_truncated_shard_detected(tmp_path, monkeypatch):
    mesh = _mesh(2, 2, 1)
    tree = _rand_tree(mesh, seed=5)
    d = str(tmp_path / "ck")
    monkeypatch.setenv("MXTPU_FAULT_SPEC",
                       "checkpoint:shard:1:truncate")
    rz.reset_faults()
    ck.save_sharded(d, tree, mesh, step=0)
    monkeypatch.delenv("MXTPU_FAULT_SPEC")
    rz.reset_faults()
    with pytest.raises(rz.CheckpointCorruptError):
        ck.load_latest(d, tree, mesh)


def test_no_halfwritten_manifest_when_save_dies(tmp_path,
                                                monkeypatch):
    """A save killed mid-shard-write (injected error) leaves NO
    manifest — the generation is invisible, the previous one stays
    newest, and no tmp files leak (the zero-orphan contract)."""
    mesh = _mesh(4, 2, 2)
    tree = _rand_tree(mesh, seed=6)
    d = str(tmp_path / "ck")
    ck.save_sharded(d, tree, mesh, step=1)
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "checkpoint:shard:3:error")
    rz.reset_faults()
    with pytest.raises(rz.TransientError):
        ck.save_sharded(d, _rand_tree(mesh, seed=7), mesh, step=2)
    monkeypatch.delenv("MXTPU_FAULT_SPEC")
    rz.reset_faults()
    assert ck.generations(d) == [1]
    assert not os.path.exists(
        os.path.join(d, "gen-00000002", "manifest.json"))
    orphans = [f for _, _, fs in os.walk(d) for f in fs
               if ".tmp." in f]
    assert orphans == []
    restored, manifest, _ = ck.load_latest(d, tree, mesh)
    assert manifest["step"] == 1
    # the next committed save sweeps the manifest-less husk: its
    # shard bytes must not leak under MXTPU_CKPT_KEEP forever
    ck.save_sharded(d, _rand_tree(mesh, seed=8), mesh, step=3)
    assert not os.path.isdir(os.path.join(d, "gen-00000002"))
    assert ck.generations(d) == [3, 1]


def test_same_step_resave_recommits_cleanly(tmp_path, monkeypatch):
    """Re-saving an existing generation (fallback -> retrain -> same
    step) must uncommit it first: a completed re-save restores the
    NEW tree, and a re-save that dies mid-shard leaves the
    generation invisible instead of pairing the old manifest with
    mixed shard files."""
    mesh = _mesh(4, 2, 2)
    d = str(tmp_path / "ck")
    t1 = _rand_tree(mesh, seed=20)
    t2 = _rand_tree(mesh, seed=21)
    ck.save_sharded(d, t1, mesh, step=5)
    ck.save_sharded(d, t2, mesh, step=5)       # clean re-save
    restored, manifest, _ = ck.load_latest(d, t1, mesh)
    assert manifest["step"] == 5
    _assert_tree_bitwise(restored, t2)
    # dying mid-re-save uncommits: nothing valid remains at step 5
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "checkpoint:shard:2:error")
    rz.reset_faults()
    with pytest.raises(rz.TransientError):
        ck.save_sharded(d, _rand_tree(mesh, seed=22), mesh, step=5)
    monkeypatch.delenv("MXTPU_FAULT_SPEC")
    rz.reset_faults()
    assert ck.generations(d) == []


def test_generation_pruning_keeps_newest(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_CKPT_KEEP", "2")
    mesh = _mesh(2, 2, 1)
    d = str(tmp_path / "ck")
    for step in range(4):
        ck.save_sharded(d, _rand_tree(mesh, seed=step), mesh,
                        step=step)
    assert ck.generations(d) == [3, 2]


def test_structure_mismatch_is_loud(tmp_path):
    """Restoring into a differently-built optimizer tree must fail
    naming the difference, never restore partially."""
    mesh = _mesh(2, 2, 1)
    tree = _rand_tree(mesh, seed=8)
    d = str(tmp_path / "ck")
    ck.save_sharded(d, tree, mesh, step=0)
    target = dict(tree)
    target["opt_state"] = {"var": tree["opt_state"]["mean"],
                           "t": tree["opt_state"]["t"]}
    with pytest.raises(ValueError, match="structure"):
        ck.load_latest(d, target, mesh)
    bad_shape = {"params": dict(tree["params"]),
                 "opt_state": tree["opt_state"]}
    bad_shape["params"]["w_up"] = jnp.zeros((4, 4), jnp.float32)
    with pytest.raises(ValueError, match="shapes/dtypes"):
        ck.load_latest(d, bad_shape, mesh)


def test_data_companion_travels_with_generation(tmp_path):
    mesh = _mesh(2, 2, 1)
    tree = _rand_tree(mesh, seed=9)
    d = str(tmp_path / "ck")
    state = {"type": "DataServiceIter", "bidx": 7}
    ck.save_sharded(d, tree, mesh, step=0, data_state=state)
    restored, manifest, gen_dir = ck.load_latest(d, tree, mesh)
    assert ck.load_data_companion(gen_dir, manifest) == state


# ------------------------------------------------- step integration
def _sharded_step(mesh):
    mx.random.seed(0)
    net = mx.gluon.nn.HybridSequential(prefix="esck_")
    with net.name_scope():
        net.add(mx.gluon.nn.Dense(16, activation="relu"),
                mx.gluon.nn.Dense(4))
    net.initialize(mx.initializer.Xavier())
    return ShardedTrainStep(
        net, optimizer="adam",
        optimizer_params=dict(learning_rate=1e-2), mesh=mesh,
        example_args=[jnp.zeros((2, 8), jnp.float32)])


def test_step_checkpoint_shrink_grow_bitwise(tmp_path):
    """Train on the 8-device mesh, checkpoint, restore onto a
    4-device world (shrink) and back onto 8 (grow): params, states
    and optimizer state bitwise equal through both hops."""
    rs = np.random.RandomState(0)
    batches = [(jnp.asarray(rs.rand(16, 8), jnp.float32),
                jnp.asarray(rs.randint(0, 4, (16,)), jnp.int32))
               for _ in range(4)]
    a = _sharded_step(make_mesh(dp=8))
    for x, y in batches:
        a(x, y)
    want = {"params": {k: np.asarray(v)
                       for k, v in a.params.items()},
            "opt": jax.tree_util.tree_map(np.asarray, a.opt_state)}
    a.save_checkpoint(str(tmp_path / "ck"))

    b = _sharded_step(make_mesh(dp=4, devices=jax.devices("cpu")[:4]))
    b.load_checkpoint(str(tmp_path / "ck"))
    for k, v in b.params.items():
        assert np.array_equal(np.asarray(v), want["params"][k]), k
        assert v.sharding.mesh.shape["dp"] == 4
    _assert_tree_bitwise(b.opt_state, want["opt"])
    b.save_checkpoint(str(tmp_path / "ck2"))

    c = _sharded_step(make_mesh(dp=8))
    c.load_checkpoint(str(tmp_path / "ck2"))
    for k, v in c.params.items():
        assert np.array_equal(np.asarray(v), want["params"][k]), k
    _assert_tree_bitwise(c.opt_state, want["opt"])
    assert int(c.step_count) == 4


def test_module_sharded_checkpoint_reshard(tmp_path):
    """Module (kvstore='tpu') elastic checkpoint: save on dp=8,
    restore on dp=4 — params visible through get_params, data
    companion round-trips."""
    from incubator_mxnet_tpu.parallel import use_mesh

    def build(mesh):
        data = mx.sym.Variable("data")
        net = mx.sym.FullyConnected(data, name="esm_fc",
                                    num_hidden=4)
        net = mx.sym.SoftmaxOutput(net, name="softmax")
        mod = mx.mod.Module(net, context=mx.cpu())
        mod.bind(data_shapes=[("data", (8, 6))],
                 label_shapes=[("softmax_label", (8,))])
        mx.random.seed(7)
        mod.init_params(mx.initializer.Xavier())
        with use_mesh(mesh):
            mod.init_optimizer(kvstore="tpu", optimizer="sgd",
                               optimizer_params=dict(
                                   learning_rate=0.1, momentum=0.9))
        return mod

    rs = np.random.RandomState(1)
    x = mx.nd.array(rs.rand(8, 6).astype(np.float32))
    y = mx.nd.array(rs.randint(0, 4, (8,)).astype(np.float32))
    batch = mx.io.DataBatch([x], [y])
    mod = build(make_mesh(dp=8))
    for _ in range(3):
        mod.forward_backward(batch)
        mod.update()
    arg, aux = mod.get_params()
    mod.save_sharded_checkpoint(
        str(tmp_path / "ck"), step=3,
        data_iter=_FakeIter({"pos": 42}))

    mod2 = build(make_mesh(dp=4, devices=jax.devices("cpu")[:4]))
    it = _FakeIter(None)
    state = mod2.load_sharded_checkpoint(str(tmp_path / "ck"),
                                         data_iter=it)
    assert state == {"pos": 42} and it.loaded == {"pos": 42}
    arg2, aux2 = mod2.get_params()
    for k in arg:
        assert np.array_equal(arg[k].asnumpy(), arg2[k].asnumpy()), k

    # eager-touched params (set_params marks the mesh step stale)
    # must be pushed before checkpointing — not silently dropped
    new_w = {k: mx.nd.array(np.full(v.shape, 0.5, np.float32))
             for k, v in arg.items()}
    mod2.set_params(new_w, aux2)
    mod2.save_sharded_checkpoint(str(tmp_path / "ck2"), step=4)
    mod3 = build(make_mesh(dp=4, devices=jax.devices("cpu")[:4]))
    mod3.load_sharded_checkpoint(str(tmp_path / "ck2"))
    arg3, _ = mod3.get_params()
    for k in new_w:
        assert np.array_equal(arg3[k].asnumpy(),
                              new_w[k].asnumpy()), k


class _FakeIter:
    def __init__(self, state):
        self._state = state
        self.loaded = None

    def state_dict(self):
        return self._state

    def load_state_dict(self, state):
        self.loaded = state


# ------------------------------------------------- elastic plumbing
def test_kill_fault_kind_parse_rules():
    assert rz.parse_fault_spec("elastic:rank0:3:kill") == \
        [("elastic", "rank0", 3, "kill")]
    with pytest.raises(ValueError, match="'kill' only"):
        rz.parse_fault_spec("collective:allreduce:1:kill")


def test_elastic_probe_counts_per_rank(monkeypatch):
    """elastic:rank<N> fires on the right rank's nth step (the kill
    itself is exercised subprocess-side in test_dist_launch)."""
    from incubator_mxnet_tpu import dist
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "elastic:rank0:2:error")
    rz.reset_faults()
    dist.elastic_probe()                      # 1st call: no fire
    with pytest.raises(rz.TransientError):
        dist.elastic_probe()                  # 2nd call: fires


def test_exithook_codes(monkeypatch):
    """Uncaught CollectiveAbortedError exits 14 under elastic mode,
    crashes normally otherwise; ElasticRestartRequested always 14."""
    import subprocess
    import sys
    prog = (
        "import os\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "from incubator_mxnet_tpu import resilience as rz\n"
        "rz.install_diverged_exithook()\n"
        "import sys\n"
        "kind = sys.argv[1]\n"
        "if kind == 'abort':\n"
        "    raise rz.CollectiveAbortedError('peer died')\n"
        "if kind == 'coll_deadline':\n"
        "    e = rz.DeadlineExceededError('collective hung')\n"
        "    e.collective = True\n"
        "    raise e\n"
        "if kind == 'local_deadline':\n"
        "    raise rz.DeadlineExceededError('local disk wedged')\n"
        "raise rz.ElasticRestartRequested('re-admit me')\n")
    env = dict(os.environ, MXTPU_ELASTIC="1")
    r = subprocess.run([sys.executable, "-c", prog, "abort"],
                       env=env, capture_output=True, timeout=120)
    assert r.returncode == rz.ELASTIC_EXIT_CODE, r.stderr[-500:]
    env_off = dict(os.environ)
    env_off.pop("MXTPU_ELASTIC", None)
    r = subprocess.run([sys.executable, "-c", prog, "abort"],
                       env=env_off, capture_output=True, timeout=120)
    assert r.returncode == 1
    r = subprocess.run([sys.executable, "-c", prog, "request"],
                       env=env_off, capture_output=True, timeout=120)
    assert r.returncode == rz.ELASTIC_EXIT_CODE
    # only COLLECTIVE deadline expiries (tagged by dist._guarded)
    # take the elastic exit — a local one means THIS rank is sick
    # and must look like a crash so the policy shrinks it out
    r = subprocess.run([sys.executable, "-c", prog, "coll_deadline"],
                       env=env, capture_output=True, timeout=120)
    assert r.returncode == rz.ELASTIC_EXIT_CODE
    r = subprocess.run([sys.executable, "-c", prog,
                        "local_deadline"],
                       env=env, capture_output=True, timeout=120)
    assert r.returncode == 1


def test_dist_shutdown_is_reentrant():
    from incubator_mxnet_tpu import dist
    dist.shutdown()          # never initialized: clean no-op
    assert not dist.is_initialized()


# ----------------------------------------------------------- lint
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_lint():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "lint", os.path.join(REPO, "ci", "lint.py"))
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    return lint


def test_lint_forbids_bare_wb_in_checkpoint_module(tmp_path):
    """parallel/checkpoint.py joined the atomic-write rule's module
    list: a bare open(..., 'wb') there is a torn-write hazard."""
    lint = _load_lint()
    d = tmp_path / "incubator_mxnet_tpu" / "parallel"
    d.mkdir(parents=True)
    f = d / "checkpoint.py"
    f.write_text("def save(p, b):\n"
                 "    with open(p, 'wb') as fh:\n"
                 "        fh.write(b)\n")
    assert any("bare open" in p for p in lint.check_file(f))


def test_lint_requires_fault_scope_documented(tmp_path,
                                              monkeypatch):
    """Every literal inject()/fault_for() scope must appear in the
    docs/resilience.md grammar (the new-fault-scope satellite)."""
    lint = _load_lint()
    monkeypatch.chdir(REPO)
    from pathlib import Path
    d = tmp_path / "incubator_mxnet_tpu"
    d.mkdir()
    f = d / "probe.py"
    f.write_text("from .resilience import inject\n"
                 "def go():\n"
                 "    inject('totally_new_scope', 'op')\n")
    probs = lint.check_fault_scopes([Path(f)])
    assert any("totally_new_scope" in p for p in probs), probs
    f.write_text("from .resilience import inject\n"
                 "def go(r):\n"
                 "    inject('elastic', 'rank%d' % r)\n")
    assert lint.check_fault_scopes([Path(f)]) == []
    # and the live tree is clean under the rule
    files = sorted(Path("incubator_mxnet_tpu").rglob("*.py")) \
        + sorted(Path("tools").rglob("*.py"))
    assert lint.check_fault_scopes(files) == []
