"""Keep the driver entry points working: dryrun_multichip must
compile+run the sharded training paths on the virtual CPU mesh."""
import sys
import pathlib

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def test_dryrun_multichip_8():
    import __graft_entry__ as g
    g.dryrun_multichip(8)


def test_entry_signature():
    import __graft_entry__ as g
    assert callable(g.entry)
    assert callable(g.dryrun_multichip)
