"""Mixture-of-Experts: top-2 routing op, Gluon MoEFFN, expert-parallel
training on the mesh (ops/moe.py, parallel/sharding.py ep rules).

A new-capability family per SURVEY §5's long-context/parallelism
mandate — designed against the mesh's 'ep' axis the way ring
attention is designed against 'sp'."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, nd, parallel
from incubator_mxnet_tpu.gluon.model_zoo.transformer import (
    MoEFFN, TransformerLM)
from incubator_mxnet_tpu.ops.moe import moe_ffn_fn, top2_gating


def _stacked(rs, e, h, d):
    return (jnp.asarray(rs.randn(e, h, d) * 0.3, jnp.float32),
            jnp.asarray(rs.randn(e, h) * 0.1, jnp.float32),
            jnp.asarray(rs.randn(e, d, h) * 0.3, jnp.float32),
            jnp.asarray(rs.randn(e, d) * 0.1, jnp.float32))


def test_top2_gating_invariants():
    rs = np.random.RandomState(0)
    # c = 2t guarantees no overflow (2nd choices queue behind ALL
    # 1st choices of their expert, so worst case needs 2t slots)
    t, e, c = 64, 4, 128
    logits = jnp.asarray(rs.randn(t, e), jnp.float32)
    combine, dispatch, aux = top2_gating(logits, c)
    assert combine.shape == dispatch.shape == (t, e, c)
    # each expert buffer slot holds at most one token
    assert float(jnp.max(jnp.sum(dispatch, axis=0))) <= 1.0 + 1e-6
    # a token occupies at most 2 slots (top-2), gates sum to <= 1
    per_tok = np.asarray(jnp.sum(dispatch, axis=(1, 2)))
    assert (per_tok <= 2 + 1e-6).all()
    gates = np.asarray(jnp.sum(combine, axis=(1, 2)))
    assert (gates <= 1 + 1e-5).all()
    # ample capacity: every token lands both choices
    assert (per_tok == 2).all()
    assert np.isfinite(float(aux)) and float(aux) > 0


def test_identical_experts_match_dense_ffn():
    """With renormalized top-2 gates and identical experts, MoE must
    equal the plain FFN for every non-dropped token."""
    rs = np.random.RandomState(1)
    t, d, e, h = 48, 8, 4, 16
    x = jnp.asarray(rs.randn(t, d), jnp.float32)
    router = jnp.asarray(rs.randn(e, d) * 0.1, jnp.float32)
    up1 = rs.randn(h, d).astype(np.float32) * 0.3
    ub1 = rs.randn(h).astype(np.float32) * 0.1
    dn1 = rs.randn(d, h).astype(np.float32) * 0.3
    db1 = rs.randn(d).astype(np.float32) * 0.1
    out, aux = moe_ffn_fn(
        x, router, jnp.asarray(np.stack([up1] * e)),
        jnp.asarray(np.stack([ub1] * e)),
        jnp.asarray(np.stack([dn1] * e)),
        jnp.asarray(np.stack([db1] * e)), capacity_factor=4.0)
    want = np.maximum(np.asarray(x) @ up1.T + ub1, 0) @ dn1.T + db1
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-4,
                               atol=1e-5)


def test_capacity_overflow_drops_tokens():
    rs = np.random.RandomState(2)
    t, d, e, h = 32, 8, 4, 8
    x = jnp.asarray(rs.randn(t, d), jnp.float32)
    router = jnp.asarray(rs.randn(e, d), jnp.float32)
    w = _stacked(rs, e, h, d)
    out, _ = moe_ffn_fn(x, router, *w, capacity_factor=0.01)
    dropped = int(np.sum(np.all(np.asarray(out) == 0, axis=-1)))
    assert dropped > 0              # overflow really drops
    out2, _ = moe_ffn_fn(x, router, *w, capacity_factor=8.0)
    assert int(np.sum(np.all(np.asarray(out2) == 0, axis=-1))) == 0


def test_moe_op_on_tape():
    """The _moe_ffn registry op records on the autograd tape and
    gradients flow to every expert parameter."""
    rs = np.random.RandomState(3)
    t, d, e, h = 16, 4, 2, 8
    x = nd.array(rs.randn(t, d).astype(np.float32))
    router = nd.array(rs.randn(e, d).astype(np.float32))
    up_w = nd.array((rs.randn(e, h, d) * 0.3).astype(np.float32))
    up_b = nd.array(np.zeros((e, h), np.float32))
    dn_w = nd.array((rs.randn(e, d, h) * 0.3).astype(np.float32))
    dn_b = nd.array(np.zeros((e, d), np.float32))
    for p in (router, up_w, dn_w):
        p.attach_grad()
    with autograd.record():
        y, aux = nd._internal._moe_ffn(x, router, up_w, up_b, dn_w,
                                       dn_b)
        loss = (y * y).sum() + 0.01 * aux
    loss.backward()
    for p in (router, up_w, dn_w):
        g = p.grad.asnumpy()
        assert np.isfinite(g).all() and np.abs(g).max() > 0


def test_moe_transformer_trains_on_ep_mesh():
    """TransformerLM(moe_experts=4) through ShardedTrainStep on a
    dp=4 x ep=2 mesh: expert weights sharded over 'ep', loss (incl.
    aux) decreases, and the result matches the SAME model trained
    ep=1 (expert parallelism must be a layout, not a semantics,
    change)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")

    def run(mesh):
        mx.random.seed(0)
        net = TransformerLM(vocab_size=64, d_model=32, n_layers=2,
                            n_heads=4, max_len=32, moe_experts=4)
        net.initialize(mx.initializer.Xavier())

        def lm_loss(outputs, labels):
            logits, aux = outputs
            lse = jax.nn.logsumexp(logits.astype(jnp.float32), -1)
            picked = jnp.take_along_axis(
                logits, labels[..., None], axis=-1)[..., 0]
            ce = jnp.mean(lse - picked.astype(jnp.float32))
            return ce + 0.01 * aux

        ex = nd.array(np.zeros((2, 32), np.int32))
        step = parallel.ShardedTrainStep(
            net, optimizer="adam",
            optimizer_params=dict(learning_rate=1e-3),
            loss_fn=lm_loss, example_args=[ex], mesh=mesh)
        rs = np.random.RandomState(0)
        toks = np.asarray(rs.randint(0, 64, (8, 32)), np.int32)
        labels = np.roll(toks, -1, axis=1)
        losses = [float(step(toks, labels)) for _ in range(6)]
        return losses

    l_ep = run(parallel.make_mesh(dp=4, ep=2))
    assert l_ep[-1] < l_ep[0], l_ep
    l_dp = run(parallel.make_mesh(dp=8))
    np.testing.assert_allclose(l_ep, l_dp, rtol=2e-4, atol=2e-4)


def test_moe_expert_shards_placed_on_ep_axis():
    """The ep rules actually shard the stacked expert dim."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mx.random.seed(0)
    net = TransformerLM(vocab_size=32, d_model=16, n_layers=1,
                        n_heads=2, max_len=16, moe_experts=4)
    net.initialize(mx.initializer.Xavier())
    ex = nd.array(np.zeros((2, 16), np.int32))
    step = parallel.ShardedTrainStep(
        net, optimizer="sgd", optimizer_params=dict(learning_rate=.1),
        loss_fn=lambda o, y: o[0].mean() + 0 * o[1],
        example_args=[ex], mesh=parallel.make_mesh(dp=2, ep=4))
    names = [n for n in step.params if "expert_up_weight" in n]
    assert names, list(step.params)[:8]
    arr = step.params[names[0]]
    # 4 experts over ep=4: each shard holds exactly one expert
    shard_shapes = {s.data.shape for s in arr.addressable_shards}
    assert all(s[0] == 1 for s in shard_shapes), shard_shapes


def test_moe_generate_matches_forward():
    """KV-cache decode runs the SAME routing code as training."""
    mx.random.seed(0)
    net = TransformerLM(vocab_size=64, d_model=32, n_layers=2,
                        n_heads=4, max_len=64, moe_experts=4)
    net.initialize(mx.initializer.Xavier())
    toks = nd.array(np.random.RandomState(0)
                    .randint(0, 64, (2, 16)).astype(np.int32))
    out = net.generate(toks, max_new_tokens=4)
    logits, _ = net(toks)
    nxt = logits.asnumpy()[:, -1].argmax(-1)
    assert (out.asnumpy()[:, 16] == nxt).all()


def test_partial_axis_mesh_gets_filtered_default_rules():
    """A hand-built Mesh defining only ('dp', 'ep') must not crash on
    the default rules' 'tp' specs — those fall back to replicated
    while the ep rules still apply (review regression)."""
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    from jax.sharding import Mesh
    devs = np.array(jax.devices()[:4]).reshape(2, 2)
    mesh = Mesh(devs, ("dp", "ep"))
    mx.random.seed(0)
    net = TransformerLM(vocab_size=32, d_model=16, n_layers=1,
                        n_heads=2, max_len=16, moe_experts=2)
    net.initialize(mx.initializer.Xavier())
    ex = nd.array(np.zeros((2, 16), np.int32))
    step = parallel.ShardedTrainStep(
        net, optimizer="sgd", optimizer_params=dict(learning_rate=.1),
        loss_fn=lambda o, y: o[0].mean() + 0 * o[1],
        example_args=[ex], mesh=mesh)
    name = [n for n in step.params if "expert_up_weight" in n][0]
    shard_shapes = {s.data.shape
                    for s in step.params[name].addressable_shards}
    assert all(s[0] == 1 for s in shard_shapes), shard_shapes
    # qkv (a 'tp' rule) replicated, not crashed
    qkv = [n for n in step.params if "dense0_weight" in n][0]
    full = step.params[qkv].shape
    assert {s.data.shape for s in
            step.params[qkv].addressable_shards} == {full}


def test_cumsum_dtype_is_accumulator_type():
    """numpy semantics: dtype upcasts BEFORE accumulation, so int8
    data summing past 127 must not wrap (review regression)."""
    x = nd.array(np.ones(200, np.int8))
    out = nd.cumsum(x, dtype="int32")
    assert out.asnumpy()[-1] == 200


def test_moe_bf16_compute_dtype():
    """MoE under ShardedTrainStep(compute_dtype=bfloat16): gating
    runs fp32 internally, the step stays finite and learns (the
    bench's hardware configuration)."""
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    mx.random.seed(0)
    net = TransformerLM(vocab_size=64, d_model=32, n_layers=2,
                        n_heads=4, max_len=16, moe_experts=4)
    net.initialize(mx.initializer.Xavier())

    def lm_loss(outputs, labels):
        logits, aux = outputs
        lse = jax.nn.logsumexp(logits.astype(jnp.float32), -1)
        picked = jnp.take_along_axis(
            logits, labels[..., None], axis=-1)[..., 0]
        return jnp.mean(lse - picked.astype(jnp.float32)) \
            + 0.01 * aux

    ex = nd.array(np.zeros((2, 16), np.int32))
    step = parallel.ShardedTrainStep(
        net, optimizer="adam",
        optimizer_params=dict(learning_rate=1e-3),
        loss_fn=lm_loss, example_args=[ex],
        mesh=parallel.make_mesh(dp=1, ep=2),
        compute_dtype=jnp.bfloat16)
    rs = np.random.RandomState(0)
    toks = np.asarray(rs.randint(0, 64, (4, 16)), np.int32)
    labels = np.roll(toks, -1, axis=1)
    losses = [float(step(toks, labels)) for _ in range(8)]
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], losses


def test_moe_sharded_checkpoint_roundtrip(tmp_path):
    """MoE expert-sharded state checkpoints and resumes across mesh
    shapes (orbax path): save on dp=4 x ep=2, load on dp=8."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")

    def build(mesh):
        mx.random.seed(0)
        # explicit prefix: checkpoint keys are parameter names, and
        # the gluon name counter is process-global
        net = TransformerLM(vocab_size=64, d_model=32, n_layers=2,
                            n_heads=4, max_len=16, moe_experts=4,
                            prefix="moeck_")
        net.initialize(mx.initializer.Xavier())
        ex = nd.array(np.zeros((2, 16), np.int32))
        return parallel.ShardedTrainStep(
            net, optimizer="adam",
            optimizer_params=dict(learning_rate=1e-3),
            loss_fn=lambda o, y: o[0].mean() + 0.01 * o[1],
            example_args=[ex], mesh=mesh)

    rs = np.random.RandomState(0)
    toks = np.asarray(rs.randint(0, 64, (8, 16)), np.int32)
    labels = np.roll(toks, -1, axis=1)

    step = build(parallel.make_mesh(dp=4, ep=2))
    for _ in range(3):
        l_before = float(step(toks, labels))
    ck = str(tmp_path / "ck")
    step.save_checkpoint(ck)

    step2 = build(parallel.make_mesh(dp=8))
    step2.load_checkpoint(ck)
    # the restored expert weights continue the same trajectory
    l_after = float(step2(toks, labels))
    step_ref = build(parallel.make_mesh(dp=4, ep=2))
    step_ref.load_checkpoint(ck)
    l_ref = float(step_ref(toks, labels))
    np.testing.assert_allclose(l_after, l_ref, rtol=2e-4)
    assert l_after < l_before + 0.1
