"""Sparse storage + kernels (model: reference
tests/python/unittest/test_sparse_ndarray.py / test_sparse_operator.py
and example/sparse/linear_classification.py — config 5).
Oracle: dense numpy."""
import os
import tempfile

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.ndarray import sparse


def _rand_csr(rs, shape, density=0.3):
    dense = rs.rand(*shape).astype(np.float32)
    dense[rs.rand(*shape) > density] = 0
    return dense, sparse.csr_matrix(dense)


def test_csr_roundtrip():
    rs = np.random.RandomState(0)
    dense, csr = _rand_csr(rs, (6, 9))
    assert csr.stype == "csr"
    np.testing.assert_allclose(csr.asnumpy(), dense)
    back = csr.tostype("default")
    np.testing.assert_allclose(back.asnumpy(), dense)
    csr2 = sparse.cast_storage(nd.array(dense), "csr")
    np.testing.assert_allclose(csr2.asnumpy(), dense)


def test_row_sparse_roundtrip():
    rs = np.random.RandomState(1)
    dense = np.zeros((8, 4), np.float32)
    rows = [1, 3, 6]
    dense[rows] = rs.rand(3, 4)
    rsp = sparse.row_sparse_array(dense)
    assert rsp.stype == "row_sparse"
    np.testing.assert_allclose(rsp.asnumpy(), dense)
    np.testing.assert_allclose(np.asarray(rsp.indices.asnumpy()),
                               rows)


def test_sparse_dot_csr_dense():
    rs = np.random.RandomState(2)
    dense, csr = _rand_csr(rs, (5, 7))
    w = rs.rand(7, 3).astype(np.float32)
    out = sparse.dot(csr, nd.array(w))
    np.testing.assert_allclose(out.asnumpy(), dense @ w, rtol=1e-5)


def test_sparse_dot_csr_T_dense():
    rs = np.random.RandomState(3)
    dense, csr = _rand_csr(rs, (5, 7))
    w = rs.rand(5, 3).astype(np.float32)
    out = sparse.dot(csr, nd.array(w), transpose_a=True)
    np.testing.assert_allclose(out.asnumpy(), dense.T @ w, rtol=1e-5)


def test_sparse_retain():
    rs = np.random.RandomState(4)
    dense = rs.rand(6, 3).astype(np.float32)
    rsp = sparse.row_sparse_array(dense)
    kept = sparse.retain(rsp, nd.array(np.array([1, 4])))
    want = np.zeros_like(dense)
    want[[1, 4]] = dense[[1, 4]]
    np.testing.assert_allclose(kept.asnumpy(), want)


def test_lazy_sgd_update():
    rs = np.random.RandomState(5)
    w = rs.rand(6, 3).astype(np.float32)
    g_dense = np.zeros_like(w)
    g_rows = [0, 2]
    g_dense[g_rows] = rs.rand(2, 3)
    weight = nd.array(w)
    grad = sparse.row_sparse_array(g_dense)
    sparse.sgd_update(weight, grad, lr=0.1, wd=0.01)
    want = w.copy()
    want[g_rows] = w[g_rows] - 0.1 * (g_dense[g_rows]
                                      + 0.01 * w[g_rows])
    np.testing.assert_allclose(weight.asnumpy(), want, rtol=1e-5)
    # untouched rows stay exactly (lazy semantics)
    np.testing.assert_array_equal(weight.asnumpy()[1], w[1])


def test_kvstore_row_sparse_pull():
    kv = mx.kvstore.create("local")
    w = np.arange(12, dtype=np.float32).reshape(4, 3)
    kv.init("emb", nd.array(w))
    out = nd.zeros((4, 3))
    kv.row_sparse_pull("emb", out=out,
                       row_ids=nd.array(np.array([1, 3])))
    want = np.zeros_like(w)
    want[[1, 3]] = w[[1, 3]]
    np.testing.assert_allclose(out.asnumpy(), want)


def test_libsvm_iter_and_linear_classification():
    """config-5 miniature: logistic regression on LibSVM CSR data."""
    rs = np.random.RandomState(6)
    dim, n = 16, 64
    true_w = rs.randn(dim).astype(np.float32)
    xs = (rs.rand(n, dim) * (rs.rand(n, dim) < 0.4)).astype(np.float32)
    ys = (xs @ true_w > 0).astype(np.float32)
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "train.libsvm")
        with open(path, "w") as f:
            for x, y in zip(xs, ys):
                toks = [f"{i}:{v}" for i, v in enumerate(x) if v != 0]
                f.write(f"{y} " + " ".join(toks) + "\n")
        it = mx.io.LibSVMIter(data_libsvm=path, data_shape=(dim,),
                              batch_size=16)
        batches = list(it)
        assert len(batches) == 4
        assert batches[0].data[0].stype == "csr"

        weight = nd.array(np.zeros((dim, 1), np.float32))
        losses = []
        for _ in range(30):
            it.reset()
            total = 0.0
            for b in batches:
                x_csr, y_b = b.data[0], b.label[0].asnumpy()
                logits = sparse.dot(x_csr, weight).asnumpy()[:, 0]
                p = 1 / (1 + np.exp(-logits))
                total += -np.mean(y_b * np.log(p + 1e-8) +
                                  (1 - y_b) * np.log(1 - p + 1e-8))
                gl = nd.array((p - y_b)[:, None] / len(y_b))
                g = sparse.dot(x_csr, gl, transpose_a=True)
                sparse.sgd_update(weight, g, lr=1.0)
            losses.append(total / len(batches))
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_libsvm_iter_pads_tail_batch():
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "t.libsvm")
        with open(path, "w") as f:
            for i in range(10):
                f.write(f"{i % 2} 0:1.0 {i % 4}:2.0\n")
        it = mx.io.LibSVMIter(data_libsvm=path, data_shape=(5,),
                              batch_size=4)
        batches = list(it)
        assert len(batches) == 3
        assert batches[-1].pad == 2
        assert batches[-1].data[0].shape == (4, 5)


def test_row_sparse_elemwise_add():
    rs = np.random.RandomState(7)
    a = np.zeros((6, 3), np.float32)
    b = np.zeros((6, 3), np.float32)
    a[[0, 2]] = rs.rand(2, 3)
    b[[2, 5]] = rs.rand(2, 3)
    out = sparse.elemwise_add(sparse.row_sparse_array(a),
                              sparse.row_sparse_array(b))
    assert out.stype == "row_sparse"
    np.testing.assert_allclose(out.asnumpy(), a + b, rtol=1e-6)
