"""ImageDetIter + detection augmenters (ref:
python/mxnet/image/detection.py ImageDetIter:624, DetAug* family)."""
import os
import tempfile

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import recordio as rio
from incubator_mxnet_tpu.image.detection import (
    DetHorizontalFlipAug, DetRandomCropAug, DetRandomPadAug,
    _parse_det_label)


def _make_det_rec(td, n=10):
    """Pack a synthetic detection dataset: colored boxes on noise."""
    rs = np.random.RandomState(0)
    prefix = os.path.join(td, "det")
    rec = rio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    for i in range(n):
        img = (rs.rand(60, 80, 3) * 255).astype(np.uint8)
        nobj = 1 + i % 3
        objs = []
        for j in range(nobj):
            x0, y0 = rs.uniform(0, 0.5, 2)
            objs += [float(j % 4), x0, y0, x0 + 0.3, y0 + 0.3]
        label = [2.0, 5.0] + objs          # header_width=2, obj_w=5
        header = rio.IRHeader(0, label, i, 0)
        rec.write_idx(i, rio.pack_img(header, img, quality=90))
    rec.close()
    return prefix


def test_parse_det_label():
    raw = [2, 5, 1, 0.1, 0.2, 0.4, 0.5]
    objs = _parse_det_label(raw)
    assert objs.shape == (1, 5)
    np.testing.assert_allclose(objs[0], [1, 0.1, 0.2, 0.4, 0.5])
    with pytest.raises(ValueError):
        _parse_det_label([2, 5, 1, 0.1])   # body not divisible


def test_image_det_iter_batches():
    with tempfile.TemporaryDirectory() as td:
        prefix = _make_det_rec(td)
        it = mx.image.ImageDetIter(
            batch_size=4, data_shape=(3, 32, 32),
            path_imgrec=prefix + ".rec", shuffle=True)
        total = 0
        for batch in it:
            data, label = batch.data[0], batch.label[0]
            assert data.shape == (4, 3, 32, 32)
            assert label.shape[0] == 4 and label.shape[2] == 5
            lab = label.asnumpy()
            valid = lab[lab[:, :, 0] >= 0]
            assert valid.size > 0
            assert (valid[:, 1:] >= 0).all() and \
                (valid[:, 1:] <= 1).all()
            total += 4 - batch.pad
        assert total == 10
        it.reset()
        assert sum(4 - b.pad for b in it) == 10


def test_det_flip_moves_boxes():
    img = np.zeros((10, 20, 3), np.uint8)
    label = np.array([[0, 0.1, 0.2, 0.3, 0.6]], np.float32)
    aug = DetHorizontalFlipAug(p=1.1)     # always flip
    _, flipped = aug(img, label)
    np.testing.assert_allclose(flipped[0], [0, 0.7, 0.2, 0.9, 0.6],
                               rtol=1e-6)


def test_det_crop_keeps_normalized_boxes():
    rs = np.random.RandomState(0)
    img = (rs.rand(64, 64, 3) * 255).astype(np.uint8)
    label = np.array([[1, 0.3, 0.3, 0.7, 0.7]], np.float32)
    aug = DetRandomCropAug(min_object_covered=0.5,
                           area_range=(0.5, 1.0))
    for _ in range(10):
        im2, lab2 = aug(img, label)
        assert lab2.shape[1] == 5
        assert (lab2[:, 1:] >= 0).all() and (lab2[:, 1:] <= 1).all()
        assert im2.shape[0] >= 1 and im2.shape[1] >= 1


def test_det_pad_shrinks_boxes():
    img = np.full((10, 10, 3), 255, np.uint8)
    label = np.array([[0, 0.0, 0.0, 1.0, 1.0]], np.float32)
    aug = DetRandomPadAug(area_range=(4.0, 4.0), p=1.1)
    im2, lab2 = aug(img, label)
    assert im2.shape[0] == 20 and im2.shape[1] == 20
    w = lab2[0, 3] - lab2[0, 1]
    np.testing.assert_allclose(w, 0.5, rtol=1e-6)


def test_det_iter_mixed_obj_width_raises():
    with tempfile.TemporaryDirectory() as td:
        rs = np.random.RandomState(0)
        prefix = os.path.join(td, "mix")
        rec = rio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec",
                                    "w")
        img = (rs.rand(16, 16, 3) * 255).astype(np.uint8)
        rec.write_idx(0, rio.pack_img(
            rio.IRHeader(0, [2, 5, 0, .1, .1, .4, .4], 0, 0), img))
        rec.write_idx(1, rio.pack_img(
            rio.IRHeader(0, [2, 6, 0, .1, .1, .4, .4, 1.0], 1, 0),
            img))
        rec.close()
        it = mx.image.ImageDetIter(batch_size=2,
                                   data_shape=(3, 16, 16),
                                   path_imgrec=prefix + ".rec")
        with pytest.raises(ValueError, match="uniform"):
            next(iter(it))
