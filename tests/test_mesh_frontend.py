"""Mesh-aware frontends: Module.fit and Gluon Trainer on the 8-device
virtual CPU mesh (VERDICT r2 tasks 2/3).

Oracle = the single-device eager paths of the same frontends: the
compiled kvstore='tpu' step must reproduce them numerically (the
reference validates DataParallelExecutorGroup the same way — multi-
vs single-device consistency, ref: tests/python/unittest/
test_module.py test_module_states and test_multi_device_exec.py).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd
from incubator_mxnet_tpu.parallel import make_mesh, shard_batch


def _toy_data(n=512, d=20, k=10, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.rand(n, d).astype(np.float32)
    w = rs.rand(d, k).astype(np.float32)
    y = (x @ w).argmax(1).astype(np.float32)
    return x, y


def _mlp_symbol():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc1", num_hidden=64)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=10)
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _fit(kvstore, x, y, optimizer="sgd",
         optimizer_params=None, num_epoch=3):
    it = mx.io.NDArrayIter(x, y, batch_size=64, shuffle=False,
                           label_name="softmax_label")
    mx.random.seed(0)
    mod = mx.mod.Module(_mlp_symbol(), context=mx.cpu())
    mod.fit(it, num_epoch=num_epoch, kvstore=kvstore,
            optimizer=optimizer,
            optimizer_params=optimizer_params
            or dict(learning_rate=0.5, momentum=0.9, wd=1e-4),
            initializer=mx.initializer.Xavier(
                rnd_type="uniform", factor_type="avg", magnitude=3))
    acc = dict(mod.score(it, "acc"))["accuracy"]
    arg, aux = mod.get_params()
    return acc, arg, mod


def test_module_fit_tpu_kvstore_matches_local():
    x, y = _toy_data()
    acc_l, p_l, _ = _fit("local", x, y)
    acc_t, p_t, mod = _fit("tpu", x, y)
    assert mod._mesh_step is not None  # actually took the mesh path
    assert acc_t > 0.8
    assert abs(acc_l - acc_t) < 1e-6
    for n in p_l:
        np.testing.assert_allclose(p_l[n].asnumpy(), p_t[n].asnumpy(),
                                   rtol=2e-4, atol=2e-5)


def test_module_tpu_kvstore_adam_and_checkpoint(tmp_path):
    x, y = _toy_data()
    acc, _, mod = _fit("tpu", x, y, optimizer="adam",
                       optimizer_params=dict(learning_rate=0.01))
    assert acc > 0.8
    mod.save_checkpoint(str(tmp_path / "m"), 0,
                        save_optimizer_states=True)
    mod2 = mx.mod.Module.load(str(tmp_path / "m"), 0)
    it = mx.io.NDArrayIter(x, y, batch_size=64,
                           label_name="softmax_label")
    mod2.bind(data_shapes=it.provide_data,
              label_shapes=it.provide_label)
    acc2 = dict(mod2.score(it, "acc"))["accuracy"]
    assert abs(acc - acc2) < 1e-6


def test_module_tpu_kvstore_rejects_exotic_optimizer():
    x, y = _toy_data(n=64)
    with pytest.raises(ValueError, match="sgd/nag/adam"):
        _fit("tpu", x, y, optimizer="rmsprop",
             optimizer_params=dict(learning_rate=0.01))


def _train_gluon(force_eager=False, shard=False, steps=15):
    rs = np.random.RandomState(0)
    X = rs.rand(64, 12).astype(np.float32)
    Y = rs.randint(0, 5, (64,)).astype(np.float32)
    mx.random.seed(0)
    net = mx.gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(mx.gluon.nn.Dense(32, activation="relu"),
                mx.gluon.nn.Dense(5))
    net.initialize(mx.initializer.Xavier())
    net(mx.nd.array(X[:2]))  # settle shapes
    tr = mx.gluon.Trainer(
        net.collect_params(), "sgd",
        dict(learning_rate=0.2, momentum=0.9, wd=1e-3),
        kvstore="tpu" if shard else "device")
    if force_eager:
        tr._init_kvstore()
        tr._fused_update = False
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    mesh = make_mesh()
    losses = []
    for _ in range(steps):
        xb, yb = mx.nd.array(X), mx.nd.array(Y)
        if shard:
            xb = mx.nd.NDArray(jax.device_put(
                xb._data, shard_batch(mesh, 2)))
            yb = mx.nd.NDArray(jax.device_put(
                yb._data, shard_batch(mesh, 1)))
        with autograd.record():
            loss = loss_fn(net(xb), yb)
        loss.backward()
        tr.step(X.shape[0])
        losses.append(float(loss.mean().asnumpy()))
    params = [p.data().asnumpy()
              for _, p in sorted(net.collect_params().items())]
    return losses, params, tr


def test_trainer_fused_matches_eager_updater():
    l_e, p_e, tr_e = _train_gluon(force_eager=True)
    l_f, p_f, tr_f = _train_gluon()
    assert tr_f._fused_update is not False and \
        tr_f._fused_update is not None
    assert l_f[-1] < l_f[0]
    np.testing.assert_allclose(l_e[-1], l_f[-1], rtol=1e-5)
    for a, b in zip(p_e, p_f):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)


def test_trainer_tpu_mesh_matches_single_device():
    l_e, p_e, _ = _train_gluon(force_eager=True)
    l_m, p_m, tr = _train_gluon(shard=True)
    # params really are replicated over the 8-device mesh
    some = tr._params[0].data()._data
    assert len(some.sharding.device_set) == 8
    np.testing.assert_allclose(l_e[-1], l_m[-1], rtol=1e-4)
    for a, b in zip(p_e, p_m):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


def test_trainer_fused_lr_schedule_no_recompile():
    """lr is a traced scalar: changing it must not recompile."""
    _, _, tr = _train_gluon(steps=2)
    tr.set_learning_rate(0.01)
    # one more step at the new lr works and changes params
    before = [p.data().asnumpy().copy() for p in tr._params]
    rs = np.random.RandomState(1)
    X = rs.rand(64, 12).astype(np.float32)
    Y = rs.randint(0, 5, (64,)).astype(np.float32)
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    net_params = tr._params
    with autograd.record():
        h = mx.nd.dot(mx.nd.array(X), net_params[0].data().T) \
            + net_params[1].data()
        h = mx.nd.relu(h)
        out = mx.nd.dot(h, net_params[2].data().T) + net_params[3].data()
        loss = loss_fn(out, mx.nd.array(Y))
    loss.backward()
    tr.step(64)
    after = [p.data().asnumpy() for p in tr._params]
    assert any(not np.allclose(a, b) for a, b in zip(before, after))


def test_module_manual_loop_tpu_kvstore_updates_params():
    """forward/backward/update manual loop must not silently no-op
    under kvstore='tpu' (round-3 review regression)."""
    x, y = _toy_data(n=64)
    it = mx.io.NDArrayIter(x, y, batch_size=64,
                           label_name="softmax_label")
    mx.random.seed(0)
    mod = mx.mod.Module(_mlp_symbol(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data,
             label_shapes=it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(kvstore="tpu", optimizer="sgd",
                       optimizer_params=dict(learning_rate=0.5))
    before, _ = mod.get_params()
    before = {n: v.asnumpy().copy() for n, v in before.items()}
    batch = next(iter(it))
    mod.forward(batch, is_train=True)
    mod.backward()
    mod.update()
    after, _ = mod.get_params()
    assert any(not np.allclose(before[n], after[n].asnumpy())
               for n in before)
    # and the fused path still works afterwards (stale-refresh)
    mod.forward_backward(batch)
    mod.update()
    after2, _ = mod.get_params()
    assert any(not np.allclose(after[n].asnumpy(),
                               after2[n].asnumpy()) for n in after)


def test_trainer_stale_grad_keeps_momentum_consistent():
    """A stale-grad step must leave the skipped parameter's weight AND
    momentum untouched, staying equivalent to the eager updater."""
    def run(force_eager):
        rs = np.random.RandomState(0)
        X = rs.rand(16, 6).astype(np.float32)
        mx.random.seed(0)
        a = mx.gluon.nn.Dense(4, in_units=6)
        b = mx.gluon.nn.Dense(4, in_units=6)
        a.initialize(mx.initializer.Xavier())
        b.initialize(mx.initializer.Xavier())
        params = dict(list(a.collect_params().items())
                      + list(b.collect_params().items()))
        tr = mx.gluon.Trainer(params, "sgd",
                              dict(learning_rate=0.1, momentum=0.9))
        if force_eager:
            tr._init_kvstore()
            tr._fused_update = False
        for i in range(4):
            use_b = i != 1  # step 1: b's grads are stale
            with autograd.record():
                out = a(mx.nd.array(X))
                if use_b:
                    out = out + b(mx.nd.array(X))
                loss = (out * out).mean()
            loss.backward()
            if not use_b:
                for p in b.collect_params().values():
                    p._grad = None
            tr.step(1, ignore_stale_grad=True)
        return [p.data().asnumpy() for _, p in sorted(params.items())]

    for pe, pf in zip(run(True), run(False)):
        np.testing.assert_allclose(pe, pf, rtol=2e-5, atol=2e-6)
