"""General C API (ref role: the core NDArray + imperative-invoke +
KVStore subset of include/mxnet/c_api.h's 162 functions —
MXNDArrayCreate c_api.cc:174, MXImperativeInvoke
c_api_ndarray.cc:131, MXKVStoreCreate c_api.cc:744).

The headline test compiles a REAL C program against mxtpu_c_api.h:
the client builds tensors, invokes registry operators, and drives
KVStore with a store-side optimizer — zero Python in the client
code."""
import ctypes
import os
import subprocess

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(REPO, "src", "c_api")
SO = os.path.join(SRC, "libmxtpu_capi.so")


def _build_lib():
    if not os.path.exists(SO):
        subprocess.run(["make", "-C", SRC], check=True,
                       capture_output=True, timeout=300)
    return SO


def _bind(lib):
    u, sz = ctypes.c_uint, ctypes.c_size_t
    vp = ctypes.c_void_p
    lib.MXTPUCApiGetLastError.restype = ctypes.c_char_p
    lib.MXNDArrayCreate.argtypes = [
        ctypes.POINTER(u), u, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.POINTER(vp)]
    lib.MXNDArrayGetSize.argtypes = [vp, ctypes.POINTER(sz),
                                     ctypes.POINTER(sz)]
    lib.MXNDArraySyncCopyFromCPU.argtypes = [vp, ctypes.c_void_p, sz]
    lib.MXNDArraySyncCopyToCPU.argtypes = [vp, ctypes.c_void_p, sz]
    lib.MXNDArrayGetShape.argtypes = [
        vp, ctypes.POINTER(u), ctypes.POINTER(ctypes.POINTER(u))]
    lib.MXNDArrayGetDType.argtypes = [vp, ctypes.POINTER(ctypes.c_int)]
    lib.MXNDArrayWaitToRead.argtypes = [vp]
    lib.MXNDArrayFree.argtypes = [vp]
    lib.MXListAllOpNames.argtypes = [
        ctypes.POINTER(u),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_char_p))]
    lib.MXImperativeInvoke.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.POINTER(vp),
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(vp),
        ctypes.c_int, ctypes.POINTER(ctypes.c_char_p),
        ctypes.POINTER(ctypes.c_char_p)]
    lib.MXKVStoreCreate.argtypes = [ctypes.c_char_p,
                                    ctypes.POINTER(vp)]
    lib.MXKVStoreFree.argtypes = [vp]
    lib.MXKVStoreInitEx.argtypes = [vp, u,
                                    ctypes.POINTER(ctypes.c_char_p),
                                    ctypes.POINTER(vp)]
    lib.MXKVStorePushEx.argtypes = [vp, u,
                                    ctypes.POINTER(ctypes.c_char_p),
                                    ctypes.POINTER(vp), ctypes.c_int]
    lib.MXKVStorePullEx.argtypes = [vp, u,
                                    ctypes.POINTER(ctypes.c_char_p),
                                    ctypes.POINTER(vp), ctypes.c_int]
    lib.MXKVStoreSetOptimizer.argtypes = [vp, ctypes.c_char_p,
                                          ctypes.c_float]
    return lib


def _nd_from_np(lib, arr):
    shape = (ctypes.c_uint * arr.ndim)(*arr.shape)
    h = ctypes.c_void_p()
    assert lib.MXNDArrayCreate(shape, arr.ndim, 0, 1, 0,
                               ctypes.byref(h)) == 0, \
        lib.MXTPUCApiGetLastError()
    flat = np.ascontiguousarray(arr, np.float32).ravel()
    assert lib.MXNDArraySyncCopyFromCPU(
        h, flat.ctypes.data_as(ctypes.c_void_p), flat.size) == 0, \
        lib.MXTPUCApiGetLastError()
    return h


def _np_from_nd(lib, h):
    ndim, pdata = ctypes.c_uint(), ctypes.POINTER(ctypes.c_uint)()
    assert lib.MXNDArrayGetShape(h, ctypes.byref(ndim),
                                 ctypes.byref(pdata)) == 0
    shape = tuple(pdata[i] for i in range(ndim.value))
    out = np.empty(int(np.prod(shape)) if shape else 1, np.float32)
    assert lib.MXNDArraySyncCopyToCPU(
        h, out.ctypes.data_as(ctypes.c_void_p), out.size) == 0, \
        lib.MXTPUCApiGetLastError()
    return out.reshape(shape)


def test_ndarray_create_copy_shape_dtype():
    lib = _bind(ctypes.CDLL(_build_lib()))
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    h = _nd_from_np(lib, x)
    size, item = ctypes.c_size_t(), ctypes.c_size_t()
    assert lib.MXNDArrayGetSize(h, ctypes.byref(size),
                                ctypes.byref(item)) == 0
    assert (size.value, item.value) == (12, 4)
    dt = ctypes.c_int()
    assert lib.MXNDArrayGetDType(h, ctypes.byref(dt)) == 0
    assert dt.value == 0          # float32
    assert lib.MXNDArrayWaitToRead(h) == 0
    np.testing.assert_array_equal(_np_from_nd(lib, h), x)
    # size mismatch is a clean error, not a crash
    bad = np.zeros(5, np.float32)
    assert lib.MXNDArraySyncCopyFromCPU(
        h, bad.ctypes.data_as(ctypes.c_void_p), bad.size) == -1
    assert b"mismatch" in lib.MXTPUCApiGetLastError()
    lib.MXNDArrayFree(h)


def test_imperative_invoke_ops():
    lib = _bind(ctypes.CDLL(_build_lib()))
    rs = np.random.RandomState(0)
    a = rs.rand(4, 5).astype(np.float32)
    b = rs.rand(5, 3).astype(np.float32)
    ha, hb = _nd_from_np(lib, a), _nd_from_np(lib, b)

    ins = (ctypes.c_void_p * 2)(ha, hb)
    outs = (ctypes.c_void_p * 4)()
    n_out = ctypes.c_int(4)
    assert lib.MXImperativeInvoke(b"dot", 2, ins,
                                  ctypes.byref(n_out), outs, 0,
                                  None, None) == 0, \
        lib.MXTPUCApiGetLastError()
    assert n_out.value == 1
    np.testing.assert_allclose(_np_from_nd(lib, outs[0]), a @ b,
                               rtol=1e-5)
    lib.MXNDArrayFree(outs[0])

    # keyword parameters travel as literal strings
    ins1 = (ctypes.c_void_p * 1)(ha)
    keys = (ctypes.c_char_p * 1)(b"axis")
    vals = (ctypes.c_char_p * 1)(b"1")
    n_out.value = 4
    assert lib.MXImperativeInvoke(b"sum", 1, ins1,
                                  ctypes.byref(n_out), outs, 1,
                                  keys, vals) == 0, \
        lib.MXTPUCApiGetLastError()
    np.testing.assert_allclose(_np_from_nd(lib, outs[0]),
                               a.sum(axis=1), rtol=1e-5)
    lib.MXNDArrayFree(outs[0])

    # unknown op: clean error
    n_out.value = 4
    assert lib.MXImperativeInvoke(b"no_such_op", 1, ins1,
                                  ctypes.byref(n_out), outs, 0,
                                  None, None) == -1
    assert b"no_such_op" in lib.MXTPUCApiGetLastError()
    lib.MXNDArrayFree(ha)
    lib.MXNDArrayFree(hb)


def test_list_all_op_names():
    lib = _bind(ctypes.CDLL(_build_lib()))
    n, arr = ctypes.c_uint(), ctypes.POINTER(ctypes.c_char_p)()
    assert lib.MXListAllOpNames(ctypes.byref(n),
                                ctypes.byref(arr)) == 0
    names = {arr[i].decode() for i in range(n.value)}
    assert n.value > 250
    for must in ("dot", "Convolution", "FullyConnected", "relu",
                 "BatchNorm", "adam_update"):
        assert must in names, must


def test_kvstore_round_trip_with_optimizer():
    lib = _bind(ctypes.CDLL(_build_lib()))
    w = np.ones((4, 2), np.float32) * 2.0
    g = np.full((4, 2), 0.5, np.float32)
    hw, hg = _nd_from_np(lib, w), _nd_from_np(lib, g)

    kv = ctypes.c_void_p()
    assert lib.MXKVStoreCreate(b"local", ctypes.byref(kv)) == 0
    keys = (ctypes.c_char_p * 1)(b"w")
    vals = (ctypes.c_void_p * 1)(hw)
    assert lib.MXKVStoreInitEx(kv, 1, keys, vals) == 0, \
        lib.MXTPUCApiGetLastError()
    assert lib.MXKVStoreSetOptimizer(kv, b"sgd",
                                     ctypes.c_float(0.1)) == 0
    grads = (ctypes.c_void_p * 1)(hg)
    assert lib.MXKVStorePushEx(kv, 1, keys, grads, 0) == 0, \
        lib.MXTPUCApiGetLastError()
    out_h = _nd_from_np(lib, np.zeros((4, 2), np.float32))
    outs = (ctypes.c_void_p * 1)(out_h)
    assert lib.MXKVStorePullEx(kv, 1, keys, outs, 0) == 0, \
        lib.MXTPUCApiGetLastError()
    np.testing.assert_allclose(_np_from_nd(lib, out_h),
                               w - 0.1 * g, rtol=1e-5)
    for h in (hw, hg, out_h):
        lib.MXNDArrayFree(h)
    lib.MXKVStoreFree(kv)


DEMO_C = r"""
/* Standalone C client for the general C API: composes a two-layer
 * computation from registry ops and runs one SGD round through
 * KVStore — no Python anywhere in this file. */
#include <stdio.h>
#include <stdlib.h>
#include "mxtpu_c_api.h"

static NDArrayHandle from_data(const float *vals, const mx_uint *shape,
                               mx_uint ndim, size_t n) {
    NDArrayHandle h;
    if (MXNDArrayCreate(shape, ndim, MXTPU_DTYPE_FLOAT32,
                        MXTPU_DEV_CPU, 0, &h) != 0 ||
        MXNDArraySyncCopyFromCPU(h, vals, n) != 0) {
        fprintf(stderr, "create: %s\n", MXTPUCApiGetLastError());
        exit(1);
    }
    return h;
}

int main(void) {
    /* x (2,3) @ w (3,2) -> relu -> sum -> scalar */
    float xv[6] = {1, -2, 3, -4, 5, -6};
    float wv[6] = {0.5, -0.5, 1.0, 1.0, -1.0, 0.25};
    mx_uint xs[2] = {2, 3}, ws[2] = {3, 2};
    NDArrayHandle x = from_data(xv, xs, 2, 6);
    NDArrayHandle w = from_data(wv, ws, 2, 6);

    NDArrayHandle ins[2]; NDArrayHandle outs[4]; int n_out = 4;
    ins[0] = x; ins[1] = w;
    if (MXImperativeInvoke("dot", 2, ins, &n_out, outs, 0,
                           NULL, NULL) != 0) {
        fprintf(stderr, "dot: %s\n", MXTPUCApiGetLastError());
        return 1;
    }
    NDArrayHandle xw = outs[0];
    n_out = 4;
    if (MXImperativeInvoke("relu", 1, &xw, &n_out, outs, 0,
                           NULL, NULL) != 0) {
        fprintf(stderr, "relu: %s\n", MXTPUCApiGetLastError());
        return 1;
    }
    NDArrayHandle r = outs[0];
    n_out = 4;
    if (MXImperativeInvoke("sum", 1, &r, &n_out, outs, 0,
                           NULL, NULL) != 0) {
        fprintf(stderr, "sum: %s\n", MXTPUCApiGetLastError());
        return 1;
    }
    float total;
    if (MXNDArraySyncCopyToCPU(outs[0], &total, 1) != 0) {
        fprintf(stderr, "copy: %s\n", MXTPUCApiGetLastError());
        return 1;
    }
    printf("SUM %.6f\n", total);

    /* one KVStore SGD round on the weight */
    KVStoreHandle kv;
    if (MXKVStoreCreate("local", &kv) != 0 ||
        MXKVStoreSetOptimizer(kv, "sgd", 0.5f) != 0) {
        fprintf(stderr, "kv: %s\n", MXTPUCApiGetLastError());
        return 1;
    }
    const char *keys[1] = {"w"};
    NDArrayHandle vals[1] = {w};
    /* init BEFORE the optimizer sees a push */
    if (MXKVStoreInitEx(kv, 1, keys, vals) != 0) {
        fprintf(stderr, "init: %s\n", MXTPUCApiGetLastError());
        return 1;
    }
    float gv[6] = {1, 1, 1, 1, 1, 1};
    NDArrayHandle grad = from_data(gv, ws, 2, 6);
    NDArrayHandle g1[1]; g1[0] = grad;
    if (MXKVStorePushEx(kv, 1, keys, g1, 0) != 0) {
        fprintf(stderr, "push: %s\n", MXTPUCApiGetLastError());
        return 1;
    }
    NDArrayHandle wout = from_data(gv, ws, 2, 6);  /* scratch */
    NDArrayHandle o1[1]; o1[0] = wout;
    if (MXKVStorePullEx(kv, 1, keys, o1, 0) != 0) {
        fprintf(stderr, "pull: %s\n", MXTPUCApiGetLastError());
        return 1;
    }
    float wnew[6];
    MXNDArraySyncCopyToCPU(wout, wnew, 6);
    for (int i = 0; i < 6; ++i) printf("W %.6f\n", wnew[i]);
    MXKVStoreFree(kv);
    MXNDArrayWaitAll();
    return 0;
}
"""


def test_c_api_standalone_client(tmp_path):
    """Compile a real C program against mxtpu_c_api.h and run it with
    a fresh embedded interpreter: tensors, ops, and KVStore all
    driven from C."""
    _build_lib()
    demo_c = tmp_path / "demo.c"
    demo_c.write_text(DEMO_C)
    demo = str(tmp_path / "demo")
    subprocess.run(
        ["gcc", "-O2", "-I", SRC, str(demo_c), "-o", demo,
         "-L", SRC, f"-Wl,-rpath,{SRC}", "-lmxtpu_capi"],
        check=True, capture_output=True, timeout=120)

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["MXTPU_FORCE_CPU"] = "1"
    env.pop("XLA_FLAGS", None)
    r = subprocess.run([demo], capture_output=True, text=True,
                       timeout=600, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = r.stdout.strip().splitlines()

    # oracle in numpy
    x = np.array([[1, -2, 3], [-4, 5, -6]], np.float32)
    w = np.array([[0.5, -0.5], [1.0, 1.0], [-1.0, 0.25]], np.float32)
    want_sum = np.maximum(x @ w, 0).sum()
    got_sum = float(lines[0].split()[1])
    assert abs(got_sum - want_sum) < 1e-4, (got_sum, want_sum)
    got_w = np.array([float(l.split()[1]) for l in lines[1:7]],
                     np.float32).reshape(3, 2)
    np.testing.assert_allclose(got_w, w - 0.5, rtol=1e-5)


def test_invoke_rejects_non_registry_attributes():
    """MXImperativeInvoke's contract is the op registry (what
    MXListAllOpNames reports) — module helpers on the nd namespace
    like 'array'/'waitall' must be rejected, not called."""
    lib = _bind(ctypes.CDLL(_build_lib()))
    x = _nd_from_np(lib, np.zeros((2, 2), np.float32))
    ins = (ctypes.c_void_p * 1)(x)
    outs = (ctypes.c_void_p * 4)()
    for name in (b"array", b"waitall", b"zeros"):
        n_out = ctypes.c_int(4)
        assert lib.MXImperativeInvoke(name, 1, ins,
                                      ctypes.byref(n_out), outs, 0,
                                      None, None) == -1, name
        assert b"unknown operator" in lib.MXTPUCApiGetLastError()
    lib.MXNDArrayFree(x)


def test_slice_reshape_save_load(tmp_path):
    """Slice/reshape views and the tagged .params save/load round
    trip — the SAME file format Python's nd.save/nd.load uses, so C
    and Python clients interoperate on artifacts."""
    lib = _bind(ctypes.CDLL(_build_lib()))
    lib.MXNDArraySlice.argtypes = [
        ctypes.c_void_p, ctypes.c_uint, ctypes.c_uint,
        ctypes.POINTER(ctypes.c_void_p)]
    lib.MXNDArrayReshape.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_void_p)]
    lib.MXNDArraySave.argtypes = [
        ctypes.c_char_p, ctypes.c_uint,
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_char_p)]
    lib.MXNDArrayLoad.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint),
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_char_p))]

    x = np.arange(24, dtype=np.float32).reshape(6, 4)
    h = _nd_from_np(lib, x)

    s = ctypes.c_void_p()
    assert lib.MXNDArraySlice(h, 1, 4, ctypes.byref(s)) == 0, \
        lib.MXTPUCApiGetLastError()
    np.testing.assert_array_equal(_np_from_nd(lib, s), x[1:4])

    r = ctypes.c_void_p()
    dims = (ctypes.c_int * 2)(8, -1)
    assert lib.MXNDArrayReshape(h, 2, dims, ctypes.byref(r)) == 0, \
        lib.MXTPUCApiGetLastError()
    np.testing.assert_array_equal(_np_from_nd(lib, r),
                                  x.reshape(8, 3))

    # save from C, load from C
    fname = str(tmp_path / "params.params").encode()
    keys = (ctypes.c_char_p * 2)(b"arg:weight", b"aux:mean")
    handles = (ctypes.c_void_p * 2)(h, s)
    assert lib.MXNDArraySave(fname, 2, handles, keys) == 0, \
        lib.MXTPUCApiGetLastError()
    n = ctypes.c_uint(8)
    loaded = (ctypes.c_void_p * 8)()
    names = ctypes.POINTER(ctypes.c_char_p)()
    assert lib.MXNDArrayLoad(fname, ctypes.byref(n), loaded,
                             ctypes.byref(names)) == 0, \
        lib.MXTPUCApiGetLastError()
    assert n.value == 2
    got = {names[i].decode(): _np_from_nd(lib, loaded[i])
           for i in range(2)}
    np.testing.assert_array_equal(got["arg:weight"], x)
    np.testing.assert_array_equal(got["aux:mean"], x[1:4])

    # and load the C-written file from PYTHON (interop proof)
    import sys as _sys
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    code = (
        "import os\n"
        "os.environ['XLA_FLAGS']='--xla_force_host_platform_device_count=1'\n"
        "import jax; jax.config.update('jax_platforms','cpu')\n"
        "import numpy as np\n"
        "from incubator_mxnet_tpu import nd\n"
        f"d = nd.load({fname.decode()!r})\n"
        "assert sorted(d) == ['arg:weight', 'aux:mean'], d\n"
        "assert d['arg:weight'].shape == (6, 4)\n"
        "print('PY_LOAD_OK')\n")
    rr = subprocess.run([_sys.executable, "-c", code],
                        capture_output=True, text=True, timeout=300,
                        env=env)
    assert rr.returncode == 0, rr.stderr[-1000:]
    assert "PY_LOAD_OK" in rr.stdout
    for hh in (h, s, r, loaded[0], loaded[1]):
        lib.MXNDArrayFree(hh)


def test_slice_save_error_contracts(tmp_path):
    """Out-of-range slices error (no silent clamp), duplicate save
    keys error (no silent drop), and a too-small Load buffer reports
    the required capacity through *num."""
    lib = _bind(ctypes.CDLL(_build_lib()))
    lib.MXNDArraySlice.argtypes = [
        ctypes.c_void_p, ctypes.c_uint, ctypes.c_uint,
        ctypes.POINTER(ctypes.c_void_p)]
    lib.MXNDArraySave.argtypes = [
        ctypes.c_char_p, ctypes.c_uint,
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_char_p)]
    lib.MXNDArrayLoad.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint),
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_char_p))]

    h = _nd_from_np(lib, np.zeros((6, 2), np.float32))
    s = ctypes.c_void_p()
    assert lib.MXNDArraySlice(h, 4, 100, ctypes.byref(s)) == -1
    assert b"out of range" in lib.MXTPUCApiGetLastError()
    assert lib.MXNDArraySlice(h, 3, 3, ctypes.byref(s)) == -1

    fname = str(tmp_path / "dup.params").encode()
    keys = (ctypes.c_char_p * 2)(b"w", b"w")
    handles = (ctypes.c_void_p * 2)(h, h)
    assert lib.MXNDArraySave(fname, 2, handles, keys) == -1
    assert b"duplicate" in lib.MXTPUCApiGetLastError()

    ok_keys = (ctypes.c_char_p * 2)(b"a", b"b")
    assert lib.MXNDArraySave(fname, 2, handles, ok_keys) == 0
    n = ctypes.c_uint(0)          # query mode: too-small on purpose
    loaded = (ctypes.c_void_p * 1)()
    names = ctypes.POINTER(ctypes.c_char_p)()
    assert lib.MXNDArrayLoad(fname, ctypes.byref(n), loaded,
                             ctypes.byref(names)) == -1
    assert n.value == 2           # required capacity reported
    lib.MXNDArrayFree(h)


SYMBOL_DEMO_C = r"""
/* Compose an MLP from C, infer shapes, serialize, and train it
 * through the C train ABI — model BUILT and TRAINED natively. */
#include <stdio.h>
#include <string.h>
#include "mxtpu_c_api.h"
#include "c_train_api.h"

static SymbolHandle op1(const char *op, SymbolHandle in,
                        const char *name, const char *k,
                        const char *v) {
    SymbolHandle out; SymbolHandle ins[1] = {in};
    const char *ks[1]; const char *vs[1];
    int np = 0;
    if (k) { ks[0] = k; vs[0] = v; np = 1; }
    if (MXSymbolCreateFromOperator(op, 1, ins, name, np, ks, vs,
                                   &out) != 0) {
        fprintf(stderr, "%s: %s\n", op, MXTPUCApiGetLastError());
        return NULL;
    }
    return out;
}

int main(void) {
    SymbolHandle data;
    if (MXSymbolCreateVariable("data", &data) != 0) return 1;
    SymbolHandle fc1 = op1("FullyConnected", data, "fc1",
                           "num_hidden", "16");
    if (!fc1) return 1;          /* bail BEFORE passing NULL on */
    SymbolHandle act = op1("Activation", fc1, "relu1",
                           "act_type", "relu");
    if (!act) return 1;
    SymbolHandle fc2 = op1("FullyConnected", act, "fc2",
                           "num_hidden", "3");
    if (!fc2) return 1;
    SymbolHandle net = op1("SoftmaxOutput", fc2, "softmax",
                           NULL, NULL);
    if (!net) return 1;

    mx_uint n_args; const char **args;
    if (MXSymbolListArguments(net, &n_args, &args) != 0) return 1;
    for (mx_uint i = 0; i < n_args; ++i) printf("ARG %s\n", args[i]);

    /* infer output shape for batch 8 x 6 features */
    const char *keys[2] = {"data", "softmax_label"};
    mx_uint indptr[3] = {0, 2, 3};
    mx_uint sdata[3] = {8, 6, 8};
    mx_uint n_out; const mx_uint *optr; const mx_uint *oshp;
    if (MXSymbolInferShape(net, 2, keys, indptr, sdata, &n_out,
                           &optr, &oshp) != 0) {
        fprintf(stderr, "infer: %s\n", MXTPUCApiGetLastError());
        return 1;
    }
    printf("OUTSHAPE");
    for (mx_uint j = optr[0]; j < optr[1]; ++j)
        printf(" %u", oshp[j]);
    printf("\n");

    const char *json;
    if (MXSymbolToJSON(net, &json) != 0) return 1;

    /* train the composed graph natively */
    const char *tkeys[2] = {"data", "softmax_label"};
    mx_uint tindptr[3] = {0, 2, 3};
    mx_uint tshape[3] = {8, 6, 8};
    TrainerHandle tr;
    if (MXTPUTrainCreate(json, NULL, 0, 1, 0, 2, tkeys, tindptr,
                         tshape, "adam", 0.05f, &tr) != 0) {
        fprintf(stderr, "train create: %s\n",
                MXTPUTrainGetLastError());
        return 1;
    }
    float x[48], y[8];
    unsigned s = 42u;
    for (int i = 0; i < 48; ++i) {
        s = s * 1103515245u + 12345u;
        x[i] = (float)((s >> 16) & 0xff) / 255.0f;
    }
    for (int i = 0; i < 8; ++i) y[i] = (float)(i % 3);
    MXTPUTrainSetInput(tr, "data", x, 48);
    MXTPUTrainSetInput(tr, "softmax_label", y, 8);
    float loss = 0, first = 0;
    for (int it = 0; it < 40; ++it) {
        if (MXTPUTrainStep(tr, &loss) != 0) {
            fprintf(stderr, "step: %s\n", MXTPUTrainGetLastError());
            return 1;
        }
        if (it == 0) first = loss;
    }
    printf("LOSS %.6f %.6f\n", first, loss);
    MXTPUTrainFree(tr);
    MXSymbolFree(data); MXSymbolFree(fc1); MXSymbolFree(act);
    MXSymbolFree(fc2); MXSymbolFree(net);
    return 0;
}
"""


def test_c_symbol_compose_and_native_train(tmp_path):
    """The full native story: a C program composes a graph through
    the symbolic C API (Variable -> FullyConnected -> Activation ->
    FullyConnected -> SoftmaxOutput), lists arguments, infers output
    shapes, serializes to the shared JSON format, and trains it
    through the C train ABI — no Python anywhere in the client."""
    import sys as _sys

    _build_lib()
    train_src = os.path.join(REPO, "src", "c_train")
    subprocess.run(["make", "-C", train_src], check=True,
                   capture_output=True, timeout=300)
    demo_c = tmp_path / "symdemo.c"
    demo_c.write_text(SYMBOL_DEMO_C)
    demo = str(tmp_path / "symdemo")
    subprocess.run(
        ["gcc", "-O2", "-I", SRC, "-I", train_src, str(demo_c),
         "-o", demo, "-L", SRC, f"-Wl,-rpath,{SRC}", "-L", train_src,
         f"-Wl,-rpath,{train_src}", "-lmxtpu_capi", "-lmxtpu_train"],
        check=True, capture_output=True, timeout=120)

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["MXTPU_FORCE_CPU"] = "1"
    env.pop("XLA_FLAGS", None)
    r = subprocess.run([demo], capture_output=True, text=True,
                       timeout=600, env=env)
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    lines = r.stdout.strip().splitlines()
    args = [l.split()[1] for l in lines if l.startswith("ARG ")]
    assert args == ["data", "fc1_weight", "fc1_bias", "fc2_weight",
                    "fc2_bias", "softmax_label"], args
    outshape = [l for l in lines if l.startswith("OUTSHAPE")][0]
    assert outshape.split()[1:] == ["8", "3"], outshape
    first, last = map(
        float, [l for l in lines if l.startswith("LOSS")][0]
        .split()[1:])
    # 0.7 bound per the suite convention (test_bucketing.py): the
    # demo's Xavier init is unseeded, so leave convergence headroom
    assert 0 < last < 0.7 * first, (first, last)


def test_autograd_from_c():
    """Imperative differentiation through the C ABI (the reference's
    MXAutograd* family): mark -> record -> invoke ops -> backward ->
    read gradients, no Python in the flow."""
    lib = _bind(ctypes.CDLL(_build_lib()))
    lib.MXAutogradSetIsRecording.argtypes = [
        ctypes.c_int, ctypes.POINTER(ctypes.c_int)]
    lib.MXAutogradIsRecording.argtypes = [ctypes.POINTER(ctypes.c_int)]
    lib.MXAutogradMarkVariable.argtypes = [ctypes.c_void_p]
    lib.MXAutogradBackward.argtypes = [ctypes.c_void_p]
    lib.MXAutogradGetGrad.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p)]

    rs = np.random.RandomState(0)
    x = rs.rand(3, 4).astype(np.float32)
    w = rs.rand(4, 2).astype(np.float32)
    hx, hw = _nd_from_np(lib, x), _nd_from_np(lib, w)
    assert lib.MXAutogradMarkVariable(hw) == 0

    prev = ctypes.c_int(-1)
    assert lib.MXAutogradSetIsRecording(1, ctypes.byref(prev)) == 0
    assert prev.value == 0
    rec = ctypes.c_int(0)
    assert lib.MXAutogradIsRecording(ctypes.byref(rec)) == 0
    assert rec.value == 1

    ins = (ctypes.c_void_p * 2)(hx, hw)
    outs = (ctypes.c_void_p * 4)()
    n_out = ctypes.c_int(4)
    assert lib.MXImperativeInvoke(b"dot", 2, ins,
                                  ctypes.byref(n_out), outs, 0,
                                  None, None) == 0
    hxw = outs[0]
    n_out.value = 4
    assert lib.MXImperativeInvoke(b"relu", 1,
                                  (ctypes.c_void_p * 1)(hxw),
                                  ctypes.byref(n_out), outs, 0,
                                  None, None) == 0
    hr = outs[0]
    n_out.value = 4
    assert lib.MXImperativeInvoke(b"sum", 1,
                                  (ctypes.c_void_p * 1)(hr),
                                  ctypes.byref(n_out), outs, 0,
                                  None, None) == 0
    hloss = outs[0]
    assert lib.MXAutogradSetIsRecording(0, ctypes.byref(prev)) == 0
    assert lib.MXAutogradBackward(hloss) == 0

    hg = ctypes.c_void_p()
    assert lib.MXAutogradGetGrad(hw, ctypes.byref(hg)) == 0, \
        lib.MXTPUCApiGetLastError()
    got = _np_from_nd(lib, hg)
    # oracle: d/dw sum(relu(x @ w)) = x.T @ 1[xw > 0]
    want = x.T @ (x @ w > 0).astype(np.float32)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    # unmarked array: clean error
    hg2 = ctypes.c_void_p()
    assert lib.MXAutogradGetGrad(hx, ctypes.byref(hg2)) == -1
    assert b"no gradient" in lib.MXTPUCApiGetLastError()
    for h in (hx, hw, hxw, hr, hloss, hg):
        lib.MXNDArrayFree(h)
