"""Test configuration: force an 8-device virtual CPU platform so
multi-device/sharding paths are exercised without TPU hardware
(analog of the reference testing model parallelism on cpu(0)/cpu(1),
ref: tests/python/unittest/test_multi_device_exec.py).

Must run before the jax backend is initialized (it is lazy, so doing
this at conftest import time is early enough).
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
