"""Perf observatory (docs/observability.md): analytic graph cost
model vs XLA's own cost_analysis on the three bench graphs, device-DB
/ roofline unit semantics, model-method FLOPs parity with the shared
formulas, the transfer-budget proof that the MFU gauges add zero
device->host reads, Module/ServingEngine perf_report tables,
launch.py fleet-MFU aggregation, op-cost lint coverage, and the
bench_gate regression gate over synthetic and real trajectories."""
import json
import os
import sys
import warnings

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, nd, perf
from incubator_mxnet_tpu import optimizer as opt_mod
from incubator_mxnet_tpu import symbol as symmod
from incubator_mxnet_tpu import telemetry as tel
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.ops import registry as op_registry
from incubator_mxnet_tpu.perf import cost_model, device_db

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")


def _load_tool(name):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import importlib
        return importlib.import_module(name)
    finally:
        sys.path.pop(0)


def _load_bench():
    sys.path.insert(0, REPO)
    try:
        import importlib
        return importlib.import_module("bench")
    finally:
        sys.path.pop(0)


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    tel.get_registry().reset()
    yield
    tel.get_registry().reset()


# ------------------------------------------------- analytic vs XLA
# The acceptance bar for the cost model: its totals must track XLA's
# own compiled cost_analysis within 10% on the three bench graphs.
@pytest.mark.parametrize("graph", ["mlp", "resnet_block",
                                   "transformer_step"])
def test_analytic_flops_within_10pct_of_xla(graph):
    bench = _load_bench()
    builder = getattr(bench, f"_graph_{graph}")
    s, shapes = builder(symmod)
    rep, xc, delta = bench._analytic_vs_xla(s, shapes)
    assert rep.flops > 0 and rep.bytes > 0
    assert xc is not None and xc["flops"] > 0, \
        "backend reported no cost_analysis"
    assert delta is not None and delta <= 0.10, \
        f"{graph}: analytic {rep.flops:.3e} vs XLA " \
        f"{xc['flops']:.3e} (delta {delta:.1%})"
    # full coverage on the bench graphs: no unknown or default-cost
    # nodes sneak into the headline numbers
    assert rep.coverage["unknown"] == 0, rep.unknown_ops
    assert rep.coverage["default"] == 0, rep.default_ops


def test_cost_report_families_scaling_and_table():
    bench = _load_bench()
    s, shapes = bench._graph_transformer_step(symmod)
    rep = perf.symbol_cost(s, shapes)
    # the symbol-level transformer step spells attention out as
    # matmuls + elementwise (no fused attention op in the graph)
    fams = set(rep.per_family)
    assert "matmul" in fams and "embedding" in fams
    # matmul dominates a transformer step's FLOPs
    assert rep.per_family["matmul"]["flops"] > 0.5 * rep.flops
    train = rep.scaled(3.0)
    assert train.flops == pytest.approx(3.0 * rep.flops)
    assert train.bytes == pytest.approx(3.0 * rep.bytes)
    assert train.arithmetic_intensity == pytest.approx(
        rep.arithmetic_intensity)
    caps = device_db.caps_for_kind("cpu")
    rows = train.table(caps, "float32")
    assert rows and all(
        {"family", "gflops", "flops_pct", "bound"} <= set(r)
        for r in rows)
    assert sum(r["flops_pct"] for r in rows) == pytest.approx(
        100.0, abs=0.5)
    summ = train.summary()
    assert summ["gflops"] == pytest.approx(train.flops / 1e9,
                                           abs=5e-4)


# --------------------------------------------- device DB + roofline
def test_device_db_peaks_and_dtype_conventions():
    v4 = device_db.caps_for_kind("TPU v4")
    assert v4.peak("bfloat16") == 275e12
    assert v4.peak("float32") == 275e12 / 8
    assert not v4.nominal
    v5e = device_db.caps_for_kind("TPU v5e chip")
    assert v5e.peak("int8") == 2 * v5e.peak("bfloat16")
    # unknown kinds: caps_for_kind degrades to nominal CPU numbers
    # (so a roofline verdict always exists) while peak_flops keeps
    # bench.py's legacy contract and returns None
    cpu = device_db.caps_for_kind("some future accelerator")
    assert cpu.nominal
    assert cpu.peak("float32") == cpu.peak("bfloat16")

    class FakeDev:
        device_kind = "some future accelerator"
    assert device_db.peak_flops(FakeDev()) is None

    class V5p:
        device_kind = "TPU v5p"
    assert device_db.peak_flops(V5p()) == 459e12


def test_cpu_nominal_peaks_respect_env(monkeypatch):
    monkeypatch.setenv("MXTPU_PERF_CPU_PEAK_GFLOPS", "50")
    monkeypatch.setenv("MXTPU_PERF_CPU_GBPS", "10")
    caps = device_db.caps_for_kind("")
    assert caps.peak("float32") == 50e9
    assert caps.hbm_bytes_per_s == 10e9


def test_roofline_units_and_bound_classification():
    caps = device_db.DeviceCaps("test", 100e9, 100.0)  # ridge = 1.0
    r = device_db.roofline(200e9, 1e9, caps, "bfloat16")
    assert r["compute_s"] == pytest.approx(2.0)
    assert r["memory_s"] == pytest.approx(0.01)
    assert r["predicted_s"] == pytest.approx(2.0)
    assert r["bound"] == "compute"
    assert r["arithmetic_intensity"] == pytest.approx(200.0)
    assert r["ridge_intensity"] == pytest.approx(1.0)
    assert device_db.roofline(1e9, 200e9, caps)["bound"] == "memory"
    assert device_db.roofline(1e9, 1e9, caps)["bound"] == "balanced"
    assert device_db.roofline(0, 0, caps)["bound"] == "idle"


# ------------------------------------- model-method formula parity
def test_transformer_flops_methods_match_shared_formulas():
    from incubator_mxnet_tpu.gluon.model_zoo.transformer import \
        TransformerLM
    net = TransformerLM(64, d_model=32, n_layers=2, n_heads=4,
                        max_len=16)
    assert net.train_flops_per_token(16) == \
        perf.transformer_train_flops_per_token(
            d_model=32, n_layers=2, vocab=64, seq_len=16, n_heads=4)
    assert net.decode_flops_per_token(12) == \
        perf.transformer_decode_flops_per_token(
            d_model=32, n_layers=2, vocab=64, context_len=12,
            n_heads=4)
    # windowed attention caps the context term
    win = TransformerLM(64, d_model=32, n_layers=2, n_heads=4,
                        max_len=64, attn_window=8)
    assert win.decode_flops_per_token(64) == \
        win.decode_flops_per_token(8)


# -------------------------------------------- transfer-budget proof
def test_mfu_gauges_add_zero_host_reads(monkeypatch):
    """The zero-added-syncs contract: with the sentinel at guard
    interval 4 and the MFU clock armed and PUBLISHING (interval 2),
    the sole device->host transfer point (read_window_bad) still
    fires exactly twice over 8 steps — the same count as the
    perf-off baseline in test_sentinel.py."""
    monkeypatch.setenv("MXTPU_NONFINITE_POLICY", "skip")
    monkeypatch.setenv("MXTPU_GUARD_INTERVAL", "4")
    monkeypatch.setenv("MXTPU_PERF_INTERVAL", "2")
    reads = []
    orig = opt_mod.read_window_bad
    monkeypatch.setattr(opt_mod, "read_window_bad",
                        lambda g: reads.append(1) or orig(g))
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    data = rs.randn(80, 10).astype("float32")
    labels = rs.randint(0, 3, 80).astype("float32")
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"))
    net.add(nn.Dense(3))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01})
    clock = trainer.arm_perf(flops_per_step=1e9,
                             bytes_per_step=1e8,
                             tokens_per_step=10)
    assert clock is trainer._perf_clock
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        for step in range(8):
            lo = (step * 10) % len(data)
            x = nd.array(data[lo:lo + 10])
            y = nd.array(labels[lo:lo + 10])
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(10)
    assert len(reads) == 2, \
        f"perf gauges changed the transfer budget: {len(reads)} reads"
    gauges = tel.snapshot()["gauges"]
    assert gauges["train_mfu"] > 0
    assert gauges["train_mbu"] > 0
    assert gauges["train_tokens_per_sec"] > 0


def test_sharded_step_cost_analysis_arms_clock(monkeypatch):
    monkeypatch.setenv("MXTPU_PERF_INTERVAL", "2")
    from incubator_mxnet_tpu import parallel
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"))
    net.add(nn.Dense(4))
    net.initialize(mx.init.Xavier())
    step = parallel.ShardedTrainStep(
        net, optimizer="sgd",
        optimizer_params={"learning_rate": 0.01},
        example_args=[mx.nd.zeros((2, 8))])
    rs = np.random.RandomState(0)
    x = np.asarray(rs.rand(8, 8), np.float32)
    y = np.asarray(rs.randint(0, 4, (8,)), np.int32)
    cost = step.cost_analysis(x, y)
    assert cost is not None and cost["flops"] > 0 \
        and cost["bytes"] > 0
    assert step._perf_clock is not None       # auto-armed
    for _ in range(4):
        loss = step(x, y)
    assert np.isfinite(float(loss))
    assert tel.snapshot()["gauges"]["train_mfu"] > 0


# ------------------------------------------------ perf_report views
def test_module_perf_report_tables():
    data = symmod.Variable("data")
    fc1 = symmod.FullyConnected(data, num_hidden=512, name="fc1")
    act = symmod.Activation(fc1, act_type="relu")
    fc2 = symmod.FullyConnected(act, num_hidden=64, name="fc2")
    out = symmod.SoftmaxOutput(fc2, name="softmax")
    mod = mx.mod.Module(out, context=mx.cpu())
    mod.bind(data_shapes=[("data", (64, 256))],
             label_shapes=[("softmax_label", (64,))])
    mod.init_params(initializer=mx.initializer.Xavier())
    rep = mod.perf_report()
    assert rep["per_family"], "empty per-family table"
    assert "matmul" in {r["family"] for r in rep["per_family"]}
    assert rep["total"]["gflops"] > 0
    assert rep["roofline"]["bound"] in (
        "compute", "memory", "balanced")
    assert rep["coverage"]["unknown"] == 0
    xla = rep.get("xla_check")
    if xla is not None:          # backend-dependent
        assert xla["rel_delta"] <= 0.10


def test_serving_engine_perf_report_and_gauges(monkeypatch):
    monkeypatch.setenv("MXTPU_PERF_INTERVAL", "2")
    from incubator_mxnet_tpu.gluon.model_zoo.transformer import \
        TransformerLM
    from incubator_mxnet_tpu.serving.engine import ServingEngine
    mx.random.seed(0)
    net = TransformerLM(64, d_model=32, n_layers=2, n_heads=4,
                        max_len=32)
    net.initialize(mx.initializer.Xavier())
    net(mx.nd.array(np.zeros((1, 4), "int32")))
    eng = ServingEngine(net, max_batch=2, block_size=8,
                        num_blocks=16)
    rs = np.random.RandomState(0)
    for _ in range(3):
        eng.submit([int(t) for t in rs.randint(1, 64, 5)],
                   max_new_tokens=6)
    events = list(eng.stream())
    assert len(events) == 3 * 6
    gauges = tel.snapshot()["gauges"]
    assert gauges["serving_mfu"] > 0
    assert gauges["serving_flops_per_token"] > 0
    rep = eng.perf_report()
    assert rep["flops_per_token"] > 0
    assert rep["per_family"], "empty decode per-family table"
    fams = {r["family"] for r in rep["per_family"]}
    assert "matmul" in fams and "attention" in fams
    assert rep["roofline"]["bound"] in (
        "compute", "memory", "balanced")


# ------------------------------------------- launch.py fleet view
def test_launch_fleet_mfu_aggregation():
    launch = _load_tool("launch")
    snaps = {
        0: {"counters": {"train_steps_total": 10},
            "gauges": {"train_mfu": 0.5}, "histograms": {}},
        1: {"counters": {"train_steps_total": 10},
            "gauges": {"train_mfu": 0.3}, "histograms": {}},
        2: {"counters": {}, "gauges": {"serving_mfu": 0.4},
            "histograms": {}},
    }
    agg = launch._aggregate_telemetry(snaps)
    assert agg["mfu"] == pytest.approx((0.5 + 0.3 + 0.4) / 3)
    assert agg["mfu_slowest"] == (1, 0.3)
    status = launch._format_status(agg)
    assert "mfu: 40.0%" in status
    assert "slowest rank 1 at 30.0%" in status
    report = launch._format_report(snaps)
    assert "mfu=50.0%" in report and "mfu=30.0%" in report
    # no rank publishing MFU -> the part is absent, not 0%
    agg0 = launch._aggregate_telemetry(
        {0: {"counters": {}, "gauges": {}, "histograms": {}}})
    assert agg0["mfu"] is None
    assert "mfu" not in launch._format_status(agg0)


# ------------------------------------------------- op-cost coverage
def test_cost_model_covers_entire_op_registry():
    names = {op.name for op in op_registry.OPS.values()}
    assert names, "op registry unexpectedly empty"
    assert cost_model.coverage_gaps(names) == []
    # and the lint rule that enforces it stays armed
    sys.path.insert(0, os.path.join(REPO, "ci"))
    try:
        import lint
    finally:
        sys.path.pop(0)
    assert hasattr(lint, "check_op_cost_coverage")


# ------------------------------------------------------ bench_gate
def _rec(metric, value, rnd, hib=True):
    return {"schema": "bench-v1", "round": rnd, "metric": metric,
            "value": value, "unit": "u", "higher_is_better": hib}


def test_bench_gate_catches_injected_regression():
    bg = _load_tool("bench_gate")
    history = [_rec("tok_s", 100.0, 1), _rec("tok_s", 110.0, 2),
               _rec("p99_s", 1.0, 1, hib=False)]
    # 20% below best-so-far (110) with a 10% band -> regression
    failures, checked = bg.gate([_rec("tok_s", 88.0, 3)], history,
                                band=0.10)
    assert checked == 1 and len(failures) == 1
    assert failures[0]["metric"] == "tok_s"
    assert failures[0]["limit"] == pytest.approx(99.0)
    # within the band -> pass
    failures, _ = bg.gate([_rec("tok_s", 100.0, 3)], history, 0.10)
    assert failures == []
    # lower-is-better: +20% past the ceiling fails, first-seen skips
    failures, checked = bg.gate(
        [_rec("p99_s", 1.2, 3, hib=False), _rec("new_metric", 1, 3)],
        history, 0.10)
    assert checked == 1 and len(failures) == 1
    assert failures[0]["metric"] == "p99_s"


def test_bench_gate_normalizes_heterogeneous_rounds():
    bg = _load_tool("bench_gate")
    doc = {"metric": "perf_report", "train": {"mfu": 0.4},
           "serving": {"tokens_per_s": 50.0}}
    recs = bg.normalize(doc, round_no=18)
    assert {r["metric"] for r in recs} == \
        {"perf_train_mfu", "perf_serving_tokens_per_s"}
    assert all(r["schema"] == "bench-v1" and r["round"] == 18
               for r in recs)
    # r01-style driver envelopes unwrap; failed rounds -> no records
    wrapped = {"n": 3, "rc": 0, "parsed": doc}
    assert len(bg.normalize(wrapped)) == 2
    assert bg.normalize({"n": 4, "rc": 1, "parsed": None}) == []
    assert bg.normalize({"metric": "unknown_experiment"}) == []


def test_bench_gate_real_history_passes_and_appends(tmp_path,
                                                    capsys):
    bg = _load_tool("bench_gate")
    history = bg.load_history()
    assert history, "committed BENCH history normalized to nothing"
    traj = bg.trajectory_summary(history)
    assert len(traj) >= 10
    assert "serving_tokens_per_s" in traj
    # the ci gate over the committed history passes
    assert bg.main(["--check"]) == 0
    out = capsys.readouterr().out
    assert "bench_gate: OK" in out
    # trajectory records append once (dedup on round+metric)
    p = tmp_path / "PROGRESS.jsonl"
    p.write_text('{"driver": "unrelated line"}\n')
    n = bg.append_progress(history, str(p))
    assert n == len(history)
    assert bg.append_progress(history, str(p)) == 0
    lines = [json.loads(x) for x in p.read_text().splitlines()]
    assert sum(1 for d in lines
               if d.get("schema") == "bench-v1") == len(history)
