"""Executor tests (ref: tests/python/unittest/test_executor.py)."""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, sym

RS = np.random.RandomState(3)


def test_bind_forward():
    a = sym.Variable("a")
    b = sym.Variable("b")
    c = sym.dot(a, b)
    av = RS.rand(3, 4).astype("float32")
    bv = RS.rand(4, 5).astype("float32")
    ex = c.bind(mx.cpu(), {"a": nd.array(av), "b": nd.array(bv)})
    out = ex.forward()
    np.testing.assert_allclose(out[0].asnumpy(), av @ bv, rtol=1e-5)


def test_backward_grads():
    a = sym.Variable("a")
    b = sym.Variable("b")
    loss = sym.sum(a * b)
    av, bv = RS.rand(3, 3).astype("float32"), RS.rand(3, 3).astype(
        "float32")
    ga, gb = nd.zeros((3, 3)), nd.zeros((3, 3))
    ex = loss.bind(mx.cpu(), {"a": nd.array(av), "b": nd.array(bv)},
                   args_grad={"a": ga, "b": gb})
    ex.forward(is_train=True)
    ex.backward()
    np.testing.assert_allclose(ga.asnumpy(), bv, rtol=1e-5)
    np.testing.assert_allclose(gb.asnumpy(), av, rtol=1e-5)


def test_grad_req_add_and_null():
    a = sym.Variable("a")
    loss = sym.sum(a * a)
    av = RS.rand(2, 2).astype("float32")
    ga = nd.zeros((2, 2))
    ex = loss.bind(mx.cpu(), {"a": nd.array(av)}, args_grad={"a": ga},
                   grad_req="add")
    for _ in range(2):
        ex.forward(is_train=True)
        ex.backward()
    np.testing.assert_allclose(ga.asnumpy(), 2 * 2 * av, rtol=1e-5)
    ex2 = loss.bind(mx.cpu(), {"a": nd.array(av)}, grad_req="null")
    ex2.forward(is_train=True)
    ex2.backward()  # no grads requested; must not crash


def test_simple_bind_allocates():
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=8, name="fc")
    net = sym.SoftmaxOutput(net, sym.Variable("label"), name="sm")
    ex = net.simple_bind(mx.cpu(), data=(4, 12), label=(4,))
    assert ex.arg_dict["fc_weight"].shape == (8, 12)
    assert ex.grad_dict["fc_weight"].shape == (8, 12)
    ex.arg_dict["data"][:] = 1.0
    out = ex.forward(is_train=False)
    assert out[0].shape == (4, 8)


def test_head_gradients():
    a = sym.Variable("a")
    out = a * 3.0
    av = RS.rand(4).astype("float32")
    ga = nd.zeros((4,))
    ex = out.bind(mx.cpu(), {"a": nd.array(av)}, args_grad={"a": ga})
    ex.forward(is_train=True)
    hg = nd.array(np.array([1, 2, 3, 4], "float32"))
    ex.backward(out_grads=hg)
    np.testing.assert_allclose(ga.asnumpy(), 3 * hg.asnumpy(), rtol=1e-5)


def test_forward_backward_fused():
    data = sym.Variable("data")
    label = sym.Variable("label")
    fc = sym.FullyConnected(data, num_hidden=3, name="fc")
    net = sym.SoftmaxOutput(fc, label, name="sm")
    ex = net.simple_bind(mx.cpu(), data=(5, 7), label=(5,))
    ex.arg_dict["data"][:] = nd.array(RS.rand(5, 7).astype("float32"))
    ex.arg_dict["fc_weight"][:] = nd.array(
        RS.rand(3, 7).astype("float32"))
    ex.arg_dict["label"][:] = nd.array(
        np.array([0, 1, 2, 0, 1], "float32"))
    outs = ex.forward_backward()
    assert outs[0].shape == (5, 3)
    g = ex.grad_dict["fc_weight"].asnumpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_batchnorm_executor_aux_update():
    data = sym.Variable("data")
    net = sym.BatchNorm(data, name="bn", momentum=0.5, fix_gamma=False)
    ex = net.simple_bind(mx.cpu(), data=(8, 3))
    x = RS.rand(8, 3).astype("float32") * 4
    ex.arg_dict["data"][:] = nd.array(x)
    ex.arg_dict["bn_gamma"][:] = 1.0
    mm_before = ex.aux_dict["bn_moving_mean"].asnumpy().copy()
    ex.forward(is_train=True)
    mm_after = ex.aux_dict["bn_moving_mean"].asnumpy()
    expected = 0.5 * mm_before + 0.5 * x.mean(0)
    np.testing.assert_allclose(mm_after, expected, rtol=1e-4)
    # inference does not touch aux
    ex.forward(is_train=False)
    np.testing.assert_allclose(ex.aux_dict["bn_moving_mean"].asnumpy(),
                               mm_after)


def test_dropout_deterministic_backward():
    # backward must replay the same dropout mask as forward
    data = sym.Variable("data")
    net = sym.sum(sym.Dropout(data, p=0.5, name="do"))
    av = np.ones((64, 64), "float32")
    ga = nd.zeros((64, 64))
    ex = net.bind(mx.cpu(), {"data": nd.array(av)},
                  args_grad={"data": ga})
    out = ex.forward(is_train=True)
    ex.backward()
    g = ga.asnumpy()
    # gradient is 2.0 where kept, 0 where dropped; sum matches forward
    np.testing.assert_allclose((g > 0).sum() * 2.0, out[0].asscalar(),
                               rtol=1e-5)


def test_reshape_executor():
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=4, name="fc")
    ex = net.simple_bind(mx.cpu(), data=(2, 6))
    ex.arg_dict["fc_weight"][:] = 0.5
    ex2 = ex.reshape(data=(8, 6))
    assert ex2.arg_dict["data"].shape == (8, 6)
    np.testing.assert_allclose(ex2.arg_dict["fc_weight"].asnumpy(),
                               0.5 * np.ones((4, 6)))
    out = ex2.forward()
    assert out[0].shape == (8, 4)


def test_copy_params_from():
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=2, name="fc")
    ex = net.simple_bind(mx.cpu(), data=(1, 3))
    ex.copy_params_from({"fc_weight": nd.ones((2, 3)),
                         "fc_bias": nd.zeros((2,))})
    ex.arg_dict["data"][:] = 1.0
    np.testing.assert_allclose(ex.forward()[0].asnumpy(),
                               [[3.0, 3.0]])
