"""Graph-optimization pass pipeline + CachedOp (docs/graph_passes.md).

Covers: golden equivalence of randomized graphs across
MXTPU_GRAPH_OPT levels (bitwise), per-pass units (CSE, folding,
identity/transpose elimination, pruning reachability, fusion),
PassManager ordering, CachedOp hit/miss + train/eval separation +
stable scalar signatures (trace-count regression), and the _Node
mutation lint rule.
"""
import os

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, nd, sym
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.graph import (CachedOp, Graph, PassManager,
                                       PASSES, optimize_symbol)
from incubator_mxnet_tpu.graph.fuse import FusedOp
from incubator_mxnet_tpu.symbol.symbol import _topo

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _op_names(symbol):
    return [n.op.name for n in _topo(symbol._heads)
            if n.op is not None]


def _bind_forward(symbol, arg_vals, level, monkeypatch, is_train=False,
                  seed=None):
    monkeypatch.setenv("MXTPU_GRAPH_OPT", str(level))
    ex = symbol.bind(mx.cpu(), {k: nd.array(v)
                                for k, v in arg_vals.items()})
    if seed is not None:
        mx.random.seed(seed)
    return [o.asnumpy() for o in ex.forward(is_train=is_train)]


# ------------------------------------------------------------ goldens
def _random_symbol(seed, n_ops=24):
    """Randomized DAG over two variables: elementwise ops, scalar
    ops, transpose pairs, const subtrees, duplicated subexpressions,
    and identities — material for every pass."""
    rs = np.random.RandomState(seed)
    pool = [sym.Variable("a"), sym.Variable("b")]
    unary = ["tanh", "sin", "relu", "abs", "negative"]
    for _ in range(n_ops):
        r = rs.rand()
        pick = lambda: pool[rs.randint(len(pool))]
        if r < 0.30:
            t = getattr(sym, unary[rs.randint(len(unary))])(pick())
        elif r < 0.55:
            f = [sym.broadcast_add, sym.broadcast_mul,
                 sym.broadcast_sub, sym.broadcast_maximum][
                rs.randint(4)]
            t = f(pick(), pick())
        elif r < 0.65:
            t = pick() * float(round(rs.uniform(0.2, 2.2), 3))
        elif r < 0.73:
            t = sym.transpose(sym.transpose(pick(), axes=(1, 0)),
                              axes=(1, 0))
        elif r < 0.81:
            t = pick() + sym.ones((4, 8)) * \
                float(round(rs.uniform(0.5, 1.5), 3))
        elif r < 0.92:
            x = pick()
            t = sym.tanh(x) + sym.tanh(x)        # CSE material
        else:
            t = sym._internal._copy(pick())      # identity material
        pool.append(t)
    return sym.Group(pool[-2:])


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_golden_equivalence_bitwise_across_levels(seed, monkeypatch):
    s = _random_symbol(seed)
    rs = np.random.RandomState(100 + seed)
    vals = {"a": rs.randn(4, 8).astype("float32"),
            "b": rs.randn(4, 8).astype("float32")}
    outs = {lv: _bind_forward(s, vals, lv, monkeypatch)
            for lv in (0, 1, 2)}
    for lv in (1, 2):
        for o_ref, o_opt in zip(outs[0], outs[lv]):
            assert np.array_equal(o_ref, o_opt), \
                f"level {lv} diverged (seed {seed})"


def test_golden_equivalence_gradients(monkeypatch):
    s = _random_symbol(7)
    rs = np.random.RandomState(7)
    vals = {"a": rs.randn(4, 8).astype("float32"),
            "b": rs.randn(4, 8).astype("float32")}
    grads = {}
    for lv in (0, 2):
        monkeypatch.setenv("MXTPU_GRAPH_OPT", str(lv))
        ex = s.simple_bind(mx.cpu(), grad_req="write",
                           a=(4, 8), b=(4, 8))
        ex.copy_params_from({k: nd.array(v) for k, v in vals.items()})
        ex.forward_backward()
        grads[lv] = {k: g.asnumpy()
                     for k, g in ex.grad_dict.items()}
    for k in grads[0]:
        np.testing.assert_allclose(grads[0][k], grads[2][k],
                                   rtol=1e-5, atol=1e-6)


def test_rng_stream_invariant_under_optimization(monkeypatch):
    """Dropout draws the identical mask at every opt level: rng fold
    indices are pinned pre-optimization (__rng_index__)."""
    x = sym.Variable("x")
    y = sym.Dropout(sym.tanh(x) + sym.tanh(x), p=0.5) * 1.0
    vals = {"x": np.random.RandomState(3).randn(8, 8)
            .astype("float32")}
    outs = {lv: _bind_forward(y, vals, lv, monkeypatch,
                              is_train=True, seed=123)
            for lv in (0, 1, 2)}
    assert np.array_equal(outs[0][0], outs[1][0])
    assert np.array_equal(outs[0][0], outs[2][0])


# ------------------------------------------------------------ passes
def test_cse_dedups_identical_subtrees():
    x = sym.Variable("x")
    y = sym.tanh(x) + sym.tanh(x)
    opt, report = y.optimize(level=1)
    assert _op_names(opt).count("tanh") == 1
    merged = [p for p in report["passes"]
              if p["pass"] == "eliminate_common_subexpressions"][0]
    assert merged["merged"] == 1


def test_cse_never_merges_rng_ops():
    x = sym.Variable("x")
    y = sym.Dropout(x, p=0.5) + sym.Dropout(x, p=0.5)
    opt, _ = y.optimize(level=1)
    assert _op_names(opt).count("Dropout") == 2


def test_constant_folding_removes_const_subtree(monkeypatch):
    x = sym.Variable("x")
    y = x + (sym.ones((4,)) * 2.0 + 1.0)
    opt, report = y.optimize(level=1)
    names = _op_names(opt)
    assert "_ones" not in names
    assert "_graph_const" in names
    folded = [p for p in report["passes"]
              if p["pass"] == "fold_constants"][0]
    assert folded["folded"] >= 1
    vals = {"x": np.zeros((2, 4), "float32")}
    out = _bind_forward(y, vals, 1, monkeypatch)[0]
    np.testing.assert_allclose(out, np.full((2, 4), 3.0))


def test_identity_elimination():
    x = sym.Variable("x")
    y = sym._internal._copy(sym.tanh(x) * 1.0) / 1.0
    opt, _ = y.optimize(level=1)
    assert _op_names(opt) == ["tanh"]


def test_scalar_identity_kept_after_relu_activation():
    """Activation(act_type='relu') preserves int dtype, so a
    downstream *1.0 still promotes and must survive; tanh-activation
    is a real float producer (review fix)."""
    x = sym.Variable("x")
    relu_mul = sym.Activation(x, act_type="relu") * 1.0
    opt, _ = relu_mul.optimize(level=1)
    assert "_mul_scalar" in _op_names(opt)
    tanh_mul = sym.Activation(x, act_type="tanh") * 1.0
    opt2, _ = tanh_mul.optimize(level=1)
    assert "_mul_scalar" not in _op_names(opt2)


def test_cachedop_entry_does_not_pin_input_arrays():
    """Replay closures capture only the argument structure — never
    the building call's tensors (review fix: an LRU of 64 entries
    must not pin 64 input batches)."""
    import gc
    import weakref
    net = _mlp()
    net.hybridize()
    x = nd.array(np.random.RandomState(0).rand(3, 8)
                 .astype("float32"))
    net(x)
    ref = weakref.ref(x)
    del x
    gc.collect()
    assert ref() is None, "CachedOp entry retained the input NDArray"


def test_scalar_identity_kept_on_integer_inputs(monkeypatch):
    """`int32 * 1.0` promotes to float32 — the node must survive so
    optimized and unoptimized graphs agree on dtype (review fix)."""
    x = sym.Variable("x")
    y = x * 1.0                       # input dtype unknown: keep
    opt, _ = y.optimize(level=1)
    assert _op_names(opt) == ["_mul_scalar"]
    monkeypatch.setenv("MXTPU_GRAPH_OPT", "1")
    ex = y.simple_bind(mx.cpu(), grad_req="null",
                       type_dict={"x": "int32"}, x=(2, 2))
    ex.copy_params_from({"x": nd.array(np.ones((2, 2), "int32"))})
    out = ex.forward()[0]
    assert out.asnumpy().dtype == np.float32


def test_add_zero_is_not_eliminated():
    # x + 0.0 rewrites -0.0 to +0.0: must survive
    x = sym.Variable("x")
    y = (x + 0.0) - 0.0
    opt, _ = y.optimize(level=1)
    assert len(_op_names(opt)) == 2


def test_transpose_pair_elimination():
    x = sym.Variable("x")
    y = sym.tanh(sym.transpose(sym.transpose(x, axes=(1, 0)),
                               axes=(1, 0)))
    opt, _ = y.optimize(level=1)
    assert _op_names(opt) == ["tanh"]
    # non-cancelling pair merges into one transpose
    z = sym.transpose(sym.transpose(x, axes=(0, 1)), axes=(1, 0))
    opt2, _ = z.optimize(level=1)
    assert _op_names(opt2) == ["transpose"]


def test_pruning_never_drops_reachable_outputs(monkeypatch):
    for seed in range(3):
        s = _random_symbol(seed, n_ops=16)
        n_heads = len(s._heads)
        opt, report = s.optimize(level=2)
        assert len(opt._heads) == n_heads
        live = {id(n) for n in _topo(opt._heads)}
        g = Graph(opt._heads)
        assert {id(n) for n in g.topo()} == live
        assert report["nodes_after"] <= report["nodes_before"]


def test_fuse_elemwise_chains(monkeypatch):
    x = sym.Variable("x")
    y = sym.relu(sym.tanh(sym.sin(x) * 0.5) + 2.0)
    opt, report = y.optimize(level=2)
    ops = [n.op for n in _topo(opt._heads) if n.op is not None]
    assert len(ops) == 1 and isinstance(ops[0], FusedOp)
    fused = [p for p in report["passes"]
             if p["pass"] == "fuse_elemwise"][0]
    assert fused["chains"] == 1 and fused["ops_fused"] == 5
    vals = {"x": np.random.RandomState(0).randn(3, 3)
            .astype("float32")}
    assert np.array_equal(_bind_forward(y, vals, 0, monkeypatch)[0],
                          _bind_forward(y, vals, 2, monkeypatch)[0])


def test_fusion_respects_multi_consumer_boundaries():
    x = sym.Variable("x")
    t = sym.tanh(x)
    y = t * 2.0 + sym.sin(t)      # t has 2 consumers: chain breaker
    opt, _ = y.optimize(level=2)
    names = [n.op.name for n in _topo(opt._heads) if n.op is not None]
    assert "tanh" in names        # never swallowed into a chain


def test_pass_manager_ordering_and_unknown_pass():
    pm = PassManager(["prune_dead_nodes", "fold_constants",
                      "eliminate_identity"])
    order = pm.pass_names
    assert order.index("eliminate_identity") \
        < order.index("fold_constants") \
        < order.index("prune_dead_nodes")
    with pytest.raises(KeyError):
        PassManager(["no_such_pass"])


def test_custom_pass_registration():
    from incubator_mxnet_tpu.graph import GraphPass, register_pass

    @register_pass
    class CountTanh(GraphPass):
        name = "count_tanh_test"
        after = ("eliminate_identity",)

        def run(self, graph):
            n = sum(1 for nd_ in graph.topo()
                    if nd_.op is not None and nd_.op.name == "tanh")
            return {"tanh": n}

    try:
        x = sym.Variable("x")
        _, report = sym.tanh(x).optimize(
            level=1, pass_names=["eliminate_identity",
                                 "count_tanh_test"])
        row = [p for p in report["passes"]
               if p["pass"] == "count_tanh_test"][0]
        assert row["tanh"] == 1
    finally:
        del PASSES["count_tanh_test"]


def test_optimize_level_zero_returns_input():
    x = sym.Variable("x")
    y = sym.tanh(x)
    opt, report = y.optimize(level=0)
    assert opt is y and report["passes"] == []


def test_executor_and_module_expose_report(monkeypatch):
    monkeypatch.setenv("MXTPU_GRAPH_OPT", "1")
    x = sym.Variable("data")
    y = sym.FullyConnected(x, num_hidden=3, name="fcrep")
    mod = mx.mod.Module(y, data_names=["data"], label_names=[])
    mod.bind(data_shapes=[("data", (2, 4))], for_training=False)
    rep = mod.graph_opt_report
    assert rep is not None and rep["level"] == 1
    assert rep["nodes_after"] <= rep["nodes_before"]


def test_pipeline_telemetry_counters_and_span():
    from incubator_mxnet_tpu import telemetry
    before = telemetry.counter("graph_passes_total").value
    x = sym.Variable("x")
    (sym.tanh(x) + sym.tanh(x)).optimize(level=1)
    assert telemetry.counter("graph_passes_total").value > before
    snap = telemetry.snapshot()
    assert "span_graph_optimize_seconds" in snap["histograms"]


# ----------------------------------------------------------- CachedOp
def _mlp(width=16):
    net = nn.HybridSequential()
    net.add(nn.Dense(width, activation="relu"))
    net.add(nn.Dense(4))
    net.initialize(mx.init.Xavier())
    return net


def test_cachedop_hit_miss_counts():
    net = _mlp()
    net.hybridize()
    x = nd.array(np.random.RandomState(0).rand(3, 8)
                 .astype("float32"))
    net(x)
    net(x)
    st = net._cached_op.stats()
    assert st["misses"] == 1 and st["hits"] == 1 \
        and st["traces"] == 1
    net(nd.array(np.random.RandomState(1).rand(5, 8)
                 .astype("float32")))
    st = net._cached_op.stats()
    assert st["misses"] == 2 and st["traces"] == 2


def test_cachedop_train_eval_cache_separation():
    net = nn.HybridSequential()
    net.add(nn.BatchNorm(in_channels=3))
    net.initialize()
    net.hybridize()
    x = nd.array(np.random.RandomState(0).rand(4, 3)
                 .astype("float32") + 2)
    y_eval = net(x).asnumpy()
    bn = net[0]
    before = bn.running_mean.data().asnumpy().copy()
    np.testing.assert_allclose(bn.running_mean.data().asnumpy(),
                               before)            # eval: no update
    with autograd.record():
        net(x)
    after = bn.running_mean.data().asnumpy()
    assert not np.allclose(before, after)         # train: updated
    st = net._cached_op.stats()
    assert st["entries"] == 2                     # train + eval keys
    y_eval2 = net(x).asnumpy()
    assert not np.allclose(y_eval, y_eval2)       # stats moved


class _ScaledDense(mx.gluon.HybridBlock):
    def __init__(self, units, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.fc = nn.Dense(units, in_units=4)

    def hybrid_forward(self, F, x, scale):
        return self.fc(x) * scale


def test_cachedop_stable_scalar_signatures():
    """Regression (ISSUE 6 bugfix): equal constant args never force a
    retrace, whatever numeric wrapper they arrive in — and the scalar
    is actually applied in the replay."""
    net = _ScaledDense(3)
    net.initialize(mx.init.One())
    net.hybridize()
    x = nd.array(np.ones((2, 4), "float32"))
    base = net(x, 1.0).asnumpy()
    for v in (2.0, np.float32(2.0), np.float64(2.0),
              np.array(2.0)):
        out = net(x, v).asnumpy()
        np.testing.assert_allclose(out, base * 2.0, rtol=1e-6)
    st = net._cached_op.stats()
    assert st["traces"] == 2, \
        f"equal scalars retraced: {st}"           # 1.0 and 2.0 only
    net(x, 2)                                     # int is a new class
    assert net._cached_op.stats()["traces"] == 3


def test_cachedop_capacity_lru():
    net = _mlp()
    rs = np.random.RandomState(0)
    net(nd.array(rs.rand(2, 8).astype("float32")))   # settle shapes
    co = CachedOp(net, capacity=2)
    for b in (2, 3, 4, 5):
        co(nd.array(rs.rand(b, 8).astype("float32")))
    assert len(co._entries) == 2
    assert co.misses == 4


def test_cachedop_unhashable_arg_falls_back():
    class Weird(mx.gluon.HybridBlock):
        def hybrid_forward(self, F, x, cfg):
            return x * (cfg["k"] if isinstance(cfg, dict) else cfg)

    net = Weird()
    net.initialize()
    net.hybridize()
    x = nd.array(np.ones((2, 2), "float32"))
    out = net(x, {"k": 3.0})
    np.testing.assert_allclose(out.asnumpy(), 3.0 * np.ones((2, 2)))
    assert net._cache_fallback        # warned once
    # the fallback is per-call: a keyable call still hits the cache
    out2 = net(x, 2.0)
    np.testing.assert_allclose(out2.asnumpy(), 2.0 * np.ones((2, 2)))
    assert net._cached_op.stats()["misses"] == 1
    out3 = net(x, {"k": 4.0})         # unsupported again: eager
    np.testing.assert_allclose(out3.asnumpy(), 4.0 * np.ones((2, 2)))
    assert net._cached_op.stats()["misses"] == 1


def test_cachedop_graph_mode_engages_and_matches_eager(monkeypatch):
    monkeypatch.setenv("MXTPU_GRAPH_OPT", "2")
    net = _mlp()
    x = nd.array(np.random.RandomState(2).rand(3, 8)
                 .astype("float32"))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    assert net._cached_op.stats()["modes"] == ["graph"]
    np.testing.assert_allclose(eager, hybrid, rtol=1e-5, atol=1e-6)


def test_cachedop_dropout_block_uses_jit_mode():
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=8))
    net.add(nn.Dropout(0.5))
    net.initialize()
    net.hybridize()
    x = nd.array(np.random.RandomState(0).rand(2, 8)
                 .astype("float32"))
    with autograd.record():
        net(x)
    assert net._cached_op.stats()["modes"] == ["jit"]


def test_cachedop_gradients_through_replay():
    net = _mlp()
    net.hybridize()
    x = nd.array(np.random.RandomState(5).rand(4, 8)
                 .astype("float32"))
    with autograd.record():
        y = net(x).sum()
    y.backward()
    g_hybrid = {k: p.grad().asnumpy().copy()
                for k, p in net.collect_params().items()}
    net.hybridize(active=False)         # same params, eager path
    with autograd.record():
        y2 = net(x).sum()
    y2.backward()
    for k, p in net.collect_params().items():
        np.testing.assert_allclose(g_hybrid[k], p.grad().asnumpy(),
                                   rtol=1e-5, atol=1e-6)


def test_symbolblock_forward_caches_graph_fn():
    x = sym.Variable("sbx")
    y = sym.tanh(sym.FullyConnected(x, num_hidden=3, name="sbfc"))
    blk = mx.gluon.SymbolBlock(
        y, x, params={"sbfc_weight": nd.array(np.ones((3, 4), "f")),
                      "sbfc_bias": nd.array(np.zeros(3, "f"))})
    v = nd.array(np.ones((2, 4), "float32"))
    blk(v)
    first = blk._graph_fn
    blk(v)
    assert blk._graph_fn is first and first is not None


# ---------------------------------------------------------- lint rule
def test_lint_graph_mutation_rule(tmp_path):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "lint", os.path.join(REPO, "ci", "lint.py"))
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    d = tmp_path / "incubator_mxnet_tpu" / "module"
    d.mkdir(parents=True)
    f = d / "x.py"
    f.write_text(
        "from incubator_mxnet_tpu.symbol.symbol import _Node\n"
        "def rewrite(node, other):\n"
        "    node.inputs = [(other, 0)]\n"
        "    node.attrs['k'] = 'v'\n"
        "    node.inputs.append((other, 1))\n")
    problems = lint.check_file(f)
    assert sum("pass pipeline" in p for p in problems) >= 4
    # escape hatch + self-attributes stay clean
    f.write_text(
        "def rewrite(node, other):\n"
        "    node.inputs = [(other, 0)]  # graph-ok: test fixture\n"
        "class T:\n"
        "    def __init__(self, inputs):\n"
        "        self.inputs = inputs\n"
        "        self.op = None\n")
    assert not any("pass pipeline" in p for p in lint.check_file(f))
    # inside graph/ the rule does not apply
    g = tmp_path / "incubator_mxnet_tpu" / "graph"
    g.mkdir()
    f2 = g / "y.py"
    f2.write_text("def rewrite(node, e):\n    node.inputs = [e]\n")
    assert not any("pass pipeline" in p for p in lint.check_file(f2))
