"""Remote data-service ranks (docs/data_service.md "Remote ranks"):
shared-transport façade, loopback mixed-placement bit-identity vs
all-local, credit-based backpressure bound, garbled-frame link
poisoning, SIGKILL-host failover chaos with no leaked shm/sockets,
state_dict placement independence, fault-grammar units, and the
lint/launch plumbing."""
import io as _pyio
import os
import pickle
import socket
import subprocess
import sys
import time
import warnings

import numpy as np
import pytest

from incubator_mxnet_tpu import recordio as rio
from incubator_mxnet_tpu import resilience as rz
from incubator_mxnet_tpu import rpc as mx_rpc
from incubator_mxnet_tpu.data_service import DataServiceIter
from incubator_mxnet_tpu.data_service.net import (RemoteShard,
                                                  RemoteShardDown,
                                                  RemoteShardServer)
from incubator_mxnet_tpu.data_service.worker import build_decode_spec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SHAPE = (3, 48, 48)
B = 8


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("MXTPU_FAULT_SPEC", raising=False)
    monkeypatch.delenv("MXTPU_DATA_REMOTE_ADDRS", raising=False)
    rz.reset_faults()
    yield
    rz.reset_faults()


def _make_jpeg_rec(prefix, n, edge=64):
    from PIL import Image
    rec = rio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    rs = np.random.RandomState(3)
    for i in range(n):
        gx = np.linspace(0, 255, edge, dtype=np.float32)
        img = (gx[None, :, None] * 0.4 + gx[:, None, None] * 0.4
               + rs.rand(edge, edge, 3) * 50).astype(np.uint8)
        buf = _pyio.BytesIO()
        Image.fromarray(img).save(buf, format="JPEG", quality=85)
        rec.write_idx(i, rio.pack(
            rio.IRHeader(0, float(i % 7), i, 0), buf.getvalue()))
    rec.close()
    return prefix


@pytest.fixture(scope="module")
def rec48(tmp_path_factory):
    td = tmp_path_factory.mktemp("dsn48")
    return _make_jpeg_rec(str(td / "ds"), 48)


@pytest.fixture(scope="module")
def rec44(tmp_path_factory):
    """Partial tail batch (pad 4 under round_batch)."""
    td = tmp_path_factory.mktemp("dsn44")
    return _make_jpeg_rec(str(td / "ds"), 44)


def _service(prefix, W, **kw):
    kw.setdefault("preprocess_threads", 2)
    return DataServiceIter(
        path_imgrec=prefix + ".rec", data_shape=SHAPE, batch_size=B,
        num_workers=W, round_batch=True, **kw)


def _np_batches(it):
    out = []
    for b in it:
        out.append((b.data[0].asnumpy().copy(),
                    b.label[0].asnumpy().copy(), b.pad))
    return out


def _assert_same(got, ref, what=""):
    assert len(got) == len(ref), (what, len(got), len(ref))
    for i, ((d, l, p), (rd, rl, rp)) in enumerate(zip(got, ref)):
        assert p == rp, (what, i, p, rp)
        assert np.array_equal(d, rd), f"{what}: batch {i} data differs"
        assert np.array_equal(l, rl), f"{what}: batch {i} label differs"


def _shm_orphans():
    return [f for f in os.listdir("/dev/shm")
            if f.startswith("mxtpu_ds")]


def _wait_shm_clean(deadline_s=10.0):
    """The resource tracker unlinks a SIGKILLed server's segments
    asynchronously — poll with a deadline instead of asserting an
    instant."""
    deadline = time.monotonic() + deadline_s
    while _shm_orphans() and time.monotonic() < deadline:
        time.sleep(0.1)
    return _shm_orphans()


@pytest.fixture
def loopback(rec48):
    """In-process RemoteShardServer on an ephemeral loopback port."""
    srv = RemoteShardServer(host="127.0.0.1", port=0,
                            max_shards=2).start()
    yield f"127.0.0.1:{srv.port}"
    srv.close()


# ----------------------------------------------- shared RPC façade
def test_serving_rpc_is_a_facade_over_shared_transport():
    from incubator_mxnet_tpu.serving import rpc as srv_rpc
    for name in ("RpcClient", "RpcServer", "RpcError",
                 "RpcFrameError", "RpcTimeoutError", "send_frame",
                 "recv_frame", "encode_frame", "default_timeout",
                 "MAGIC", "MAX_FRAME_BYTES"):
        assert getattr(srv_rpc, name) is getattr(mx_rpc, name), name
    # serving default scope is unchanged by the extraction
    assert mx_rpc.DEFAULT_FAULT_SCOPE == ("router", "net")


def test_send_frame_fault_scope_none_bypasses_injection(monkeypatch):
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "router:net:*:error")
    rz.reset_faults()
    a, b = socket.socketpair()
    try:
        # scope None: the spec must not fire (control-frame path)
        mx_rpc.send_frame(a, {"op": "x"}, fault_scope=None)
        msg, _ = mx_rpc.recv_frame(b, timeout=2.0)
        assert msg == {"op": "x"}
        with pytest.raises(mx_rpc.RpcError):
            mx_rpc.send_frame(a, {"op": "y"})   # default scope fires
    finally:
        a.close()
        b.close()


# ------------------------------------------------ loopback identity
def test_mixed_remote_bit_identical_two_epochs(rec48, loopback):
    with _service(rec48, 3) as local:
        ref = _np_batches(local)
        local.reset()
        ref += _np_batches(local)
    with _service(rec48, 3, remote_addrs=[loopback]) as mixed:
        got = _np_batches(mixed)
        mixed.reset()
        got += _np_batches(mixed)
        st = mixed.stats()
        assert st["remote_shards"] == 1
        assert st["shards"][2]["remote"] == loopback
        assert st["shards"][0]["remote"] is None
        assert st["restarts"] == 0
    assert len(ref) == 12
    _assert_same(got, ref, "mixed vs local, 2 epochs")
    assert not _wait_shm_clean()


def test_remote_pad_tail_bit_identical(rec44, loopback):
    with _service(rec44, 3) as local:
        ref = _np_batches(local)
    with _service(rec44, 3, remote_addrs=[loopback]) as mixed:
        got = _np_batches(mixed)
    assert ref[-1][2] == 4      # pad rides the wire header verbatim
    _assert_same(got, ref, "pad tail")


def test_all_remote_via_env_var(rec48, loopback, monkeypatch):
    """MXTPU_DATA_REMOTE_ADDRS homes EVERY shard remotely (two
    streams on one server) with the same delivered stream."""
    with _service(rec48, 2) as local:
        ref = _np_batches(local)
    monkeypatch.setenv("MXTPU_DATA_REMOTE_ADDRS",
                       f"{loopback},{loopback}")
    with _service(rec48, 2) as svc:
        got = _np_batches(svc)
        assert svc.stats()["remote_shards"] == 2
    _assert_same(got, ref, "all-remote vs all-local")


def test_state_dict_roundtrip_mixed_to_local(rec48, loopback):
    """A position saved under mixed placement restores into an
    all-local service: state is placement-independent."""
    with _service(rec48, 3) as local:
        ref = _np_batches(local)
    with _service(rec48, 3, remote_addrs=[loopback]) as mixed:
        head = [next(mixed) for _ in range(2)]
        for b, (rd, rl, rp) in zip(head, ref[:2]):
            assert np.array_equal(b.data[0].asnumpy(), rd)
        state = pickle.loads(pickle.dumps(mixed.state_dict()))
    with _service(rec48, 3) as svc2:
        svc2.load_state_dict(state)
        svc2.reset()
        tail = _np_batches(svc2)
    _assert_same(tail, ref[2:], "resume mixed -> all-local")


# ----------------------------------------------------- backpressure
def _epoch_msg(prefix, credits, shard=0, num_shards=1):
    order = list(range(48))
    return {"op": "epoch", "shard": shard, "stream": 1,
            "credits": credits,
            "static": {"path_imgrec": prefix + ".rec",
                       "idx_path": prefix + ".idx",
                       "shard": shard, "num_shards": num_shards,
                       "batch_size": B, "label_width": 1,
                       "round_batch": True,
                       "decode": build_decode_spec(SHAPE),
                       "ring_depth": 4},
            "cmd": {"order": order, "num_batches": 6,
                    "start_event": 0, "start_batch": 0,
                    "start_bad": 0, "seed": 0}}


def _drain_frames(cli, want, wait_s):
    """Collect batch frames until ``want`` arrive or ``wait_s``
    passes; heartbeats/pongs don't count."""
    got = []
    deadline = time.monotonic() + wait_s
    while len(got) < want and time.monotonic() < deadline:
        try:
            msg, _ = cli.recv(timeout=0.2)
        except mx_rpc.RpcTimeoutError:
            continue
        if msg.get("op") == "batch":
            got.append(msg)
    return got


def test_credit_backpressure_bounds_inflight_frames(rec48):
    """The server may send at most ``credits`` batch frames ahead of
    grants — the ring's semaphore contract, extended over the wire."""
    srv = RemoteShardServer(host="127.0.0.1", port=0,
                            max_shards=1).start()
    cli = mx_rpc.RpcClient("127.0.0.1", srv.port, fault_scope=None)
    try:
        cli.connect(timeout=5.0)
        cli.send(_epoch_msg(rec48, credits=2))
        first = _drain_frames(cli, want=6, wait_s=3.0)
        # exactly the granted 2 in flight, no matter how long we wait
        assert len(first) == 2, [m.get("op") for m in first]
        cli.send({"op": "credit", "shard": 0, "n": 2})
        more = _drain_frames(cli, want=6, wait_s=3.0)
        assert len(more) == 2
        seqs = [m["seq"] for m in first + more]
        assert seqs == [0, 1, 2, 3]     # in order, none lost
        # grant the rest: 2 remaining DATA batches + the END marker
        cli.send({"op": "credit", "shard": 0, "n": 10})
        tail = _drain_frames(cli, want=3, wait_s=5.0)
        assert [m["kind"] for m in tail] == [1, 1, 2]
    finally:
        cli.close()
        srv.close()


# -------------------------------------------------- fault injection
def test_garbled_frame_poisons_one_link_only(rec48, monkeypatch):
    """data_service:net corrupt: the CRC check rejects the frame,
    that connection dies, the shard reconnects at its cursors —
    bit-identical stream, one restart charged, shard stays remote."""
    with _service(rec48, 3) as local:
        ref = _np_batches(local)
    srv = RemoteShardServer(host="127.0.0.1", port=0,
                            max_shards=2).start()
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "data_service:net:2:corrupt")
    rz.reset_faults()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        with _service(rec48, 3,
                      remote_addrs=[f"127.0.0.1:{srv.port}"]) as svc:
            got = _np_batches(svc)
            st = svc.stats()
    srv.close()
    assert st["restarts"] == 1
    assert st["remote_shards"] == 1      # reconnected, not demoted
    _assert_same(got, ref, "garbled frame")
    assert not _wait_shm_clean()


def test_host_kill_chaos_demotes_and_stays_bit_identical(
        rec48, tmp_path, monkeypatch):
    """data_service:host kill: the server process hard-exits before
    its nth batch frame (the CLI entrypoint, a real subprocess).  The
    shard re-homes to a local worker at its cursors; the epoch stays
    bit-identical; no shm segment or socket survives."""
    with _service(rec48, 3) as local:
        ref = _np_batches(local)
    pf = str(tmp_path / "port")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MXTPU_FAULT_SPEC="data_service:host:2:kill")
    env.setdefault("PYTHONPATH", REPO)
    proc = subprocess.Popen(
        [sys.executable, "-m",
         "incubator_mxnet_tpu.data_service.net",
         "--port-file", pf, "--shards", "1"],
        env=env, stderr=subprocess.PIPE)
    try:
        deadline = time.monotonic() + 30
        while not os.path.exists(pf) \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        assert os.path.exists(pf), "server never wrote its port file"
        port = int(open(pf).read())
        monkeypatch.setenv("MXTPU_DATA_HOST_GRACE", "3")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            with _service(rec48, 3,
                          remote_addrs=[f"127.0.0.1:{port}"]) as svc:
                got = _np_batches(svc)
                st = svc.stats()
        assert st["restarts"] >= 1
        assert st["remote_shards"] == 0          # demoted to local
        _assert_same(got, ref, "host kill mid-epoch")
        err = proc.stderr.read().decode()
        assert "MXTPU_KILLED injected data_service:host kill" in err
        assert proc.wait(timeout=10) != 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
        proc.stderr.close()
    # the SIGKILLed host's decode worker dies via PDEATHSIG and the
    # resource tracker unlinks its ring segment: nothing may survive
    assert not _wait_shm_clean()


def test_unreachable_remote_falls_back_to_local(rec48, monkeypatch):
    """A dead addr at epoch start burns one restart and re-homes the
    shard locally — the job degrades, it does not die."""
    with _service(rec48, 2) as local:
        ref = _np_batches(local)
    monkeypatch.setenv("MXTPU_DATA_HOST_GRACE", "1")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        with _service(rec48, 2,
                      remote_addrs=["127.0.0.1:1"]) as svc:
            got = _np_batches(svc)
            st = svc.stats()
    assert st["restarts"] == 1 and st["remote_shards"] == 0
    _assert_same(got, ref, "unreachable remote")


def test_fault_grammar_accepts_data_service_scopes():
    assert rz.parse_fault_spec("data_service:net:2:corrupt") == \
        [("data_service", "net", 2, "corrupt")]
    assert rz.parse_fault_spec("data_service:host:1:kill") == \
        [("data_service", "host", 1, "kill")]
    with pytest.raises(ValueError):
        rz.parse_fault_spec("record:read:1:kill")
    with pytest.raises(ValueError):
        rz.parse_fault_spec("elastic:rank0:1:corrupt")


# ---------------------------------------------------------- lint
def _load_lint():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "lint", os.path.join(REPO, "ci", "lint.py"))
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    return lint


def test_lint_socket_wait_rule_covers_net_module(tmp_path):
    lint = _load_lint()
    d = tmp_path / "incubator_mxnet_tpu" / "data_service"
    d.mkdir(parents=True)
    f = d / "net.py"
    f.write_text("def f(sock):\n    return sock.recv(4)\n")
    assert any("unbounded socket" in p for p in lint.check_file(f))
    f.write_text("def f(cli):\n    return cli.recv(timeout=0.2)\n")
    assert not any("unbounded socket" in p
                   for p in lint.check_file(f))
    # wall-clock time is banned in the failover timing logic too
    f.write_text("import time\n\ndef f():\n    return time.time()\n")
    assert any("time.time" in p for p in lint.check_file(f))


# -------------------------------------------------------- launch
def _load_launch():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "launch", os.path.join(REPO, "tools", "launch.py"))
    launch = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(launch)
    return launch


def _fleet_args(launch, **kw):
    import argparse
    kw.setdefault("port", 29500)
    kw.setdefault("env", [])
    kw.setdefault("heartbeat_interval", 2.0)
    kw.setdefault("heartbeat_timeout", 0.0)
    kw.setdefault("max_restarts", 0)
    kw.setdefault("ssh_cmd", "ssh")
    return argparse.Namespace(**kw)


def test_launch_data_fleet_addrs_and_slots():
    launch = _load_launch()
    args = _fleet_args(launch)
    fleet = launch._DataFleet(args, [("h1", 2), ("h2", 1)], None)
    # one stream per slot, fixed ports derived from --port, stable
    # across respawns (the exported value must outlive any server)
    assert fleet.addrs() == "h1:30500,h1:30500,h2:30501"
    img_s, restarts, healthy, total = fleet.telemetry()
    assert (img_s, restarts, healthy, total) == (0.0, 0, 0, 2)
    lines = fleet.report_lines()
    assert len(lines) == 2 and "down" in lines[0]


def test_launch_status_line_shows_data_fleet():
    launch = _load_launch()
    agg = launch._aggregate_telemetry({})
    agg["data_fleet"] = (1234.0, 1, 1, 2)
    line = launch._format_status(agg)
    assert "remote data: 1/2 host(s) 1234 img/s restarts=1" in line


def test_launch_error_counters_include_net_restarts():
    launch = _load_launch()
    assert "data_service_net_restarts_total" in launch._ERROR_COUNTERS
