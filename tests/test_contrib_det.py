"""Detection/contrib op family vs numpy oracles (ref semantics:
src/operator/contrib/multibox_*.cc, roi_pooling.cc, proposal.cc,
psroi_pooling.cu, deformable_convolution-inl.h)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd


def _np_iou(a, b):
    tlx = np.maximum(a[0], b[0]); tly = np.maximum(a[1], b[1])
    brx = np.minimum(a[2], b[2]); bry = np.minimum(a[3], b[3])
    i = max(brx - tlx, 0.0) * max(bry - tly, 0.0)
    u = ((a[2] - a[0]) * (a[3] - a[1])
         + (b[2] - b[0]) * (b[3] - b[1]) - i)
    return 0.0 if u <= 0 else i / u


# ---------------------------------------------------------------- prior
def np_multibox_prior(h, w, sizes, ratios, clip, steps, offsets):
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w
    out = []
    for r in range(h):
        cy = (r + offsets[0]) * step_y
        for c in range(w):
            cx = (c + offsets[1]) * step_x
            for s in sizes:
                ww = s * h / w / 2; hh = s / 2
                out.append([cx - ww, cy - hh, cx + ww, cy + hh])
            for j in range(1, len(ratios)):
                rt = np.sqrt(ratios[j])
                ww = sizes[0] * h / w * rt / 2
                hh = sizes[0] / rt / 2
                out.append([cx - ww, cy - hh, cx + ww, cy + hh])
    out = np.array(out, np.float32)
    if clip:
        out = np.clip(out, 0, 1)
    return out[None]


@pytest.mark.parametrize("sizes,ratios,clip,steps", [
    ((0.5,), (1.0,), False, (-1.0, -1.0)),
    ((0.3, 0.6), (1.0, 2.0, 0.5), True, (-1.0, -1.0)),
    ((0.4,), (1.0, 3.0), False, (0.1, 0.2)),
])
def test_multibox_prior(sizes, ratios, clip, steps):
    x = nd.zeros((2, 8, 5, 7))
    out = nd._internal._contrib_MultiBoxPrior(
        x, sizes=sizes, ratios=ratios, clip=clip, steps=steps)
    ref = np_multibox_prior(5, 7, sizes, ratios, clip, steps, (0.5, 0.5))
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-5, atol=1e-6)


def test_contrib_namespace():
    x = nd.zeros((1, 3, 4, 4))
    out = mx.contrib.nd.MultiBoxPrior(x, sizes=(0.5,))
    assert out.shape == (1, 16, 4)


# ---------------------------------------------------------------- target
def np_multibox_target(anchors, labels, cls_preds, overlap_threshold,
                       ignore_label, neg_ratio, neg_thresh, variances):
    """Literal port of MultiBoxTargetForward (multibox_target.cc)."""
    B, L, LW = labels.shape
    A = anchors.shape[0]
    loc_t = np.zeros((B, A * 4), np.float32)
    loc_m = np.zeros((B, A * 4), np.float32)
    cls_t = np.full((B, A), ignore_label, np.float32)
    for b in range(B):
        lab = labels[b]
        nv = 0
        for i in range(L):
            if lab[i, 0] == -1:
                break
            nv += 1
        if nv == 0:
            continue
        ious = np.zeros((A, nv))
        for j in range(A):
            for k in range(nv):
                ious[j, k] = _np_iou(anchors[j], lab[k, 1:5])
        gt_flags = [False] * nv
        anchor_flags = [-1] * A
        matches = [(-1.0, -1)] * A
        while not all(gt_flags):
            best_a = best_g = -1
            best = 1e-6
            for j in range(A):
                if anchor_flags[j] == 1:
                    continue
                for k in range(nv):
                    if gt_flags[k]:
                        continue
                    if ious[j, k] > best:
                        best, best_a, best_g = ious[j, k], j, k
            if best_a == -1:
                break
            matches[best_a] = (best, best_g)
            gt_flags[best_g] = True
            anchor_flags[best_a] = 1
        if overlap_threshold > 0:
            for j in range(A):
                if anchor_flags[j] == 1:
                    continue
                bg, bi = -1, -1.0
                for k in range(nv):
                    if ious[j, k] > bi:
                        bi, bg = ious[j, k], k
                if bg != -1:
                    matches[j] = (bi, bg)
                    if bi > overlap_threshold:
                        anchor_flags[j] = 1
        num_pos = sum(1 for f in anchor_flags if f == 1)
        if neg_ratio > 0:
            num_neg = min(int(num_pos * neg_ratio), A - num_pos)
            if num_neg > 0:
                cand = []
                for j in range(A):
                    if anchor_flags[j] == 1:
                        continue
                    if matches[j][0] < neg_thresh and anchor_flags[j] == -1:
                        p = cls_preds[b, :, j]
                        e = np.exp(p - p.max())
                        prob = e[0] / e.sum()
                        cand.append((-prob, j))
                # SortElemDescend on value=-prob: descending -prob ==
                # ascending background prob (hardest negatives first)
                cand.sort(key=lambda t: -t[0])
                for _, j in cand[:num_neg]:
                    anchor_flags[j] = 0
        else:
            for j in range(A):
                if anchor_flags[j] != 1:
                    anchor_flags[j] = 0
        for i in range(A):
            if anchor_flags[i] == 1:
                g = matches[i][1]
                cls_t[b, i] = lab[g, 0] + 1
                loc_m[b, i * 4:i * 4 + 4] = 1
                al, at, ar, ab_ = anchors[i]
                aw, ah = ar - al, ab_ - at
                ax, ay = (al + ar) / 2, (at + ab_) / 2
                gl, gt_, gr, gb = lab[g, 1:5]
                gw, gh = gr - gl, gb - gt_
                gx, gy = (gl + gr) / 2, (gt_ + gb) / 2
                loc_t[b, i * 4 + 0] = (gx - ax) / aw / variances[0]
                loc_t[b, i * 4 + 1] = (gy - ay) / ah / variances[1]
                loc_t[b, i * 4 + 2] = np.log(gw / aw) / variances[2]
                loc_t[b, i * 4 + 3] = np.log(gh / ah) / variances[3]
            elif anchor_flags[i] == 0:
                cls_t[b, i] = 0
    return loc_t, loc_m, cls_t


def _target_fixture(seed=0, B=2, L=4, A=20, C=3):
    rs = np.random.RandomState(seed)
    anchors = np.zeros((A, 4), np.float32)
    ctr = rs.rand(A, 2) * 0.8 + 0.1
    wh = rs.rand(A, 2) * 0.3 + 0.05
    anchors[:, :2] = ctr - wh / 2
    anchors[:, 2:] = ctr + wh / 2
    labels = -np.ones((B, L, 5), np.float32)
    for b in range(B):
        n = rs.randint(1, L)
        for i in range(n):
            c = rs.randint(0, C - 1)
            x1, y1 = rs.rand(2) * 0.5
            w, h = rs.rand(2) * 0.4 + 0.1
            labels[b, i] = [c, x1, y1, x1 + w, y1 + h]
    cls_preds = rs.randn(B, C, A).astype(np.float32)
    return anchors, labels, cls_preds


@pytest.mark.parametrize("neg_ratio", [-1.0, 2.0])
def test_multibox_target(neg_ratio):
    anchors, labels, cls_preds = _target_fixture()
    var = (0.1, 0.1, 0.2, 0.2)
    outs = nd._internal._contrib_MultiBoxTarget(
        nd.array(anchors[None]), nd.array(labels), nd.array(cls_preds),
        overlap_threshold=0.5, negative_mining_ratio=neg_ratio,
        negative_mining_thresh=0.5, variances=var)
    ref = np_multibox_target(anchors, labels, cls_preds, 0.5, -1.0,
                             neg_ratio, 0.5, var)
    for got, want in zip(outs, ref):
        np.testing.assert_allclose(got.asnumpy(), want, rtol=1e-4,
                                   atol=1e-5)


def test_multibox_target_no_gt():
    anchors, labels, cls_preds = _target_fixture()
    labels[:] = -1
    loc_t, loc_m, cls_t = nd._internal._contrib_MultiBoxTarget(
        nd.array(anchors[None]), nd.array(labels), nd.array(cls_preds))
    assert np.all(loc_t.asnumpy() == 0)
    assert np.all(loc_m.asnumpy() == 0)
    assert np.all(cls_t.asnumpy() == -1)


# ------------------------------------------------------------- detection
def np_multibox_detection(cls_prob, loc_pred, anchors, threshold,
                          clip, variances, nms_threshold,
                          force_suppress, nms_topk):
    """Literal port of MultiBoxDetectionForward."""
    B, C, A = cls_prob.shape
    out = -np.ones((B, A, 6), np.float32)
    for b in range(B):
        rows = []
        for i in range(A):
            score, cid = -1.0, 0
            for j in range(1, C):
                if cls_prob[b, j, i] > score:
                    score, cid = cls_prob[b, j, i], j
            if cid > 0 and score < threshold:
                cid = 0
            if cid > 0:
                al, at, ar, ab_ = anchors[i]
                aw, ah = ar - al, ab_ - at
                ax, ay = (al + ar) / 2, (at + ab_) / 2
                p = loc_pred[b, i * 4:i * 4 + 4]
                ox = p[0] * variances[0] * aw + ax
                oy = p[1] * variances[1] * ah + ay
                ow = np.exp(p[2] * variances[2]) * aw / 2
                oh = np.exp(p[3] * variances[3]) * ah / 2
                box = [ox - ow, oy - oh, ox + ow, oy + oh]
                if clip:
                    box = [min(max(v, 0.0), 1.0) for v in box]
                rows.append([cid - 1.0, score] + box)
        rows.sort(key=lambda r: -r[1])
        nkeep = len(rows) if nms_topk <= 0 else min(nms_topk, len(rows))
        rows = rows[:nkeep]
        for i in range(len(rows)):
            if rows[i][0] < 0:
                continue
            for j in range(i + 1, len(rows)):
                if rows[j][0] < 0:
                    continue
                if force_suppress or rows[i][0] == rows[j][0]:
                    iou = _np_iou(rows[i][2:], rows[j][2:])
                    if iou >= nms_threshold:
                        rows[j][0] = -1
        for i, r in enumerate(rows):
            out[b, i] = r
    return out


@pytest.mark.parametrize("force", [False, True])
def test_multibox_detection(force):
    rs = np.random.RandomState(3)
    B, C, A = 2, 4, 12
    anchors, _, _ = _target_fixture(A=A)
    cls_prob = rs.rand(B, C, A).astype(np.float32)
    cls_prob /= cls_prob.sum(axis=1, keepdims=True)
    loc_pred = (rs.randn(B, A * 4) * 0.3).astype(np.float32)
    var = (0.1, 0.1, 0.2, 0.2)
    got = nd._internal._contrib_MultiBoxDetection(
        nd.array(cls_prob), nd.array(loc_pred), nd.array(anchors[None]),
        threshold=0.1, nms_threshold=0.45, force_suppress=force,
        variances=var).asnumpy()
    want = np_multibox_detection(cls_prob, loc_pred, anchors, 0.1, True,
                                 var, 0.45, force, -1)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_multibox_detection_topk():
    """nms_topk windows the NMS; rows past the window are suppressed."""
    rs = np.random.RandomState(31)
    B, C, A = 1, 3, 10
    anchors, _, _ = _target_fixture(A=A)
    cls_prob = rs.rand(B, C, A).astype(np.float32)
    cls_prob /= cls_prob.sum(axis=1, keepdims=True)
    loc_pred = (rs.randn(B, A * 4) * 0.2).astype(np.float32)
    got = nd._internal._contrib_MultiBoxDetection(
        nd.array(cls_prob), nd.array(loc_pred), nd.array(anchors[None]),
        threshold=0.1, nms_threshold=0.45, nms_topk=4).asnumpy()
    want = np_multibox_detection(cls_prob, loc_pred, anchors, 0.1, True,
                                 (0.1, 0.1, 0.2, 0.2), 0.45, False, 4)
    # rows 0..3 must match the oracle exactly; later rows suppressed
    np.testing.assert_allclose(got[:, :4], want[:, :4], rtol=1e-4,
                               atol=1e-5)
    assert np.all(got[:, 4:, 0] == -1)


# ------------------------------------------------------------ roipooling
def np_roi_pooling(data, rois, pooled, scale):
    R = rois.shape[0]
    B, C, H, W = data.shape
    ph, pw = pooled
    out = np.zeros((R, C, ph, pw), np.float32)
    for n in range(R):
        b = int(rois[n, 0])
        x1 = int(round(rois[n, 1] * scale))
        y1 = int(round(rois[n, 2] * scale))
        x2 = int(round(rois[n, 3] * scale))
        y2 = int(round(rois[n, 4] * scale))
        rh, rw = max(y2 - y1 + 1, 1), max(x2 - x1 + 1, 1)
        bh, bw = rh / ph, rw / pw
        for c in range(C):
            for i in range(ph):
                for j in range(pw):
                    hs = min(max(int(np.floor(i * bh)) + y1, 0), H)
                    he = min(max(int(np.ceil((i + 1) * bh)) + y1, 0), H)
                    ws = min(max(int(np.floor(j * bw)) + x1, 0), W)
                    we = min(max(int(np.ceil((j + 1) * bw)) + x1, 0), W)
                    if he <= hs or we <= ws:
                        out[n, c, i, j] = 0
                    else:
                        out[n, c, i, j] = data[b, c, hs:he, ws:we].max()
    return out


def test_roi_pooling():
    rs = np.random.RandomState(5)
    data = rs.randn(2, 3, 12, 16).astype(np.float32)
    rois = np.array([[0, 0, 0, 7, 5], [1, 2, 2, 15, 11],
                     [0, 4, 1, 6, 3], [1, 13, 9, 15, 11]], np.float32)
    got = nd.ROIPooling(nd.array(data), nd.array(rois),
                        pooled_size=(3, 3), spatial_scale=1.0).asnumpy()
    want = np_roi_pooling(data, rois, (3, 3), 1.0)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_roi_pooling_scale_and_grad():
    import jax
    rs = np.random.RandomState(7)
    data = rs.randn(1, 2, 8, 8).astype(np.float32)
    rois = np.array([[0, 0, 0, 15, 15]], np.float32)
    got = nd.ROIPooling(nd.array(data), nd.array(rois),
                        pooled_size=(2, 2), spatial_scale=0.5).asnumpy()
    want = np_roi_pooling(data, rois, (2, 2), 0.5)
    np.testing.assert_allclose(got, want, rtol=1e-5)
    # gradient flows to max positions
    from incubator_mxnet_tpu import autograd
    x = nd.array(data)
    x.attach_grad()
    with autograd.record():
        y = nd.ROIPooling(x, nd.array(rois), pooled_size=(2, 2),
                          spatial_scale=0.5)
    y.backward()
    g = x.grad.asnumpy()
    assert g.sum() > 0  # some gradient reached the input
    assert (g != 0).sum() <= 2 * 4  # at most one position per bin


# ---------------------------------------------------------- psroipooling
def np_psroi_pooling(data, rois, scale, od, p, g):
    B, C, H, W = data.shape
    R = rois.shape[0]
    out = np.zeros((R, od, p, p), np.float32)
    for n in range(R):
        b = int(rois[n, 0])
        x1 = round(rois[n, 1]) * scale
        y1 = round(rois[n, 2]) * scale
        x2 = (round(rois[n, 3]) + 1) * scale
        y2 = (round(rois[n, 4]) + 1) * scale
        rw = max(x2 - x1, 0.1); rh = max(y2 - y1, 0.1)
        bh, bw = rh / p, rw / p
        for ct in range(od):
            for i in range(p):
                for j in range(p):
                    hs = min(max(int(np.floor(i * bh + y1)), 0), H)
                    he = min(max(int(np.ceil((i + 1) * bh + y1)), 0), H)
                    ws = min(max(int(np.floor(j * bw + x1)), 0), W)
                    we = min(max(int(np.ceil((j + 1) * bw + x1)), 0), W)
                    gh = min(max(i * g // p, 0), g - 1)
                    gw = min(max(j * g // p, 0), g - 1)
                    c = (ct * g + gh) * g + gw
                    if he <= hs or we <= ws:
                        continue
                    region = data[b, c, hs:he, ws:we]
                    out[n, ct, i, j] = region.sum() / region.size
    return out


def test_psroi_pooling():
    rs = np.random.RandomState(11)
    od, g = 2, 3
    data = rs.randn(2, od * g * g, 9, 9).astype(np.float32)
    rois = np.array([[0, 1, 1, 6, 6], [1, 0, 2, 8, 7]], np.float32)
    got = nd._internal._contrib_PSROIPooling(
        nd.array(data), nd.array(rois), spatial_scale=1.0,
        output_dim=od, pooled_size=g, group_size=g).asnumpy()
    want = np_psroi_pooling(data, rois, 1.0, od, g, g)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


# -------------------------------------------------------------- proposal
def test_proposal_shapes_and_validity():
    rs = np.random.RandomState(13)
    K = 6  # 2 ratios x 3 scales
    H = W = 8
    cls_prob = rs.rand(1, 2 * K, H, W).astype(np.float32)
    bbox_pred = (rs.randn(1, 4 * K, H, W) * 0.1).astype(np.float32)
    im_info = np.array([[128.0, 128.0, 1.0]], np.float32)
    rois, scores = nd._internal._contrib_Proposal(
        nd.array(cls_prob), nd.array(bbox_pred), nd.array(im_info),
        rpn_pre_nms_top_n=50, rpn_post_nms_top_n=16, threshold=0.7,
        rpn_min_size=4, scales=(8.0, 16.0), ratios=(0.5, 1.0, 2.0),
        feature_stride=16, output_score=True)
    r = rois.asnumpy()
    assert r.shape == (16, 5)
    assert np.all(r[:, 0] == 0)
    assert np.all(r[:, 1] >= 0) and np.all(r[:, 3] <= 127.0)
    assert np.all(r[:, 1] <= r[:, 3]) and np.all(r[:, 2] <= r[:, 4])
    assert scores.asnumpy().shape == (16, 1)


def test_multi_proposal_batch():
    rs = np.random.RandomState(17)
    K, H, W, B = 3, 6, 6, 2
    cls_prob = rs.rand(B, 2 * K, H, W).astype(np.float32)
    bbox_pred = (rs.randn(B, 4 * K, H, W) * 0.1).astype(np.float32)
    im_info = np.array([[96.0, 96.0, 1.0]] * B, np.float32)
    rois = nd._internal._contrib_MultiProposal(
        nd.array(cls_prob), nd.array(bbox_pred), nd.array(im_info),
        rpn_pre_nms_top_n=30, rpn_post_nms_top_n=8,
        scales=(8.0,), ratios=(0.5, 1.0, 2.0)).asnumpy()
    assert rois.shape == (B * 8, 5)
    assert np.all(rois[:8, 0] == 0) and np.all(rois[8:, 0] == 1)


# ------------------------------------------------- deformable convolution
def test_deformable_conv_zero_offset_matches_conv():
    """With zero offsets, deformable conv == standard convolution."""
    rs = np.random.RandomState(19)
    B, C, H, W, O, k = 2, 4, 7, 7, 6, 3
    data = rs.randn(B, C, H, W).astype(np.float32)
    weight = (rs.randn(O, C, k, k) * 0.2).astype(np.float32)
    Ho = Wo = 7  # pad 1 stride 1
    offset = np.zeros((B, 2 * k * k, Ho, Wo), np.float32)
    got = nd._internal._contrib_DeformableConvolution(
        nd.array(data), nd.array(offset), nd.array(weight),
        kernel=(k, k), pad=(1, 1), num_filter=O, no_bias=True).asnumpy()
    want = nd.Convolution(nd.array(data), nd.array(weight),
                          kernel=(k, k), pad=(1, 1), num_filter=O,
                          no_bias=True).asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_deformable_conv_integer_shift():
    """Offset of exactly +1 in x equals convolving the shifted image."""
    rs = np.random.RandomState(23)
    B, C, H, W, O, k = 1, 2, 6, 6, 3, 1
    data = rs.randn(B, C, H, W).astype(np.float32)
    weight = rs.randn(O, C, 1, 1).astype(np.float32)
    offset = np.zeros((B, 2, H, W), np.float32)
    offset[:, 1] = 1.0  # shift +1 in x
    got = nd._internal._contrib_DeformableConvolution(
        nd.array(data), nd.array(offset), nd.array(weight),
        kernel=(1, 1), num_filter=O, no_bias=True).asnumpy()
    shifted = np.zeros_like(data)
    shifted[..., :-1] = data[..., 1:]
    want = np.einsum("oc,bchw->bohw", weight[:, :, 0, 0], shifted)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_deformable_conv_grad():
    from incubator_mxnet_tpu import autograd
    rs = np.random.RandomState(29)
    data = nd.array(rs.randn(1, 2, 5, 5).astype(np.float32))
    offset = nd.array((rs.randn(1, 2 * 9, 5, 5) * 0.1).astype(np.float32))
    weight = nd.array((rs.randn(4, 2, 3, 3) * 0.2).astype(np.float32))
    for v in (data, offset, weight):
        v.attach_grad()
    with autograd.record():
        out = nd._internal._contrib_DeformableConvolution(
            data, offset, weight, kernel=(3, 3), pad=(1, 1),
            num_filter=4, no_bias=True)
        loss = (out * out).sum()
    loss.backward()
    for v in (data, offset, weight):
        assert np.isfinite(v.grad.asnumpy()).all()
        assert np.abs(v.grad.asnumpy()).sum() > 0
