"""The shipped test_utils fixtures themselves (VERDICT r2 task 5)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import test_utils as tu


def _net():
    x = mx.sym.Variable("x")
    w = mx.sym.Variable("w")
    return mx.sym.FullyConnected(x, w, no_bias=True, num_hidden=4,
                                 name="fc")


def test_check_numeric_gradient_catches_good_and_bad():
    sym = mx.sym.tanh(mx.sym.Variable("x"))
    tu.check_numeric_gradient(sym, {"x": np.random.rand(3, 3)
                                    .astype(np.float32)})


def test_check_symbolic_forward_backward():
    rs = np.random.RandomState(0)
    x = rs.rand(2, 3).astype(np.float32)
    w = rs.rand(4, 3).astype(np.float32)
    sym = _net()
    tu.check_symbolic_forward(sym, {"x": x, "w": w}, [x @ w.T],
                              rtol=1e-4)
    og = rs.rand(2, 4).astype(np.float32)
    tu.check_symbolic_backward(sym, {"x": x, "w": w}, [og],
                               {"x": og @ w, "w": og.T @ x},
                               rtol=1e-4)


def test_check_consistency_dtypes():
    """fp32 vs bf16 vs fp16 runs of the same graph agree at relaxed
    tolerance — and the dtypes actually differ (round-3 review
    regression: specs used to be silently flattened to fp32)."""
    sym = _net()
    ctx_list = [
        dict(ctx=mx.cpu(), x=(2, 3), w=(4, 3)),
        dict(ctx=mx.cpu(), x=(2, 3), w=(4, 3),
             type_dict={"x": "bfloat16", "w": "bfloat16"}),
        dict(ctx=mx.cpu(), x=(2, 3), w=(4, 3),
             type_dict={"x": np.float16, "w": np.float16}),
    ]
    results = tu.check_consistency(sym, ctx_list)
    assert len(results) == 3


def test_check_consistency_lowprec_first_spec():
    """A low-precision entry listed first must still relax tolerance."""
    sym = _net()
    ctx_list = [
        dict(ctx=mx.cpu(), x=(2, 3), w=(4, 3),
             type_dict={"x": np.float16, "w": np.float16}),
        dict(ctx=mx.cpu(), x=(2, 3), w=(4, 3)),
    ]
    tu.check_consistency(sym, ctx_list)


def test_rand_ndarray_stypes():
    d = tu.rand_ndarray((4, 5))
    assert d.shape == (4, 5)
    c = tu.rand_ndarray((4, 5), stype="csr")
    assert c.stype == "csr" and not c.has_dense_mirror()
    r = tu.rand_ndarray((4, 5), stype="row_sparse")
    assert r.stype == "row_sparse" and not r.has_dense_mirror()


def test_assert_almost_equal_raises():
    with pytest.raises(AssertionError):
        tu.assert_almost_equal(np.ones(3), np.zeros(3))
