"""Fault-tolerant serving fleet (ISSUE 16; docs/serving.md "Fleet"):
CRC-framed RPC with poisoned-connection recovery, full-jitter
reconnect backoff, the circuit breaker's exactly-one-half-open-probe
contract, fleet admission, prefix-affinity routing, failover
re-dispatch with token-identical continuations, exactly-one-terminal
fleet-wide under router:replica kill + router:net garble chaos,
SIGTERM fleet drain with restorable per-replica snapshots, the
cross-process flight-recorder stitcher, and the launch.py
--serve-fleet / ci/lint.py socket-wait satellites."""
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import resilience as rz
from incubator_mxnet_tpu import telemetry, tracing
from incubator_mxnet_tpu.gluon.model_zoo.transformer import (
    TransformerLM)
from incubator_mxnet_tpu.serving import (
    EXPIRED, FINISHED, ServeRejectedError, ServingEngine)
from incubator_mxnet_tpu.serving import replica as replica_mod
from incubator_mxnet_tpu.serving import router as router_mod
from incubator_mxnet_tpu.serving import rpc
from incubator_mxnet_tpu.serving.router import ServingRouter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

VOCAB = 37


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("MXTPU_FAULT_SPEC", raising=False)
    rz.reset_faults()
    yield
    rz.reset_faults()


def _tiny(vocab=VOCAB, **kw):
    cfg = dict(d_model=32, n_layers=2, n_heads=4, max_len=64)
    cfg.update(kw)
    mx.random.seed(0)
    net = TransformerLM(vocab, **cfg)
    net.initialize(mx.initializer.Xavier())
    return net


_NET = None


def _shared_net():
    """One deterministic tiny LM for the module (seeded init; engines
    never mutate the model) — the same weights every replica process
    builds, which is what makes re-dispatch token-identical."""
    global _NET
    if _NET is None:
        _NET = _tiny()
    return _NET


def _gen_ref(net, prompt, max_new):
    out = net.generate(
        mx.nd.array(np.asarray([prompt], np.int32)), max_new)
    return [int(t) for t in out.asnumpy()[0]]


def _counter(name):
    return telemetry.get_registry().counter(name).value


def _start_replica(name, **engine_kw):
    """One in-process replica on an ephemeral port, engine loop on a
    daemon thread (kill-kind faults need the subprocess variant)."""
    srv = replica_mod.ReplicaServer(_shared_net(), name=name,
                                    port=0, **engine_kw)
    t = threading.Thread(target=srv.serve_forever, daemon=True,
                         name=f"replica-loop-{name}")
    t.start()
    return srv, t


# ---------------------------------------------------- backoff policy
def test_full_jitter_backoff_distribution():
    base, cap = 1.0, 8.0
    caps = [min(base * 2 ** i, cap) for i in range(6)]

    def mk(seed):
        return rz.RetryPolicy(max_retries=6, base_delay=base,
                              max_delay=cap, jitter=True, seed=seed)

    assert mk(7).delays() == mk(7).delays()     # seeded: reproducible
    for seed in range(40):
        for d, c in zip(mk(seed).delays(), caps):
            assert 0.0 <= d <= c        # full window, never beyond it
    # genuinely *full* jitter: across seeds the capped tail delays
    # land in both halves of [0, cap] (fractional jitter never gets
    # below the base — that tight wave is the thundering herd)
    tails = [mk(seed).delays()[-1] for seed in range(40)]
    assert min(tails) < cap / 4
    assert max(tails) > cap / 2
    # legacy fractional jitter is unchanged: widens, never shrinks
    p = rz.RetryPolicy(max_retries=4, base_delay=1.0, max_delay=8.0,
                       jitter=0.5, seed=7)
    for b, d in zip([1.0, 2.0, 4.0, 8.0], p.delays()):
        assert b <= d <= b * 1.5


# ------------------------------------------------------- rpc framing
def test_garbled_crc_frame_drops_connection_not_later_requests():
    got = []

    def handler(msg, conn, budget):
        got.append((msg, budget))
        return {"op": "echo", "x": msg.get("x")}

    srv = rpc.RpcServer(handler, name="t-echo").start()
    try:
        err0 = _counter("rpc_frame_errors_total")
        cli = rpc.RpcClient("127.0.0.1", srv.port).connect()
        reply, _ = cli.call({"op": "echo", "x": 1}, budget=12.5)
        assert reply == {"op": "echo", "x": 1}
        assert got[0][1] == 12.5    # deadline budget crossed the wire
        # garble a frame below the client API: CRC over the clean
        # payload, then one byte flipped on the wire — exactly what
        # the router:net corrupt injection produces
        header, payload = rpc.encode_frame({"op": "echo", "x": 2})
        cli._sock.sendall(header + bytes([payload[0] ^ 0xFF])
                          + payload[1:])
        # the server rejects the frame, counts it, and drops THIS
        # connection (poisoned framing); the client sees EOF, not an
        # idle timeout
        with pytest.raises(rpc.RpcError):
            cli.recv(timeout=10.0)
        assert not cli.connected
        assert _counter("rpc_frame_errors_total") - err0 == 1
        # a reconnect talks to the same server unpoisoned
        cli.connect_retry()
        reply, _ = cli.call({"op": "echo", "x": 3})
        assert reply == {"op": "echo", "x": 3}
        # the garbled frame was never delivered upward
        assert [m.get("x") for m, _ in got] == [1, 3]
    finally:
        srv.close()


def test_injected_net_corrupt_poisons_exactly_nth_frame(monkeypatch):
    seen = []
    srv = rpc.RpcServer(lambda m, c, b: seen.append(m),
                        name="t-sink").start()
    try:
        cli = rpc.RpcClient("127.0.0.1", srv.port).connect()
        monkeypatch.setenv("MXTPU_FAULT_SPEC", "router:net:2:corrupt")
        rz.reset_faults()
        cli.send({"op": "a"})
        cli.send({"op": "b"})       # the garbled one
        # the receiver dropped the connection; our next send fails
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            try:
                cli.send({"op": "probe"})
                time.sleep(0.02)
            except rpc.RpcError:
                break
        assert not cli.connected
        cli.connect_retry()
        cli.send({"op": "c"})
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and \
                [m["op"] for m in seen if m["op"] == "c"] == []:
            time.sleep(0.02)
        ops = [m["op"] for m in seen]
        assert "a" in ops and "c" in ops and "b" not in ops
    finally:
        srv.close()


# --------------------------------------------------- circuit breaker
def test_breaker_half_open_admits_exactly_one_probe():
    b = router_mod._Breaker(threshold=2, cooldown=5.0)
    now = 100.0
    assert b.allow(now)
    assert b.fail(now) is False and b.state == "closed"
    assert b.fail(now) is True and b.state == "open"  # newly opened
    assert not b.allow(now + 4.9)
    assert b.allow(now + 5.0)       # cooldown over -> half_open
    assert b.state == "half_open" and b.probe_rid is None
    b.probe_rid = 42                # the dispatch path stamps it
    assert not b.allow(now + 5.1)   # EXACTLY one probe in flight
    assert not b.allow(now + 5.2)
    assert b.fail(now + 5.3) is True        # probe failed: re-open
    assert b.state == "open" and b.probe_rid is None
    assert not b.allow(now + 5.4)
    assert b.allow(now + 10.4)      # next cooldown, next single probe
    b.probe_rid = 43
    b.ok()                          # probe succeeded
    assert b.state == "closed" and b.probe_rid is None
    assert b.allow(now + 10.5) and b.allow(now + 10.6)  # no gate


def test_breaker_trips_probes_reopen_then_recover(monkeypatch):
    rep, t = _start_replica("b0", max_batch=1, block_size=4,
                            num_blocks=64, prefix_cache=False)
    tracing.get_recorder().clear()
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "router:replica:*:error")
    rz.reset_faults()
    router = ServingRouter(replicas=[("127.0.0.1", rep.port)],
                           breaker_threshold=1, breaker_cooldown=0.2,
                           poll_interval=0.02).connect()
    try:
        link = router._links["replica0"]
        req = router.submit([1, 2, 3], 4, deadline=300.0)
        deadline = time.monotonic() + 60
        # every dispatch nacks: one failure trips the breaker open
        while time.monotonic() < deadline \
                and link.breaker.state != "open":
            router.poll()
            time.sleep(0.02)
        assert link.breaker.state == "open"
        # each cooldown admits one half-open probe; the probe nacks
        # and the breaker re-opens (trace: half_open then reopened)
        while time.monotonic() < deadline and not \
                tracing.events("router_breaker", state="reopened"):
            router.poll()
            time.sleep(0.02)
        assert tracing.events("router_breaker", state="half_open")
        assert tracing.events("router_breaker", state="reopened")
        # heal the replica: the next probe's tokens close the breaker
        # and the parked request finishes — never silently lost
        monkeypatch.setenv("MXTPU_FAULT_SPEC", "")
        rz.reset_faults()
        assert router.wait([req], timeout=120.0)
        assert req.state == FINISHED
        assert req.tokens == _gen_ref(_shared_net(), [1, 2, 3], 4)
        assert link.breaker.state == "closed"
        assert tracing.events("router_breaker", state="closed")
    finally:
        router.close()
        rep.close()
        t.join(timeout=10)


# ------------------------------------------------ admission + net
def test_fleet_admission_sheds_typed_and_drain_rejects():
    router = ServingRouter(replicas=[], queue_limit=2,
                           queue_tokens=50, poll_interval=0.01)
    rej0 = _counter("router_rejected_total")
    router.submit([1, 2, 3], 2)
    with pytest.raises(ServeRejectedError, match="queue_tokens"):
        router.submit(list(range(48)), 2)
    router.submit([4, 5], 2)
    with pytest.raises(ServeRejectedError, match="queue_limit"):
        router.submit([6], 2)
    assert _counter("router_rejected_total") - rej0 == 2
    router.drain(wait=False)
    with pytest.raises(ServeRejectedError, match="draining"):
        router.submit([7], 2)
    router.close()


def test_deadline_net_expires_unserviceable_request():
    """A request whose owner can never deliver a terminal (here: no
    replica at all) must still end in exactly one terminal state —
    the router's deadline net expires it locally."""
    router = ServingRouter(replicas=[], poll_interval=0.01,
                           expiry_grace=0.05)
    req = router.submit([1, 2, 3], 4, deadline=0.1)
    assert router.wait([req], timeout=30.0)
    assert req.state == EXPIRED
    assert "router net" in req.error
    assert req.id in router._terminal_ids
    assert not router._pending            # not parked after terminal
    router.close()


def test_prefix_affinity_prefers_prior_replica():
    router = ServingRouter(
        replicas=["127.0.0.1:9", "127.0.0.1:10"], block_size=4)
    now = time.monotonic()
    for link in router._links.values():
        link.alive = True
        link.last_heard = now
    req = router_mod.FleetRequest(0, list(range(12)), 4)
    # no affinity yet: least-queued wins
    assert router._pick(req).name == "replica0"
    router._remember_affinity(req, router._links["replica1"])
    assert router._pick(req).name == "replica1"     # cache affinity
    router._links["replica1"].inflight = {1, 2, 3}
    assert router._pick(req).name == "replica1"     # beats load
    router._links["replica1"].alive = False         # unusable: fall
    assert router._pick(req).name == "replica0"     # back to load
    router.close()


# ------------------------------------------------- in-process fleet
def test_fleet_finishes_token_identical_no_leaks():
    net = _shared_net()
    rs = np.random.RandomState(60)
    prompts = [list(rs.randint(0, VOCAB, int(rs.randint(3, 12))))
               for _ in range(6)]
    refs = [_gen_ref(net, p, 6) for p in prompts]
    reps = [_start_replica(f"f{i}", max_batch=2, block_size=4,
                           num_blocks=64, prefix_cache=False)
            for i in range(2)]
    tracing.get_recorder().clear()
    router = ServingRouter(
        replicas=[("127.0.0.1", r.port) for r, _ in reps],
        poll_interval=0.01).connect()
    try:
        reqs = [router.submit(p, 6, deadline=300.0) for p in prompts]
        assert router.wait(reqs, timeout=300.0)
        for req, ref in zip(reqs, refs):
            assert req.state == FINISHED
            assert req.tokens == ref        # fleet == single engine
            assert len(tracing.events("router_terminal",
                                      rid=req.id)) == 1
        assert {r.link for r in reqs} == {"replica0", "replica1"}
        # per-replica block-pool audit over the stats RPC
        for name in ("replica0", "replica1"):
            st = router.replica_stats(name)
            assert st["num_allocated"] == 0
            assert st["pool_live"] == {}
        drained = router.drain(wait=True, timeout=60.0)
        assert drained == {"replica0", "replica1"}
    finally:
        router.close()
        for r, t in reps:
            r.close()
            t.join(timeout=10)


def test_replica_death_redispatches_token_identical():
    net = _shared_net()
    rs = np.random.RandomState(61)
    prompts = [list(rs.randint(0, VOCAB, int(rs.randint(3, 10))))
               for _ in range(4)]
    refs = [_gen_ref(net, p, 10) for p in prompts]
    reps = [_start_replica(f"d{i}", max_batch=2, block_size=4,
                           num_blocks=64, prefix_cache=False)
            for i in range(2)]
    tracing.get_recorder().clear()
    router = ServingRouter(
        replicas=[("127.0.0.1", r.port) for r, _ in reps],
        poll_interval=0.01).connect()
    try:
        reqs = [router.submit(p, 10, deadline=300.0)
                for p in prompts]
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and not \
                any(r.generated for r in reqs):
            router.poll()
            time.sleep(0.01)
        assert any(r.generated for r in reqs)
        victim = reps[0][0]
        owned = [r for r in reqs if r.link == "replica0"
                 and not r.done]
        assert owned            # least-queued routing spread the load
        victim.close()          # ungraceful: sockets die mid-stream
        assert router.wait(reqs, timeout=300.0)
        for req, ref in zip(reqs, refs):
            assert req.state == FINISHED
            assert req.tokens == ref    # greedy recompute: identical
            assert len(tracing.events("router_terminal",
                                      rid=req.id)) == 1
        moved = [r for r in owned if r.redispatches > 0]
        assert moved            # the victim's in-flight work re-homed
        assert tracing.events("router_redispatch")
        assert all(r.link == "replica1" for r in moved)
    finally:
        router.close()
        for r, t in reps:
            r.close()
            t.join(timeout=10)


def test_router_sigterm_drains_fleet_snapshots_restorable(tmp_path):
    net = _shared_net()
    rs = np.random.RandomState(62)
    prompts = [list(rs.randint(0, VOCAB, int(rs.randint(3, 10))))
               for _ in range(4)]
    refs = [_gen_ref(net, p, 10) for p in prompts]
    # max_batch=1: each replica gets one running + one queued request,
    # so the drain snapshots carry genuinely unfinished work
    reps = [_start_replica(f"s{i}", max_batch=1, block_size=4,
                           num_blocks=64, prefix_cache=False)
            for i in range(2)]
    router = ServingRouter(
        replicas=[("127.0.0.1", r.port) for r, _ in reps],
        poll_interval=0.01).connect()
    prev = signal.getsignal(signal.SIGTERM)
    try:
        reqs = [router.submit(p, 10, deadline=300.0)
                for p in prompts]
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and not \
                any(r.generated for r in reqs):
            router.poll()
            time.sleep(0.01)
        # SIGTERM only latches (no socket work in the handler); the
        # next poll performs the drain
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        assert router.install_sigterm(snapshot_dir=str(tmp_path))
        signal.raise_signal(signal.SIGTERM)
        assert router._drain_requested
        router.poll()
        assert router._draining
        with pytest.raises(ServeRejectedError, match="draining"):
            router.submit([1, 2, 3], 2)
        drained = router.drain(wait=True, timeout=120.0)
        assert drained == {"replica0", "replica1"}
        # every replica snapshotted; restoring each into a fresh
        # engine completes its requests token-identically — the
        # shrink/grow fleet restart story
        restored = {}
        for name in sorted(drained):
            snap = tmp_path / f"{name}.snap"
            assert snap.exists()
            eng = ServingEngine.restore(net, str(snap), max_batch=1,
                                        block_size=4, num_blocks=64,
                                        prefix_cache=False)
            restored.update(eng.run())
            assert eng.pool.num_allocated == 0
        for req, ref in zip(reqs, refs):
            # running requests finished live (drain completes the
            # running batch) AND restore from their snapshot copy is
            # token-identical; queued ones live on only in snapshots
            if req.done:
                assert req.state == FINISHED and req.tokens == ref
            else:
                assert req.id in restored
            if req.id in restored:
                assert restored[req.id] == ref
        assert any(not r.done for r in reqs)    # drain left work
    finally:
        signal.signal(signal.SIGTERM, prev)
        router.close()
        for r, t in reps:
            r.close()
            t.join(timeout=10)


# ------------------------------------------ chaos acceptance (procs)
def _spawn_replica_proc(tmp_path, idx, fault_spec=""):
    port_file = tmp_path / f"port{idx}"
    log = open(tmp_path / f"replica{idx}.log", "wb")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("MXTPU_FAULT_SPEC", None)
    if fault_spec:
        env["MXTPU_FAULT_SPEC"] = fault_spec
    proc = subprocess.Popen(
        [sys.executable, "-m", "incubator_mxnet_tpu.serving.replica",
         "--port-file", str(port_file), "--name", f"chaos{idx}",
         "--max-batch", "2", "--block-size", "4",
         "--num-blocks", "64", "--prefix-cache", "0"],
        cwd=REPO, env=env, stdout=log, stderr=log)
    return proc, port_file, log


def _wait_ports(port_files, timeout=180):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(f.exists() for f in port_files):
            return [int(f.read_text()) for f in port_files]
        time.sleep(0.1)
    raise AssertionError("replica subprocesses never came up")


def test_chaos_kill_and_net_garble_exactly_one_terminal(
        tmp_path, monkeypatch):
    """Acceptance: a 3-replica fleet under a seeded router:replica
    kill (one replica hard-dies mid-stream) plus router:net frame
    garbling + delay on the router's own send path.  Every admitted
    request must end in exactly one terminal state fleet-wide,
    re-dispatched outputs must be token-identical to an unkilled
    single-engine run, surviving replicas must leak zero blocks, and
    the drain snapshots must restore into fresh engines."""
    net = _shared_net()
    rs = np.random.RandomState(77)
    prompts = [list(rs.randint(0, VOCAB, int(rs.randint(3, 12))))
               for _ in range(6)]
    refs = [_gen_ref(net, p, 8) for p in prompts]
    # replica 0 hard-dies (os._exit) serving its 2nd dispatch
    specs = ["router:replica:2:kill", "", ""]
    procs = [_spawn_replica_proc(tmp_path, i, spec)
             for i, spec in enumerate(specs)]
    try:
        ports = _wait_ports([pf for _, pf, _ in procs])
        tracing.get_recorder().clear()
        # the router's own frame path: garble the 3rd frame it sends
        # (CRC rejection drops that link) and delay the 9th
        monkeypatch.setenv("MXTPU_FAULT_SPEC",
                           "router:net:3:corrupt,router:net:9:hang")
        monkeypatch.setenv("MXTPU_FAULT_HANG_S", "0.2")
        rz.reset_faults()
        red0 = _counter("router_redispatches_total")
        router = ServingRouter(
            replicas=[("127.0.0.1", p) for p in ports],
            poll_interval=0.02, stale_after=5.0).connect()
        try:
            reqs = [router.submit(p, 8, deadline=300.0)
                    for p in prompts]
            assert router.wait(reqs, timeout=300.0)
            for req, ref in zip(reqs, refs):
                assert req.state == FINISHED, (req.id, req.error)
                assert req.tokens == ref    # token-identical failover
                assert len(tracing.events("router_terminal",
                                          rid=req.id)) == 1
            # the killed replica really died, and its work re-homed
            assert procs[0][0].wait(timeout=60) == 1
            assert sum(r.redispatches for r in reqs) >= 1
            assert _counter("router_redispatches_total") > red0
            assert tracing.events("router_redispatch")
            # survivors leak zero blocks (per-replica RPC audit)
            for name in ("replica1", "replica2"):
                st = router.replica_stats(name)
                assert st["num_allocated"] == 0
                assert st["pool_live"] == {}
            # phase 2: drain mid-stream; survivors snapshot, exit 0,
            # and the snapshots restore token-identically
            prompts2 = [list(rs.randint(0, VOCAB,
                                        int(rs.randint(3, 10))))
                        for _ in range(4)]
            refs2 = [_gen_ref(net, p, 10) for p in prompts2]
            reqs2 = [router.submit(p, 10, deadline=300.0)
                     for p in prompts2]
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline and not \
                    any(r.generated for r in reqs2):
                router.poll()
                time.sleep(0.02)
            snapdir = tmp_path / "snaps"
            snapdir.mkdir()
            drained = router.drain(wait=True, timeout=120.0,
                                   snapshot_dir=str(snapdir))
            assert drained == {"replica1", "replica2"}
            for proc, _, _ in procs[1:]:
                assert proc.wait(timeout=120) == 0  # drained cleanly
            restored = {}
            for name in sorted(drained):
                snap = snapdir / f"{name}.snap"
                assert snap.exists()
                eng = ServingEngine.restore(
                    net, str(snap), max_batch=2, block_size=4,
                    num_blocks=64, prefix_cache=False)
                restored.update(eng.run())
                assert eng.pool.num_allocated == 0
            for req, ref in zip(reqs2, refs2):
                if req.done:
                    assert req.state == FINISHED
                    assert req.tokens == ref
                else:
                    assert req.id in restored   # never silently lost
                if req.id in restored:
                    assert restored[req.id] == ref
        finally:
            router.close()
    finally:
        for proc, _, log in procs:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
            log.close()


# --------------------------------------------- cross-process stitch
def test_stitch_dumps_merges_fleet_timeline(tmp_path):
    a = tmp_path / "flight.rank0.jsonl"
    b = tmp_path / "flight.rank1.jsonl"
    a.write_text("\n".join([
        json.dumps({"flight_recorder": 1, "rank": 0}),
        json.dumps({"event": "router_dispatch", "rid": 1,
                    "replica": "r0", "ts": 1.0, "seq": 0}),
        json.dumps({"event": "router_terminal", "rid": 1,
                    "replica": "r0", "ts": 4.0, "seq": 1}),
    ]) + "\n")
    b.write_text("\n".join([
        json.dumps({"flight_recorder": 1, "rank": 1}),
        "torn non-json line",
        json.dumps({"event": "fleet_dispatch", "rid": 1,
                    "replica": "r0", "ts": 2.0, "seq": 0}),
        json.dumps({"event": "fleet_terminal", "rid": 1,
                    "replica": "r0", "ts": 3.0, "seq": 1}),
        json.dumps({"event": "fleet_dispatch", "rid": 2,
                    "replica": "r0", "ts": 2.5, "seq": 2}),
    ]) + "\n")
    # rid filter reads one request's hops across both processes in
    # wall-clock order; missing files (a killed replica never dumps)
    # and torn lines are skipped
    evs = tracing.stitch_dumps(
        [str(a), str(b), str(tmp_path / "missing.jsonl")], rid=1)
    assert [e["event"] for e in evs] == [
        "router_dispatch", "fleet_dispatch", "fleet_terminal",
        "router_terminal"]
    assert evs[0]["src"] == "flight.rank0.jsonl"
    assert evs[1]["src"] == "flight.rank1.jsonl"
    assert len(tracing.stitch_dumps([str(a), str(b)])) == 5


# ------------------------------------------------ launch.py helpers
def _load_launch():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "launch", os.path.join(REPO, "tools", "launch.py"))
    launch = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(launch)
    return launch


def test_fleet_env_and_status_helpers():
    launch = _load_launch()

    class Args:
        env = ["FOO=bar"]

    env = launch._fleet_env(Args(), "replica", 1, 7000, [7001, 7002])
    assert env["MXTPU_FLEET_ROLE"] == "replica"
    assert env["MXTPU_FLEET_REPLICAS"] == "2"
    assert env["MXTPU_REPLICA_PORT"] == "7002"
    assert env["MXTPU_REPLICA_ADDRS"] == \
        "127.0.0.1:7001,127.0.0.1:7002"
    assert env["MXTPU_WORKER_RANK"] == "1"
    assert env["FOO"] == "bar"
    renv = launch._fleet_env(Args(), "router", 0, 7000, [7001, 7002])
    assert renv["MXTPU_FLEET_ROLE"] == "router"
    assert renv["MXTPU_ROUTER_PORT"] == "7000"
    assert "MXTPU_REPLICA_PORT" not in renv
    with pytest.raises(ValueError, match="KEY=VALUE"):
        bad = Args()
        bad.env = ["NOVALUE"]
        launch._fleet_env(bad, "router", 0, 7000, [7001])
    # status line: health ratio + request rate from counter deltas
    rate_state = {"ts": None, "total": 0}
    snaps = {0: {"counters": {"serving_requests_total": 10}}}
    line = launch._fleet_status(snaps, 2, 3, rate_state)
    assert "fleet: 2/3 healthy" in line
    assert "0.0 req/s" in line          # no prior tick: no rate yet
    time.sleep(0.05)
    snaps2 = {0: {"counters": {"serving_requests_total": 30}}}
    line2 = launch._fleet_status(snaps2, 3, 3, rate_state)
    assert "fleet: 3/3 healthy" in line2
    assert "0.0 req/s" not in line2     # 20 reqs since last tick


# ------------------------------------------------------- lint rule
def _load_lint():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "lint", os.path.join(REPO, "ci", "lint.py"))
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    return lint


def test_lint_flags_unbounded_socket_waits(tmp_path):
    lint = _load_lint()
    d = tmp_path / "incubator_mxnet_tpu" / "serving"
    d.mkdir(parents=True)
    f = d / "rpc.py"
    f.write_text("import socket\ns = socket.socket()\n"
                 "c = s.accept()\n")
    assert any("accept" in p for p in lint.check_file(f))
    # a timeout= kwarg bounds the wait
    f.write_text("import socket\n"
                 "c = socket.create_connection(('h', 1), timeout=5)\n")
    assert not any("create_connection" in p
                   for p in lint.check_file(f))
    # same-line annotation
    f.write_text("import socket\ns = socket.socket()\n"
                 "c = s.recv(4)  # deadline-ok: settimeout armed\n")
    assert not any("recv" in p for p in lint.check_file(f))
    # contiguous comment block above annotates too
    f.write_text("import socket\ns = socket.socket()\n"
                 "# bounded by the caller's poll loop\n"
                 "# deadline-ok: settimeout(poll) armed above\n"
                 "c = s.accept()\n")
    assert not any("accept" in p for p in lint.check_file(f))
    # ...but only a CONTIGUOUS block: code between breaks the chain
    f.write_text("import socket\n# deadline-ok: stale note\n"
                 "s = socket.socket()\n"
                 "c = s.accept()\n")
    assert any("accept" in p for p in lint.check_file(f))
    # outside the fleet RPC modules the rule does not fire
    o = tmp_path / "incubator_mxnet_tpu" / "other.py"
    o.write_text("import socket\ns = socket.socket()\n"
                 "c = s.accept()\n")
    assert not any("accept" in p for p in lint.check_file(o))
