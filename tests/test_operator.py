"""Per-op numeric checks vs numpy oracle
(ref: tests/python/unittest/test_operator.py — the main correctness
net; numpy is the oracle for CPU, interpreter for compiled TPU)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, autograd

RS = np.random.RandomState(42)


def _a(*shape):
    return RS.rand(*shape).astype("float32") + 0.1


def test_unary_math_ops():
    x = _a(3, 4)
    cases = {
        "exp": np.exp, "log": np.log, "sqrt": np.sqrt,
        "square": np.square, "abs": np.abs, "sign": np.sign,
        "floor": np.floor, "ceil": np.ceil, "round": np.round,
        "sin": np.sin, "cos": np.cos, "tanh": np.tanh,
        "log1p": np.log1p, "expm1": np.expm1,
        "rsqrt": lambda v: 1 / np.sqrt(v),
        "reciprocal": lambda v: 1 / v,
        "cbrt": np.cbrt,
    }
    for name, ref in cases.items():
        out = getattr(nd, name)(nd.array(x)).asnumpy()
        np.testing.assert_allclose(out, ref(x), rtol=1e-4, atol=1e-5,
                                   err_msg=name)


def test_activation_types():
    x = _a(2, 5) - 0.5
    np.testing.assert_allclose(
        nd.Activation(nd.array(x), act_type="relu").asnumpy(),
        np.maximum(x, 0), rtol=1e-5)
    np.testing.assert_allclose(
        nd.Activation(nd.array(x), act_type="sigmoid").asnumpy(),
        1 / (1 + np.exp(-x)), rtol=1e-5)
    np.testing.assert_allclose(
        nd.LeakyReLU(nd.array(x), act_type="leaky", slope=0.1).asnumpy(),
        np.where(x > 0, x, 0.1 * x), rtol=1e-5)
    np.testing.assert_allclose(
        nd.LeakyReLU(nd.array(x), act_type="elu", slope=0.3).asnumpy(),
        np.where(x > 0, x, 0.3 * np.expm1(x)), rtol=1e-5)


def test_fully_connected():
    x, w, b = _a(4, 6), _a(3, 6), _a(3)
    out = nd.FullyConnected(nd.array(x), nd.array(w), nd.array(b),
                            num_hidden=3).asnumpy()
    np.testing.assert_allclose(out, x @ w.T + b, rtol=1e-4)
    out2 = nd.FullyConnected(nd.array(x), nd.array(w), no_bias=True,
                             num_hidden=3).asnumpy()
    np.testing.assert_allclose(out2, x @ w.T, rtol=1e-4)


def test_convolution_shapes_and_values():
    x = _a(2, 3, 8, 8)
    w = _a(4, 3, 3, 3)
    b = _a(4)
    out = nd.Convolution(nd.array(x), nd.array(w), nd.array(b),
                         kernel=(3, 3), num_filter=4)
    assert out.shape == (2, 4, 6, 6)
    out_s = nd.Convolution(nd.array(x), nd.array(w), nd.array(b),
                           kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                           num_filter=4)
    assert out_s.shape == (2, 4, 4, 4)
    # value check against naive loop at one output position
    ref00 = (x[0, :, 0:3, 0:3] * w[1]).sum() + b[1]
    np.testing.assert_allclose(out.asnumpy()[0, 1, 0, 0], ref00,
                               rtol=1e-4)


def test_grouped_and_1d_conv():
    x = _a(2, 4, 10)
    w = _a(6, 2, 3)
    out = nd.Convolution(nd.array(x), nd.array(w), no_bias=True,
                         kernel=(3,), num_filter=6, num_group=2)
    assert out.shape == (2, 6, 8)


def test_deconvolution_shape():
    x = _a(1, 3, 4, 4)
    w = _a(3, 2, 3, 3)  # (in, out, kh, kw)
    out = nd.Deconvolution(nd.array(x), nd.array(w), kernel=(3, 3),
                           stride=(2, 2), num_filter=2)
    assert out.shape == (1, 2, 9, 9)
    # identity check: stride 1, kernel 1
    w1 = np.ones((1, 1, 1, 1), "float32")
    x1 = _a(1, 1, 3, 3)
    out1 = nd.Deconvolution(nd.array(x1), nd.array(w1), kernel=(1, 1),
                            num_filter=1)
    np.testing.assert_allclose(out1.asnumpy(), x1, rtol=1e-5)


def test_pooling():
    x = _a(1, 2, 4, 4)
    mp = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2),
                    pool_type="max")
    assert mp.shape == (1, 2, 2, 2)
    np.testing.assert_allclose(mp.asnumpy()[0, 0, 0, 0],
                               x[0, 0, :2, :2].max(), rtol=1e-6)
    ap = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2),
                    pool_type="avg")
    np.testing.assert_allclose(ap.asnumpy()[0, 1, 1, 1],
                               x[0, 1, 2:, 2:].mean(), rtol=1e-6)
    gp = nd.Pooling(nd.array(x), global_pool=True, pool_type="max")
    assert gp.shape == (1, 2, 1, 1)


def test_batchnorm_train_and_inference():
    x = _a(4, 3, 2, 2)
    gamma, beta = np.ones(3, "float32"), np.zeros(3, "float32")
    mean = np.zeros(3, "float32")
    var = np.ones(3, "float32")
    g, b = nd.array(gamma), nd.array(beta)
    mm, mv = nd.array(mean), nd.array(var)
    # training mode normalizes by batch stats and updates aux in place
    with autograd.train_mode():
        out = nd.BatchNorm(nd.array(x), g, b, mm, mv, fix_gamma=False,
                           momentum=0.9)
    bm = x.mean(axis=(0, 2, 3))
    np.testing.assert_allclose(out.asnumpy().mean(axis=(0, 2, 3)),
                               np.zeros(3), atol=1e-5)
    np.testing.assert_allclose(mm.asnumpy(), 0.9 * mean + 0.1 * bm,
                               rtol=1e-4)
    # inference mode uses moving stats
    out_inf = nd.BatchNorm(nd.array(x), g, b, nd.array(mean),
                           nd.array(var), fix_gamma=False)
    np.testing.assert_allclose(out_inf.asnumpy(),
                               (x - 0) / np.sqrt(1 + 1e-3), rtol=1e-3)


def test_softmax_family():
    x = _a(3, 5)
    sm = nd.softmax(nd.array(x)).asnumpy()
    e = np.exp(x - x.max(-1, keepdims=True))
    np.testing.assert_allclose(sm, e / e.sum(-1, keepdims=True), rtol=1e-5)
    lsm = nd.log_softmax(nd.array(x)).asnumpy()
    np.testing.assert_allclose(lsm, np.log(sm), rtol=1e-4, atol=1e-5)


def test_softmax_output_gradient():
    x = _a(4, 3)
    label = np.array([0, 2, 1, 1], "float32")
    data = nd.array(x)
    data.attach_grad()
    with autograd.record():
        out = nd.SoftmaxOutput(data, nd.array(label))
    out.backward()
    sm = np.exp(x - x.max(-1, keepdims=True))
    sm /= sm.sum(-1, keepdims=True)
    onehot = np.eye(3, dtype="float32")[label.astype(int)]
    np.testing.assert_allclose(data.grad.asnumpy(), sm - onehot,
                               rtol=1e-4, atol=1e-6)


def test_regression_outputs():
    x = _a(4, 2)
    lbl = _a(4, 2)
    data = nd.array(x)
    data.attach_grad()
    with autograd.record():
        out = nd.LinearRegressionOutput(data, nd.array(lbl))
    np.testing.assert_allclose(out.asnumpy(), x, rtol=1e-6)
    out.backward()
    np.testing.assert_allclose(data.grad.asnumpy(), (x - lbl) / 2,
                               rtol=1e-5)


def test_matrix_ops():
    x = _a(2, 3, 4)
    np.testing.assert_allclose(nd.transpose(nd.array(x)).asnumpy(),
                               x.transpose())
    np.testing.assert_allclose(
        nd.slice(nd.array(x), begin=(0, 1), end=(2, 3)).asnumpy(),
        x[0:2, 1:3])
    np.testing.assert_allclose(
        nd.slice_axis(nd.array(x), axis=2, begin=1, end=3).asnumpy(),
        x[:, :, 1:3])
    np.testing.assert_allclose(nd.clip(nd.array(x), a_min=0.2,
                                       a_max=0.8).asnumpy(),
                               np.clip(x, 0.2, 0.8))
    np.testing.assert_allclose(nd.tile(nd.array(x), reps=(2, 1, 1)
                                       ).asnumpy(), np.tile(x, (2, 1, 1)))
    np.testing.assert_allclose(nd.reverse(nd.array(x), axis=1).asnumpy(),
                               x[:, ::-1, :])
    np.testing.assert_allclose(
        nd.Pad(nd.array(x[:, :, :, None] if x.ndim == 3 else x),
               mode="constant",
               pad_width=(0, 0, 0, 0, 1, 1, 0, 0)).asnumpy(),
        np.pad(x[:, :, :, None], ((0, 0), (0, 0), (1, 1), (0, 0))))


def test_dot_and_batch_dot():
    a, b = _a(3, 4), _a(4, 5)
    np.testing.assert_allclose(nd.dot(nd.array(a), nd.array(b)).asnumpy(),
                               a @ b, rtol=1e-4)
    np.testing.assert_allclose(
        nd.dot(nd.array(a), nd.array(b.T), transpose_b=True).asnumpy(),
        a @ b, rtol=1e-4)
    ba, bb = _a(2, 3, 4), _a(2, 4, 5)
    np.testing.assert_allclose(
        nd.batch_dot(nd.array(ba), nd.array(bb)).asnumpy(),
        np.matmul(ba, bb), rtol=1e-4)


def test_indexing_ops():
    w = _a(10, 4)
    idx = np.array([1, 3, 5], "float32")
    np.testing.assert_allclose(
        nd.Embedding(nd.array(idx), nd.array(w), input_dim=10,
                     output_dim=4).asnumpy(), w[idx.astype(int)])
    np.testing.assert_allclose(
        nd.take(nd.array(w), nd.array(idx)).asnumpy(), w[idx.astype(int)])
    oh = nd.one_hot(nd.array(idx), depth=10).asnumpy()
    assert oh.shape == (3, 10) and oh[0, 1] == 1 and oh.sum() == 3
    data = _a(3, 5)
    pk = nd.pick(nd.array(data), nd.array(np.array([0, 2, 4], "float32")),
                 axis=1).asnumpy()
    np.testing.assert_allclose(pk, data[np.arange(3), [0, 2, 4]])


def test_ordering_ops():
    x = _a(3, 6)
    np.testing.assert_allclose(nd.sort(nd.array(x)).asnumpy(),
                               np.sort(x, -1))
    np.testing.assert_allclose(nd.argsort(nd.array(x)).asnumpy(),
                               np.argsort(x, -1, kind="stable"))
    v, i = nd.topk(nd.array(x), k=2, ret_typ="both")
    np.testing.assert_allclose(v.asnumpy(), np.sort(x, -1)[:, ::-1][:, :2],
                               rtol=1e-6)


def test_sequence_ops():
    x = _a(4, 3, 2)  # (T, B, F)
    lengths = np.array([2, 4, 1], "float32")
    masked = nd.SequenceMask(nd.array(x), nd.array(lengths),
                             use_sequence_length=True).asnumpy()
    assert (masked[2:, 0] == 0).all() and (masked[1:, 2] == 0).all()
    assert (masked[:2, 0] == x[:2, 0]).all()
    last = nd.SequenceLast(nd.array(x), nd.array(lengths),
                           use_sequence_length=True).asnumpy()
    np.testing.assert_allclose(last[0], x[1, 0])
    np.testing.assert_allclose(last[1], x[3, 1])
    rev = nd.SequenceReverse(nd.array(x), nd.array(lengths),
                             use_sequence_length=True).asnumpy()
    np.testing.assert_allclose(rev[0, 0], x[1, 0])
    np.testing.assert_allclose(rev[1, 0], x[0, 0])
    np.testing.assert_allclose(rev[2, 0], x[2, 0])


def test_norm_ops():
    x = _a(2, 3, 4, 4)
    g = np.ones(3, "float32")
    b = np.zeros(3, "float32")
    out = nd.InstanceNorm(nd.array(x), nd.array(g), nd.array(b))
    np.testing.assert_allclose(out.asnumpy().mean(axis=(2, 3)),
                               np.zeros((2, 3)), atol=1e-4)
    l2 = nd.L2Normalization(nd.array(x)).asnumpy()
    norms = np.sqrt((l2.reshape(2, -1) ** 2).sum(1))
    np.testing.assert_allclose(norms, np.ones(2), rtol=1e-4)
    ln = nd.LayerNorm(nd.array(x), nd.array(np.ones(4, "float32")),
                      nd.array(np.zeros(4, "float32")))
    np.testing.assert_allclose(ln.asnumpy().mean(-1),
                               np.zeros((2, 3, 4)), atol=1e-4)
    lr = nd.LRN(nd.array(x), nsize=3)
    assert lr.shape == x.shape


def test_linalg_ops():
    a = _a(3, 3)
    spd = a @ a.T + 3 * np.eye(3, dtype="float32")
    L = nd.linalg.potrf(nd.array(spd)).asnumpy()
    np.testing.assert_allclose(L @ L.T, spd, rtol=1e-4)
    np.testing.assert_allclose(
        nd.linalg.gemm2(nd.array(a), nd.array(a), transpose_b=True
                        ).asnumpy(), a @ a.T, rtol=1e-4)
    sld = nd.linalg.sumlogdiag(nd.array(spd)).asnumpy()
    np.testing.assert_allclose(sld, np.log(np.diag(spd)).sum(), rtol=1e-5)


def test_random_ops_statistics():
    mx.random.seed(7)
    u = nd.random.uniform(0, 1, (2000,)).asnumpy()
    assert 0.45 < u.mean() < 0.55
    n = nd.random.normal(2, 3, (2000,)).asnumpy()
    assert 1.7 < n.mean() < 2.3 and 2.6 < n.std() < 3.4
    mx.random.seed(7)
    u2 = nd.random.uniform(0, 1, (2000,)).asnumpy()
    np.testing.assert_allclose(u, u2)  # reproducible after reseed
    m = nd.random.multinomial(nd.array(np.array([[0.0, 1.0, 0.0]] * 5,
                                                "float32"))).asnumpy()
    assert (m == 1).all()


def test_upsampling_and_spatial():
    x = _a(1, 2, 3, 3)
    up = nd.UpSampling(nd.array(x), scale=2, sample_type="nearest")
    assert up.shape == (1, 2, 6, 6)
    np.testing.assert_allclose(up.asnumpy()[0, 0, :2, :2],
                               np.full((2, 2), x[0, 0, 0, 0]), rtol=1e-6)
    # identity affine spatial transformer
    theta = np.tile(np.array([1, 0, 0, 0, 1, 0], "float32"), (1, 1))
    st = nd.SpatialTransformer(nd.array(x), nd.array(theta),
                               target_shape=(3, 3))
    np.testing.assert_allclose(st.asnumpy(), x, atol=1e-5)


def test_optimizer_ops():
    w, g = _a(5), _a(5)
    out = nd._internal.sgd_update(nd.array(w), nd.array(g), lr=0.1,
                                  wd=0.0)
    np.testing.assert_allclose(out.asnumpy(), w - 0.1 * g, rtol=1e-5)
    mom = np.zeros(5, "float32")
    outs = nd._internal.sgd_mom_update(nd.array(w), nd.array(g),
                                       nd.array(mom), lr=0.1, momentum=0.9)
    np.testing.assert_allclose(outs[0].asnumpy(), w - 0.1 * g, rtol=1e-5)
    mean, var = np.zeros(5, "float32"), np.zeros(5, "float32")
    outs = nd._internal.adam_update(nd.array(w), nd.array(g),
                                    nd.array(mean), nd.array(var),
                                    lr=0.01)
    assert outs[0].shape == (5,)


def test_where_and_control():
    cond = np.array([1, 0, 1], "float32")
    x, y = _a(3), _a(3)
    np.testing.assert_allclose(
        nd.where(nd.array(cond), nd.array(x), nd.array(y)).asnumpy(),
        np.where(cond != 0, x, y))


def test_cast_and_zeros_like():
    x = _a(2, 2)
    assert nd.Cast(nd.array(x), dtype="int32").dtype == np.int32
    np.testing.assert_allclose(nd.zeros_like(nd.array(x)).asnumpy(),
                               np.zeros_like(x))
    np.testing.assert_allclose(nd.ones_like(nd.array(x)).asnumpy(),
                               np.ones_like(x))


def test_gather_scatter_nd():
    data = _a(3, 4)
    idx = np.array([[0, 2], [1, 3]], "float32")
    out = nd.gather_nd(nd.array(data), nd.array(idx)).asnumpy()
    np.testing.assert_allclose(out, data[[0, 2], [1, 3]])
    sc = nd.scatter_nd(nd.array(np.array([5.0, 7.0], "float32")),
                       nd.array(idx), shape=(3, 4)).asnumpy()
    assert sc[0, 1] == 5 and sc[2, 3] == 7 and sc.sum() == 12


def test_numeric_gradient_spotcheck():
    # finite differences vs autograd for a composite expression
    x0 = _a(3, 3)
    x = nd.array(x0)
    x.attach_grad()
    with autograd.record():
        y = (nd.tanh(nd.dot(x, x)) * nd.sigmoid(x)).sum()
    y.backward()
    eps = 1e-3

    def f(v):
        t = np.tanh(v @ v) * (1 / (1 + np.exp(-v)))
        return t.sum()

    num = np.zeros_like(x0)
    for i in range(3):
        for j in range(3):
            p = x0.copy(); p[i, j] += eps
            m = x0.copy(); m[i, j] -= eps
            num[i, j] = (f(p) - f(m)) / (2 * eps)
    np.testing.assert_allclose(x.grad.asnumpy(), num, rtol=1e-2,
                               atol=1e-3)


def test_batchnorm_backward_training():
    # regression: aux-op (BatchNorm) backward under record used to crash
    x0 = _a(4, 3, 2, 2)
    x = nd.array(x0)
    x.attach_grad()
    g = nd.array(np.ones(3, "float32"))
    g.attach_grad()
    b = nd.array(np.zeros(3, "float32"))
    b.attach_grad()
    mm, mv = nd.zeros((3,)), nd.ones((3,))
    with autograd.record():
        y = nd.BatchNorm(x, g, b, mm, mv, fix_gamma=False)
        loss = (y * y).sum()
    loss.backward()
    assert x.grad.shape == x0.shape
    assert np.isfinite(x.grad.asnumpy()).all()
    assert np.isfinite(g.grad.asnumpy()).all()
    # moving stats were updated by the recorded training forward
    assert not np.allclose(mm.asnumpy(), np.zeros(3))


def test_trsm_rightside_transpose():
    a = _a(3, 3)
    A = np.tril(a) + 3 * np.eye(3, dtype="float32")
    B = _a(2, 3)
    # X A^T = B
    X = nd.linalg.trsm(nd.array(A), nd.array(B), transpose=True,
                       rightside=True).asnumpy()
    np.testing.assert_allclose(X @ A.T, B, rtol=1e-4)
    # X A = B
    X2 = nd.linalg.trsm(nd.array(A), nd.array(B), rightside=True).asnumpy()
    np.testing.assert_allclose(X2 @ A, B, rtol=1e-4)


def test_gamma_negative_sign():
    out = nd.gamma(nd.array(np.array([-0.5, -1.5, 0.5], "float32")))
    from scipy.special import gamma as spgamma  # noqa
    np.testing.assert_allclose(out.asnumpy(),
                               [spgamma(-0.5), spgamma(-1.5),
                                spgamma(0.5)], rtol=1e-4)


def test_randint_distribution():
    mx.random.seed(0)
    r = mx.random.randint(-5, 5, (4000,)).asnumpy()
    assert r.min() == -5 and r.max() == 4
    counts = np.bincount(r + 5, minlength=10)
    assert (counts > 250).all()  # roughly uniform, all endpoints hit
