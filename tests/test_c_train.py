"""Native C training ABI (ref role: cpp-package/include/mxnet-cpp/
MxNetCpp.h — the reference's C++ training surface):
libmxtpu_train.so embeds the interpreter; a C client creates a
trainer from symbol JSON, feeds batches, steps the fused
fwd+bwd+update executable, and exports trained params that the
predict ABI then serves."""
import ctypes
import os
import subprocess

import numpy as np

import incubator_mxnet_tpu as mx

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(REPO, "src", "c_train")
SO = os.path.join(SRC, "libmxtpu_train.so")


def _build_lib():
    if not os.path.exists(SO):
        subprocess.run(["make", "-C", SRC], check=True,
                       capture_output=True, timeout=300)
    return SO


def _bind(lib):
    u = ctypes.c_uint
    lib.MXTPUTrainGetLastError.restype = ctypes.c_char_p
    lib.MXTPUTrainCreate.argtypes = [
        ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, u, ctypes.POINTER(ctypes.c_char_p),
        ctypes.POINTER(u), ctypes.POINTER(u), ctypes.c_char_p,
        ctypes.c_float, ctypes.POINTER(ctypes.c_void_p)]
    lib.MXTPUTrainSetInput.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_float), u]
    lib.MXTPUTrainStep.argtypes = [ctypes.c_void_p,
                                   ctypes.POINTER(ctypes.c_float)]
    lib.MXTPUTrainForward.argtypes = [ctypes.c_void_p]
    lib.MXTPUTrainGetOutputShape.argtypes = [
        ctypes.c_void_p, u, ctypes.POINTER(ctypes.POINTER(u)),
        ctypes.POINTER(u)]
    lib.MXTPUTrainGetOutput.argtypes = [
        ctypes.c_void_p, u, ctypes.POINTER(ctypes.c_float), u]
    lib.MXTPUTrainGetParams.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_int)]
    lib.MXTPUTrainFree.argtypes = [ctypes.c_void_p]
    return lib


def _train_symbol():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc1", num_hidden=16)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=3)
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _problem():
    rs = np.random.RandomState(0)
    x = rs.rand(32, 6).astype(np.float32)
    w = rs.rand(6, 3).astype(np.float32)
    y = (x @ w).argmax(1).astype(np.float32)
    return x, y


def test_c_train_loss_decreases_and_params_deploy(tmp_path):
    lib = _bind(ctypes.CDLL(_build_lib()))
    sym_json = _train_symbol().tojson().encode()
    x, y = _problem()

    keys = (ctypes.c_char_p * 2)(b"data", b"softmax_label")
    indptr = (ctypes.c_uint * 3)(0, 2, 3)
    shape = (ctypes.c_uint * 3)(32, 6, 32)
    handle = ctypes.c_void_p()
    rc = lib.MXTPUTrainCreate(sym_json, None, 0, 1, 0, 2, keys,
                              indptr, shape, b"sgd",
                              ctypes.c_float(0.5),
                              ctypes.byref(handle))
    assert rc == 0, lib.MXTPUTrainGetLastError()

    xf = np.ascontiguousarray(x).ravel()
    yf = np.ascontiguousarray(y).ravel()
    xp = xf.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
    yp = yf.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
    assert lib.MXTPUTrainSetInput(handle, b"data", xp, xf.size) == 0
    assert lib.MXTPUTrainSetInput(handle, b"softmax_label", yp,
                                  yf.size) == 0

    loss = ctypes.c_float()
    losses = []
    for _ in range(40):
        assert lib.MXTPUTrainStep(handle, ctypes.byref(loss)) == 0, \
            lib.MXTPUTrainGetLastError()
        losses.append(float(loss.value))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])

    # eval forward + output readback
    assert lib.MXTPUTrainForward(handle) == 0
    sdata = ctypes.POINTER(ctypes.c_uint)()
    ndim = ctypes.c_uint()
    assert lib.MXTPUTrainGetOutputShape(
        handle, 0, ctypes.byref(sdata), ctypes.byref(ndim)) == 0
    oshape = tuple(sdata[i] for i in range(ndim.value))
    assert oshape == (32, 3), oshape
    probs = np.zeros(32 * 3, np.float32)
    pp = probs.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
    assert lib.MXTPUTrainGetOutput(handle, 0, pp, probs.size) == 0
    acc = (probs.reshape(32, 3).argmax(1) == y).mean()
    assert acc > 0.8, acc

    # trained params round-trip into the predict ABI's loader
    blob = ctypes.c_void_p()
    size = ctypes.c_int()
    assert lib.MXTPUTrainGetParams(handle, ctypes.byref(blob),
                                   ctypes.byref(size)) == 0
    raw = ctypes.string_at(blob, size.value)
    pfile = tmp_path / "trained.params"
    pfile.write_bytes(raw)
    from incubator_mxnet_tpu.model import split_tagged_params
    arg_p, aux_p = split_tagged_params(mx.nd.load(str(pfile)))
    assert "fc1_weight" in arg_p and "fc2_bias" in arg_p
    # rebuilding a python Module from the blob reproduces the output
    mod = mx.mod.Module(_train_symbol(), context=mx.cpu())
    mod.bind(data_shapes=[mx.io.DataDesc("data", (32, 6))],
             label_shapes=[mx.io.DataDesc("softmax_label", (32,))],
             for_training=False)
    mod.set_params(arg_p, aux_p)
    mod.forward(mx.io.DataBatch([mx.nd.array(x)],
                                [mx.nd.array(y)]), is_train=False)
    np.testing.assert_allclose(
        mod.get_outputs()[0].asnumpy().ravel(), probs, rtol=1e-4,
        atol=1e-5)

    assert lib.MXTPUTrainFree(handle) == 0

    # error surface: unknown input key fails loudly at create
    bad_keys = (ctypes.c_char_p * 2)(b"data", b"nope_label")
    h2 = ctypes.c_void_p()
    rc = lib.MXTPUTrainCreate(sym_json, None, 0, 1, 0, 2, bad_keys,
                              indptr, shape, b"sgd",
                              ctypes.c_float(0.1), ctypes.byref(h2))
    assert rc == -1
    assert b"nope_label" in lib.MXTPUTrainGetLastError()


def test_c_train_resume_from_params(tmp_path):
    """param_bytes at create resumes training instead of Xavier."""
    lib = _bind(ctypes.CDLL(_build_lib()))
    sym_json = _train_symbol().tojson().encode()
    x, y = _problem()
    keys = (ctypes.c_char_p * 2)(b"data", b"softmax_label")
    indptr = (ctypes.c_uint * 3)(0, 2, 3)
    shape = (ctypes.c_uint * 3)(32, 6, 32)

    h1 = ctypes.c_void_p()
    assert lib.MXTPUTrainCreate(sym_json, None, 0, 1, 0, 2, keys,
                                indptr, shape, b"sgd",
                                ctypes.c_float(0.5),
                                ctypes.byref(h1)) == 0
    xf, yf = x.ravel().copy(), y.ravel().copy()
    xp = xf.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
    yp = yf.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
    lib.MXTPUTrainSetInput(h1, b"data", xp, xf.size)
    lib.MXTPUTrainSetInput(h1, b"softmax_label", yp, yf.size)
    loss = ctypes.c_float()
    for _ in range(20):
        lib.MXTPUTrainStep(h1, ctypes.byref(loss))
    mid_loss = float(loss.value)
    blob, size = ctypes.c_void_p(), ctypes.c_int()
    assert lib.MXTPUTrainGetParams(h1, ctypes.byref(blob),
                                   ctypes.byref(size)) == 0
    raw = ctypes.string_at(blob, size.value)
    lib.MXTPUTrainFree(h1)

    h2 = ctypes.c_void_p()
    assert lib.MXTPUTrainCreate(sym_json, raw, len(raw), 1, 0, 2,
                                keys, indptr, shape, b"sgd",
                                ctypes.c_float(0.5),
                                ctypes.byref(h2)) == 0, \
        lib.MXTPUTrainGetLastError()
    lib.MXTPUTrainSetInput(h2, b"data", xp, xf.size)
    lib.MXTPUTrainSetInput(h2, b"softmax_label", yp, yf.size)
    assert lib.MXTPUTrainStep(h2, ctypes.byref(loss)) == 0
    # resumed loss continues from the trained state, not from scratch
    assert float(loss.value) < mid_loss * 1.5
    lib.MXTPUTrainFree(h2)


def test_c_train_regression_head_reports_mse():
    """Loss semantics follow the head op (VERDICT r4 next-step 10):
    a LinearRegressionOutput head must report mean squared error —
    not the mean of the predictions — and it must decrease."""
    lib = _bind(ctypes.CDLL(_build_lib()))
    # the glue shares this process's interpreter: pin the init draw so
    # convergence doesn't depend on sibling tests' PRNG consumption
    mx.random.seed(7)
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="rfc", num_hidden=1)
    sym = mx.sym.LinearRegressionOutput(net, name="lro")
    sym_json = sym.tojson().encode()

    rs = np.random.RandomState(1)
    x = rs.rand(32, 4).astype(np.float32)
    w = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
    y = (x @ w).astype(np.float32)

    keys = (ctypes.c_char_p * 2)(b"data", b"lro_label")
    indptr = (ctypes.c_uint * 3)(0, 2, 4)
    shape = (ctypes.c_uint * 4)(32, 4, 32, 1)
    h = ctypes.c_void_p()
    assert lib.MXTPUTrainCreate(sym_json, None, 0, 1, 0, 2, keys,
                                indptr, shape, b"adam",
                                ctypes.c_float(0.1),
                                ctypes.byref(h)) == 0, \
        lib.MXTPUTrainGetLastError()
    xf, yf = x.ravel().copy(), y.ravel().copy()
    lib.MXTPUTrainSetInput(
        h, b"data", xf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        xf.size)
    lib.MXTPUTrainSetInput(
        h, b"lro_label",
        yf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), yf.size)
    loss = ctypes.c_float()
    losses = []
    for _ in range(100):
        assert lib.MXTPUTrainStep(h, ctypes.byref(loss)) == 0
        losses.append(float(loss.value))

    # the first reported value must be an MSE (positive, plausibly
    # large), and training must shrink it hard on this linear problem
    assert losses[0] > 0.1, losses[0]
    assert losses[-1] < 0.05 * losses[0], (losses[0], losses[-1])

    # cross-check the final report against an MSE computed from the
    # outputs the ABI itself returns
    assert lib.MXTPUTrainForward(h) == 0
    out = np.empty(32, np.float32)
    assert lib.MXTPUTrainGetOutput(
        h, 0, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out.size) == 0
    mse = float(((out.reshape(32, 1) - y) ** 2).mean())
    assert abs(mse - losses[-1]) < max(0.1 * losses[-1], 1e-3), \
        (mse, losses[-1])
    lib.MXTPUTrainFree(h)
