"""Optimizer tests (ref: tests/python/unittest/test_optimizer.py)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, optimizer


def _quadratic_converges(opt, steps=120, tol=0.05):
    """All optimizers should minimize ||w||^2 from a fixed start."""
    w = nd.array(np.linspace(-1, 1, 8).astype("float32"))
    updater = optimizer.get_updater(opt)
    for _ in range(steps):
        grad = 2 * w
        updater(0, grad, w)
    return float(w.norm().asscalar())


@pytest.mark.parametrize("name,kwargs", [
    ("sgd", {"learning_rate": 0.1}),
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("nag", {"learning_rate": 0.05, "momentum": 0.9}),
    ("adam", {"learning_rate": 0.05}),
    ("adagrad", {"learning_rate": 0.3}),
    ("rmsprop", {"learning_rate": 0.02}),
    ("rmsprop", {"learning_rate": 0.02, "centered": True}),
    ("adadelta", {"rho": 0.9}),
    ("ftrl", {"learning_rate": 0.5}),
    ("adamax", {"learning_rate": 0.1}),
    ("nadam", {"learning_rate": 0.05}),
    ("signum", {"learning_rate": 0.01}),
])
def test_optimizer_convergence(name, kwargs):
    opt = optimizer.create(name, **kwargs)
    final = _quadratic_converges(opt)
    assert final < 0.25, f"{name} failed to converge: |w|={final}"


def test_sgd_matches_manual():
    w0 = np.array([1.0, -2.0, 3.0], "float32")
    g = np.array([0.5, 0.5, 0.5], "float32")
    w = nd.array(w0)
    opt = optimizer.create("sgd", learning_rate=0.1, wd=0.0)
    updater = optimizer.get_updater(opt)
    updater("x_weight", nd.array(g), w)
    np.testing.assert_allclose(w.asnumpy(), w0 - 0.1 * g, rtol=1e-6)


def test_weight_decay_and_clip():
    w = nd.array(np.ones(4, "float32"))
    opt = optimizer.create("sgd", learning_rate=1.0, wd=0.1,
                           clip_gradient=0.05)
    updater = optimizer.get_updater(opt)
    updater("x_weight", nd.array(np.full(4, 10.0, "float32")), w)
    # grad clipped to 0.05, wd adds 0.1*1
    np.testing.assert_allclose(w.asnumpy(),
                               np.ones(4) - 1.0 * (0.05 + 0.1),
                               rtol=1e-5)


def test_bias_no_decay_default():
    opt = optimizer.create("sgd", learning_rate=1.0, wd=0.5,
                           param_idx2name={0: "fc_bias"})
    assert opt._get_wd(0) == 0.0
    opt2 = optimizer.create("sgd", learning_rate=1.0, wd=0.5,
                            param_idx2name={0: "fc_weight"})
    assert opt2._get_wd(0) == 0.5


def test_multi_precision_sgd():
    w = nd.array(np.ones(4), dtype="float16")
    opt = optimizer.create("sgd", learning_rate=0.1, momentum=0.9,
                           multi_precision=True)
    updater = optimizer.get_updater(opt)
    for _ in range(3):
        updater(0, nd.array(np.ones(4), dtype="float16"), w)
    assert w.dtype == np.float16
    state = updater.states[0]
    assert isinstance(state, tuple) and state[1].dtype == np.float32


def test_lr_scheduler_factor():
    sched = mx.lr_scheduler.FactorScheduler(step=10, factor=0.5,
                                            base_lr=1.0)
    lrs = [sched(i) for i in [1, 11, 21, 31]]
    np.testing.assert_allclose(lrs, [1.0, 0.5, 0.25, 0.125])


def test_lr_scheduler_multifactor():
    sched = mx.lr_scheduler.MultiFactorScheduler([5, 15], factor=0.1,
                                                 base_lr=1.0)
    assert sched(2) == 1.0
    assert abs(sched(10) - 0.1) < 1e-9
    assert abs(sched(20) - 0.01) < 1e-9


def test_lr_in_optimizer_updates():
    opt = optimizer.create(
        "sgd", learning_rate=1.0,
        lr_scheduler=mx.lr_scheduler.FactorScheduler(1, 0.5))
    w = nd.array(np.zeros(1, "float32"))
    updater = optimizer.get_updater(opt)
    updater(0, nd.array(np.ones(1, "float32")), w)
    v1 = float(w.asscalar())
    updater(0, nd.array(np.ones(1, "float32")), w)
    assert abs(w.asscalar() - v1) < abs(v1)  # lr decayed


def test_updater_state_serialization():
    opt = optimizer.create("adam", learning_rate=0.1)
    w = nd.array(np.ones(3, "float32"))
    updater = optimizer.get_updater(opt)
    updater(0, nd.array(np.ones(3, "float32")), w)
    blob = updater.get_states()
    u2 = optimizer.get_updater(optimizer.create("adam",
                                                learning_rate=0.1))
    u2.set_states(blob)
    m1, v1 = updater.states[0]
    m2, v2 = u2.states[0]
    np.testing.assert_allclose(m1.asnumpy(), m2.asnumpy())
    np.testing.assert_allclose(v1.asnumpy(), v2.asnumpy())
