"""Resilience layer (resilience.py + its threading through dist/
kvstore/model/launch.py): backoff schedules, fault-spec parsing,
deadline-guarded collectives, atomic checksummed checkpoint saves,
corrupt-load fallback, and hung-worker heartbeat detection — all on
CPU, all via deterministic fault injection (docs/resilience.md)."""
import os
import subprocess
import sys
import threading
import time
import warnings

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import resilience as rz

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("MXTPU_FAULT_SPEC", raising=False)
    rz.reset_faults()
    yield
    rz.reset_faults()


# ---------------------------------------------------------------- policy
def test_backoff_schedule_exponential_capped():
    p = rz.RetryPolicy(max_retries=5, base_delay=0.5, max_delay=3.0,
                       jitter=0)
    assert p.delays() == [0.5, 1.0, 2.0, 3.0, 3.0]


def test_backoff_jitter_bounded_and_seed_deterministic():
    mk = lambda: rz.RetryPolicy(max_retries=4, base_delay=1.0,
                                max_delay=8.0, jitter=0.5, seed=7)
    a, b = mk().delays(), mk().delays()
    assert a == b                       # seeded: reproducible
    for base, d in zip([1.0, 2.0, 4.0, 8.0], a):
        assert base <= d <= base * 1.5  # jitter widens, never shrinks


def test_retry_call_retries_then_succeeds_and_exhausts():
    calls = []

    def flaky(fail_n):
        calls.append(1)
        if len(calls) <= fail_n:
            raise rz.TransientError("flake")
        return "ok"

    pol = rz.RetryPolicy(max_retries=3, base_delay=0.001,
                         max_delay=0.001, jitter=0)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        assert rz.retry_call(flaky, 2, policy=pol) == "ok"
    assert len(calls) == 3

    calls.clear()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        with pytest.raises(rz.TransientError):
            rz.retry_call(flaky, 99, policy=pol)
    assert len(calls) == 4              # 1 try + 3 retries


def test_transient_mapping_markers_split():
    def grpc_deadline():
        raise RuntimeError("DEADLINE_EXCEEDED: barrier timed out")

    # collective default: a transport deadline is NOT transient
    # (re-entering the op would desynchronize the ranks)
    with pytest.raises(RuntimeError, match="DEADLINE_EXCEEDED"):
        rz.call_transient_mapped(grpc_deadline)
    # coordinator join: the same failure is worth retrying
    with pytest.raises(rz.TransientError):
        rz.call_transient_mapped(grpc_deadline,
                                 markers=rz.JOIN_TRANSIENT_MARKERS)

    def unavailable():
        raise RuntimeError("UNAVAILABLE: connection reset")

    with pytest.raises(rz.TransientError):
        rz.call_transient_mapped(unavailable)

    def misconfig():
        raise RuntimeError("invalid process id 7")

    with pytest.raises(RuntimeError, match="invalid process id"):
        rz.call_transient_mapped(misconfig,
                                 markers=rz.JOIN_TRANSIENT_MARKERS)
    # resilience errors always pass through unmapped
    with pytest.raises(rz.DeadlineExceededError):
        rz.call_transient_mapped(rz.deadline_call,
                                 lambda: time.sleep(5), 0.1)


def test_retry_call_does_not_catch_other_errors():
    def boom():
        raise ValueError("not transient")

    with pytest.raises(ValueError):
        rz.retry_call(boom, policy=rz.RetryPolicy(3, 0.001, 0.001, 0))


# ---------------------------------------------------------------- deadline
def test_deadline_call_passthrough_and_timeout():
    assert rz.deadline_call(lambda: 5, 10, op_name="x") == 5
    assert rz.deadline_call(lambda: 5, 0, op_name="x") == 5  # disabled
    t0 = time.time()
    with pytest.raises(rz.DeadlineExceededError, match="myop.*det=1"):
        rz.deadline_call(lambda: time.sleep(30), 0.2,
                         op_name="myop", detail="det=1")
    assert time.time() - t0 < 5


def test_deadline_call_propagates_worker_exception():
    def boom():
        raise KeyError("inner")

    with pytest.raises(KeyError):
        rz.deadline_call(boom, 5, op_name="x")


# ---------------------------------------------------------------- faults
def test_fault_spec_rejects_data_kinds_outside_checkpoint_scope():
    with pytest.raises(ValueError, match="only.*checkpoint"):
        rz.parse_fault_spec("collective:allreduce:1:truncate")
    with pytest.raises(ValueError, match="only.*checkpoint"):
        rz.parse_fault_spec("heartbeat:beat:1:corrupt")


def test_fault_spec_parsing():
    specs = rz.parse_fault_spec(
        "collective:allreduce:2:hang, checkpoint:save:1:truncate,"
        "heartbeat:beat:*:hang")
    assert specs == [("collective", "allreduce", 2, "hang"),
                     ("checkpoint", "save", 1, "truncate"),
                     ("heartbeat", "beat", "*", "hang")]
    assert rz.parse_fault_spec("") == []
    for bad in ("nope", "a:b:c", "a:b:0:hang", "a:b:x:hang",
                "a:b:1:explode", ":op:1:hang"):
        with pytest.raises(ValueError, match="fault spec"):
            rz.parse_fault_spec(bad)


def test_fault_counters_fire_on_nth_call(monkeypatch):
    monkeypatch.setenv("MXTPU_FAULT_SPEC",
                       "kv:push:2:error,kv:pull:*:error")
    rz.reset_faults()
    assert rz.fault_for("kv", "push") is None          # call 1
    assert rz.fault_for("kv", "push") == "error"       # call 2
    assert rz.fault_for("kv", "push") is None          # call 3
    for _ in range(3):
        assert rz.fault_for("kv", "pull") == "error"   # every call
    assert rz.fault_for("other", "push") is None


def test_injected_error_raises_transient(monkeypatch):
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "dist:init:1:error")
    rz.reset_faults()
    with pytest.raises(rz.TransientError, match="dist:init"):
        rz.inject("dist", "init")
    rz.inject("dist", "init")           # call 2: clean


# ------------------------------------------------------- dist collectives
def test_injected_collective_hang_times_out_with_diagnostics(
        monkeypatch):
    """Acceptance (a): a hung collective surfaces a timeout error
    naming op, tag and rank rather than blocking forever."""
    import jax.numpy as jnp

    monkeypatch.setenv("MXTPU_FAULT_SPEC", "collective:allreduce:1:hang")
    monkeypatch.setenv("MXTPU_COLLECTIVE_TIMEOUT", "0.5")
    monkeypatch.setenv("MXTPU_FAULT_HANG_S", "3")
    rz.reset_faults()
    t0 = time.time()
    with pytest.raises(rz.DeadlineExceededError) as exc:
        mx.dist.allreduce_sum(jnp.ones((3,)))
    assert time.time() - t0 < 5
    msg = str(exc.value)
    assert "allreduce" in msg and "rank=0" in msg and "tag=" in msg
    # only the 1st call was poisoned; the op itself still works
    out = mx.dist.allreduce_sum(jnp.ones((3,)))
    np.testing.assert_allclose(np.asarray(out), 1.0)


def test_injected_barrier_hang_names_tag(monkeypatch):
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "collective:barrier:1:hang")
    monkeypatch.setenv("MXTPU_COLLECTIVE_TIMEOUT", "0.4")
    monkeypatch.setenv("MXTPU_FAULT_HANG_S", "3")
    rz.reset_faults()
    with pytest.raises(rz.DeadlineExceededError,
                       match="barrier.*tag=mytag"):
        mx.dist.barrier("mytag")


def test_kvstore_push_retries_injected_transient(monkeypatch):
    """kvstore collective transport absorbs a transient dist error
    via retry_call (the kvstore.push/init code path)."""
    from incubator_mxnet_tpu.kvstore import KVStore

    monkeypatch.setenv("MXTPU_FAULT_SPEC", "collective:broadcast:1:error")
    monkeypatch.setenv("MXTPU_RETRY_BASE_DELAY_S", "0.001")
    monkeypatch.setenv("MXTPU_RETRY_MAX_DELAY_S", "0.001")
    rz.reset_faults()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        out = KVStore._dist_retry(mx.dist.broadcast,
                                  "kvstore.init.broadcast",
                                  np.ones((2,), np.float32))
    np.testing.assert_allclose(np.asarray(out), 1.0)


def test_join_retry_resets_jax_global_state(monkeypatch):
    """A transient coordinator-join failure must clear jax's
    distributed global state before the retry: jax sets
    global_state.client before connect(), so without the reset every
    retry dies on 'should only be called once' instead of
    re-attempting the join."""
    import jax
    from jax._src.distributed import global_state
    from incubator_mxnet_tpu import dist

    monkeypatch.setenv("MXTPU_RETRY_BASE_DELAY_S", "0.001")
    monkeypatch.setenv("MXTPU_RETRY_MAX_DELAY_S", "0.001")
    attempts = []

    def fake_initialize(**kwargs):
        attempts.append(kwargs["process_id"])
        if len(attempts) < 3:
            # mimic jax: leave globals populated, then fail connect
            global_state.client = object()
            raise RuntimeError("UNAVAILABLE: failed to connect to "
                               "coordinator")
        assert global_state.client is None     # reset happened

    monkeypatch.setattr(jax.distributed, "initialize",
                        fake_initialize)
    monkeypatch.setattr(dist, "_initialized", False)
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            r = dist.init(coordinator_address="127.0.0.1:1",
                          num_workers_=2, rank_=1)
        assert r == 1 and len(attempts) == 3
    finally:
        monkeypatch.setattr(dist, "_initialized", False)
        global_state.client = None
        rz.stop_heartbeat()


def test_multirank_in_op_failure_is_never_retried(monkeypatch):
    """An in-op transport error on a multi-rank collective surfaces
    as CollectiveAbortedError and is NOT retried — peers may have
    completed the op, and a rank-local re-entry would pair with
    their next collective (rank desync)."""
    import jax
    from jax.experimental import multihost_utils
    from incubator_mxnet_tpu.kvstore import KVStore

    calls = []

    def failing_allgather(v):
        calls.append(1)
        raise RuntimeError("UNAVAILABLE: connection reset by peer")

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(multihost_utils, "process_allgather",
                        failing_allgather)
    with pytest.raises(rz.CollectiveAbortedError,
                       match="not retried"):
        KVStore._dist_retry(mx.dist.allreduce_sum,
                            "kvstore.push(w).allreduce",
                            np.ones((2,), np.float32))
    # UNAVAILABLE would count as transient pre-entry; in-op it must
    # produce exactly one attempt
    assert len(calls) == 1


# ------------------------------------------------------- atomic ckpt io
def test_atomic_save_reader_never_sees_partial_file(tmp_path):
    """Acceptance (c): while a slow multi-chunk save is in flight the
    destination path stays absent; whenever it is visible it is the
    complete payload, never a torn prefix."""
    path = str(tmp_path / "big.bin")
    chunks = 20

    def slow_writer(f):
        for _ in range(chunks):
            f.write(os.urandom(1 << 14))
            f.flush()
            time.sleep(0.01)

    observations = []
    done = threading.Event()

    def poller():
        while not done.is_set():
            try:
                observations.append(os.path.getsize(path))
            except OSError:
                pass                    # not visible yet
            time.sleep(0.002)

    t = threading.Thread(target=poller)
    t.start()
    try:
        rz.atomic_save(path, slow_writer)
    finally:
        done.set()
        t.join()
    # every observation of the path (if any raced in before done) was
    # of the full payload — a reader can never see a partial file
    assert all(sz == chunks * (1 << 14) for sz in observations)
    assert rz.verify_checkpoint(path, require_sidecar=True)
    assert os.path.getsize(path) == chunks * (1 << 14)
    # no stray temp files left behind
    leftovers = [p for p in os.listdir(tmp_path) if ".tmp." in p]
    assert leftovers == []


def test_atomic_save_failure_leaves_no_temp(tmp_path):
    path = str(tmp_path / "x.bin")

    def bad_writer(f):
        raise RuntimeError("disk on fire")

    with pytest.raises(RuntimeError):
        rz.atomic_save(path, bad_writer)
    assert os.listdir(tmp_path) == []


def test_nd_save_writes_sidecar_and_detects_corruption(tmp_path):
    path = str(tmp_path / "w.params")
    mx.nd.save(path, {"a": mx.nd.ones((4, 4))})
    assert os.path.exists(rz.checksum_path(path))
    assert rz.verify_checkpoint(path, require_sidecar=True)
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) // 2)
    with pytest.raises(rz.CheckpointCorruptError):
        mx.nd.load(path)


def test_legacy_checkpoint_without_sidecar_still_loads(tmp_path):
    path = str(tmp_path / "legacy.params")
    mx.nd.save(path, {"a": mx.nd.ones((2,))})
    os.unlink(rz.checksum_path(path))
    out = mx.nd.load(path)
    np.testing.assert_allclose(out["a"].asnumpy(), 1.0)


def test_truncated_checkpoint_falls_back_to_last_good(
        tmp_path, monkeypatch):
    """Acceptance (b): a truncated checkpoint load falls back to the
    newest valid earlier checkpoint, with a warning."""
    prefix = str(tmp_path / "model")
    mx.model.save_checkpoint(prefix, 1, None,
                             {"w": mx.nd.full((3,), 1.0)}, {})
    mx.model.save_checkpoint(prefix, 2, None,
                             {"w": mx.nd.full((3,), 2.0)}, {})
    # epoch 3's save is torn by an injected fault
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "checkpoint:save:1:truncate")
    rz.reset_faults()
    mx.model.save_checkpoint(prefix, 3, None,
                             {"w": mx.nd.full((3,), 3.0)}, {})
    monkeypatch.delenv("MXTPU_FAULT_SPEC")
    assert not rz.verify_checkpoint(prefix + "-0003.params")

    with pytest.warns(RuntimeWarning, match="falling back.*epoch 2"):
        _, arg, _ = mx.model.load_checkpoint(prefix, 3)
    np.testing.assert_allclose(arg["w"].asnumpy(), 2.0)

    # fallback disabled -> the corruption surfaces
    with pytest.raises(rz.CheckpointCorruptError):
        mx.model.load_checkpoint(prefix, 3, fallback=False)


def test_corrupt_with_no_valid_predecessor_raises(tmp_path,
                                                  monkeypatch):
    prefix = str(tmp_path / "solo")
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "checkpoint:save:1:corrupt")
    rz.reset_faults()
    mx.model.save_checkpoint(prefix, 1, None,
                             {"w": mx.nd.ones((2,))}, {})
    monkeypatch.delenv("MXTPU_FAULT_SPEC")
    with pytest.raises(rz.CheckpointCorruptError,
                       match="no earlier checkpoint"):
        mx.model.load_checkpoint(prefix, 1)


def test_optimizer_states_atomic_and_validated(tmp_path):
    kv = mx.kvstore.create("local")
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1))
    fname = str(tmp_path / "opt.states")
    kv.save_optimizer_states(fname)
    assert rz.verify_checkpoint(fname, require_sidecar=True)
    kv.load_optimizer_states(fname)
    with open(fname, "r+b") as f:
        f.truncate(2)
    with pytest.raises(rz.CheckpointCorruptError):
        kv.load_optimizer_states(fname)
    # legacy pre-sidecar truncated states (no .crc32 to fail against)
    # must still surface as CheckpointCorruptError, not a raw pickle
    # error
    os.unlink(rz.checksum_path(fname))
    with pytest.raises(rz.CheckpointCorruptError, match="decode"):
        kv.load_optimizer_states(fname)
    # legacy bit-flip (not truncation): corrupt pickles raise
    # arbitrary exception types, all of which must map to
    # CheckpointCorruptError so degrade paths can catch them
    kv.save_optimizer_states(fname)
    os.unlink(rz.checksum_path(fname))
    with open(fname, "r+b") as f:
        f.seek(os.path.getsize(fname) // 2)
        b = f.read(1)
        f.seek(-1, 1)
        f.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(rz.CheckpointCorruptError):
        kv.load_optimizer_states(fname)


def test_load_checkpoint_reports_effective_epoch(tmp_path,
                                                 monkeypatch):
    prefix = str(tmp_path / "m")
    mx.model.save_checkpoint(prefix, 1, None,
                             {"w": mx.nd.full((2,), 1.0)}, {})
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "checkpoint:save:1:truncate")
    rz.reset_faults()
    mx.model.save_checkpoint(prefix, 2, None,
                             {"w": mx.nd.full((2,), 2.0)}, {})
    monkeypatch.delenv("MXTPU_FAULT_SPEC")
    with pytest.warns(RuntimeWarning, match="falling back"):
        _, arg, _, eff = mx.model.load_checkpoint(prefix, 2,
                                                  return_epoch=True)
    assert eff == 1                 # callers pair .states with this
    np.testing.assert_allclose(arg["w"].asnumpy(), 1.0)
    _, _, _, eff = mx.model.load_checkpoint(prefix, 1,
                                            return_epoch=True)
    assert eff == 1


def test_fallback_survives_unpadded_checkpoint_names(tmp_path,
                                                     monkeypatch):
    """An unpadded (hand-renamed / external) params file in the
    fallback scan must be opened by its real path, not a re-derived
    :04d name — and must not abort the fallback to older epochs."""
    prefix = str(tmp_path / "m")
    mx.model.save_checkpoint(prefix, 6, None,
                             {"w": mx.nd.full((2,), 6.0)}, {})
    # epoch 7 saved under an unpadded name
    mx.nd.save(prefix + "-7.params", {"arg:w": mx.nd.full((2,), 7.0)})
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "checkpoint:save:1:truncate")
    rz.reset_faults()
    mx.model.save_checkpoint(prefix, 8, None,
                             {"w": mx.nd.full((2,), 8.0)}, {})
    monkeypatch.delenv("MXTPU_FAULT_SPEC")
    with pytest.warns(RuntimeWarning, match="falling back.*epoch 7"):
        _, arg, _, eff = mx.model.load_checkpoint(prefix, 8,
                                                  return_epoch=True)
    assert eff == 7
    np.testing.assert_allclose(arg["w"].asnumpy(), 7.0)
    # direct request of the unpadded epoch resolves through the same
    # on-disk scan
    _, arg, _ = mx.model.load_checkpoint(prefix, 7)
    np.testing.assert_allclose(arg["w"].asnumpy(), 7.0)


def test_padded_name_wins_epoch_ties_for_params_and_companions(
        tmp_path):
    """When a padded and an unpadded file both claim an epoch, every
    resolver (primary load, fallback scan, companion path) picks the
    canonical padded one — weights and .states must share a stem."""
    prefix = str(tmp_path / "ck")
    mx.model.save_checkpoint(prefix, 7, None,
                             {"w": mx.nd.full((2,), 1.0)}, {})
    mx.nd.save(prefix + "-7.params", {"arg:w": mx.nd.full((2,), 2.0)})
    _, arg, _ = mx.model.load_checkpoint(prefix, 7)
    np.testing.assert_allclose(arg["w"].asnumpy(), 1.0)
    assert mx.model.checkpoint_companion_path(prefix, 7) == \
        prefix + "-0007.states"
    assert mx.model._checkpoint_epochs(prefix) == \
        [(7, prefix + "-0007.params")]


def test_object_dtype_archive_is_not_corruption(tmp_path):
    """A well-formed npz with object-dtype members is a format
    mismatch and must stay loud — not CheckpointCorruptError, which
    would silently fall back to an older epoch."""
    p = str(tmp_path / "obj.params")
    with open(p, "wb") as f:
        np.savez(f, a=np.array([{"x": 1}], dtype=object))
    with pytest.raises(ValueError, match="allow_pickle"):
        mx.nd.load(p)


def test_feedforward_load_begin_epoch_follows_fallback(tmp_path,
                                                       monkeypatch):
    """FeedForward.load must number epochs from the checkpoint that
    actually loaded, not the one requested — or a post-fallback fit()
    would attribute saves/LR schedules to epochs the substituted
    weights never trained through."""
    prefix = str(tmp_path / "ff")
    mx.model.save_checkpoint(prefix, 1, None,
                             {"w": mx.nd.full((2,), 1.0)}, {})
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "checkpoint:save:1:truncate")
    rz.reset_faults()
    mx.model.save_checkpoint(prefix, 2, None,
                             {"w": mx.nd.full((2,), 2.0)}, {})
    monkeypatch.delenv("MXTPU_FAULT_SPEC")
    with pytest.warns(RuntimeWarning, match="falling back"):
        model = mx.model.FeedForward.load(prefix, 2)
    assert model.begin_epoch == 1
    np.testing.assert_allclose(model.arg_params["w"].asnumpy(), 1.0)


def test_crash_before_sidecar_write_leaves_loadable_file(tmp_path,
                                                         monkeypatch):
    """A save killed between the data rename and the sidecar write
    must leave a loadable checkpoint: the stale sidecar is removed
    before the rename, and a missing sidecar passes validation — the
    renamed data file is complete (it was fsynced as a temp), so
    rejecting it would block resume for nothing."""
    path = str(tmp_path / "re.params")
    mx.nd.save(path, {"w": mx.nd.full((2,), 1.0)})

    def die_before_sidecar(p, data):
        raise OSError("simulated crash before sidecar commit")

    monkeypatch.setattr(rz, "_replace_with_bytes", die_before_sidecar)
    with pytest.raises(OSError, match="simulated crash"):
        mx.nd.save(path, {"w": mx.nd.full((2,), 2.0)})
    monkeypatch.undo()

    assert not os.path.exists(rz.checksum_path(path))
    out = mx.nd.load(path)      # new, complete data — no stale-CRC veto
    np.testing.assert_allclose(out["w"].asnumpy(), 2.0)


def test_parameter_dict_load_reports_corruption(tmp_path):
    from incubator_mxnet_tpu.gluon.parameter import ParameterDict

    fname = str(tmp_path / "p.params")
    pd = ParameterDict()
    p = pd.get("w", shape=(2,))
    p.initialize()
    pd.save(fname)
    with open(fname, "r+b") as f:
        f.truncate(4)
    with pytest.raises(rz.CheckpointCorruptError, match="p.params"):
        ParameterDict().load(fname)


# ------------------------------------------------------- heartbeats
def test_module_load_degrades_to_fresh_optimizer_state(tmp_path,
                                                       monkeypatch):
    """Module.load(load_optimizer_states=True) whose paired .states
    file is missing (fallback epoch never had one, or retention
    removed it) resumes with fresh optimizer state and a warning,
    not a crashed resume."""
    prefix = str(tmp_path / "mm")
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=2, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    X = np.random.RandomState(0).randn(8, 3).astype("float32")
    y = (X.sum(1) > 0).astype("float32")
    it = mx.io.NDArrayIter(X, y, batch_size=4,
                           label_name="softmax_label")
    mod = mx.mod.Module(net, label_names=["softmax_label"])
    mod.fit(it, num_epoch=1,
            epoch_end_callback=mx.callback.do_checkpoint(prefix))
    assert not os.path.exists(prefix + "-0001.states")
    loaded = mx.mod.Module.load(prefix, 1, load_optimizer_states=True)
    loaded.bind(data_shapes=it.provide_data,
                label_shapes=it.provide_label)
    with pytest.warns(RuntimeWarning, match="freshly initialized"):
        loaded.init_optimizer()
    assert loaded._preload_opt_states is None


def test_read_validated_bytes_single_pass(tmp_path):
    """read_validated_bytes returns the payload in one disk pass and
    still vetoes a post-save truncation."""
    p = str(tmp_path / "b.params")
    rz.atomic_write_bytes(p, b"payload-bytes")
    assert rz.read_validated_bytes(p) == b"payload-bytes"
    with open(p, "r+b") as f:
        f.truncate(3)
    with pytest.raises(rz.CheckpointCorruptError):
        rz.read_validated_bytes(p)


def test_heartbeat_thread_refreshes_file(tmp_path):
    path = str(tmp_path / "hb")
    try:
        assert rz.start_heartbeat(path, interval=0.05) == path
        deadline = time.time() + 5
        while not os.path.exists(path) and time.time() < deadline:
            time.sleep(0.01)
        m1 = os.path.getmtime(path)
        deadline = time.time() + 5
        while os.path.getmtime(path) == m1 and time.time() < deadline:
            time.sleep(0.01)
        assert os.path.getmtime(path) > m1
    finally:
        rz.stop_heartbeat()


def test_heartbeat_retargets_on_new_path(tmp_path):
    """start_heartbeat with a different path must stop the old beat
    and refresh the new file — or a monitor watching the new path
    would kill a healthy worker."""
    a, b = str(tmp_path / "hb-a"), str(tmp_path / "hb-b")
    try:
        assert rz.start_heartbeat(a, interval=0.05) == a
        assert rz.start_heartbeat(b, interval=0.05) == b
        deadline = time.time() + 5
        while not os.path.exists(b) and time.time() < deadline:
            time.sleep(0.02)
        assert os.path.exists(b)
    finally:
        rz.stop_heartbeat()


def test_heartbeat_disabled_without_path(monkeypatch):
    monkeypatch.delenv("MXTPU_HEARTBEAT_FILE", raising=False)
    assert rz.start_heartbeat() is None


def test_launch_kills_hung_worker_via_heartbeat(tmp_path):
    """A worker that beats once then goes silent (but never exits) is
    detected as hung, killed, and fails the job — instead of blocking
    the launcher forever."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    script = (
        "import os, time\n"
        "hb = os.environ['MXTPU_HEARTBEAT_FILE']\n"
        "open(hb, 'w').write('beat')\n"      # one beat, then wedge
        "if os.environ['MXTPU_WORKER_RANK'] == '0':\n"
        "    time.sleep(600)\n"
        "time.sleep(600)\n")
    t0 = time.time()
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--heartbeat-timeout", "1",
         "--heartbeat-interval", "0.2", "--",
         sys.executable, "-c", script],
        capture_output=True, text=True, timeout=60, env=env, cwd=REPO)
    assert r.returncode != 0, r.stdout + r.stderr
    assert "hung" in r.stderr, r.stderr[-2000:]
    assert time.time() - t0 < 50


def test_launch_rejects_interval_exceeding_timeout():
    """A heartbeat interval the timeout can't accommodate would make
    the monitor kill every healthy worker — launch refuses it."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "1", "--heartbeat-timeout", "1",
         "--heartbeat-interval", "2", "--",
         sys.executable, "-c", "pass"],
        capture_output=True, text=True, timeout=60, cwd=REPO)
    assert r.returncode != 0
    assert "at least twice" in r.stderr


def test_launch_heartbeat_not_required_for_fast_jobs(tmp_path):
    """Workers that never write a heartbeat file (non-framework
    commands) are unmonitored and finish normally."""
    env = dict(os.environ)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--heartbeat-timeout", "1",
         "--heartbeat-interval", "0.2", "--",
         sys.executable, "-c", "print('fine')"],
        capture_output=True, text=True, timeout=60, env=env, cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr


# ------------------------------------------------------- misc satellites
def test_log_metrics_callback_closes_writer(tmp_path):
    from incubator_mxnet_tpu.contrib.tensorboard import (
        LogMetricsCallback, _JsonlWriter)

    w = _JsonlWriter(str(tmp_path))
    with LogMetricsCallback(str(tmp_path), summary_writer=w) as cb:
        assert cb.writer is w
    assert cb.writer is None
    assert not w._f.closed          # caller-owned: not closed for them
    w.close()

    cb2 = LogMetricsCallback(str(tmp_path))
    inner = cb2.writer
    cb2.close()
    if isinstance(inner, _JsonlWriter):
        assert inner._f.closed      # owned: released


def test_list_env_exported_via_star():
    import incubator_mxnet_tpu as pkg

    assert "list_env" in pkg.__all__
    ns = {}
    exec("from incubator_mxnet_tpu import *", ns)
    assert callable(ns["list_env"])
    assert "MXTPU_COLLECTIVE_TIMEOUT" in pkg.list_env()
