"""Real sparse storage (VERDICT r2 task 4): no O(full-shape) buffer
on construction or through the sparse kernel/kvstore paths, plus the
LibSVM linear-classification convergence gate (driver config 5,
ref: example/sparse/linear_classification.py).
"""
import os

import numpy as np

import jax.numpy as jnp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.ndarray import sparse


def test_row_sparse_no_dense_buffer_at_scale():
    """(100000, 128) row-sparse with 5 rows must allocate O(k), not
    O(vocab) — the round-2 verdict's memory gate."""
    k, vocab, dim = 5, 100_000, 128
    rows = np.arange(0, k * 7, 7, dtype=np.int64)
    vals = np.random.RandomState(0).rand(k, dim).astype(np.float32)
    arr = sparse.row_sparse_array((vals, rows), shape=(vocab, dim))
    assert not arr.has_dense_mirror()
    assert arr.shape == (vocab, dim)
    assert arr.data.shape == (k, dim)
    assert arr.indices.shape == (k,)
    # sparse kernels keep it sparse
    kept = sparse.retain(arr, mx.nd.array([0, 7, 9], dtype="int64"))
    assert not arr.has_dense_mirror()
    assert not kept.has_dense_mirror()
    np.testing.assert_allclose(np.asarray(kept.data._data)[0], vals[0])
    np.testing.assert_allclose(np.asarray(kept.data._data)[2], 0.0)
    both = sparse.elemwise_add(arr, kept)
    assert not both.has_dense_mirror()
    # densify only on explicit request
    dense = arr.tostype("default")
    assert dense.shape == (vocab, dim)


def test_csr_no_dense_buffer_and_dot():
    rs = np.random.RandomState(1)
    dense = np.zeros((6, 50_000), np.float32)
    cols = rs.randint(0, 50_000, 30)
    dense[rs.randint(0, 6, 30), cols] = rs.rand(30)
    csr = sparse.csr_matrix(dense)
    assert not csr.has_dense_mirror()
    w = mx.nd.array(rs.rand(50_000, 4).astype(np.float32))
    out = sparse.dot(csr, w)
    assert not csr.has_dense_mirror()
    np.testing.assert_allclose(np.asarray(out._data), dense @
                               np.asarray(w._data), rtol=1e-4,
                               atol=1e-5)


def test_csr_T_dot_row_sparse_output():
    """dot(csr.T, dense, forward_stype='row_sparse') returns only the
    touched columns (the embedding-grad path)."""
    rs = np.random.RandomState(2)
    dense = np.zeros((4, 1000), np.float32)
    dense[0, 5] = 1.0
    dense[1, 5] = 2.0
    dense[2, 700] = 3.0
    csr = sparse.csr_matrix(dense)
    d = rs.rand(4, 3).astype(np.float32)
    out = sparse.dot(csr, mx.nd.array(d), transpose_a=True,
                     forward_stype="row_sparse")
    assert isinstance(out, sparse.RowSparseNDArray)
    assert not out.has_dense_mirror()
    got_idx = np.asarray(out.indices._data)
    np.testing.assert_array_equal(got_idx, [5, 700])
    want = dense.T @ d
    np.testing.assert_allclose(np.asarray(out.data._data),
                               want[[5, 700]], rtol=1e-5)


def test_kvstore_row_sparse_pull_sparse_out():
    vocab, dim = 10_000, 16
    kv = mx.kvstore.create("local")
    w = mx.nd.array(np.random.RandomState(3).rand(vocab, dim)
                    .astype(np.float32))
    kv.init("emb", w)
    out = sparse.zeros("row_sparse", (vocab, dim))
    rids = mx.nd.array([3, 8, 42], dtype="int64")
    kv.row_sparse_pull("emb", out=out, row_ids=rids)
    assert not out.has_dense_mirror()
    assert out.data.shape == (3, dim)
    np.testing.assert_allclose(np.asarray(out.data._data),
                               np.asarray(w._data)[[3, 8, 42]])


def _write_libsvm(path, X, y):
    with open(path, "w") as f:
        for row, label in zip(X, y):
            items = [str(float(label))]
            for j in np.nonzero(row)[0]:
                items.append(f"{j}:{row[j]:.6f}")
            f.write(" ".join(items) + "\n")


def test_libsvm_linear_classification_converges(tmp_path):
    """Driver config 5: sparse logistic regression on LibSVM data
    (ref: example/sparse/linear_classification.py) — CSR batches,
    sparse dot forward, row-sparse gradient, lazy sgd update."""
    rs = np.random.RandomState(4)
    n, d = 512, 2000
    X = np.zeros((n, d), np.float32)
    for i in range(n):
        nz = rs.choice(d, 20, replace=False)
        X[i, nz] = rs.rand(20)
    w_true = np.zeros(d, np.float32)
    w_true[rs.choice(d, 100, replace=False)] = \
        rs.randn(100).astype(np.float32) * 2
    y = (X @ w_true > 0).astype(np.float32)
    path = tmp_path / "train.libsvm"
    _write_libsvm(path, X, y)

    batch = 64
    it = mx.io.LibSVMIter(data_libsvm=str(path), data_shape=(d,),
                          batch_size=batch)
    weight = mx.nd.array(np.zeros((d, 1), np.float32))
    losses = []
    for epoch in range(15):
        it.reset()
        total, nb = 0.0, 0
        for b in it:
            csr = b.data[0]
            assert isinstance(csr, sparse.CSRNDArray)
            label = b.label[0]._data.reshape(-1, 1)
            logits = sparse.dot(csr, weight)._data
            p = 1.0 / (1.0 + jnp.exp(-logits))
            eps = 1e-7
            loss = -jnp.mean(label * jnp.log(p + eps)
                             + (1 - label) * jnp.log(1 - p + eps))
            dlogits = (p - label) / label.shape[0]
            grad = sparse.dot(csr, mx.nd.NDArray(dlogits),
                              transpose_a=True,
                              forward_stype="row_sparse")
            assert isinstance(grad, sparse.RowSparseNDArray)
            sparse.sgd_update(mx.nd.NDArray(weight._data), grad,
                              lr=5.0, out=weight)
            total += float(loss)
            nb += 1
        losses.append(total / nb)
    assert losses[-1] < 0.35 * losses[0], losses
    acc = float(np.mean(
        ((X @ np.asarray(weight._data).ravel()) > 0) == y))
    assert acc > 0.9, acc


def test_retain_empty_and_duplicate_semantics():
    """Regression (round-3 review): retain on an empty array returns
    zero rows; duplicate stored indices sum (scatter-add semantics)."""
    z = sparse.zeros("row_sparse", (5, 2))
    kept = sparse.retain(z, mx.nd.array([0, 3], dtype="int64"))
    np.testing.assert_allclose(np.asarray(kept.data._data),
                               np.zeros((2, 2)))
    g = sparse.row_sparse_array(
        (np.array([[1.0], [3.0]], np.float32),
         np.array([2, 2], np.int64)), shape=(5, 1))
    kept = sparse.retain(g, mx.nd.array([2], dtype="int64"))
    np.testing.assert_allclose(np.asarray(kept.data._data), [[4.0]])
    # unsorted want order is preserved
    g2 = sparse.row_sparse_array(
        (np.array([[1.0], [2.0]], np.float32),
         np.array([1, 3], np.int64)), shape=(5, 1))
    kept = sparse.retain(g2, mx.nd.array([3, 1], dtype="int64"))
    np.testing.assert_array_equal(np.asarray(kept.indices._data),
                                  [3, 1])
    np.testing.assert_allclose(np.asarray(kept.data._data),
                               [[2.0], [1.0]])


def test_row_sparse_pull_duplicate_row_ids():
    """Regression (round-3 review): duplicated row_ids must not
    double the pulled rows on densify."""
    kv = mx.kvstore.create("local")
    w = mx.nd.array(np.arange(8, dtype=np.float32).reshape(4, 2))
    kv.init("w", w)
    out = sparse.zeros("row_sparse", (4, 2))
    kv.row_sparse_pull("w", out=out,
                       row_ids=mx.nd.array([1, 1, 3], dtype="int64"))
    dense = np.asarray(out._data)
    np.testing.assert_allclose(dense[1], [2.0, 3.0])
    np.testing.assert_allclose(dense[3], [6.0, 7.0])
