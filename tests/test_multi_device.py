"""group2ctx placement (ref: tests/python/unittest/
test_multi_device_exec.py:22 test_ctx_group).

On the 8-virtual-CPU-device conftest mesh, cpu(1)/cpu(2) are distinct
jax devices, so placement and the cross-device copies are real."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx


def _mlp():
    with mx.AttrScope(ctx_group="stage1"):
        data = mx.sym.Variable("data")
        fc1 = mx.sym.FullyConnected(data=data, name="fc1",
                                    num_hidden=32)
        act1 = mx.sym.Activation(data=fc1, name="relu1",
                                 act_type="relu")
    set_stage1 = set(act1.list_arguments())
    with mx.AttrScope(ctx_group="stage2"):
        fc2 = mx.sym.FullyConnected(data=act1, name="fc2",
                                    num_hidden=16)
        act2 = mx.sym.Activation(data=fc2, name="relu2",
                                 act_type="relu")
        fc3 = mx.sym.FullyConnected(data=act2, name="fc3",
                                    num_hidden=10)
        mlp = mx.sym.SoftmaxOutput(data=fc3, name="softmax")
    return mlp, set_stage1


@pytest.mark.parametrize("grad_req", ["write", "null_data"])
def test_ctx_group_placement_and_numerics(grad_req):
    mlp, set_stage1 = _mlp()
    group2ctx = {"stage1": mx.cpu(1), "stage2": mx.cpu(2)}
    if grad_req == "null_data":
        grad_req = {a: ("null" if a == "data" else "write")
                    for a in mlp.list_arguments()}
    texec = mlp.simple_bind(mx.cpu(0), group2ctx=group2ctx,
                            data=(4, 20), softmax_label=(4,),
                            grad_req=grad_req)

    # arg arrays allocated on their group's context (reference assert)
    for arr, name in zip(texec.arg_arrays, mlp.list_arguments()):
        want = group2ctx["stage1" if name in set_stage1 else "stage2"]
        assert arr.context == want, (name, arr.context)
        assert arr._data.devices() == {want.jax_device}

    # numerics must match an un-grouped single-device bind
    rng = np.random.RandomState(0)
    vals = {n: rng.normal(size=a.shape).astype(np.float32)
            for n, a in zip(mlp.list_arguments(), texec.arg_arrays)}
    vals["softmax_label"] = rng.randint(
        0, 10, size=(4,)).astype(np.float32)
    ref = mlp.simple_bind(mx.cpu(0), data=(4, 20),
                          softmax_label=(4,), grad_req="write")
    for n in mlp.list_arguments():
        texec.arg_dict[n][:] = vals[n]
        ref.arg_dict[n][:] = vals[n]
    out = texec.forward(is_train=True)[0].asnumpy()
    out_ref = ref.forward(is_train=True)[0].asnumpy()
    np.testing.assert_allclose(out, out_ref, rtol=1e-5, atol=1e-6)

    texec.backward()
    ref.backward()
    for n in mlp.list_arguments():
        g, gr = texec.grad_dict.get(n), ref.grad_dict.get(n)
        if g is None:
            continue
        # eager (placed) vs jit-fused execution reassociates float
        # reductions; tolerance covers that, not a placement bug
        np.testing.assert_allclose(g.asnumpy(), gr.asnumpy(),
                                   rtol=5e-3, atol=1e-4)


def test_ctx_group_same_device_degenerates_to_jit():
    mlp, _ = _mlp()
    texec = mlp.simple_bind(
        mx.cpu(0), group2ctx={"stage1": mx.cpu(0),
                              "stage2": mx.cpu(0)},
        data=(2, 20), softmax_label=(2,), grad_req="write")
    assert not texec._placed
    texec.forward(is_train=False)


def test_group2ctx_rejects_non_context():
    mlp, _ = _mlp()
    with pytest.raises(TypeError):
        mlp.simple_bind(mx.cpu(0), group2ctx={"stage1": "cpu"},
                        data=(2, 20), softmax_label=(2,))


def test_reshape_preserves_group2ctx():
    mlp, set_stage1 = _mlp()
    group2ctx = {"stage1": mx.cpu(1), "stage2": mx.cpu(2)}
    texec = mlp.simple_bind(mx.cpu(0), group2ctx=group2ctx,
                            data=(4, 20), softmax_label=(4,),
                            grad_req="write")
    assert texec._placed
    bigger = texec.reshape(data=(8, 20), softmax_label=(8,))
    assert bigger._placed
    for arr, name in zip(bigger.arg_arrays, mlp.list_arguments()):
        want = group2ctx["stage1" if name in set_stage1 else "stage2"]
        assert arr.context == want, (name, arr.context)


def test_variable_only_ctx_group_forces_placed_mode():
    """Variables tagged with ctx_group but ops built outside the
    scope: arrays commit to group devices, so jit would reject them
    as incompatible inputs — must fall back to placed execution."""
    with mx.AttrScope(ctx_group="stage1"):
        data = mx.sym.Variable("data")
        w = mx.sym.Variable("w")
    with mx.AttrScope(ctx_group="stage2"):
        b = mx.sym.Variable("b")
    out = mx.sym.broadcast_add(mx.sym.dot(data, w), b)
    ex = out.simple_bind(
        mx.cpu(0), group2ctx={"stage1": mx.cpu(1),
                              "stage2": mx.cpu(2)},
        data=(2, 3), w=(3, 4), b=(1, 4), grad_req="write")
    assert ex._placed
    ex.arg_dict["data"][:] = np.ones((2, 3), np.float32)
    ex.arg_dict["w"][:] = np.ones((3, 4), np.float32)
    ex.arg_dict["b"][:] = np.ones((1, 4), np.float32)
    res = ex.forward()[0]
    np.testing.assert_allclose(res.asnumpy(), np.full((2, 4), 4.0))


def test_placed_outputs_carry_group_context():
    mlp, _ = _mlp()
    group2ctx = {"stage1": mx.cpu(1), "stage2": mx.cpu(2)}
    texec = mlp.simple_bind(mx.cpu(0), group2ctx=group2ctx,
                            data=(4, 20), softmax_label=(4,),
                            grad_req="write")
    out = texec.forward()[0]
    assert out.context == mx.cpu(2)       # head is in stage2
