"""NDArray tests (ref: tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd


def test_creation():
    x = nd.zeros((2, 3))
    assert x.shape == (2, 3)
    assert x.dtype == np.float32
    assert (x.asnumpy() == 0).all()
    y = nd.ones((4,), dtype="int32")
    assert y.dtype == np.int32
    z = nd.full((2, 2), 7.5)
    assert (z.asnumpy() == 7.5).all()
    a = nd.arange(0, 10, 2)
    np.testing.assert_allclose(a.asnumpy(), np.arange(0, 10, 2,
                                                      "float32"))


def test_arithmetic_and_scalars():
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = nd.array([[10.0, 20.0], [30.0, 40.0]])
    np.testing.assert_allclose((a + b).asnumpy(), a.asnumpy() + b.asnumpy())
    np.testing.assert_allclose((a - b).asnumpy(), a.asnumpy() - b.asnumpy())
    np.testing.assert_allclose((a * b).asnumpy(), a.asnumpy() * b.asnumpy())
    np.testing.assert_allclose((b / a).asnumpy(), b.asnumpy() / a.asnumpy())
    np.testing.assert_allclose((a + 1).asnumpy(), a.asnumpy() + 1)
    np.testing.assert_allclose((2 - a).asnumpy(), 2 - a.asnumpy())
    np.testing.assert_allclose((3 / a).asnumpy(), 3 / a.asnumpy())
    np.testing.assert_allclose((a ** 2).asnumpy(), a.asnumpy() ** 2)
    np.testing.assert_allclose((-a).asnumpy(), -a.asnumpy())
    np.testing.assert_allclose((a > 2).asnumpy(),
                               (a.asnumpy() > 2).astype("float32"))


def test_inplace():
    a = nd.ones((2, 2))
    a += 1
    np.testing.assert_allclose(a.asnumpy(), 2 * np.ones((2, 2)))
    a *= 3
    np.testing.assert_allclose(a.asnumpy(), 6 * np.ones((2, 2)))
    a[:] = 5
    np.testing.assert_allclose(a.asnumpy(), 5 * np.ones((2, 2)))


def test_indexing():
    a = nd.array(np.arange(24).reshape(2, 3, 4))
    np.testing.assert_allclose(a[1].asnumpy(),
                               np.arange(24).reshape(2, 3, 4)[1])
    np.testing.assert_allclose(a[0, 1:3].asnumpy(),
                               np.arange(24).reshape(2, 3, 4)[0, 1:3])
    a[0] = 0
    assert (a.asnumpy()[0] == 0).all()
    a[1, 2, 3] = -1
    assert a.asnumpy()[1, 2, 3] == -1


def test_reshape_special_codes():
    a = nd.zeros((2, 3, 4))
    assert a.reshape((6, 4)).shape == (6, 4)
    assert a.reshape((-1,)).shape == (24,)
    assert a.reshape((0, -1)).shape == (2, 12)
    assert a.reshape((-2,)).shape == (2, 3, 4)
    assert a.reshape((-3, 4)).shape == (6, 4)
    assert a.reshape((-4, 1, 2, 3, 4)).shape == (1, 2, 3, 4)


def test_shape_methods():
    a = nd.array(np.arange(12).reshape(3, 4))
    assert a.T.shape == (4, 3)
    assert a.flatten().shape == (3, 4)
    assert a.expand_dims(0).shape == (1, 3, 4)
    assert a.transpose((1, 0)).shape == (4, 3)
    assert nd.concatenate([a, a], axis=0).shape == (6, 4)
    parts = a.split(2, axis=1)
    assert parts[0].shape == (3, 2)


def test_reductions():
    x = np.random.RandomState(0).rand(3, 4, 5).astype("float32")
    a = nd.array(x)
    np.testing.assert_allclose(a.sum().asnumpy(), x.sum(), rtol=1e-5)
    np.testing.assert_allclose(a.mean(axis=1).asnumpy(), x.mean(1),
                               rtol=1e-5)
    np.testing.assert_allclose(a.max(axis=(0, 2)).asnumpy(),
                               x.max((0, 2)), rtol=1e-5)
    np.testing.assert_allclose(
        nd.sum(a, axis=1, exclude=True).asnumpy(), x.sum((0, 2)),
        rtol=1e-4)


def test_dtype_cast_copy():
    a = nd.ones((2, 2))
    b = a.astype("int32")
    assert b.dtype == np.int32
    c = a.copy()
    c[:] = 9
    assert (a.asnumpy() == 1).all()
    d = nd.zeros((2, 2))
    a.copyto(d)
    assert (d.asnumpy() == 1).all()


def test_context_placement():
    ctx = mx.cpu(1)
    a = nd.ones((2, 2), ctx=ctx)
    assert a.context == mx.cpu(1)
    b = a.as_in_context(mx.cpu(0))
    assert b.context == mx.cpu(0)
    # cross-device add after explicit transfer
    c = b + a.as_in_context(mx.cpu(0))
    assert (c.asnumpy() == 2).all()


def test_save_load(tmp_path):
    fname = str(tmp_path / "arrays.bin")
    d = {"w": nd.ones((2, 2)), "b": nd.zeros((3,))}
    nd.save(fname, d)
    loaded = nd.load(fname)
    assert set(loaded) == {"w", "b"}
    np.testing.assert_allclose(loaded["w"].asnumpy(), np.ones((2, 2)))
    lst = [nd.ones((1,)), nd.zeros((2,))]
    nd.save(fname, lst)
    loaded = nd.load(fname)
    assert isinstance(loaded, list) and len(loaded) == 2


def test_save_load_extension_dtypes(tmp_path):
    # ml_dtypes extension dtypes (bfloat16) must round-trip: npz has
    # no native descr for them, so save() encodes raw bits + dtype tag
    fname = str(tmp_path / "bf16.bin")
    w = nd.array(np.arange(6, dtype="float32").reshape(2, 3))
    d = {"w16": w.astype("bfloat16"), "w32": w}
    nd.save(fname, d)
    loaded = nd.load(fname)
    assert str(loaded["w16"].dtype) == "bfloat16"
    assert str(loaded["w32"].dtype) == "float32"
    np.testing.assert_allclose(
        loaded["w16"].astype("float32").asnumpy(), w.asnumpy())


def test_wait_and_iter():
    a = nd.ones((4, 2))
    a.wait_to_read()
    nd.waitall()
    rows = list(a)
    assert len(rows) == 4 and rows[0].shape == (2,)
    assert len(a) == 4


def test_sparse_roundtrip():
    dense = np.array([[0, 1, 0], [2, 0, 3]], dtype="float32")
    csr = nd.CSRNDArray.__new__  # placeholder to ensure class exists
    from incubator_mxnet_tpu.ndarray import sparse
    c = sparse.csr_matrix(dense)
    np.testing.assert_allclose(c.asnumpy(), dense)
    assert c.stype == "csr"
    r = sparse.row_sparse_array(dense)
    np.testing.assert_allclose(r.asnumpy(), dense)
    assert r.stype == "row_sparse"
    back = c.tostype("default")
    assert back.stype == "default"


def test_constant_first_arg():
    # raw numpy in the FIRST tensor slot: ctx inference and autograd
    # must treat it as an inlined constant, not an NDArray
    x = nd.array(np.arange(3, dtype="float32"))
    out = nd.broadcast_add(np.ones(3, "float32"), x)
    np.testing.assert_allclose(out.asnumpy(), [1, 2, 3])
    from incubator_mxnet_tpu import autograd
    with autograd.record():
        y = nd.broadcast_mul(np.full(3, 2.0, "float32"), x)
    assert np.all(np.isfinite(y.asnumpy()))


def test_memory_info():
    """ctx.memory_info() / mx.tpu_memory_info(): (free, total) bytes
    (the reference's mx.context.gpu_memory_info role).  On virtual
    CPU devices the PJRT allocator exposes no stats, so host memory
    is reported — still (free <= total), both positive."""
    import incubator_mxnet_tpu as mx

    for ctx in (mx.cpu(0), mx.tpu(0)):
        free, total = ctx.memory_info()
        assert 0 < free <= total, (ctx, free, total)
    free, total = mx.tpu_memory_info(0)
    assert 0 < free <= total
    assert mx.gpu_memory_info is mx.tpu_memory_info
