"""Elastic shrink/grow e2e worker (spawned by tests/test_dist_launch
through ``tools/launch.py --elastic`` — not a pytest module).

Storyline, driven by the launcher's MXTPU_WORLD_GENERATION:

- generation 1 (world 2, 8 virtual devices): rank 0 trains a
  ShardedTrainStep (dp×tp mesh) fed by a 2-worker DataServiceIter,
  saving sharded checkpoint generations (data companion included);
  the ``elastic:rank0:<n>:kill`` fault spec hard-kills it mid-step —
  the launcher shrinks the world to the survivors.
- generation 2 (world 1, 4 virtual devices — SHRINK): resumes from
  the newest manifest generation resharded onto the smaller mesh,
  with the data cursors resharded 2 -> 1 workers; after a few more
  steps it checkpoints and raises ElasticRestartRequested (exit 14)
  to re-admit the replaced worker at this checkpoint boundary.
- generation 3 (world 2, 8 devices — GROW): resumes on the full
  mesh and trains to completion.

Ranks > 0 idle and exit 0: multi-process collectives are not
implemented on this container's CPU backend (see
tests/test_dist_launch.py baseline), so the elasticity under test is
the launcher/world/checkpoint/data contract, with the mesh virtual
per rank — the same code path a TPU pod runs with real devices.
"""
import os
import sys

GEN = int(os.environ.get("MXTPU_WORLD_GENERATION", "1"))
RANK = int(os.environ.get("MXTPU_WORKER_RANK", "0"))

# mesh size per generation: 8 devices, shrink to 4, grow back to 8
N_DEV = 4 if GEN == 2 else 8
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = \
    f"--xla_force_host_platform_device_count={N_DEV}"
if not (GEN == 1 and RANK == 0):
    # the injected mid-step kill targets generation 1's rank 0 only;
    # later generations must run the same code clean
    os.environ.pop("MXTPU_FAULT_SPEC", None)

import numpy as np  # noqa: E402

TOTAL_STEPS = 12
GROW_AT_STEP = 8
BATCH = 8
SHAPE = (3, 32, 32)


def main():
    if RANK != 0:
        print(f"RANK_IDLE gen={GEN} rank={RANK}", flush=True)
        return

    import jax
    import jax.numpy as jnp
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import resilience
    from incubator_mxnet_tpu.data_service import DataServiceIter
    from incubator_mxnet_tpu.parallel import (ShardedTrainStep,
                                              make_mesh)

    ckdir = os.path.join(os.environ["MXTPU_ELASTIC_DIR"], "ck")
    rec_prefix = os.environ["MXTPU_ELASTIC_REC"]
    devs = jax.devices("cpu")[:N_DEV]
    mesh = make_mesh(dp=N_DEV // 2, tp=2, devices=devs)

    mx.random.seed(0)
    net = mx.gluon.nn.HybridSequential(prefix="el_")
    with net.name_scope():
        net.add(mx.gluon.nn.Dense(16, activation="relu"),
                mx.gluon.nn.Dense(4))
    net.initialize(mx.initializer.Xavier())
    step = ShardedTrainStep(
        net, optimizer="adam",
        optimizer_params=dict(learning_rate=1e-3), mesh=mesh,
        example_args=[jnp.zeros((2, int(np.prod(SHAPE))),
                                jnp.float32)])

    data_workers = 2 if GEN == 1 else 1
    it = DataServiceIter(path_imgrec=rec_prefix + ".rec",
                         data_shape=SHAPE, batch_size=BATCH,
                         num_workers=data_workers,
                         preprocess_threads=1)
    try:
        run(step, it, ckdir, mesh, data_workers, resilience)
    finally:
        it.close()


def run(step, it, ckdir, mesh, data_workers, resilience):
    import numpy as np

    resumed = None
    try:
        data_state = step.load_checkpoint(ckdir)
        resumed = int(step.step_count)
        if data_state is not None:
            old_w = data_state.get("num_shards")
            it.load_state_dict(data_state)
            print(f"DATA {old_w}->{data_workers}", flush=True)
    except resilience.CheckpointCorruptError:
        pass        # generation 1: nothing to resume from
    print(f"BOOT gen={GEN} world={os.environ['MXTPU_NUM_WORKERS']} "
          f"devices={mesh.devices.size} "
          f"resumed={resumed}", flush=True)

    def batches():
        while True:
            try:
                b = it.next()
            except StopIteration:
                it.reset()
                continue
            x = b.data[0].asnumpy().reshape(BATCH, -1)
            y = b.label[0].asnumpy().astype(np.int32)
            yield x, y

    for x, y in batches():
        loss = float(step(x, y))
        assert np.isfinite(loss), loss
        n = int(step.step_count)
        print(f"STEP {n} gen={GEN} loss={loss:.4f}", flush=True)
        if n % 2 == 0:
            step.save_checkpoint(ckdir,
                                 data_state=it.state_dict())
        if GEN == 2 and n >= GROW_AT_STEP:
            # re-admission protocol: checkpoint, then request the
            # coordinated restart — the launcher grows the world
            # back to the target at this checkpoint boundary
            step.save_checkpoint(ckdir,
                                 data_state=it.state_dict())
            print("GROW_REQUEST", flush=True)
            raise resilience.ElasticRestartRequested(
                "re-admit replaced worker at checkpoint boundary")
        if n >= TOTAL_STEPS:
            break
    print(f"ELASTIC_DONE gen={GEN} steps={int(step.step_count)}",
          flush=True)


if __name__ == "__main__":
    try:
        main()
    except Exception:
        # uncaught ElasticRestartRequested must reach the exithook's
        # exit code even though this main has a try guard
        from incubator_mxnet_tpu import resilience
        exc = sys.exc_info()[1]
        if isinstance(exc, resilience.ElasticRestartRequested):
            sys.stdout.flush()
            sys.stderr.flush()
            os._exit(resilience.ELASTIC_EXIT_CODE)
        raise
