"""Pretrained model store (ref:
python/mxnet/gluon/model_zoo/model_store.py — but local-cache-only
under zero egress: weights are installed, then pretrained=True
resolves them)."""
import os

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon
from incubator_mxnet_tpu.gluon.model_zoo import model_store, vision


def test_get_model_file_missing_is_informative(tmp_path):
    with pytest.raises(FileNotFoundError) as e:
        model_store.get_model_file("resnet18_v1", root=str(tmp_path))
    assert "import_model_file" in str(e.value)


def test_import_resolve_and_verify(tmp_path):
    src = tmp_path / "src.params"
    net = vision.squeezenet1_0()
    net.initialize(mx.initializer.Xavier())
    net(mx.nd.array(np.zeros((1, 3, 64, 64), "float32")))
    net.save_params(str(src))
    cached = model_store.import_model_file(str(src), "squeezenet1.0",
                                           root=str(tmp_path / "c"))
    assert os.path.basename(cached).startswith("squeezenet1.0-")
    got = model_store.get_model_file("squeezenet1.0",
                                     root=str(tmp_path / "c"))
    assert got == cached
    assert model_store.list_models(root=str(tmp_path / "c")) == \
        ["squeezenet1.0"]
    # corrupting the file must fail the sha1 tag check
    with open(cached, "r+b") as f:
        f.write(b"\0\0\0\0")
    with pytest.raises(OSError):
        model_store.get_model_file("squeezenet1.0",
                                   root=str(tmp_path / "c"))
    model_store.purge(root=str(tmp_path / "c"))
    assert model_store.list_models(root=str(tmp_path / "c")) == []


def test_pretrained_true_loads_weights(tmp_path):
    mx.random.seed(7)
    net = vision.squeezenet1_0()
    net.initialize(mx.initializer.Xavier())
    x = mx.nd.array(np.random.RandomState(0).rand(1, 3, 64, 64)
                    .astype("float32"))
    ref_out = net(x).asnumpy()
    src = tmp_path / "w.params"
    net.save_params(str(src))
    model_store.import_model_file(str(src), "squeezenet1.0",
                                  root=str(tmp_path / "cache"))
    net2 = vision.squeezenet1_0(pretrained=True,
                                root=str(tmp_path / "cache"))
    np.testing.assert_allclose(net2(x).asnumpy(), ref_out,
                               rtol=1e-5, atol=1e-5)


def test_pretrained_missing_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        vision.resnet18_v1(pretrained=True, root=str(tmp_path))
