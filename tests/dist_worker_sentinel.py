"""Worker script for the multi-rank step-sentinel test — run under
tools/launch.py (see tests/test_sentinel.py).

Rank 0 injects grad:nonfinite on two of six guarded steps; rank 1
injects nothing.  The allreduced finiteness flag must make BOTH
ranks skip exactly the same steps — a rank-local skip would
desynchronize optimizer state (docs/numeric_stability.md).

Not a pytest module: tests/test_sentinel.py spawns it.
"""
import os

import jax
jax.config.update("jax_platforms", "cpu")

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import dist
from incubator_mxnet_tpu import optimizer as opt_mod
from incubator_mxnet_tpu import resilience as rz


def main():
    r = dist.init()
    assert dist.num_workers() == 2, dist.num_workers()
    if r == 0:
        os.environ["MXTPU_FAULT_SPEC"] = \
            "grad:nonfinite:2:nan,grad:nonfinite:5:inf"
        rz.reset_faults()

    opt = opt_mod.create("sgd", learning_rate=0.1)
    guard = rz.NumericGuard(policy="skip", interval=1,
                            max_bad_steps=0)
    up = opt_mod.GuardedUpdater(opt, guard=guard)
    w = mx.nd.array(np.ones((4,), np.float32))
    decisions = []
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        for _ in range(6):
            g = mx.nd.array(np.full((4,), 0.25, np.float32))
            proceed = up.begin_step([g])
            decisions.append(1.0 if proceed else 0.0)
            up(0, g, w)

    # every rank must have skipped steps 2 and 5 (rank 0's faults)
    assert decisions == [1, 0, 1, 1, 0, 1], (r, decisions)
    # cross-check agreement collectively: if the vectors were equal
    # on all ranks, the sum is exactly 2x the local vector
    local = jax.numpy.asarray(decisions, jax.numpy.float32)
    summed = np.asarray(dist.allreduce_sum(local))
    assert np.allclose(summed, 2 * np.asarray(decisions)), \
        (r, decisions, summed)
    # weights identical across ranks (same updates applied)
    wsum = np.asarray(dist.allreduce_sum(
        jax.numpy.asarray(w.asnumpy())))
    assert np.allclose(wsum, 2 * w.asnumpy()), (r, wsum)
    assert np.all(np.isfinite(w.asnumpy()))
    assert guard.skipped_steps == 2

    print(f"SENTINEL_OK rank {r}", flush=True)


if __name__ == "__main__":
    main()
