"""tools/launch.py spawning multi-process kvstore workers
(VERDICT r2 task 6; ref: tools/launch.py:64 +
tests/nightly/dist_sync_kvstore.py run as local processes)."""
import os
import subprocess
import sys


def test_launch_two_process_kvstore():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    # let the children pick their own backend (the worker script pins
    # cpu in-process); drop the 8-device flag so each worker is 1 dev
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "launch.py"),
         "-n", "2", "--", sys.executable,
         os.path.join(repo, "tests", "dist_worker_check.py")],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=repo)
    out = r.stdout + r.stderr
    assert r.returncode == 0, out[-3000:]
    assert "DIST_OK rank 0" in out, out[-3000:]
    assert "DIST_OK rank 1" in out, out[-3000:]


def test_launch_tears_down_on_worker_crash():
    """A crashing worker must fail the job quickly instead of leaving
    peers blocked in a collective (round-3 review regression)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "launch.py"),
         "-n", "2", "--", sys.executable, "-c",
         "import os,sys,time\n"
         "if os.environ['MXTPU_WORKER_RANK']=='1': sys.exit(3)\n"
         "time.sleep(600)"],
        capture_output=True, text=True, timeout=60, env=env, cwd=repo)
    assert r.returncode == 3, (r.returncode, r.stderr[-500:])


def test_launch_elastic_restart(tmp_path):
    """--max-restarts relaunches the whole job after a failure; the
    second attempt (simulating resume-from-checkpoint) succeeds."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    marker = tmp_path / "crashed_once"
    script = (
        "import os, sys\n"
        f"m = {str(marker)!r}\n"
        "if os.environ['MXTPU_WORKER_RANK'] == '0' "
        "and not os.path.exists(m):\n"
        "    open(m, 'w').close()\n"
        "    sys.exit(9)\n"
        "print('ATTEMPT', os.environ['MXTPU_RESTART_ATTEMPT'],"
        " 'rank', os.environ['MXTPU_WORKER_RANK'])\n")
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "launch.py"),
         "-n", "2", "--max-restarts", "2", "--",
         sys.executable, "-c", script],
        capture_output=True, text=True, timeout=120, env=env, cwd=repo)
    out = r.stdout + r.stderr
    assert r.returncode == 0, out[-2000:]
    assert "restarting job (attempt 1/2)" in out, out[-2000:]
    assert "ATTEMPT 1 rank 0" in out, out[-2000:]

    # without restarts the same failure fails the job
    os.unlink(marker)
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "launch.py"),
         "-n", "2", "--", sys.executable, "-c", script],
        capture_output=True, text=True, timeout=120, env=env, cwd=repo)
    assert r.returncode == 9


def test_elastic_resume_from_checkpoint(tmp_path):
    """The full elasticity claim (SURVEY §5): kill rank 1 mid-train,
    --max-restarts relaunches the job, workers resume from the last
    checkpoint (not epoch 0) and converge."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    env["MXTPU_ELASTIC_DIR"] = str(tmp_path)
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "launch.py"),
         "-n", "2", "--max-restarts", "2", "--", sys.executable,
         os.path.join(repo, "tests", "dist_elastic_worker.py")],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=repo)
    out = r.stdout + r.stderr
    assert r.returncode == 0, out[-3000:]
    assert "CRASHING rank 1 after epoch 1" in out, out[-3000:]
    assert "restarting job (attempt 1/2)" in out, out[-3000:]
    # resumed from the epoch-2 checkpoint, not from scratch
    assert "RESUMED_FROM 2 rank 0" in out, out[-3000:]
    assert "RESUMED_FROM 2 rank 1" in out, out[-3000:]
    assert "ELASTIC_OK rank 0 attempt 1" in out, out[-3000:]
    assert "ELASTIC_OK rank 1 attempt 1" in out, out[-3000:]
