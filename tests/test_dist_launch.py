"""tools/launch.py spawning multi-process kvstore workers
(VERDICT r2 task 6; ref: tools/launch.py:64 +
tests/nightly/dist_sync_kvstore.py run as local processes)."""
import os
import subprocess
import sys


def test_launch_two_process_kvstore():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    # let the children pick their own backend (the worker script pins
    # cpu in-process); drop the 8-device flag so each worker is 1 dev
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "launch.py"),
         "-n", "2", "--", sys.executable,
         os.path.join(repo, "tests", "dist_worker_check.py")],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=repo)
    out = r.stdout + r.stderr
    assert r.returncode == 0, out[-3000:]
    assert "DIST_OK rank 0" in out, out[-3000:]
    assert "DIST_OK rank 1" in out, out[-3000:]


def test_launch_tears_down_on_worker_crash():
    """A crashing worker must fail the job quickly instead of leaving
    peers blocked in a collective (round-3 review regression)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "launch.py"),
         "-n", "2", "--", sys.executable, "-c",
         "import os,sys,time\n"
         "if os.environ['MXTPU_WORKER_RANK']=='1': sys.exit(3)\n"
         "time.sleep(600)"],
        capture_output=True, text=True, timeout=60, env=env, cwd=repo)
    assert r.returncode == 3, (r.returncode, r.stderr[-500:])


def test_launch_elastic_restart(tmp_path):
    """--max-restarts relaunches the whole job after a failure; the
    second attempt (simulating resume-from-checkpoint) succeeds."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    marker = tmp_path / "crashed_once"
    script = (
        "import os, sys\n"
        f"m = {str(marker)!r}\n"
        "if os.environ['MXTPU_WORKER_RANK'] == '0' "
        "and not os.path.exists(m):\n"
        "    open(m, 'w').close()\n"
        "    sys.exit(9)\n"
        # one os.write syscall: atomic for <PIPE_BUF, so concurrent
        # workers sharing the pipe can't interleave mid-line
        "os.write(1, ('ATTEMPT %s rank %s\\n' % ("
        "os.environ['MXTPU_RESTART_ATTEMPT'],"
        " os.environ['MXTPU_WORKER_RANK'])).encode())\n")
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "launch.py"),
         "-n", "2", "--max-restarts", "2", "--",
         sys.executable, "-c", script],
        capture_output=True, text=True, timeout=120, env=env, cwd=repo)
    out = r.stdout + r.stderr
    assert r.returncode == 0, out[-2000:]
    assert "restarting job (attempt 1/2)" in out, out[-2000:]
    assert "ATTEMPT 1 rank 0" in out, out[-2000:]

    # without restarts the same failure fails the job
    os.unlink(marker)
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "launch.py"),
         "-n", "2", "--", sys.executable, "-c", script],
        capture_output=True, text=True, timeout=120, env=env, cwd=repo)
    assert r.returncode == 9


def test_elastic_resume_from_checkpoint(tmp_path):
    """The full elasticity claim (SURVEY §5): kill rank 1 mid-train,
    --max-restarts relaunches the job, workers resume from the last
    checkpoint (not epoch 0) and converge."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    env["MXTPU_ELASTIC_DIR"] = str(tmp_path)
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "launch.py"),
         "-n", "2", "--max-restarts", "2", "--", sys.executable,
         os.path.join(repo, "tests", "dist_elastic_worker.py")],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=repo)
    out = r.stdout + r.stderr
    assert r.returncode == 0, out[-3000:]
    assert "CRASHING rank 1 after epoch 1" in out, out[-3000:]
    assert "restarting job (attempt 1/2)" in out, out[-3000:]
    # resumed from the epoch-2 checkpoint, not from scratch
    assert "RESUMED_FROM 2 rank 0" in out, out[-3000:]
    assert "RESUMED_FROM 2 rank 1" in out, out[-3000:]
    assert "ELASTIC_OK rank 0 attempt 1" in out, out[-3000:]
    assert "ELASTIC_OK rank 1 attempt 1" in out, out[-3000:]


def test_launch_elastic_shrink_grow_policy(tmp_path):
    """--elastic restart ledger: a crash shrinks the next world to
    the survivors, an elastic exit (14) re-admits replaced workers
    back to the target world, each with its own counter/log line and
    a fresh MXTPU_WORLD_GENERATION (no jax involved)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = (
        "import os, sys\n"
        "gen = os.environ['MXTPU_WORLD_GENERATION']\n"
        "n = os.environ['MXTPU_NUM_WORKERS']\n"
        "r = os.environ['MXTPU_WORKER_RANK']\n"
        "el = os.environ.get('MXTPU_ELASTIC')\n"
        "os.write(1, f'GEN {gen} WORLD {n} RANK {r} "
        "ELASTIC {el}\\n'.encode())\n"
        "if gen == '1' and r == '1':\n"
        "    sys.exit(5)\n"          # crash -> shrink 2 -> 1
        "if gen == '2':\n"
        "    sys.exit(14)\n")        # coordinated -> grow back to 2
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "launch.py"),
         "-n", "2", "--elastic", "--max-elastic-restarts", "3",
         "--", sys.executable, "-c", script],
        capture_output=True, text=True, timeout=120, cwd=repo)
    out = r.stdout + r.stderr
    assert r.returncode == 0, out[-2000:]
    assert "ELASTIC restart 1/3: world 2 -> 1 (shrink: rank(s) [1]" \
        in out, out[-2000:]
    assert "ELASTIC restart 2/3: world 1 -> 2 (grow" in out, \
        out[-2000:]
    assert "GEN 2 WORLD 1 RANK 0 ELASTIC 1" in out, out[-2000:]
    assert "GEN 3 WORLD 2 RANK 1 ELASTIC 1" in out, out[-2000:]


def test_launch_elastic_budget_and_divergence_split(tmp_path):
    """Divergence (exit 13) keeps consuming --max-restarts even
    under --elastic; the elastic budget refuses past its own cap."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # divergence: --max-restarts 0 -> no restart, rc 13, no ELASTIC
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "launch.py"),
         "-n", "1", "--elastic", "--", sys.executable, "-c",
         "import sys; sys.exit(13)"],
        capture_output=True, text=True, timeout=60, cwd=repo)
    assert r.returncode == 13
    assert "ELASTIC restart" not in r.stdout + r.stderr
    # crash loop: budget 1 -> exactly one elastic restart, then out
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "launch.py"),
         "-n", "2", "--elastic", "--max-elastic-restarts", "1",
         "--", sys.executable, "-c", "import sys; sys.exit(3)"],
        capture_output=True, text=True, timeout=60, cwd=repo)
    out = r.stdout + r.stderr
    assert r.returncode == 3
    assert "ELASTIC restart 1/1" in out, out[-1500:]
    assert "elastic restart budget spent" in out, out[-1500:]


def test_launch_elastic_ssh_excludes_failed_host(tmp_path):
    """ssh-mode shrink must drop the failed rank's HOST from the
    next assignment (its machine may be gone) and re-derive the
    coordinator from the live pool — not respawn onto the dead box
    with a pinned coordinator."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    shim = _write_shim(tmp_path)
    hostfile = tmp_path / "hosts"
    hostfile.write_text("hostA 1\nhostB 1\n")
    log = tmp_path / "shim.log"
    script = (
        "import os, sys\n"
        "if os.environ['MXTPU_WORLD_GENERATION'] == '1' "
        "and os.environ['MXTPU_WORKER_RANK'] == '1':\n"
        "    sys.exit(5)\n")
    env = dict(os.environ)
    env["SSH_SHIM_LOG"] = str(log)
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "launch.py"),
         "-n", "2", "--launcher", "ssh", "-H", str(hostfile),
         "--ssh-cmd", shim, "--elastic", "--max-elastic-restarts",
         "2", "--", sys.executable, "-c", script],
        capture_output=True, text=True, timeout=120, env=env,
        cwd=repo)
    out = r.stdout + r.stderr
    assert r.returncode == 0, out[-2000:]
    assert "excluding failed host(s) ['hostB']" in out, out[-2000:]
    assert "ELASTIC restart 1/2: world 2 -> 1 (shrink" in out, \
        out[-2000:]
    calls = [ln for ln in log.read_text().splitlines()
             if ln.startswith("SHIM ")]
    # attempt 1: both hosts; attempt 2: only hostA (world 1)
    assert len(calls) == 3, calls
    assert calls[2].startswith("SHIM hostA "), calls[2]
    assert "MXTPU_COORD_ADDR=hostA:" in calls[2], calls[2]


def test_elastic_shrink_grow_reshard_e2e(tmp_path):
    """The full elastic claim (docs/elastic.md): elastic:rank0 kill
    mid-step -> launch.py --elastic shrinks the world, the survivor
    resumes from the newest sharded manifest generation RESHARDED
    onto a smaller mesh with the data cursors resharded 2 -> 1
    workers, requests re-admission at a checkpoint boundary (exit
    14), and the grown world finishes the run — zero orphan tmp
    files in the checkpoint directory."""
    from test_data_service import _make_jpeg_rec

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rec = _make_jpeg_rec(str(tmp_path / "ds"), 48, edge=32)
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "launch.py"),
         "-n", "2", "--elastic", "--max-elastic-restarts", "3",
         "--env", "MXTPU_FAULT_SPEC=elastic:rank0:5:kill",
         "--env", f"MXTPU_ELASTIC_DIR={tmp_path}",
         "--env", f"MXTPU_ELASTIC_REC={rec}",
         "--", sys.executable,
         os.path.join(repo, "tests", "dist_elastic_reshard_worker.py")],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=repo)
    out = r.stdout + r.stderr
    assert r.returncode == 0, out[-4000:]
    # gen 1: fresh start on the 8-device mesh, killed mid-step 5
    assert "BOOT gen=1 world=2 devices=8 resumed=None" in out, \
        out[-4000:]
    assert "MXTPU_KILLED injected elastic:rank0 kill" in out, \
        out[-4000:]
    assert "ELASTIC restart 1/3: world 2 -> 1 (shrink: rank(s) [0]" \
        in out, out[-4000:]
    # gen 2: shrunk world resumes the manifest on 4 devices, data
    # cursors resharded 2 -> 1, then requests re-admission
    assert "BOOT gen=2 world=1 devices=4 resumed=4" in out, \
        out[-4000:]
    assert "DATA 2->1" in out, out[-4000:]
    assert "GROW_REQUEST" in out, out[-4000:]
    assert "ELASTIC restart 2/3: world 1 -> 2 (grow" in out, \
        out[-4000:]
    # gen 3: grown world resumes at the grow checkpoint and finishes
    assert "BOOT gen=3 world=2 devices=8 resumed=8" in out, \
        out[-4000:]
    assert "DATA 1->1" in out, out[-4000:]
    assert "ELASTIC_DONE gen=3 steps=12" in out, out[-4000:]
    # zero half-written tmp files anywhere near the checkpoints
    orphans = [f for _, _, fs in os.walk(tmp_path) for f in fs
               if ".tmp." in f]
    assert orphans == [], orphans


SSH_SHIM = """#!/bin/sh
# Faithful stand-in for ssh in an image without an ssh client: accepts
# `shim [-o opt]... host 'remote command'` and runs the command through
# a local shell, exactly as sshd would hand it to the remote login
# shell.  Records each call so the test can assert per-host dispatch.
echo "SHIM $@" >> "$SSH_SHIM_LOG"
while [ $# -gt 0 ]; do
    case "$1" in
        -o) shift 2 ;;
        -*) shift ;;
        *) break ;;
    esac
done
host="$1"; shift
exec sh -c "$*"
"""


def _write_shim(tmp_path):
    shim = tmp_path / "fake_ssh"
    shim.write_text(SSH_SHIM)
    shim.chmod(0o755)
    return str(shim)


def test_launch_ssh_two_host_kvstore(tmp_path):
    """--launcher ssh spawns real per-host remote-shell sessions with
    env propagated inline (VERDICT r4 next-step 7).  The transport is
    swapped for a local shim (this image has no ssh client); with a
    real ssh binary the identical code path runs unchanged."""
    import socket

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    shim = _write_shim(tmp_path)
    hostfile = tmp_path / "hosts"
    hostfile.write_text("# two slots on this machine\nlocalhost 2\n")
    log = tmp_path / "shim.log"

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["SSH_SHIM_LOG"] = str(log)
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "launch.py"),
         "-n", "2", "--launcher", "ssh", "-H", str(hostfile),
         "--port", str(port), "--ssh-cmd", shim,
         "--env", "MXTPU_TEST_FLAG=hello", "--", sys.executable,
         os.path.join(repo, "tests", "dist_worker_check.py")],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=repo)
    out = r.stdout + r.stderr
    assert r.returncode == 0, out[-3000:]
    assert "DIST_OK rank 0" in out, out[-3000:]
    assert "DIST_OK rank 1" in out, out[-3000:]
    # the transport was exercised once per worker, to the right host
    calls = log.read_text().strip().splitlines()
    assert len(calls) == 2, calls
    assert all("localhost" in c for c in calls), calls
    # inline env propagation (rank + custom --env var)
    joined = "\n".join(calls)
    assert "MXTPU_WORKER_RANK=0" in joined, joined
    assert "MXTPU_WORKER_RANK=1" in joined, joined
    assert "MXTPU_TEST_FLAG=hello" in joined, joined
    assert f"MXTPU_COORD_ADDR=localhost:{port}" in joined, joined


def test_launch_ssh_hostfile_round_robin(tmp_path):
    """Ranks fill each host's slots before wrapping; rank 0's host is
    the coordinator."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    import launch as launch_mod
    hosts = [("a", 2), ("b", 1)]
    assert launch_mod._assign_hosts(hosts, 5) == \
        ["a", "a", "b", "a", "a"]
    hf = tmp_path / "hosts"
    hf.write_text("h1 1\n# comment\n\nh2 3\n")
    assert launch_mod._parse_hostfile(str(hf)) == [("h1", 1),
                                                   ("h2", 3)]


def test_hostfile_zero_slots_is_clean_error(tmp_path):
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    import launch as launch_mod
    import pytest
    with pytest.raises(ValueError, match="no usable slots"):
        launch_mod._assign_hosts([("drained-host", 0)], 2)


def test_dist_rank_from_mpi_env(monkeypatch):
    """--launcher mpi workers get their rank from the MPI runtime's
    env (dist._env_rank), not MXTPU_WORKER_RANK."""
    from incubator_mxnet_tpu import dist
    monkeypatch.setenv("MXTPU_RANK_FROM_MPI", "1")
    monkeypatch.setenv("OMPI_COMM_WORLD_RANK", "3")
    monkeypatch.setenv("MXTPU_WORKER_RANK", "0")   # must be ignored
    assert dist._env_rank() == 3
    monkeypatch.delenv("OMPI_COMM_WORLD_RANK")
    monkeypatch.setenv("SLURM_PROCID", "5")
    assert dist._env_rank() == 5
    monkeypatch.delenv("SLURM_PROCID")
    import pytest
    with pytest.raises(RuntimeError, match="mpirun"):
        dist._env_rank()
    monkeypatch.delenv("MXTPU_RANK_FROM_MPI")
    monkeypatch.setenv("MXTPU_WORKER_RANK", "2")
    assert dist._env_rank() == 2


def test_kill_job_cleans_up_stuck_workers(tmp_path):
    """tools/kill_job.py (the reference kill-mxnet.py role) walks the
    hostfile over the launch transport and kills matching processes."""
    import time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    shim = _write_shim(tmp_path)
    hostfile = tmp_path / "hosts"
    hostfile.write_text("localhost 1\n")
    log = tmp_path / "shim.log"

    tag = f"mxtpu_stuck_{os.getpid()}"
    stuck = subprocess.Popen(
        [sys.executable, "-c",
         f"import time  # {tag}\ntime.sleep(600)"])
    try:
        time.sleep(0.3)
        assert stuck.poll() is None
        env = dict(os.environ)
        env["SSH_SHIM_LOG"] = str(log)
        r = subprocess.run(
            [sys.executable, os.path.join(repo, "tools",
                                          "kill_job.py"),
             "-H", str(hostfile), "--ssh-cmd", shim, tag],
            capture_output=True, text=True, timeout=60, env=env)
        assert r.returncode == 0, r.stdout + r.stderr
        deadline = time.time() + 10
        while stuck.poll() is None and time.time() < deadline:
            time.sleep(0.1)
        assert stuck.poll() is not None, "stuck worker survived"
    finally:
        if stuck.poll() is None:
            stuck.kill()

    # refuses self-matching patterns
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "kill_job.py"),
         "launch.py"],
        capture_output=True, text=True, timeout=30)
    assert r.returncode != 0
