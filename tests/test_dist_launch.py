"""tools/launch.py spawning multi-process kvstore workers
(VERDICT r2 task 6; ref: tools/launch.py:64 +
tests/nightly/dist_sync_kvstore.py run as local processes)."""
import os
import subprocess
import sys


def test_launch_two_process_kvstore():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    # let the children pick their own backend (the worker script pins
    # cpu in-process); drop the 8-device flag so each worker is 1 dev
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "launch.py"),
         "-n", "2", "--", sys.executable,
         os.path.join(repo, "tests", "dist_worker_check.py")],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=repo)
    out = r.stdout + r.stderr
    assert r.returncode == 0, out[-3000:]
    assert "DIST_OK rank 0" in out, out[-3000:]
    assert "DIST_OK rank 1" in out, out[-3000:]


def test_launch_tears_down_on_worker_crash():
    """A crashing worker must fail the job quickly instead of leaving
    peers blocked in a collective (round-3 review regression)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "launch.py"),
         "-n", "2", "--", sys.executable, "-c",
         "import os,sys,time\n"
         "if os.environ['MXTPU_WORKER_RANK']=='1': sys.exit(3)\n"
         "time.sleep(600)"],
        capture_output=True, text=True, timeout=60, env=env, cwd=repo)
    assert r.returncode == 3, (r.returncode, r.stderr[-500:])


def test_launch_elastic_restart(tmp_path):
    """--max-restarts relaunches the whole job after a failure; the
    second attempt (simulating resume-from-checkpoint) succeeds."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    marker = tmp_path / "crashed_once"
    script = (
        "import os, sys\n"
        f"m = {str(marker)!r}\n"
        "if os.environ['MXTPU_WORKER_RANK'] == '0' "
        "and not os.path.exists(m):\n"
        "    open(m, 'w').close()\n"
        "    sys.exit(9)\n"
        # one os.write syscall: atomic for <PIPE_BUF, so concurrent
        # workers sharing the pipe can't interleave mid-line
        "os.write(1, ('ATTEMPT %s rank %s\\n' % ("
        "os.environ['MXTPU_RESTART_ATTEMPT'],"
        " os.environ['MXTPU_WORKER_RANK'])).encode())\n")
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "launch.py"),
         "-n", "2", "--max-restarts", "2", "--",
         sys.executable, "-c", script],
        capture_output=True, text=True, timeout=120, env=env, cwd=repo)
    out = r.stdout + r.stderr
    assert r.returncode == 0, out[-2000:]
    assert "restarting job (attempt 1/2)" in out, out[-2000:]
    assert "ATTEMPT 1 rank 0" in out, out[-2000:]

    # without restarts the same failure fails the job
    os.unlink(marker)
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "launch.py"),
         "-n", "2", "--", sys.executable, "-c", script],
        capture_output=True, text=True, timeout=120, env=env, cwd=repo)
    assert r.returncode == 9


def test_elastic_resume_from_checkpoint(tmp_path):
    """The full elasticity claim (SURVEY §5): kill rank 1 mid-train,
    --max-restarts relaunches the job, workers resume from the last
    checkpoint (not epoch 0) and converge."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    env["MXTPU_ELASTIC_DIR"] = str(tmp_path)
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "launch.py"),
         "-n", "2", "--max-restarts", "2", "--", sys.executable,
         os.path.join(repo, "tests", "dist_elastic_worker.py")],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=repo)
    out = r.stdout + r.stderr
    assert r.returncode == 0, out[-3000:]
    assert "CRASHING rank 1 after epoch 1" in out, out[-3000:]
    assert "restarting job (attempt 1/2)" in out, out[-3000:]
    # resumed from the epoch-2 checkpoint, not from scratch
    assert "RESUMED_FROM 2 rank 0" in out, out[-3000:]
    assert "RESUMED_FROM 2 rank 1" in out, out[-3000:]
    assert "ELASTIC_OK rank 0 attempt 1" in out, out[-3000:]
    assert "ELASTIC_OK rank 1 attempt 1" in out, out[-3000:]


SSH_SHIM = """#!/bin/sh
# Faithful stand-in for ssh in an image without an ssh client: accepts
# `shim [-o opt]... host 'remote command'` and runs the command through
# a local shell, exactly as sshd would hand it to the remote login
# shell.  Records each call so the test can assert per-host dispatch.
echo "SHIM $@" >> "$SSH_SHIM_LOG"
while [ $# -gt 0 ]; do
    case "$1" in
        -o) shift 2 ;;
        -*) shift ;;
        *) break ;;
    esac
done
host="$1"; shift
exec sh -c "$*"
"""


def _write_shim(tmp_path):
    shim = tmp_path / "fake_ssh"
    shim.write_text(SSH_SHIM)
    shim.chmod(0o755)
    return str(shim)


def test_launch_ssh_two_host_kvstore(tmp_path):
    """--launcher ssh spawns real per-host remote-shell sessions with
    env propagated inline (VERDICT r4 next-step 7).  The transport is
    swapped for a local shim (this image has no ssh client); with a
    real ssh binary the identical code path runs unchanged."""
    import socket

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    shim = _write_shim(tmp_path)
    hostfile = tmp_path / "hosts"
    hostfile.write_text("# two slots on this machine\nlocalhost 2\n")
    log = tmp_path / "shim.log"

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["SSH_SHIM_LOG"] = str(log)
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "launch.py"),
         "-n", "2", "--launcher", "ssh", "-H", str(hostfile),
         "--port", str(port), "--ssh-cmd", shim,
         "--env", "MXTPU_TEST_FLAG=hello", "--", sys.executable,
         os.path.join(repo, "tests", "dist_worker_check.py")],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=repo)
    out = r.stdout + r.stderr
    assert r.returncode == 0, out[-3000:]
    assert "DIST_OK rank 0" in out, out[-3000:]
    assert "DIST_OK rank 1" in out, out[-3000:]
    # the transport was exercised once per worker, to the right host
    calls = log.read_text().strip().splitlines()
    assert len(calls) == 2, calls
    assert all("localhost" in c for c in calls), calls
    # inline env propagation (rank + custom --env var)
    joined = "\n".join(calls)
    assert "MXTPU_WORKER_RANK=0" in joined, joined
    assert "MXTPU_WORKER_RANK=1" in joined, joined
    assert "MXTPU_TEST_FLAG=hello" in joined, joined
    assert f"MXTPU_COORD_ADDR=localhost:{port}" in joined, joined


def test_launch_ssh_hostfile_round_robin(tmp_path):
    """Ranks fill each host's slots before wrapping; rank 0's host is
    the coordinator."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    import launch as launch_mod
    hosts = [("a", 2), ("b", 1)]
    assert launch_mod._assign_hosts(hosts, 5) == \
        ["a", "a", "b", "a", "a"]
    hf = tmp_path / "hosts"
    hf.write_text("h1 1\n# comment\n\nh2 3\n")
    assert launch_mod._parse_hostfile(str(hf)) == [("h1", 1),
                                                   ("h2", 3)]


def test_hostfile_zero_slots_is_clean_error(tmp_path):
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    import launch as launch_mod
    import pytest
    with pytest.raises(ValueError, match="no usable slots"):
        launch_mod._assign_hosts([("drained-host", 0)], 2)


def test_dist_rank_from_mpi_env(monkeypatch):
    """--launcher mpi workers get their rank from the MPI runtime's
    env (dist._env_rank), not MXTPU_WORKER_RANK."""
    from incubator_mxnet_tpu import dist
    monkeypatch.setenv("MXTPU_RANK_FROM_MPI", "1")
    monkeypatch.setenv("OMPI_COMM_WORLD_RANK", "3")
    monkeypatch.setenv("MXTPU_WORKER_RANK", "0")   # must be ignored
    assert dist._env_rank() == 3
    monkeypatch.delenv("OMPI_COMM_WORLD_RANK")
    monkeypatch.setenv("SLURM_PROCID", "5")
    assert dist._env_rank() == 5
    monkeypatch.delenv("SLURM_PROCID")
    import pytest
    with pytest.raises(RuntimeError, match="mpirun"):
        dist._env_rank()
    monkeypatch.delenv("MXTPU_RANK_FROM_MPI")
    monkeypatch.setenv("MXTPU_WORKER_RANK", "2")
    assert dist._env_rank() == 2


def test_kill_job_cleans_up_stuck_workers(tmp_path):
    """tools/kill_job.py (the reference kill-mxnet.py role) walks the
    hostfile over the launch transport and kills matching processes."""
    import time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    shim = _write_shim(tmp_path)
    hostfile = tmp_path / "hosts"
    hostfile.write_text("localhost 1\n")
    log = tmp_path / "shim.log"

    tag = f"mxtpu_stuck_{os.getpid()}"
    stuck = subprocess.Popen(
        [sys.executable, "-c",
         f"import time  # {tag}\ntime.sleep(600)"])
    try:
        time.sleep(0.3)
        assert stuck.poll() is None
        env = dict(os.environ)
        env["SSH_SHIM_LOG"] = str(log)
        r = subprocess.run(
            [sys.executable, os.path.join(repo, "tools",
                                          "kill_job.py"),
             "-H", str(hostfile), "--ssh-cmd", shim, tag],
            capture_output=True, text=True, timeout=60, env=env)
        assert r.returncode == 0, r.stdout + r.stderr
        deadline = time.time() + 10
        while stuck.poll() is None and time.time() < deadline:
            time.sleep(0.1)
        assert stuck.poll() is not None, "stuck worker survived"
    finally:
        if stuck.poll() is None:
            stuck.kill()

    # refuses self-matching patterns
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "kill_job.py"),
         "launch.py"],
        capture_output=True, text=True, timeout=30)
    assert r.returncode != 0
