"""BucketingModule + BucketSentenceIter end-to-end (model: reference
tests/python/train/test_bucketing.py and example/rnn/lstm_bucketing.py
— a bucketed LSTM language model must train and drop perplexity)."""
import numpy as np

import incubator_mxnet_tpu as mx


def _make_sentences(rs, n=200, vmin=1, vmax=20):
    """Random 'sentences' with a learnable pattern: each token is
    followed by (token+1) mod vocab."""
    sents = []
    for _ in range(n):
        length = rs.randint(4, 17)
        start = rs.randint(vmin, vmax)
        sent = [(start + i) % vmax + 1 for i in range(length)]
        sents.append(sent)
    return sents


def _sym_gen_factory(vocab_size, num_hidden, num_embed, batch):
    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data, input_dim=vocab_size,
                                 output_dim=num_embed, name="embed")
        # NT -> TNC for the fused RNN op
        tnc = mx.sym.swapaxes(embed, dim1=0, dim2=1)
        params = mx.sym.Variable("rnn_parameters")
        init_h = mx.sym.zeros((1, batch, num_hidden))
        init_c = mx.sym.zeros((1, batch, num_hidden))
        out = mx.sym.RNN(tnc, params, init_h, init_c,
                         state_size=num_hidden, num_layers=1,
                         mode="lstm", name="rnn")
        ntc = mx.sym.swapaxes(out, dim1=0, dim2=1)
        pred = mx.sym.Reshape(ntc, shape=(-1, num_hidden))
        pred = mx.sym.FullyConnected(pred, num_hidden=vocab_size,
                                     name="pred")
        lab = mx.sym.Reshape(label, shape=(-1,))
        pred = mx.sym.SoftmaxOutput(pred, lab, name="softmax")
        return pred, ("data",), ("softmax_label",)
    return sym_gen


def _initializer():
    """Fused rnn_parameters is 1-D: route it to Uniform, rest Xavier
    (the reference used init.FusedRNN for this, ref: initializer.py
    FusedRNN:676)."""
    return mx.initializer.Mixed(
        [".*rnn_parameters", ".*"],
        [mx.initializer.Uniform(0.1), mx.initializer.Xavier()])


def test_bucket_sentence_iter():
    rs = np.random.RandomState(0)
    sents = _make_sentences(rs)
    it = mx.rnn.BucketSentenceIter(sents, batch_size=8,
                                   buckets=[8, 12, 16],
                                   invalid_label=0)
    seen = set()
    n = 0
    for batch in it:
        assert batch.data[0].shape == (8, batch.bucket_key)
        assert batch.label[0].shape == (8, batch.bucket_key)
        seen.add(batch.bucket_key)
        n += 1
    assert n > 0
    assert len(seen) > 1, "expected multiple buckets"
    it.reset()
    assert sum(1 for _ in it) == n


def test_bucketing_module_trains():
    rs = np.random.RandomState(1)
    vocab_size, num_hidden, num_embed, batch = 22, 16, 8, 8
    sents = _make_sentences(rs)
    it = mx.rnn.BucketSentenceIter(sents, batch_size=batch,
                                   buckets=[8, 12, 16],
                                   invalid_label=0)

    # state/parameter shapes depend on batch: provide via shapes dict
    sym_gen = _sym_gen_factory(vocab_size, num_hidden, num_embed,
                               batch)

    mod = mx.mod.BucketingModule(
        sym_gen, default_bucket_key=it.default_bucket_key)

    from incubator_mxnet_tpu.ops.rnn import rnn_param_size
    psize = rnn_param_size("lstm", 1, num_embed, num_hidden)

    def shapes_for(bkey, bsz):
        return ([mx.io.DataDesc("data", (bsz, bkey)),
                 mx.io.DataDesc("rnn_parameters", (psize,))],
                [mx.io.DataDesc("softmax_label", (bsz, bkey))])

    dsh, lsh = shapes_for(it.default_bucket_key, batch)
    mod.bind(data_shapes=dsh, label_shapes=lsh)
    mod.init_params(_initializer())
    mod.init_optimizer(kvstore=None, optimizer="adam",
                       optimizer_params=(("learning_rate", 0.01),))
    metric = mx.metric.Perplexity(ignore_label=0)

    def run_epoch():
        metric.reset()
        it.reset()
        for batch_data in it:
            dsh_b, lsh_b = shapes_for(batch_data.bucket_key, batch)
            batch_data.provide_data = dsh_b
            batch_data.provide_label = lsh_b
            mod.forward(batch_data, is_train=True)
            mod.backward()
            mod.update()
            mod.update_metric(metric, batch_data.label)
        return metric.get()[1]

    first = run_epoch()
    last = None
    for _ in range(4):
        last = run_epoch()
    assert last < first * 0.7, (first, last)


def test_bucketing_param_sync_across_buckets():
    """Updates on one bucket must be visible after switching."""
    rs = np.random.RandomState(2)
    vocab_size, num_hidden, num_embed, batch = 10, 4, 4, 2
    sym_gen = _sym_gen_factory(vocab_size, num_hidden, num_embed,
                               batch)
    from incubator_mxnet_tpu.ops.rnn import rnn_param_size
    psize = rnn_param_size("lstm", 1, num_embed, num_hidden)

    def shapes_for(bkey):
        return ([mx.io.DataDesc("data", (batch, bkey)),
                 mx.io.DataDesc("rnn_parameters", (psize,))],
                [mx.io.DataDesc("softmax_label", (batch, bkey))])

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=8)
    dsh, lsh = shapes_for(8)
    mod.bind(data_shapes=dsh, label_shapes=lsh)
    mod.init_params(_initializer())
    mod.init_optimizer(kvstore=None, optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.5),))

    def batch_for(bkey):
        d = mx.nd.array(rs.randint(1, vocab_size, (batch, bkey)))
        l = mx.nd.array(rs.randint(1, vocab_size, (batch, bkey)))
        dsh_b, lsh_b = shapes_for(bkey)
        return mx.io.DataBatch([d], [l], bucket_key=bkey,
                               provide_data=dsh_b,
                               provide_label=lsh_b)

    b8 = batch_for(8)
    mod.forward(b8, is_train=True)
    mod.backward()
    mod.update()
    w8 = mod.get_params()[0]["embed_weight"].asnumpy()
    # switch to a new bucket: params must carry over
    b4 = batch_for(4)
    mod.forward(b4, is_train=True)
    w4 = mod.get_params()[0]["embed_weight"].asnumpy()
    np.testing.assert_allclose(w8, w4, rtol=1e-6)


def test_bucket_iter_empty_bucket_ok():
    """An explicit bucket with no sentences must not crash."""
    it = mx.rnn.BucketSentenceIter([[1, 2, 3], [1, 2, 3, 4]],
                                   batch_size=1, buckets=[4, 30],
                                   invalid_label=0)
    n = sum(1 for _ in it)
    assert n == 2
