"""gluon.contrib cells (ref: python/mxnet/gluon/contrib/rnn/)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, nd
from incubator_mxnet_tpu.gluon.contrib.rnn import (
    Conv1DLSTMCell, Conv2DGRUCell, Conv2DLSTMCell, Conv2DRNNCell,
    VariationalDropoutCell)


def _step(cell, shape):
    cell.initialize(mx.initializer.Xavier())
    x = nd.array(np.random.RandomState(0).rand(*shape)
                 .astype("float32"))
    states = cell.begin_state(batch_size=shape[0])
    out, new_states = cell(x, states)
    return out, new_states


def test_conv2d_rnn_cell():
    cell = Conv2DRNNCell(input_shape=(3, 8, 8), hidden_channels=5,
                         i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
    out, states = _step(cell, (2, 3, 8, 8))
    assert out.shape == (2, 5, 8, 8)
    assert len(states) == 1 and states[0].shape == (2, 5, 8, 8)


def test_conv2d_lstm_cell_and_unroll():
    cell = Conv2DLSTMCell(input_shape=(2, 6, 6), hidden_channels=4,
                          i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
    out, states = _step(cell, (2, 2, 6, 6))
    assert out.shape == (2, 4, 6, 6)
    assert len(states) == 2             # h and c
    # unroll over a sequence
    seq = [nd.array(np.random.RandomState(i).rand(2, 2, 6, 6)
                    .astype("float32")) for i in range(3)]
    outs, final = cell.unroll(3, seq, merge_outputs=False)
    assert len(outs) == 3
    assert outs[-1].shape == (2, 4, 6, 6)


def test_conv2d_gru_cell():
    cell = Conv2DGRUCell(input_shape=(3, 5, 5), hidden_channels=6,
                         i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
    out, states = _step(cell, (1, 3, 5, 5))
    assert out.shape == (1, 6, 5, 5)


def test_conv1d_lstm_cell():
    cell = Conv1DLSTMCell(input_shape=(4, 10), hidden_channels=3,
                          i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
    out, states = _step(cell, (2, 4, 10))
    assert out.shape == (2, 3, 10)


def test_even_h2h_kernel_rejected():
    with pytest.raises(ValueError, match="odd"):
        Conv2DRNNCell(input_shape=(3, 8, 8), hidden_channels=5,
                      i2h_kernel=3, h2h_kernel=2)


def test_variational_dropout_locked_mask():
    mx.random.seed(0)
    base = gluon.rnn.LSTMCell(8)
    cell = VariationalDropoutCell(base, drop_inputs=0.5,
                                  drop_outputs=0.5)
    cell.initialize(mx.initializer.Xavier())
    x = nd.array(np.ones((2, 8), "float32"))
    states = cell.begin_state(batch_size=2)
    with autograd.record():
        o1, s1 = cell(x, states)
        o2, _ = cell(x, s1)
    # same mask across steps: zeroed output channels stay zeroed
    m1 = (o1.asnumpy() == 0)
    m2 = (o2.asnumpy() == 0)
    assert (m1 == m2).all()
    assert m1.any(), "dropout never dropped anything at p=0.5"
    cell.reset()
    # inference mode: no dropout
    o3, _ = cell(x, cell.begin_state(batch_size=2))
    assert not np.isnan(o3.asnumpy()).any()


def test_variational_dropout_trains():
    mx.random.seed(1)
    base = gluon.rnn.GRUCell(4)
    cell = VariationalDropoutCell(base, drop_states=0.3)
    cell.initialize(mx.initializer.Xavier())
    seq = [nd.array(np.random.RandomState(i).rand(2, 4)
                    .astype("float32")) for i in range(3)]
    with autograd.record():
        outs, _ = cell.unroll(3, seq, merge_outputs=False)
        loss = sum((o * o).sum() for o in outs)
    loss.backward()
    g = base.i2h_weight.data().grad
    assert g is not None and np.isfinite(g.asnumpy()).all()
