"""Worker script for the multi-process kvstore test — run under
tools/launch.py (the reference's single-machine dist trick:
tests/nightly/dist_sync_kvstore.py via tools/launch.py -n 2).

Not a pytest module: tests/test_dist_launch.py spawns it.
"""
import sys

import jax
jax.config.update("jax_platforms", "cpu")

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import dist


def main():
    kv = mx.kvstore.create("dist_sync")
    assert kv.num_workers == 2, kv.num_workers
    r = kv.rank

    # init: rank 0's proposal wins everywhere
    kv.init(3, mx.nd.array(np.full((4,), r + 10.0, np.float32)))
    out = mx.nd.zeros((4,))
    kv.pull(3, out=out)
    assert np.allclose(out.asnumpy(), 10.0), out.asnumpy()

    # push aggregates across workers (no updater -> pull merged grad)
    kv.push(3, mx.nd.array(np.full((4,), float(r + 1), np.float32)))
    kv.pull(3, out=out)
    assert np.allclose(out.asnumpy(), 3.0), out.asnumpy()

    # updater path: every worker applies the same summed grad
    opt = mx.optimizer.create("sgd", learning_rate=0.1,
                              rescale_grad=1.0)
    kv.set_optimizer(opt)
    kv.push(3, mx.nd.array(np.full((4,), float(r + 1), np.float32)))
    kv.pull(3, out=out)
    assert np.allclose(out.asnumpy(), 9.7), out.asnumpy()
    kv.barrier()

    # end-to-end: Module.fit on rank-sharded data, replicas converge
    # to identical parameters (the dist_sync contract)
    rs = np.random.RandomState(0)  # same data both ranks; shard below
    x = rs.rand(128, 10).astype(np.float32)
    w = rs.rand(10, 5).astype(np.float32)
    y = (x @ w).argmax(1).astype(np.float32)
    shard_x, shard_y = x[r::2], y[r::2]
    it = mx.io.NDArrayIter(shard_x, shard_y, batch_size=16,
                           label_name="softmax_label")
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc", num_hidden=5)
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mx.random.seed(0)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=2, kvstore="dist_sync", optimizer="sgd",
            optimizer_params=dict(learning_rate=0.5),
            initializer=mx.initializer.Xavier())
    arg, _ = mod.get_params()
    flat = np.concatenate([v.asnumpy().ravel()
                           for _, v in sorted(arg.items())])
    assert np.all(np.isfinite(flat))
    gathered = np.asarray(dist.allreduce_sum(
        jax.numpy.asarray(flat)))  # sum == 2x each if identical
    assert np.allclose(gathered, 2 * flat, rtol=1e-6), \
        np.abs(gathered - 2 * flat).max()

    print(f"DIST_OK rank {r}", flush=True)


if __name__ == "__main__":
    main()
