"""Driver-config example workloads with convergence gates
(VERDICT r2 task 7): config 2 (image classification, mesh path),
config 3 (bucketed LSTM perplexity), config 4 (SSD detection mAP).

Each example runs in --quick mode, which asserts its own gate
(loss / perplexity / mAP); these tests run them in-process on the
8-device virtual CPU mesh, same as they run unchanged on TPU.
"""
import os
import sys

import numpy as np

_EXAMPLES = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "examples")
if _EXAMPLES not in sys.path:
    sys.path.insert(0, _EXAMPLES)


def test_imagenet_synthetic_quick():
    import train_imagenet_synthetic as ex
    summary = ex.main(["--quick"])
    assert summary["final_loss"] < summary["first_loss"] * 0.7
    assert summary["mesh_dp"] == 8  # really trained on the mesh


def test_lstm_bucketing_quick():
    import lstm_bucketing as ex
    summary = ex.main(["--quick"])
    assert summary["final_ppl"] < summary["first_ppl"] * 0.6
    assert summary["final_ppl"] < summary["uniform_ppl"]


def test_ssd_train_quick():
    import ssd_train as ex
    summary = ex.main(["--quick"])
    assert summary["mAP"] > 0.5
    assert summary["final_loss"] < summary["first_loss"] * 0.7


def test_ssd_anchor_scale_8732():
    """Detection kernels at the reference's real SSD300 anchor count
    (VERDICT r2 weak #6: 'never run at realistic scale')."""
    import ssd_train as ex
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd
    ex.anchor_scale_check(mx, nd)


def test_transformer_lm_quick():
    import transformer_lm as ex
    summary = ex.main(["--quick"])
    assert summary["final_loss"] < summary["first_loss"] * 0.5
    assert "fox" in summary["generated"]


def test_transformer_lm_seq_parallel_quick():
    from incubator_mxnet_tpu.parallel import make_mesh, use_mesh
    import transformer_lm as ex
    with use_mesh(make_mesh(dp=2, sp=4)):
        summary = ex.main(["--quick", "--seq-parallel",
                           "--batch-size", "16"])
    assert summary["final_loss"] < summary["first_loss"] * 0.5


def test_train_mnist_quick():
    """Config 1: MLP on MNIST via the Module API (ref:
    example/image-classification/train_mnist.py)."""
    import train_mnist as ex
    summary = ex.main(["--quick", "--num-epochs", "3"])
    assert summary["val_acc"] > 0.95


def test_linear_classification_quick():
    """Driver config 5 (sparse): row_sparse weight through KVStore
    with O(touched-rows) pulls and lazy store-side SGD."""
    import linear_classification as ex
    summary = ex.main(["--quick"])
    assert summary["final_nll"] < summary["first_nll"] * 0.65
    assert summary["val_acc"] > 0.8
    # the sparse pull must actually be saving traffic
    assert summary["pull_savings"] > 0.25


def test_transformer_lm_moe_quick():
    """--moe-experts: the example trains a routed-MoE LM to the same
    convergence gate, on the mesh, with the aux loss in the
    objective."""
    import transformer_lm as ex
    summary = ex.main(["--quick", "--moe-experts", "4"])
    assert summary["final_loss"] < summary["first_loss"] * 0.5
    assert "fox" in summary["generated"]
