"""Module API + end-to-end training tests
(ref: tests/python/unittest/test_module.py, tests/python/train/
test_mlp.py — the integration/convergence net)."""
import logging

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, sym


def _mlp_symbol(num_hidden=32, num_classes=4):
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=num_hidden, name="fc1")
    net = sym.Activation(net, act_type="relu", name="relu1")
    net = sym.FullyConnected(net, num_hidden=num_classes, name="fc2")
    return sym.SoftmaxOutput(net, sym.Variable("softmax_label"),
                             name="softmax")


def _toy_dataset(n=400, dim=20, classes=4, seed=0):
    rs = np.random.RandomState(seed)
    centers = rs.randn(classes, dim).astype("float32") * 3
    labels = rs.randint(0, classes, n)
    data = centers[labels] + rs.randn(n, dim).astype("float32")
    return data.astype("float32"), labels.astype("float32")


def test_module_bind_shapes():
    net = _mlp_symbol()
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 20))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params()
    assert mod.binded and mod.params_initialized
    arg, aux = mod.get_params()
    assert arg["fc1_weight"].shape == (32, 20)


def test_module_fit_converges():
    data, labels = _toy_dataset()
    train_iter = mx.io.NDArrayIter(data, labels, batch_size=40,
                                   shuffle=True)
    val_iter = mx.io.NDArrayIter(data, labels, batch_size=40)
    net = _mlp_symbol()
    mod = mx.mod.Module(net, context=mx.cpu())
    mx.random.seed(0)
    mod.fit(train_iter, eval_data=val_iter, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            initializer=mx.init.Xavier(), num_epoch=5,
            eval_metric="acc")
    score = mod.score(val_iter, "acc")
    assert score[0][1] > 0.9, f"accuracy too low: {score}"


def test_module_fit_adam_kvstore_local():
    data, labels = _toy_dataset(seed=1)
    train_iter = mx.io.NDArrayIter(data, labels, batch_size=50,
                                   shuffle=True)
    net = _mlp_symbol()
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(train_iter, optimizer="adam", kvstore="local",
            optimizer_params={"learning_rate": 0.01},
            initializer=mx.init.Xavier(), num_epoch=4)
    score = mod.score(mx.io.NDArrayIter(data, labels, batch_size=50),
                      "acc")
    assert score[0][1] > 0.9


def test_module_predict_and_outputs():
    data, labels = _toy_dataset(n=60)
    it = mx.io.NDArrayIter(data, labels, batch_size=20)
    net = _mlp_symbol()
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=it.provide_data,
             label_shapes=it.provide_label)
    mod.init_params()
    out = mod.predict(it)
    assert out.shape == (60, 4)
    np.testing.assert_allclose(out.asnumpy().sum(-1), np.ones(60),
                               rtol=1e-4)


def test_module_checkpoint_roundtrip(tmp_path):
    data, labels = _toy_dataset(n=80)
    it = mx.io.NDArrayIter(data, labels, batch_size=16)
    net = _mlp_symbol()
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.init.Xavier())
    prefix = str(tmp_path / "mlp")
    mod.save_checkpoint(prefix, 3)
    loaded_sym, arg, aux = mx.load_checkpoint(prefix, 3)
    assert loaded_sym.list_arguments() == net.list_arguments()
    orig, _ = mod.get_params()
    np.testing.assert_allclose(arg["fc1_weight"].asnumpy(),
                               orig["fc1_weight"].asnumpy())
    # load into a fresh module and verify outputs match
    mod2 = mx.mod.Module(loaded_sym, context=mx.cpu())
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod2.init_params(arg_params=arg, aux_params=aux, force_init=True)
    it.reset()
    batch = it.next()
    mod.forward(batch, is_train=False)
    mod2.forward(batch, is_train=False)
    np.testing.assert_allclose(mod.get_outputs()[0].asnumpy(),
                               mod2.get_outputs()[0].asnumpy(),
                               rtol=1e-5)


def test_module_score_and_speedometer(caplog):
    data, labels = _toy_dataset(n=100)
    it = mx.io.NDArrayIter(data, labels, batch_size=25)
    net = _mlp_symbol()
    mod = mx.mod.Module(net, context=mx.cpu())
    cb = mx.callback.Speedometer(25, frequent=2)
    with caplog.at_level(logging.INFO):
        mod.fit(it, num_epoch=1, batch_end_callback=cb,
                initializer=mx.init.Xavier())
    res = mod.score(it, ["acc", "ce"])
    names = [r[0] for r in res]
    assert "accuracy" in names and "cross-entropy" in names


def test_fixed_params():
    net = _mlp_symbol()
    data, labels = _toy_dataset(n=40)
    it = mx.io.NDArrayIter(data, labels, batch_size=20)
    mod = mx.mod.Module(net, context=mx.cpu(),
                        fixed_param_names=["fc1_weight"])
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.init.Xavier())
    w_before = mod.get_params()[0]["fc1_weight"].asnumpy().copy()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})
    batch = it.next()
    mod.forward_backward(batch)
    mod.update()
    w_after = mod.get_params()[0]["fc1_weight"].asnumpy()
    np.testing.assert_allclose(w_before, w_after)  # frozen
    # but fc2 moved
    assert not np.allclose(
        mod.get_params()[0]["fc2_weight"].asnumpy().sum(), 0)


def test_module_load_applies_params(tmp_path):
    data, labels = _toy_dataset(n=40)
    it = mx.io.NDArrayIter(data, labels, batch_size=20)
    net = _mlp_symbol()
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.init.Xavier())
    prefix = str(tmp_path / "m")
    mod.save_checkpoint(prefix, 1)
    loaded = mx.mod.Module.load(prefix, 1)
    loaded.bind(data_shapes=it.provide_data,
                label_shapes=it.provide_label)
    # params must already be applied (no init_params call needed)
    it.reset()
    batch = it.next()
    mod.forward(batch, is_train=False)
    loaded.forward(batch, is_train=False)
    np.testing.assert_allclose(mod.get_outputs()[0].asnumpy(),
                               loaded.get_outputs()[0].asnumpy(),
                               rtol=1e-5)
