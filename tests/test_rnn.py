"""RNN stack tests (model: reference tests/python/unittest/test_gluon_rnn.py
— cell-vs-fused cross-checks are the consistency oracle, plus numpy
reference recurrences)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, autograd
from incubator_mxnet_tpu.gluon import rnn


def _np_lstm_ref(x, w_ih, w_hh, b_ih, b_hh, h0, c0):
    """Numpy LSTM, gate order i,f,g,o."""
    T, N, C = x.shape
    H = h0.shape[-1]
    h, c = h0.copy(), c0.copy()
    ys = []
    sig = lambda v: 1.0 / (1.0 + np.exp(-v))  # noqa: E731
    for t in range(T):
        g = x[t] @ w_ih.T + b_ih + h @ w_hh.T + b_hh
        i, f, gg, o = np.split(g, 4, axis=-1)
        c = sig(f) * c + sig(i) * np.tanh(gg)
        h = sig(o) * np.tanh(c)
        ys.append(h)
    return np.stack(ys), h, c


def test_fused_lstm_matches_numpy():
    rs = np.random.RandomState(0)
    T, N, C, H = 5, 3, 4, 6
    x = rs.rand(T, N, C).astype(np.float32)
    w_ih = rs.rand(4 * H, C).astype(np.float32) * 0.3
    w_hh = rs.rand(4 * H, H).astype(np.float32) * 0.3
    b_ih = rs.rand(4 * H).astype(np.float32) * 0.1
    b_hh = rs.rand(4 * H).astype(np.float32) * 0.1
    flat = np.concatenate([w_ih.ravel(), w_hh.ravel(), b_ih, b_hh])
    h0 = np.zeros((1, N, H), np.float32)
    c0 = np.zeros((1, N, H), np.float32)
    out = nd.RNN(nd.array(x), nd.array(flat), nd.array(h0),
                 nd.array(c0), state_size=H, num_layers=1,
                 mode="lstm", state_outputs=True)
    y, hT, cT = out
    ref_y, ref_h, ref_c = _np_lstm_ref(x, w_ih, w_hh, b_ih, b_hh,
                                       h0[0], c0[0])
    np.testing.assert_allclose(y.asnumpy(), ref_y, rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(hT.asnumpy()[0], ref_h, rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(cT.asnumpy()[0], ref_c, rtol=1e-5,
                               atol=1e-5)


@pytest.mark.parametrize("mode,layer_cls,cell_cls", [
    ("lstm", rnn.LSTM, rnn.LSTMCell),
    ("gru", rnn.GRU, rnn.GRUCell),
    ("rnn_relu", rnn.RNN, rnn.RNNCell),
])
def test_fused_layer_matches_cell_unroll(mode, layer_cls, cell_cls):
    """The fused lax.scan layer and the step-by-step cell must agree
    (the reference's test_rnn_cells consistency pattern)."""
    mx.random.seed(42)
    T, N, C, H = 4, 2, 3, 5
    rs = np.random.RandomState(1)
    x = rs.rand(N, T, C).astype(np.float32)

    layer = layer_cls(H, num_layers=1, layout="NTC", input_size=C)
    layer.initialize(mx.initializer.Uniform(0.2))
    y_fused = layer(nd.array(x))

    kw = {"activation": "relu"} if mode == "rnn_relu" else {}
    cell = cell_cls(H, input_size=C, **kw)
    cell.initialize()
    lp = layer.collect_params()
    for suffix in ["i2h_weight", "h2h_weight", "i2h_bias",
                   "h2h_bias"]:
        cell.params[cell.prefix + suffix].set_data(
            lp[layer.prefix + "l0_" + suffix].data())
    y_cell, _ = cell.unroll(T, nd.array(x), layout="NTC",
                            merge_outputs=True)
    np.testing.assert_allclose(y_fused.asnumpy(), y_cell.asnumpy(),
                               rtol=1e-5, atol=1e-5)


def test_bidirectional_lstm_shapes_and_reverse():
    T, N, C, H = 6, 2, 3, 4
    layer = rnn.LSTM(H, num_layers=2, bidirectional=True,
                     input_size=C)
    layer.initialize()
    x = nd.random.uniform(shape=(T, N, C))
    out, states = layer(x, layer.begin_state(N))
    assert out.shape == (T, N, 2 * H)
    assert states[0].shape == (4, N, H)  # L*D
    assert states[1].shape == (4, N, H)


def test_rnn_layer_grad_flows():
    layer = rnn.GRU(4, num_layers=2, input_size=3, dropout=0.1)
    layer.initialize()
    x = nd.random.uniform(shape=(5, 2, 3))
    x.attach_grad()
    with autograd.record():
        y = layer(x)
        loss = (y * y).sum()
    loss.backward()
    assert np.isfinite(x.grad.asnumpy()).all()
    assert np.abs(x.grad.asnumpy()).sum() > 0
    for _, p in layer.collect_params().items():
        g = p.grad().asnumpy()
        assert np.isfinite(g).all()


def test_sequential_stack_and_modifiers():
    stack = rnn.SequentialRNNCell()
    stack.add(rnn.LSTMCell(4, input_size=3))
    stack.add(rnn.DropoutCell(0.0))
    stack.add(rnn.ResidualCell(rnn.GRUCell(4, input_size=4)))
    stack.initialize()
    x = nd.random.uniform(shape=(2, 5, 3))
    out, states = stack.unroll(5, x, layout="NTC",
                               merge_outputs=True)
    assert out.shape == (2, 5, 4)
    assert len(states) == 3  # lstm h,c + gru h


def test_zoneout_cell_inference():
    cell = rnn.ZoneoutCell(rnn.RNNCell(4, input_size=3),
                           zoneout_outputs=0.3, zoneout_states=0.2)
    cell.initialize()
    x = nd.random.uniform(shape=(2, 5, 3))
    out, _ = cell.unroll(5, x, layout="NTC", merge_outputs=True)
    assert out.shape == (2, 5, 4)


def test_bidirectional_cell_unroll():
    bi = rnn.BidirectionalCell(rnn.LSTMCell(4, input_size=3),
                               rnn.LSTMCell(4, input_size=3))
    bi.initialize()
    x = nd.random.uniform(shape=(2, 5, 3))
    out, states = bi.unroll(5, x, layout="NTC", merge_outputs=True)
    assert out.shape == (2, 5, 8)
    assert len(states) == 4


def test_unfuse_matches_fused():
    T, N, C, H = 3, 2, 4, 5
    layer = rnn.LSTM(H, num_layers=2, input_size=C)
    layer.initialize(mx.initializer.Uniform(0.1))
    x = nd.random.uniform(shape=(T, N, C))
    y_fused = layer(x)
    stack = layer._unfuse()
    y_cells, _ = stack.unroll(T, x, layout="TNC", merge_outputs=True)
    np.testing.assert_allclose(y_fused.asnumpy(), y_cells.asnumpy(),
                               rtol=1e-5, atol=1e-5)


def test_rnn_in_hybrid_block_trains():
    """Tiny LM-style model with a fused LSTM learns on random data."""
    class Model(mx.gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.rnn = rnn.LSTM(16, input_size=8, layout="NTC")
                self.out = mx.gluon.nn.Dense(4)

        def hybrid_forward(self, F, x):
            h = self.rnn(x)
            return self.out(h.reshape((-1, 16)))

        def forward(self, x):
            from incubator_mxnet_tpu import nd as F
            return self.hybrid_forward(F, x)

    rs = np.random.RandomState(3)
    net = Model()
    net.initialize(mx.initializer.Xavier())
    x = nd.array(rs.rand(4, 5, 8))
    y = nd.array(rs.randint(0, 4, (20,)))
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = mx.gluon.Trainer(net.collect_params(), "adam",
                               {"learning_rate": 0.01}, kvstore=None)
    losses = []
    for _ in range(25):
        with autograd.record():
            out = net(x)
            loss = loss_fn(out, y).mean()
        loss.backward()
        trainer.step(1)
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])


def test_unroll_valid_length_states():
    """States returned with valid_length must be the state at each
    sequence's last valid step, not after padding."""
    cell = rnn.LSTMCell(4, input_size=3)
    cell.initialize(mx.initializer.Uniform(0.2))
    rs = np.random.RandomState(9)
    x = nd.array(rs.rand(2, 6, 3))  # NTC
    vl = nd.array(np.array([3, 6], np.float32))
    out_v, states_v = cell.unroll(6, x, layout="NTC",
                                  merge_outputs=True, valid_length=vl)
    # oracle: unroll only the first 3 steps for sequence 0
    out_3, states_3 = cell.unroll(
        3, nd.array(rs.rand(0, 0, 0).reshape(0, 0, 0))
        if False else x[:, :3], layout="NTC", merge_outputs=True)
    np.testing.assert_allclose(states_v[0].asnumpy()[0],
                               states_3[0].asnumpy()[0], rtol=1e-5)
    np.testing.assert_allclose(states_v[1].asnumpy()[0],
                               states_3[1].asnumpy()[0], rtol=1e-5)
    # masked region of outputs must be zero
    assert np.abs(out_v.asnumpy()[0, 3:]).sum() == 0


def test_bidirectional_valid_length_full_equals_none():
    """valid_length == full length must reproduce the plain unroll."""
    bi = rnn.BidirectionalCell(rnn.LSTMCell(4, input_size=3),
                               rnn.LSTMCell(4, input_size=3))
    bi.initialize(mx.initializer.Uniform(0.2))
    rs = np.random.RandomState(10)
    x = nd.array(rs.rand(5, 2, 3))  # TNC
    out_plain, _ = bi.unroll(5, x, layout="TNC", merge_outputs=True)
    vl = nd.array(np.array([5, 5], np.float32))
    out_vl, _ = bi.unroll(5, x, layout="TNC", merge_outputs=True,
                          valid_length=vl)
    np.testing.assert_allclose(out_plain.asnumpy(),
                               out_vl.asnumpy(), rtol=1e-5,
                               atol=1e-6)


def test_unroll_list_inputs():
    """Per-step list inputs infer batch from axis 0 of each step."""
    cell = rnn.RNNCell(5, input_size=3)
    cell.initialize()
    rs = np.random.RandomState(11)
    steps = [nd.array(rs.rand(2, 3)) for _ in range(4)]
    out, states = cell.unroll(4, steps, layout="TNC",
                              merge_outputs=True)
    assert out.shape == (4, 2, 5)
    assert states[0].shape == (2, 5)


def test_lstm_state_clip():
    rs = np.random.RandomState(12)
    T, N, C, H = 4, 2, 3, 4
    from incubator_mxnet_tpu.ops.rnn import rnn_param_size
    psize = rnn_param_size("lstm", 1, C, H)
    flat = nd.array(rs.rand(psize) * 2)
    x = nd.array(rs.rand(T, N, C) * 3)
    h0 = nd.zeros((1, N, H))
    c0 = nd.zeros((1, N, H))
    y, h, c = nd.RNN(x, flat, h0, c0, state_size=H, num_layers=1,
                     mode="lstm", state_outputs=True,
                     lstm_state_clip_min=-0.05,
                     lstm_state_clip_max=0.05)
    assert np.abs(c.asnumpy()).max() <= 0.05 + 1e-6
    # outputs must reflect clipped recurrence: |y| <= tanh(0.05)
    assert np.abs(y.asnumpy()).max() <= np.tanh(0.05) + 1e-6


def test_rnn_eager_steady_state_no_recompile(caplog):
    """Regression (r5): eager RNN training must stop compiling after
    warmup.  The generic dispatch re-traced a fresh closure per call
    and lax.scan's compile cache keys on jaxpr identity, so EVERY
    eager step paid 4 XLA scan compiles (forward + vjp x 2
    directions) — long example loops eventually died in LLVM with
    ENOMEM.  cache_vjp routes RNN through a stable jitted pair."""
    import logging

    import jax

    from incubator_mxnet_tpu import autograd, gluon
    from incubator_mxnet_tpu.gluon import rnn as grnn

    # deliberately odd shapes: no other test uses them, so the warmup
    # is guaranteed to compile fresh even mid-suite (jit caches are
    # process-wide) — which the channel-validation assert relies on
    net = grnn.LSTM(9, num_layers=1, bidirectional=True,
                    layout="NTC", input_size=5)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    x = nd.array(np.random.RandomState(0)
                 .randn(3, 7, 5).astype(np.float32))

    def step():
        with autograd.record():
            out, _ = net(x, net.begin_state(3))
            loss = (out * out).mean()
        loss.backward()
        trainer.step(3)

    jax.config.update("jax_log_compiles", True)
    try:
        logger = logging.getLogger("jax._src.interpreters.pxla")
        with caplog.at_level(logging.WARNING, logger=logger.name):
            for _ in range(2):   # warmup: compiles allowed
                step()
        # validate the detection channel itself: if jax ever moves or
        # renames the compile log, this test must fail loudly, not
        # pass vacuously
        assert any("Compiling" in r.getMessage()
                   for r in caplog.records), \
            "compile-log channel broken: warmup produced no records"
        caplog.clear()   # drop the warmup's compile records
        with caplog.at_level(logging.WARNING,
                             logger=logger.name):
            for _ in range(3):
                step()
        compiles = [r for r in caplog.records
                    if "Compiling" in r.getMessage()]
        assert not compiles, \
            f"{len(compiles)} recompiles at steady state: " \
            f"{[r.getMessage()[:80] for r in compiles[:4]]}"
    finally:
        jax.config.update("jax_log_compiles", False)
