"""RecordIO + image pipeline tests (model: reference
tests/python/unittest/test_recordio.py / test_image.py; oracle:
roundtrip identity and the pure-Python backend vs the native one)."""
import glob
import os
import sys
import tempfile

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import recordio as rio


def test_recordio_roundtrip():
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "t.rec")
        w = rio.MXRecordIO(path, "w")
        records = [b"hello", b"x" * 1000, b"", b"abc" * 77]
        for r in records:
            w.write(r)
        w.close()
        r = rio.MXRecordIO(path, "r")
        got = []
        while True:
            rec = r.read()
            if rec is None:
                break
            got.append(rec)
        r.close()
        assert got == records


def test_recordio_embedded_magic():
    """Records containing the magic word must survive (split+rejoin
    escaping, ref: dmlc recordio)."""
    import struct
    magic = struct.pack("<I", 0xced7230a)
    payload = b"A" * 10 + magic + b"B" * 7 + magic + magic + b"C"
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "t.rec")
        w = rio.MXRecordIO(path, "w")
        w.write(payload)
        w.write(magic)
        w.close()
        r = rio.MXRecordIO(path, "r")
        assert r.read() == payload
        assert r.read() == magic
        assert r.read() is None
        r.close()


def test_native_and_python_backends_agree():
    """The C++ writer's bytes must be readable by the Python reader
    and vice versa."""
    if rio._native_lib() is None:
        pytest.skip("native recordio unavailable")
    payload = [b"abc", b"d" * 501, struct_magic()]
    with tempfile.TemporaryDirectory() as td:
        p1 = os.path.join(td, "native.rec")
        w = rio.MXRecordIO(p1, "w")
        assert w._lib is not None  # native
        for r in payload:
            w.write(r)
        w.close()
        r1 = rio.MXRecordIO(p1, "r")
        r1._lib = None  # force python reader
        r1.reset()
        got = [r1.read() for _ in payload]
        assert got == payload
        r1.close()
        # python writer -> native reader
        p2 = os.path.join(td, "py.rec")
        w2 = rio.MXRecordIO(p2, "w")
        w2._lib = None
        w2.reset()
        for r in payload:
            w2.write(r)
        w2.close()
        r2 = rio.MXRecordIO(p2, "r")
        assert r2._lib is not None
        got = [r2.read() for _ in payload]
        assert got == payload
        r2.close()


def struct_magic():
    import struct
    return b"Z" * 3 + struct.pack("<I", 0xced7230a) + b"Q" * 9


def test_indexed_recordio():
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "t.rec")
        idxp = os.path.join(td, "t.idx")
        w = rio.MXIndexedRecordIO(idxp, path, "w")
        for i in range(10):
            w.write_idx(i, f"record-{i}".encode())
        w.close()
        assert os.path.exists(idxp)
        r = rio.MXIndexedRecordIO(idxp, path, "r")
        assert r.read_idx(7) == b"record-7"
        assert r.read_idx(0) == b"record-0"
        assert r.read_idx(9) == b"record-9"
        r.close()


def test_pack_unpack_img():
    rs = np.random.RandomState(0)
    img = (rs.rand(32, 24, 3) * 255).astype(np.uint8)
    header = rio.IRHeader(0, 3.0, 42, 0)
    s = rio.pack_img(header, img, quality=100, img_fmt=".png")
    h2, img2 = rio.unpack_img(s)
    assert h2.label == 3.0 and h2.id == 42
    np.testing.assert_array_equal(img2, img)  # png is lossless


def test_pack_multi_label():
    header = rio.IRHeader(0, [1.0, 2.0, 3.0], 7, 0)
    s = rio.pack(header, b"payload")
    h2, payload = rio.unpack(s)
    assert h2.flag == 3
    np.testing.assert_allclose(h2.label, [1, 2, 3])
    assert payload == b"payload"


def _make_rec_dataset(td, n=24, size=40):
    """Synthetic labeled image dataset packed via the im2rec tool."""
    rs = np.random.RandomState(1)
    from PIL import Image
    for cls in range(3):
        d = os.path.join(td, "data", f"class{cls}")
        os.makedirs(d, exist_ok=True)
        for i in range(n // 3):
            arr = np.full((size, size, 3), cls * 80, np.uint8) + \
                (rs.rand(size, size, 3) * 40).astype(np.uint8)
            Image.fromarray(arr).save(os.path.join(d, f"{i}.png"))
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "..", "tools"))
    import im2rec
    prefix = os.path.join(td, "ds")
    im2rec.make_list(prefix, os.path.join(td, "data"))
    im2rec.pack(prefix, os.path.join(td, "data"))
    return prefix


def test_im2rec_and_image_record_iter():
    with tempfile.TemporaryDirectory() as td:
        prefix = _make_rec_dataset(td)
        assert os.path.exists(prefix + ".rec")
        assert os.path.exists(prefix + ".idx")
        it = mx.image.ImageRecordIter(
            path_imgrec=prefix + ".rec", data_shape=(3, 32, 32),
            batch_size=8, shuffle=True, rand_mirror=True,
            preprocess_threads=2)
        total, labels_seen = 0, set()
        for batch in it:
            assert batch.data[0].shape == (8, 3, 32, 32)
            total += 8 - batch.pad
            labels_seen.update(
                batch.label[0].asnumpy()[:8 - batch.pad].tolist())
        assert total == 24
        assert labels_seen == {0.0, 1.0, 2.0}
        # second epoch works (prefetcher restart)
        it.reset()
        assert sum(8 - b.pad for b in it) == 24


def test_image_iter_from_list():
    with tempfile.TemporaryDirectory() as td:
        prefix = _make_rec_dataset(td)
        it = mx.image.ImageIter(
            batch_size=6, data_shape=(3, 32, 32),
            path_imglist=prefix + ".lst",
            path_root=os.path.join(td, "data"))
        batch = it.next()
        assert batch.data[0].shape == (6, 3, 32, 32)


def test_augmenters():
    rs = np.random.RandomState(2)
    img = mx.nd.array((rs.rand(50, 40, 3) * 255).astype(np.uint8))
    out = mx.image.resize_short(img, 30)
    assert min(out.shape[:2]) == 30
    crop, rect = mx.image.center_crop(img, (24, 24))
    assert crop.shape == (24, 24, 3)
    norm = mx.image.color_normalize(
        crop, mean=[1, 2, 3], std=[2, 2, 2])
    np.testing.assert_allclose(
        norm.asnumpy(),
        (crop.asnumpy().astype(np.float32) - [1, 2, 3]) / [2, 2, 2],
        rtol=1e-5)


def test_pack_flag_roundtrip_and_2d_labels():
    # caller-set nonzero flag with scalar label must round-trip
    h, payload = rio.unpack(rio.pack(rio.IRHeader(2, 5.0, 1, 0),
                                     b"payload"))
    assert h.label == 5.0 and payload == b"payload"
    # 2-D labels flatten to size, not first-axis length
    lab = np.arange(6, dtype=np.float32).reshape(2, 3)
    h2, p2 = rio.unpack(rio.pack(rio.IRHeader(0, lab, 0, 0), b"x"))
    assert h2.flag == 6
    np.testing.assert_allclose(h2.label, lab.reshape(-1))
    assert p2 == b"x"


def test_record_iter_midepoch_reset():
    """reset() mid-epoch must restart cleanly with no stale batches."""
    with tempfile.TemporaryDirectory() as td:
        prefix = _make_rec_dataset(td)
        it = mx.image.ImageRecordIter(
            path_imgrec=prefix + ".rec", data_shape=(3, 32, 32),
            batch_size=8, preprocess_threads=2, prefetch_buffer=1)
        it.next()  # consume one batch, producer mid-flight
        it.reset()
        assert sum(8 - b.pad for b in it) == 24


def test_record_iter_corrupt_record_raises():
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "bad.rec")
        w = rio.MXRecordIO(path, "w")
        w.write(b"this is not an image")
        w.close()
        it = mx.image.ImageRecordIter(
            path_imgrec=path, data_shape=(3, 8, 8), batch_size=1,
            preprocess_threads=1)
        with pytest.raises(Exception):
            it.next()


def test_record_iter_round_batch_wraps():
    with tempfile.TemporaryDirectory() as td:
        prefix = _make_rec_dataset(td)  # 24 records
        it = mx.image.ImageRecordIter(
            path_imgrec=prefix + ".rec", data_shape=(3, 32, 32),
            batch_size=10, preprocess_threads=2, round_batch=True)
        batches = list(it)
        assert [b.pad for b in batches] == [0, 0, 6]
        tail = batches[-1].data[0].asnumpy()
        assert np.abs(tail[4:]).sum() > 0  # wrapped, not zero-padded


def test_im2rec_multithreaded_matches_serial():
    # --num-thread encodes on a pool but the writer stays in list
    # order, so the .rec/.idx must be byte-identical to serial
    with tempfile.TemporaryDirectory() as td:
        _make_rec_dataset(td)
        import im2rec
        root = os.path.join(td, "data")
        p1 = os.path.join(td, "serial")
        p2 = os.path.join(td, "mt")
        im2rec.make_list(p1, root)
        im2rec.make_list(p2, root)
        n1 = im2rec.pack(p1, root, resize=32, num_thread=1)
        n2 = im2rec.pack(p2, root, resize=32, num_thread=4)
        assert n1 == n2 == 24
        with open(p1 + ".rec", "rb") as a, open(p2 + ".rec", "rb") as b:
            assert a.read() == b.read()
        with open(p1 + ".idx") as a, open(p2 + ".idx") as b:
            assert a.read() == b.read()


def test_native_decode_matches_pil_pipeline(tmp_path):
    """The native fast path (src/imgdec) must match the PIL augmenter
    chain on the same .rec file: same-size images (identity crop),
    mean/std normalization, labels preserved."""
    from incubator_mxnet_tpu.image import native_dec
    if not native_dec.available():
        pytest.skip("native decoder unavailable")
    import io as pyio

    from PIL import Image

    rs = np.random.RandomState(0)
    prefix = str(tmp_path / "d")
    rec = rio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    for i in range(12):
        gx = np.linspace(0, 255, 32, dtype=np.float32)
        img = (gx[None, :, None] * 0.4 + gx[:, None, None] * 0.4
               + rs.rand(32, 32, 3) * 50).astype(np.uint8)
        b = pyio.BytesIO()
        Image.fromarray(img).save(b, format="JPEG", quality=92)
        rec.write_idx(i, rio.pack(
            rio.IRHeader(0, float(i % 5), i, 0), b.getvalue()))
    rec.close()

    def run(native):
        os.environ["MXTPU_NATIVE_DECODE"] = "1" if native else "0"
        try:
            it = mx.io.ImageRecordIter(
                path_imgrec=prefix + ".rec", data_shape=(3, 32, 32),
                batch_size=4, shuffle=False, mean_r=10.0, mean_g=5.0,
                mean_b=2.0, std_r=2.0, std_g=2.0, std_b=2.0,
                preprocess_threads=2)
            assert (it._native is not None) == native
            out = [(b.data[0].asnumpy().copy(),
                    b.label[0].asnumpy().copy()) for b in it]
        finally:
            os.environ.pop("MXTPU_NATIVE_DECODE", None)
        return out

    nat, pil = run(True), run(False)
    assert len(nat) == len(pil) == 3
    for (dn, ln), (dp, lp) in zip(nat, pil):
        np.testing.assert_array_equal(ln, lp)
        # identical libjpeg decode; normalization in float both ways
        np.testing.assert_allclose(dn, dp, atol=1e-4)


def test_native_small_image_matches_pil_crop_semantics(tmp_path):
    """Images smaller than the target: native must follow PIL's
    center_crop (crop available region, then resize the crop), not
    full-frame squash (review regression)."""
    from incubator_mxnet_tpu.image import native_dec
    if not native_dec.available():
        pytest.skip("native decoder unavailable")
    import io as pyio

    from PIL import Image

    from incubator_mxnet_tpu.image.image import (CenterCropAug,
                                                 imdecode)

    rs = np.random.RandomState(2)
    img = (rs.rand(20, 64, 3) * 255).astype(np.uint8)   # h < target
    b = pyio.BytesIO()
    Image.fromarray(img).save(b, format="JPEG", quality=95)
    raw = b.getvalue()
    out = native_dec.decode_batch([raw], (32, 32))[0]
    pil_img = imdecode(raw)
    want = np.asarray(CenterCropAug((32, 32))(pil_img)) \
        .astype(np.float32).transpose(2, 0, 1)
    # same crop window; resize kernels differ (bilinear vs PIL) —
    # structural agreement, not bit equality
    corr = np.corrcoef(out.ravel(), want.ravel())[0, 1]
    assert corr > 0.97, corr


def test_non_jpeg_batch_falls_back_to_pil(tmp_path):
    """A .rec of PNGs must keep working with the native gate on."""
    import io as pyio

    from PIL import Image

    rs = np.random.RandomState(1)
    prefix = str(tmp_path / "p")
    rec = rio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    for i in range(6):
        img = (rs.rand(16, 16, 3) * 255).astype(np.uint8)
        b = pyio.BytesIO()
        Image.fromarray(img).save(b, format="PNG")
        rec.write_idx(i, rio.pack(
            rio.IRHeader(0, float(i), i, 0), b.getvalue()))
    rec.close()
    it = mx.io.ImageRecordIter(path_imgrec=prefix + ".rec",
                               data_shape=(3, 16, 16), batch_size=3,
                               shuffle=False)
    batches = list(it)
    assert len(batches) == 2
    assert np.isfinite(batches[0].data[0].asnumpy()).all()


def test_native_decode_failure_falls_back_per_batch(tmp_path):
    """A JPEG libjpeg rejects (truncated) inside a native batch must
    fall back to the PIL path, not abort the epoch (review
    regression: CMYK/odd JPEGs that PIL handles)."""
    from incubator_mxnet_tpu.image import native_dec
    if not native_dec.available():
        pytest.skip("native decoder unavailable")
    import io as pyio

    from PIL import Image

    rs = np.random.RandomState(4)
    prefix = str(tmp_path / "t")
    rec = rio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    for i in range(4):
        img = (rs.rand(16, 16, 3) * 255).astype(np.uint8)
        b = pyio.BytesIO()
        Image.fromarray(img).save(b, format="JPEG", quality=95)
        raw = b.getvalue()
        if i == 2:
            raw = raw[:len(raw) // 2]   # truncated: both paths fail
        rec.write_idx(i, rio.pack(
            rio.IRHeader(0, float(i), i, 0), raw))
    rec.close()
    it = mx.io.ImageRecordIter(path_imgrec=prefix + ".rec",
                               data_shape=(3, 16, 16), batch_size=4,
                               shuffle=False)
    # truly corrupt record: PIL fallback also fails -> surfaces
    with pytest.raises(Exception):
        next(iter(it))


def test_native_std_only_matches_pil_noop(tmp_path):
    """std_* without mean_*: CreateAugmenter skips normalization, so
    the native path must too (review regression)."""
    import io as pyio

    from PIL import Image

    rs = np.random.RandomState(5)
    prefix = str(tmp_path / "s")
    rec = rio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    img = (rs.rand(16, 16, 3) * 255).astype(np.uint8)
    b = pyio.BytesIO()
    Image.fromarray(img).save(b, format="JPEG", quality=95)
    rec.write_idx(0, rio.pack(rio.IRHeader(0, 0.0, 0, 0),
                              b.getvalue()))
    rec.close()

    def run(native):
        os.environ["MXTPU_NATIVE_DECODE"] = "1" if native else "0"
        try:
            it = mx.io.ImageRecordIter(
                path_imgrec=prefix + ".rec", data_shape=(3, 16, 16),
                batch_size=1, std_r=58.0, std_g=57.0, std_b=57.0)
            return next(iter(it)).data[0].asnumpy()
        finally:
            os.environ.pop("MXTPU_NATIVE_DECODE", None)
    np.testing.assert_allclose(run(True), run(False), atol=1e-4)


def test_native_decode_concurrent_batches(tmp_path):
    """Two threads calling decode_batch simultaneously (the train+val
    ImageRecordIter producer-thread situation — ctypes drops the GIL)
    must each get complete, correct batches.  Regression for the r4
    advisor HIGH finding: Pool::run state was overwritten by a second
    caller mid-batch, silently zero-filling the first caller's
    remaining images."""
    from incubator_mxnet_tpu.image import native_dec
    if not native_dec.available():
        pytest.skip("native decoder unavailable")
    import io as pyio
    import threading

    from PIL import Image

    rs = np.random.RandomState(11)
    raws_a, raws_b = [], []
    for raws, seed_off in ((raws_a, 0), (raws_b, 100)):
        for i in range(24):
            img = (rs.rand(24, 24, 3) * 255).astype(np.uint8)
            b = pyio.BytesIO()
            Image.fromarray(img).save(b, format="JPEG", quality=95)
            raws.append(b.getvalue())

    # single-threaded oracle, computed before the race
    ref_a = native_dec.decode_batch(raws_a, (24, 24), nthreads=1)
    ref_b = native_dec.decode_batch(raws_b, (24, 24), nthreads=1)

    n_iters, n_threads = 30, 4
    fails = []

    def worker(raws, ref):
        try:
            for _ in range(n_iters):
                out = native_dec.decode_batch(raws, (24, 24),
                                              nthreads=3)
                np.testing.assert_array_equal(out, ref)
        except Exception as exc:  # noqa: BLE001
            fails.append(exc)

    threads = [threading.Thread(
        target=worker, args=((raws_a, ref_a) if t % 2 == 0
                             else (raws_b, ref_b)))
        for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not fails, fails[0]


def test_native_decode_error_isolated_per_batch(tmp_path):
    """A failing batch's error message must survive a concurrent good
    batch: the error is returned through a per-call buffer, not the
    shared imgdec_last_error global."""
    from incubator_mxnet_tpu.image import native_dec
    if not native_dec.available():
        pytest.skip("native decoder unavailable")
    import io as pyio
    import threading

    from PIL import Image

    img = (np.random.RandomState(3).rand(16, 16, 3) * 255)
    b = pyio.BytesIO()
    Image.fromarray(img.astype(np.uint8)).save(b, format="JPEG")
    good, bad = b.getvalue(), b.getvalue()[:40]

    stop = threading.Event()
    spin_fails = []

    def spin_good():
        try:
            while not stop.is_set():
                native_dec.decode_batch([good] * 4, (16, 16),
                                        nthreads=2)
        except Exception as exc:  # noqa: BLE001
            spin_fails.append(exc)

    t = threading.Thread(target=spin_good)
    t.start()
    try:
        for _ in range(20):
            with pytest.raises(ValueError) as ei:
                native_dec.decode_batch([good, bad], (16, 16),
                                        nthreads=2)
            msg = str(ei.value)
            assert "failed for 1/2" in msg
            # the libjpeg message for THIS batch, never empty/stale
            assert msg.rstrip()[-1] != ":"
    finally:
        stop.set()
        t.join()
    # the concurrent good-batch load must have survived the whole
    # test — a dead spinner would mask the cross-batch regression
    assert not spin_fails, spin_fails[0]
