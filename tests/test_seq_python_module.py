"""SequentialModule + PythonModule/PythonLossModule (ref:
python/mxnet/module/sequential_module.py:28, python_module.py:28)."""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import sym
from incubator_mxnet_tpu.io.io import DataBatch, DataDesc


def _stage1():
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=16, name="s1fc")
    return sym.Activation(net, act_type="relu")


def _stage2():
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=4, name="s2fc")
    return sym.SoftmaxOutput(net, name="softmax")


def _batches(n=20, b=8):
    rs = np.random.RandomState(0)
    for _ in range(n):
        x = rs.rand(b, 6).astype("float32")
        y = (x[:, 0] > 0.5).astype("float32")
        yield DataBatch([mx.nd.array(x)], [mx.nd.array(y)])


def test_sequential_module_trains():
    seq = mx.mod.SequentialModule()
    seq.add(mx.mod.Module(_stage1(), label_names=[]))
    seq.add(mx.mod.Module(_stage2()), take_labels=True)
    seq.bind(data_shapes=[DataDesc("data", (8, 6))],
             label_shapes=[DataDesc("softmax_label", (8,))])
    seq.init_params(initializer=mx.initializer.Xavier())
    seq.init_optimizer(optimizer="sgd",
                       optimizer_params=dict(learning_rate=0.5))
    first = last = None
    for epoch in range(15):
        for batch in _batches():
            seq.forward(batch, is_train=True)
            seq.backward()
            seq.update()
        out = seq.get_outputs()[0].asnumpy()
        y = batch.label[0].asnumpy()
        acc = (out.argmax(1) == y).mean()
        if first is None:
            first = acc
        last = acc
    assert last >= 0.85, (first, last)
    arg, _ = seq.get_params()
    assert "s1fc_weight" in arg and "s2fc_weight" in arg


def test_python_loss_module_in_chain():
    # stage: linear scores -> python MSE-style loss head
    data = sym.Variable("data")
    scores = sym.FullyConnected(data, num_hidden=1, name="lin")

    def grad_func(label, pred):
        # d/dpred of 0.5*(pred - label)^2
        return pred - label.reshape(pred.shape)

    seq = mx.mod.SequentialModule()
    seq.add(mx.mod.Module(scores, label_names=[]))
    seq.add(mx.mod.PythonLossModule(grad_func=grad_func),
            take_labels=True)
    seq.bind(data_shapes=[DataDesc("data", (8, 3))],
             label_shapes=[DataDesc("softmax_label", (8,))])
    seq.init_params(initializer=mx.initializer.Xavier())
    seq.init_optimizer(optimizer="sgd",
                       optimizer_params=dict(learning_rate=0.2))
    rs = np.random.RandomState(1)
    w_true = np.array([[1.0], [-2.0], [0.5]], np.float32)
    losses = []
    for _ in range(150):
        x = rs.rand(8, 3).astype("float32")
        y = (x @ w_true).ravel()
        batch = DataBatch([mx.nd.array(x)], [mx.nd.array(y)])
        seq.forward(batch, is_train=True)
        pred = seq.get_outputs()[0].asnumpy().ravel()
        losses.append(float(((pred - y) ** 2).mean()))
        seq.backward()
        seq.update()
    assert losses[-1] < losses[0] * 0.1, (losses[0], losses[-1])
