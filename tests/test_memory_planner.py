"""Memory-pressure survival (docs/memory.md): the analytic HBM
planner cross-checked against XLA's ``memory_analysis()`` on the
bench train graphs, the preflight degrade ladder
(remat -> grad_accum -> typed MemoryPlanError), the runtime
``mem:oom`` guard (one rung + a single retry, bitwise-identical loss
on the remat rung), the exit-15 contracts, planner-sized serving KV
pools, and the lint rule that keeps broad handlers from swallowing a
real RESOURCE_EXHAUSTED untyped."""
import importlib
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import parallel, telemetry, tracing
from incubator_mxnet_tpu import resilience as rz
from incubator_mxnet_tpu import symbol as symmod
from incubator_mxnet_tpu.perf import memory_planner as mp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# which placeholder names in each bench graph are inputs (the rest
# are parameters the planner must count as resident)
GRAPH_INPUTS = {
    "mlp": {"data", "label"},
    "resnet_block": {"data"},
    "transformer_step": {"tokens", "labels"},
}


def _load_bench():
    sys.path.insert(0, REPO)
    try:
        return importlib.import_module("bench")
    finally:
        sys.path.pop(0)


@pytest.fixture(autouse=True)
def _clean_slate(monkeypatch):
    for var in ("MXTPU_FAULT_SPEC", "MXTPU_MEM_POLICY",
                "MXTPU_HBM_BYTES", "MXTPU_MEM_GATE_MARGIN",
                "MXTPU_TRACE_DUMP"):
        monkeypatch.delenv(var, raising=False)
    rz.reset_faults()
    telemetry.get_registry().reset()
    tracing.reset_for_tests()
    yield
    rz.reset_faults()
    telemetry.get_registry().reset()
    tracing.reset_for_tests()


def _counter(name):
    return telemetry.get_registry().counter(name).value


def _gauge(name):
    return telemetry.get_registry().gauge(name).value


# -------------------------------------------------------------- sizing
def test_tree_bytes_counts_metadata_only():
    tree = {"w": jax.ShapeDtypeStruct((64, 32), jnp.float32),
            "b": jax.ShapeDtypeStruct((32,), jnp.bfloat16),
            "i": jax.ShapeDtypeStruct((7,), jnp.int32)}
    assert mp.tree_bytes(tree) == 64 * 32 * 4 + 32 * 2 + 7 * 4
    assert mp.max_leaf_bytes(tree) == 64 * 32 * 4
    # bare shapes default to 4-byte elements
    assert mp.tree_bytes([np.zeros((3, 5), np.float32)]) == 60


def test_next_divisor_walks_the_ladder():
    assert mp.next_divisor(32, 1) == 2
    assert mp.next_divisor(32, 2) == 4
    assert mp.next_divisor(12, 2) == 3
    assert mp.next_divisor(12, 6) == 12
    assert mp.next_divisor(12, 12) is None
    assert mp.next_divisor(0, 1) is None


def test_memory_plan_total_and_describe():
    plan = mp.MemoryPlan(params=10 << 20, grads=5 << 20,
                         activations=2 << 20, meta={"site": "t"})
    assert plan.total() == float(17 << 20)
    text = plan.describe()
    assert "params=10.0MB" in text and "site=t" in text
    d = plan.as_dict()
    assert d["total"] == plan.total() and d["site"] == "t"


# ---------------------------------------------------------- grads model
def _mlp_liveness():
    bench = _load_bench()
    s, shapes = bench._graph_mlp(symmod)
    return mp.symbol_liveness(s, shapes,
                              input_names=GRAPH_INPUTS["mlp"])


def test_plan_grads_follow_donation_and_accum():
    live = _mlp_liveness()
    donate = mp.plan_memory(liveness=live, donate=True)
    keep = mp.plan_memory(liveness=live, donate=False)
    # donation aliases the masters: only the working gradient stays
    assert donate.grads == live["max_param_bytes"]
    assert keep.grads == live["params_bytes"]
    assert keep.outputs > 0.0 and donate.outputs == 0.0
    # accumulation materializes the full accumulator tree
    accum = mp.plan_memory(liveness=live, grad_accum=2)
    assert accum.grads == live["params_bytes"] + live["max_param_bytes"]
    assert accum.activations == pytest.approx(donate.activations / 2)
    # eval has no gradient term and peaks at the forward watermark
    ev = mp.plan_memory(liveness=live, train=False)
    assert ev.grads == 0.0
    assert ev.activations == live["forward_peak_bytes"]


def test_batch_shards_shrink_batch_carried_terms():
    live = _mlp_liveness()
    one = mp.plan_memory(liveness=live)
    four = mp.plan_memory(liveness=live, batch_shards=4)
    assert four.activations == pytest.approx(one.activations / 4)
    assert four.inputs == pytest.approx(one.inputs / 4)
    assert four.params == one.params


# ----------------------------------------------- cross-check vs XLA
def _train_compiled(s, shapes, inputs, grad_accum=1, remat=False):
    """Compile one donated SGD train step straight from the Symbol
    (abstract lowering only — nothing runs), so memory_analysis()
    reports the same step shape the planner models."""
    from incubator_mxnet_tpu.executor import build_graph_fn

    arg_names = s.list_arguments()
    aux_names = s.list_auxiliary_states()
    known = {k: v for k, v in shapes.items()
             if k in set(arg_names) | set(aux_names)}
    arg_shapes, _, aux_shapes = s.infer_shape_partial(**known)
    run = build_graph_fn(s)
    all_args = {n: tuple(sh) for n, sh in zip(arg_names, arg_shapes)}
    auxs = {n: jax.ShapeDtypeStruct(tuple(sh), np.float32)
            for n, sh in zip(aux_names, aux_shapes)}
    params = {n: jax.ShapeDtypeStruct(sh, np.float32)
              for n, sh in all_args.items() if n not in inputs}
    datas = {n: jax.ShapeDtypeStruct(
        sh, np.int32 if ("label" in n or "tokens" in n)
        else np.float32) for n, sh in all_args.items() if n in inputs}
    rng = jax.ShapeDtypeStruct((2,), np.uint32)

    def lossf(p, d, av, r):
        fwd = run({**p, **{k: v.astype(np.float32)
                           for k, v in d.items()}}, av, r, True)
        outs = fwd[0] if isinstance(fwd, tuple) else fwd
        loss = outs[-1] if isinstance(outs, (list, tuple)) else outs
        return jnp.mean(loss)

    lf = jax.checkpoint(lossf) if remat else lossf

    def step(p, d, av, r):
        if grad_accum <= 1:
            loss, g = jax.value_and_grad(lf)(p, d, av, r)
        else:
            def micro(carry, dslice):
                gsum, lsum = carry
                mloss, mg = jax.value_and_grad(lf)(p, dslice, av, r)
                gsum = jax.tree_util.tree_map(
                    lambda a, b: a + b, gsum, mg)
                return (gsum, lsum + mloss), None

            dm = {k: d[k].reshape(
                (grad_accum, d[k].shape[0] // grad_accum)
                + d[k].shape[1:]) for k in sorted(datas)}
            zeros = jax.tree_util.tree_map(jnp.zeros_like, p)
            (g, loss), _ = jax.lax.scan(
                micro, (zeros, jnp.zeros((), jnp.float32)), dm)
        newp = jax.tree_util.tree_map(
            lambda a, b: a - 0.1 * b, p, g)
        return loss, newp

    return (jax.jit(step, donate_argnums=(0,))
            .lower(params, datas, auxs, rng).compile())


@pytest.mark.parametrize("graph,accum", [
    ("mlp", 1), ("mlp", 2),
    ("resnet_block", 1), ("resnet_block", 2),
    ("transformer_step", 1),
])
def test_planner_within_20pct_of_xla(graph, accum):
    bench = _load_bench()
    s, shapes = getattr(bench, f"_graph_{graph}")(symmod)
    inputs = GRAPH_INPUTS[graph]
    compiled = _train_compiled(s, shapes, inputs, grad_accum=accum)
    xla = mp.xla_live_bytes(compiled.memory_analysis())
    if not xla:
        pytest.skip("backend reports no memory analysis")
    plan = mp.plan_memory(s, shapes, input_names=inputs,
                          grad_accum=accum, donate=True)
    rel = (plan.total() - xla) / xla
    assert abs(rel) <= 0.20, (
        f"{graph} accum={accum}: planner {plan.total():.0f} vs XLA "
        f"{xla:.0f} ({rel:+.1%}) — {plan.describe()}")


@pytest.mark.parametrize("graph",
                         ["mlp", "resnet_block", "transformer_step"])
def test_remat_and_accum_move_the_plan_directionally(graph):
    # planner-only: CPU XLA does not shrink temps under
    # jax.checkpoint, so remat is asserted against the model itself
    bench = _load_bench()
    s, shapes = getattr(bench, f"_graph_{graph}")(symmod)
    live = mp.symbol_liveness(s, shapes,
                              input_names=GRAPH_INPUTS[graph])
    base = mp.plan_memory(liveness=live)
    remat = mp.plan_memory(liveness=live, remat=True)
    assert remat.activations <= base.activations
    assert remat.total() <= base.total()
    accum = mp.plan_memory(liveness=live, grad_accum=2)
    assert accum.activations < base.activations
    assert accum.grads > base.grads


def test_remat_strictly_helps_on_a_deep_graph():
    bench = _load_bench()
    s, shapes = bench._graph_resnet_block(symmod)
    live = mp.symbol_liveness(s, shapes,
                              input_names=GRAPH_INPUTS["resnet_block"])
    assert live["forward_peak_bytes"] < live["retained_bytes"]


# ------------------------------------------------------------- preflight
def test_preflight_takes_remat_rung_and_records_it(monkeypatch):
    live = _mlp_liveness()

    def make(remat, accum):
        return mp.plan_memory(liveness=live, remat=remat,
                              grad_accum=accum)

    base, remat = make(False, 1).total(), make(True, 1).total()
    assert remat < base
    monkeypatch.setenv("MXTPU_MEM_GATE_MARGIN", "0")
    monkeypatch.setenv("MXTPU_HBM_BYTES",
                       str(int((base + remat) / 2)))
    res = mp.preflight(make, site="t", can_remat=True, batch_size=32)
    assert res.rungs == ["remat"]
    assert res.remat is True and res.grad_accum == 1
    assert _counter("memory_plan_degrades_total") == 1
    evs = tracing.events("mem_degrade", site="t")
    assert evs and evs[0]["rung"] == "remat"
    assert evs[0]["predicted_bytes"] == base
    assert _gauge("memory_plan_peak_bytes") == remat


def test_preflight_grad_accum_rungs_walk_divisors(monkeypatch):
    def make(remat, accum):
        return mp.MemoryPlan(params=100.0, activations=1000.0 / accum)

    monkeypatch.setenv("MXTPU_MEM_GATE_MARGIN", "0")
    monkeypatch.setenv("MXTPU_HBM_BYTES", "400")
    res = mp.preflight(make, site="t", can_remat=False, batch_size=8)
    assert res.rungs == ["grad_accum=2", "grad_accum=4"]
    assert res.grad_accum == 4 and res.remat is False
    assert res.plan.total() == 350.0


def test_preflight_dry_ladder_raises_typed(monkeypatch):
    def make(remat, accum):
        return mp.MemoryPlan(params=1e12)

    monkeypatch.setenv("MXTPU_HBM_BYTES", "1000")
    with pytest.raises(rz.MemoryPlanError) as ei:
        mp.preflight(make, site="gate", can_remat=True, batch_size=4)
    err = ei.value
    assert err.EXIT_CODE == rz.OOM_EXIT_CODE == 15
    assert err.rungs == ["remat", "grad_accum=2", "grad_accum=4"]
    assert "gate" in str(err) and "params=" in str(err)


def test_preflight_policy_off_and_warn(monkeypatch):
    def make(remat, accum):
        return mp.MemoryPlan(params=1e12)

    monkeypatch.setenv("MXTPU_HBM_BYTES", "1000")
    monkeypatch.setenv("MXTPU_MEM_POLICY", "off")
    assert mp.preflight(make, site="t") is None
    monkeypatch.setenv("MXTPU_MEM_POLICY", "warn")
    res = mp.preflight(make, site="t", can_remat=True, batch_size=4)
    assert res.rungs == [] and res.plan.total() == 1e12
    assert _counter("memory_plan_degrades_total") == 0


# --------------------------------------------------- train-step wiring
def _tiny_step(**kw):
    mx.random.seed(0)
    net = mx.gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(mx.gluon.nn.Dense(16, activation="relu"))
        net.add(mx.gluon.nn.Dense(4))
    net.initialize(mx.initializer.Xavier())
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.rand(8, 12), jnp.float32)
    y = jnp.asarray(rs.randint(0, 4, (8,)), jnp.int32)
    step = parallel.ShardedTrainStep(
        net, optimizer="sgd",
        optimizer_params=dict(learning_rate=0.1),
        mesh=parallel.make_mesh(), example_args=[x], **kw)
    return step, x, y


def test_sharded_step_preflight_populates_plan():
    step, x, y = _tiny_step()
    step(x, y, rng=jax.random.PRNGKey(0))
    assert step._mem_plan is not None
    assert step._mem_plan.total() > 0
    assert _gauge("memory_plan_peak_bytes") == step._mem_plan.total()


def test_no_planning_on_the_hot_path(monkeypatch):
    step, x, y = _tiny_step()
    step(x, y, rng=jax.random.PRNGKey(0))

    def boom(*a, **k):   # pragma: no cover - fails the test if hit
        raise AssertionError("memory planning ran on the step path")

    monkeypatch.setattr(mp, "preflight", boom)
    monkeypatch.setattr(mp, "plan_memory", boom)
    step(x, y, rng=jax.random.PRNGKey(1))


def test_module_bind_gated_by_preflight(monkeypatch):
    from incubator_mxnet_tpu import sym
    monkeypatch.setenv("MXTPU_HBM_BYTES", "1000")
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = sym.SoftmaxOutput(net, sym.Variable("softmax_label"),
                            name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    with pytest.raises(rz.MemoryPlanError) as ei:
        mod.bind(data_shapes=[("data", (8, 20))],
                 label_shapes=[("softmax_label", (8,))])
    assert "module_bind" in str(ei.value)
    # warn policy lets the same bind through
    monkeypatch.setenv("MXTPU_MEM_POLICY", "warn")
    mod2 = mx.mod.Module(net, context=mx.cpu())
    mod2.bind(data_shapes=[("data", (8, 20))],
              label_shapes=[("softmax_label", (8,))])
    assert mod2.binded


# ------------------------------------------------------ runtime mem:oom
def test_injected_oom_takes_one_rung_and_retries(monkeypatch):
    ref_step, x, y = _tiny_step()
    ref = [float(np.asarray(
        ref_step(x, y, rng=jax.random.PRNGKey(s))))
        for s in range(4)]

    monkeypatch.setenv("MXTPU_FAULT_SPEC", "mem:oom:2:error")
    rz.reset_faults()
    step, x, y = _tiny_step()
    assert step.remat is False
    got = [float(np.asarray(step(x, y, rng=jax.random.PRNGKey(s))))
           for s in range(4)]
    # the second crossing blew up; the guard took the remat rung and
    # retried the same batch once — remat changes the schedule, not
    # the math, so every loss is bitwise identical to the clean twin
    assert step.remat is True
    assert got == ref
    assert _counter("oom_retries_total") == 1
    evs = tracing.events("mem_degrade", cause="runtime_oom")
    assert evs and evs[0]["rung"] == "remat"
    assert evs[0]["site"] == "sharded_train_step"


def test_mem_fault_grammar_is_error_only():
    specs = rz.parse_fault_spec("mem:oom:2:error")
    assert specs == [("mem", "oom", 2, "error")]
    with pytest.raises(ValueError):
        rz.parse_fault_spec("mem:oom:1:hang")


def test_is_oom_classifier():
    assert rz.is_oom(RuntimeError("RESOURCE_EXHAUSTED: hbm"))
    assert rz.is_oom(RuntimeError("Allocator ran out of memory"))
    assert not rz.is_oom(RuntimeError("XLA compilation cached"))
    assert not rz.is_oom(rz.MemoryPlanError("t"))
    assert rz.as_oom_error(ValueError("shape mismatch"), "t") is None
    oom = rz.as_oom_error(RuntimeError("Out of memory"), "site_x",
                          plan=mp.MemoryPlan(params=4.0))
    assert isinstance(oom, rz.OomError)
    assert "site_x" in str(oom)


# ------------------------------------------------------- exit contracts
def _run_py(code, extra_env=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra_env or {})
    return subprocess.run([sys.executable, "-c", code], cwd=REPO,
                          env=env, capture_output=True, text=True,
                          timeout=240)


def test_exithook_maps_memory_errors_to_exit_15():
    for err in ("OomError('t')",
                "MemoryPlanError('t', rungs=['remat'])"):
        res = _run_py(
            "import incubator_mxnet_tpu.resilience as rz\n"
            "rz.install_diverged_exithook()\n"
            f"raise rz.{err}\n")
        assert res.returncode == 15, res.stderr
        assert err.split("(")[0] in res.stderr


def test_policy_off_dies_loudly_with_dump(tmp_path):
    dump = tmp_path / "flight.jsonl"
    code = (
        "import numpy as np, jax, jax.numpy as jnp\n"
        "import incubator_mxnet_tpu as mx\n"
        "from incubator_mxnet_tpu import parallel\n"
        "import incubator_mxnet_tpu.resilience as rz\n"
        "rz.install_diverged_exithook()\n"
        "mx.random.seed(0)\n"
        "net = mx.gluon.nn.HybridSequential()\n"
        "with net.name_scope():\n"
        "    net.add(mx.gluon.nn.Dense(8, in_units=12))\n"
        "net.initialize(mx.initializer.Xavier())\n"
        "x = jnp.ones((8, 12), jnp.float32)\n"
        "y = jnp.zeros((8,), jnp.int32)\n"
        "step = parallel.ShardedTrainStep(net, optimizer='sgd',\n"
        "    optimizer_params=dict(learning_rate=0.1),\n"
        "    mesh=parallel.make_mesh())\n"
        "step(x, y, rng=jax.random.PRNGKey(0))\n")
    res = _run_py(code, extra_env={
        "MXTPU_MEM_POLICY": "off",
        "MXTPU_FAULT_SPEC": "mem:oom:1:error",
        "MXTPU_TRACE_DUMP": str(dump),
    })
    assert res.returncode == 15, res.stderr
    assert "OomError" in res.stderr
    dumps = list(tmp_path.glob("flight*.jsonl"))
    assert dumps and dumps[0].stat().st_size > 0


# ----------------------------------------------------- serving KV pools
def test_serving_auto_num_blocks_sizes_from_headroom(monkeypatch):
    from incubator_mxnet_tpu.gluon.model_zoo.transformer import (
        TransformerLM)
    from incubator_mxnet_tpu.serving import ServingEngine

    def tiny():
        mx.random.seed(0)
        net = TransformerLM(37, d_model=32, n_layers=2, n_heads=4,
                            max_len=64)
        net.initialize(mx.initializer.Xavier())
        return net

    monkeypatch.setenv("MXTPU_HBM_BYTES", str(64 << 20))
    eng = ServingEngine(tiny(), max_batch=2, block_size=4,
                        num_blocks="auto")
    assert eng.auto_blocks
    # plenty of headroom: capped at a full context row per slot + scratch
    assert eng.num_blocks == eng.max_batch * eng.max_blocks + 1
    assert _gauge("memory_plan_peak_bytes") > 0
    # a chip the weights alone overflow refuses with a typed error
    monkeypatch.setenv("MXTPU_HBM_BYTES", "20000")
    with pytest.raises(rz.MemoryPlanError) as ei:
        ServingEngine(tiny(), max_batch=2, block_size=4,
                      num_blocks="auto")
    assert "serving_engine" in str(ei.value)


# -------------------------------------------------------------- lint
def _load_lint():
    spec = importlib.util.spec_from_file_location(
        "lint", os.path.join(REPO, "ci", "lint.py"))
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    return lint


def test_lint_flags_unguarded_broad_except(tmp_path):
    lint = _load_lint()
    d = tmp_path / "incubator_mxnet_tpu" / "parallel"
    d.mkdir(parents=True)
    f = d / "step.py"
    f.write_text(
        "class S:\n"
        "    def run(self, x):\n"
        "        try:\n"
        "            return self._step(x)\n"
        "        except Exception:\n"
        "            return None\n")
    assert any("typed OOM guard" in p for p in lint.check_file(f))

    f.write_text(   # routed through the typed guard: clean
        "class S:\n"
        "    def run(self, x):\n"
        "        try:\n"
        "            return self._step(x)\n"
        "        except Exception as exc:\n"
        "            oom = as_oom_error(exc, 'run')\n"
        "            if oom is not None:\n"
        "                raise oom from exc\n"
        "            raise\n")
    assert not any("typed OOM guard" in p for p in lint.check_file(f))

    f.write_text(   # annotated escape hatch: clean
        "class S:\n"
        "    def run(self, x):\n"
        "        try:\n"
        "            return self._step(x)\n"
        "        except Exception:   # oom-ok: probing optional API\n"
        "            return None\n")
    assert not any("typed OOM guard" in p for p in lint.check_file(f))

    f.write_text(   # broad except with no execute call inside: clean
        "class S:\n"
        "    def run(self, x):\n"
        "        try:\n"
        "            return int(x)\n"
        "        except Exception:\n"
        "            return 0\n")
    assert not any("typed OOM guard" in p for p in lint.check_file(f))
