"""Flight recorder (incubator_mxnet_tpu/tracing.py,
docs/observability.md): ring bounds + thread safety, disabled-mode
zero side effects, retrace attribution (shape/dtype/static/train),
the compile-budget watchdog, fault dumps (DivergedError /
DataPipelineError / serving eviction), serving lifecycle
completeness with preemption visible, device-memory accounting,
profiler async events, launch.py memory aggregation, and the new
lint rules."""
import json
import logging
import os
import sys
import threading
import warnings

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import (autograd, gluon, nd, profiler,
                                 resilience as rz, telemetry as tel,
                                 tracing)
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.utils.log import get_logger

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

VOCAB = 37


def _load_tool(name):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import importlib
        return importlib.import_module(name)
    finally:
        sys.path.pop(0)


def _load_lint():
    sys.path.insert(0, os.path.join(REPO, "ci"))
    try:
        import lint
        return lint
    finally:
        sys.path.pop(0)


@pytest.fixture(autouse=True)
def _fresh_tracing(monkeypatch):
    for var in ("MXTPU_TELEMETRY", "MXTPU_TRACE_DUMP",
                "MXTPU_TRACE_BUFFER", "MXTPU_COMPILE_BUDGET",
                "MXTPU_FAULT_SPEC"):
        monkeypatch.delenv(var, raising=False)
    tracing.reset_for_tests()
    tel.get_registry().reset()
    rz.reset_faults()
    yield
    tracing.reset_for_tests()
    tel.get_registry().reset()
    rz.reset_faults()


def _tiny_lm(**kw):
    from incubator_mxnet_tpu.gluon.model_zoo.transformer import \
        TransformerLM
    cfg = dict(d_model=32, n_layers=2, n_heads=4, max_len=64)
    cfg.update(kw)
    mx.random.seed(0)
    net = TransformerLM(VOCAB, **cfg)
    net.initialize(mx.initializer.Xavier())
    return net


# --------------------------------------------------------- ring buffer
def test_ring_bound_and_drop_count():
    rec = tracing.FlightRecorder(capacity=8)
    for i in range(20):
        rec.record("compile", i=i)
    evs = rec.events()
    assert len(evs) == 8
    assert rec.recorded == 20
    assert rec.dropped == 12
    # oldest evicted first; order and seq stamps survive
    assert [e["i"] for e in evs] == list(range(12, 20))
    assert [e["seq"] for e in evs] == list(range(12, 20))


def test_ring_capacity_env(monkeypatch):
    monkeypatch.setenv("MXTPU_TRACE_BUFFER", "3")
    tracing.reset_for_tests()
    for i in range(5):
        tracing.trace_event("compile", i=i)
    assert len(tracing.events()) == 3
    assert tracing.get_recorder().capacity == 3


def test_ring_thread_safety():
    rec = tracing.FlightRecorder(capacity=128)
    n_threads, per = 8, 200

    def worker(t):
        for i in range(per):
            rec.record("compile", t=t, i=i)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert rec.recorded == n_threads * per
    evs = rec.events()
    assert len(evs) == 128
    # seq stamps are unique and strictly increasing in buffer order
    seqs = [e["seq"] for e in evs]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)


def test_clear_does_not_count_as_dropped():
    rec = tracing.FlightRecorder(capacity=8)
    for i in range(10):
        rec.record("compile", i=i)
    assert rec.dropped == 2
    rec.clear()                 # deliberate drop != lost history
    rec.record("compile", i=99)
    assert rec.dropped == 2
    assert len(rec.events()) == 1


def test_snapshot_lock_timeout_never_blocks():
    rec = tracing.FlightRecorder(capacity=8)
    rec.record("compile", i=0)
    rec._lock.acquire()         # simulate the interrupted holder
    try:
        evs = rec._snapshot(lock_timeout=0.05)
        assert [e["i"] for e in evs] == [0]
    finally:
        rec._lock.release()


def test_sigusr1_dumps_without_killing(tmp_path):
    """An operator's `kill -USR1` must leave a dump AND a live
    process; run in a subprocess so the handler install stays out of
    the test runner."""
    import subprocess
    dump = str(tmp_path / "flight.jsonl")
    code = (
        "import os, signal\n"
        "os.environ['MXTPU_TRACE_DUMP'] = %r\n"
        "from incubator_mxnet_tpu import tracing\n"
        "tracing.trace_event('serve_enqueue', rid=5)\n"
        "assert tracing.install_signal_dump()\n"
        "os.kill(os.getpid(), signal.SIGUSR1)\n"
        "print('alive')\n" % dump)
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0 and "alive" in r.stdout, r.stderr
    lines = [json.loads(line)
             for line in open(dump).read().splitlines()]
    assert lines[0]["reason"].startswith("signal_")
    assert lines[1]["rid"] == 5


def test_sigterm_sig_ign_preserved(tmp_path):
    """A SIGTERM disposition of SIG_IGN (set by a parent that meant
    'only SIGKILL stops this worker') must survive the dump handler:
    dump, then stay alive — never escalate to SIG_DFL."""
    import subprocess
    dump = str(tmp_path / "flight.jsonl")
    code = (
        "import os, signal\n"
        "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
        "os.environ['MXTPU_TRACE_DUMP'] = %r\n"
        "from incubator_mxnet_tpu import tracing\n"
        "tracing.trace_event('serve_enqueue', rid=7)\n"
        "assert tracing.install_signal_dump()\n"
        "os.kill(os.getpid(), signal.SIGTERM)\n"
        "print('alive')\n" % dump)
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0 and "alive" in r.stdout, r.stderr
    lines = [json.loads(line)
             for line in open(dump).read().splitlines()]
    assert lines[0]["reason"].startswith("signal_")
    assert lines[1]["rid"] == 7


def test_events_filtering():
    tracing.trace_event("serve_enqueue", rid=1)
    tracing.trace_event("serve_enqueue", rid=2)
    tracing.trace_event("serve_retire", rid=1)
    assert len(tracing.events("serve_enqueue")) == 2
    assert len(tracing.events(rid=1)) == 2
    assert [e["event"] for e in tracing.events(rid=1)] == \
        ["serve_enqueue", "serve_retire"]


def test_disabled_mode_zero_side_effects(monkeypatch):
    monkeypatch.setenv("MXTPU_TELEMETRY", "0")
    tracing.trace_event("serve_enqueue", rid=1)
    assert tracing.recorder() is tracing.NULL_RECORDER
    # no recorder object was even allocated
    assert tracing._RECORDER["obj"] is None
    assert tracing.events() == []
    assert tracing.update_memory_gauges() == {}
    assert tel.get_registry().snapshot()["gauges"] == {}
    # the compile ledger honors the same contract: no history, no
    # totals, no budget accounting
    monkeypatch.setenv("MXTPU_COMPILE_BUDGET", "0.001")
    led = tracing.compile_ledger("disabled_site")
    assert led.record({"shape": (1,)}, 99.0) == "disabled"
    assert tracing.compile_totals() == (0, 0.0)
    assert len(led._sigs) == 0


# ------------------------------------------------- retrace attribution
def test_signature_diff_unit():
    base = {"shape": ((2, 3),), "dtype": ("float32",),
            "static_arg": (("i", 2),), "train_flag": False}
    assert tracing.signature_diff(base, []) == ("first_compile", [])

    def vary(**kw):
        sig = dict(base)
        sig.update(kw)
        return sig

    assert tracing.signature_diff(
        vary(shape=((4, 3),)), [base]) == ("shape", ["shape"])
    assert tracing.signature_diff(
        vary(dtype=("int32",)), [base]) == ("dtype", ["dtype"])
    assert tracing.signature_diff(
        vary(static_arg=(("i", 3),)), [base]) == \
        ("static_arg", ["static_arg"])
    assert tracing.signature_diff(
        vary(train_flag=True), [base]) == \
        ("train_flag", ["train_flag"])
    reason, changed = tracing.signature_diff(
        vary(shape=((4, 3),), train_flag=True), [base])
    assert reason == "shape+train_flag"
    assert tracing.signature_diff(dict(base), [base]) == \
        ("duplicate", [])
    # nearest-entry selection: diff against the closest prior
    # signature, not the first one
    other = vary(shape=((9, 9),), dtype=("int32",),
                 static_arg=(("i", 7),))
    assert tracing.signature_diff(
        vary(dtype=("int32",)), [other, base]) == \
        ("dtype", ["dtype"])


def test_compile_ledger_records_and_budget(caplog):
    led = tracing.compile_ledger("unit_site")
    assert tracing.compile_ledger("unit_site") is led
    assert led.record({"shape": (2,)}, 0.25) == "first_compile"
    assert led.record({"shape": (4,)}, 0.25) == "shape"
    evs = tracing.events("compile", site="unit_site")
    assert [e["reason"] for e in evs] == ["first_compile", "shape"]
    assert all(e["seconds"] == 0.25 for e in evs)
    reg = tel.get_registry()
    assert reg.counter("compile_events_total").value == 2
    assert reg.histogram("compile_seconds").count == 2
    assert tracing.compile_totals() == (2, 0.5)


def test_compile_budget_watchdog_warns_on_storm(caplog,
                                                monkeypatch):
    monkeypatch.setenv("MXTPU_COMPILE_BUDGET", "1.0")
    logger = get_logger()
    logger.propagate = True   # let caplog's root handler see it
    try:
        led = tracing.compile_ledger("storm_site")
        with caplog.at_level(logging.WARNING, logger=logger.name):
            led.record({"shape": (1,)}, 0.6)     # 0.6 < 1.0
            assert not caplog.records
            led.record({"shape": (2,)}, 0.6)     # 1.2 >= 1.0: warn
            assert len(caplog.records) == 1
            led.record({"shape": (3,)}, 0.3)     # 1.5 < 2.0: quiet
            assert len(caplog.records) == 1
            led.record({"shape": (4,)}, 0.6)     # 2.1 >= 2.0: again
            assert len(caplog.records) == 2
        assert "compile budget exceeded" in caplog.records[0].message
        assert "storm_site" in caplog.records[-1].getMessage()
    finally:
        logger.propagate = False


def test_cachedop_miss_attribution_shape_static_train():
    net = nn.Dense(4)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    site = f"cachedop:{net.name}"
    net(nd.array(np.zeros((2, 3), "float32")))
    net(nd.array(np.zeros((5, 3), "float32")))       # shape miss
    with autograd.record():                          # train-flag miss
        net(nd.array(np.zeros((5, 3), "float32")))
    reasons = [e["reason"]
               for e in tracing.events("compile", site=site)]
    assert reasons[0] == "first_compile"
    assert "shape" in reasons[1]
    assert "train_flag" in reasons[2]
    # replay: no further compile events
    n = len(tracing.events("compile", site=site))
    net(nd.array(np.zeros((2, 3), "float32")))
    assert len(tracing.events("compile", site=site)) == n


def test_cachedop_dtype_and_static_arg_components():
    from incubator_mxnet_tpu.graph import cached_op as co
    f32 = co._ArgsTemplate([nd.array(np.zeros((2, 3), "float32"))])
    i32 = co._ArgsTemplate([nd.array(np.zeros((2, 3), "int32"))])
    c_f = co._signature_components(f32, False)
    c_i = co._signature_components(i32, False)
    assert tracing.signature_diff(c_i, [c_f]) == ("dtype", ["dtype"])
    s2 = co._ArgsTemplate([nd.array(np.zeros((2, 3), "float32")), 2])
    s3 = co._ArgsTemplate([nd.array(np.zeros((2, 3), "float32")), 3])
    assert tracing.signature_diff(
        co._signature_components(s3, False),
        [co._signature_components(s2, False)]) == \
        ("static_arg", ["static_arg"])


def test_generate_compile_ledger():
    net = _tiny_lm()
    x = nd.array(np.asarray([[1, 2, 3]], np.int32))
    net.generate(x, 4)
    net.generate(x, 4)                               # replay
    net.generate(x, 6)                               # static miss
    net.generate(nd.array(np.asarray([[1, 2, 3, 4]], np.int32)), 6)
    evs = tracing.events("compile", site="transformer_generate")
    assert len(evs) == 3
    assert evs[0]["reason"] == "first_compile"
    assert evs[1]["reason"] == "static_arg"
    assert evs[2]["reason"] == "shape"
    assert all(e["seconds"] > 0 for e in evs)


# ----------------------------------------------------------- fault dumps
def test_manual_dump_atomic_jsonl(tmp_path):
    for i in range(5):
        tracing.trace_event("serve_enqueue", rid=i)
    path = str(tmp_path / "flight.jsonl")
    assert tracing.dump(path, reason="unit") == path
    lines = [json.loads(line)
             for line in open(path).read().splitlines()]
    assert lines[0]["flight_recorder"] == 1
    assert lines[0]["reason"] == "unit"
    assert lines[0]["events"] == 5 and lines[0]["dropped"] == 0
    assert [e["rid"] for e in lines[1:]] == list(range(5))
    assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]


def test_auto_dump_path_suffixed_per_rank(tmp_path, monkeypatch):
    """Multi-rank runs (MXTPU_WORKER_RANK set) suffix the automatic
    dump path per rank, so a healthy rank's SIGTERM dump can never
    clobber the faulting rank's post-mortem; explicit paths are
    written verbatim."""
    path = str(tmp_path / "flight.jsonl")
    monkeypatch.setenv("MXTPU_TRACE_DUMP", path)
    monkeypatch.setenv("MXTPU_WORKER_RANK", "3")
    tracing.trace_event("serve_enqueue", rid=1)
    got = tracing.dump(reason="unit")
    assert got == str(tmp_path / "flight.rank3.jsonl")
    lines = [json.loads(line)
             for line in open(got).read().splitlines()]
    assert lines[0]["rank"] == 3
    # explicit path: no suffix, caller said exactly where
    assert tracing.dump(path, reason="unit") == path


def test_no_dump_path_means_no_dump(tmp_path):
    tracing.trace_event("serve_enqueue", rid=0)
    assert tracing.dump() is None
    rz.DataPipelineError("boom")        # constructing must be inert
    assert os.listdir(tmp_path) == []


def test_dump_on_data_pipeline_error(tmp_path, monkeypatch):
    path = str(tmp_path / "flight.jsonl")
    monkeypatch.setenv("MXTPU_TRACE_DUMP", path)
    tracing.trace_event("serve_enqueue", rid=7)
    rz.DataPipelineError("prefetch wedged")
    lines = [json.loads(line)
             for line in open(path).read().splitlines()]
    assert lines[0]["reason"] == "data_pipeline_error"
    assert any(e.get("rid") == 7 for e in lines[1:])


def test_dump_on_diverged_error_e2e(tmp_path, monkeypatch):
    """Fault-injected divergence (MXTPU_FAULT_SPEC grad:nonfinite)
    leaves a flight-recorder dump holding the last events before the
    divergence — the sentinel's bad-step trail included."""
    path = str(tmp_path / "flight.jsonl")
    monkeypatch.setenv("MXTPU_TRACE_DUMP", path)
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "grad:nonfinite:*:nan")
    monkeypatch.setenv("MXTPU_NONFINITE_POLICY", "skip")
    monkeypatch.setenv("MXTPU_MAX_BAD_STEPS", "2")
    rz.reset_faults()
    mx.random.seed(42)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"))
    net.add(nn.Dense(3))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    rs = np.random.RandomState(0)
    x = nd.array(rs.randn(10, 4).astype("float32"))
    y = nd.array(rs.randint(0, 3, 10).astype("float32"))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(rz.DivergedError):
            for _ in range(8):
                with autograd.record():
                    loss = loss_fn(net(x), y)
                loss.backward()
                trainer.step(10)
    lines = [json.loads(line)
             for line in open(path).read().splitlines()]
    assert lines[0]["reason"] == "diverged_error"
    events = [e["event"] for e in lines[1:]]
    assert events.count("sentinel_bad_step") >= 2
    assert events[-1] == "sentinel_diverged"


# ------------------------------------------------ serving lifecycle
def test_serving_lifecycle_complete_with_preemption_and_eviction(
        monkeypatch):
    """Every submitted request's lifecycle is closed in the ring:
    enqueue -> admit -> ... -> exactly one terminal retire|evict;
    preemption is visible as preempt+requeue+re-admit; evicted and
    preempted requests record queue-wait like retired ones."""
    from incubator_mxnet_tpu.serving import ServingEngine
    net = _tiny_lm()
    rs = np.random.RandomState(19)
    prompts = [list(rs.randint(0, VOCAB, n)) for n in (9, 10, 6)]
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "serve:request:3:error")
    rz.reset_faults()
    # pool too small for two full sequences -> preemption
    eng = ServingEngine(net, max_batch=2, block_size=4,
                        num_blocks=12, prefix_cache=False)
    reqs = [eng.submit(p, 14) for p in prompts]
    eng.run()
    assert sum(r.preemptions for r in reqs) >= 1
    assert [r.state for r in reqs].count("failed") == 1
    for req in reqs:
        evs = tracing.events(rid=req.id, engine=eng.engine_id)
        names = [e["event"] for e in evs]
        assert names[0] == "serve_enqueue"
        terminal = [n for n in names
                    if n in ("serve_retire", "serve_evict")]
        assert len(terminal) == 1          # no silent exits
        assert terminal[0] == ("serve_evict"
                               if req.state == "failed"
                               else "serve_retire")
        assert evs[-1]["event"] == terminal[0]
        assert evs[-1]["queue_wait_s"] >= 0
        if req.preemptions:
            assert "serve_preempt" in names
            assert "serve_requeue" in names
            # one admit per admission; a request evicted on a
            # re-admission attempt dies queued, one admit short
            expect = req.preemptions + 1
            if req.state == "failed":
                assert names.count("serve_admit") in (expect - 1,
                                                      expect)
            else:
                assert names.count("serve_admit") == expect
        # cumulative queue wait covers every queued segment: the
        # terminal event's value is the sum over (re-)admissions
        # plus any open segment closed at eviction
        segs = [e["queue_wait_s"] for e in evs
                if e["event"] == "serve_admit"]
        if req.state == "failed":
            assert evs[-1]["queue_wait_s"] >= round(sum(segs), 6)
    # stats() parity: every request summarized, evicted included
    summaries = {s["id"]: s for s in eng.stats()["requests"]}
    assert set(summaries) == {r.id for r in reqs}
    failed = [s for s in summaries.values()
              if s["state"] == "failed"]
    assert len(failed) == 1 and failed[0]["queue_wait_s"] is not None
    assert failed[0]["error"]


def test_serving_eviction_triggers_fault_dump(tmp_path, monkeypatch):
    from incubator_mxnet_tpu.serving import ServingEngine
    path = str(tmp_path / "flight.jsonl")
    monkeypatch.setenv("MXTPU_TRACE_DUMP", path)
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "serve:request:1:error")
    rz.reset_faults()
    net = _tiny_lm()
    eng = ServingEngine(net, max_batch=1, block_size=4,
                        num_blocks=32, prefix_cache=False)
    req = eng.submit([1, 2, 3], 2)
    eng.run()
    assert req.state == "failed"
    lines = [json.loads(line)
             for line in open(path).read().splitlines()]
    assert lines[0]["reason"] == "serving_eviction"
    evicted = [e for e in lines[1:] if e.get("rid") == req.id
               and e.get("engine") == eng.engine_id]
    assert [e["event"] for e in evicted] == \
        ["serve_enqueue", "serve_evict"]


def test_serving_stats_ttft_decomposition():
    from incubator_mxnet_tpu.serving import ServingEngine
    net = _tiny_lm()
    eng = ServingEngine(net, max_batch=2, block_size=4,
                        num_blocks=64)
    reqs = [eng.submit([1, 2, 3, 4, 5], 6), eng.submit([7, 8], 4)]
    eng.run()
    stats = eng.stats()
    assert stats["live"] == []
    by_id = {s["id"]: s for s in stats["requests"]}
    for req in reqs:
        s = by_id[req.id]
        assert s["state"] == "finished"
        assert s["tokens_generated"] == req.max_new_tokens
        assert s["ttft_s"] >= s["prefill_s"] >= 0
        assert s["queue_wait_s"] >= 0
        assert s["decode_s"] >= 0
    assert stats["trace_counts"].get("decode") == 1


def test_serving_disabled_telemetry_records_nothing(monkeypatch):
    from incubator_mxnet_tpu.serving import ServingEngine
    monkeypatch.setenv("MXTPU_TELEMETRY", "0")
    net = _tiny_lm()
    eng = ServingEngine(net, max_batch=1, block_size=4,
                        num_blocks=32)
    eng.submit([1, 2, 3], 3)
    eng.run()
    assert tracing._RECORDER["obj"] is None
    assert tracing.events() == []
    # stats() still works: it is an API, not telemetry
    assert len(eng.stats()["requests"]) == 1


# ----------------------------------------------- profiler async events
def test_profiler_async_events_and_lanes(tmp_path):
    from incubator_mxnet_tpu.serving import ServingEngine
    net = _tiny_lm()
    profiler.set_config(filename=str(tmp_path / "prof.json"))
    profiler.set_state("run")
    try:
        eng = ServingEngine(net, max_batch=1, block_size=4,
                            num_blocks=32)
        eng.submit([1, 2, 3, 4], 3)
        eng.run()
        out = profiler.dump_profile()
    finally:
        profiler.set_state("stop")
    data = json.load(open(out))
    evs = data["traceEvents"]
    asyncs = [e for e in evs if e.get("ph") in ("b", "e")
              and e.get("cat") == "serving"]
    assert asyncs, "no async serving events in the dump"
    aid = f"req{eng.engine_id}.0"
    names = {e["name"] for e in asyncs if e["id"] == aid}
    assert {"request", "queue_wait", "prefill",
            "decode"} <= names
    # every b has a matching e per (name, id), ON THE SAME LANE —
    # terminal events fire after Scheduler.clear nulls req.slot, so
    # lane choice must not depend on the live slot
    for name in names:
        pair = [e for e in asyncs
                if e["id"] == aid and e["name"] == name]
        phases = [e["ph"] for e in pair]
        assert phases.count("b") == phases.count("e")
        assert len({e["tid"] for e in pair}) == 1, \
            f"phase {name} split across lanes"
    lanes = {e["args"]["name"] for e in evs
             if e.get("ph") == "M" and e["name"] == "thread_name"}
    assert "serve queue" in lanes
    assert "serve slot 0" in lanes


def test_profiler_async_rejects_bad_phase():
    with pytest.raises(ValueError, match="'b'/'e'"):
        profiler._profiler.add_async_event("x", "id1", "X")


# ------------------------------------------------- memory accounting
def test_memory_accounting_attribution():
    import jax.numpy as jnp
    bufs = [jnp.zeros((16, 16), jnp.float32),
            jnp.zeros((8,), jnp.float32)]
    nbytes = sum(int(b.nbytes) for b in bufs)
    unreg = tracing.register_memory("kv_pools", lambda: bufs)
    stats = tracing.device_memory_stats()
    assert stats["host_rss_bytes"] > 0
    assert stats["device_bytes_kv_pools"] == nbytes
    assert stats["device_live_bytes"] >= nbytes
    assert stats["device_bytes_workspace"] >= 0
    unreg()
    assert tracing.device_memory_stats()[
        "device_bytes_kv_pools"] == 0
    with pytest.raises(ValueError, match="memory kind"):
        tracing.register_memory("frobnicator", lambda: [])
    # a raising provider is skipped, never fatal
    unreg2 = tracing.register_memory(
        "params", lambda: (_ for _ in ()).throw(RuntimeError()))
    assert tracing.device_memory_stats()["device_bytes_params"] == 0
    unreg2()


def test_memory_gauges_ride_heartbeat_payload():
    tracing.compile_ledger("hb_site").record({"shape": (1,)}, 0.01)
    payload = tel.heartbeat_payload()
    snap = json.loads(payload)
    assert snap["gauges"]["host_rss_bytes"] > 0
    assert "device_live_bytes" in snap["gauges"]
    assert snap["counters"]["compile_events_total"] == 1


def test_serving_engine_registers_kv_pool_bytes():
    from incubator_mxnet_tpu.serving import ServingEngine
    net = _tiny_lm()
    eng = ServingEngine(net, max_batch=1, block_size=4,
                        num_blocks=16)
    expect = sum(int(a.nbytes)
                 for a in eng._kpools + eng._vpools)
    stats = tracing.device_memory_stats()
    assert stats["device_bytes_kv_pools"] == expect
    # owner teardown unregisters the provider (weakref.finalize):
    # repeated engine construction must not grow the table forever
    import gc
    del eng
    gc.collect()
    assert tracing.device_memory_stats()[
        "device_bytes_kv_pools"] == 0
    assert not tracing._MEM_PROVIDERS.get("kv_pools")


# --------------------------------------------- launch.py aggregation
def test_launch_aggregates_memory_and_compiles():
    launch = _load_tool("launch")
    snaps = {
        0: {"counters": {"train_steps_total": 10},
            "gauges": {"device_live_bytes": float(100 << 20),
                       "host_rss_bytes": float(500 << 20)}},
        1: {"counters": {"train_steps_total": 10,
                         "compile_events_total": 3},
            "gauges": {"device_live_bytes": float(200 << 20)}},
    }
    agg = launch._aggregate_telemetry(snaps)
    assert agg["max_memory"] == (1, float(200 << 20))
    assert agg["memory"][0] == float(100 << 20)
    assert agg["compiles"] == {1: 3}
    status = launch._format_status(agg)
    assert "mem: max rank 1 at 200MB" in status
    assert "compiles=3" in status
    report = launch._format_report(snaps)
    assert "max memory: rank 1 at 200MB" in report
    assert "rank 1: steps=10 mem=200MB compiles=3" in report
    # rss fallback when no device gauge is present
    assert launch._rank_memory(
        {"gauges": {"host_rss_bytes": 7.0}}) == 7.0
    assert launch._fmt_bytes(3 << 30) == "3.0GB"


# ------------------------------------------------------------ lint rules
def test_lint_trace_event_catalog_rule(tmp_path, monkeypatch):
    lint = _load_lint()
    monkeypatch.chdir(REPO)
    d = tmp_path / "incubator_mxnet_tpu"
    d.mkdir()
    f = d / "x.py"
    f.write_text("from . import tracing\n"
                 "tracing.trace_event('totally_undocumented_ev')\n")
    probs = lint.check_metric_catalog([f])
    assert any("trace-event name" in p for p in probs)
    f.write_text("from . import tracing\n"
                 "tracing.trace_event('serve_enqueue', rid=1)\n")
    assert not lint.check_metric_catalog([f])


def test_lint_host_sync_rule_covers_tracing(tmp_path):
    lint = _load_lint()
    d = tmp_path / "incubator_mxnet_tpu"
    d.mkdir()
    f = d / "tracing.py"
    f.write_text(
        "import numpy as np\n\n\n"
        "def update_memory_gauges(arr):\n"
        "    return np.asarray(arr)\n")
    assert any("host sync" in p for p in lint.check_file(f))
    f.write_text(
        "import numpy as np\n\n\n"
        "def update_memory_gauges(arr):\n"
        "    return np.asarray(arr)  # sync-ok: unit test\n")
    assert not any("host sync" in p for p in lint.check_file(f))
    # the shipped module passes its own rule
    real = os.path.join(REPO, "incubator_mxnet_tpu", "tracing.py")
    assert not any("host sync" in p
                   for p in lint.check_file(
                       __import__("pathlib").Path(real)))
