"""Serving tier (incubator_mxnet_tpu/serving/, docs/serving.md):
block-pool invariants, continuous-batching equivalence with
generate(), prefix-cache reuse, trace-count regression, preemption,
fault eviction, int8 quantization, predictor.serve, and the lint
rules that guard the hot paths."""
import os

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import resilience, telemetry
from incubator_mxnet_tpu.gluon.model_zoo.transformer import (
    TransformerLM)
from incubator_mxnet_tpu.serving import (
    FAILED, FINISHED, BlockPool, BlockPoolExhausted, PrefixCache,
    ServingEngine, quantization_error, quantize_weights,
    weights_nbytes)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

VOCAB = 37


def _tiny(vocab=VOCAB, **kw):
    cfg = dict(d_model=32, n_layers=2, n_heads=4, max_len=64)
    cfg.update(kw)
    mx.random.seed(0)
    net = TransformerLM(vocab, **cfg)
    net.initialize(mx.initializer.Xavier())
    return net


def _gen_ref(net, prompt, max_new):
    """Sequential one-request-at-a-time generate() reference."""
    out = net.generate(
        mx.nd.array(np.asarray([prompt], np.int32)), max_new)
    return [int(t) for t in out.asnumpy()[0]]


def _counter(name):
    return telemetry.get_registry().counter(name).value


# ------------------------------------------------------------ block pool
def test_block_pool_alloc_free_refcount():
    pool = BlockPool(num_blocks=8, block_size=4)
    assert pool.capacity == 7 and pool.num_free == 7
    a = pool.alloc(3)
    assert len(set(a)) == 3 and 0 not in a
    assert pool.num_allocated == 3
    assert all(pool.refcount(b) == 1 for b in a)
    pool.incref(a[:1])
    assert pool.refcount(a[0]) == 2
    pool.free(a)                       # a[0] survives via the incref
    assert pool.refcount(a[0]) == 1
    assert pool.num_allocated == 1
    pool.free(a[:1])
    assert pool.num_free == 7
    assert 0.0 == pool.utilization()


def test_block_pool_double_free_and_exhaustion():
    pool = BlockPool(num_blocks=4, block_size=2)
    a = pool.alloc(3)
    with pytest.raises(BlockPoolExhausted):
        pool.alloc(1)
    pool.free(a[:1])
    with pytest.raises(ValueError, match="double free"):
        pool.free(a[:1])
    with pytest.raises(ValueError, match="incref on free"):
        pool.incref(a[:1])
    # all-or-nothing alloc: a failed alloc leaks nothing
    free_before = pool.num_free
    with pytest.raises(BlockPoolExhausted):
        pool.alloc(free_before + 1)
    assert pool.num_free == free_before


# ---------------------------------------------------------- prefix cache
def test_prefix_cache_match_insert_evict():
    pool = BlockPool(num_blocks=16, block_size=4)
    cache = PrefixCache(pool)
    toks = list(range(10))             # 2 full blocks + remainder
    blocks = pool.alloc(3)
    assert cache.insert(toks, blocks) == 2
    assert pool.refcount(blocks[0]) == 2
    m, n = cache.match(toks)
    assert m == blocks[:2] and n == 8
    assert pool.refcount(blocks[0]) == 3
    pool.free(m)
    # exactly-two-full-blocks prompt: the last token stays suffix
    m, n = cache.match(toks[:8])
    assert m == blocks[:1] and n == 4
    pool.free(m)
    # different history, same block tokens -> no chain match
    m, n = cache.match([5] * 12)
    assert m == [] and n == 0
    # eviction frees only cache-held blocks
    pool.free(blocks)                  # request lets go
    assert cache.evict(5) == 2
    assert len(cache) == 0 and pool.num_free == pool.capacity


# ------------------------------------------- equivalence with generate()
def test_continuous_batching_matches_sequential_generate():
    net = _tiny()
    rs = np.random.RandomState(3)
    prompts = [list(rs.randint(0, VOCAB, n)) for n in (4, 9, 6, 13)]
    refs = [_gen_ref(net, p, 11) for p in prompts]
    eng = ServingEngine(net, max_batch=2, block_size=4,
                        num_blocks=64)
    reqs = [eng.submit(p, 11) for p in prompts]
    out = eng.run()
    for req, ref in zip(reqs, refs):
        assert req.state == FINISHED
        assert [int(t) for t in req.tokens] == ref
        assert out[req.id] == req.tokens
    # drained engine returns every request block to the pool (the
    # prefix cache may retain some, refcounted to itself only)
    assert all(r.block_ids == [] for r in reqs)


def test_rope_gqa_model_matches_generate():
    # the 'modern' layer stack: rotary positions + grouped-query kv
    net = _tiny(pos="rope", n_kv_heads=2)
    rs = np.random.RandomState(5)
    prompts = [list(rs.randint(0, VOCAB, n)) for n in (5, 8)]
    refs = [_gen_ref(net, p, 9) for p in prompts]
    eng = ServingEngine(net, max_batch=2, block_size=4,
                        num_blocks=64)
    reqs = [eng.submit(p, 9) for p in prompts]
    eng.run()
    for req, ref in zip(reqs, refs):
        assert [int(t) for t in req.tokens] == ref


def test_eos_stops_early_and_frees_blocks():
    net = _tiny()
    rs = np.random.RandomState(7)
    prompt = list(rs.randint(0, VOCAB, 6))
    ref = _gen_ref(net, prompt, 12)
    eos = ref[len(prompt) + 3]         # stop at the 4th new token
    eng = ServingEngine(net, max_batch=1, block_size=4,
                        num_blocks=32, prefix_cache=False)
    req = eng.submit(prompt, 12, eos_id=eos)
    eng.run()
    assert req.state == FINISHED
    assert req.generated[-1] == eos
    assert len(req.generated) <= 12
    assert eng.pool.num_allocated == 0


# --------------------------------------------------- trace-count guards
def test_admission_retirement_never_retrace():
    net = _tiny()
    rs = np.random.RandomState(11)
    # same pow2 prefill bucket (5..8 tokens) across all requests
    prompts = [list(rs.randint(0, VOCAB, n))
               for n in (5, 6, 7, 8, 5, 6)]
    eng = ServingEngine(net, max_batch=2, block_size=4,
                        num_blocks=64, prefix_cache=False)
    for p in prompts[:4]:
        eng.submit(p, 7)
    eng.run()
    assert eng.trace_counts == {"prefill_8": 1, "decode": 1}
    # a second wave (new admissions + retirements) replays both
    for p in prompts[4:]:
        eng.submit(p, 5)
    eng.run()
    assert eng.trace_counts == {"prefill_8": 1, "decode": 1}


# -------------------------------------------------------- prefix caching
def test_prefix_cache_reuse_is_copy_free_and_exact():
    net = _tiny()
    rs = np.random.RandomState(13)
    system = list(rs.randint(0, VOCAB, 12))    # 3 full blocks @ bs=4
    prompts = [system + list(rs.randint(0, VOCAB, n))
               for n in (3, 6, 2)]
    refs = [_gen_ref(net, p, 8) for p in prompts]

    hits0 = _counter("serving_prefix_cache_hits_total")
    eng = ServingEngine(net, max_batch=1, block_size=4,
                        num_blocks=64)
    reqs = []
    for p in prompts:                 # sequential: warm, then reuse
        r = eng.submit(p, 8)
        eng.run()
        reqs.append(r)
    for req, ref in zip(reqs, refs):
        assert [int(t) for t in req.tokens] == ref
    assert _counter("serving_prefix_cache_hits_total") - hits0 >= 24
    # copy-free: later requests adopted the SAME block ids
    assert len(eng.cache) >= 3
    # disabled-cache engine produces identical tokens (correctness
    # does not depend on sharing)
    eng2 = ServingEngine(net, max_batch=1, block_size=4,
                         num_blocks=64, prefix_cache=False)
    r2 = eng2.submit(prompts[1], 8)
    eng2.run()
    assert [int(t) for t in r2.tokens] == refs[1]


def test_prefix_cache_blocks_shared_between_live_requests():
    net = _tiny()
    rs = np.random.RandomState(17)
    system = list(rs.randint(0, VOCAB, 8))     # 2 full blocks @ bs=4
    eng = ServingEngine(net, max_batch=2, block_size=4,
                        num_blocks=64)
    r1 = eng.submit(system + [1, 2, 3], 4)
    eng.step()                                 # admit + first token
    r2 = eng.submit(system + [4, 5], 4)
    eng.step()
    shared = set(r1.block_ids[:2]) & set(r2.block_ids[:2])
    assert len(shared) == 2                    # same physical blocks
    for b in shared:
        assert eng.pool.refcount(b) >= 3       # r1 + r2 + cache
    eng.run()
    refs = [_gen_ref(net, r.prompt, 4) for r in (r1, r2)]
    assert [int(t) for t in r1.tokens] == refs[0]
    assert [int(t) for t in r2.tokens] == refs[1]


# ------------------------------------------------ preemption + requeue
def test_pool_exhaustion_preempts_and_requeues():
    net = _tiny()
    rs = np.random.RandomState(19)
    prompts = [list(rs.randint(0, VOCAB, n)) for n in (9, 10)]
    refs = [_gen_ref(net, p, 14) for p in prompts]
    pre0 = _counter("serving_preemptions_total")
    # pool too small for both full sequences -> one must be preempted
    eng = ServingEngine(net, max_batch=2, block_size=4,
                        num_blocks=12, prefix_cache=False)
    reqs = [eng.submit(p, 14) for p in prompts]
    eng.run()
    assert _counter("serving_preemptions_total") - pre0 >= 1
    assert sum(r.preemptions for r in reqs) >= 1
    for req, ref in zip(reqs, refs):
        assert req.state == FINISHED
        assert [int(t) for t in req.tokens] == ref
    assert eng.pool.num_allocated == 0         # no leaked blocks
    assert eng.pool.utilization() == 0.0


def test_single_request_too_big_for_pool_raises():
    net = _tiny()
    eng = ServingEngine(net, max_batch=1, block_size=4,
                        num_blocks=4, prefix_cache=False)
    with pytest.raises(ValueError, match="needs .* blocks"):
        eng.submit(list(range(10)), 8)


# ------------------------------------------------------ fault injection
def test_fault_evicts_request_without_killing_batchmates(monkeypatch):
    net = _tiny()
    rs = np.random.RandomState(23)
    prompts = [list(rs.randint(0, VOCAB, n)) for n in (6, 7, 8)]
    refs = [_gen_ref(net, p, 8) for p in prompts]
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "serve:request:2:error")
    resilience.reset_faults()
    try:
        eng = ServingEngine(net, max_batch=3, block_size=4,
                            num_blocks=64, prefix_cache=False)
        reqs = [eng.submit(p, 8) for p in prompts]
        out = eng.run()
        # run() reports ALL drained requests, failed ones included
        assert set(out) == {r.id for r in reqs}
    finally:
        monkeypatch.setenv("MXTPU_FAULT_SPEC", "")
        resilience.reset_faults()
    assert [r.state for r in reqs] == [FINISHED, FAILED, FINISHED]
    assert isinstance(reqs[1].error, resilience.TransientError)
    # batchmates' outputs are exactly the sequential references
    assert [int(t) for t in reqs[0].tokens] == refs[0]
    assert [int(t) for t in reqs[2].tokens] == refs[2]
    assert eng.pool.num_allocated == 0


# -------------------------------------------------------- quantization
def test_int8_quantization_density_and_logit_tolerance():
    net = _tiny()
    net(mx.nd.array(np.zeros((1, 2), "int32")))   # settle deferred
    wts = net._decode_weights()
    qwts = quantize_weights(wts)
    assert weights_nbytes(qwts) < 0.5 * weights_nbytes(wts)
    assert quantization_error(wts, qwts) <= 1 / 127 + 1e-6
    rs = np.random.RandomState(29)
    prompt = list(rs.randint(0, VOCAB, 9))
    engs = {}
    for mode in ("off", "int8"):
        eng = ServingEngine(net, max_batch=1, block_size=4,
                            num_blocks=64, quantize=mode,
                            keep_logits=True)
        # max_new=1: compare logits over the SAME context (longer
        # greedy runs may diverge at near-ties, by design)
        engs[mode] = eng.submit(prompt, 1)
        eng.run()
    lf = np.asarray(engs["off"].logits)
    lq = np.asarray(engs["int8"].logits)
    scale = np.abs(lf).max()
    assert np.abs(lq - lf).max() <= 0.05 * scale
    with pytest.raises(ValueError, match="quantize"):
        ServingEngine(net, quantize="int4")


# ------------------------------------------------------- API validation
def test_submit_validation_and_stream():
    net = _tiny()
    eng = ServingEngine(net, max_batch=2, block_size=4,
                        num_blocks=64)
    with pytest.raises(ValueError, match="empty"):
        eng.submit([], 4)
    with pytest.raises(ValueError, match="max_new_tokens"):
        eng.submit([1, 2], 0)
    with pytest.raises(ValueError, match="max_len"):
        eng.submit(list(range(60)), 20)
    req = eng.submit([1, 2, 3], 5)
    events = list(eng.stream())
    assert [t for r, t in events if r is req] == \
        [int(t) for t in req.generated]
    assert len(req.generated) == 5
    assert not eng.has_work()


def test_engine_rejects_unsupported_models():
    win = _tiny(attn_window=4)
    with pytest.raises(NotImplementedError, match="window"):
        ServingEngine(win, max_batch=1, num_blocks=16)
    with pytest.raises(TypeError, match="TransformerLM"):
        ServingEngine(object(), max_batch=1)
    # MoE: shared expert capacity makes logits depend on batchmates,
    # which would break the greedy generate() equivalence contract
    moe = _tiny(moe_experts=2)
    with pytest.raises(NotImplementedError, match="MoE"):
        ServingEngine(moe, max_batch=2, num_blocks=16)


def test_gauges_and_occupancy_reported():
    net = _tiny()
    eng = ServingEngine(net, max_batch=2, block_size=4,
                        num_blocks=32, prefix_cache=False)
    eng.submit([1, 2, 3, 4, 5], 6)
    eng.step()
    reg = telemetry.get_registry()
    assert reg.gauge("serving_batch_occupancy").value == 0.5
    util = reg.gauge("serving_block_pool_utilization").value
    assert 0.0 < util < 1.0
    eng.run()
    assert reg.gauge("serving_batch_occupancy").value == 0.0


# ------------------------------------------------------ predictor.serve
def test_predictor_serve_over_exported_artifact(tmp_path):
    from incubator_mxnet_tpu import predictor
    net = _tiny()
    rs = np.random.RandomState(31)
    prompt = list(rs.randint(0, VOCAB, 7))
    ref = _gen_ref(net, prompt, 6)
    f = str(tmp_path / "lm.params")
    net.collect_params().save(f)
    # a FRESH instance (different auto name-scope prefix)
    fresh = _tiny()
    eng = predictor.serve(f, fresh, max_batch=2, block_size=4,
                          num_blocks=64)
    req = eng.submit(prompt, 6)
    eng.run()
    assert [int(t) for t in req.tokens] == ref


def test_predictor_instance_serve_method(tmp_path):
    from incubator_mxnet_tpu import predictor, sym
    net = _tiny()
    net(mx.nd.array(np.zeros((1, 2), "int32")))   # settle deferred
    f = str(tmp_path / "lm.params")
    net.collect_params().save(f)
    # a Predictor constructed over the LM artifact (any symbol —
    # here a passthrough; extra params are allowed) exposes .serve
    p = predictor.Predictor(sym.Variable("data"), f,
                            {"data": (1, 4)})
    eng = p.serve(_tiny(), max_batch=1, block_size=4,
                  num_blocks=64)
    rs = np.random.RandomState(37)
    prompt = list(rs.randint(0, VOCAB, 5))
    req = eng.submit(prompt, 4)
    eng.run()
    assert [int(t) for t in req.tokens] == _gen_ref(net, prompt, 4)


# -------------------------------------------- executor partial batches
def test_partial_last_batch_padded_and_sliced():
    from incubator_mxnet_tpu import sym
    data = sym.Variable("data")
    r = sym.Reshape(data, shape=(8, 24))
    net = sym.FullyConnected(r, num_hidden=4, name="fc")
    exe = net.simple_bind(mx.cpu(), grad_req="null", data=(8, 6, 4))
    rs = np.random.RandomState(0)
    for n, a in exe.arg_dict.items():
        if n != "data":
            a[:] = mx.nd.array(rs.rand(*a.shape).astype("float32"))
    x = np.random.RandomState(1).rand(8, 6, 4).astype("float32")
    full = exe.forward(data=x)[0].asnumpy()
    part = exe.forward(data=x[:3])[0].asnumpy()
    assert part.shape == (3, 4)
    np.testing.assert_array_equal(part, full[:3])
    # oversize batches still fail loudly (only PARTIAL pads)
    with pytest.raises(Exception):
        exe.forward(data=np.zeros((9, 6, 4), "float32"))


def test_partial_batch_never_pads_batch_reducing_outputs():
    # a graph whose output reduces over the batch axis must NOT see
    # padded rows — padding would silently corrupt the mean; the
    # old exact-shape behavior (recompile at the true shape) stays
    from incubator_mxnet_tpu import sym
    data = sym.Variable("data")
    loss = sym.mean(data, axis=(), keepdims=False) \
        if hasattr(sym, "mean") else None
    if loss is None:
        pytest.skip("no sym.mean")
    exe = loss.simple_bind(mx.cpu(), grad_req="null", data=(8, 4))
    x = np.full((5, 4), 3.0, np.float32)
    out = float(exe.forward(data=x)[0].asnumpy())
    assert out == pytest.approx(3.0)   # padded zeros would give 1.875


# ------------------------------------------------------------ lint rules
def _load_lint():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "lint", os.path.join(REPO, "ci", "lint.py"))
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    return lint


def test_lint_covers_serving_queue_and_sync_rules(tmp_path):
    lint = _load_lint()
    d = tmp_path / "incubator_mxnet_tpu" / "serving"
    d.mkdir(parents=True)
    f = d / "x.py"
    # unbounded queue.get in serving/ is flagged
    f.write_text("import queue\nq = queue.Queue()\nv = q.get()\n")
    assert any("unbounded queue .get()" in p
               for p in lint.check_file(f))
    # unannotated host sync in a scheduler-loop function is flagged
    eng = d / "engine.py"
    eng.write_text(
        "import numpy as np\n\n\n"
        "class E:\n"
        "    def _decode_once(self, nxt):\n"
        "        return np.asarray(nxt)\n")
    assert any("host sync" in p for p in lint.check_file(eng))
    eng.write_text(
        "import numpy as np\n\n\n"
        "class E:\n"
        "    def _decode_once(self, nxt):\n"
        "        return np.asarray(nxt)  # sync-ok: token read\n")
    assert not any("host sync" in p for p in lint.check_file(eng))
