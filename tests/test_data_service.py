"""Sharded multi-process input data service (docs/data_service.md):
shard-assignment exactly-once coverage, deterministic-mode bit-identity
vs the single-process ImageRecordIter, exact mid-epoch resume across
the process frontier, SIGKILL-worker supervision under the restart
budget, globally-aggregated corrupt-record quarantine, the device
prefetch depth knob, launch.py export, and the new lint rules."""
import io as _pyio
import os
import pickle
import signal
import time
import warnings

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import recordio as rio
from incubator_mxnet_tpu import resilience as rz
from incubator_mxnet_tpu import telemetry, tracing
from incubator_mxnet_tpu.data_service import DataServiceIter
from incubator_mxnet_tpu.io.sharding import (assigned_batches,
                                             reshard_batch_cursors,
                                             shard_keys, shard_range)
from incubator_mxnet_tpu.resilience import DataPipelineError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SHAPE = (3, 48, 48)
B = 8


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("MXTPU_FAULT_SPEC", raising=False)
    rz.reset_faults()
    yield
    rz.reset_faults()


def _make_jpeg_rec(prefix, n, edge=64, bad=()):
    """n labeled JPEG records (garbage payloads at ``bad`` indices)."""
    from PIL import Image
    rec = rio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    rs = np.random.RandomState(3)
    for i in range(n):
        if i in bad:
            payload = b"\x00not-an-image" + bytes(rs.randint(
                0, 256, 64, dtype=np.uint8))
        else:
            gx = np.linspace(0, 255, edge, dtype=np.float32)
            img = (gx[None, :, None] * 0.4 + gx[:, None, None] * 0.4
                   + rs.rand(edge, edge, 3) * 50).astype(np.uint8)
            buf = _pyio.BytesIO()
            Image.fromarray(img).save(buf, format="JPEG", quality=85)
            payload = buf.getvalue()
        rec.write_idx(i, rio.pack(
            rio.IRHeader(0, float(i % 7), i, 0), payload))
    rec.close()
    return prefix


@pytest.fixture(scope="module")
def rec48(tmp_path_factory):
    """48 records: divides evenly into 6 batches of 8."""
    td = tmp_path_factory.mktemp("ds48")
    return _make_jpeg_rec(str(td / "ds"), 48)


@pytest.fixture(scope="module")
def rec44(tmp_path_factory):
    """44 records: partial tail batch (pad 4 under round_batch)."""
    td = tmp_path_factory.mktemp("ds44")
    return _make_jpeg_rec(str(td / "ds"), 44)


def _single(prefix, **kw):
    kw.setdefault("preprocess_threads", 2)
    kw.setdefault("shuffle", False)
    return mx.io.ImageRecordIter(
        path_imgrec=prefix + ".rec", data_shape=SHAPE, batch_size=B,
        round_batch=True, **kw)


def _service(prefix, W, **kw):
    kw.setdefault("preprocess_threads", 2)
    return DataServiceIter(
        path_imgrec=prefix + ".rec", data_shape=SHAPE, batch_size=B,
        num_workers=W, round_batch=True, **kw)


def _np_batches(it):
    out = []
    for b in it:
        out.append((b.data[0].asnumpy().copy(),
                    b.label[0].asnumpy().copy(), b.pad))
    return out


def _assert_same(got, ref, what=""):
    assert len(got) == len(ref), (what, len(got), len(ref))
    for i, ((d, l, p), (rd, rl, rp)) in enumerate(zip(got, ref)):
        assert p == rp, (what, i, p, rp)
        assert np.array_equal(d, rd), f"{what}: batch {i} data differs"
        assert np.array_equal(l, rl), f"{what}: batch {i} label differs"


def _shm_orphans():
    return [f for f in os.listdir("/dev/shm")
            if f.startswith("mxtpu_ds")]


# ------------------------------------------------ sharding contracts
@pytest.mark.parametrize("n,parts", [(48, 1), (48, 3), (44, 3),
                                     (10, 3), (7, 8), (1, 4), (0, 2)])
def test_shard_range_union_disjoint_exact(n, parts):
    seen = []
    prev_stop = 0
    for k in range(parts):
        start, stop = shard_range(n, parts, k)
        assert start == prev_stop          # adjacent edges touch
        assert start <= stop
        seen.extend(range(start, stop))
        prev_stop = stop
    assert prev_stop == n
    assert seen == list(range(n))          # union exact, no overlap


def test_shard_range_balanced():
    # floor arithmetic: part sizes differ by at most one (the naive
    # n//P*k chunking loses up to P-1 tail records)
    sizes = [shard_range(44, 3, k)[1] - shard_range(44, 3, k)[0]
             for k in range(3)]
    assert sum(sizes) == 44
    assert max(sizes) - min(sizes) <= 1


def test_shard_range_errors():
    with pytest.raises(ValueError):
        shard_range(10, 0, 0)
    with pytest.raises(ValueError):
        shard_range(10, 2, 2)
    with pytest.raises(ValueError):
        shard_range(10, 2, -1)


@pytest.mark.parametrize("nb,W", [(6, 1), (6, 2), (7, 3), (2, 4)])
def test_assigned_batches_union_disjoint(nb, W):
    all_batches = []
    for w in range(W):
        mine = assigned_batches(nb, W, w)
        assert mine == list(range(w, nb, W))
        all_batches.extend(mine)
    assert sorted(all_batches) == list(range(nb))


def test_image_record_iter_parts_cover_exactly_once(rec44):
    """num_parts/part_index cuts on record boundaries via the .idx
    and covers every record exactly once (the off-by-one guard the
    service's contiguous sharding reuses)."""
    ids = []
    for k in range(3):
        it = _single(rec44, num_parts=3, part_index=k)
        ids.append(list(it._keys))
        # each part also delivers exactly its keys' worth of images
        assert sum(B - b.pad for b in it) == len(ids[-1])
    flat = [k for part in ids for k in part]
    assert sorted(flat) == sorted(set(flat))          # disjoint
    full = _single(rec44)
    assert sorted(flat) == sorted(full._keys)         # union == all


# -------------------------------------------------- bit-identity
@pytest.mark.parametrize("W", [1, 2, 3])
def test_service_bit_identical_to_single_process(rec48, W):
    ref = _np_batches(_single(rec48))
    with _service(rec48, W) as svc:
        _assert_same(_np_batches(svc), ref, f"W={W}")
    assert not _shm_orphans()


def test_service_partial_tail_round_batch(rec44):
    ref = _np_batches(_single(rec44))
    assert ref[-1][2] == 4                      # pad under round_batch
    with _service(rec44, 2) as svc:
        _assert_same(_np_batches(svc), ref, "tail pad")


def test_service_second_epoch_clean_turnover(rec48):
    ref = _np_batches(_single(rec48))
    with _service(rec48, 2) as svc:
        _np_batches(svc)
        procs = [p.pid for p in svc._procs]
        svc.reset()                             # persistent workers:
        _assert_same(_np_batches(svc), ref, "epoch 2")
        assert [p.pid for p in svc._procs] == procs   # no respawn


def test_service_midepoch_reset_restarts_epoch(rec48):
    ref = _np_batches(_single(rec48))
    with _service(rec48, 2) as svc:
        for _ in range(2):
            svc.next()
        svc.reset()                             # mid-epoch abandon
        _assert_same(_np_batches(svc), ref, "after abandon")


def test_service_shuffled_epoch_matches_single_process(rec48):
    np.random.seed(23)
    ref = _np_batches(_single(rec48, shuffle=True))
    np.random.seed(23)
    with _service(rec48, 2, shuffle=True) as svc:
        _assert_same(_np_batches(svc), ref, "shuffled")


# ---------------------------------------------------------- resume
@pytest.mark.parametrize("W", [1, 2])
def test_service_state_roundtrip_resumes_exact_batch(rec48, W):
    with _service(rec48, W) as svc:
        for _ in range(3):
            svc.next()
        state = pickle.loads(pickle.dumps(svc.state_dict()))
        want = _np_batches(svc)
    with _service(rec48, W) as svc2:
        svc2.load_state_dict(state)
        svc2.reset()     # fit()'s epoch-start reset must not rewind
        _assert_same(_np_batches(svc2), want, f"resume W={W}")


def test_service_skip_matches_consumption(rec48):
    with _service(rec48, 2) as svc:
        for _ in range(2):
            svc.next()
        want = _np_batches(svc)
    with _service(rec48, 2) as svc2:
        svc2.skip(2)
        _assert_same(_np_batches(svc2), want, "skip")


def test_service_data_companion_roundtrip(rec48, tmp_path):
    """The .data checkpoint companion path (model.save_data_state)
    carries the multi-process position unchanged."""
    from incubator_mxnet_tpu import model as M
    prefix = str(tmp_path / "ckpt")
    with _service(rec48, 2) as svc:
        for _ in range(4):
            svc.next()
        M.save_data_state(prefix, 3, svc)
        want = _np_batches(svc)
    with _service(rec48, 2) as svc2:
        assert M.load_data_state(prefix, 3, svc2)
        svc2.reset()
        _assert_same(_np_batches(svc2), want, "companion")


def test_reshard_batch_cursors_exactly_once():
    """For every (num_batches, position, shard count): the union of
    remaining per-shard assignments is exactly [position,
    num_batches), each batch once — the elastic data-plane
    contract."""
    for nb in (0, 1, 5, 6, 7, 12):
        for W in (1, 2, 3, 5, 8):
            for g in range(nb + 2):
                delivered, done = reshard_batch_cursors(nb, g, W)
                remaining = []
                for w in range(W):
                    assigned = assigned_batches(nb, W, w)
                    assert delivered[w] <= len(assigned)
                    remaining += assigned[delivered[w]:]
                    assert done[w] == (delivered[w] >= len(assigned))
                assert sorted(remaining) == list(range(min(g, nb),
                                                       nb)), \
                    (nb, g, W)


@pytest.mark.parametrize("W_new", [1, 3, 4])
def test_service_resume_reshards_worker_count(rec48, W_new):
    """Elastic data plane (docs/elastic.md): a position saved with
    W workers resumes bit-consistently under W′ — the remaining
    stream equals the uninterrupted same-W stream exactly."""
    with _service(rec48, 2) as svc:
        for _ in range(3):
            svc.next()
        state = pickle.loads(pickle.dumps(svc.state_dict()))
        want = _np_batches(svc)
    with _service(rec48, W_new) as svc2:
        svc2.load_state_dict(state)
        svc2.reset()     # fit()'s epoch-start reset must not rewind
        _assert_same(_np_batches(svc2), want,
                     f"reshard 2->{W_new}")


def test_service_reshard_partial_tail_and_epoch_end(rec44):
    """Reshard with a padded tail batch, and at exact epoch end."""
    with _service(rec44, 2) as svc:
        got = _np_batches(svc)          # full epoch (6 batches)
        # mid-epoch position past the middle
        svc.reset()
        for _ in range(4):
            svc.next()
        state = svc.state_dict()
        end_state = None
        for _ in range(2):
            svc.next()
        end_state = svc.state_dict()    # epoch fully consumed
    with _service(rec44, 3) as svc2:
        svc2.load_state_dict(state)
        _assert_same(_np_batches(svc2), got[4:], "reshard tail")
    with _service(rec44, 3) as svc3:
        svc3.load_state_dict(end_state)
        with pytest.raises(StopIteration):
            svc3.next()


def test_service_reshard_under_quarantine_replays_coherent(
        rec48, tmp_path, monkeypatch):
    """With quarantined records the saved cursors are entangled with
    the OLD shards' top-up reads (each worker tops a short batch up
    from its own later keys), so batch composition near a corrupt
    record depends on the worker count and cross-W bit-identity is
    impossible.  The documented contract (docs/elastic.md): the
    resharded resume replays and delivers exactly the W′ stream from
    the same global batch position — identical to a fresh W′ service
    fast-forwarded by skip(), no batch slot replayed or skipped."""
    monkeypatch.setenv("MXTPU_MAX_BAD_RECORDS", "16")
    prefix = _make_jpeg_rec(str(tmp_path / "bad"), 48, bad=(5, 17))
    with _service(prefix, 2) as svc:
        for _ in range(2):
            svc.next()
        state = pickle.loads(pickle.dumps(svc.state_dict()))
        assert state["bad_total"] > 0    # quarantine actually fired
    with _service(prefix, 3) as ref:
        ref.skip(2)
        want = _np_batches(ref)
    with _service(prefix, 3) as svc2:
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            svc2.load_state_dict(state)
            svc2.reset()
            got = _np_batches(svc2)
        _assert_same(got, want, "reshard replay")
        assert any("exact replay" in str(w.message) for w in caught)

    # double resize BEFORE the iterator is driven: a state saved
    # while the replay resume is still pending must carry its
    # position (pending_skip) into the next reshard, not restart
    # the epoch
    with _service(prefix, 3) as mid:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            mid.load_state_dict(state)
            pending = pickle.loads(pickle.dumps(mid.state_dict()))
    with _service(prefix, 4) as ref4:
        ref4.skip(2)
        want4 = _np_batches(ref4)
    with _service(prefix, 4) as svc4:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            svc4.load_state_dict(pending)
        _assert_same(_np_batches(svc4), want4, "double reshard")


def test_service_resume_wrong_dataset_raises(rec48, rec44):
    with _service(rec48, 2) as svc:
        state = svc.state_dict()
    with _service(rec44, 2) as svc2:
        with pytest.raises(ValueError, match="key set"):
            svc2.load_state_dict(state)


# ------------------------------------------------------ supervision
def test_service_sigkill_worker_recovers_bit_identical(
        rec48, monkeypatch):
    monkeypatch.setenv("MXTPU_DATA_WORKER_RESTARTS", "2")
    monkeypatch.setenv("MXTPU_DATA_TIMEOUT", "60")
    ref = _np_batches(_single(rec48))
    rec = tracing.get_recorder()
    # depth-1 rings: a worker can stage at most one undelivered batch,
    # so the SIGKILL below is guaranteed to interrupt the epoch
    # mid-stream (a deep ring could hold the whole tiny epoch already)
    with _service(rec48, 2, ring_depth=1) as svc:
        got = [svc.next()]
        os.kill(svc._procs[1].pid, signal.SIGKILL)
        t0 = time.monotonic()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            try:
                while True:
                    got.append(svc.next())
            except StopIteration:
                pass
        recovery_s = time.monotonic() - t0
        assert svc._restarts == 1
    got = [(b.data[0].asnumpy(), b.label[0].asnumpy(), b.pad)
           for b in got]
    _assert_same(got, ref, "after SIGKILL")
    # bounded recovery: within the data timeout, not hanging forever
    assert recovery_s < 60
    assert rec.events("data_service_worker_dead")
    assert rec.events("data_service_worker_restart")
    assert not _shm_orphans()


def test_service_restart_budget_exhausted_raises_typed(
        rec48, monkeypatch):
    monkeypatch.setenv("MXTPU_DATA_WORKER_RESTARTS", "0")
    monkeypatch.setenv("MXTPU_DATA_TIMEOUT", "60")
    with _service(rec48, 2, ring_depth=1) as svc:
        svc.next()
        os.kill(svc._procs[0].pid, signal.SIGKILL)
        with pytest.raises(DataPipelineError, match="restart budget"):
            while True:
                svc.next()
    assert not _shm_orphans()


def test_service_worker_fault_injection_surfaces_typed(
        rec48, monkeypatch):
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "data_service:worker:1:error")
    with _service(rec48, 2) as svc:
        with pytest.raises(DataPipelineError):
            while True:
                svc.next()


def test_service_ring_fault_injection_surfaces(rec48, monkeypatch):
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "data_service:ring:1:error")
    with _service(rec48, 2) as svc:
        with pytest.raises(rz.TransientError):
            svc.next()


# ------------------------------------------------------- quarantine
def test_service_quarantine_within_budget_tops_up(
        tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_MAX_BAD_RECORDS", "4")
    prefix = _make_jpeg_rec(str(tmp_path / "bad"), 48, bad={5, 19})
    ref = _np_batches(_single(prefix))        # single-process path
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with _service(prefix, 2) as svc:
            got = _np_batches(svc)
    # same surviving images, full batches topped up from the stream
    assert svc._bad_total == 2
    total_ref = sum(B - p for _, _, p in ref)
    total_got = sum(B - p for _, _, p in got)
    assert total_got == total_ref == 46


def test_service_quarantine_budget_zero_default_raises(
        tmp_path, monkeypatch):
    monkeypatch.delenv("MXTPU_MAX_BAD_RECORDS", raising=False)
    prefix = _make_jpeg_rec(str(tmp_path / "bad0"), 24, bad={2})
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with _service(prefix, 2) as svc:
            with pytest.raises(DataPipelineError,
                               match="MXTPU_MAX_BAD_RECORDS"):
                _np_batches(svc)


def test_service_quarantine_aggregates_across_shards(
        tmp_path, monkeypatch):
    # one bad record per shard; budget 1 < 2 aggregate -> typed fail
    monkeypatch.setenv("MXTPU_MAX_BAD_RECORDS", "1")
    prefix = _make_jpeg_rec(str(tmp_path / "bad2"), 32, bad={1, 9})
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with _service(prefix, 2) as svc:
            with pytest.raises(DataPipelineError, match="2 corrupt"):
                _np_batches(svc)


# -------------------------------------------- device prefetch depth
def test_device_prefetch_depth_env_knob(monkeypatch):
    from incubator_mxnet_tpu.io.io import (DevicePrefetchIter,
                                           NDArrayIter)
    monkeypatch.setenv("MXTPU_DEVICE_PREFETCH_DEPTH", "5")
    inner = NDArrayIter(np.zeros((16, 2), np.float32), batch_size=4)
    it = DevicePrefetchIter(inner, ctx=mx.cpu(0))
    assert it._depth == 5
    assert it._queue.maxsize == 5
    it2 = DevicePrefetchIter(
        NDArrayIter(np.zeros((16, 2), np.float32), batch_size=4),
        ctx=mx.cpu(0), depth=3)
    assert it2._depth == 3                     # explicit arg wins
    assert sum(1 for _ in it) == 4


def test_device_prefetch_depth_bounds_staging():
    """A fast producer is staged at most depth+1 batches ahead
    (depth queued + one in flight): bounded memory by construction."""
    from incubator_mxnet_tpu.io.io import DevicePrefetchIter, NDArrayIter

    class Counting(NDArrayIter):
        pulled = 0

        def next(self):
            b = super().next()
            type(self).pulled += 1
            return b

    inner = Counting(np.zeros((400, 2), np.float32), batch_size=4)
    it = DevicePrefetchIter(inner, ctx=mx.cpu(0), depth=2)
    deadline = time.monotonic() + 10
    while Counting.pulled < 3 and time.monotonic() < deadline:
        time.sleep(0.01)                 # let the stage fill
    time.sleep(0.3)                      # would overrun if unbounded
    assert Counting.pulled <= 2 + 1
    n = sum(1 for _ in it)
    assert n == 100


def test_device_prefetch_over_service_end_to_end(rec48):
    from incubator_mxnet_tpu.io.io import DevicePrefetchIter
    ref = _np_batches(_single(rec48))
    with _service(rec48, 2) as svc:
        pre = DevicePrefetchIter(svc, ctx=mx.cpu(0), depth=3)
        got = _np_batches(pre)
    _assert_same(got, ref, "device prefetch over service")


# ------------------------------------------------- telemetry & stats
def test_service_stats_and_telemetry(rec48):
    with _service(rec48, 2) as svc:
        _np_batches(svc)
        st = svc.stats()
    assert st["img_per_sec"] > 0
    assert st["restarts"] == 0 and st["bad_records"] == 0
    assert set(st["shards"]) == {0, 1}
    for shard in st["shards"].values():
        assert shard["done"]
        assert shard["delivered"] > 0
    assert telemetry.counter("data_service_batches_total").value > 0


# ------------------------------------------------------ launch flag
def test_launch_data_workers_export():
    import argparse
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "launch", os.path.join(REPO, "tools", "launch.py"))
    launch = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(launch)
    args = argparse.Namespace(num_workers=2, env=[],
                              data_timeout=None, data_workers=4)
    env = launch._worker_env(args, 0, "127.0.0.1:1", 0)
    assert env["MXTPU_DATA_WORKERS"] == "4"
    args.data_workers = None
    env = launch._worker_env(args, 0, "127.0.0.1:1", 0)
    assert "MXTPU_DATA_WORKERS" not in env


# ------------------------------------------------------- lint rules
def _load_lint():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "lint", os.path.join(REPO, "ci", "lint.py"))
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    return lint


def test_lint_flags_unbounded_semaphore_acquire(tmp_path):
    lint = _load_lint()
    d = tmp_path / "incubator_mxnet_tpu" / "data_service"
    d.mkdir(parents=True)
    f = d / "x.py"
    f.write_text("def f(sem):\n    sem.acquire()\n")
    assert any("unbounded .acquire()" in p for p in lint.check_file(f))
    f.write_text("def f(sem):\n    sem.acquire(timeout=0.2)\n")
    assert not any("unbounded" in p for p in lint.check_file(f))
    f.write_text("def f(sem):\n"
                 "    sem.acquire()  # deadline-ok: startup only\n")
    assert not any("unbounded" in p for p in lint.check_file(f))


def test_lint_dynamic_metric_prefix_rule(tmp_path):
    lint = _load_lint()
    d = tmp_path / "incubator_mxnet_tpu"
    d.mkdir()
    f = d / "m.py"
    f.write_text("from incubator_mxnet_tpu.telemetry import gauge\n"
                 "w = 1\n"
                 "gauge('unheard_of_shard%d_rate' % w).set(1)\n")
    probs = lint.check_metric_catalog([f])
    assert any("no catalogued pattern" in p for p in probs)
    f.write_text("from incubator_mxnet_tpu.telemetry import gauge\n"
                 "w = 1\n"
                 "gauge('data_service_shard%d_img_per_sec' % w)\n")
    assert not lint.check_metric_catalog([f])


def test_lint_flags_acquire_without_finite_timeout(tmp_path):
    """acquire(True)/acquire(block=True)/wait(timeout=None) block
    exactly like the zero-arg forms and must be flagged; the
    non-blocking acquire(False) and any finite timeout are exempt."""
    lint = _load_lint()
    d = tmp_path / "incubator_mxnet_tpu" / "data_service"
    d.mkdir(parents=True)
    f = d / "x.py"
    for bad in ("sem.acquire(True)", "sem.acquire(block=True)",
                "sem.wait(timeout=None)"):
        f.write_text(f"def f(sem):\n    {bad}\n")
        assert any("unbounded" in p for p in lint.check_file(f)), bad
    for ok in ("sem.acquire(False)", "sem.acquire(True, 0.2)",
               "sem.acquire(timeout=0.2)", "sem.wait(0.5)",
               "sem.wait(timeout=1.0)"):
        f.write_text(f"def f(sem):\n    {ok}\n")
        assert not any("unbounded" in p
                       for p in lint.check_file(f)), ok


def test_service_rand_mirror_resume_and_restart_exact(
        rec48, monkeypatch):
    """Mirror draws are keyed to the GLOBAL batch index and the seed
    base rides the state_dict: mid-epoch resume and dead-worker
    respawn reproduce the exact per-batch mirror pattern the
    uninterrupted epoch used (native path)."""
    monkeypatch.setenv("MXTPU_DATA_WORKER_RESTARTS", "2")
    np.random.seed(11)
    with _service(rec48, 2, rand_mirror=True) as svc:
        ref = _np_batches(svc)
    np.random.seed(11)
    with _service(rec48, 2, rand_mirror=True) as svc:
        _assert_same(_np_batches(svc), ref, "mirror determinism")
    # mid-epoch checkpoint + resume in a FRESH service (whose own
    # seed base differs) lands on the same remaining batches
    np.random.seed(11)
    head = []
    with _service(rec48, 2, rand_mirror=True) as svc:
        for _ in range(3):
            b = svc.next()
            head.append((b.data[0].asnumpy().copy(),
                         b.label[0].asnumpy().copy(), b.pad))
        state = pickle.loads(pickle.dumps(svc.state_dict()))
    with _service(rec48, 2, rand_mirror=True) as svc2:
        svc2.load_state_dict(state)
        svc2.reset()
        _assert_same(head + _np_batches(svc2), ref,
                     "rand_mirror resume")
    # a SIGKILLed worker respawns mid-epoch with the same draws
    np.random.seed(11)
    with _service(rec48, 2, rand_mirror=True, ring_depth=1) as svc:
        got = [svc.next()]
        os.kill(svc._procs[1].pid, signal.SIGKILL)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            try:
                while True:
                    got.append(svc.next())
            except StopIteration:
                pass
        assert svc._restarts == 1
    got = [(b.data[0].asnumpy(), b.label[0].asnumpy(), b.pad)
           for b in got]
    _assert_same(got, ref, "rand_mirror after SIGKILL")


def test_ring_put_error_oversized_payload_stays_typed():
    """A worker exception whose pickle exceeds the slot's data area
    must arrive as a typed, truncated summary — not a slot-cut
    pickle that unpickles to a bare UnpicklingError."""
    import multiprocessing as _mp
    from incubator_mxnet_tpu.data_service import ring as _ring
    r = _ring.ShmBatchRing(1, (1, 8, 8), 1, 1,
                           _mp.get_context("fork"))
    try:
        assert r.put_error(ValueError("boom " * 50000))
        kind, _, _, _, _, _, payload = r.get("t", lambda: True, 5)
        assert kind == _ring.KIND_ERROR
        assert isinstance(payload, DataPipelineError)
        assert "ValueError" in str(payload)
    finally:
        r.close()


@pytest.fixture(scope="module")
def rec_png24(tmp_path_factory):
    """24 PNG records: every batch fails the native JPEG gate, so
    the whole epoch decodes via the PIL-fallback path."""
    from PIL import Image
    td = tmp_path_factory.mktemp("dspng")
    prefix = str(td / "ds")
    rec = rio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    rs = np.random.RandomState(5)
    for i in range(24):
        img = rs.randint(0, 256, (64, 64, 3), dtype=np.uint8)
        buf = _pyio.BytesIO()
        Image.fromarray(img).save(buf, format="PNG")
        rec.write_idx(i, rio.pack(
            rio.IRHeader(0, float(i % 7), i, 0), buf.getvalue()))
    rec.close()
    return prefix


def test_service_rand_mirror_pil_path_resume_and_restart_exact(
        rec_png24, monkeypatch):
    """The PIL-fallback augmenters draw from the stdlib `random`
    module; the per-batch reseed must make THAT path bit-exact
    across respawn/resume too, not just the native mirror vector."""
    monkeypatch.setenv("MXTPU_DATA_WORKER_RESTARTS", "2")
    np.random.seed(13)
    with _service(rec_png24, 2, rand_mirror=True) as svc:
        ref = _np_batches(svc)
    np.random.seed(13)
    with _service(rec_png24, 2, rand_mirror=True) as svc:
        _assert_same(_np_batches(svc), ref, "pil mirror determinism")
    np.random.seed(13)
    head = []
    with _service(rec_png24, 2, rand_mirror=True) as svc:
        b = svc.next()
        head.append((b.data[0].asnumpy().copy(),
                     b.label[0].asnumpy().copy(), b.pad))
        state = pickle.loads(pickle.dumps(svc.state_dict()))
    with _service(rec_png24, 2, rand_mirror=True) as svc2:
        svc2.load_state_dict(state)
        svc2.reset()
        _assert_same(head + _np_batches(svc2), ref,
                     "pil rand_mirror resume")
    np.random.seed(13)
    with _service(rec_png24, 2, rand_mirror=True,
                  ring_depth=1) as svc:
        got = [svc.next()]
        os.kill(svc._procs[1].pid, signal.SIGKILL)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            try:
                while True:
                    got.append(svc.next())
            except StopIteration:
                pass
        assert svc._restarts == 1
    got = [(b.data[0].asnumpy(), b.label[0].asnumpy(), b.pad)
           for b in got]
    _assert_same(got, ref, "pil rand_mirror after SIGKILL")
