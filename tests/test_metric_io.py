"""Metric + io tests (ref: tests/python/unittest/test_metric.py,
test_io.py)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, metric


def test_accuracy():
    m = metric.create("acc")
    pred = nd.array(np.array([[0.9, 0.1], [0.2, 0.8], [0.6, 0.4]],
                             "float32"))
    label = nd.array(np.array([0, 1, 1], "float32"))
    m.update([label], [pred])
    assert abs(m.get()[1] - 2.0 / 3) < 1e-6


def test_topk():
    m = metric.create("top_k_accuracy", top_k=2)
    pred = nd.array(np.array([[0.5, 0.3, 0.2], [0.1, 0.2, 0.7]],
                             "float32"))
    label = nd.array(np.array([1, 0], "float32"))
    m.update([label], [pred])
    assert abs(m.get()[1] - 0.5) < 1e-6


def test_mse_mae_rmse():
    pred = nd.array(np.array([[1.0], [2.0]], "float32"))
    label = nd.array(np.array([[1.5], [1.0]], "float32"))
    for name, want in [("mse", (0.25 + 1.0) / 2),
                       ("mae", (0.5 + 1.0) / 2)]:
        m = metric.create(name)
        m.update([label], [pred])
        assert abs(m.get()[1] - want) < 1e-6


def test_perplexity_and_ce():
    pred = nd.array(np.array([[0.5, 0.5], [0.9, 0.1]], "float32"))
    label = nd.array(np.array([0, 0], "float32"))
    ce = metric.create("ce")
    ce.update([label], [pred])
    want = -(np.log(0.5) + np.log(0.9)) / 2
    assert abs(ce.get()[1] - want) < 1e-5
    p = metric.create("perplexity")
    p.update([label], [pred])
    assert abs(p.get()[1] - np.exp(want)) < 1e-4


def test_composite_and_custom():
    comp = metric.CompositeEvalMetric()
    comp.add("acc")
    comp.add(metric.np_metric(
        lambda l, p: float(np.abs(l - p.argmax(1)).mean()), "err"))
    pred = nd.array(np.array([[0.9, 0.1], [0.1, 0.9]], "float32"))
    label = nd.array(np.array([0, 1], "float32"))
    comp.update([label], [pred])
    names, values = comp.get()
    assert values[0] == 1.0 and values[1] == 0.0


def test_ndarray_iter_basics():
    data = np.arange(40, dtype="float32").reshape(10, 4)
    labels = np.arange(10, dtype="float32")
    it = mx.io.NDArrayIter(data, labels, batch_size=3,
                           last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 4
    assert batches[0].data[0].shape == (3, 4)
    assert batches[-1].pad == 2
    it.reset()
    again = list(it)
    np.testing.assert_allclose(again[0].data[0].asnumpy(),
                               batches[0].data[0].asnumpy())


def test_ndarray_iter_discard_shuffle():
    data = np.arange(22, dtype="float32").reshape(11, 2)
    it = mx.io.NDArrayIter(data, None, batch_size=4,
                           last_batch_handle="discard", shuffle=True)
    batches = list(it)
    assert len(batches) == 2
    assert it.provide_label == []


def test_csv_iter(tmp_path):
    f = tmp_path / "d.csv"
    np.savetxt(f, np.arange(12).reshape(4, 3), delimiter=",")
    lf = tmp_path / "l.csv"
    np.savetxt(lf, np.arange(4), delimiter=",")
    it = mx.io.CSVIter(data_csv=str(f), data_shape=(3,),
                       label_csv=str(lf), batch_size=2)
    b = next(iter(it))
    assert b.data[0].shape == (2, 3)
    assert b.label[0].shape == (2,)


def test_prefetching_iter():
    data = np.random.rand(20, 4).astype("float32")
    base = mx.io.NDArrayIter(data, np.zeros(20, "float32"), batch_size=5)
    pf = mx.io.PrefetchingIter(base)
    batches = []
    try:
        while True:
            batches.append(pf.next())
    except StopIteration:
        pass
    assert len(batches) == 4
    pf.reset()
    b = pf.next()
    assert b.data[0].shape == (5, 4)


def test_resize_iter():
    data = np.random.rand(10, 2).astype("float32")
    base = mx.io.NDArrayIter(data, None, batch_size=5)
    r = mx.io.ResizeIter(base, size=7)
    assert len(list(r)) == 7


def test_libsvm_iter(tmp_path):
    f = tmp_path / "d.svm"
    f.write_text("1 0:1.5 3:2.0\n0 1:1.0\n1 2:3.0 3:1.0\n")
    it = mx.io.LibSVMIter(data_libsvm=str(f), data_shape=(4,),
                          batch_size=3)
    b = it.next()
    arr = b.data[0].asnumpy()
    assert arr.shape == (3, 4)
    assert arr[0, 0] == 1.5 and arr[0, 3] == 2.0
    np.testing.assert_allclose(b.label[0].asnumpy(), [1, 0, 1])


def test_initializers():
    for init, check in [
        (mx.init.Zero(), lambda a: (a == 0).all()),
        (mx.init.One(), lambda a: (a == 1).all()),
        (mx.init.Constant(2.5), lambda a: (a == 2.5).all()),
        (mx.init.Uniform(0.1), lambda a: (np.abs(a) <= 0.1).all()),
        (mx.init.Normal(0.01), lambda a: np.abs(a).max() < 0.1),
        (mx.init.Xavier(), lambda a: a.std() > 0),
    ]:
        arr = nd.zeros((16, 16))
        init("test_weight", arr)
        assert check(arr.asnumpy()), type(init).__name__


def test_orthogonal_initializer():
    arr = nd.zeros((8, 8))
    mx.init.Orthogonal()("q_weight", arr)
    a = arr.asnumpy()
    prod = a @ a.T
    np.testing.assert_allclose(prod / prod[0, 0], np.eye(8), atol=1e-4)


def test_mixed_initializer():
    # sub-initializers still route by name suffix (reference
    # semantics: bias handling comes from Initializer.__call__)
    init = mx.init.Mixed(["bias$", ".*"],
                         [mx.init.Zero(), mx.init.Constant(3.0)])
    b = nd.ones((4,))
    w = nd.zeros((4,))
    init(mx.initializer.InitDesc("fc_bias"), b)
    init(mx.initializer.InitDesc("fc_weight"), w)
    assert (b.asnumpy() == 0).all() and (w.asnumpy() == 3).all()


def test_initializer_routes_by_suffix():
    x = mx.init.Xavier()
    g = nd.zeros((4,))
    x(mx.initializer.InitDesc("bn_gamma"), g)
    assert (g.asnumpy() == 1).all()
    mm = nd.zeros((4,))
    x(mx.initializer.InitDesc("bn_moving_var"), mm)
    assert (mm.asnumpy() == 1).all()


def test_kvstore_local():
    kv = mx.kvstore.create("local")
    kv.init(3, nd.ones((2, 2)))
    # push grads from 2 "devices" and pull merged
    kv.push(3, [nd.ones((2, 2)), nd.ones((2, 2)) * 2])
    out = nd.zeros((2, 2))
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), 3 * np.ones((2, 2)))


def test_kvstore_updater_path():
    kv = mx.kvstore.create("local")
    kv.init("w", nd.ones((3,)))
    kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=0.5))
    kv.push("w", nd.ones((3,)))
    out = nd.zeros((3,))
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), np.ones(3) - 0.5)


def test_kvstore_dist_async_rejected():
    with pytest.raises(ValueError):
        mx.kvstore.create("dist_async")


def test_ndarray_iter_roll_over():
    data = np.arange(20, dtype="float32").reshape(10, 2)
    it = mx.io.NDArrayIter(data, None, batch_size=4,
                           last_batch_handle="roll_over")
    b1 = list(it)
    assert len(b1) == 2  # partial batch held over, not emitted
    it.reset()
    b2 = list(it)
    assert len(b2) == 3  # 2 carried + 10 = 12 -> 3 full batches
    np.testing.assert_allclose(b2[0].data[0].asnumpy()[:2],
                               data[8:10])


def test_prefetching_iter_exhausted_raises():
    data = np.random.rand(8, 2).astype("float32")
    pf = mx.io.PrefetchingIter(
        mx.io.NDArrayIter(data, None, batch_size=4))
    assert len(list(pf)) == 2
    import pytest as _pytest
    with _pytest.raises(StopIteration):
        pf.next()  # must not hang


def test_wd_default_rule_matches_reference():
    opt = mx.optimizer.create("sgd", wd=0.5,
                              param_idx2name={0: "bn_gamma",
                                              1: "fc_bias",
                                              2: "embed0"})
    assert opt._get_wd(0) == 0.5   # gamma IS decayed in reference
    assert opt._get_wd(1) == 0.0   # bias exempt
    assert opt._get_wd(2) == 0.0   # non-weight/gamma exempt


def test_device_prefetch_iter_matches_and_commits():
    """DevicePrefetchIter (src/io/iter_prefetcher.h:47 analog) yields
    the same batches as its inner iterator, device-committed."""
    import jax

    import incubator_mxnet_tpu as mx

    x = np.arange(48, dtype=np.float32).reshape(12, 4)
    y = np.arange(12, dtype=np.float32)
    inner = mx.io.NDArrayIter(x, y, batch_size=4)
    pre = mx.io.DevicePrefetchIter(
        mx.io.NDArrayIter(x, y, batch_size=4), ctx=mx.cpu(3))
    got = list(pre)
    want = list(inner)
    assert len(got) == len(want) == 3
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g.data[0].asnumpy(),
                                      w.data[0].asnumpy())
        np.testing.assert_array_equal(g.label[0].asnumpy(),
                                      w.label[0].asnumpy())
        assert g.data[0]._data.devices() == \
            {mx.cpu(3).jax_device}
    # reset restarts cleanly and yields a full epoch again
    pre.reset()
    assert sum(b.data[0].shape[0] for b in pre) == 12
    assert pre.provide_data[0].shape == (4, 4)


def test_device_prefetch_iter_propagates_worker_error():
    import incubator_mxnet_tpu as mx

    class Boom(mx.io.DataIter):
        batch_size = 2

        def next(self):
            raise RuntimeError("decode exploded")

        @property
        def provide_data(self):
            return []

        @property
        def provide_label(self):
            return []

    pre = mx.io.DevicePrefetchIter(Boom(), ctx=mx.cpu(0))
    with pytest.raises(RuntimeError, match="decode exploded"):
        pre.next()


def test_device_prefetch_iter_terminal_states_do_not_block():
    """Repeated next() after exhaustion / error must re-raise, not
    block on a producerless queue (review regression)."""
    import incubator_mxnet_tpu as mx

    x = np.arange(8, dtype=np.float32).reshape(4, 2)
    pre = mx.io.DevicePrefetchIter(
        mx.io.NDArrayIter(x, None, batch_size=2), ctx=mx.cpu(0))
    assert len(list(pre)) == 2
    for _ in range(3):
        with pytest.raises(StopIteration):
            pre.next()

    class Boom(mx.io.DataIter):
        batch_size = 2

        def next(self):
            raise RuntimeError("decode exploded")

        @property
        def provide_data(self):
            return []

        @property
        def provide_label(self):
            return []

    pre = mx.io.DevicePrefetchIter(Boom(), ctx=mx.cpu(0))
    for _ in range(2):
        with pytest.raises(RuntimeError, match="decode exploded"):
            pre.next()
