"""FeedForward legacy estimator (ref: python/mxnet/model.py
FeedForward:408 — numpy-in fit/predict/score + save/load)."""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import sym
from incubator_mxnet_tpu.model import FeedForward


def _net():
    data = sym.Variable("data")
    net = sym.Activation(sym.FullyConnected(data, num_hidden=16,
                                            name="ffc1"),
                         act_type="relu")
    net = sym.FullyConnected(net, num_hidden=2, name="ffc2")
    return sym.SoftmaxOutput(net, name="softmax")


def _data(n=200):
    rs = np.random.RandomState(0)
    X = rs.rand(n, 6).astype("float32")
    y = (X[:, 0] + X[:, 1] > 1.0).astype("float32")
    return X, y


def test_fit_predict_score_numpy():
    import random as pyrandom
    X, y = _data()
    mx.random.seed(0)
    np.random.seed(0)      # iterator shuffle order
    pyrandom.seed(0)
    model = FeedForward(_net(), num_epoch=25, learning_rate=0.5,
                        numpy_batch_size=48)  # 200 % 48 != 0: pad path
    model.fit(X, y)
    acc = model.score(X, y)
    assert acc > 0.9, acc
    preds = model.predict(X)
    assert preds.shape == (200, 2)
    assert ((preds.argmax(1) == y).mean()) == acc


def test_create_and_save_load_roundtrip(tmp_path):
    X, y = _data(120)
    mx.random.seed(0)
    model = FeedForward.create(_net(), X, y, num_epoch=8,
                               learning_rate=0.5,
                               numpy_batch_size=24)
    preds = model.predict(X)
    prefix = str(tmp_path / "ff")
    model.save(prefix, 8)
    loaded = FeedForward.load(prefix, 8)
    preds2 = loaded.predict(X)
    np.testing.assert_allclose(preds2, preds, rtol=1e-5, atol=1e-6)


def test_predict_requires_params():
    import pytest
    model = FeedForward(_net())
    with pytest.raises(AssertionError, match="fit"):
        model.predict(np.zeros((4, 6), "float32"))
