"""Registry-wide operator sweep (VERDICT r2 task 5): every
differentiable op gets a numeric-gradient check through the symbolic
executor (the reference's per-op check_numeric_gradient discipline,
ref: python/mxnet/test_utils.py:789 used across
tests/python/unittest/test_operator.py), and non-differentiable /
custom-VJP ops get a forward execution check.

The sweep runs per unique compute function; the meta test at the
bottom asserts the swept functions cover >150 registry names.
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import test_utils as tu
from incubator_mxnet_tpu.ops.registry import OPS

RS = np.random.RandomState(7)


def P(*shape, lo=0.3, hi=0.9, dtype=np.float32):
    """Positive floats inside every unary op's domain (log, sqrt,
    arcsin, erfinv ... all defined on (0.3, 0.9))."""
    return (RS.uniform(lo, hi, shape)).astype(dtype)


def S(*shape):  # symmetric positive definite
    a = RS.rand(*shape).astype(np.float32)
    return a @ a.T + np.eye(shape[0], dtype=np.float32) * shape[0]


# ---------------------------------------------------------------------------
# specs: name -> dict(inputs=[...], params={}, fwd=bool)
# default (no spec): n_args inputs of shape (2,3) in (0.3,0.9),
# numeric-gradient checked when op.differentiable
# ---------------------------------------------------------------------------

TRI = np.tril(RS.rand(3, 3).astype(np.float32) + 0.5)

SPECS = {
    # ---- scalar-arg elemwise
    **{n: dict(inputs=[P(2, 3)], params=dict(scalar=0.7))
       for n in ["_plus_scalar", "_minus_scalar", "_rminus_scalar",
                 "_mul_scalar", "_div_scalar", "_rdiv_scalar",
                 "_power_scalar", "_rpower_scalar", "_hypot_scalar",
                 "_maximum_scalar", "_minimum_scalar"]},
    **{n: dict(inputs=[P(2, 3)], params=dict(scalar=0.7), fwd=True)
       for n in ["_mod_scalar", "_rmod_scalar", "_equal_scalar",
                 "_not_equal_scalar", "_greater_scalar",
                 "_greater_equal_scalar", "_lesser_scalar",
                 "_lesser_equal_scalar"]},
    "arccosh": dict(inputs=[P(2, 3, lo=1.3, hi=2.0)]),
    "clip": dict(inputs=[P(2, 3)], params=dict(a_min=0.4, a_max=0.8)),
    "smooth_l1": dict(inputs=[P(2, 3)]),
    # ---- shape manipulation
    "reshape": dict(inputs=[P(2, 6)], params=dict(shape=(3, 4))),
    "expand_dims": dict(inputs=[P(2, 3)], params=dict(axis=1)),
    "squeeze": dict(inputs=[P(2, 1, 3)]),
    "transpose": dict(inputs=[P(2, 3)]),
    "swapaxes": dict(inputs=[P(2, 3, 4)],
                     params=dict(dim1=0, dim2=2)),
    "tile": dict(inputs=[P(2, 3)], params=dict(reps=(2, 2))),
    "repeat": dict(inputs=[P(2, 3)], params=dict(repeats=2)),
    "pad": dict(inputs=[P(1, 2, 3, 3)],
                params=dict(mode="constant",
                            pad_width=(0, 0, 0, 0, 1, 1, 1, 1))),
    "flip": dict(inputs=[P(2, 3)], params=dict(axis=0)),
    "reverse": dict(inputs=[P(2, 3)], params=dict(axis=1)),
    "slice": dict(inputs=[P(4, 4)],
                  params=dict(begin=(1, 0), end=(3, 2))),
    "slice_axis": dict(inputs=[P(4, 4)],
                       params=dict(axis=1, begin=0, end=2)),
    "slice_like": dict(inputs=[P(4, 4), P(2, 3)]),
    "broadcast_to": dict(inputs=[P(1, 3)], params=dict(shape=(4, 3))),
    "broadcast_axis": dict(inputs=[P(1, 3)],
                           params=dict(axis=0, size=4)),
    "broadcast_like": dict(inputs=[P(1, 3), P(4, 3)]),
    "stack": dict(inputs=[P(2, 3), P(2, 3)], params=dict(axis=0)),
    "concat": dict(inputs=[P(2, 3), P(2, 3)], params=dict(dim=1)),
    "split": dict(inputs=[P(4, 6)],
                  params=dict(num_outputs=2, axis=1)),
    "where": dict(inputs=[(RS.rand(2, 3) > 0.5).astype(np.float32),
                          P(2, 3), P(2, 3)]),
    "one_hot": dict(inputs=[np.array([0, 2, 1], np.int32)],
                    params=dict(depth=4), fwd=True),
    # ---- matmul / linalg
    "dot": dict(inputs=[P(2, 3), P(3, 4)]),
    "batch_dot": dict(inputs=[P(2, 2, 3), P(2, 3, 2)]),
    "_flash_attention": dict(
        inputs=[P(2, 4, 3), P(2, 4, 3), P(2, 4, 3)],
        params=dict(causal=True, interpret=True)),
    "khatri_rao": dict(inputs=[P(2, 3), P(4, 3)]),
    "linalg_gemm": dict(inputs=[P(2, 3), P(3, 4), P(2, 4)]),
    "linalg_gemm2": dict(inputs=[P(2, 3), P(3, 4)]),
    "linalg_syrk": dict(inputs=[P(3, 4)]),
    "linalg_potrf": dict(inputs=[S(3, 3)], rtol=0.08),
    "linalg_potri": dict(inputs=[S(3, 3)], rtol=0.08),
    "linalg_sumlogdiag": dict(inputs=[S(3, 3)]),
    "linalg_trmm": dict(inputs=[TRI, P(3, 3)]),
    "linalg_trsm": dict(inputs=[TRI + np.eye(3, dtype=np.float32),
                                P(3, 3)], rtol=0.08),
    "linalg_gelqf": dict(inputs=[P(2, 3)], fwd=True),
    "linalg_syevd": dict(inputs=[S(3, 3)], fwd=True),
    # ---- indexing
    "take": dict(inputs=[P(5, 3), np.array([0, 2], np.int32)]),
    "batch_take": dict(inputs=[P(3, 4),
                               np.array([0, 2, 1], np.int32)]),
    "pick": dict(inputs=[P(3, 4), np.array([0, 2, 1], np.float32)],
                 grad_nodes=["a0"]),
    "gather_nd": dict(inputs=[P(3, 4),
                              np.array([[0, 2], [1, 3]], np.int32)]),
    "scatter_nd": dict(
        inputs=[P(2), np.array([[0, 2], [1, 3]], np.int32)],
        params=dict(shape=(3, 4))),
    "Embedding": dict(inputs=[np.array([0, 2], np.int32), P(5, 4)],
                      params=dict(input_dim=5, output_dim=4)),
    # ---- reductions with axes
    "max_axis": dict(inputs=[P(3, 4)], params=dict(axis=1)),
    "min_axis": dict(inputs=[P(3, 4)], params=dict(axis=1)),
    "sum_axis": dict(inputs=[P(3, 4)], params=dict(axis=1)),
    "argmax": dict(inputs=[P(3, 4)], params=dict(axis=1), fwd=True),
    "argmin": dict(inputs=[P(3, 4)], params=dict(axis=1), fwd=True),
    "argmax_channel": dict(inputs=[P(3, 4)], fwd=True),
    "argsort": dict(inputs=[P(3, 4)], fwd=True),
    "sort": dict(inputs=[P(3, 4)], fwd=True),
    "topk": dict(inputs=[P(3, 4)], params=dict(k=2), fwd=True),
    "norm": dict(inputs=[P(2, 3)]),
    # ---- nn layers
    "FullyConnected": dict(inputs=[P(2, 3), P(4, 3), P(4)],
                           params=dict(num_hidden=4)),
    "Convolution": dict(
        inputs=[P(1, 2, 5, 5), P(3, 2, 3, 3), P(3)],
        params=dict(kernel=(3, 3), num_filter=3), rtol=0.08),
    "Deconvolution": dict(
        inputs=[P(1, 2, 4, 4), P(2, 3, 3, 3), P(3)],
        params=dict(kernel=(3, 3), num_filter=3, no_bias=False),
        rtol=0.08),
    "Pooling": dict(inputs=[P(1, 2, 4, 4)],
                    params=dict(kernel=(2, 2), stride=(2, 2),
                                pool_type="avg")),
    "UpSampling": dict(inputs=[P(1, 2, 3, 3)],
                       params=dict(scale=2, sample_type="nearest")),
    "LRN": dict(inputs=[P(1, 4, 3, 3)], params=dict(nsize=3)),
    "LayerNorm": dict(inputs=[P(2, 4), P(4), P(4)]),
    "InstanceNorm": dict(inputs=[P(2, 3, 4), P(3), P(3)]),
    "L2Normalization": dict(inputs=[P(2, 3, 4)]),
    "Activation": dict(inputs=[P(2, 3)],
                       params=dict(act_type="tanh")),
    "LeakyReLU": dict(inputs=[P(2, 3)]),
    "softmax": dict(inputs=[P(2, 4)]),
    "log_softmax": dict(inputs=[P(2, 4)]),
    "softmax_cross_entropy": dict(
        inputs=[P(3, 4), np.array([0, 2, 1], np.float32)],
        grad_nodes=["a0"]),
    "SequenceMask": dict(inputs=[P(3, 2, 4)]),
    "SequenceLast": dict(inputs=[P(3, 2, 4)]),
    "SequenceReverse": dict(inputs=[P(3, 2, 4)]),
    "SliceChannel": dict(inputs=[P(2, 4)],
                         params=dict(num_outputs=2, axis=1)),
    "Flatten": dict(inputs=[P(2, 3, 4)]),
    "Cast": dict(inputs=[P(2, 3)], params=dict(dtype="float32"),
                 fwd=True),
    "Crop": dict(inputs=[P(1, 2, 4, 4)],
                 params=dict(h_w=(2, 2), offset=(1, 1))),
    "GridGenerator": dict(
        inputs=[np.array([[1, 0, 0, 0, 1, 0]], np.float32)],
        params=dict(transform_type="affine", target_shape=(3, 3))),
    "BilinearSampler": dict(
        inputs=[P(1, 2, 4, 4),
                (RS.rand(1, 2, 3, 3) * 0.8 - 0.4).astype(np.float32)],
        rtol=0.08),
    # off-lattice affine: bilinear grads are discontinuous exactly
    # on integer sample coords, so keep them strictly interior
    "SpatialTransformer": dict(
        inputs=[P(1, 2, 4, 4),
                np.array([[0.45, 0, 0.05, 0, 0.45, 0.05]],
                         np.float32)],
        params=dict(target_shape=(3, 3)), rtol=0.08),
    "ROIPooling": dict(
        inputs=[P(1, 2, 6, 6),
                np.array([[0, 0, 0, 3, 3]], np.float32)],
        params=dict(pooled_size=(2, 2), spatial_scale=1.0),
        grad_nodes=["a0"], fwd=True),
    # ---- heads with custom-VJP loss backward: forward-only (their
    # backward is the *loss* gradient, not d(forward) — by design)
    **{n: dict(inputs=[P(3, 4), np.array([0, 2, 1], np.float32)],
               fwd=True)
       for n in ["SoftmaxOutput", "SVMOutput",
                 "LinearRegressionOutput", "MAERegressionOutput",
                 "LogisticRegressionOutput"]},
    "make_loss": dict(inputs=[P(2, 3)], fwd=True),
    "BlockGrad": dict(inputs=[P(2, 3)], fwd=True),
    "stop_gradient": dict(inputs=[P(2, 3)], fwd=True),
    "_identity_with_attr_like_rhs": dict(inputs=[P(2, 3), P(2, 3)],
                                         fwd=True),
    "elemwise_addto": dict(inputs=[P(2, 3), P(2, 3)], fwd=True),
    # comparisons / mod: derivative zero or undefined -> forward-only
    **{n: dict(inputs=[P(2, 3), P(2, 3)], fwd=True)
       for n in ["_equal", "_not_equal", "_greater", "_greater_equal",
                 "_lesser", "_lesser_equal", "_mod",
                 "broadcast_equal", "broadcast_not_equal",
                 "broadcast_greater", "broadcast_greater_equal",
                 "broadcast_lesser", "broadcast_lesser_equal",
                 "broadcast_mod", "broadcast_logical_and",
                 "broadcast_logical_or", "broadcast_logical_xor"]},
    "add_n": dict(inputs=[P(2, 3), P(2, 3)]),
    "Correlation": dict(inputs=[P(1, 2, 4, 4), P(1, 2, 4, 4)],
                        params=dict(kernel_size=1, max_displacement=1,
                                    pad_size=1), rtol=0.08),
    "IdentityAttachKLSparseReg": dict(inputs=[P(3, 4)], fwd=True),
    "reshape_like": dict(inputs=[P(2, 3), P(3, 2)]),
    "_sparse_retain": dict(
        inputs=[P(4, 2), np.array([1, 3], np.float32)],
        grad_nodes=["a0"]),
    "_square_sum": dict(inputs=[P(3, 4)], params=dict(axis=1)),
    "ElementWiseSum": dict(inputs=[P(2, 3), P(2, 3)]),
    "einsum": dict(inputs=[P(3, 4), P(4, 5)],
                   params=dict(subscripts="ij,jk->ik")),
    "_rope": dict(inputs=[P(2, 4, 8)]),   # head dim must be even
}

SKIP = set(
    # random / sampling (distributional, tested in test_operator)
    [n for n in OPS if "random" in n or "sample" in n
     or n in ("normal", "uniform", "shuffle", "_shuffle")]
    # optimizer update kernels (tested in test_optimizer)
    + [n for n in OPS if n.endswith("_update")]
    # init / constant ops (no tensor input)
    + ["_zeros", "_ones", "_eye", "_full", "_arange", "zeros_like",
       "ones_like"]
    # aux-state / rng / recurrent ops covered by dedicated suites
    + ["BatchNorm", "BatchNorm_v1", "CuDNNBatchNorm", "Dropout",
       "RNN", "Custom", "CTCLoss", "ctc_loss", "_contrib_CTCLoss",
       "_contrib_ctc_loss"]
    # contrib detection ops: tests/test_contrib_det.py
    + [n for n in OPS if n.startswith("_contrib_")]
    # sparse kernels: tests/test_sparse*.py
    + [n for n in OPS if n.startswith("_sparse_")]
    # MoE routing: shape contract (stacked expert weights) needs the
    # dedicated suite (tests/test_moe.py)
    + ["_moe_ffn"]
    # in-place assignment / device plumbing / misc utilities
    + ["_slice_assign", "_slice_assign_scalar", "_crop_assign",
       "_crop_assign_scalar", "_scatter_set_nd", "_CrossDeviceCopy",
       "_cross_device_copy", "amp_cast", "cast", "crop",
       "broadcast_axes", "_NDArray", "_Native"])


def _build_cases():
    cases = {}
    seen_fns = set()
    # spec'd names first so aliases of spec'd ops dedupe onto them
    order = [n for n in SPECS if n in OPS] + \
        [n for n in sorted(OPS) if n not in SPECS]
    for name in order:
        op = OPS[name]
        if name in SKIP or id(op.fn) in seen_fns:
            continue
        spec = SPECS.get(name)
        if spec is None:
            n_in = len(op.arg_names) or 1
            if n_in > 3:
                continue
            spec = dict(inputs=[P(2, 3) for _ in range(n_in)])
        seen_fns.add(id(op.fn))
        cases[name] = spec
    return cases


CASES = _build_cases()


def _symbol_for(name, spec):
    op_fn = getattr(mx.sym, name, None) or \
        getattr(mx.sym._internal, name)
    variables = [mx.sym.Variable(f"a{i}")
                 for i in range(len(spec["inputs"]))]
    return op_fn(*variables, **spec.get("params", {}))


@pytest.mark.parametrize("name", sorted(CASES))
def test_op_sweep(name):
    spec = CASES[name]
    sym = _symbol_for(name, spec)
    location = {f"a{i}": v for i, v in enumerate(spec["inputs"])}
    op = OPS[name]
    fwd_only = spec.get("fwd", False) or not op.differentiable
    if fwd_only:
        exe, _ = tu._bind(sym, location, grad_req="null")
        outs = exe.forward(is_train=False)
        for o in outs:
            a = o.asnumpy()
            assert np.all(np.isfinite(a.astype(np.float64))), name
    else:
        tu.check_numeric_gradient(
            sym, location, numeric_eps=1e-3,
            rtol=spec.get("rtol", 0.05), atol=spec.get("atol", 5e-3),
            grad_nodes=spec.get("grad_nodes"))


def test_sweep_covers_registry():
    """The swept compute functions must cover >150 registry names
    (aliases included), per the round-2 verdict's bar."""
    swept_fns = {id(OPS[n].fn) for n in CASES}
    covered = [n for n in OPS if id(OPS[n].fn) in swept_fns]
    assert len(covered) > 150, (len(covered), len(CASES))
