"""Tool scripts (ref: tools/rec2idx.py, tools/parse_log.py)."""
import os
import subprocess
import sys
import tempfile

import numpy as np

from incubator_mxnet_tpu import recordio as rio

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")
sys.path.insert(0, TOOLS)


def test_rec2idx_roundtrip():
    with tempfile.TemporaryDirectory() as td:
        prefix = os.path.join(td, "d")
        rec = rio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec",
                                    "w")
        payloads = {}
        for i in range(7):
            buf = os.urandom(10 + i)
            payloads[i] = buf
            rec.write_idx(i, rio.pack(
                rio.IRHeader(0, float(i), i, 0), buf))
        rec.close()
        orig = open(prefix + ".idx").read()
        os.unlink(prefix + ".idx")

        import rec2idx
        idx_path, n = rec2idx.build_index(prefix + ".rec")
        assert n == 7
        assert open(idx_path).read() == orig
        # random access works through the rebuilt index
        r = rio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec",
                                  "r")
        for i in (3, 0, 6):
            header, buf = rio.unpack(r.read_idx(i))
            assert buf == payloads[i]
            assert int(header.id) == i


def test_parse_log():
    import parse_log
    log = """\
INFO Epoch[0] Batch [20]  Speed: 100.0 samples/sec  accuracy=0.5
INFO Epoch[0] Batch [40]  Speed: 300.0 samples/sec  accuracy=0.6
INFO Epoch[0] Train-accuracy=0.61
INFO Epoch[0] Time cost=10.5
INFO Epoch[0] Validation-accuracy=0.58
INFO Epoch[1] Batch [20]  Speed: 200.0 samples/sec  accuracy=0.7
INFO Epoch[1] Train-accuracy=0.72
INFO Epoch[1] Time cost=9.0
INFO Epoch[1] Validation-accuracy=0.69
"""
    epochs = parse_log.parse(log.splitlines())
    assert epochs[0]["speed"] == [100.0, 300.0]
    assert epochs[0]["train"]["accuracy"] == 0.61
    assert epochs[1]["val"]["accuracy"] == 0.69
    md = parse_log.render(epochs)
    assert "train-accuracy" in md and "| 0" in md
    csv = parse_log.render(epochs, "csv")
    assert csv.splitlines()[0].startswith("epoch,speed")
    assert "200" in csv


def test_bandwidth_tool_collectives_and_kvstore():
    """tools/bandwidth.py (ref: tools/bandwidth/measure.py) runs all
    benches on the 8-virtual-device mesh and reports sane records."""
    import bandwidth
    recs = bandwidth.main(["--benches", "collectives,kvstore,h2d",
                           "--sizes-mb", "0.5", "--iters", "2"])
    by_bench = {}
    for r in recs:
        by_bench.setdefault(r["bench"], []).append(r)
    coll = by_bench["collectives"]
    assert {c["op"] for c in coll} == {
        "allreduce", "reduce_scatter", "all_gather", "ppermute"}
    assert all(c["ms"] > 0 and c["bus_gbps"] > 0 for c in coll)
    assert all(c["devices"] == 8 for c in coll)
    kv = by_bench["kvstore"][0]
    assert kv["payload_mb"] > 10 and kv["gbps"] > 0
    h2d = by_bench["h2d"][0]
    assert h2d["h2d_gbps"] > 0 and h2d["d2h_gbps"] > 0


def test_pipeline_bench_mode(tmp_path):
    """bench.py's pipeline mode: .rec decode -> DevicePrefetchIter ->
    train step, end-to-end on the CPU backend with the tiny net."""
    import json
    env = dict(os.environ)
    env.update(MXTPU_BENCH_PLATFORM="cpu", MXTPU_BENCH_MODEL="pipeline",
               MXTPU_BENCH_PIPE_IMGS="64", MXTPU_BENCH_PIPE_NET="tiny",
               MXTPU_BENCH_BATCH="16")
    env.pop("XLA_FLAGS", None)
    repo = os.path.dirname(TOOLS)
    r = subprocess.run([sys.executable, os.path.join(repo, "bench.py")],
                       capture_output=True, text=True, timeout=420,
                       env=env, cwd=repo)
    assert r.returncode == 0, r.stderr[-2000:]
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["metric"].startswith("tiny_e2e_pipeline")
    assert rec["value"] > 0 and rec["feed_only_img_s"] > 0
    assert rec["naked_step_img_s"] > 0 and rec["e2e_over_step"] > 0
