"""Training-step sentinel (docs/numeric_stability.md): NumericGuard
policies, dynamic loss scaling, guarded Trainer/Module update paths
(eager, fused, and kvstore='tpu' mesh), divergence rollback to the
newest valid checkpoint, and the one-scalar-per-guard-interval
transfer budget — all CPU-tested via the grad:nonfinite / loss:spike
fault-injection scopes."""
import math
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, nd
from incubator_mxnet_tpu import optimizer as opt_mod
from incubator_mxnet_tpu import resilience as rz
from incubator_mxnet_tpu.gluon import nn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_sentinel_env(monkeypatch):
    for var in ("MXTPU_FAULT_SPEC", "MXTPU_NONFINITE_POLICY",
                "MXTPU_GUARD_INTERVAL", "MXTPU_MAX_BAD_STEPS",
                "MXTPU_LOSS_SCALE", "MXTPU_LOSS_SCALE_DYNAMIC",
                "MXTPU_LOSS_SCALE_WINDOW", "MXTPU_LOSS_SPIKE_FACTOR"):
        monkeypatch.delenv(var, raising=False)
    rz.reset_faults()
    yield
    rz.reset_faults()


# ---------------------------------------------------------------- guard
def test_guard_policies_and_divergence():
    g = rz.NumericGuard(policy="skip", interval=1, max_bad_steps=3)
    assert g.enabled
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        assert g.record(True) == "ok"
        assert g.record(False) == "skip"
        assert g.record(True) == "ok"          # resets consecutive
        assert g.consecutive_bad == 0
        assert g.record(False) == "skip"
        assert g.record(False) == "skip"
        with pytest.raises(rz.DivergedError):
            g.record(False)
    assert g.bad_steps == 4 and g.skipped_steps == 3

    with pytest.raises(rz.BadStepError):
        rz.NumericGuard(policy="raise", max_bad_steps=0).record(False)

    g = rz.NumericGuard(policy="warn", max_bad_steps=0)
    with pytest.warns(RuntimeWarning):
        assert g.record(False) == "ok"         # warn applies anyway

    assert not rz.NumericGuard(policy="off").enabled
    with pytest.raises(ValueError):
        rz.NumericGuard(policy="bogus")


def test_guard_interval_due_cadence():
    g = rz.NumericGuard(policy="skip", interval=3, max_bad_steps=0)
    due = [g.begin_step() for _ in range(7)]
    assert due == [True, False, False, True, False, False, True]
    # disabled guard is never due
    g2 = rz.NumericGuard(policy="off", interval=1)
    assert [g2.begin_step() for _ in range(3)] == [False] * 3


def test_fault_spec_numeric_kinds():
    assert rz.parse_fault_spec("grad:nonfinite:3:nan,loss:spike:1:inf")
    assert rz.parse_fault_spec("loss:spike:2:spike")
    with pytest.raises(ValueError):        # numeric kind, wrong scope
        rz.parse_fault_spec("checkpoint:save:1:nan")
    with pytest.raises(ValueError):        # spike is loss-only
        rz.parse_fault_spec("grad:nonfinite:1:spike")


def test_check_loss_finiteness_spike_and_injection(monkeypatch):
    g = rz.NumericGuard(policy="skip", interval=1, max_bad_steps=0,
                        spike_factor=10.0)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        assert g.check_loss(1.0) == "ok"
        assert g.check_loss(float("nan")) == "skip"
        assert g.check_loss(1.1) == "ok"
        assert g.check_loss(500.0) == "skip"   # > 10x running mean
        assert g.check_loss(1.2) == "ok"
    # injection: the 2nd check_loss call sees a spiking loss
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "loss:spike:2:spike")
    rz.reset_faults()
    g = rz.NumericGuard(policy="skip", interval=1, max_bad_steps=0,
                        spike_factor=10.0)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        assert g.check_loss(1.0) == "ok"
        assert g.check_loss(1.0) == "skip"
        assert g.check_loss(1.0) == "ok"
    assert g.bad_steps == 1
    # policy off: check_loss costs nothing and never flags
    assert rz.NumericGuard(policy="off").check_loss(
        float("nan")) == "ok"
    # an injected spike flags even with the detector threshold left
    # at its disabled default (and must not corrupt the loss EMA)
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "loss:spike:2:spike")
    rz.reset_faults()
    g = rz.NumericGuard(policy="skip", interval=1, max_bad_steps=0,
                        spike_factor=0.0)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        assert g.check_loss(1.0) == "ok"
        assert g.check_loss(1.0) == "skip"
    assert g._loss_ema == 1.0


# ---------------------------------------------------------------- scaler
def test_loss_scaler_backoff_and_growth():
    s = opt_mod.LossScaler(init_scale=8.0, dynamic=True, growth=2.0,
                           backoff=0.5, window=2, max_scale=16.0)
    assert s.active
    assert s.update(overflow=True) == 4.0
    assert s.num_backoffs == 1
    s.update(False)
    assert s.scale == 4.0                   # window not reached yet
    s.update(False)
    assert s.scale == 8.0 and s.num_growths == 1
    for _ in range(4):
        s.update(False)
    assert s.scale == 16.0                  # capped at max
    # floor: backoff never goes below 1
    s2 = opt_mod.LossScaler(init_scale=1.0, dynamic=True, backoff=0.5,
                            window=100, max_scale=16.0)
    assert s2.update(overflow=True) == 1.0
    # defaults are inert
    s3 = opt_mod.LossScaler()
    assert not s3.active and s3.update(True) == 1.0


def test_loss_scaler_state_roundtrip():
    s = opt_mod.LossScaler(init_scale=4.0, dynamic=True, window=5)
    s.update(False)
    state = s.state_dict()
    s2 = opt_mod.LossScaler(init_scale=1.0, dynamic=True, window=5)
    s2.load_state_dict(state)
    assert s2.scale == 4.0 and s2._good_steps == 1


# ---------------------------------------------------------------- flag
def test_all_finite_and_window_accumulation():
    ok = mx.nd.array(np.ones((3, 2), np.float32))
    bad = mx.nd.array(np.array([1.0, np.inf], np.float32))
    ints = mx.nd.array(np.arange(4), dtype="int32")
    assert bool(opt_mod.all_finite([ok])) is True
    assert bool(opt_mod.all_finite([ok, bad])) is False
    # integer leaves are skipped; empty list is trivially finite
    assert opt_mod.all_finite([ints]) is True
    assert opt_mod.all_finite([]) is True
    assert bool(opt_mod.all_finite([ints, ok])) is True
    # the window counter accumulates per-step flags on device and
    # resets on read
    g = rz.NumericGuard(policy="skip", interval=4, max_bad_steps=0)
    for arrays in ([ok], [ok, bad], [bad], [ok]):
        opt_mod.accumulate_window(g, opt_mod.all_finite(arrays))
    assert opt_mod.read_window_bad(g) == 2
    assert opt_mod.read_window_bad(g) == 0      # reset after read


# ---------------------------------------------------------------- updater
def test_guarded_updater_skips_step_and_step_count(monkeypatch):
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "grad:nonfinite:2:nan")
    rz.reset_faults()
    opt = opt_mod.create("sgd", learning_rate=0.1)
    up = opt_mod.GuardedUpdater(
        opt, guard=rz.NumericGuard(policy="skip", interval=1,
                                   max_bad_steps=0))
    w = mx.nd.array(np.ones((4,), np.float32))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        g1 = mx.nd.array(np.full((4,), 0.5, np.float32))
        assert up.begin_step([g1])
        up(0, g1, w)
        after_good = w.asnumpy().copy()
        g2 = mx.nd.array(np.full((4,), 0.5, np.float32))
        assert not up.begin_step([g2])      # poisoned -> skip
        up(0, g2, w)                        # no-op
    assert np.allclose(w.asnumpy(), after_good)
    assert np.all(np.isfinite(w.asnumpy()))
    # skipped step advanced neither num_update nor the per-key count
    assert opt.num_update == 1
    assert up.guard.skipped_steps == 1


def test_guarded_updater_raise_policy(monkeypatch):
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "grad:nonfinite:1:inf")
    rz.reset_faults()
    up = opt_mod.GuardedUpdater(
        opt_mod.create("sgd"),
        guard=rz.NumericGuard(policy="raise", interval=1,
                              max_bad_steps=0))
    g = mx.nd.array(np.ones((2,), np.float32))
    with pytest.raises(rz.BadStepError):
        up.begin_step([g])


def test_transfer_budget_one_read_per_interval(monkeypatch):
    """The guard's entire sync cost: one scalar device->host read
    per MXTPU_GUARD_INTERVAL steps, counted both by the guard and by
    intercepting read_window_bad (the sole transfer point) itself."""
    reads = []
    orig = opt_mod.read_window_bad
    monkeypatch.setattr(opt_mod, "read_window_bad",
                        lambda g: reads.append(1) or orig(g))
    opt = opt_mod.create("sgd", learning_rate=0.1)
    guard = rz.NumericGuard(policy="skip", interval=4,
                            max_bad_steps=0)
    up = opt_mod.GuardedUpdater(opt, guard=guard)
    w = mx.nd.array(np.ones((4,), np.float32))
    for _ in range(8):
        g = mx.nd.array(np.full((4,), 0.1, np.float32))
        assert up.begin_step([g])
        up(0, g, w)
    assert len(reads) == 2                  # steps 0 and 4
    assert guard.checks == 2
    assert guard.steps == 8


# ---------------------------------------------------------------- gluon
def _toy_data(n=100, dim=10, classes=3, seed=0):
    rs = np.random.RandomState(seed)
    return (rs.randn(n, dim).astype("float32"),
            rs.randint(0, classes, n).astype("float32"))


def _train_gluon(optimizer, steps=6, batch=10):
    mx.random.seed(42)
    data, labels = _toy_data()
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"))
    net.add(nn.Dense(3))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), optimizer,
                            {"learning_rate": 0.1})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        for step in range(steps):
            lo = (step * batch) % len(data)
            x = nd.array(data[lo:lo + batch])
            y = nd.array(labels[lo:lo + batch])
            with autograd.record():
                loss = loss_fn(net(x), y) * trainer.loss_scale
            loss.backward()
            trainer.step(batch)
    return net, trainer


def test_trainer_fused_path_skips_injected_bad_steps(monkeypatch):
    monkeypatch.setenv("MXTPU_NONFINITE_POLICY", "skip")
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "grad:nonfinite:3:nan")
    rz.reset_faults()
    net, trainer = _train_gluon("sgd", steps=6)
    assert trainer._fused_active()
    for p in net.collect_params().values():
        assert np.all(np.isfinite(p.data().asnumpy()))
    assert trainer.guard.skipped_steps == 1
    assert trainer.guard.steps == 6
    # skipped step did not advance the LR-schedule step count
    assert trainer._optimizer.num_update == 5


def test_trainer_eager_path_skips_injected_bad_steps(monkeypatch):
    monkeypatch.setenv("MXTPU_NONFINITE_POLICY", "skip")
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "grad:nonfinite:2:inf")
    rz.reset_faults()
    # adagrad has no functional counterpart -> eager updater loop
    net, trainer = _train_gluon("adagrad", steps=5)
    assert not trainer._fused_active()
    for p in net.collect_params().values():
        assert np.all(np.isfinite(p.data().asnumpy()))
    assert trainer.guard.skipped_steps == 1
    assert trainer._optimizer.num_update == 4


@pytest.mark.parametrize("optimizer", ["adagrad", "sgd"])
def test_trainer_warn_policy_applies_bad_updates(optimizer,
                                                 monkeypatch):
    """The injection poisons REAL gradients, and warn's contract is
    to apply the update anyway: on both the eager (adagrad) and
    fused (sgd) paths the parameters end up NaN — proof the
    skip-policy runs above are protecting, not just counting, and
    that the fused where-select stays off under warn."""
    monkeypatch.setenv("MXTPU_NONFINITE_POLICY", "warn")
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "grad:nonfinite:2:nan")
    rz.reset_faults()
    net, trainer = _train_gluon(optimizer, steps=4)
    assert trainer.guard.bad_steps >= 1
    assert trainer.guard.skipped_steps == 0
    flat = np.concatenate([p.data().asnumpy().ravel()
                           for p in net.collect_params().values()])
    assert not np.all(np.isfinite(flat))


def test_trainer_interval_observes_offread_bad_steps(monkeypatch):
    """MXTPU_GUARD_INTERVAL > 1 on the fused path: a bad step landing
    BETWEEN host reads is dropped on device by the in-jit select and
    still observed at the next read via the on-device bad counter —
    exact skipped count and num_update compensation, one read per
    window."""
    monkeypatch.setenv("MXTPU_NONFINITE_POLICY", "skip")
    monkeypatch.setenv("MXTPU_GUARD_INTERVAL", "4")
    # steps 2 and 3 go bad; the window read happens at step 4
    monkeypatch.setenv("MXTPU_FAULT_SPEC",
                       "grad:nonfinite:2:nan,grad:nonfinite:3:inf")
    rz.reset_faults()
    net, trainer = _train_gluon("sgd", steps=8)
    for p in net.collect_params().values():
        assert np.all(np.isfinite(p.data().asnumpy()))
    assert trainer.guard.checks == 2            # steps 4 and 8 read
    assert trainer.guard.skipped_steps == 2     # both observed
    assert trainer.guard.bad_steps == 1         # one bad *window*
    # 8 scheduled_lr advances minus the 2 device-dropped updates
    assert trainer._optimizer.num_update == 6


def test_trainer_dynamic_loss_scale_backoff_and_regrowth(monkeypatch):
    monkeypatch.setenv("MXTPU_LOSS_SCALE", "8")
    monkeypatch.setenv("MXTPU_LOSS_SCALE_DYNAMIC", "1")
    monkeypatch.setenv("MXTPU_LOSS_SCALE_WINDOW", "2")
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "grad:nonfinite:2:nan")
    rz.reset_faults()
    net, trainer = _train_gluon("sgd", steps=7)
    # dynamic scaling promoted the guard to skip-on-overflow
    assert trainer.guard.policy == "skip"
    scaler = trainer._scaler
    assert scaler.num_backoffs == 1         # step 2 overflowed: 8->4
    assert scaler.num_growths == 2          # 4 -> 8 -> 16 over two
    assert scaler.scale == 16.0             # windows of good steps
    for p in net.collect_params().values():
        assert np.all(np.isfinite(p.data().asnumpy()))


def test_trainer_static_loss_scale_matches_unscaled(monkeypatch):
    """A static loss scale must be arithmetically invisible: loss x8
    at record time, grads /8 in step()."""
    net_a, _ = _train_gluon("sgd", steps=3)
    monkeypatch.setenv("MXTPU_LOSS_SCALE", "8")
    net_b, trainer = _train_gluon("sgd", steps=3)
    assert trainer.loss_scale == 8.0
    for pa, pb in zip(net_a.collect_params().values(),
                      net_b.collect_params().values()):
        np.testing.assert_allclose(pa.data().asnumpy(),
                                   pb.data().asnumpy(),
                                   rtol=2e-5, atol=2e-6)


# ---------------------------------------------------------------- module
def _toy_module_problem(n=64, dim=10, classes=5, batch=16, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.rand(n, dim).astype(np.float32)
    w = rs.rand(dim, classes).astype(np.float32)
    y = (x @ w).argmax(1).astype(np.float32)
    it = mx.io.NDArrayIter(x, y, batch_size=batch,
                           label_name="softmax_label")
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc", num_hidden=classes)
    return it, mx.sym.SoftmaxOutput(net, name="softmax")


@pytest.mark.parametrize("kvstore", ["local", "tpu"])
def test_module_fit_skips_injected_bad_steps(kvstore, monkeypatch):
    monkeypatch.setenv("MXTPU_NONFINITE_POLICY", "skip")
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "grad:nonfinite:2:nan")
    rz.reset_faults()
    it, sym = _toy_module_problem()
    mod = mx.mod.Module(sym, context=mx.cpu())
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        mod.fit(it, num_epoch=2, kvstore=kvstore, optimizer="sgd",
                optimizer_params=dict(learning_rate=0.5),
                initializer=mx.initializer.Xavier())
    arg, _ = mod.get_params()
    for k, v in arg.items():
        assert np.all(np.isfinite(v.asnumpy())), k
    assert mod._guard.skipped_steps == 1
    assert mod._guard.steps == 8            # 2 epochs x 4 batches


def test_module_fit_guard_interval_transfer_budget(monkeypatch):
    monkeypatch.setenv("MXTPU_NONFINITE_POLICY", "skip")
    monkeypatch.setenv("MXTPU_GUARD_INTERVAL", "4")
    it, sym = _toy_module_problem()
    mod = mx.mod.Module(sym, context=mx.cpu())
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        mod.fit(it, num_epoch=2, optimizer="sgd",
                initializer=mx.initializer.Xavier())
    # 8 update steps, interval 4 -> exactly 2 host reads
    assert mod._guard.steps == 8
    assert mod._guard.checks == 2


@pytest.mark.parametrize("kvstore", ["local", "tpu"])
def test_module_fit_rollback_on_divergence(kvstore, monkeypatch,
                                           tmp_path):
    """MXTPU_MAX_BAD_STEPS consecutive bad steps: fit restores the
    newest valid checkpoint — params, optimizer .states, and the
    .data iterator companion — then re-raises DivergedError.  On
    the kvstore='tpu' path the restored values must also displace
    the mesh step's device copies (not be clobbered by a pending
    mesh sync)."""
    monkeypatch.setenv("MXTPU_NONFINITE_POLICY", "skip")
    monkeypatch.setenv("MXTPU_MAX_BAD_STEPS", "3")
    it, sym = _toy_module_problem()
    prefix = str(tmp_path / "ckpt")

    mod = mx.mod.Module(sym, context=mx.cpu())
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        mod.fit(it, num_epoch=1, kvstore=kvstore, optimizer="sgd",
                optimizer_params=dict(learning_rate=0.5),
                initializer=mx.initializer.Xavier())
        it.reset()
        next(it)
        next(it)    # checkpoint mid-epoch: iter positioned at batch 2
        mod.save_checkpoint(prefix, 0, save_optimizer_states=True,
                            data_iter=it)
    saved_cursor = it.state_dict()["cursor"]
    saved = {k: v.asnumpy().copy()
             for k, v in mod.get_params()[0].items()}

    monkeypatch.setenv("MXTPU_FAULT_SPEC", "grad:nonfinite:*:nan")
    rz.reset_faults()
    it2, _ = _toy_module_problem()
    mod2 = mx.mod.Module(sym, context=mx.cpu())
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(rz.DivergedError):
            mod2.fit(it2, num_epoch=2, kvstore=kvstore,
                     optimizer="sgd",
                     optimizer_params=dict(learning_rate=0.5),
                     initializer=mx.initializer.Xavier(),
                     checkpoint_prefix=prefix)
    arg, _ = mod2.get_params()
    for k, v in arg.items():
        np.testing.assert_allclose(v.asnumpy(), saved[k],
                                   err_msg=k)
    # optimizer state restored alongside (momentum-free sgd states
    # exist as an empty-but-valid pickle; the load must not degrade)
    assert mod2._guard.consecutive_bad == 3
    # data iterator resumed at the checkpointed batch position
    assert it2.state_dict()["cursor"] == saved_cursor


def test_module_fit_rollback_without_checkpoint_still_raises(
        monkeypatch):
    monkeypatch.setenv("MXTPU_NONFINITE_POLICY", "skip")
    monkeypatch.setenv("MXTPU_MAX_BAD_STEPS", "2")
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "grad:nonfinite:*:inf")
    rz.reset_faults()
    it, sym = _toy_module_problem()
    mod = mx.mod.Module(sym, context=mx.cpu())
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(rz.DivergedError):
            mod.fit(it, num_epoch=1, optimizer="sgd",
                    initializer=mx.initializer.Xavier())


# ---------------------------------------------------------------- exits
def test_diverged_exithook_distinct_exit_code():
    code = ("import incubator_mxnet_tpu.resilience as rz\n"
            "rz.install_diverged_exithook()\n"
            "raise rz.DivergedError('test divergence')\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([sys.executable, "-c", code],
                       capture_output=True, text=True, timeout=120,
                       env=env, cwd=REPO)
    assert r.returncode == rz.DivergedError.EXIT_CODE, \
        (r.returncode, r.stderr[-500:])
    assert "DivergedError" in r.stderr
    # ordinary exceptions keep the generic code
    r2 = subprocess.run(
        [sys.executable, "-c",
         "import incubator_mxnet_tpu.resilience as rz\n"
         "rz.install_diverged_exithook()\nraise ValueError('x')\n"],
        capture_output=True, text=True, timeout=120, env=env,
        cwd=REPO)
    assert r2.returncode == 1


def test_launch_exports_sentinel_flags():
    """--nonfinite-policy/--max-bad-steps reach the worker env (the
    yarn print mode shows the exact per-worker command line)."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--launcher", "yarn",
         "--nonfinite-policy", "skip", "--max-bad-steps", "5",
         "--", "python", "train.py"],
        capture_output=True, text=True, timeout=60, cwd=REPO)
    assert r.returncode == 0, r.stderr
    assert "MXTPU_NONFINITE_POLICY=skip" in r.stdout
    assert "MXTPU_MAX_BAD_STEPS=5" in r.stdout


# ---------------------------------------------------------------- multi
def test_multirank_skip_decisions_agree():
    """Two ranks, fault injection on rank 0 only: the allreduced
    finiteness flag must make BOTH ranks skip the same steps."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--", sys.executable,
         os.path.join(REPO, "tests", "dist_worker_sentinel.py")],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=REPO)
    out = r.stdout + r.stderr
    if r.returncode != 0 and (
            "implemented" in out or "UNIMPLEMENTED" in out):
        pytest.skip("multi-process collectives unavailable on this "
                    "backend")
    assert r.returncode == 0, out[-3000:]
    assert "SENTINEL_OK rank 0" in out, out[-3000:]
    assert "SENTINEL_OK rank 1" in out, out[-3000:]


# ---------------------------------------------------------------- satellites
def test_clip_global_norm_nonfinite_safe():
    from incubator_mxnet_tpu.gluon.utils import clip_global_norm
    a = mx.nd.array(np.full((4,), 3.0, np.float32))
    b = mx.nd.array(np.full((4,), 4.0, np.float32))
    total = clip_global_norm([a, b], max_norm=1.0)
    assert math.isclose(total, 10.0, rel_tol=1e-5)
    assert np.allclose(a.asnumpy(), 3.0 / 10.0, atol=1e-5)

    bad = mx.nd.array(np.array([1.0, np.nan], np.float32))
    ok = mx.nd.array(np.full((2,), 2.0, np.float32))
    before = ok.asnumpy().copy()
    with pytest.warns(RuntimeWarning, match="non-finite"):
        total = clip_global_norm([ok, bad], max_norm=1.0)
    assert not math.isfinite(total)         # caller can skip the step
    np.testing.assert_allclose(ok.asnumpy(), before)  # untouched
    # check_isfinite=False keeps the legacy silent behavior
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        total = clip_global_norm([ok, bad], max_norm=1.0,
                                 check_isfinite=False)
    assert not math.isfinite(total)


def test_metric_running_sums_exclude_nonfinite_batches():
    m = mx.metric.MSE()
    good_l = [mx.nd.array(np.ones((4, 1), np.float32))]
    good_p = [mx.nd.array(np.full((4, 1), 2.0, np.float32))]
    m.update(good_l, good_p)
    with pytest.warns(RuntimeWarning, match="non-finite"):
        m.update([mx.nd.array(np.array([[np.nan]] * 4, np.float32))],
                 good_p)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")     # warned once only
        m.update([mx.nd.array(np.array([[np.inf]] * 4, np.float32))],
                 good_p)
    m.update(good_l, good_p)
    name, val = m.get()
    assert math.isfinite(val) and math.isclose(val, 1.0)
    assert m.num_nonfinite == 2
    m.reset()
    assert m.num_nonfinite == 0

    # cross-entropy path: NaN probabilities are excluded the same way
    ce = mx.metric.CrossEntropy()
    label = [mx.nd.array(np.zeros((2,), np.float32))]
    ce.update(label, [mx.nd.array(np.full((2, 2), 0.5, np.float32))])
    with pytest.warns(RuntimeWarning):
        ce.update(label,
                  [mx.nd.array(np.full((2, 2), np.nan, np.float32))])
    assert math.isfinite(ce.get()[1])
    assert ce.num_nonfinite == 1


def test_monitor_nonfinite_count_localizes_bad_op():
    from incubator_mxnet_tpu import monitor as mon_mod
    mon = mx.monitor.Monitor(interval=1,
                             stat_func=mon_mod.nonfinite_count)
    mon.install()
    try:
        mon.tic()
        ok = mx.nd.exp(mx.nd.array(np.zeros((2,), np.float32)))
        bad = mx.nd.log(mx.nd.array(np.array([-1.0, 1.0], np.float32)))
        _ = ok + bad
    finally:
        mon.uninstall()
    rows = {name: stat for _, name, stat in mon.queue}
    assert rows.get("exp") == 0              # clean op reads 0
    assert rows.get("log") == 1              # first poisoned op
    assert any(name not in ("exp", "log") and stat >= 1
               for name, stat in rows.items()
               if name != "exp")             # contamination flows on


def test_monitor_default_stat_nan_tolerant():
    from incubator_mxnet_tpu.monitor import _default_stat
    x = np.array([1.0, -3.0, np.nan, np.inf], np.float32)
    assert math.isclose(_default_stat(x), 2.0)   # mean over finite
    assert math.isnan(_default_stat(np.array([np.nan], np.float32)))
    assert math.isclose(_default_stat(np.array([2, 4], np.int32)),
                        3.0)


def test_lint_flags_host_sync_in_guarded_hot_paths(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "ci"))
    try:
        import lint
    finally:
        sys.path.pop(0)
    d = tmp_path / "incubator_mxnet_tpu" / "gluon"
    d.mkdir(parents=True)
    f = d / "trainer.py"
    f.write_text("def step(self, n):\n"
                 "    v = self._flag.item()\n"
                 "    return v\n")
    problems = lint.check_file(f)
    assert any("host sync" in p for p in problems), problems
    # sync-ok annotation exempts the guard-interval read
    f.write_text("def step(self, n):\n"
                 "    v = self._flag.item()  # sync-ok: interval\n"
                 "    return v\n")
    assert not any("host sync" in p for p in lint.check_file(f))
    # helper functions outside the hot set are untouched
    f.write_text("def save_states(self):\n"
                 "    return self._flag.item()\n")
    assert not any("host sync" in p for p in lint.check_file(f))
    # jnp.asarray (host->device) is not flagged; np.asarray is
    f.write_text("import numpy as np\nimport jax.numpy as jnp\n"
                 "def update(self, g):\n"
                 "    a = jnp.asarray(g)\n"
                 "    b = np.asarray(g)\n"
                 "    return a, b\n")
    problems = lint.check_file(f)
    assert sum("host sync" in p for p in problems) == 1, problems
