"""User-facing custom-kernel registration (the mx.rtc analog;
ref: python/mxnet/rtc.py:1, include/mxnet/rtc.h:136).

A user writes a Pallas kernel, registers it, and it behaves like any
built-in op: eager nd, symbolic graphs through the Executor, Gluon
hybridize, and autograd via a custom VJP.  On this CPU host the
kernel runs through the Pallas interpreter (auto-detected)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, nd, rtc


@pytest.fixture
def scale_op():
    def scale_kernel(x_ref, o_ref, *, alpha):
        o_ref[...] = x_ref[...] * alpha

    fn = rtc.compile_kernel(
        scale_kernel,
        out_shape=lambda x, alpha=2.0: jax.ShapeDtypeStruct(
            x.shape, x.dtype))
    rtc.register(
        "test_rtc_scale", fn, arg_names=["data"],
        vjp=(lambda x, alpha=2.0: (fn(x, alpha=alpha), None),
             lambda alpha, res, g: (g * (alpha * 10),)))
    # deliberately wrong-by-10x gradient proves the custom VJP (not
    # autodiff through the kernel) is what backward uses
    yield
    rtc.unregister("test_rtc_scale")


def test_eager_and_grad(scale_op):
    x = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    y = nd.test_rtc_scale(x, alpha=3.0)
    np.testing.assert_allclose(y.asnumpy(), x.asnumpy() * 3.0)

    x.attach_grad()
    with autograd.record():
        y = nd.test_rtc_scale(x, alpha=3.0)
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(),
                               np.full((2, 3), 30.0))


def test_symbolic_executor(scale_op):
    data = mx.sym.Variable("data")
    s = mx.sym.test_rtc_scale(data, alpha=4.0)
    out = s.eval(mx.cpu(0), data=nd.ones((3, 2)))[0]
    np.testing.assert_allclose(out.asnumpy(), np.full((3, 2), 4.0))


def test_gluon_hybridize(scale_op):
    class Net(mx.gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            return F.test_rtc_scale(x, alpha=2.0) + 1

    net = Net()
    net.hybridize()
    out = net(nd.ones((2, 2)))
    np.testing.assert_allclose(out.asnumpy(), np.full((2, 2), 3.0))


def test_register_plain_jax_fn_autodiff():
    rtc.register("test_rtc_gelu2",
                 lambda x: jax.nn.gelu(x) * 2)
    try:
        x = nd.array(np.linspace(-2, 2, 8, dtype=np.float32))
        x.attach_grad()
        with autograd.record():
            y = nd.test_rtc_gelu2(x)
        y.backward()
        g = jax.grad(lambda v: (jax.nn.gelu(v) * 2).sum())(
            jnp.asarray(x.asnumpy()))
        np.testing.assert_allclose(x.grad.asnumpy(), np.asarray(g),
                                   rtol=1e-5)
    finally:
        rtc.unregister("test_rtc_gelu2")


def test_register_rejects_shadowing():
    with pytest.raises(ValueError, match="already exists"):
        rtc.register("relu", lambda x: x)


def test_tiled_kernel_with_grid():
    from jax.experimental import pallas as pl

    def addone_kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...] + 1.0

    fn = rtc.compile_kernel(
        addone_kernel,
        out_shape=lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
        grid=lambda x: (x.shape[0] // 8,),
        in_specs=lambda x: [pl.BlockSpec(
            (8, x.shape[1]), lambda i: (i, 0))],
        out_specs=lambda x: pl.BlockSpec(
            (8, x.shape[1]), lambda i: (i, 0)))
    x = jnp.zeros((32, 16), jnp.float32)
    np.testing.assert_allclose(np.asarray(fn(x)), np.ones((32, 16)))


def test_alias_conflict_leaves_registry_clean():
    from incubator_mxnet_tpu.ops.registry import OPS
    with pytest.raises(ValueError, match="conflict"):
        rtc.register("test_rtc_fresh", lambda x: x,
                     aliases=("relu",))
    assert "test_rtc_fresh" not in OPS
    # a corrected retry must succeed
    rtc.register("test_rtc_fresh", lambda x: x)
    rtc.unregister("test_rtc_fresh")


def test_vjp_uses_defaults_when_params_omitted():
    """Backward with a defaulted static param: the bwd rule must see
    the fwd rule's default, not crash on arity (review regression)."""
    def scale_kernel(x_ref, o_ref, *, alpha):
        o_ref[...] = x_ref[...] * alpha

    fn = rtc.compile_kernel(
        scale_kernel,
        out_shape=lambda x, alpha=2.0: jax.ShapeDtypeStruct(
            x.shape, x.dtype))
    rtc.register(
        "test_rtc_defscale", fn, arg_names=["data"],
        vjp=(lambda x, alpha=2.0: (fn(x, alpha=alpha), None),
             lambda alpha, res, g: (g * alpha,)))
    try:
        x = nd.array(np.ones((2, 2), np.float32))
        x.attach_grad()
        with autograd.record():
            y = nd.test_rtc_defscale(x)      # alpha omitted -> 2.0
        y.backward()
        np.testing.assert_allclose(x.grad.asnumpy(),
                                   np.full((2, 2), 2.0))
    finally:
        rtc.unregister("test_rtc_defscale")


def test_aliases_attach_and_unregister():
    from incubator_mxnet_tpu.ops.registry import OPS
    rtc.register("test_rtc_primary", lambda x: x * 2,
                 aliases=("test_rtc_alias",))
    try:
        out = nd.test_rtc_alias(nd.array(np.ones(3, np.float32)))
        np.testing.assert_allclose(out.asnumpy(), 2.0)
        s = mx.sym.test_rtc_alias(mx.sym.Variable("x"))
        assert s is not None
    finally:
        rtc.unregister("test_rtc_primary")
    assert "test_rtc_primary" not in OPS
    assert "test_rtc_alias" not in OPS
    assert not hasattr(nd, "test_rtc_alias")
    # full re-registration under both names succeeds
    rtc.register("test_rtc_primary", lambda x: x,
                 aliases=("test_rtc_alias",))
    rtc.unregister("test_rtc_primary")


def test_register_warns_when_arg_names_uninferrable():
    """compile_kernel wrappers take *arrays, so multi-input kernels
    without explicit arg_names would silently register as 1-ary
    symbolically (advisor r4) — the user gets a warning."""
    import warnings as _warnings

    def star_only(*arrays):
        return arrays[0] + arrays[1]

    with _warnings.catch_warnings(record=True) as rec:
        _warnings.simplefilter("always")
        rtc.register("test_rtc_star", star_only)
    try:
        assert any("arg_names" in str(w.message) for w in rec), \
            [str(w.message) for w in rec]
    finally:
        rtc.unregister("test_rtc_star")

    # explicit arg_names: no warning
    with _warnings.catch_warnings(record=True) as rec:
        _warnings.simplefilter("always")
        rtc.register("test_rtc_star2", star_only,
                     arg_names=["a", "b"])
    try:
        assert not any("arg_names" in str(w.message) for w in rec)
    finally:
        rtc.unregister("test_rtc_star2")
