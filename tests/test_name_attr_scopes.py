"""Name/attribute scopes (ref: python/mxnet/name.py NameManager/
Prefix, python/mxnet/attribute.py AttrScope)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import sym


def test_prefix_scope_names():
    data = sym.Variable("data")
    with mx.name.Prefix("stage1_"):
        fc = sym.FullyConnected(data, num_hidden=4)
    assert fc.list_outputs()[0].startswith("stage1_fullyconnected")
    args = fc.list_arguments()
    assert any(a.startswith("stage1_") and a.endswith("_weight")
               for a in args), args


def test_name_manager_scope_restarts_counters():
    data = sym.Variable("data")
    with mx.name.NameManager():
        a = sym.Activation(data, act_type="relu")
        b = sym.Activation(data, act_type="relu")
    assert a.list_outputs()[0].startswith("activation0")
    assert b.list_outputs()[0].startswith("activation1")
    with mx.name.NameManager():      # fresh scope, fresh counters
        c = sym.Activation(data, act_type="relu")
    assert c.list_outputs()[0].startswith("activation0")


def test_attr_scope_tags_symbols():
    with mx.AttrScope(ctx_group="dev1", lr_mult="0.1"):
        v = sym.Variable("w")
        fc = sym.FullyConnected(v, num_hidden=2, name="fc")
    assert v.attr("ctx_group") == "dev1"
    assert v.attr("lr_mult") == "0.1"
    assert fc.attr("ctx_group") == "dev1"
    # nesting: inner scope wins, outer restored on exit
    with mx.AttrScope(ctx_group="a"):
        with mx.AttrScope(ctx_group="b"):
            u = sym.Variable("u")
        w2 = sym.Variable("w2")
    assert u.attr("ctx_group") == "b"
    assert w2.attr("ctx_group") == "a"
    # explicit attr beats the scope
    with mx.AttrScope(ctx_group="a"):
        z = sym.Variable("z", attr={"ctx_group": "explicit"})
    assert z.attr("ctx_group") == "explicit"


def test_attr_scope_rejects_non_string():
    with pytest.raises(ValueError):
        mx.AttrScope(lr_mult=0.1)


def test_attr_scope_json_roundtrip(tmp_path):
    with mx.AttrScope(ctx_group="dev2"):
        data = sym.Variable("data")
        net = sym.FullyConnected(data, num_hidden=2, name="fc")
    s2 = sym.load_json(net.tojson())
    assert s2.attr("ctx_group") == "dev2"


def test_executor_works_under_scopes():
    with mx.name.Prefix("p_"), mx.AttrScope(tag="x"):
        data = sym.Variable("data")
        net = sym.FullyConnected(data, num_hidden=3)
    ex = net.simple_bind(mx.cpu(), data=(2, 4))
    out = ex.forward()[0]
    assert out.shape == (2, 3)


def test_attr_scope_lr_mult_reaches_optimizer():
    from incubator_mxnet_tpu.optimizer import SGD
    with mx.AttrScope(__lr_mult__="0.1", __wd_mult__="0.5"):
        data = sym.Variable("data")
        net = sym.FullyConnected(data, num_hidden=3, name="fc")
    opt = SGD(sym=net)
    assert opt.lr_mult.get("fc_weight") == 0.1, opt.lr_mult
    assert opt.wd_mult.get("fc_weight") == 0.5, opt.wd_mult
    # the dunder spelling via Variable kwargs still works
    v = sym.Variable("w", lr_mult=0.2)
    opt2 = SGD(sym=sym.FullyConnected(
        sym.Variable("d"), weight=v, num_hidden=2, name="g"))
    assert opt2.lr_mult.get("w") == 0.2, opt2.lr_mult


def test_duplicate_arg_names_rejected_at_bind():
    data = sym.Variable("data")
    # two fresh scopes -> both layers named fullyconnected0,
    # deterministically colliding regardless of the global counter
    with mx.name.NameManager():
        a = sym.FullyConnected(data, num_hidden=2)
    with mx.name.NameManager():
        b = sym.FullyConnected(a, num_hidden=2)
    with pytest.raises(ValueError, match="duplicate argument"):
        b.simple_bind(mx.cpu(), data=(2, 3))
