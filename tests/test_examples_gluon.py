"""Appendix-C example workloads, round 5 batch (ref:
example/gluon/*, example/multi-task, example/nce-loss,
example/model-parallel-lstm, example/speech_recognition,
example/vae, example/recommenders, example/memcost).

Each example asserts its own convergence/behavior gate in --quick
mode; these tests run them exactly as a user would — a fresh
``python examples/<name>.py --quick`` subprocess on the 8-virtual-
device CPU mesh (MXTPU_FORCE_CPU).  Subprocess isolation is load-
bearing, not style: accumulating a dozen example workloads' compiled
programs in one process segfaulted XLA:CPU's compiler on the CTC
scan-transpose (deterministically, only after ~8 prior tests), and a
fresh interpreter per workload is also the honest way to test a
script-shaped artifact.
"""
import json
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_EXAMPLES = os.path.join(_REPO, "examples")

_QUICK = [
    "word_language_model",
    "model_parallel_lstm",
    "memcost",
    "nce_loss",
    "matrix_factorization",
    "multi_task",
    "vae",
    "cnn_text_classification",
    "speech_ctc",
    "dcgan",
    "actor_critic",
    "adversary_fgsm",
    "fcn_segmentation",
    "svm_mnist",
    "bi_lstm_sort",
    "stochastic_depth",
    "profiler_demo",
    "captcha_crnn",
    "neural_style",
]


@pytest.mark.parametrize("name", _QUICK)
def test_example_quick(name):
    env = dict(os.environ)
    env["MXTPU_FORCE_CPU"] = "1"
    env.pop("JAX_PLATFORMS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(_EXAMPLES, f"{name}.py"),
         "--quick"],
        capture_output=True, text=True, timeout=900, env=env,
        cwd=_REPO)
    assert r.returncode == 0, (
        f"{name} --quick failed (rc={r.returncode})\n"
        f"stdout tail: {r.stdout[-1500:]}\n"
        f"stderr tail: {r.stderr[-1500:]}")
    # the last stdout line is the example's JSON summary
    last = [l for l in r.stdout.strip().splitlines()
            if l.startswith("{")][-1]
    json.loads(last)   # parseable summary contract
