"""Aux subsystems: profiler, monitor, custom ops, visualization
(model: reference tests/python/unittest/test_profiler.py,
test_operator.py CustomOp cases, test_viz.py)."""
import json
import os
import tempfile

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, autograd


def test_profiler_collects_and_dumps():
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "prof.json")
        mx.profiler.set_config(filename=path, mode="sync")
        mx.profiler.set_state("run")
        a = nd.ones((8, 8))
        b = (a * 2 + 1).sum()
        b.wait_to_read()
        mx.profiler.set_state("stop")
        out = mx.profiler.dump_profile()
        assert out == path
        with open(path) as f:
            trace = json.load(f)
        # the dump also carries chrome-tracing metadata ('M') and
        # telemetry counter ('C') events, which have no duration —
        # only complete ('X') events do (docs/observability.md)
        ops = [e for e in trace["traceEvents"] if e["ph"] == "X"]
        names = [e["name"] for e in ops]
        assert len(names) >= 2
        assert all(e["dur"] >= 0 for e in ops)
        assert any("sum" in n or "mul" in n or "plus" in n
                   for n in names), names
        assert any(e["ph"] == "M" and e["name"] == "process_name"
                   for e in trace["traceEvents"])


def test_monitor_observes_ops():
    mon = mx.Monitor(interval=1, pattern=".*").install()
    try:
        mon.tic()
        x = nd.ones((4, 4))
        y = x * 3
        y.wait_to_read()
        rows = mon.toc()
        assert rows, "monitor saw no ops"
        assert any(abs(stat - 3.0) < 1e-6 for _, _, stat in rows)
    finally:
        mon.uninstall()


@mx.operator.register("scale2")
class Scale2Prop(mx.operator.CustomOpProp):
    def __init__(self, factor=2.0):
        super().__init__(need_top_grad=True)
        self.factor = float(factor)

    def create_operator(self, ctx, in_shapes, in_dtypes):
        factor = self.factor

        class Scale2(mx.operator.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                self.assign(out_data[0], req[0],
                            in_data[0] * factor)

            def backward(self, req, out_grad, in_data, out_data,
                         in_grad, aux):
                self.assign(in_grad[0], req[0],
                            out_grad[0] * factor)
        return Scale2()


def test_custom_op_forward_backward():
    x = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    y = nd.Custom(x, op_type="scale2", factor=3.0)
    np.testing.assert_allclose(y.asnumpy(), x.asnumpy() * 3.0)
    x.attach_grad()
    with autograd.record():
        z = nd.Custom(x, op_type="scale2", factor=3.0).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(),
                               np.full((2, 3), 3.0))


def test_custom_op_in_hybrid_jit():
    """Custom ops must survive jit (pure_callback path)."""
    import jax

    @jax.jit
    def f(v):
        from incubator_mxnet_tpu.operator import custom
        return custom(v, op_type="scale2", factor=4.0)

    import jax.numpy as jnp
    out = f(jnp.ones((3,)))
    np.testing.assert_allclose(np.asarray(out), np.full((3,), 4.0))


def test_custom_op_in_symbol_executor():
    data = mx.sym.Variable("data")
    out = mx.sym.Custom(data, op_type="scale2", factor=5.0,
                        name="my_custom")
    exe = out.simple_bind(None, data=(2, 2))
    res = exe.forward(is_train=False, data=nd.ones((2, 2)))
    np.testing.assert_allclose(res[0].asnumpy(),
                               np.full((2, 2), 5.0))


def test_print_summary():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act, num_hidden=4, name="fc2")
    out = mx.sym.SoftmaxOutput(fc2, name="softmax")
    total = mx.visualization.print_summary(
        out, shape={"data": (8, 10)})
    # fc1: 10*16+16, fc2: 16*4+4
    assert total == 10 * 16 + 16 + 16 * 4 + 4


def test_autograd_function():
    class Sigmoid(autograd.Function):
        def forward(self, x):
            y = 1 / (1 + nd.exp(-x))
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            y, = self.saved_tensors
            return dy * y * (1 - y)

    x = nd.array(np.array([0.0, 1.0, -2.0], np.float32))
    x.attach_grad()
    f = Sigmoid()
    with autograd.record():
        y = f(x)
    y.backward()
    sig = 1 / (1 + np.exp(-x.asnumpy()))
    np.testing.assert_allclose(x.grad.asnumpy(), sig * (1 - sig),
                               rtol=1e-5)


def test_monitor_compiled_path_per_op_rows():
    """Module.install_monitor streams EVERY graph op's outputs (ref:
    MXExecutorSetMonitorCallback), not just the heads, and
    uninstall restores the fused executable."""
    import incubator_mxnet_tpu as mx

    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc1", num_hidden=8)
    net = mx.sym.Activation(net, name="relu1", act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=4)
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[mx.io.DataDesc("data", (2, 6))],
             label_shapes=[mx.io.DataDesc("softmax_label", (2,))],
             for_training=True)
    mod.init_params(mx.initializer.Xavier())
    mon = mx.Monitor(interval=1, pattern=".*")
    mod.install_monitor(mon)
    mon.tic()
    mod.forward(mx.io.DataBatch(
        [mx.nd.array(np.random.RandomState(0)
                     .rand(2, 6).astype("float32"))],
        [mx.nd.array(np.zeros(2, "float32"))]), is_train=False)
    rows = mon.toc()
    names = {r[1] for r in rows}
    assert any("fc1" in n for n in names), names
    assert any("relu1" in n for n in names), names
    assert any("softmax" in n for n in names), names
    assert all(np.isfinite(r[2]) for r in rows)
    mon.uninstall()
    assert mod._exec._monitor_cb is None
    mod.forward(mx.io.DataBatch(
        [mx.nd.ones((2, 6))], [mx.nd.zeros((2,))]), is_train=False)


def test_monitor_streams_during_training_step():
    """The fit path calls forward_backward, which must also run
    tapped while a monitor is installed (review regression: only
    forward() was tapped, so fit(monitor=...) produced no rows)."""
    import incubator_mxnet_tpu as mx

    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc1", num_hidden=4)
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[mx.io.DataDesc("data", (2, 3))],
             label_shapes=[mx.io.DataDesc("softmax_label", (2,))],
             for_training=True)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd")
    mon = mx.Monitor(interval=1)
    mod.install_monitor(mon)
    mon.tic()
    mod.forward_backward(mx.io.DataBatch(
        [mx.nd.ones((2, 3))], [mx.nd.zeros((2,))]))
    mod.update()
    rows = mon.toc()
    names = [r[1] for r in rows]
    assert any("fc1" in n for n in names), names
    # exactly once per op per batch (review regression: forward +
    # backward's replay each tapped, doubling every row)
    fc1_rows = [n for n in names if "fc1" in n]
    assert len(fc1_rows) == 1, fc1_rows
    mon.uninstall()
    # untapped training still works after uninstall
    mod.forward_backward(mx.io.DataBatch(
        [mx.nd.ones((2, 3))], [mx.nd.zeros((2,))]))


def test_naive_engine_matches_async_results():
    """SURVEY §5 race-detection analog: the async-dispatch schedule
    must produce bit-identical results to serial naive mode (the
    reference validates its engine with a randomized scheduled-vs-
    serial stress test, threaded_engine_test.cc:122)."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, engine, nd

    def workload():
        mx.random.seed(7)
        rs = np.random.RandomState(7)
        a = nd.array(rs.rand(8, 8).astype("float32"))
        b = nd.array(rs.rand(8, 8).astype("float32"))
        a.attach_grad()
        with autograd.record():
            c = nd.dot(a, b)
            d = nd.relu(c) + nd.sigmoid(c)
            # in-place style mutation + dependent reads, the classic
            # RAW/WAR shapes the reference's var protocol guards
            e = d.copy()
            e[:] = e * 2 - d
            loss = nd.sum(e * e)
        loss.backward()
        return (loss.asnumpy().copy(), e.asnumpy().copy(),
                a.grad.asnumpy().copy())

    engine.set_engine_type("async")
    async_res = workload()
    engine.set_engine_type("naive")
    try:
        naive_res = workload()
    finally:
        engine.set_engine_type("async")
    for x, y in zip(async_res, naive_res):
        np.testing.assert_array_equal(x, y)


def test_monitor_tapped_mode_warns(caplog):
    """Arming a monitor on an executor flips forward to un-jitted
    per-op evaluation (~100x slower); a user must be told
    (VERDICT r4 weak #5)."""
    import logging

    import incubator_mxnet_tpu as mx

    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc", num_hidden=4)
    mod = mx.mod.Module(net, context=mx.cpu(),
                        label_names=[])
    mod.bind(data_shapes=[mx.io.DataDesc("data", (2, 3))],
             for_training=False)
    mod.init_params(mx.initializer.Xavier())
    logger = logging.getLogger("mxtpu")
    logger.propagate = True   # let caplog's root handler see it
    try:
        with caplog.at_level(logging.WARNING, logger="mxtpu"):
            mod._exec.set_monitor_callback(lambda name, arrs: None)
        assert any("un-jitted" in r.message and "slower" in r.message
                   for r in caplog.records), caplog.records
        # disarming is silent
        caplog.clear()
        with caplog.at_level(logging.WARNING, logger="mxtpu"):
            mod._exec.set_monitor_callback(None)
        assert not caplog.records
    finally:
        logger.propagate = False


def test_tensorboard_log_metrics_callback(tmp_path):
    """Contrib TensorBoard bridge (ref: contrib/tensorboard.py
    LogMetricsCallback): metrics stream to a writer; the JSONL
    fallback is asserted directly so the test needs no tensorboard."""
    import json as _json
    from collections import namedtuple

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.contrib.tensorboard import (
        LogMetricsCallback, _JsonlWriter)

    metric = mx.metric.Accuracy()
    metric.update([mx.nd.array([0.0, 1.0])],
                  [mx.nd.array([[0.9, 0.1], [0.2, 0.8]])])
    Param = namedtuple("BatchEndParam",
                       ["epoch", "nbatch", "eval_metric"])
    logdir = str(tmp_path / "tb")
    cb = LogMetricsCallback(
        logdir, prefix="train",
        summary_writer=_JsonlWriter(logdir))
    cb(Param(epoch=0, nbatch=1, eval_metric=metric))
    cb(Param(epoch=0, nbatch=2, eval_metric=metric))
    files = [f for f in os.listdir(logdir) if f.endswith(".jsonl")]
    assert files
    rows = [_json.loads(l) for l in
            open(os.path.join(logdir, files[0]))]
    assert [r["step"] for r in rows] == [1, 2]
    assert all(r["tag"] == "train-accuracy" for r in rows)
    assert all(r["value"] == 1.0 for r in rows)

    # the real torch SummaryWriter path, when available
    try:
        from torch.utils.tensorboard import SummaryWriter  # noqa
    except Exception:
        return
    cb2 = LogMetricsCallback(str(tmp_path / "tb2"), prefix="t")
    cb2(Param(epoch=0, nbatch=1, eval_metric=metric))
    cb2.writer.flush()
    assert os.listdir(str(tmp_path / "tb2"))


def test_contrib_autograd_legacy_api():
    """contrib.autograd keeps the pre-1.0 experimental names alive
    over the core tape (ref: python/mxnet/contrib/autograd.py)."""
    import numpy as np

    from incubator_mxnet_tpu import nd
    from incubator_mxnet_tpu.contrib import autograd as cag

    @cag.grad_and_loss
    def f(a, b):
        return a * b + a

    x = nd.array(np.array([1., 2., 3.], np.float32))
    y = nd.array(np.array([4., 5., 6.], np.float32))
    grads, _ = f(x, y)
    np.testing.assert_allclose(grads[0].asnumpy(), [5., 6., 7.])
    np.testing.assert_allclose(grads[1].asnumpy(), [1., 2., 3.])

    x2 = nd.array(np.array([2., 3.], np.float32))
    x2.attach_grad()
    with cag.train_section():
        z = nd.sum(x2 * x2)
    cag.compute_gradient([z])
    np.testing.assert_allclose(x2.grad.asnumpy(), [4., 6.])


def test_enable_compile_cache_persists(tmp_path):
    """utils.platform.enable_compile_cache points jax's persistent
    executable cache at a directory; a compile must leave an entry
    (the mechanism that lets a timed-out cold compile over the
    tunnel seed the next bench attempt)."""
    import jax
    import jax.numpy as jnp

    from incubator_mxnet_tpu.utils.platform import \
        enable_compile_cache

    cachedir = str(tmp_path / "xla-cache")
    assert enable_compile_cache(cachedir)
    try:
        # unique shape so the compile can't be a jit-cache hit
        x = jnp.ones((13, 29), jnp.float32)
        jax.block_until_ready(jax.jit(lambda a: (a @ a.T).sum())(x))
        entries = os.listdir(cachedir)
        assert entries, "no persistent cache entry written"
    finally:
        jax.config.update("jax_compilation_cache_dir", None)
