"""Aux subsystems: profiler, monitor, custom ops, visualization
(model: reference tests/python/unittest/test_profiler.py,
test_operator.py CustomOp cases, test_viz.py)."""
import json
import os
import tempfile

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, autograd


def test_profiler_collects_and_dumps():
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "prof.json")
        mx.profiler.set_config(filename=path, mode="sync")
        mx.profiler.set_state("run")
        a = nd.ones((8, 8))
        b = (a * 2 + 1).sum()
        b.wait_to_read()
        mx.profiler.set_state("stop")
        out = mx.profiler.dump_profile()
        assert out == path
        with open(path) as f:
            trace = json.load(f)
        names = [e["name"] for e in trace["traceEvents"]]
        assert len(names) >= 2
        assert all(e["dur"] >= 0 for e in trace["traceEvents"])
        assert any("sum" in n or "mul" in n or "plus" in n
                   for n in names), names


def test_monitor_observes_ops():
    mon = mx.Monitor(interval=1, pattern=".*").install()
    try:
        mon.tic()
        x = nd.ones((4, 4))
        y = x * 3
        y.wait_to_read()
        rows = mon.toc()
        assert rows, "monitor saw no ops"
        assert any(abs(stat - 3.0) < 1e-6 for _, _, stat in rows)
    finally:
        mon.uninstall()


@mx.operator.register("scale2")
class Scale2Prop(mx.operator.CustomOpProp):
    def __init__(self, factor=2.0):
        super().__init__(need_top_grad=True)
        self.factor = float(factor)

    def create_operator(self, ctx, in_shapes, in_dtypes):
        factor = self.factor

        class Scale2(mx.operator.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                self.assign(out_data[0], req[0],
                            in_data[0] * factor)

            def backward(self, req, out_grad, in_data, out_data,
                         in_grad, aux):
                self.assign(in_grad[0], req[0],
                            out_grad[0] * factor)
        return Scale2()


def test_custom_op_forward_backward():
    x = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    y = nd.Custom(x, op_type="scale2", factor=3.0)
    np.testing.assert_allclose(y.asnumpy(), x.asnumpy() * 3.0)
    x.attach_grad()
    with autograd.record():
        z = nd.Custom(x, op_type="scale2", factor=3.0).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(),
                               np.full((2, 3), 3.0))


def test_custom_op_in_hybrid_jit():
    """Custom ops must survive jit (pure_callback path)."""
    import jax

    @jax.jit
    def f(v):
        from incubator_mxnet_tpu.operator import custom
        return custom(v, op_type="scale2", factor=4.0)

    import jax.numpy as jnp
    out = f(jnp.ones((3,)))
    np.testing.assert_allclose(np.asarray(out), np.full((3,), 4.0))


def test_custom_op_in_symbol_executor():
    data = mx.sym.Variable("data")
    out = mx.sym.Custom(data, op_type="scale2", factor=5.0,
                        name="my_custom")
    exe = out.simple_bind(None, data=(2, 2))
    res = exe.forward(is_train=False, data=nd.ones((2, 2)))
    np.testing.assert_allclose(res[0].asnumpy(),
                               np.full((2, 2), 5.0))


def test_print_summary():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act, num_hidden=4, name="fc2")
    out = mx.sym.SoftmaxOutput(fc2, name="softmax")
    total = mx.visualization.print_summary(
        out, shape={"data": (8, 10)})
    # fc1: 10*16+16, fc2: 16*4+4
    assert total == 10 * 16 + 16 + 16 * 4 + 4


def test_autograd_function():
    class Sigmoid(autograd.Function):
        def forward(self, x):
            y = 1 / (1 + nd.exp(-x))
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            y, = self.saved_tensors
            return dy * y * (1 - y)

    x = nd.array(np.array([0.0, 1.0, -2.0], np.float32))
    x.attach_grad()
    f = Sigmoid()
    with autograd.record():
        y = f(x)
    y.backward()
    sig = 1 / (1 + np.exp(-x.asnumpy()))
    np.testing.assert_allclose(x.grad.asnumpy(), sig * (1 - sig),
                               rtol=1e-5)
