"""Hardened input pipeline (docs/data_pipeline.md): resumable
iterator state (exact-batch replay after restart), prefetch
hang-proofing under MXTPU_DATA_TIMEOUT, corrupt-record quarantine
budgets, recordio stream validation/resync, and the .data checkpoint
companions — all on CPU via MXTPU_FAULT_SPEC injection."""
import os
import pickle
import time
import warnings

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import model as M
from incubator_mxnet_tpu import recordio as rio
from incubator_mxnet_tpu import resilience as rz
from incubator_mxnet_tpu.io.io import (DataIter, NDArrayIter,
                                       PrefetchingIter)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("MXTPU_FAULT_SPEC", raising=False)
    rz.reset_faults()
    yield
    rz.reset_faults()


def _drain(it):
    out = []
    try:
        while True:
            out.append(it.next())
    except StopIteration:
        return out


def _data_of(batches):
    return [b.data[0].asnumpy() for b in batches]


# ------------------------------------------------------- NDArrayIter
def test_ndarrayiter_state_roundtrip_replays_exact_batches():
    np.random.seed(11)
    x = np.arange(60).reshape(30, 2).astype(np.float32)
    it = NDArrayIter(x, np.arange(30, dtype=np.float32),
                     batch_size=4, shuffle=True)
    it.reset()
    for _ in range(3):
        it.next()
    state = it.state_dict()
    want = _data_of(_drain(it))

    it2 = NDArrayIter(x, np.arange(30, dtype=np.float32),
                      batch_size=4, shuffle=True)
    it2.load_state_dict(pickle.loads(pickle.dumps(state)))
    it2.reset()            # epoch-start reset must not rewind
    got = _data_of(_drain(it2))
    assert len(got) == len(want)
    for a, b in zip(want, got):
        np.testing.assert_array_equal(a, b)


def test_ndarrayiter_resume_shield_is_one_shot():
    x = np.arange(20).reshape(10, 2).astype(np.float32)
    it = NDArrayIter(x, batch_size=2)
    it.reset()
    it.next()
    state = it.state_dict()
    it2 = NDArrayIter(x, batch_size=2)
    it2.load_state_dict(state)
    it2.reset()                      # absorbed
    assert len(_drain(it2)) == 4     # 5 batches - 1 already served
    it2.reset()                      # real reset: full epoch again
    assert len(_drain(it2)) == 5


def test_ndarrayiter_next_epoch_shuffle_matches_after_resume():
    np.random.seed(3)
    x = np.arange(24).reshape(12, 2).astype(np.float32)
    it = NDArrayIter(x, batch_size=3, shuffle=True)
    it.reset()
    it.next()
    state = it.state_dict()
    _drain(it)
    rng = np.random.get_state()      # pin: both draw one permutation
    it.reset()
    epoch2 = _data_of(_drain(it))

    np.random.seed(999)              # resume must restore RNG itself
    it2 = NDArrayIter(x, batch_size=3, shuffle=True)
    it2.load_state_dict(state)
    _drain(it2)
    np.random.set_state(rng)
    it2.reset()
    epoch2b = _data_of(_drain(it2))
    for a, b in zip(epoch2, epoch2b):
        np.testing.assert_array_equal(a, b)


def test_ndarrayiter_rejects_state_from_bigger_dataset():
    big = NDArrayIter(np.zeros((50, 2), np.float32), batch_size=5)
    small = NDArrayIter(np.zeros((10, 2), np.float32), batch_size=5)
    with pytest.raises(ValueError, match="different dataset"):
        small.load_state_dict(big.state_dict())


def test_dataiter_base_state_dict_is_explicit():
    with pytest.raises(NotImplementedError, match="DataIter"):
        DataIter().state_dict()


# ---------------------------------------------------- PrefetchingIter
def test_prefetching_iter_state_roundtrip():
    np.random.seed(7)
    x = np.arange(80).reshape(40, 2).astype(np.float32)
    pf = PrefetchingIter(NDArrayIter(x, batch_size=6, shuffle=True))
    for _ in range(2):
        pf.next()
    state = pf.state_dict()
    want = _data_of(_drain(pf))

    pf2 = PrefetchingIter(NDArrayIter(x, batch_size=6, shuffle=True))
    pf2.load_state_dict(state)
    got = _data_of(_drain(pf2))
    assert len(got) == len(want)
    for a, b in zip(want, got):
        np.testing.assert_array_equal(a, b)


def test_prefetching_iter_reset_does_not_deadlock_on_full_queue():
    # depth-1 queue + no consumption: the producer is parked in put()
    # when reset() arrives — the old drain-then-join order wedged here
    x = np.arange(400).reshape(200, 2).astype(np.float32)
    pf = PrefetchingIter(NDArrayIter(x, batch_size=2),
                         prefetch_depth=1)
    time.sleep(0.2)          # let the producer fill the queue + block
    start = time.monotonic()
    pf.reset()
    assert time.monotonic() - start < 10
    assert len(_drain(pf)) == 100    # clean epoch after the reset


def test_prefetching_iter_worker_exception_is_typed(monkeypatch):
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "data:prefetch:2:error")
    rz.reset_faults()
    x = np.arange(40).reshape(20, 2).astype(np.float32)
    pf = PrefetchingIter(NDArrayIter(x, batch_size=2))
    pf.next()
    with pytest.raises(rz.DataPipelineError, match="PrefetchingIter"):
        pf.next()
    # terminal: later calls re-raise instead of blocking forever
    with pytest.raises(rz.DataPipelineError):
        pf.next()


def test_prefetch_hang_surfaces_within_data_timeout(monkeypatch):
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "data:prefetch:2:hang")
    monkeypatch.setenv("MXTPU_FAULT_HANG_S", "3600")
    monkeypatch.setenv("MXTPU_DATA_TIMEOUT", "1.5")
    rz.reset_faults()
    x = np.arange(40).reshape(20, 2).astype(np.float32)
    pf = PrefetchingIter(NDArrayIter(x, batch_size=2))
    pf.next()
    start = time.monotonic()
    with pytest.raises(rz.DataPipelineError,
                       match="MXTPU_DATA_TIMEOUT"):
        pf.next()
    assert time.monotonic() - start < 10    # bounded, not eternal


# ------------------------------------------------------- recordio
def _write_rec(path, payloads, idx_path=None):
    if idx_path:
        w = rio.MXIndexedRecordIO(idx_path, path, "w")
        for i, p in enumerate(payloads):
            w.write_idx(i, p)
    else:
        w = rio.MXRecordIO(path, "w")
        for p in payloads:
            w.write(p)
    w.close()


def test_recordio_pickled_writer_appends_not_truncates(tmp_path):
    # regression: __setstate__ reopening a "w" writer with "w"
    # semantics truncated everything it had written pre-pickle
    path = str(tmp_path / "w.rec")
    w = rio.MXRecordIO(path, "w")
    w.write(b"first-record")
    w2 = pickle.loads(pickle.dumps(w))
    w2.write(b"second-record")
    w2.close()
    w.close()
    r = rio.MXRecordIO(path, "r")
    assert r.read() == b"first-record"
    assert r.read() == b"second-record"
    assert r.read() is None
    r.close()


def test_recordio_indexed_pickled_writer_keeps_index(tmp_path):
    path, ipath = str(tmp_path / "i.rec"), str(tmp_path / "i.idx")
    w = rio.MXIndexedRecordIO(ipath, path, "w")
    w.write_idx(0, b"zero")
    w2 = pickle.loads(pickle.dumps(w))
    w2.write_idx(1, b"one!")
    w2.close()
    w.close()
    r = rio.MXIndexedRecordIO(ipath, path, "r")
    assert r.read_idx(1) == b"one!"
    assert r.read_idx(0) == b"zero"
    r.close()


def test_recordio_read_names_file_and_offset_on_corruption(tmp_path):
    path = str(tmp_path / "c.rec")
    _write_rec(path, [b"A" * 10, b"B" * 10])
    raw = bytearray(open(path, "rb").read())
    raw[20:24] = b"XXXX"             # record 2's magic
    open(path, "wb").write(bytes(raw))
    r = rio.MXRecordIO(path, "r")
    assert r.read() == b"A" * 10
    with pytest.raises(IOError, match="c.rec"):
        r.read()
    r.close()


def test_recordio_resync_skips_corrupt_region(tmp_path):
    path = str(tmp_path / "r.rec")
    _write_rec(path, [bytes([65 + i]) * 10 for i in range(5)])
    raw = bytearray(open(path, "rb").read())
    raw[40:44] = b"XXXX"             # record 3's magic (20B/record)
    open(path, "wb").write(bytes(raw))
    r = rio.MXRecordIO(path, "r")
    assert r.read() == b"A" * 10
    assert r.read() == b"B" * 10
    with pytest.raises(IOError):
        r.read()
    assert r.resync() is not None
    assert r.read() == b"D" * 10
    assert r.read() == b"E" * 10
    assert r.read() is None
    r.close()


def test_record_read_fault_injection_corrupts_payload(
        tmp_path, monkeypatch):
    path = str(tmp_path / "f.rec")
    _write_rec(path, [b"aaaa", b"bbbb"])
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "record:read:2:corrupt")
    rz.reset_faults()
    r = rio.MXRecordIO(path, "r")
    assert r.read() == b"aaaa"
    assert r.read() != b"bbbb"       # bit-flipped by injection
    r.close()


# --------------------------------------------------- ImageRecordIter
def _make_image_rec(tmp_path, n=24, bad=()):
    rec = str(tmp_path / "d.rec")
    idx = str(tmp_path / "d.idx")
    w = rio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(n):
        if i in bad:
            w.write_idx(i, rio.pack(rio.IRHeader(0, i, i, 0),
                                    b"not-an-image"))
        else:
            img = np.full((16, 16, 3), (i * 9) % 255, np.uint8)
            w.write_idx(i, rio.pack_img(rio.IRHeader(0, i, i, 0),
                                        img))
    w.close()
    return rec


def _labels_of(it):
    out = []
    try:
        while True:
            b = it.next()
            out.extend(
                b.label[0].asnumpy()[:it.batch_size - b.pad].tolist())
    except StopIteration:
        return out


def test_record_iter_bad_record_raises_with_zero_budget(tmp_path):
    rec = _make_image_rec(tmp_path, bad={5})
    it = mx.image.ImageRecordIter(
        path_imgrec=rec, data_shape=(3, 16, 16), batch_size=8,
        preprocess_threads=2)
    with pytest.raises(rz.DataPipelineError,
                       match="MXTPU_MAX_BAD_RECORDS"):
        _labels_of(it)


def test_record_iter_quarantines_within_budget(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_MAX_BAD_RECORDS", "3")
    rec = _make_image_rec(tmp_path, bad={5, 11})
    it = mx.image.ImageRecordIter(
        path_imgrec=rec, data_shape=(3, 16, 16), batch_size=8,
        preprocess_threads=2)
    with warnings.catch_warnings(record=True) as wl:
        warnings.simplefilter("always")
        labels = _labels_of(it)
    assert sorted(labels) == [float(i) for i in range(24)
                              if i not in (5, 11)]
    assert sum("bad-record budget" in str(x.message)
               for x in wl) == 2


def test_record_iter_budget_exceeded_raises(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_MAX_BAD_RECORDS", "1")
    rec = _make_image_rec(tmp_path, bad={2, 3})
    it = mx.image.ImageRecordIter(
        path_imgrec=rec, data_shape=(3, 16, 16), batch_size=8,
        preprocess_threads=2)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        with pytest.raises(rz.DataPipelineError, match="exceed"):
            _labels_of(it)


def test_record_iter_injected_corruption_is_quarantined(
        tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_MAX_BAD_RECORDS", "2")
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "record:read:4:corrupt")
    rz.reset_faults()
    rec = _make_image_rec(tmp_path)
    it = mx.image.ImageRecordIter(
        path_imgrec=rec, data_shape=(3, 16, 16), batch_size=8,
        preprocess_threads=2)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        labels = _labels_of(it)
    assert len(labels) == 23         # one record lost to injection


def test_record_iter_state_roundtrip_with_shuffle(tmp_path):
    rec = _make_image_rec(tmp_path)
    np.random.seed(21)
    it = mx.image.ImageRecordIter(
        path_imgrec=rec, data_shape=(3, 16, 16), batch_size=4,
        shuffle=True, preprocess_threads=2)
    for _ in range(2):
        it.next()
    state = pickle.loads(pickle.dumps(it.state_dict()))
    want = _labels_of(it)

    np.random.seed(1234)             # state must restore RNG itself
    it2 = mx.image.ImageRecordIter(
        path_imgrec=rec, data_shape=(3, 16, 16), batch_size=4,
        shuffle=True, preprocess_threads=2)
    it2.load_state_dict(state)
    it2.reset()                      # epoch-start reset: absorbed
    got = _labels_of(it2)
    assert want == got


def test_record_iter_resume_exact_after_pre_checkpoint_quarantine(
        tmp_path, monkeypatch):
    """A quarantined record before the checkpoint consumes an extra
    stream slot; the resume coordinate must account for it — no
    record may be delivered twice after a restart (review
    regression)."""
    monkeypatch.setenv("MXTPU_MAX_BAD_RECORDS", "5")
    rec = _make_image_rec(tmp_path, bad={2})     # inside batch 0
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        it = mx.image.ImageRecordIter(
            path_imgrec=rec, data_shape=(3, 16, 16), batch_size=4,
            preprocess_threads=2)
        first = it.next().label[0].asnumpy().tolist()
        assert first == [0.0, 1.0, 3.0, 4.0]     # 2 replaced by 4
        state = it.state_dict()
        want = _labels_of(it)

        it2 = mx.image.ImageRecordIter(
            path_imgrec=rec, data_shape=(3, 16, 16), batch_size=4,
            preprocess_threads=2)
        it2.load_state_dict(state)
        got = _labels_of(it2)
    assert got == want
    assert 4.0 not in got            # the replacement is not re-read
    assert sorted(first + got) == [float(i) for i in range(24)
                                   if i != 2]


def test_record_iter_skip_is_exact_under_quarantine(
        tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_MAX_BAD_RECORDS", "5")
    rec = _make_image_rec(tmp_path, bad={1})
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        it = mx.image.ImageRecordIter(
            path_imgrec=rec, data_shape=(3, 16, 16), batch_size=4,
            preprocess_threads=2)
        ref = [it.next().label[0].asnumpy().tolist()
               for _ in range(3)]
        it2 = mx.image.ImageRecordIter(
            path_imgrec=rec, data_shape=(3, 16, 16), batch_size=4,
            preprocess_threads=2)
        it2.skip(2)                  # replay-discards batches 0-1
        got = it2.next().label[0].asnumpy().tolist()
    assert got == ref[2]


def test_record_iter_sequential_backend_resume(tmp_path):
    rec = _make_image_rec(tmp_path)
    os.unlink(str(tmp_path / "d.idx"))     # force sequential reads
    it = mx.image.ImageRecordIter(
        path_imgrec=rec, data_shape=(3, 16, 16), batch_size=4,
        preprocess_threads=2)
    for _ in range(3):
        it.next()
    state = it.state_dict()
    want = _labels_of(it)
    it2 = mx.image.ImageRecordIter(
        path_imgrec=rec, data_shape=(3, 16, 16), batch_size=4,
        preprocess_threads=2)
    it2.load_state_dict(state)
    got = _labels_of(it2)
    assert want == got


def test_record_iter_hang_injection_times_out(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_FAULT_SPEC", "data:record_batch:2:hang")
    monkeypatch.setenv("MXTPU_FAULT_HANG_S", "3600")
    monkeypatch.setenv("MXTPU_DATA_TIMEOUT", "1.5")
    rz.reset_faults()
    rec = _make_image_rec(tmp_path)
    it = mx.image.ImageRecordIter(
        path_imgrec=rec, data_shape=(3, 16, 16), batch_size=8,
        preprocess_threads=2, prefetch_buffer=1)
    start = time.monotonic()
    with pytest.raises(rz.DataPipelineError,
                       match="ImageRecordIter"):
        for _ in range(4):
            it.next()
    assert time.monotonic() - start < 10


# ------------------------------------------------- .data companions
def test_data_state_companion_saved_and_restored(tmp_path):
    prefix = str(tmp_path / "run")
    np.random.seed(5)
    x = np.arange(60).reshape(30, 2).astype(np.float32)
    it = NDArrayIter(x, batch_size=4, shuffle=True)
    it.reset()
    for _ in range(3):
        it.next()
    path = M.save_data_state(prefix, 2, it)
    assert os.path.exists(path)
    assert os.path.exists(rz.checksum_path(path))   # CRC sidecar
    want = _data_of(_drain(it))

    it2 = NDArrayIter(x, batch_size=4, shuffle=True)
    assert M.load_data_state(prefix, 2, it2)
    it2.reset()
    got = _data_of(_drain(it2))
    for a, b in zip(want, got):
        np.testing.assert_array_equal(a, b)


def test_data_state_corrupt_degrades_with_warning(tmp_path):
    prefix = str(tmp_path / "run")
    it = NDArrayIter(np.zeros((8, 2), np.float32), batch_size=2)
    M.save_data_state(prefix, 1, it)
    with open(f"{prefix}-0001.data", "r+b") as f:
        f.write(b"XX")
    it2 = NDArrayIter(np.zeros((8, 2), np.float32), batch_size=2)
    with warnings.catch_warnings(record=True) as wl:
        warnings.simplefilter("always")
        assert not M.load_data_state(prefix, 1, it2)
    assert any("could not be loaded" in str(x.message) for x in wl)
    with pytest.raises(rz.CheckpointCorruptError):
        M.load_data_state(prefix, 1, it2, strict=True)


def test_data_state_missing_degrades(tmp_path):
    it = NDArrayIter(np.zeros((8, 2), np.float32), batch_size=2)
    with warnings.catch_warnings(record=True) as wl:
        warnings.simplefilter("always")
        assert not M.load_data_state(str(tmp_path / "no"), 3, it)
    assert any("epoch start" in str(x.message) for x in wl)


def test_module_save_checkpoint_writes_data_companion(tmp_path):
    from incubator_mxnet_tpu.module import Module
    prefix = str(tmp_path / "mod")
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=2, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = Module(net)
    x = np.random.RandomState(0).rand(12, 4).astype(np.float32)
    it = NDArrayIter(x, np.zeros(12, np.float32), batch_size=4)
    mod.bind(data_shapes=it.provide_data,
             label_shapes=it.provide_label)
    mod.init_params()
    it.reset()
    it.next()
    mod.save_checkpoint(prefix, 1, data_iter=it)
    assert os.path.exists(f"{prefix}-0001.data")

    it2 = NDArrayIter(x, np.zeros(12, np.float32), batch_size=4)
    assert Module.load_data_state(prefix, 1, it2)
    it2.reset()
    assert len(_drain(it2)) == 2     # 3 batches - 1 served


# ---------------------------------------------------- train-loop e2e
def test_train_loop_checkpoint_at_batch_k_replays_remainder(tmp_path):
    """Acceptance: a run checkpointed at batch k and 'restarted'
    (fresh iterator + load) sees the identical remaining batch
    sequence — data AND labels."""
    prefix = str(tmp_path / "job")
    np.random.seed(42)
    x = np.random.rand(32, 3).astype(np.float32)
    y = np.arange(32, dtype=np.float32)

    it = NDArrayIter(x, y, batch_size=4, shuffle=True)
    it.reset()
    seen = []
    for k in range(3):               # "train" 3 batches
        b = it.next()
        seen.append(b.label[0].asnumpy())
    M.save_data_state(prefix, 0, it)         # checkpoint at batch 3
    expected = [b.label[0].asnumpy() for b in _drain(it)]

    # --- simulated restart: new process would rebuild + load ---
    np.random.seed(0)                # RNG deliberately perturbed
    it_r = NDArrayIter(x, y, batch_size=4, shuffle=True)
    M.load_data_state(prefix, 0, it_r)
    it_r.reset()                     # fit()'s epoch-start reset
    replayed = [b.label[0].asnumpy() for b in _drain(it_r)]
    assert len(replayed) == len(expected) == 5
    for a, b in zip(expected, replayed):
        np.testing.assert_array_equal(a, b)
    # nothing already trained on is replayed
    done = {v for arr in seen for v in arr.tolist()}
    new = {v for arr in replayed for v in arr.tolist()}
    assert not done & new


# ------------------------------------------------------- launcher
def test_launch_exports_data_timeout():
    import argparse
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "launch", os.path.join(REPO, "tools", "launch.py"))
    launch = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(launch)
    args = argparse.Namespace(num_workers=2, env=[],
                              data_timeout=45.0)
    env = launch._worker_env(args, 0, "127.0.0.1:1", 0)
    assert env["MXTPU_DATA_TIMEOUT"] == "45.0"
    args.data_timeout = None
    env = launch._worker_env(args, 0, "127.0.0.1:1", 0)
    assert "MXTPU_DATA_TIMEOUT" not in env


# ------------------------------------------------------- lint rules
def test_lint_forbids_unbounded_queue_get(tmp_path):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "lint", os.path.join(REPO, "ci", "lint.py"))
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    d = tmp_path / "incubator_mxnet_tpu" / "io"
    d.mkdir(parents=True)
    f = d / "x.py"
    f.write_text("import queue\nq = queue.Queue()\nv = q.get()\n")
    problems = lint.check_file(f)
    assert any("unbounded queue .get()" in p for p in problems)
    f.write_text("import queue\nq = queue.Queue()\n"
                 "v = q.get(timeout=1.0)\n")
    assert not any("unbounded" in p for p in lint.check_file(f))


def test_lint_env_var_rules_pass_on_repo():
    import subprocess
    import sys
    out = subprocess.run([sys.executable, "ci/lint.py"], cwd=REPO,
                         capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr
