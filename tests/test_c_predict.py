"""Native C predict ABI (ref role: include/mxnet/c_predict_api.h /
src/c_api/c_predict_api.cc): libmxtpu_predict.so embeds the
interpreter and serves exported models to C programs.

Two drive modes:
  * ctypes  — the .so loaded into this process (attaches to the
    running interpreter), full create/input/forward/output cycle
  * C client — a real C program compiled against the header, run in
    a subprocess with a fresh embedded interpreter
"""
import ctypes
import os
import subprocess
import textwrap

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(REPO, "src", "c_predict")
SO = os.path.join(SRC, "libmxtpu_predict.so")


def _build_lib():
    if not os.path.exists(SO):
        subprocess.run(["make", "-C", SRC], check=True,
                       capture_output=True, timeout=300)
    return SO


def _export_model(tmp_path):
    mx.random.seed(3)
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(8, activation="relu"),
                gluon.nn.Dense(3))
    net.initialize(mx.initializer.Xavier())
    net.hybridize()
    x = mx.nd.array(np.random.RandomState(0).rand(2, 5)
                    .astype("float32"))
    ref_out = net(x).asnumpy()
    prefix = str(tmp_path / "cnet")
    net.export(prefix)
    return prefix, x.asnumpy(), ref_out


def _bind(lib):
    u = ctypes.c_uint
    lib.MXTPUGetLastError.restype = ctypes.c_char_p
    lib.MXTPUPredCreate.argtypes = [
        ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, u, ctypes.POINTER(ctypes.c_char_p),
        ctypes.POINTER(u), ctypes.POINTER(u),
        ctypes.POINTER(ctypes.c_void_p)]
    lib.MXTPUPredSetInput.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_float), u]
    lib.MXTPUPredForward.argtypes = [ctypes.c_void_p]
    lib.MXTPUPredGetOutputShape.argtypes = [
        ctypes.c_void_p, u, ctypes.POINTER(ctypes.POINTER(u)),
        ctypes.POINTER(u)]
    lib.MXTPUPredGetOutput.argtypes = [
        ctypes.c_void_p, u, ctypes.POINTER(ctypes.c_float), u]
    lib.MXTPUPredFree.argtypes = [ctypes.c_void_p]
    return lib


def test_c_predict_ctypes_roundtrip(tmp_path):
    lib = _bind(ctypes.CDLL(_build_lib()))
    prefix, x, ref_out = _export_model(tmp_path)
    sym_json = open(prefix + "-symbol.json", "rb").read()
    params = open(prefix + "-0000.params", "rb").read()

    keys = (ctypes.c_char_p * 1)(b"data")
    indptr = (ctypes.c_uint * 2)(0, 2)
    shape = (ctypes.c_uint * 2)(2, 5)
    handle = ctypes.c_void_p()
    rc = lib.MXTPUPredCreate(sym_json, params, len(params), 1, 0,
                             1, keys, indptr, shape,
                             ctypes.byref(handle))
    assert rc == 0, lib.MXTPUGetLastError()

    flat = np.ascontiguousarray(x, dtype=np.float32).ravel()
    buf = flat.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
    assert lib.MXTPUPredSetInput(handle, b"data", buf, flat.size) == 0,\
        lib.MXTPUGetLastError()
    assert lib.MXTPUPredForward(handle) == 0, lib.MXTPUGetLastError()

    sdata = ctypes.POINTER(ctypes.c_uint)()
    ndim = ctypes.c_uint()
    assert lib.MXTPUPredGetOutputShape(
        handle, 0, ctypes.byref(sdata), ctypes.byref(ndim)) == 0
    out_shape = tuple(sdata[i] for i in range(ndim.value))
    assert out_shape == (2, 3), out_shape

    out = np.zeros(6, dtype=np.float32)
    optr = out.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
    assert lib.MXTPUPredGetOutput(handle, 0, optr, out.size) == 0, \
        lib.MXTPUGetLastError()
    np.testing.assert_allclose(out.reshape(2, 3), ref_out,
                               rtol=1e-5, atol=1e-5)
    assert lib.MXTPUPredFree(handle) == 0

    # error path: bad input key reports through MXTPUGetLastError
    handle2 = ctypes.c_void_p()
    assert lib.MXTPUPredCreate(sym_json, params, len(params), 1, 0,
                               1, keys, indptr, shape,
                               ctypes.byref(handle2)) == 0
    rc = lib.MXTPUPredSetInput(handle2, b"nope", buf, flat.size)
    assert rc == -1
    assert b"nope" in lib.MXTPUGetLastError()
    lib.MXTPUPredFree(handle2)


DEMO_C = r"""
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include "c_predict_api.h"

static char *slurp(const char *path, long *size) {
    FILE *f = fopen(path, "rb");
    if (!f) { perror(path); exit(2); }
    fseek(f, 0, SEEK_END); *size = ftell(f); fseek(f, 0, SEEK_SET);
    char *buf = (char *)malloc(*size + 1);
    if (fread(buf, 1, *size, f) != (size_t)*size) exit(2);
    buf[*size] = 0; fclose(f);
    return buf;
}

int main(int argc, char **argv) {
    long sym_size, param_size;
    char *sym = slurp(argv[1], &sym_size);
    char *params = slurp(argv[2], &param_size);
    const char *keys[1] = {"data"};
    mx_uint indptr[2] = {0, 2};
    mx_uint shape[2] = {2, 5};
    PredictorHandle h;
    if (MXTPUPredCreate(sym, params, (int)param_size, 1, 0, 1, keys,
                        indptr, shape, &h) != 0) {
        fprintf(stderr, "create: %s\n", MXTPUGetLastError());
        return 1;
    }
    float in[10];
    for (int i = 0; i < 10; ++i) in[i] = (float)i / 10.0f;
    if (MXTPUPredSetInput(h, "data", in, 10) != 0 ||
        MXTPUPredForward(h) != 0) {
        fprintf(stderr, "run: %s\n", MXTPUGetLastError());
        return 1;
    }
    mx_uint *oshape, ondim;
    MXTPUPredGetOutputShape(h, 0, &oshape, &ondim);
    mx_uint total = 1;
    for (mx_uint i = 0; i < ondim; ++i) total *= oshape[i];
    float *out = (float *)malloc(total * sizeof(float));
    if (MXTPUPredGetOutput(h, 0, out, total) != 0) {
        fprintf(stderr, "out: %s\n", MXTPUGetLastError());
        return 1;
    }
    for (mx_uint i = 0; i < total; ++i) printf("%.6f\n", out[i]);
    MXTPUPredFree(h);
    return 0;
}
"""


def test_c_predict_standalone_client(tmp_path):
    """Compile a real C program against the header and run it with a
    fresh embedded interpreter — the reference's deployment story."""
    _build_lib()
    prefix, _, _ = _export_model(tmp_path)

    demo_c = tmp_path / "demo.c"
    demo_c.write_text(DEMO_C)
    demo = str(tmp_path / "demo")
    subprocess.run(
        ["gcc", "-O2", "-I", SRC, str(demo_c), "-o", demo,
         "-L", SRC, f"-Wl,-rpath,{SRC}", "-lmxtpu_predict"],
        check=True, capture_output=True, timeout=120)

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["MXTPU_FORCE_CPU"] = "1"
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [demo, prefix + "-symbol.json", prefix + "-0000.params"],
        capture_output=True, text=True, timeout=600, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    vals = np.array([float(v) for v in r.stdout.split()],
                    dtype=np.float32)
    assert vals.shape == (6,)

    # oracle: same input through the Python predictor
    from incubator_mxnet_tpu.predictor import Predictor
    x = (np.arange(10, dtype=np.float32) / 10.0).reshape(2, 5)
    pred = Predictor(prefix + "-symbol.json", prefix + "-0000.params",
                     {"data": (2, 5)})
    np.testing.assert_allclose(vals.reshape(2, 3), pred.predict(x),
                               rtol=1e-5, atol=1e-5)
