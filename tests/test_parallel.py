"""Tests for the parallel package on the 8-device virtual CPU mesh
(conftest.py sets --xla_force_host_platform_device_count=8).

Testing model follows the reference's: model parallelism exercised on
CPU contexts without real accelerators (ref:
tests/python/unittest/test_multi_device_exec.py,
tests/nightly/dist_sync_kvstore.py run as local processes).
Oracle = unsharded single-device execution of the same computation.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import parallel
from incubator_mxnet_tpu.parallel import optim as foptim


def test_make_mesh_axes():
    mesh = parallel.make_mesh()
    assert mesh.axis_names == parallel.AXES
    assert mesh.shape["dp"] == 8
    mesh2 = parallel.make_mesh(tp=2, sp=2)
    assert mesh2.shape["dp"] == 2
    assert mesh2.shape["tp"] == 2
    with pytest.raises(ValueError):
        parallel.make_mesh(dp=16)


def test_functionalize_matches_eager():
    net = mx.gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(mx.gluon.nn.Dense(16, activation="relu"))
        net.add(mx.gluon.nn.Dense(4))
    net.initialize()
    x = mx.nd.array(np.random.RandomState(0).rand(5, 8))
    eager = net(x).asnumpy()
    pure = parallel.functionalize(net, x)
    outs, _ = pure.apply(pure.params(), pure.states(), [x._data],
                         jax.random.PRNGKey(0), training=False)
    np.testing.assert_allclose(eager, np.asarray(outs[0]), rtol=1e-5)


def test_functional_sgd_matches_imperative():
    rs = np.random.RandomState(1)
    w = jnp.asarray(rs.rand(4, 3), jnp.float32)
    g = jnp.asarray(rs.rand(4, 3), jnp.float32)
    opt = foptim.sgd(learning_rate=0.1, momentum=0.9, wd=0.01)
    params = {"w": w}
    state = opt.init(params)
    p1, s1 = opt.update(params, {"w": g}, state)
    p2, _ = opt.update(p1, {"w": g}, s1)
    # reference semantics: grad += wd*w; mom = m*mom - lr*grad; w += mom
    wn, m = np.asarray(w), np.zeros_like(w)
    for _ in range(2):
        gg = np.asarray(g) + 0.01 * wn
        m = 0.9 * m - 0.1 * gg
        wn = wn + m
    np.testing.assert_allclose(np.asarray(p2["w"]), wn, rtol=1e-5)


def test_functional_nag_matches_imperative():
    rs = np.random.RandomState(11)
    w0 = rs.rand(4, 3).astype(np.float32)
    g = rs.rand(4, 3).astype(np.float32)
    opt = foptim.create("nag", learning_rate=0.1, momentum=0.9,
                        wd=0.01)
    p = {"w": jnp.asarray(w0)}
    s = opt.init(p)
    for _ in range(3):
        p, s = opt.update(p, {"w": jnp.asarray(g)}, s)
    iopt = mx.optimizer.create("nag", learning_rate=0.1, momentum=0.9,
                               wd=0.01)
    wi = mx.nd.array(w0)
    st = iopt.create_state(0, wi)
    for _ in range(3):
        iopt.update(0, wi, mx.nd.array(g), st)
    np.testing.assert_allclose(np.asarray(p["w"]), wi.asnumpy(),
                               rtol=1e-5)


def test_pipeline_stage_count_mismatch_raises():
    mesh = parallel.make_mesh(pp=2)
    stacked = parallel.stack_stage_params(
        [{"w": jnp.zeros((3, 3))} for _ in range(4)])
    with pytest.raises(ValueError, match="stages"):
        parallel.pipeline_apply(lambda p, x: x, stacked,
                                jnp.zeros((4, 3)), mesh,
                                n_microbatches=2)


def test_sharded_train_step_dp_loss_decreases():
    rs = np.random.RandomState(2)
    net = mx.gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(mx.gluon.nn.Dense(32, activation="relu"))
        net.add(mx.gluon.nn.Dense(10))
    net.initialize()
    x = jnp.asarray(rs.rand(16, 20), jnp.float32)
    y = jnp.asarray(rs.randint(0, 10, (16,)), jnp.int32)
    step = parallel.ShardedTrainStep(
        net, optimizer="sgd", optimizer_params=dict(learning_rate=0.5),
        mesh=parallel.make_mesh(), example_args=[x])
    losses = [float(step(x, y)) for _ in range(60)]
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_sharded_train_step_matches_single_device():
    """DP-sharded step == unsharded step (the check_consistency analog)."""
    rs = np.random.RandomState(3)
    x = jnp.asarray(rs.rand(8, 6), jnp.float32)
    y = jnp.asarray(rs.randint(0, 3, (8,)), jnp.int32)

    def make_step(mesh):
        mx.random.seed(0)
        net = mx.gluon.nn.Dense(3, in_units=6, prefix="net_")
        net.initialize(mx.initializer.Xavier())
        return parallel.ShardedTrainStep(
            net, optimizer="sgd",
            optimizer_params=dict(learning_rate=0.1), mesh=mesh)

    sharded = make_step(parallel.make_mesh())
    single = make_step(parallel.make_mesh(
        devices=jax.devices()[:1]))
    for _ in range(3):
        l_sh = float(sharded(x, y, rng=jax.random.PRNGKey(7)))
        l_si = float(single(x, y, rng=jax.random.PRNGKey(7)))
    np.testing.assert_allclose(l_sh, l_si, rtol=1e-4)
    for n in sharded.params:
        np.testing.assert_allclose(np.asarray(sharded.params[n]),
                                   np.asarray(single.params[n]),
                                   rtol=1e-4, atol=1e-5)


def test_tensor_parallel_rules():
    mesh = parallel.make_mesh(tp=4)
    rules = parallel.tp_rules_for_dense_stacks()
    params = {"mlp_up_weight": jnp.zeros((8, 4)),
              "mlp_down_weight": jnp.zeros((4, 8)),
              "norm_gamma": jnp.zeros((4,))}
    sh = rules.shardings(mesh, params)
    assert sh["mlp_up_weight"].spec == parallel.P("tp", None)
    assert sh["mlp_down_weight"].spec == parallel.P(None, "tp")
    assert sh["norm_gamma"].spec == parallel.P()
    # a tp-sharded matmul chain still computes the right thing
    rs = np.random.RandomState(4)
    x = jnp.asarray(rs.rand(2, 4), jnp.float32)
    wu = jnp.asarray(rs.rand(8, 4), jnp.float32)
    wd = jnp.asarray(rs.rand(4, 8), jnp.float32)
    from incubator_mxnet_tpu.parallel.sharding import apply_rules
    pv = apply_rules(mesh, {"mlp_up_weight": wu,
                            "mlp_down_weight": wd}, rules)

    @jax.jit
    def f(p, x):
        h = jax.nn.relu(x @ p["mlp_up_weight"].T)
        return h @ p["mlp_down_weight"].T
    got = f(pv, x)
    want = jax.nn.relu(x @ wu.T) @ wd.T
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5)


def test_pipeline_apply_matches_sequential():
    mesh = parallel.make_mesh(pp=4)
    rs = np.random.RandomState(5)
    n_stages, d = 4, 6
    ws = [jnp.asarray(rs.rand(d, d) * 0.5, jnp.float32)
          for _ in range(n_stages)]
    stacked = parallel.stack_stage_params([{"w": w} for w in ws])

    def stage(p, x):
        return jnp.tanh(x @ p["w"])

    x = jnp.asarray(rs.rand(8, d), jnp.float32)
    y = parallel.pipeline_apply(stage, stacked, x, mesh,
                                n_microbatches=4)
    want = x
    for w in ws:
        want = jnp.tanh(want @ w)
    np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


def test_pipeline_grad():
    mesh = parallel.make_mesh(pp=2)
    rs = np.random.RandomState(6)
    d = 4
    ws = [jnp.asarray(rs.rand(d, d) * 0.5, jnp.float32)
          for _ in range(2)]
    stacked = parallel.stack_stage_params([{"w": w} for w in ws])
    x = jnp.asarray(rs.rand(4, d), jnp.float32)

    def stage(p, x):
        return jnp.tanh(x @ p["w"])

    def loss_pp(stk):
        return jnp.sum(parallel.pipeline_apply(
            stage, stk, x, mesh, n_microbatches=2) ** 2)

    def loss_seq(stk):
        h = x
        for i in range(2):
            h = jnp.tanh(
                h @ jax.tree_util.tree_map(lambda a: a[i], stk)["w"])
        return jnp.sum(h ** 2)

    g_pp = jax.grad(loss_pp)(stacked)
    g_seq = jax.grad(loss_seq)(stacked)
    np.testing.assert_allclose(np.asarray(g_pp["w"]),
                               np.asarray(g_seq["w"]),
                               rtol=1e-4, atol=1e-5)


def _ref_attention(q, k, v, causal):
    b, l, h, d = q.shape
    s = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    if causal:
        mask = np.tril(np.ones((l, l), bool))
        s = np.where(mask[None, None], s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention(causal):
    mesh = parallel.make_mesh(sp=4)
    rs = np.random.RandomState(7)
    b, l, h, d = 2, 16, 2, 8
    q = rs.rand(b, l, h, d).astype(np.float32)
    k = rs.rand(b, l, h, d).astype(np.float32)
    v = rs.rand(b, l, h, d).astype(np.float32)
    out = parallel.ring_attention(jnp.asarray(q), jnp.asarray(k),
                                  jnp.asarray(v), mesh, causal=causal)
    want = _ref_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-4,
                               atol=1e-5)


def test_ring_attention_grad():
    mesh = parallel.make_mesh(sp=2)
    rs = np.random.RandomState(8)
    b, l, h, d = 1, 8, 1, 4
    q = jnp.asarray(rs.rand(b, l, h, d), jnp.float32)
    k = jnp.asarray(rs.rand(b, l, h, d), jnp.float32)
    v = jnp.asarray(rs.rand(b, l, h, d), jnp.float32)

    def f(q):
        return jnp.sum(parallel.ring_attention(q, k, v, mesh) ** 2)

    def f_ref(q):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.sum(jnp.einsum("bhqk,bkhd->bqhd", p, v) ** 2)

    np.testing.assert_allclose(np.asarray(jax.grad(f)(q)),
                               np.asarray(jax.grad(f_ref)(q)),
                               rtol=1e-4, atol=1e-5)


def test_two_steps_same_block_donation_safe():
    """Donation must not delete the live Parameters or a sibling
    step's buffers: device_put aliases when a value already lives on
    the target device (regression: axon backend, round 3)."""
    rs = np.random.RandomState(9)
    mx.random.seed(0)
    net = mx.gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(mx.gluon.nn.Dense(8, in_units=6),
                mx.gluon.nn.BatchNorm(), mx.gluon.nn.Dense(3))
    net.initialize(mx.initializer.Xavier())
    pure = parallel.functionalize(net, jnp.zeros((4, 6), jnp.float32))
    x = jnp.asarray(rs.rand(8, 6), jnp.float32)
    y = jnp.asarray(rs.randint(0, 3, (8,)), jnp.int32)

    def make():
        return parallel.ShardedTrainStep(
            pure, optimizer="sgd",
            optimizer_params=dict(learning_rate=0.1),
            mesh=parallel.make_mesh())

    s1 = make()
    float(s1(x, y, rng=jax.random.PRNGKey(0)))
    s2 = make()  # reads the live Parameters again
    float(s2(x, y, rng=jax.random.PRNGKey(0)))
    float(s1(x, y, rng=jax.random.PRNGKey(0)))  # s1 still usable
    for p in net.collect_params().values():
        assert not p.data()._data.is_deleted()


def test_write_back_then_step_keeps_parameters_alive():
    """write_back must hand Parameters owned copies, not the step's
    donated buffers (regression: round 3, reverse aliasing path)."""
    rs = np.random.RandomState(10)
    mx.random.seed(0)
    net = mx.gluon.nn.Dense(3, in_units=6)
    net.initialize(mx.initializer.Xavier())
    step = parallel.ShardedTrainStep(
        net, optimizer="sgd", optimizer_params=dict(learning_rate=0.1),
        mesh=parallel.make_mesh(),
        example_args=[jnp.zeros((2, 6), jnp.float32)])
    x = jnp.asarray(rs.rand(8, 6), jnp.float32)
    y = jnp.asarray(rs.randint(0, 3, (8,)), jnp.int32)
    float(step(x, y))
    step.write_back()
    float(step(x, y))  # donates step buffers; Parameters must survive
    for p in net.collect_params().values():
        assert not p.data()._data.is_deleted()
        np.asarray(p.data()._data)  # still readable


def test_grad_accum_matches_full_batch():
    from incubator_mxnet_tpu import gluon

    def build(**kw):
        mx.random.seed(0)
        net = gluon.nn.Dense(4, in_units=8, prefix="ga_")
        net.initialize(mx.initializer.Xavier())
        return parallel.ShardedTrainStep(
            net, optimizer="sgd",
            optimizer_params=dict(learning_rate=0.1),
            example_args=[jnp.zeros((2, 8), jnp.float32)], **kw)

    rs = np.random.RandomState(0)
    xs = jnp.asarray(rs.rand(16, 8), jnp.float32)
    ys = jnp.asarray(rs.randint(0, 4, (16,)), jnp.int32)
    full = build()
    acc = build(grad_accum=2)
    l_full = [float(full(xs, ys)) for _ in range(3)]
    l_acc = [float(acc(xs, ys)) for _ in range(3)]
    # mean-of-micro-grads == full-batch grads for a linear net
    np.testing.assert_allclose(l_acc, l_full, rtol=1e-5)


def test_remat_matches_plain():
    from incubator_mxnet_tpu import gluon

    def build(**kw):
        mx.random.seed(1)
        net = gluon.nn.HybridSequential(prefix="rm_")
        with net.name_scope():
            net.add(gluon.nn.Dense(16, activation="relu"),
                    gluon.nn.Dense(4))
        net.initialize(mx.initializer.Xavier())
        return parallel.ShardedTrainStep(
            net, optimizer="sgd",
            optimizer_params=dict(learning_rate=0.1),
            example_args=[jnp.zeros((2, 8), jnp.float32)], **kw)

    rs = np.random.RandomState(1)
    xs = jnp.asarray(rs.rand(16, 8), jnp.float32)
    ys = jnp.asarray(rs.randint(0, 4, (16,)), jnp.int32)
    plain = build()
    remat = build(remat=True)
    l_plain = [float(plain(xs, ys)) for _ in range(3)]
    l_remat = [float(remat(xs, ys)) for _ in range(3)]
    np.testing.assert_allclose(l_remat, l_plain, rtol=1e-5)


def test_grad_accum_guards():
    import pytest
    from incubator_mxnet_tpu import gluon

    mx.random.seed(0)
    net = gluon.nn.Dense(4, in_units=8, prefix="gg_")
    net.initialize(mx.initializer.Xavier())
    step = parallel.ShardedTrainStep(
        net, optimizer="sgd", optimizer_params=dict(learning_rate=.1),
        example_args=[jnp.zeros((2, 8), jnp.float32)], grad_accum=3)
    with pytest.raises(ValueError, match="divisible"):
        step(jnp.zeros((16, 8), jnp.float32),
             jnp.zeros((16,), jnp.int32))
    mx.random.seed(0)
    net2 = gluon.nn.Dense(4, in_units=8, prefix="gh_")
    net2.initialize(mx.initializer.Xavier())
    step2 = parallel.ShardedTrainStep(
        net2, optimizer="sgd",
        optimizer_params=dict(learning_rate=.1), batch_axis=1,
        example_args=[jnp.zeros((2, 8), jnp.float32)], grad_accum=2)
    with pytest.raises(ValueError, match="batch_axis"):
        step2(jnp.zeros((8, 16), jnp.float32),
              jnp.zeros((16,), jnp.int32))


def test_lr_schedule_in_step():
    # zero-wd, momentum-free SGD on a frozen gradient: per-step delta
    # is exactly lr(t), so the schedule is observable from weights
    from incubator_mxnet_tpu.parallel import optim as fo
    from incubator_mxnet_tpu import gluon

    mx.random.seed(0)
    net = gluon.nn.Dense(1, in_units=1, use_bias=False, prefix="ls_")
    net.initialize(mx.initializer.One())

    def loss_fn(outputs, labels):
        return outputs[0].sum()          # d/dw = sum(x)

    sched = fo.warmup_linear(1.0, warmup_steps=2, total_steps=6,
                             end_lr=0.0)
    step = parallel.ShardedTrainStep(
        net, optimizer="sgd", optimizer_params=dict(learning_rate=9.9),
        loss_fn=loss_fn, lr_schedule=sched,
        example_args=[jnp.zeros((2, 1), jnp.float32)])
    x = jnp.ones((8, 1), jnp.float32)
    y = jnp.zeros((8,), jnp.int32)
    ws = [float(next(iter(step.params.values()))[0, 0])]
    for _ in range(5):
        step(x, y)
        ws.append(float(next(iter(step.params.values()))[0, 0]))
    deltas = [ws[i] - ws[i + 1] for i in range(5)]
    expected = [float(sched(t)) * 8.0 for t in range(5)]
    assert expected[0] > 0  # first update is not a no-op
    np.testing.assert_allclose(deltas, expected, rtol=1e-5)


def test_lr_schedule_survives_checkpoint(tmp_path):
    from incubator_mxnet_tpu.parallel import optim as fo
    from incubator_mxnet_tpu import gluon

    def build():
        mx.random.seed(0)
        net = gluon.nn.Dense(4, in_units=8, prefix="lc_")
        net.initialize(mx.initializer.Xavier())
        return parallel.ShardedTrainStep(
            net, optimizer="sgd",
            optimizer_params=dict(learning_rate=0.1),
            lr_schedule=fo.warmup_cosine(0.1, 2, 10),
            example_args=[jnp.zeros((2, 8), jnp.float32)])

    rs = np.random.RandomState(0)
    batches = [(jnp.asarray(rs.rand(8, 8), jnp.float32),
                jnp.asarray(rs.randint(0, 4, (8,)), jnp.int32))
               for _ in range(6)]
    ref = build()
    ref_losses = [float(ref(x, y)) for x, y in batches]
    a = build()
    for x, y in batches[:3]:
        a(x, y)
    a.save_checkpoint(str(tmp_path / "ck"))
    b = build()
    b.load_checkpoint(str(tmp_path / "ck"))
    assert int(b.step_count) == 3       # schedule resumes mid-curve
    resumed = [float(b(x, y)) for x, y in batches[3:]]
    np.testing.assert_allclose(resumed, ref_losses[3:], rtol=1e-6)


def test_zero1_sharded_optimizer_state_matches_replicated():
    """ZeRO-1 (zero=True): fp32 masters + adam moments live
    dp-sharded; training is numerically identical to the replicated
    layout (GSPMD inserts the scatter/gather)."""
    import jax
    from jax.sharding import PartitionSpec as P

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon, parallel

    def build(zero):
        mx.random.seed(0)
        net = gluon.nn.HybridSequential()
        with net.name_scope():
            net.add(gluon.nn.Dense(64, activation="relu"),
                    gluon.nn.Dense(8))
        net.initialize(mx.initializer.Xavier())
        return parallel.ShardedTrainStep(
            net, optimizer="adam",
            optimizer_params=dict(learning_rate=1e-2),
            example_args=[mx.nd.zeros((2, 16))],
            mesh=parallel.make_mesh(), zero=zero,
            compute_dtype=None)

    rs = np.random.RandomState(0)
    x = rs.rand(16, 16).astype(np.float32)
    y = rs.randint(0, 8, (16,)).astype(np.int32)

    z, r = build(True), build(False)
    assert z.zero
    # masters are genuinely dp-sharded (not replicated)
    sharded = [n for n, a in z.params.items()
               if a.sharding.spec != P()]
    assert sharded, "no parameter got dp-sharded"
    # adam moments inherit the sharded layout
    m = z.opt_state["mean"][sharded[0]]
    assert m.sharding.spec != P()

    losses_z = [float(z(x, y, rng=jax.random.PRNGKey(1)))
                for _ in range(4)]
    losses_r = [float(r(x, y, rng=jax.random.PRNGKey(1)))
                for _ in range(4)]
    np.testing.assert_allclose(losses_z, losses_r, rtol=1e-5)
    # training actually converged a bit under ZeRO
    assert losses_z[-1] < losses_z[0]


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_exact(causal):
    """All-to-all head-sharded attention (parallel/ulysses.py) is
    EXACT: numerics match the dense oracle for both maskings."""
    mesh = parallel.make_mesh(sp=4)
    rs = np.random.RandomState(7)
    b, l, h, d = 2, 16, 8, 4
    q = rs.rand(b, l, h, d).astype(np.float32)
    k = rs.rand(b, l, h, d).astype(np.float32)
    v = rs.rand(b, l, h, d).astype(np.float32)
    out = parallel.ulysses_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), mesh,
        causal=causal)
    want = _ref_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-4,
                               atol=1e-5)


def test_ulysses_attention_grad_and_head_constraint():
    mesh = parallel.make_mesh(sp=2)
    rs = np.random.RandomState(8)
    b, l, h, d = 1, 8, 2, 4
    q = jnp.asarray(rs.rand(b, l, h, d), jnp.float32)
    k = jnp.asarray(rs.rand(b, l, h, d), jnp.float32)
    v = jnp.asarray(rs.rand(b, l, h, d), jnp.float32)

    def f(q):
        return jnp.sum(
            parallel.ulysses_attention(q, k, v, mesh) ** 2)

    def f_ref(q):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.sum(jnp.einsum("bhqk,bkhd->bqhd", p, v) ** 2)

    np.testing.assert_allclose(np.asarray(jax.grad(f)(q)),
                               np.asarray(jax.grad(f_ref)(q)),
                               rtol=1e-4, atol=1e-4)
    # heads not divisible by sp: loud error naming the fallback
    q3 = jnp.zeros((1, 8, 3, 4), jnp.float32)
    with pytest.raises(ValueError, match="ring"):
        parallel.ulysses_attention(q3, q3, q3, mesh)
