"""Data iterators (ref: python/mxnet/io.py, src/io/).

The reference's C++ iterator stack (parser -> augmenter -> batcher ->
prefetcher) is re-created host-side: numpy/threads feed device
buffers, with async device transfer riding JAX dispatch.
"""
from .io import (DataDesc, DataBatch, DataIter, NDArrayIter, ResizeIter,
                 PrefetchingIter, DevicePrefetchIter, CSVIter,
                 MNISTIter, ImageRecordIter, LibSVMIter,
                 DataServiceIter)
from .sharding import shard_keys, shard_range, assigned_batches

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter",
           "ResizeIter", "PrefetchingIter", "DevicePrefetchIter",
           "CSVIter", "MNISTIter", "ImageRecordIter", "LibSVMIter",
           "DataServiceIter", "shard_keys", "shard_range",
           "assigned_batches"]
