"""Record/batch shard assignment shared by ``ImageRecordIter``
(``num_parts``/``part_index``) and the multi-process data service
(docs/data_service.md).

Two partition shapes, both with an exactly-once coverage contract
(union == everything, parts pairwise disjoint — pinned by
tests/test_data_service.py, which the service reuses as its own
shard-correctness proof):

- :func:`shard_range` / :func:`shard_keys` — *contiguous* record
  partition for distributed readers (ref:
  src/io/iter_image_recordio_2.cc partition logic: part ``k`` of
  ``P`` reads records ``[floor(kN/P), floor((k+1)N/P))``).  Cutting
  on record boundaries via the ``.idx`` keys means no part ever
  starts mid-record, and the floor arithmetic makes the edges exact
  for every ``N``/``P`` (the naive ``N//P * k`` chunking drops up to
  ``P-1`` tail records).
- :func:`assigned_batches` — *round-robin batch* assignment for the
  data service's decode workers: worker ``w`` of ``W`` owns global
  batch indices ``w, w+W, w+2W, ...``, so a round-robin merge over
  workers reconstructs the exact single-process batch order (the
  determinism contract of ``DataServiceIter``).
"""

__all__ = ["shard_range", "shard_keys", "assigned_batches",
           "reshard_batch_cursors"]


def shard_range(n, num_parts, part_index):
    """Half-open record range ``[start, stop)`` owned by
    ``part_index`` of ``num_parts`` over ``n`` records.

    Floor arithmetic: ``start = n*k // P``.  Adjacent parts share an
    edge (``stop(k) == start(k+1)``), part 0 starts at 0 and the last
    part stops at ``n``, so coverage is exact for any ``n`` —
    including ``n < num_parts`` (some parts are empty, none overlap).
    """
    if num_parts < 1:
        raise ValueError(f"num_parts must be >= 1, got {num_parts}")
    if not 0 <= part_index < num_parts:
        raise ValueError(
            f"part_index {part_index} out of range for "
            f"{num_parts} part(s)")
    return (n * part_index // num_parts,
            n * (part_index + 1) // num_parts)


def shard_keys(keys, num_parts, part_index):
    """The contiguous slice of ``keys`` owned by ``part_index``."""
    start, stop = shard_range(len(keys), num_parts, part_index)
    return keys[start:stop]


def assigned_batches(num_batches, num_shards, shard):
    """Global batch indices owned by ``shard`` of ``num_shards``:
    ``shard, shard+num_shards, ...`` below ``num_batches``."""
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    if not 0 <= shard < num_shards:
        raise ValueError(
            f"shard {shard} out of range for {num_shards} shard(s)")
    return list(range(shard, num_batches, num_shards))


def reshard_batch_cursors(num_batches, next_batch, num_shards):
    """Re-express a global round-robin stream position under a new
    shard count (the data-plane half of elastic restart,
    docs/elastic.md): the merged stream delivers global batches in
    order, so its exact position is ONE number — ``next_batch``, the
    next global batch index — and any shard count can re-derive its
    per-shard cursors from it.

    Returns ``(delivered, done)`` lists of length ``num_shards``:
    ``delivered[w]`` counts the batches of shard ``w``'s assignment
    (:func:`assigned_batches`) that lie before ``next_batch``, and
    ``done[w]`` is True when nothing of the assignment remains.  The
    union of remaining per-shard assignments is exactly the global
    range ``[next_batch, num_batches)``, each batch exactly once —
    the same exactly-once contract as the forward partition.
    """
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    if next_batch < 0:
        raise ValueError(f"next_batch must be >= 0, got {next_batch}")
    g = min(int(next_batch), int(num_batches))
    delivered, done = [], []
    for w in range(num_shards):
        d = 0 if g <= w else (g - 1 - w) // num_shards + 1
        delivered.append(d)
        done.append(w + d * num_shards >= num_batches)
    return delivered, done
