"""Data iterator stack.

Role analogs (ref file:line):
- DataDesc/DataBatch/DataIter: python/mxnet/io.py:43,116,177
- NDArrayIter: python/mxnet/io.py NDArrayIter (in-memory batcher)
- PrefetchingIter: python/mxnet/io.py + src/io/iter_prefetcher.h:47
  (double-buffered background thread)
- CSVIter: src/io/iter_csv.cc:151
- MNISTIter: src/io/iter_mnist.cc:80 (reads idx files)
- LibSVMIter: src/io/iter_libsvm.cc:200
- ImageRecordIter: src/io/iter_image_recordio_2.cc:660 (RecordIO;
  full pipeline lands with the recordio milestone — here the class
  validates args and defers to the image package)
"""
import gzip
import queue
import struct
import threading
import time

import numpy as np

from .. import telemetry
from ..ndarray import array as nd_array
from ..ndarray.ndarray import NDArray
from ..resilience import DataPipelineError, data_timeout, inject

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter",
           "ResizeIter", "PrefetchingIter", "DevicePrefetchIter",
           "CSVIter", "MNISTIter",
           "LibSVMIter", "ImageRecordIter", "DataServiceIter"]

# prefetch consumers poll in short slices so a dead producer thread
# is noticed within one slice, not only at the full data timeout
_GET_POLL_S = 0.2


def _bounded_get(q, source, thread=None, timeout=None):
    """``q.get()`` bounded by ``MXTPU_DATA_TIMEOUT``.

    Raises :class:`DataPipelineError` naming ``source`` when the
    producer thread has died without delivering, or when nothing
    arrives within the deadline — the two ways a background producer
    can otherwise hang its consumer forever.  ``timeout=None`` reads
    the env flag; a value <= 0 disables the deadline (the dead-thread
    check still applies).

    Telemetry: each successful get publishes its queue-wait time and
    the post-get queue depth (`prefetch_queue_wait_seconds` /
    `prefetch_queue_depth`) — the operator-visible signal for "the
    input pipeline is the bottleneck"."""
    t0 = time.monotonic()
    item = _bounded_get_inner(q, source, thread, timeout)
    tel_hist = telemetry.histogram("prefetch_queue_wait_seconds")
    if tel_hist is not telemetry.NULL_METRIC:
        tel_hist.observe(time.monotonic() - t0)
        telemetry.gauge("prefetch_queue_depth").set(q.qsize())
    return item


def _bounded_get_inner(q, source, thread, timeout):
    if timeout is None:
        timeout = data_timeout()
    deadline = time.monotonic() + timeout \
        if timeout and timeout > 0 else None
    while True:
        try:
            return q.get(timeout=_GET_POLL_S)
        except queue.Empty:
            pass
        if thread is not None and not thread.is_alive():
            try:  # the final put may have landed after our get timed out
                return q.get_nowait()
            except queue.Empty:
                raise DataPipelineError(
                    f"{source}: prefetch worker thread died without "
                    "delivering a batch, end-of-epoch, or error "
                    "(killed mid-put?); the stream cannot continue"
                ) from None
        if deadline is not None and time.monotonic() >= deadline:
            raise DataPipelineError(
                f"{source} stalled: no batch arrived within "
                f"{timeout:g}s (MXTPU_DATA_TIMEOUT); the upstream "
                "iterator or its storage is wedged — raise the "
                "timeout for slow sources, or inspect the source "
                "named above") from None


def _stop_aware_put(q, stop, item):
    """Bounded put that re-checks ``stop`` so a producer blocked on a
    full queue can always observe reset/teardown (the reset-deadlock
    window of a bare ``q.put()``).  True when the item landed."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.05)
            return True
        except queue.Full:
            continue
    return False


def _halt_worker_thread(thread, q, stop, source, timeout=30):
    """Stop + join a prefetch producer and drain its queue race-free:
    set ``stop`` (the producer's stop-aware put exits on it), drain
    *while* joining (a producer mid-``put`` needs a slot freed to
    notice the event), then re-drain after the join so no item from a
    final put survives — the deadlock/stale-item window of a naive
    drain-then-join order.  A worker still alive past ``timeout`` is
    wedged inside the inner iterator; resetting the shared inner
    under a live consumer would interleave two readers, so fail
    loudly instead."""
    stop.set()
    if thread is not None:
        deadline = time.monotonic() + timeout
        while thread.is_alive():
            try:
                q.get_nowait()
            except queue.Empty:
                pass
            thread.join(timeout=0.05)
            if time.monotonic() > deadline:
                raise DataPipelineError(
                    f"{source}: worker still blocked in the inner "
                    f"iterator after {timeout}s; it cannot be reset "
                    "safely (check the inner iterator for hangs)")
    try:
        while True:
            q.get_nowait()
    except queue.Empty:
        pass


class DataDesc:
    """Name + shape (+dtype/layout) of one input (ref: io.py:43)."""

    def __init__(self, name, shape, dtype=np.float32, layout="NCHW"):
        self.name = name
        self.shape = tuple(shape)
        self.dtype = dtype
        self.layout = layout

    def __repr__(self):
        return f"DataDesc[{self.name},{self.shape},{self.dtype}," \
               f"{self.layout}]"

    def __iter__(self):  # unpacks like the reference's namedtuple
        yield self.name
        yield self.shape

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch:
    """One mini-batch (ref: io.py:116)."""

    def __init__(self, data, label=None, pad=0, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        self.data = data
        self.label = label if label is not None else []
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """Iterator base (ref: io.py:177)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(self.getdata(), self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        return 0

    # ------------------------------------------------- resumable state
    def state_dict(self):
        """Checkpointable position: enough to resume the stream at
        the exact batch after a restart (epoch order, cursor, RNG,
        bad-record count — see docs/data_pipeline.md).  Saved
        alongside model checkpoints by ``model.save_data_state``."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support checkpointable "
            "iterator state (state_dict)")

    def load_state_dict(self, state):
        """Restore a :meth:`state_dict` snapshot.  The restored
        position survives exactly one subsequent ``reset()`` (train
        loops reset at epoch start before iterating, and a resumed
        run must not rewind to batch 0); iterating disarms that
        shield, so the next epoch boundary reshuffles normally."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support checkpointable "
            "iterator state (load_state_dict)")

    def skip(self, num_batches):
        """Advance past ``num_batches`` without delivering them (used
        by prefetch wrappers to fast-forward an inner iterator to a
        resume point).  Subclasses with a cursor override this to
        skip without materializing data."""
        for _ in range(num_batches):
            self.next()


def _init_data(data, allow_empty, default_name):
    """Normalize data into list of (name, numpy array)."""
    if data is None:
        if not allow_empty:
            raise ValueError("data cannot be None")
        return []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {f"_{i}_{default_name}": d for i, d in enumerate(data)}
    out = []
    for k, v in data.items():
        if isinstance(v, NDArray):
            v = v.asnumpy()
        out.append((k, np.asarray(v)))
    return out


class NDArrayIter(DataIter):
    """In-memory batch iterator (ref: python/mxnet/io.py NDArrayIter).

    Supports shuffle, discard/pad/roll-over last-batch handling.
    """

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, False, data_name)
        self.label = _init_data(label, True, label_name)
        self.num_data = self.data[0][1].shape[0]
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.cursor = -batch_size
        self._carry = np.array([], dtype=np.int64)  # roll_over leftovers
        self._resume_pending = False
        self._order = np.arange(self.num_data)
        if shuffle:
            np.random.shuffle(self._order)
        if last_batch_handle == "discard":
            self.num_batches = self.num_data // batch_size
        else:
            self.num_batches = (self.num_data + batch_size - 1) \
                // batch_size

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:],
                         v.dtype) for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:],
                         v.dtype) for k, v in self.label]

    def reset(self):
        if getattr(self, "_resume_pending", False):
            # a just-restored position must survive the epoch-start
            # reset of the training loop (fit() resets before
            # iterating) — one-shot, disarmed here or by iterating
            self._resume_pending = False
            return
        base = np.arange(self.num_data)
        if self.shuffle:
            np.random.shuffle(base)
        if self.last_batch_handle == "roll_over":
            # leftover samples from last epoch lead the new one
            # (ref: io.py NDArrayIter roll_over semantics)
            self._order = np.concatenate([self._carry, base])
            self._carry = np.array([], dtype=np.int64)
        else:
            self._order = base
        self.cursor = -self.batch_size

    def state_dict(self):
        """Position snapshot: cursor + epoch order + roll-over carry
        + global numpy RNG state (the shuffle source), so a restore
        replays both the remaining batches of this epoch and every
        later epoch's shuffle."""
        return {"type": "NDArrayIter",
                "cursor": int(self.cursor),
                "order": self._order.copy(),
                "carry": self._carry.copy(),
                "np_rng": np.random.get_state()}

    def load_state_dict(self, state):
        if state.get("type") != "NDArrayIter":
            raise ValueError(
                f"state_dict type {state.get('type')!r} does not "
                "match NDArrayIter")
        order = np.asarray(state["order"], dtype=np.int64)
        if len(order) and order.max() >= self.num_data:
            raise ValueError(
                "iterator state references sample "
                f"{int(order.max())} but the dataset has only "
                f"{self.num_data} samples — state from a different "
                "dataset?")
        self._order = order
        self._carry = np.asarray(state["carry"], dtype=np.int64)
        self.cursor = int(state["cursor"])
        if state.get("np_rng") is not None:
            np.random.set_state(state["np_rng"])
        self._resume_pending = True

    def skip(self, num_batches):
        self._resume_pending = False
        self.cursor += num_batches * self.batch_size

    def iter_next(self):
        self._resume_pending = False
        self.cursor += self.batch_size
        if self.last_batch_handle == "discard":
            return self.cursor + self.batch_size <= self.num_data
        if self.last_batch_handle == "roll_over":
            if self.cursor + self.batch_size <= len(self._order):
                return True
            if self.cursor < len(self._order):
                self._carry = self._order[self.cursor:]
            return False
        return self.cursor < self.num_data

    def _slice(self, arrays):
        out = []
        for _, v in arrays:
            idx = self._order[self.cursor:self.cursor + self.batch_size]
            part = v[idx]
            if len(part) < self.batch_size:  # pad by wrapping
                extra = self._order[:self.batch_size - len(part)]
                part = np.concatenate([part, v[extra]], axis=0)
            out.append(nd_array(part))
        return out

    def getdata(self):
        return self._slice(self.data)

    def getlabel(self):
        return self._slice(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


class ResizeIter(DataIter):
    """Resize an iterator to a fixed number of batches per epoch
    (ref: python/mxnet/io.py ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Background-thread double buffering (ref: io.py PrefetchingIter /
    src/io/iter_prefetcher.h:47).  Overlaps host batch prep with
    device compute — the host-side half of the reference's
    compute/IO overlap."""

    def __init__(self, iters, rename_data=None, rename_label=None,
                 prefetch_depth=2):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        super().__init__(iters[0].batch_size)
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self._depth = prefetch_depth
        self._queue = queue.Queue(maxsize=prefetch_depth)
        self._stop = threading.Event()
        self._thread = None
        self._exhausted = False
        self._error = None
        self._delivered = 0
        self._pending_resume = None
        self._capture_epoch_state()
        self._start()

    def _start(self):
        # the worker must capture ITS queue/stop: reading them off
        # self would let a worker that outlives a reset() resurrect
        # into the replacement queue
        q, stop = self._queue, self._stop

        def worker():
            while not stop.is_set():
                try:
                    inject("data", "prefetch")
                    batches = [it.next() for it in self.iters]
                except StopIteration:
                    _stop_aware_put(q, stop, None)
                    return
                except Exception as exc:   # surface in the consumer
                    _stop_aware_put(q, stop, ("err", exc))
                    return
                if not _stop_aware_put(q, stop, batches):
                    return
        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    @property
    def provide_data(self):
        out = []
        for i, it in enumerate(self.iters):
            descs = it.provide_data
            if self.rename_data:
                descs = [DataDesc(self.rename_data[i].get(d.name, d.name),
                                  d.shape, d.dtype) for d in descs]
            out.extend(descs)
        return out

    @property
    def provide_label(self):
        out = []
        for i, it in enumerate(self.iters):
            descs = it.provide_label
            if self.rename_label:
                descs = [DataDesc(
                    self.rename_label[i].get(d.name, d.name),
                    d.shape, d.dtype) for d in descs]
            out.extend(descs)
        return out

    def _halt_worker(self):
        _halt_worker_thread(self._thread, self._queue, self._stop,
                            "PrefetchingIter")
        self._thread = None

    def _capture_epoch_state(self):
        """Snapshot the inner iterators' epoch-start state; the
        worker read-ahead makes their *live* state run ahead of what
        the consumer has seen, so state_dict pairs this snapshot with
        the delivered-batch count instead."""
        try:
            self._epoch_state = [it.state_dict() for it in self.iters]
        except NotImplementedError:
            self._epoch_state = None

    def reset(self):
        self._halt_worker()
        if self._pending_resume is not None:
            self._apply_resume()
        else:
            for it in self.iters:
                it.reset()
        self._stop = threading.Event()
        self._queue = queue.Queue(maxsize=self._depth)
        self._exhausted = False
        self._error = None
        self._delivered = 0
        self._capture_epoch_state()
        self._start()

    def _apply_resume(self):
        state = self._pending_resume
        self._pending_resume = None
        for it, s in zip(self.iters, state["inner"]):
            it.load_state_dict(s)
            # skip() fast-forwards to the resume batch and disarms
            # the inner one-shot reset shield — the prefetcher owns
            # resume here, so the next inner reset must be a real one
            it.skip(state["delivered"])

    def state_dict(self):
        if self._pending_resume is not None:
            return dict(self._pending_resume)    # un-applied restore
        if self._epoch_state is None:
            raise NotImplementedError(
                "PrefetchingIter state needs every inner iterator to "
                "implement state_dict")
        return {"type": "PrefetchingIter",
                "inner": self._epoch_state,
                "delivered": self._delivered}

    def load_state_dict(self, state):
        if len(state.get("inner") or []) != len(self.iters):
            raise ValueError(
                "state_dict holds state for "
                f"{len(state.get('inner') or [])} inner iterators; "
                f"this PrefetchingIter wraps {len(self.iters)}")
        self._halt_worker()
        self._pending_resume = dict(state)

    def next(self):
        if self._pending_resume is not None:
            self.reset()    # applies the restored position
        if self._exhausted:
            raise StopIteration
        if self._error is not None:
            raise self._error
        item = _bounded_get(self._queue, "PrefetchingIter",
                            thread=self._thread)
        if item is None:
            self._exhausted = True  # worker exited; don't block again
            raise StopIteration
        if isinstance(item, tuple) and len(item) == 2 \
                and item[0] == "err":
            if isinstance(item[1], DataPipelineError):
                self._error = item[1]   # already typed: keep the
            else:                       # actionable message on top
                self._error = DataPipelineError(
                    f"PrefetchingIter worker raised "
                    f"{type(item[1]).__name__}: {item[1]}")
                self._error.__cause__ = item[1]
            raise self._error
        batches = item
        self._delivered += 1
        telemetry.counter("prefetch_batches_total").inc()
        data = [d for b in batches for d in b.data]
        label = [l for b in batches for l in b.label]
        return DataBatch(data, label, pad=batches[0].pad)

    def iter_next(self):
        raise NotImplementedError("use next()")


class DevicePrefetchIter(DataIter):
    """Device-side double buffering: stage batch N+1's host→HBM
    transfer while batch N's step computes.

    The reference prefetches decoded batches into host memory
    (src/io/iter_prefetcher.h:47); the TPU-side half of that overlap
    is committing the batch to device memory *ahead* of the step, so
    the compiled executable never stalls on transfer.  jax transfers
    are started by a background thread here (``jax.device_put``
    returns immediately with the copy in flight), bounded by
    ``depth`` in-flight batches so HBM use stays at
    ``depth × batch_bytes``.

    Wrap any DataIter::

        train = mx.io.DevicePrefetchIter(
            mx.io.ImageRecordIter(...), ctx=mx.tpu(0))
        module.fit(train, ...)

    ``depth`` bounds the in-flight staged batches (HBM use stays at
    ``depth × batch_bytes``); ``None`` reads
    ``MXTPU_DEVICE_PREFETCH_DEPTH`` (default 2) so a fast
    multi-process producer (``DataServiceIter``) can deepen the
    device stage without code changes (docs/data_pipeline.md).
    """

    def __init__(self, data_iter, ctx=None, depth=None):
        super().__init__(data_iter.batch_size)
        from ..context import default_context
        from ..utils.env import get_env
        self._iter = data_iter
        self._ctx = ctx or default_context()
        if depth is None:
            depth = get_env("MXTPU_DEVICE_PREFETCH_DEPTH")
        self._depth = max(1, int(depth))
        self._delivered = 0
        self._pending_resume = None
        self._capture_epoch_state()
        self._spawn()

    def _capture_epoch_state(self):
        try:
            self._epoch_state = self._iter.state_dict()
        except NotImplementedError:
            self._epoch_state = None

    def _spawn(self):
        self._queue = queue.Queue(maxsize=self._depth)
        self._stop = threading.Event()
        self._terminal = None
        # the worker must capture ITS queue/stop: reading them off
        # self would let a worker that outlives a reset() resurrect
        # into the replacement queue
        q, stop = self._queue, self._stop

        def worker():
            import jax
            dev = self._ctx.jax_device
            while not stop.is_set():
                try:
                    inject("data", "device_prefetch")
                    batch = self._iter.next()
                except StopIteration:
                    _stop_aware_put(q, stop, ("end", None))
                    return
                except Exception as exc:     # surface in consumer
                    _stop_aware_put(q, stop, ("err", exc))
                    return
                try:
                    stage = [NDArray(jax.device_put(a._data, dev),
                                     self._ctx)
                             for a in batch.data]
                    label = [NDArray(jax.device_put(a._data, dev),
                                     self._ctx)
                             for a in (batch.label or [])]
                except Exception as exc:
                    _stop_aware_put(q, stop, ("err", exc))
                    return
                if not _stop_aware_put(q, stop, ("ok", DataBatch(
                        stage, label, pad=batch.pad,
                        provide_data=getattr(batch, "provide_data",
                                             None),
                        provide_label=getattr(batch, "provide_label",
                                              None)))):
                    return

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    @property
    def provide_data(self):
        return self._iter.provide_data

    @property
    def provide_label(self):
        return self._iter.provide_label

    def _halt_worker(self):
        _halt_worker_thread(self._thread, self._queue, self._stop,
                            "DevicePrefetchIter")

    def reset(self):
        self._halt_worker()
        if self._pending_resume is not None:
            state = self._pending_resume
            self._pending_resume = None
            self._iter.load_state_dict(state["inner"])
            self._iter.skip(state["delivered"])
        else:
            self._iter.reset()
        self._delivered = 0
        self._capture_epoch_state()
        self._spawn()

    def state_dict(self):
        if self._pending_resume is not None:
            return dict(self._pending_resume)
        if self._epoch_state is None:
            raise NotImplementedError(
                "DevicePrefetchIter state needs the inner iterator "
                "to implement state_dict")
        return {"type": "DevicePrefetchIter",
                "inner": self._epoch_state,
                "delivered": self._delivered}

    def load_state_dict(self, state):
        if state.get("type") != "DevicePrefetchIter":
            raise ValueError(
                f"state_dict type {state.get('type')!r} does not "
                "match DevicePrefetchIter")
        self._halt_worker()
        self._pending_resume = dict(state)

    def next(self):
        if self._pending_resume is not None:
            self.reset()    # applies the restored position
        if self._terminal is not None:     # worker is gone: re-raise
            kind, payload = self._terminal  # instead of blocking on a
            if kind == "end":               # producerless queue
                raise StopIteration
            raise payload
        kind, payload = _bounded_get(self._queue, "DevicePrefetchIter",
                                     thread=self._thread)
        if kind == "end":
            self._terminal = (kind, payload)
            raise StopIteration
        if kind == "err":
            if isinstance(payload, DataPipelineError):
                err = payload           # already typed: keep the
            else:                       # actionable message on top
                err = DataPipelineError(
                    f"DevicePrefetchIter worker raised "
                    f"{type(payload).__name__}: {payload}")
                err.__cause__ = payload
            self._terminal = (kind, err)
            raise err
        self._delivered += 1
        telemetry.counter("prefetch_batches_total").inc()
        return payload

    def iter_next(self):
        raise NotImplementedError("use next()")


class CSVIter(NDArrayIter):
    """CSV file iterator (ref: src/io/iter_csv.cc:151)."""

    def __init__(self, data_csv, data_shape, label_csv=None,
                 label_shape=(1,), batch_size=1, round_batch=True,
                 **kwargs):
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",",
                               dtype=np.float32)
            label = label.reshape((-1,) + tuple(label_shape))
            if label_shape == (1,):
                label = label.reshape(-1)
        super().__init__(data, label, batch_size,
                         last_batch_handle="pad" if round_batch
                         else "discard", label_name="label")


def _read_idx_images(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        assert magic == 2051, f"bad MNIST image magic {magic}"
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(
            n, rows, cols)


def _read_idx_labels(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        assert magic == 2049, f"bad MNIST label magic {magic}"
        return np.frombuffer(f.read(), dtype=np.uint8)


class MNISTIter(NDArrayIter):
    """MNIST idx-format iterator (ref: src/io/iter_mnist.cc:80)."""

    def __init__(self, image="train-images-idx3-ubyte",
                 label="train-labels-idx1-ubyte", batch_size=128,
                 shuffle=True, flat=False, seed=0, silent=False,
                 num_parts=1, part_index=0, **kwargs):
        imgs = _read_idx_images(image).astype(np.float32) / 255.0
        lbls = _read_idx_labels(label).astype(np.float32)
        if num_parts > 1:
            imgs = imgs[part_index::num_parts]
            lbls = lbls[part_index::num_parts]
        if flat:
            imgs = imgs.reshape(len(imgs), -1)
        else:
            imgs = imgs[:, None, :, :]
        super().__init__(imgs, lbls, batch_size, shuffle=shuffle)


class LibSVMIter(DataIter):
    """LibSVM text-format iterator yielding CSR data batches (ref:
    src/io/iter_libsvm.cc:200 — the reference also emits CSR)."""

    def __init__(self, data_libsvm, data_shape, label_shape=(1,),
                 batch_size=1, num_parts=1, part_index=0, **kwargs):
        dim = int(np.prod(data_shape))
        feats, labels = [], []
        with open(data_libsvm) as f:
            for ln in f:
                parts = ln.strip().split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                row = np.zeros(dim, np.float32)
                for tok in parts[1:]:
                    i, v = tok.split(":")
                    row[int(i)] = float(v)
                feats.append(row)
        feats = np.stack(feats)[part_index::num_parts]
        self._labels = np.asarray(labels,
                                  np.float32)[part_index::num_parts]
        self._feats = feats
        self._dim = dim
        self._cursor = 0
        super().__init__(batch_size)
        self.provide_data = [DataDesc("data", (batch_size, dim))]
        self.provide_label = [DataDesc("label",
                                       (batch_size,) + tuple(
                                           label_shape)
                                       if label_shape != (1,)
                                       else (batch_size,))]

    def reset(self):
        self._cursor = 0

    def iter_next(self):
        return self._cursor < len(self._feats)

    def next(self):
        if not self.iter_next():
            raise StopIteration
        from ..ndarray import sparse as nd_sparse
        from ..ndarray import array as nd_array
        i = self._cursor
        self._cursor += self.batch_size
        chunk = self._feats[i:i + self.batch_size]
        labels = self._labels[i:i + self.batch_size]
        pad = self.batch_size - len(chunk)
        if pad:  # pad the tail batch by wrapping (NDArrayIter 'pad')
            chunk = np.concatenate([chunk, self._feats[:pad]])
            labels = np.concatenate([labels, self._labels[:pad]])
        data = nd_sparse.csr_matrix(chunk)
        label = nd_array(labels)
        return DataBatch([data], [label], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)


def ImageRecordIter(*args, **kwargs):
    """RecordIO image pipeline (ref: iter_image_recordio_2.cc).
    Provided by the image/recordio milestone."""
    from ..image.record_iter import ImageRecordIter as _Impl
    return _Impl(*args, **kwargs)


def DataServiceIter(*args, **kwargs):
    """Sharded multi-process input data service: N decode worker
    processes feeding bounded shared-memory rings
    (docs/data_service.md).  Provided by the data_service package."""
    from ..data_service.service import DataServiceIter as _Impl
    return _Impl(*args, **kwargs)
