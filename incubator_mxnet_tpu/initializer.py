"""Weight initializers (ref: python/mxnet/initializer.py — InitDesc:34,
Initializer:53, Load:287, Mixed:334, Zero:377, One:402, Constant:426,
Uniform:442, Normal:475, Orthogonal:508, Xavier:545, MSRAPrelu:611,
Bilinear:635, LSTMBias:653)."""
import json
import re

import numpy as np

from . import nd
from .utils.registry import get_registry

__all__ = ["InitDesc", "Initializer", "Zero", "One", "Constant",
           "Uniform", "Normal", "Orthogonal", "Xavier", "MSRAPrelu",
           "Bilinear", "LSTMBias", "Mixed", "Load", "create", "register"]

_REG = get_registry("initializer")
register = _REG.register


def create(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    return _REG.get(name)(**kwargs)


class InitDesc(str):
    """Name + attrs describing how to init one parameter."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    """Base initializer; callable on (InitDesc, NDArray)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        return json.dumps([type(self).__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, str):
            raise TypeError("desc must be str/InitDesc")
        init_attr = getattr(desc, "attrs", {}).get("__init__", "")
        if init_attr:
            klass, kwargs = json.loads(init_attr)
            create(klass, **kwargs)._init_weight(desc, arr)
            return
        name = desc.lower()
        if name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("beta"):
            self._init_beta(desc, arr)
        elif "moving_mean" in name or "running_mean" in name:
            self._init_zero(desc, arr)
        elif ("moving_var" in name or "running_var" in name
              or "moving_inv_var" in name):
            self._init_one(desc, arr)
        elif "moving_avg" in name:
            self._init_zero(desc, arr)
        else:
            self._init_default(desc, arr)

    def _init_weight(self, desc, arr):
        raise NotImplementedError

    def _init_bias(self, desc, arr):
        arr[:] = 0.0

    def _init_gamma(self, desc, arr):
        arr[:] = 1.0

    def _init_beta(self, desc, arr):
        arr[:] = 0.0

    def _init_zero(self, desc, arr):
        arr[:] = 0.0

    def _init_one(self, desc, arr):
        arr[:] = 1.0

    def _init_default(self, desc, arr):
        self._init_weight(desc, arr)


@register("zeros")
class Zero(Initializer):
    def _init_weight(self, desc, arr):
        arr[:] = 0.0


@register("ones")
class One(Initializer):
    def _init_weight(self, desc, arr):
        arr[:] = 1.0


@register("constant")
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, desc, arr):
        arr[:] = self.value


@register("uniform")
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, desc, arr):
        nd.random.uniform(-self.scale, self.scale, arr.shape, out=arr)


@register("normal")
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, desc, arr):
        nd.random.normal(0, self.sigma, arr.shape, out=arr)


@register("orthogonal")
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, desc, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = np.random.normal(0.0, 1.0, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        arr[:] = nd.array(self.scale * q.reshape(arr.shape))


@register("xavier")
class Xavier(Initializer):
    """Glorot init (ref: initializer.py:545)."""

    def __init__(self, rnd_type="uniform", factor_type="avg",
                 magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, desc, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise ValueError(f"Xavier requires ndim>=2, got {desc} "
                             f"{shape}")
        if len(shape) > 2:
            hw_scale = np.prod(shape[2:])
        fan_in = shape[1] * hw_scale
        fan_out = shape[0] * hw_scale
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise ValueError("bad factor_type")
        scale = np.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            nd.random.uniform(-scale, scale, arr.shape, out=arr)
        elif self.rnd_type == "gaussian":
            nd.random.normal(0, scale, arr.shape, out=arr)
        else:
            raise ValueError("bad rnd_type")


@register("msraprelu")
class MSRAPrelu(Xavier):
    """He init for PReLU nets (ref: initializer.py:611)."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register("bilinear")
class Bilinear(Initializer):
    """Bilinear upsampling kernel (ref: initializer.py:635)."""

    def _init_weight(self, desc, arr):
        weight = np.zeros(int(np.prod(arr.shape)), dtype="float32")
        shape = arr.shape
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(np.prod(shape)):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = nd.array(weight.reshape(shape))


@register("lstmbias")
class LSTMBias(Initializer):
    """Forget-gate bias init (ref: initializer.py:653)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        arr[:] = 0.0
        num_hidden = int(arr.shape[0] / 4)
        a = arr.asnumpy()
        a[num_hidden:2 * num_hidden] = self.forget_bias
        arr[:] = nd.array(a)

    _init_bias = _init_weight
    _init_default = _init_weight


@register("mixed")
class Mixed(Initializer):
    """Pattern-routed initializers (ref: initializer.py:334)."""

    def __init__(self, patterns, initializers):
        super().__init__()
        if len(patterns) != len(initializers):
            raise ValueError("patterns and initializers must pair up")
        self.map = list(zip([re.compile(p) for p in patterns],
                            initializers))

    def __call__(self, desc, arr):
        for prog, init in self.map:
            if prog.match(str(desc)):
                init(desc, arr)
                return
        raise ValueError(f"no initializer pattern matches {desc}; add "
                         "a '.*' fallback")


@register("load")
class Load:
    """Init from saved params dict (ref: initializer.py:287)."""

    def __init__(self, param, default_init=None, verbose=False):
        if isinstance(param, str):
            param = nd.load(param)
        self.param = {k.split(":", 1)[-1]: v for k, v in param.items()}
        self.default_init = default_init

    def __call__(self, desc, arr):
        name = str(desc)
        if name in self.param:
            src = self.param[name]
            if src.shape != arr.shape:
                raise ValueError(
                    f"shape mismatch for {name}: saved {src.shape} vs "
                    f"required {arr.shape}")
            arr[:] = src
        else:
            if self.default_init is None:
                raise ValueError(f"no saved param and no default init "
                                 f"for {name}")
            self.default_init(desc, arr)


# `init` namespace alias used as mx.init.Xavier() in the reference
class _InitModule:
    InitDesc = InitDesc
    Initializer = Initializer
    Zero = Zero
    One = One
    Constant = Constant
    Uniform = Uniform
    Normal = Normal
    Orthogonal = Orthogonal
    Xavier = Xavier
    MSRAPrelu = MSRAPrelu
    Bilinear = Bilinear
    LSTMBias = LSTMBias
    Mixed = Mixed
    Load = Load


init = _InitModule()
