"""Profiler: per-op timing + chrome://tracing dump (ref:
src/engine/profiler.h OprExecStat:39 / Profiler:80 / DumpProfile:107,
python/mxnet/profiler.py, env vars MXNET_PROFILER_AUTOSTART).

Two layers, mirroring the reference's split between its own op stats
and nvprof:
- framework layer: every imperative op dispatch is timed (optionally
  synchronized for true kernel time, mode='sync') and dumped as
  chrome://tracing JSON via dump_profile();
- XLA layer: start_xla_trace/stop_xla_trace wrap jax.profiler for
  TensorBoard/Perfetto-grade device traces.
"""
import json
import os
import threading
import time

__all__ = ["set_config", "set_state", "dump_profile", "pause",
           "resume", "start_xla_trace", "stop_xla_trace", "Profiler"]

# synthetic thread ids ("lanes") for async request streams: chrome
# tracing wants a tid per row, and the serving engine's per-request
# b/e events should render on named serving rows next to the span
# stream, not interleaved with real thread ids
SERVE_QUEUE_LANE = 900000
SERVE_SLOT_LANE0 = 900001


class Profiler:
    """Singleton collecting OprExecStat-style events."""

    def __init__(self):
        self.filename = "profile.json"
        self.mode = "coarse"  # 'coarse' | 'sync' (block per op)
        self.state = "stop"
        self._events = []
        self._lock = threading.Lock()
        self._dump_lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._tls = threading.local()
        self._lanes = {}    # synthetic tid -> display name ('M')

    # ------------------------------------------------------------ api
    def set_config(self, filename="profile.json", mode="coarse",
                   **_ignored):
        self.filename = filename
        self.mode = mode

    def set_state(self, state):
        assert state in ("run", "stop")
        self.state = state

    @property
    def running(self):
        return self.state == "run"

    def add_event(self, name, t_start, t_end, category="operator"):
        with self._lock:
            self._events.append({
                "name": name, "cat": category, "ph": "X",
                "ts": (t_start - self._t0) * 1e6,
                "dur": (t_end - t_start) * 1e6,
                "pid": os.getpid(), "tid": threading.get_ident(),
            })

    def add_async_event(self, name, aid, ph, category="serving",
                        lane=None, args=None):
        """Record one chrome-tracing *async* event: ``ph`` is ``"b"``
        (begin) or ``"e"`` (end), paired by ``(cat, id, name)``.
        Async events span dispatch sites — the serving engine opens a
        request's ``queue_wait`` in ``submit()`` and closes it at
        admission, iterations apart — which ``X`` duration events
        cannot express.  ``lane`` places the event on a synthetic,
        named timeline row (see :meth:`set_lane_name`)."""
        if ph not in ("b", "e"):
            raise ValueError(f"async phase must be 'b'/'e' ({ph!r})")
        ev = {"name": name, "cat": category, "ph": ph, "id": str(aid),
              "ts": (time.perf_counter() - self._t0) * 1e6,
              "pid": os.getpid(),
              "tid": lane if lane is not None
              else threading.get_ident()}
        if args:
            ev["args"] = dict(args)
        with self._lock:
            self._events.append(ev)

    def set_lane_name(self, lane, name):
        """Name a synthetic lane (tid); dumped as ``thread_name``
        ``M`` metadata so Perfetto shows e.g. 'serve slot 0' instead
        of a bare number."""
        with self._lock:
            self._lanes[int(lane)] = str(name)

    def _meta_events(self, events):
        """chrome-tracing metadata ('M') events: name the process and
        every thread that recorded an event, so the timeline rows
        read 'mxtpu rank N' / real thread names instead of bare
        ids."""
        pid = os.getpid()
        rank = os.environ.get("MXTPU_WORKER_RANK", "0")
        out = [{"name": "process_name", "ph": "M", "pid": pid,
                "args": {"name": f"mxtpu rank {rank}"}}]
        names = {t.ident: t.name for t in threading.enumerate()}
        with self._lock:
            names.update(self._lanes)
        tids = {e["tid"] for e in events if "tid" in e}
        for tid in sorted(tids):
            out.append({"name": "thread_name", "ph": "M", "pid": pid,
                        "tid": tid,
                        "args": {"name": names.get(tid,
                                                   f"thread-{tid}")}})
        return out

    def _counter_events(self):
        """Telemetry registry counters/gauges as chrome-tracing
        counter ('C') events stamped at dump time, so spans and
        counters land in ONE timeline (docs/observability.md)."""
        from . import telemetry
        if not telemetry.enabled():
            return []
        snap = telemetry.snapshot()
        ts = (time.perf_counter() - self._t0) * 1e6
        pid = os.getpid()
        events = []
        for kind in ("counters", "gauges"):
            for name, value in sorted(snap[kind].items()):
                events.append({"name": name, "cat": "telemetry",
                               "ph": "C", "ts": ts, "pid": pid,
                               "args": {name: value}})
        return events

    def dump(self, finished=True):
        """Write the trace.  Concurrent-safe: dumps are serialized
        (two racing dumps can no longer interleave writes into the
        same file, where the empty loser used to clobber the winner
        and lose its events), the snapshot-and-clear is atomic under
        the event lock (an add_event racing the file write lands in
        the retained buffer for the *next* dump instead of being
        dropped), and the file itself is written temp + rename so a
        reader never sees a torn JSON document."""
        with self._dump_lock:
            with self._lock:
                events = self._events
                if finished:
                    self._events = []
                else:
                    events = list(events)
            data = {"traceEvents":
                    self._meta_events(events) + events
                    + self._counter_events()}
            # resilience's mkstemp-based temp+fsync+rename: unique
            # tmp names (no concurrent-writer collision) and cleanup
            # on a failed serialize
            from . import resilience
            resilience._replace_with_bytes(
                self.filename, json.dumps(data).encode(),
                sync_dir=False)
        return self.filename

    # -------------------------------------------------- op dispatch hook
    def record_op(self, name, outs):
        """Called from imperative_invoke when running."""
        if self.mode == "sync":
            for o in outs:
                try:
                    o.block_until_ready()
                except AttributeError:
                    pass
        t0 = getattr(self._tls, "pending_t0", None)
        self.add_event(name, t0 if t0 is not None
                       else time.perf_counter(), time.perf_counter())

    def op_start(self):
        # per-thread start time: ops dispatched concurrently from the
        # threaded data pipeline must not cross-read each other's t0
        self._tls.pending_t0 = time.perf_counter()


_profiler = Profiler()


def set_config(**kwargs):
    """(ref: profiler.py profiler_set_config)"""
    _profiler.set_config(**kwargs)


def set_state(state="stop"):
    """'run' or 'stop' (ref: profiler.py profiler_set_state)."""
    _profiler.set_state(state)


def pause():
    _profiler.set_state("stop")


def resume():
    _profiler.set_state("run")


def dump_profile():
    """Write chrome://tracing JSON (ref: MXDumpProfile)."""
    return _profiler.dump()


def start_xla_trace(logdir="/tmp/xla_trace"):
    """Device-level trace via the XLA profiler (TensorBoard-viewable)."""
    import jax
    jax.profiler.start_trace(logdir)
    return logdir


def stop_xla_trace():
    import jax
    jax.profiler.stop_trace()


# autostart parity (ref: env var MXNET_PROFILER_AUTOSTART)
if os.environ.get("MXTPU_PROFILER_AUTOSTART", "") == "1":
    _profiler.set_state("run")
