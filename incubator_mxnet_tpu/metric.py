"""Evaluation metrics (ref: python/mxnet/metric.py — EvalMetric:44,
CompositeEvalMetric:209, Accuracy:339, TopKAccuracy:404, F1:478,
Perplexity:573, MAE:678, MSE:737, RMSE:795, CrossEntropy:854,
NegativeLogLikelihood:922, PearsonCorrelation:990, Loss:1043,
CustomMetric:1087)."""
import math

import numpy as np

from .ndarray.ndarray import NDArray
from .utils.registry import get_registry

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy",
           "TopKAccuracy", "F1", "Perplexity", "MAE", "MSE", "RMSE",
           "CrossEntropy", "NegativeLogLikelihood",
           "PearsonCorrelation", "Loss", "CustomMetric", "np_metric",
           "create", "register"]

_REG = get_registry("metric")
register = _REG.register


def create(metric, *args, **kwargs):
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, (list, tuple)):
        composite = CompositeEvalMetric()
        for m in metric:
            composite.add(create(m, *args, **kwargs))
        return composite
    return _REG.get(metric)(*args, **kwargs)


def _as_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return np.asarray(x)


def check_label_shapes(labels, preds, shape=False):
    if len(labels) != len(preds):
        raise ValueError(f"labels ({len(labels)}) and predictions "
                         f"({len(preds)}) must have equal length")


class EvalMetric:
    """Metric base (ref: metric.py:44)."""

    def __init__(self, name, output_names=None, label_names=None):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0
        self.num_nonfinite = 0          # excluded bad batches
        self._nonfinite_warned = False

    def _include(self, sum_inc, num_inc):
        """Guarded accumulate into the running sums.

        A non-finite increment — the footprint of a NaN/Inf pred or
        label batch — is *excluded* (counted in ``num_nonfinite``,
        warned once) instead of being added: one bad batch must not
        turn every subsequent ``get()`` into NaN for the rest of the
        epoch.  Returns True when the increment was applied."""
        if not np.all(np.isfinite(sum_inc)):
            self.num_nonfinite += 1
            if not self._nonfinite_warned:
                import warnings
                warnings.warn(
                    f"metric {self.name}: non-finite batch update "
                    f"({sum_inc}) excluded from the running sum; "
                    "further exclusions counted in num_nonfinite "
                    "(warned once)", RuntimeWarning)
                self._nonfinite_warned = True
            return False
        self.sum_metric += sum_inc
        self.num_inst += num_inc
        return True

    def update(self, labels, preds):
        raise NotImplementedError

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[n] for n in self.output_names if n in pred]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[n] for n in self.label_names if n in label]
        else:
            label = list(label.values())
        self.update(label, pred)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def get_config(self):
        return {"metric": type(self).__name__, "name": self.name}

    def __str__(self):
        return f"EvalMetric: {dict(self.get_name_value())}"


class CompositeEvalMetric(EvalMetric):
    """(ref: metric.py:209)"""

    def __init__(self, metrics=None, name="composite",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def get(self):
        names, values = [], []
        for m in self.metrics:
            name, value = m.get()
            names.append(name)
            values.append(value)
        return names, values


@register("acc")
class Accuracy(EvalMetric):
    """(ref: metric.py:339)"""

    def __init__(self, axis=1, name="accuracy", **kwargs):
        super().__init__(name, **kwargs)
        self.axis = axis

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            pred, label = _as_np(pred), _as_np(label)
            if pred.ndim > label.ndim:
                pred = pred.argmax(axis=self.axis)
            pred = pred.astype("int32").flatten()
            label = label.astype("int32").flatten()
            self.sum_metric += (pred == label).sum()
            self.num_inst += len(label)


@register("top_k_accuracy")
class TopKAccuracy(EvalMetric):
    """(ref: metric.py:404)"""

    def __init__(self, top_k=1, name="top_k_accuracy", **kwargs):
        super().__init__(f"{name}_{top_k}", **kwargs)
        self.top_k = top_k

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            pred, label = _as_np(pred), _as_np(label).astype("int32")
            idx = np.argsort(pred, axis=1)[:, ::-1][:, :self.top_k]
            self.sum_metric += (idx == label.reshape(-1, 1)).any(1).sum()
            self.num_inst += len(label)


@register("f1")
class F1(EvalMetric):
    """Binary F1 (ref: metric.py:478)"""

    def __init__(self, name="f1", average="macro", **kwargs):
        super().__init__(name, **kwargs)
        self.average = average
        self.reset_stats()

    def reset_stats(self):
        self._tp = self._fp = self._fn = 0.0

    def reset(self):
        super().reset()
        self.reset_stats()

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            pred, label = _as_np(pred), _as_np(label)
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = pred.argmax(-1)
            pred = (pred.flatten() > 0.5).astype(int) if \
                pred.dtype.kind == "f" and pred.ndim == label.ndim \
                else pred.flatten().astype(int)
            label = label.flatten().astype(int)
            self._tp += ((pred == 1) & (label == 1)).sum()
            self._fp += ((pred == 1) & (label == 0)).sum()
            self._fn += ((pred == 0) & (label == 1)).sum()
            prec = self._tp / max(self._tp + self._fp, 1e-12)
            rec = self._tp / max(self._tp + self._fn, 1e-12)
            f1 = 2 * prec * rec / max(prec + rec, 1e-12)
            self.sum_metric = f1
            self.num_inst = 1


@register("perplexity")
class Perplexity(EvalMetric):
    """(ref: metric.py:573)"""

    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 **kwargs):
        super().__init__(name, **kwargs)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        loss, num = 0.0, 0
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            label = label.reshape(-1).astype("int32")
            pred = pred.reshape(-1, pred.shape[-1])
            probs = pred[np.arange(len(label)), label]
            if self.ignore_label is not None:
                ignore = label == self.ignore_label
                probs = np.where(ignore, 1.0, probs)
                num -= ignore.sum()
            loss -= np.log(np.maximum(probs, 1e-10)).sum()
            num += len(label)
        self._include(loss, num)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@register("mae")
class MAE(EvalMetric):
    def __init__(self, name="mae", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            label = label.reshape(pred.shape)
            self._include(np.abs(label - pred).mean(), 1)


@register("mse")
class MSE(EvalMetric):
    def __init__(self, name="mse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            label = label.reshape(pred.shape)
            self._include(((label - pred) ** 2).mean(), 1)


@register("rmse")
class RMSE(EvalMetric):
    def __init__(self, name="rmse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            label = label.reshape(pred.shape)
            self._include(np.sqrt(((label - pred) ** 2).mean()), 1)


@register("ce", aliases=["cross-entropy"])
class CrossEntropy(EvalMetric):
    """(ref: metric.py:854)"""

    def __init__(self, eps=1e-12, name="cross-entropy", **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            label = label.reshape(-1).astype("int32")
            prob = pred[np.arange(len(label)), label]
            self._include((-np.log(prob + self.eps)).sum(), len(label))


@register("nll_loss")
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", **kwargs):
        super().__init__(eps=eps, name=name, **kwargs)


@register("pearsonr")
class PearsonCorrelation(EvalMetric):
    """(ref: metric.py:990)"""

    def __init__(self, name="pearsonr", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label).reshape(-1), \
                _as_np(pred).reshape(-1)
            if len(label) > 1:
                self._include(np.corrcoef(label, pred)[0, 1], 1)


@register("loss")
class Loss(EvalMetric):
    """Mean of raw outputs, for loss-symbol heads (ref: metric.py:1043)."""

    def __init__(self, name="loss", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, _, preds):
        for pred in preds:
            pred = _as_np(pred)
            self._include(pred.sum(), pred.size)


class CustomMetric(EvalMetric):
    """(ref: metric.py:1087)"""

    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 **kwargs):
        name = name or getattr(feval, "__name__", "custom")
        super().__init__(f"custom({name})", **kwargs)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            reval = self._feval(_as_np(label), _as_np(pred))
            if isinstance(reval, tuple):
                s, n = reval
                self._include(s, n)
            else:
                self._include(reval, 1)


def np_metric(numpy_feval, name=None, allow_extra_outputs=False):
    """Wrap a numpy feval into a metric (ref: metric.py np)."""
    return CustomMetric(numpy_feval, name, allow_extra_outputs)
