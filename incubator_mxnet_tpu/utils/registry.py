"""Generic name->object registry with alias support.

Mirrors the registry discipline used across the reference
(ref: python/mxnet/registry.py, nnvm Op registry): a single source of
truth from which frontends generate their surfaces.
"""


class Registry:
    def __init__(self, kind):
        self.kind = kind
        self._entries = {}

    def register(self, name=None, obj=None, aliases=()):
        """Register ``obj`` under ``name``; usable as a decorator."""
        def _do(o, nm):
            nm = nm or getattr(o, "__name__", None)
            if nm is None:
                raise ValueError("registry entry needs a name")
            key = nm.lower()
            if key in self._entries and self._entries[key] is not o:
                raise ValueError(
                    f"{self.kind} '{nm}' already registered")
            self._entries[key] = o
            for a in aliases:
                self._entries[a.lower()] = o
            return o
        if obj is not None:
            return _do(obj, name)
        if callable(name):  # bare decorator: @reg.register
            return _do(name, None)
        return lambda o: _do(o, name)

    def get(self, name):
        try:
            return self._entries[name.lower()]
        except KeyError:
            raise KeyError(
                f"unknown {self.kind} '{name}'; known: "
                f"{sorted(self._entries)}") from None

    def find(self, name):
        return self._entries.get(name.lower())

    def __contains__(self, name):
        return name.lower() in self._entries

    def keys(self):
        return sorted(self._entries)


_REGISTRIES = {}


def get_registry(kind):
    if kind not in _REGISTRIES:
        _REGISTRIES[kind] = Registry(kind)
    return _REGISTRIES[kind]
