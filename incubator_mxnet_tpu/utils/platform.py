"""Host-platform pinning for CLI entry points.

``MXTPU_FORCE_CPU=1`` pins jax to the host CPU with 8 virtual
devices — for machines whose accelerator plugin is absent or
unhealthy.  Env vars alone are overridden by a sitecustomize that
forces the accelerator platform; the in-process config update is
authoritative, provided no backend has initialized yet (importing
jax or this package is fine; creating an array is not).
"""
import os

__all__ = ["maybe_force_cpu"]


def maybe_force_cpu():
    if not os.environ.get("MXTPU_FORCE_CPU"):
        return False
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    return True
