"""Host-platform pinning for CLI entry points.

``MXTPU_FORCE_CPU=1`` pins jax to the host CPU with 8 virtual
devices — for machines whose accelerator plugin is absent or
unhealthy.  Env vars alone are overridden by a sitecustomize that
forces the accelerator platform; the in-process config update is
authoritative, provided no backend has initialized yet (importing
jax or this package is fine; creating an array is not).
"""
import os
import warnings

__all__ = ["maybe_force_cpu", "enable_compile_cache"]


def enable_compile_cache(path=None):
    """Point jax's persistent compilation cache at ``path`` (default
    ``$MXTPU_COMPILE_CACHE`` or ``~/.cache/mxtpu-xla``).

    Why this matters here: over the axon tunnel a cold ResNet-50
    train-step compile runs tens of minutes; a benchmark attempt that
    times out would otherwise pay it all again on retry.  With the
    persistent cache the first (even killed) attempt seeds the disk
    cache for every later process.  Safe no-op when the backend
    can't serialize executables (jax logs and continues)."""
    import jax
    path = path or os.environ.get(
        "MXTPU_COMPILE_CACHE",
        os.path.expanduser("~/.cache/mxtpu-xla"))
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # cache everything, however quick the compile (-1 disables
        # the entry-size floor; 0 would mean "use the default")
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update(
            "jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as exc:        # old jaxlib knob names
        warnings.warn(f"compile cache not enabled: {exc}",
                      stacklevel=2)
        return False
    return True


def maybe_force_cpu():
    """Returns True iff the CPU pin is in effect.  '0'/'false'/unset
    disable it; a warning is issued when pinning is requested but can
    no longer take effect (a backend already initialized)."""
    flag = os.environ.get("MXTPU_FORCE_CPU", "").lower()
    if flag in ("", "0", "false", "no"):
        return False
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax
    try:
        from jax._src import xla_bridge as _xb
        if _xb.backends_are_initialized():
            if any(p != "cpu" for p in _xb._backends):
                warnings.warn(
                    "MXTPU_FORCE_CPU set, but an accelerator backend "
                    "already initialized — the CPU pin cannot take "
                    "effect; call maybe_force_cpu() before any device "
                    "op", stacklevel=2)
                return False
            return True
    except (ImportError, AttributeError):
        pass
    jax.config.update("jax_platforms", "cpu")
    return True
