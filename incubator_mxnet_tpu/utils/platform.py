"""Host-platform pinning for CLI entry points.

``MXTPU_FORCE_CPU=1`` pins jax to the host CPU with 8 virtual
devices — for machines whose accelerator plugin is absent or
unhealthy.  Env vars alone are overridden by a sitecustomize that
forces the accelerator platform; the in-process config update is
authoritative, provided no backend has initialized yet (importing
jax or this package is fine; creating an array is not).
"""
import os
import warnings

__all__ = ["maybe_force_cpu"]


def maybe_force_cpu():
    """Returns True iff the CPU pin is in effect.  '0'/'false'/unset
    disable it; a warning is issued when pinning is requested but can
    no longer take effect (a backend already initialized)."""
    flag = os.environ.get("MXTPU_FORCE_CPU", "").lower()
    if flag in ("", "0", "false", "no"):
        return False
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax
    try:
        from jax._src import xla_bridge as _xb
        if _xb.backends_are_initialized():
            if any(p != "cpu" for p in _xb._backends):
                warnings.warn(
                    "MXTPU_FORCE_CPU set, but an accelerator backend "
                    "already initialized — the CPU pin cannot take "
                    "effect; call maybe_force_cpu() before any device "
                    "op", stacklevel=2)
                return False
            return True
    except (ImportError, AttributeError):
        pass
    jax.config.update("jax_platforms", "cpu")
    return True
