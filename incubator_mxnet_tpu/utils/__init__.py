"""Base utilities: env-flag registry, logging, generic class registry.

TPU-native replacement for the roles dmlc-core plays in the reference
(ref: dmlc::GetEnv use sites, dmlc LOG/CHECK, python/mxnet/registry.py).
"""
from .env import EnvFlag, get_env, register_env, list_env
from .registry import Registry, get_registry
from .log import get_logger

__all__ = [
    "EnvFlag", "get_env", "register_env", "list_env",
    "Registry", "get_registry", "get_logger",
]
