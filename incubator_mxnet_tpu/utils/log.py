"""Logging facade (role of dmlc LOG/CHECK in the reference)."""
import logging

_FMT = "[%(asctime)s] %(levelname)s %(name)s: %(message)s"


def get_logger(name="mxtpu", level=None):
    logger = logging.getLogger(name)
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(_FMT))
        logger.addHandler(h)
        logger.propagate = False
    if level is not None:
        logger.setLevel(level)
    elif logger.level == logging.NOTSET:
        logger.setLevel(logging.INFO)
    return logger
