"""Shared concurrency helpers for producer/consumer pipelines."""
import collections

__all__ = ["bounded_window"]


def bounded_window(items, submit, max_inflight):
    """Yield submitted handles in order with at most ``max_inflight``
    outstanding.  The backpressure pattern shared by the DataLoader
    worker pool and the im2rec encoder: unconsumed results hold
    memory (or /dev/shm segments), so producers must not run a whole
    epoch ahead of the consumer (the reference bounds its queues the
    same way, ~2x the worker count)."""
    inflight = collections.deque()
    it = iter(items)
    exhausted = False
    while inflight or not exhausted:
        while not exhausted and len(inflight) < max_inflight:
            try:
                item = next(it)
            except StopIteration:
                exhausted = True
                break
            inflight.append(submit(item))
        if inflight:
            yield inflight.popleft()
