"""Runtime environment-flag registry.

The reference reads ~29 documented env vars ad hoc via ``dmlc::GetEnv``
(ref: docs/faq/env_var.md).  Here flags are declared once in a central
registry so ``list_env()`` is always complete and typos fail loudly.

Flags use the ``MXTPU_`` prefix; the reference's ``MXNET_`` prefix is
accepted as a fallback for familiarity.
"""
import os

_REGISTRY = {}


class EnvFlag:
    """A declared environment flag with type, default and docstring."""

    __slots__ = ("name", "type", "default", "help")

    def __init__(self, name, type_, default, help_=""):
        self.name = name
        self.type = type_
        self.default = default
        self.help = help_

    def get(self):
        raw = os.environ.get(self.name)
        if raw is None and self.name.startswith("MXTPU_"):
            raw = os.environ.get("MXNET_" + self.name[len("MXTPU_"):])
        if raw is None:
            return self.default
        if self.type is bool:
            return raw not in ("0", "false", "False", "")
        try:
            return self.type(raw)
        except ValueError:
            return self.default


def register_env(name, type_, default, help_=""):
    flag = EnvFlag(name, type_, default, help_)
    _REGISTRY[name] = flag
    return flag


def get_env(name):
    """Read a registered env flag (raises KeyError on unregistered names)."""
    return _REGISTRY[name].get()


def list_env():
    """All registered flags, for docs/diagnose output."""
    return dict(_REGISTRY)


# Core runtime flags (analogs of the reference's engine/exec/kvstore vars).
register_env("MXTPU_ENGINE_TYPE", str, "async",
             "'async' (default, XLA async dispatch) or 'naive' "
             "(block after every op; analog of MXNET_ENGINE_TYPE=NaiveEngine)")
register_env("MXTPU_EXEC_BULK_EXEC_TRAIN", bool, True,
             "fuse forward+backward into one compiled executable")
register_env("MXTPU_DEFAULT_DTYPE", str, "float32",
             "default dtype for new arrays")
register_env("MXTPU_ENABLE_X64", bool, False, "enable float64/int64 support")
register_env("MXTPU_PROFILER_AUTOSTART", bool, False,
             "start the profiler at import time")
register_env("MXTPU_PROFILER_DIR", str, "profile_output",
             "directory for profiler trace dumps")
register_env("MXTPU_KVSTORE_BIGARRAY_BOUND", int, 1000000,
             "size threshold for chunked kvstore reductions")
register_env("MXTPU_CPU_WORKER_NTHREADS", int, 4,
             "host worker threads for data pipeline")
register_env("MXTPU_SEED", int, 0, "global RNG seed at import")

# Graph optimization (graph/; docs/graph_passes.md).
register_env("MXTPU_GRAPH_OPT", int, 1,
             "graph-optimization level for Executor.bind and CachedOp "
             "symbol tracing: 0 disables the pass pipeline, 1 "
             "(default) runs the safe structural passes (identity/"
             "transpose-pair elimination, constant folding, CSE, "
             "dead-node pruning), 2 adds elementwise-chain "
             "pre-fusion")
register_env("MXTPU_CACHEDOP_CAPACITY", int, 64,
             "max compiled signatures a hybridized block's CachedOp "
             "retains (LRU eviction); <=0 disables the bound")

# Serving tier (serving/; docs/serving.md).
register_env("MXTPU_SERVE_BLOCK_SIZE", int, 16,
             "tokens per paged-KV block in the serving engine; "
             "smaller = less tail waste per sequence, larger = "
             "fewer gather indices per step")
register_env("MXTPU_SERVE_NUM_BLOCKS", int, 512,
             "KV block-pool size per layer (block 0 is the reserved "
             "scratch block); bounds total serving HBM at "
             "n_layers * 2 * num_blocks * block_size * kv_heads * "
             "head_dim floats")
register_env("MXTPU_SERVE_MAX_BATCH", int, 8,
             "concurrent decode slots in the continuous-batching "
             "scheduler (the compiled step's batch dimension)")
register_env("MXTPU_SERVE_PREFIX_CACHE", bool, True,
             "share prompt-prefix KV blocks across requests by "
             "token-hash (copy-free; refcounted); 0 disables")
register_env("MXTPU_SERVE_QUANT", str, "off",
             "serving weight quantization: 'off' (fp32) or 'int8' "
             "(per-output-channel symmetric, fp32 scales, "
             "dequantized inside the compiled step)")

# Serving SLO / survival layer (docs/serving.md "SLOs, shedding,
# and drain").  All deadline arithmetic is monotonic-clock
# (lint-enforced); 0 disables each knob.
register_env("MXTPU_SERVE_TTFT_DEADLINE", float, 0.0,
             "default per-request time-to-first-token deadline (s) "
             "for ServingEngine.submit; a request still waiting for "
             "its first token past this expires (terminal state "
             "'expired', blocks freed same iteration); 0 disables")
register_env("MXTPU_SERVE_DEADLINE", float, 0.0,
             "default per-request total deadline (s): submit -> "
             "last token; an in-flight request past it expires with "
             "its partial output retained; 0 disables")
register_env("MXTPU_SERVE_QUEUE_LIMIT", int, 0,
             "bounded serving wait queue: submit() raises "
             "ServeRejectedError once this many requests are "
             "queued, shedding load at the door instead of letting "
             "queue wait grow without bound; 0 = unbounded")
register_env("MXTPU_SERVE_QUEUE_TOKENS", int, 0,
             "queued prompt-token budget: submit() rejects when the "
             "waiting queue's summed token length would exceed "
             "this (bounds requeue/recompute debt, not just "
             "request count); 0 = unbounded")
register_env("MXTPU_SERVE_STEP_TIMEOUT", float, 0.0,
             "decode-step watchdog budget (s): an engine iteration "
             "whose decode step runs longer logs loudly, records a "
             "serve_step_overrun trace event and dumps the flight "
             "recorder (MXTPU_TRACE_DUMP) — detection, not "
             "interruption: a wedged device call is the heartbeat "
             "monitor's job; 0 disables")

# Serving fleet (serving/rpc.py, router.py, replica.py,
# tools/launch.py --serve-fleet; docs/serving.md "Fleet").
register_env("MXTPU_RPC_TIMEOUT", float, 30.0,
             "default per-call deadline (s) for every fleet RPC "
             "socket wait (connect, frame send, frame recv); rpc.py "
             "refuses unbounded waits, so 0 is coerced to this "
             "default rather than disabling the bound")
register_env("MXTPU_ROUTER_PORT", int, 0,
             "port ServingRouter.listen() binds its client-facing "
             "RPC front door to; 0 = ephemeral (the bound port is "
             "reported by listen())")
register_env("MXTPU_FLEET_REPLICAS", int, 0,
             "replica count exported by tools/launch.py "
             "--serve-fleet to every fleet process; 0 = not "
             "launcher-managed")
register_env("MXTPU_BREAKER_THRESHOLD", int, 3,
             "consecutive per-replica dispatch failures that trip "
             "the router's circuit breaker from closed to open")
register_env("MXTPU_BREAKER_COOLDOWN", float, 5.0,
             "seconds an open breaker waits before half-open admits "
             "exactly one probe request (monotonic clock)")
register_env("MXTPU_FLEET_ROLE", str, "",
             "role exported by tools/launch.py --serve-fleet to "
             "each fleet process: 'router' or 'replica' (empty = "
             "not fleet-launched)")
register_env("MXTPU_REPLICA_ADDRS", str, "",
             "comma-separated host:port list of replica RPC servers "
             "exported by tools/launch.py --serve-fleet to the "
             "router process")
register_env("MXTPU_REPLICA_PORT", int, 0,
             "port a replica worker binds its RPC server to "
             "(exported per replica by tools/launch.py "
             "--serve-fleet); 0 = ephemeral")

# Resilience layer (resilience.py; docs/resilience.md).
register_env("MXTPU_COLLECTIVE_TIMEOUT", float, 600.0,
             "wall-clock deadline (s) for dist collectives; a hung "
             "allreduce/broadcast/barrier raises a diagnostic "
             "DeadlineExceededError instead of blocking forever; "
             "0 disables")
register_env("MXTPU_RETRY_MAX", int, 4,
             "max retries for transient dist failures "
             "(coordinator join, kvstore push/pull)")
register_env("MXTPU_RETRY_BASE_DELAY_S", float, 0.1,
             "first backoff delay (s); doubles per retry")
register_env("MXTPU_RETRY_MAX_DELAY_S", float, 5.0,
             "backoff delay cap (s)")
register_env("MXTPU_RETRY_JITTER", float, 0.25,
             "fraction of each backoff delay added as random jitter")
register_env("MXTPU_FAULT_SPEC", str, "",
             "deterministic fault injection: comma-separated "
             "scope:op:nth:kind entries, e.g. "
             "'collective:allreduce:2:hang,checkpoint:save:1:truncate'")
register_env("MXTPU_FAULT_HANG_S", float, 3600.0,
             "how long an injected 'hang' fault sleeps")
register_env("MXTPU_HEARTBEAT_FILE", str, "",
             "path the worker's heartbeat thread refreshes (set per "
             "worker by tools/launch.py; empty disables heartbeats)")
register_env("MXTPU_HEARTBEAT_INTERVAL", float, 2.0,
             "seconds between per-worker heartbeat file refreshes")
register_env("MXTPU_HEARTBEAT_TIMEOUT", float, 60.0,
             "launcher kills a worker whose heartbeat is staler than "
             "this (s); 0 disables hung-worker detection")
register_env("MXTPU_CKPT_FALLBACK", bool, True,
             "on corrupt/truncated checkpoint load, fall back to the "
             "newest earlier checkpoint that validates")

# Elastic multi-chip training (parallel/checkpoint.py, dist.py,
# tools/launch.py --elastic; docs/elastic.md).
register_env("MXTPU_CKPT_KEEP", int, 3,
             "sharded-checkpoint generations retained per directory "
             "(parallel.checkpoint.save_sharded prunes older "
             "fully-committed generations past this); <=0 keeps all")
register_env("MXTPU_ELASTIC", bool, False,
             "exported by tools/launch.py --elastic: workers treat "
             "an uncaught CollectiveAbortedError / collective "
             "DeadlineExceededError as a coordinated elastic abort "
             "and exit with the distinct elastic code (14) so the "
             "launcher restarts on the surviving world instead of "
             "counting a crash")
register_env("MXTPU_WORLD_GENERATION", int, 0,
             "monotonically increasing world generation exported by "
             "tools/launch.py to every (re)launched worker, so logs "
             "and telemetry attribute which world a metric came "
             "from; 0 = not launcher-managed")

# Training-step sentinel (resilience.NumericGuard, optimizer,
# gluon/trainer, module fit loops; docs/numeric_stability.md).
register_env("MXTPU_NONFINITE_POLICY", str, "off",
             "non-finite gradient/loss policy for guarded training "
             "steps: off (default) | warn | skip (drop the update, "
             "keep weights/optimizer/LR state untouched) | raise "
             "(BadStepError on the first bad step)")
register_env("MXTPU_GUARD_INTERVAL", int, 1,
             "guarded steps between device->host reads of the fused "
             "finiteness scalar; the sentinel's entire sync cost is "
             "one scalar read per interval")
register_env("MXTPU_MAX_BAD_STEPS", int, 10,
             "consecutive bad steps before DivergedError (fit loops "
             "roll back to the newest valid checkpoint and re-raise "
             "for the launcher restart loop); 0 disables")
register_env("MXTPU_LOSS_SPIKE_FACTOR", float, 0.0,
             "NumericGuard.check_loss flags a finite loss larger "
             "than this factor x its running mean as a bad step; "
             "0 (default) checks only finiteness")
register_env("MXTPU_LOSS_SCALE", float, 1.0,
             "initial loss scale for optimizer.LossScaler (gluon "
             "Trainer mixed-precision loops multiply the loss by "
             "Trainer.loss_scale; step() rescales gradients back)")
register_env("MXTPU_LOSS_SCALE_DYNAMIC", bool, False,
             "grow/backoff the loss scale dynamically on "
             "overflow signals from the step sentinel")
register_env("MXTPU_LOSS_SCALE_GROWTH", float, 2.0,
             "dynamic loss-scale growth factor after "
             "MXTPU_LOSS_SCALE_WINDOW consecutive good steps")
register_env("MXTPU_LOSS_SCALE_BACKOFF", float, 0.5,
             "dynamic loss-scale backoff factor on an overflow "
             "(non-finite) step")
register_env("MXTPU_LOSS_SCALE_WINDOW", int, 2000,
             "consecutive good steps before the dynamic loss scale "
             "grows")
register_env("MXTPU_LOSS_SCALE_MAX", float, float(2 ** 24),
             "upper bound for the dynamic loss scale")

# Telemetry (telemetry.py; docs/observability.md).
register_env("MXTPU_TELEMETRY", bool, True,
             "process-wide metrics registry + step-timeline spans "
             "(docs/observability.md); 0 disables every registry "
             "write, span, and emitter thread — instrumented paths "
             "become no-ops")
register_env("MXTPU_TELEMETRY_FILE", str, "",
             "path the TelemetryEmitter appends periodic JSONL "
             "snapshots to (a Prometheus textfile is kept at "
             "<file>.prom); nonzero-rank workers write to "
             "<file>.rank<N> so a launcher-shared path never has "
             "two writers; empty disables the emitter thread")
register_env("MXTPU_TELEMETRY_INTERVAL", float, 10.0,
             "seconds between TelemetryEmitter snapshot flushes")
register_env("MXTPU_TELEMETRY_MAX_MB", float, 64.0,
             "rotate the JSONL telemetry file to <file>.1 past this "
             "size; 0 disables rotation")
register_env("MXTPU_STATUS_INTERVAL", float, 30.0,
             "seconds between tools/launch.py aggregated cluster "
             "status lines (built from per-worker heartbeat "
             "telemetry snapshots); 0 disables")

# Introspection plane (debugz.py, tools/debugz.py;
# docs/observability.md "Introspection plane").
register_env("MXTPU_DEBUGZ", bool, True,
             "embed the read-only debugz RPC endpoint in every "
             "long-running process (train ranks, serving router/"
             "replicas, remote data-service hosts); 0 disables the "
             "endpoint entirely — no socket, no thread")
register_env("MXTPU_DEBUGZ_PORT", int, 0,
             "port the per-process debugz endpoint binds; 0 = "
             "ephemeral (pair with MXTPU_DEBUGZ_PORTFILE for "
             "race-free discovery)")
register_env("MXTPU_DEBUGZ_PORTFILE", str, "",
             "path the debugz endpoint writes its bound port to "
             "(atomic temp+rename, the same handshake as the "
             "replica/data-service --port-file); exported per rank "
             "by tools/launch.py so live status polls can find the "
             "endpoint; empty skips the port file")
register_env("MXTPU_ANOMALY_WINDOW", int, 64,
             "rolling window (samples) the AnomalyWatch keeps per "
             "timeline component for its median/MAD baseline")
register_env("MXTPU_ANOMALY_THRESHOLD", float, 6.0,
             "MAD-normalized deviation score at which AnomalyWatch "
             "opens an anomaly episode (value > median + "
             "threshold * MAD)")
register_env("MXTPU_ANOMALY_MIN_STEPS", int, 16,
             "warmup samples per component before AnomalyWatch may "
             "open an episode (an empty baseline flags everything)")
register_env("MXTPU_ANOMALY_COOLDOWN", int, 8,
             "consecutive below-threshold samples that close an "
             "open anomaly episode (hysteresis: one sustained "
             "regression is one episode, not a flapping stream)")

# Flight recorder / tracing (tracing.py; docs/observability.md).
register_env("MXTPU_TRACE_BUFFER", int, 4096,
             "flight-recorder ring-buffer capacity (structured "
             "events retained for post-mortem dumps); older events "
             "are evicted and counted as dropped")
register_env("MXTPU_TRACE_DUMP", str, "",
             "path the flight recorder dumps to (atomic JSONL) on "
             "DivergedError / DataPipelineError / serving eviction "
             "faults / SIGTERM+SIGUSR1; empty (default) disables "
             "automatic fault dumps (tracing.dump(path) always "
             "works)")
register_env("MXTPU_COMPILE_BUDGET", float, 0.0,
             "retrace-storm watchdog: warn loudly when cumulative "
             "compile wall-time across all ledger sites crosses "
             "this many seconds (and again at every doubling); "
             "0 disables")

# Data-pipeline resilience (io/, gluon/data/; docs/data_pipeline.md).
register_env("MXTPU_DATA_TIMEOUT", float, 600.0,
             "wall-clock deadline (s) on input-pipeline queue waits; "
             "a stalled prefetch worker or DataLoader raises a "
             "diagnostic DataPipelineError naming the source instead "
             "of blocking next() forever; 0 disables")
register_env("MXTPU_DATA_WORKER_RESTARTS", int, 2,
             "times a DataLoader re-dispatches the index batch of a "
             "dead (segfaulted / OOM-killed) worker process before "
             "raising DataPipelineError")
register_env("MXTPU_MAX_BAD_RECORDS", int, 0,
             "bad-record budget for record-backed iterators: corrupt "
             "records are skipped and logged until this many have "
             "been seen, then the iterator raises DataPipelineError; "
             "0 (default) raises on the first bad record")
register_env("MXTPU_DL_DEAD_GRACE", float, 60.0,
             "seconds a multiprocess DataLoader waits for a dead "
             "worker's in-flight batch before declaring it lost and "
             "re-dispatching (MXTPU_DATA_WORKER_RESTARTS budget)")

# Sharded multi-process data service (data_service/;
# docs/data_service.md).
register_env("MXTPU_DATA_WORKERS", int, 2,
             "decode worker processes a DataServiceIter spawns when "
             "num_workers is not given; tools/launch.py "
             "--data-workers exports this to every rank")
register_env("MXTPU_DATA_RING_DEPTH", int, 4,
             "batches each data-service shard stages in its bounded "
             "shared-memory ring; backpressure blocks the worker — "
             "never grows memory — once the ring is full (host "
             "memory is num_workers * depth * batch_bytes)")
register_env("MXTPU_DATA_REMOTE_ADDRS", str, "",
             "comma-separated host:port list of RemoteShardServer "
             "ranks (data_service/net.py); when set (or "
             "remote_addrs= is passed) the LAST len(addrs) shards "
             "of a DataServiceIter stream over sockets instead of "
             "local shm rings — same merge order, bit-identical "
             "batches; tools/launch.py --data-hosts exports it")
register_env("MXTPU_DATA_NET_CREDITS", int, 0,
             "in-flight batch frames a remote data-service shard "
             "may send ahead of consumption (credit-based "
             "backpressure mirroring the shm ring's semaphore "
             "contract); 0 (default) uses the shard's ring depth")
register_env("MXTPU_DATA_HOST_GRACE", float, 10.0,
             "seconds a train host tolerates total silence (no "
             "batch, heartbeat, or pong frames) from a remote "
             "data-service host before declaring it dead and "
             "failing its shards over (docs/data_service.md "
             "\"Remote ranks\")")
register_env("MXTPU_DEVICE_PREFETCH_DEPTH", int, 2,
             "in-flight device batches a DevicePrefetchIter stages "
             "when its depth argument is not given (HBM use is "
             "depth * batch_bytes); deepen it when a multi-process "
             "producer outruns the depth-2 default")

# Perf observatory (docs/observability.md "Perf observatory").
register_env("MXTPU_PERF_INTERVAL", int, 10,
             "training steps between train_mfu/train_mbu/"
             "train_tokens_per_sec gauge publications when no guard "
             "cadence is supplied; publication is wall-clock-only "
             "and never adds a device->host sync")
register_env("MXTPU_PERF_GATE_BAND", float, 0.10,
             "relative noise band tools/bench_gate.py tolerates "
             "before a headline metric below best-so-far counts as "
             "a regression (0.10 = 10%)")
register_env("MXTPU_PERF_CPU_PEAK_GFLOPS", float, 100.0,
             "nominal peak GFLOP/s assumed for a CPU host in the "
             "device capability DB (perf/device_db.py) so roofline/"
             "MFU plumbing produces a verdict on CPU-only runs; "
             "reports computed against it carry nominal_peaks=true")
register_env("MXTPU_PERF_CPU_GBPS", float, 25.0,
             "nominal CPU memory bandwidth (GB/s) for the device "
             "capability DB's roofline math on CPU-only hosts")

# Memory planner + preflight OOM gate (docs/memory.md).
register_env("MXTPU_MEM_POLICY", str, "degrade",
             "memory-pressure policy for the preflight HBM gate and "
             "the runtime OOM guard: off (never plan, raw XLA "
             "RESOURCE_EXHAUSTED kills the job), warn (plan + log "
             "overflow, never act), degrade (walk the ladder: "
             "enable remat -> raise grad_accum -> typed "
             "MemoryPlanError)")
register_env("MXTPU_HBM_BYTES", float, 0.0,
             "per-device HBM capacity override in bytes for "
             "perf/device_db.py; 0 (default) uses the device "
             "generation's known capacity (CPU hosts get a nominal "
             "value tagged nominal_hbm=true); shrink it to exercise "
             "the degrade ladder deterministically")
register_env("MXTPU_MEM_GATE_MARGIN", float, 0.05,
             "fraction of device HBM the preflight gate holds back "
             "as safety margin (XLA fragmentation + unmodeled "
             "scratch): a plan overflows when predicted peak > "
             "(1 - margin) * capacity")
