"""Live introspection plane: the per-process ``debugz`` endpoint.

Every long-running process — train ranks (wired in ``dist.init``),
the serving router/replicas, remote data-service shard servers —
embeds one read-only debug endpoint served over the CRC-framed,
deadline-budgeted :mod:`.rpc` transport.  A fleet operator (or
``tools/launch.py``'s status ticks) can ask a *live, possibly
wedged* process "what are you doing right now" instead of waiting
for heartbeat-file mtimes or post-mortem flight-recorder dumps.

Ops (request ``{"op": <name>, ...}`` → one reply frame):

``varz``      full telemetry snapshot (counters/gauges/histograms)
``statusz``   role-specific live state from registered providers
              plus push-published train-loop fields
``tracez``    flight-recorder tail, filterable by ``event``/``rid``
``memz``      memory gauges + the analytic :class:`MemoryPlan`
``profilez``  arm the chrome-tracing profiler for N seconds and
              return the dump inline
``healthz``   own-heartbeat age + anomaly-watchdog verdicts

Contract (lint-enforced, see ``ci/lint.py``):

* **read-only** — no op mutates model / engine / stream state
  (``profilez`` toggles only the profiler recorder);
* **zero device syncs** — every payload is host-side Python data;
  an op must never block on an accelerator transfer;
* **deadline-bounded** — handlers run inline on the rpc reader
  thread and do only bounded work, so the caller's own socket
  deadline is the only wait anywhere.  A SIGSTOPped process simply
  never answers; it cannot wedge the caller.

This module is imported before jax in ``dist.init`` and must stay
jax-free at import time.
"""

import os
import tempfile
import threading
import time

from . import rpc
from . import resilience
from . import telemetry
from . import tracing
from .utils.env import get_env

__all__ = [
    "OPS",
    "DebugzServer",
    "maybe_start",
    "port",
    "publish",
    "register_provider",
    "server",
    "stop",
]

#: every op the endpoint answers — ci/lint.py requires each name to
#: appear in the docs/observability.md introspection catalog
OPS = ("varz", "statusz", "tracez", "memz", "profilez", "healthz")

#: hard cap on profilez arming (seconds) — keeps the op bounded even
#: against an absurd request
PROFILEZ_MAX_S = 30.0

_LOCK = threading.Lock()
_STATE = {"server": None}
_PROVIDERS = {}      # name -> zero-arg callable returning a dict
_PUBLISHED = {}      # name -> dict merged by publish()


# ---------------------------------------------------------------------------
# role-specific state sources
# ---------------------------------------------------------------------------


def register_provider(name, fn):
    """Register a zero-arg callable whose dict return feeds
    ``statusz`` under ``name`` (engine stats, router stats, shard
    cursors...).  The callable runs on the debugz reader thread and
    must be host-side and non-blocking.  Returns an unregister
    callable; re-registering a name replaces the old source."""
    with _LOCK:
        _PROVIDERS[name] = fn

    def unregister():
        with _LOCK:
            if _PROVIDERS.get(name) is fn:
                del _PROVIDERS[name]
    return unregister


def publish(name, **fields):
    """Push-style counterpart of :func:`register_provider` for code
    with no natural object to poll — the train loop publishes
    ``step``/``epoch``/last timeline split after each step and
    ``statusz`` serves the latest merge."""
    with _LOCK:
        d = _PUBLISHED.setdefault(name, {})
        d.update(fields)


def _status_payload():
    with _LOCK:
        providers = dict(_PROVIDERS)
        published = {k: dict(v) for k, v in _PUBLISHED.items()}
    out = dict(published)
    for name, fn in providers.items():
        try:
            out[name] = fn()
        except Exception as e:  # one broken source must not take
            out[name] = {"error": str(e)}  # down the whole statusz
    return out


# ---------------------------------------------------------------------------
# the endpoint
# ---------------------------------------------------------------------------


class DebugzServer:
    """Read-only debug endpoint for one process (see module doc)."""

    def __init__(self, role, host="127.0.0.1", port=0):
        self.role = role
        self._t0 = time.monotonic()
        self._stop = threading.Event()
        # fault_scope=None: injected router/net faults must never
        # corrupt the plane used to debug them
        self._srv = rpc.RpcServer(
            self._handle, host=host, port=int(port),
            name=f"debugz-{role}", fault_scope=None)

    @property
    def host(self):
        return self._srv.host

    @property
    def port(self):
        return self._srv.port

    def start(self):
        self._srv.start()
        return self

    def close(self):
        self._stop.set()
        self._srv.close()

    # -- dispatch ----------------------------------------------------------

    def _handle(self, msg, conn, budget):
        op = msg.get("op")
        if op not in OPS:
            return {"op": "error",
                    "error": f"unknown debugz op: {op!r}",
                    "ops": list(OPS)}
        reply = getattr(self, "_op_" + op)(msg)
        reply.setdefault("op", op)
        reply["role"] = self.role
        reply["rank"] = int(os.environ.get("MXTPU_WORKER_RANK", 0))
        reply["uptime_s"] = round(time.monotonic() - self._t0, 3)
        return reply

    # -- ops ---------------------------------------------------------------

    def _op_varz(self, msg):
        return {"telemetry": telemetry.snapshot()}

    def _op_statusz(self, msg):
        return {"status": _status_payload()}

    def _op_tracez(self, msg):
        match = {}
        if msg.get("rid") is not None:
            match["rid"] = msg["rid"]
        evs = tracing.events(event=msg.get("event"), **match)
        limit = int(msg.get("limit") or 0)
        if limit > 0:
            evs = evs[-limit:]
        return {"events": evs,
                "dropped": tracing.get_recorder().dropped}

    def _op_memz(self, msg):
        # device_memory_stats is metadata-only (live_bytes walks
        # already-materialised buffer sizes; no transfer, no sync)
        return {"memory": tracing.device_memory_stats(),
                "plan": tracing.memory_plan()}

    def _op_profilez(self, msg):
        seconds = float(msg.get("seconds") or 1.0)
        seconds = max(0.0, min(seconds, PROFILEZ_MAX_S))
        from . import profiler
        if profiler._profiler.running:
            return {"error": "profiler busy (already running)"}
        prev = profiler._profiler.filename
        path = os.path.join(
            tempfile.gettempdir(),
            f"mxtpu-debugz-profile-{os.getpid()}.json")
        profiler.set_config(filename=path)
        profiler.set_state("run")
        try:
            # bounded by PROFILEZ_MAX_S; wakes early on close()
            self._stop.wait(seconds)
        finally:
            profiler.set_state("stop")
            profiler.dump_profile()
            profiler.set_config(filename=prev)
        try:
            with open(path) as f:
                dump = f.read()
        except OSError as e:
            return {"error": f"profile dump unreadable: {e}"}
        return {"profile": dump, "seconds": seconds}

    def _op_healthz(self, msg):
        age = resilience.heartbeat_age()
        verdicts = telemetry.anomaly_verdicts()
        anomalous = any(v.get("anomalous") for v in verdicts.values())
        return {"heartbeat_age_s":
                None if age is None else round(age, 3),
                "anomaly": verdicts,
                "anomalous": anomalous,
                "ok": not anomalous}


# ---------------------------------------------------------------------------
# process-wide lifecycle
# ---------------------------------------------------------------------------


def maybe_start(role):
    """Start this process's endpoint once (idempotent), gated on
    ``MXTPU_DEBUGZ``.  Binds ``MXTPU_DEBUGZ_PORT`` (0 = ephemeral)
    and, when ``MXTPU_DEBUGZ_PORTFILE`` is set (launch.py exports a
    per-rank path), publishes ``host:port`` there via the atomic
    temp+rename handshake.  Returns the server, or None when
    disabled or the bind fails — introspection must never kill the
    process it introspects."""
    if not get_env("MXTPU_DEBUGZ"):
        return None
    with _LOCK:
        if _STATE["server"] is not None:
            return _STATE["server"]
    try:
        srv = DebugzServer(role,
                           port=get_env("MXTPU_DEBUGZ_PORT")).start()
    except OSError:
        return None
    with _LOCK:
        if _STATE["server"] is not None:  # lost a startup race
            srv.close()
            return _STATE["server"]
        _STATE["server"] = srv
    portfile = get_env("MXTPU_DEBUGZ_PORTFILE")
    if portfile:
        try:
            tmp = portfile + ".tmp"
            with open(tmp, "w") as f:
                f.write(f"{srv.host}:{srv.port}\n")
            os.replace(tmp, portfile)
        except OSError:
            pass
    return srv


def server():
    """The running endpoint, or None."""
    with _LOCK:
        return _STATE["server"]


def port():
    """Bound port of the running endpoint, or None."""
    srv = server()
    return None if srv is None else srv.port


def stop():
    """Close the endpoint and clear provider/publish registries
    (tests / clean shutdown)."""
    with _LOCK:
        srv = _STATE["server"]
        _STATE["server"] = None
        _PROVIDERS.clear()
        _PUBLISHED.clear()
    if srv is not None:
        srv.close()
