"""Imperative autograd: record/pause scopes, tape, backward.

API parity with the reference's mx.autograd (ref:
python/mxnet/autograd.py — record:122, pause, train_mode/predict_mode,
mark_variables, backward:243, grad:270, Function:364; C++ side
src/imperative/imperative.cc Backward:361).

Design: instead of the reference's nnvm-graph tape + imperative
re-execution, every recorded op captures its jax VJP closure at call
time (``jax.vjp`` during the forward).  backward() walks the tape in
reverse topological order calling those closures — XLA has already
compiled each, and fuses chains of them when backward runs under jit
(executor path).
"""
import threading

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["record", "pause", "train_mode", "predict_mode", "is_recording",
           "is_training", "set_recording", "set_training",
           "mark_variables", "backward", "grad", "get_symbol", "Function"]

_state = threading.local()


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
    return _state


def is_recording():
    return _st().recording


def is_training():
    return _st().training


def set_recording(is_record):
    prev = _st().recording
    _st().recording = bool(is_record)
    return prev


def set_training(train_mode_):
    prev = _st().training
    _st().training = bool(train_mode_)
    return prev


class _RecordingStateScope:
    def __init__(self, is_record, train):
        self._rec = is_record
        self._train = train

    def __enter__(self):
        s = _st()
        self._prev = (s.recording, s.training)
        if self._rec is not None:
            s.recording = self._rec
        if self._train is not None:
            s.training = self._train
        return self

    def __exit__(self, *exc):
        s = _st()
        s.recording, s.training = self._prev


def record(train_mode=True):
    """Scope in which imperative ops are recorded for backward()."""
    return _RecordingStateScope(True, train_mode)


def pause(train_mode=False):
    """Scope in which recording (and by default training mode) is off."""
    return _RecordingStateScope(False, train_mode)


def train_mode():
    return _RecordingStateScope(None, True)


def predict_mode():
    return _RecordingStateScope(None, False)


# ---------------------------------------------------------------------------
# tape
# ---------------------------------------------------------------------------


class _ConstInput:
    """Marker in TapeNode.inputs for a non-NDArray tensor argument
    whose value was inlined at call time (raw numpy/list/scalar):
    carries no gradient and cannot be rebound by get_symbol."""

    def __repr__(self):
        return "<const-input>"


CONST_INPUT = _ConstInput()


class TapeNode:
    """One recorded op: the vjp closure plus input links.

    ``op``/``params`` (set by the registry invoke path) identify the
    recorded operator so get_symbol can re-trace the history into a
    Symbol graph; closure-only nodes (CachedOp, custom Function)
    leave them None."""

    __slots__ = ("vjp_fn", "inputs", "out_avals", "name", "op",
                 "params")

    def __init__(self, vjp_fn, inputs, out_avals, name, op=None,
                 params=None):
        self.vjp_fn = vjp_fn
        self.inputs = inputs        # list of NDArray (tensor inputs)
        self.out_avals = out_avals  # [(shape, dtype)] per output
        self.name = name
        self.op = op
        self.params = params


def mark_variables(variables, gradients, grad_reqs="write"):
    """Attach gradient buffers to arrays, making them autograd leaves
    (ref: autograd.py mark_variables / imperative.cc MarkVariables)."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._grad = g
        v._grad_req = req
        v._autograd = None


def _zero_cotangent(shape, dtype):
    if not jnp.issubdtype(np.dtype(dtype), jnp.floating) and \
       not jnp.issubdtype(np.dtype(dtype), jnp.complexfloating):
        return np.zeros(shape, jax.dtypes.float0)
    return jnp.zeros(shape, dtype)


def _toposort(heads):
    """Reverse-topological order of tape nodes reachable from heads."""
    order, seen = [], set()
    # iterative DFS with post-order collection
    for h in heads:
        entry = getattr(h, "_autograd", None)
        if entry is None:
            continue
        stack = [(entry[0], False)]
        while stack:
            node, processed = stack.pop()
            if processed:
                order.append(node)
                continue
            if id(node) in seen:
                continue
            seen.add(id(node))
            stack.append((node, True))
            for inp in node.inputs:
                e = getattr(inp, "_autograd", None) if inp is not None \
                    else None
                if e is not None and id(e[0]) not in seen:
                    stack.append((e[0], False))
    return order[::-1]  # heads-first


def _run_backward(heads, head_grads, variables=None, retain_graph=False):
    from .ndarray.ndarray import NDArray
    heads = [heads] if isinstance(heads, NDArray) else list(heads)
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif isinstance(head_grads, NDArray):
        head_grads = [head_grads]

    # cotangent accumulator keyed by (id(node), out_idx); plus leaf grads
    cts = {}
    leaf_grads = {}

    def _add_ct(arr, ct):
        entry = getattr(arr, "_autograd", None)
        if entry is not None:
            key = (id(entry[0]), entry[1])
            cts[key] = ct if key not in cts else cts[key] + ct
        if getattr(arr, "_grad", None) is not None or (
                variables is not None and
                any(arr is v for v in variables)):
            k = id(arr)
            if k in leaf_grads:
                leaf_grads[k] = (arr, leaf_grads[k][1] + ct)
            else:
                leaf_grads[k] = (arr, ct)

    for h, hg in zip(heads, head_grads):
        ct = jnp.ones(h.shape, h._data.dtype) if hg is None else hg._data
        _add_ct(h, ct)

    for node in _toposort(heads):
        outs_ct = []
        missing = True
        for i, (shape, dtype) in enumerate(node.out_avals):
            key = (id(node), i)
            if key in cts:
                outs_ct.append(cts.pop(key))
                missing = False
            else:
                outs_ct.append(_zero_cotangent(shape, dtype))
        if missing:
            continue
        arg = tuple(outs_ct) if len(outs_ct) > 1 else outs_ct[0]
        in_cts = node.vjp_fn(arg)
        for inp, ct in zip(node.inputs, in_cts):
            if inp is None or inp is CONST_INPUT or ct is None:
                continue
            if isinstance(ct, np.ndarray) and ct.dtype == jax.dtypes.float0:
                continue
            _add_ct(inp, ct)

    # write leaf gradients honoring grad_req
    for arr, ct in leaf_grads.values():
        req = getattr(arr, "_grad_req", "write")
        if req == "null":
            continue
        gbuf = getattr(arr, "_grad", None)
        if gbuf is not None:
            if req == "add":
                gbuf._data = gbuf._data + ct
            else:
                gbuf._data = ct.astype(gbuf._data.dtype) \
                    if ct.dtype != gbuf._data.dtype else ct
    if not retain_graph:
        for h in heads:
            h._autograd = None
    return leaf_grads


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Compute gradients of heads w.r.t. marked variables, storing them
    in each variable's attached grad buffer."""
    _run_backward(heads, head_grads, retain_graph=retain_graph)


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False):
    """Return gradients of heads w.r.t. ``variables``
    (ref: autograd.py grad:270).  create_graph (2nd order) is not yet
    recorded; use jax.grad composition for higher-order needs."""
    from .ndarray.ndarray import NDArray
    variables = [variables] if isinstance(variables, NDArray) \
        else list(variables)
    leaf = _run_backward(heads, head_grads, variables=variables,
                         retain_graph=bool(retain_graph or create_graph))
    out = []
    for v in variables:
        if id(v) not in leaf:
            raise ValueError("one of the variables does not participate "
                             "in the graph of heads")
        out.append(NDArray(leaf[id(v)][1], ctx=v.context))
    return out


def get_symbol(x):
    """Re-trace the recorded history of ``x`` into a Symbol (ref:
    python/mxnet/autograd.py get_symbol — there via the C tape, here
    by replaying the registry ops each TapeNode recorded).

    Leaf arrays become Variables named var0, var1... in first-use
    order.  Only registry-op history is traceable; CachedOp/custom
    Function nodes recorded closures, not ops, and raise.
    """
    from .symbol import symbol as sym_mod

    entry = getattr(x, "_autograd", None)
    if entry is None:
        return sym_mod.Variable("var0")

    node_syms = {}     # id(node) -> Symbol with all outputs
    arr_syms = {}      # id(leaf array) -> Variable symbol
    counter = [0]

    def leaf(arr):
        if id(arr) not in arr_syms:
            arr_syms[id(arr)] = sym_mod.Variable(f"var{counter[0]}")
            counter[0] += 1
        return arr_syms[id(arr)]

    # dependencies-first order (toposort returns heads-first)
    for node in reversed(_toposort([x])):
        if node.op is None:
            raise ValueError(
                f"get_symbol: '{node.name}' was recorded as an opaque "
                "closure (CachedOp/custom Function); only registry-op "
                "history is traceable — hybridize the block instead")
        sym_args = []
        for inp in node.inputs:
            if inp is None:
                sym_args.append(None)
                continue
            if inp is CONST_INPUT:
                raise ValueError(
                    f"get_symbol: an input of '{node.name}' was an "
                    "inlined constant (raw numpy/list/scalar), which "
                    "a Symbol cannot re-bind — pass tensor inputs as "
                    "NDArrays to make the history re-traceable")
            e = getattr(inp, "_autograd", None)
            if e is not None and id(e[0]) in node_syms:
                s = node_syms[id(e[0])]
                sym_args.append(s[e[1]] if len(s) > 1 else s)
            else:
                sym_args.append(leaf(inp))
        node_syms[id(node)] = sym_mod._invoke(
            node.op, sym_args, dict(node.params or {}))

    node, idx = entry
    s = node_syms[id(node)]
    return s[idx] if len(s) > 1 else s


class Function:
    """User-defined differentiable function
    (ref: python/mxnet/autograd.py Function:364).

    Subclass and implement forward(self, *inputs) and
    backward(self, *output_grads), both on NDArrays.
    """

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *arrays):
        self._saved = arrays

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray
        with pause():
            outputs = self.forward(*inputs)
        single = isinstance(outputs, NDArray)
        outs = [outputs] if single else list(outputs)
        if is_recording():
            func = self

            def vjp_fn(out_cts):
                cts = (out_cts,) if len(outs) == 1 else out_cts
                with pause():
                    grads = func.backward(
                        *[NDArray(c) for c in cts])
                if isinstance(grads, NDArray):
                    grads = [grads]
                return [g._data if g is not None else None for g in grads]

            node = TapeNode(vjp_fn, list(inputs),
                            [(o.shape, o._data.dtype) for o in outs],
                            type(self).__name__)
            for i, o in enumerate(outs):
                o._autograd = (node, i)
        return outputs if single else outs
