"""Network visualization (ref: python/mxnet/visualization.py —
print_summary:355, plot_network).

print_summary walks the Symbol graph exactly like the reference
(topological order, per-layer shape + parameter count columns);
plot_network emits graphviz when the library is present.
"""

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape=None, line_length=120,
                  positions=(0.44, 0.64, 0.74, 1.0)):
    """Print a layer-table summary of a Symbol (ref:
    visualization.py print_summary)."""
    if shape is not None:
        arg_shapes, out_shapes, aux_shapes = \
            symbol.infer_shape_partial(**shape)
        shape_dict = dict(zip(symbol.list_arguments(), arg_shapes))
    else:
        shape_dict = {}
    positions = [int(line_length * p) for p in positions]
    fields = ["Layer (type)", "Output Shape", "Param #",
              "Previous Layer"]

    def print_row(values, positions):
        line = ""
        for i, v in enumerate(values):
            line += str(v)
            line = line[:positions[i]]
            line += " " * (positions[i] - len(line))
        print(line)

    print("_" * line_length)
    print_row(fields, positions)
    print("=" * line_length)
    total_params = 0

    internals = symbol.get_internals()
    seen_params = set()
    rows = []
    for out in internals:
        node = out._heads[0][0] if hasattr(out, "_heads") else None
        if node is None or node.is_variable:
            continue
        name = node.name
        op_name = node.op.name if hasattr(node.op, "name") else \
            str(node.op)
        prevs = [inp[0].name for inp in node.inputs
                 if not inp[0].is_variable]
        n_params = 0
        for inp, _ in node.inputs:
            if inp.is_variable and inp.name in shape_dict and \
                    inp.name not in seen_params and \
                    not inp.name.endswith(("data", "label")):
                cnt = 1
                for d in shape_dict[inp.name]:
                    cnt *= d
                n_params += cnt
                seen_params.add(inp.name)
        total_params += n_params
        out_shape = ""
        if shape is not None:
            try:
                _, os_, _ = out.infer_shape_partial(**shape)
                out_shape = str(os_[0]) if os_ else ""
            except Exception:
                out_shape = "?"
        rows.append(([f"{name} ({op_name})", out_shape, n_params,
                      ",".join(prevs)]))
    for i, row in enumerate(rows):
        print_row(row, positions)
        print(("=" if i == len(rows) - 1 else "_") * line_length)
    print(f"Total params: {total_params}")
    print("_" * line_length)
    return total_params


def plot_network(symbol, title="plot", shape=None,
                 node_attrs=None, save_format="pdf"):
    """Graphviz plot of the Symbol graph (ref: visualization.py
    plot_network); needs the optional `graphviz` package."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise ImportError(
            "plot_network requires the graphviz package; "
            "print_summary works without it") from e
    dot = Digraph(name=title, format=save_format)
    internals = symbol.get_internals()
    for out in internals:
        node = out._heads[0][0] if hasattr(out, "_heads") else None
        if node is None:
            continue
        if node.is_variable:
            dot.node(node.name, node.name, shape="oval")
        else:
            op_name = node.op.name if hasattr(node.op, "name") \
                else str(node.op)
            dot.node(node.name, f"{node.name}\n{op_name}",
                     shape="box")
            for inp, _ in node.inputs:
                dot.edge(inp.name, node.name)
    return dot
