"""Global PRNG state: a stateful facade over stateless jax.random keys.

The reference manages per-device PRNG states as engine resources
(ref: include/mxnet/resource.h kRandom, src/resource.cc).  The
TPU-native design is stateless threaded keys: a global root key that
`seed()` resets, split once per sampling op.  Traced contexts
(hybridized blocks, compiled executors) push a *provider* that splits
from a key passed in as a function argument, so random ops stay
jit-pure and reproducible.
"""
import threading

import jax

_state = threading.local()


def _root():
    if not hasattr(_state, "key"):
        _state.key = jax.random.PRNGKey(0)
        _state.providers = []
    return _state


def seed(seed_state):
    """Seed the global generator (analog of mx.random.seed)."""
    s = _root()
    s.key = jax.random.PRNGKey(int(seed_state))


def next_key():
    """Next fresh key: from the innermost provider if one is active
    (traced contexts), else by splitting the global root key."""
    s = _root()
    if s.providers:
        return s.providers[-1]()
    s.key, sub = jax.random.split(s.key)
    return sub


class key_provider:
    """Context manager installing a key source for traced regions.

    ``base_key`` is split deterministically per draw, so a traced
    function that takes a key argument stays a pure function of it.
    """

    def __init__(self, base_key):
        self.base_key = base_key
        self.count = 0

    def _next(self):
        k = jax.random.fold_in(self.base_key, self.count)
        self.count += 1
        return k

    def __enter__(self):
        _root().providers.append(self._next)
        return self

    def __exit__(self, *exc):
        _root().providers.pop()
