"""Device contexts: ``tpu(i)`` / ``cpu(i)`` with a ``with ctx:`` stack.

TPU-native analog of the reference's Context (ref:
python/mxnet/context.py — mx.cpu()/mx.gpu(), `with ctx:` stack, and
include/mxnet/base.h Context struct).  ``gpu(i)`` is accepted as an
alias for ``tpu(i)`` so reference scripts run unmodified.

A Context maps onto a concrete ``jax.Device``.  On a CPU-only test
host with ``--xla_force_host_platform_device_count=N``, ``tpu(i)`` and
``cpu(i)`` both resolve to the i-th virtual CPU device, which is what
lets multi-device code paths be tested without TPU hardware.
"""
import threading

import jax

_ACCEL_TYPES = ("tpu", "gpu", "axon")  # accelerator platform names, in order


class Context:
    """A device context. devtype is 'cpu' or 'tpu'."""

    _default_ctx = threading.local()
    devtype2mask = {"cpu": 1, "tpu": 2, "gpu": 2, "cpu_pinned": 3,
                    "cpu_shared": 5}
    devmask2type = {1: "cpu", 2: "tpu", 3: "cpu_pinned", 5: "cpu_shared"}

    def __init__(self, device_type, device_id=0):
        if device_type == "gpu":  # compat alias
            device_type = "tpu"
        if device_type not in ("cpu", "tpu", "cpu_pinned", "cpu_shared"):
            raise ValueError(f"unknown device type {device_type!r}")
        self.device_type = device_type
        self.device_id = device_id

    # -- identity ---------------------------------------------------------
    def __eq__(self, other):
        return (isinstance(other, Context)
                and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    def __str__(self):
        return self.__repr__()

    # -- jax mapping ------------------------------------------------------
    @property
    def jax_device(self):
        """The concrete jax.Device this context denotes."""
        devs = _devices_for(self.device_type)
        if not devs:
            # graceful degradation: fall back to whatever exists
            devs = jax.devices()
        return devs[self.device_id % len(devs)]

    def empty_cache(self):
        """Release cached device memory (analog of ctx.empty_cache)."""
        # XLA/PJRT owns the allocator; live buffers are freed by GC.
        import gc
        gc.collect()

    def memory_info(self):
        """(free_bytes, total_bytes) for this context's device (the
        reference's ``mx.context.gpu_memory_info`` role,
        python/mxnet/context.py).

        Reads the PJRT allocator's statistics (HBM on TPU).  Backends
        that expose no stats (virtual CPU devices) report host memory
        so capacity planning code keeps working off-device."""
        import os
        stats = None
        try:
            stats = self.jax_device.memory_stats()
        except Exception:  # noqa: BLE001 — optional PJRT surface
            stats = None
        if stats and stats.get("bytes_limit"):
            total = int(stats["bytes_limit"])
            used = int(stats.get("bytes_in_use", 0))
            return max(total - used, 0), total
        page = os.sysconf("SC_PAGE_SIZE")
        total = os.sysconf("SC_PHYS_PAGES") * page
        avail = os.sysconf("SC_AVPHYS_PAGES") * page
        return avail, total

    # -- with-statement stack --------------------------------------------
    def __enter__(self):
        if not hasattr(Context._default_ctx, "stack"):
            Context._default_ctx.stack = []
        Context._default_ctx.stack.append(self)
        return self

    def __exit__(self, *exc):
        Context._default_ctx.stack.pop()


def _devices_for(device_type):
    all_devs = jax.devices()
    if device_type.startswith("cpu"):
        cpus = [d for d in all_devs if d.platform == "cpu"]
        return cpus or all_devs
    accel = [d for d in all_devs if d.platform in _ACCEL_TYPES]
    # on CPU-only hosts, "tpu(i)" maps onto virtual cpu devices so that
    # multi-device code paths still exercise distinct devices
    return accel or all_devs


def cpu(device_id=0):
    """Return a CPU context."""
    return Context("cpu", device_id)


def tpu(device_id=0):
    """Return a TPU context."""
    return Context("tpu", device_id)


def gpu(device_id=0):
    """Compat alias for :func:`tpu` so reference scripts run unchanged."""
    return Context("tpu", device_id)


def cpu_pinned(device_id=0):
    return Context("cpu_pinned", device_id)


def num_tpus():
    """Number of attached accelerator devices (0 on pure-CPU hosts)."""
    return len([d for d in jax.devices() if d.platform in _ACCEL_TYPES])


num_gpus = num_tpus


def tpu_memory_info(device_id=0):
    """(free_bytes, total_bytes) of the accelerator's memory (the
    reference's ``mx.context.gpu_memory_info``)."""
    return tpu(device_id).memory_info()


gpu_memory_info = tpu_memory_info


def default_context():
    """Context used when none is given: innermost `with ctx:`, else
    tpu(0) if an accelerator is attached, else cpu(0)."""
    stack = getattr(Context._default_ctx, "stack", None)
    if stack:
        return stack[-1]
    return tpu(0) if num_tpus() else cpu(0)


def current_context():
    return default_context()
