"""Testing fixtures mirroring the reference's test_utils
(ref: python/mxnet/test_utils.py — check_numeric_gradient:789,
check_symbolic_forward:921, check_symbolic_backward:995,
check_consistency:1203, assert_almost_equal, rand_ndarray).

The numeric-gradient oracle is the same idea as the reference's: a
random-projection scalar head, central finite differences per input
element, compared against the framework's own backward (which here is
jax.vjp through the fused Executor).  ``check_consistency`` compares
the same graph across contexts/dtypes — the cpu-vs-gpu consistency
matrix of the reference mapped onto cpu-vs-(virtual-)tpu devices and
float dtypes.
"""
import numpy as np

from .context import default_context
from .executor import Executor
from .ndarray.ndarray import NDArray, array as _nd_array

__all__ = ["assert_almost_equal", "same", "rand_shape_2d",
           "rand_shape_3d", "rand_ndarray", "random_arrays",
           "numeric_grad", "check_numeric_gradient",
           "check_symbolic_forward", "check_symbolic_backward",
           "check_consistency", "default_context"]

_RNG = np.random.RandomState(12345)


# ---------------------------------------------------------------------------
# comparison / data helpers
# ---------------------------------------------------------------------------


def same(a, b):
    return np.array_equal(np.asarray(a), np.asarray(b))


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-20,
                        names=("a", "b"), equal_nan=False):
    """(ref: test_utils.py assert_almost_equal)"""
    a = np.asarray(a.asnumpy() if isinstance(a, NDArray) else a)
    b = np.asarray(b.asnumpy() if isinstance(b, NDArray) else b)
    np.testing.assert_allclose(a, b, rtol=rtol, atol=atol,
                               equal_nan=equal_nan,
                               err_msg=f"{names[0]} != {names[1]}")


def rand_shape_2d(dim0=10, dim1=10):
    return (_RNG.randint(1, dim0 + 1), _RNG.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (_RNG.randint(1, dim0 + 1), _RNG.randint(1, dim1 + 1),
            _RNG.randint(1, dim2 + 1))


def rand_ndarray(shape, stype="default", density=None, dtype=None,
                 ctx=None):
    """(ref: test_utils.py rand_ndarray) dense / csr / row_sparse."""
    dtype = np.dtype(dtype or np.float32)
    dense = _RNG.uniform(-1, 1, shape).astype(dtype)
    if stype == "default":
        return _nd_array(dense, ctx=ctx)
    density = 0.3 if density is None else density
    mask = _RNG.rand(*shape) < density
    dense = dense * mask
    from .ndarray import sparse
    if stype == "csr":
        return sparse.csr_matrix(dense, shape=shape)
    if stype == "row_sparse":
        return sparse.row_sparse_array(dense, shape=shape)
    raise ValueError(stype)


def random_arrays(*shapes):
    arrays = [_RNG.standard_normal(s).astype(np.float32)
              for s in shapes]
    return arrays[0] if len(arrays) == 1 else arrays


# ---------------------------------------------------------------------------
# executor plumbing
# ---------------------------------------------------------------------------


def _as_np_dict(sym, location):
    names = sym.list_arguments()
    if isinstance(location, (list, tuple)):
        location = dict(zip(names, location))
    return {k: np.asarray(v.asnumpy() if isinstance(v, NDArray)
                          else v) for k, v in location.items()}


def _bind(sym, location, aux_states=None, grad_req="write", ctx=None,
          dtype=np.float32):
    """``dtype=None`` keeps each location entry's own dtype (the
    check_consistency path); otherwise float entries are cast."""
    ctx = ctx or default_context()
    loc = _as_np_dict(sym, location)

    def _in(v):
        if dtype is not None and np.issubdtype(
                np.asarray(v).dtype, np.floating):
            return v.astype(dtype)
        return v
    args = {k: _nd_array(_in(v), ctx=ctx) for k, v in loc.items()}
    aux = None
    if aux_states:
        aux = {k: _nd_array(np.asarray(
            v.asnumpy() if isinstance(v, NDArray) else v), ctx=ctx)
            for k, v in aux_states.items()}
    if isinstance(grad_req, str):
        req = {n: grad_req for n in args}
    else:
        req = dict(grad_req)
    for n, v in loc.items():  # integer inputs carry no gradient
        if not np.issubdtype(np.asarray(v).dtype, np.floating):
            req[n] = "null"
    grads = {n: _nd_array(np.zeros_like(np.asarray(args[n].asnumpy())))
             for n in args if req.get(n, "null") != "null"}
    return Executor(sym, ctx, args, grads, req, aux or {}), loc


# ---------------------------------------------------------------------------
# numeric gradient
# ---------------------------------------------------------------------------


def numeric_grad(f, loc, eps=1e-4):
    """Central-difference gradients of scalar ``f(dict)->float`` w.r.t.
    every element of every float entry of ``loc``."""
    grads = {}
    for name, v in loc.items():
        if not np.issubdtype(np.asarray(v).dtype, np.floating):
            continue
        v = np.array(v, np.float64)
        g = np.zeros_like(v)
        flat = v.ravel()
        gflat = g.ravel()
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + eps
            fp = f({**loc, name: v.astype(np.float32)})
            flat[i] = orig - eps
            fm = f({**loc, name: v.astype(np.float32)})
            flat[i] = orig
            gflat[i] = (fp - fm) / (2 * eps)
        grads[name] = g
    return grads


def check_numeric_gradient(sym, location, aux_states=None,
                           numeric_eps=1e-4, rtol=1e-2, atol=1e-4,
                           grad_nodes=None, ctx=None):
    """Finite differences vs the framework backward
    (ref: test_utils.py:789).  Uses a fixed random projection of the
    outputs as the scalar head, like the reference."""
    exe, loc = _bind(sym, location, aux_states, ctx=ctx)
    outs = exe.forward(is_train=True)
    projs = [np.asarray(
        _RNG.standard_normal(o.shape), np.float32) for o in outs]

    def head(loc_np):
        # route through Executor._set_inputs so the bound dtype is
        # preserved (it casts, validates names)
        outs = exe.forward(is_train=True, **loc_np)
        return float(sum((np.asarray(o.asnumpy(), np.float64) * p).sum()
                         for o, p in zip(outs, projs)))

    num = numeric_grad(head, loc, eps=numeric_eps)
    # symbolic: backward with the projection as head gradients
    exe.forward_backward(out_grads=[_nd_array(p) for p in projs],
                         **loc)
    grad_nodes = grad_nodes or [n for n in num]
    for name in grad_nodes:
        if name not in num:
            continue
        sym_grad = exe.grad_dict[name].asnumpy()
        assert_almost_equal(num[name], sym_grad, rtol=rtol, atol=atol,
                            names=(f"numeric[{name}]",
                                   f"symbolic[{name}]"))


def check_symbolic_forward(sym, location, expected, rtol=1e-4,
                           atol=1e-6, aux_states=None, ctx=None):
    """(ref: test_utils.py:921)"""
    exe, _ = _bind(sym, location, aux_states, grad_req="null",
                   ctx=ctx)
    outs = exe.forward(is_train=False)
    if not isinstance(expected, (list, tuple)):
        expected = [expected]
    for o, e in zip(outs, expected):
        assert_almost_equal(o, e, rtol=rtol, atol=atol,
                            names=("forward", "expected"))
    return outs


def check_symbolic_backward(sym, location, out_grads, expected,
                            rtol=1e-4, atol=1e-6, aux_states=None,
                            grad_req="write", ctx=None):
    """(ref: test_utils.py:995)"""
    exe, _ = _bind(sym, location, aux_states, grad_req=grad_req,
                   ctx=ctx)
    exe.forward(is_train=True)
    ogs = [_nd_array(np.asarray(
        g.asnumpy() if isinstance(g, NDArray) else g))
        for g in (out_grads if isinstance(out_grads, (list, tuple))
                  else [out_grads])]
    exe.forward_backward(out_grads=ogs)
    if isinstance(expected, (list, tuple)):
        expected = dict(zip(sym.list_arguments(), expected))
    for name, e in expected.items():
        if e is None:
            continue
        assert_almost_equal(exe.grad_dict[name], e, rtol=rtol,
                            atol=atol,
                            names=(f"grad[{name}]", "expected"))
    return {n: g.asnumpy() for n, g in exe.grad_dict.items()}


def check_consistency(sym, ctx_list, scale=1.0, grad_req="write",
                      tol=None, rtol=1e-4, atol=1e-5):
    """Run the same graph under every (ctx, dtype) spec and compare
    forward outputs + gradients pairwise (ref: test_utils.py:1203).

    ctx_list entries: dict(ctx=Context, type_dict={name: dtype}) —
    the reference's format.  bf16/fp16 entries get relaxed tolerance.
    """
    from .base import np_dtype
    assert len(ctx_list) > 1
    arg_names = sym.list_arguments()
    shapes = ctx_list[0].get("shapes") or {
        k: v for k, v in ctx_list[0].items()
        if isinstance(v, tuple) and k != "ctx"}
    base = {n: (_RNG.standard_normal(shapes[n]) * scale
                ).astype(np.float32) for n in arg_names
            if n in shapes}
    results = []
    for spec in ctx_list:
        ctx = spec.get("ctx") or default_context()
        type_dict = spec.get("type_dict", {})
        # np_dtype resolves 'bfloat16' (ml_dtypes) too
        loc = {n: v.astype(np_dtype(type_dict.get(n, np.float32)))
               for n, v in base.items()}
        exe, _ = _bind(sym, loc, grad_req=grad_req, ctx=ctx,
                       dtype=None)  # keep the spec's dtypes
        outs = exe.forward_backward()  # one pass: outputs AND grads
        results.append((
            [np.asarray(o.asnumpy(), np.float64) for o in outs],
            {n: np.asarray(g.asnumpy(), np.float64)
             for n, g in exe.grad_dict.items()},
            any(np_dtype(t).itemsize < 4
                for t in type_dict.values())))
    ref_outs, ref_grads, ref_low = results[0]
    for outs, grads, lowprec in results[1:]:
        low = lowprec or ref_low  # either side low-precision
        r = 2e-2 if low else (tol or rtol)
        a = 1e-2 if low else atol
        for o, ro in zip(outs, ref_outs):
            np.testing.assert_allclose(o, ro, rtol=r, atol=a)
        for n in grads:
            np.testing.assert_allclose(grads[n], ref_grads[n],
                                       rtol=r, atol=a)
    return results
