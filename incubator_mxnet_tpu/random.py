"""mx.random: seeding + module-level sampling helpers
(ref: python/mxnet/random.py)."""
from .random_state import seed  # noqa: F401
from .ndarray.random import (uniform, normal, gamma, exponential, poisson,  # noqa: F401
                             negative_binomial,
                             generalized_negative_binomial, multinomial,
                             shuffle, randint)

__all__ = ["seed", "uniform", "normal", "gamma", "exponential", "poisson",
           "negative_binomial", "generalized_negative_binomial",
           "multinomial", "shuffle", "randint"]
