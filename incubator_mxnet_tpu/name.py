"""Name scopes for symbol auto-naming (ref: python/mxnet/name.py —
NameManager/Prefix).

``with mx.name.Prefix("stage1_"):`` prefixes every auto-generated
symbol name created in the scope; a plain ``NameManager`` scope gives
a fresh counter namespace (handy for reproducible graph JSON in
tests).  Outside any scope, naming falls back to the process-global
counters in symbol.NameManager, preserving existing behavior.
"""
import threading

__all__ = ["NameManager", "Prefix", "current"]

_state = threading.local()


def _stack():
    if not hasattr(_state, "stack"):
        _state.stack = []
    return _state.stack


class NameManager:
    """Scoped auto-namer: each instance owns its own counters."""

    def __init__(self):
        self._counters = {}

    def get(self, name, hint):
        """Explicit ``name`` wins; otherwise generate ``hint<N>``."""
        if name:
            return name
        hint = hint.lower().lstrip("_")
        idx = self._counters.get(hint, 0)
        self._counters[hint] = idx + 1
        return f"{hint}{idx}"

    def __enter__(self):
        _stack().append(self)
        return self

    def __exit__(self, *exc):
        _stack().pop()


class Prefix(NameManager):
    """NameManager that prepends a fixed prefix
    (ref: name.py Prefix)."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        if name:
            return name
        return self._prefix + super().get(None, hint)


def current():
    """The innermost active manager, or None (legacy global
    counters)."""
    stack = _stack()
    return stack[-1] if stack else None
