"""Image pipeline (ref: python/mxnet/image/ + src/io/ image stack)."""
from .image import (imdecode, imresize, resize_short, fixed_crop,
                    center_crop, random_crop, color_normalize,
                    Augmenter, ResizeAug, ForceResizeAug, CastAug,
                    HorizontalFlipAug, RandomCropAug, CenterCropAug,
                    ColorNormalizeAug, BrightnessJitterAug,
                    CreateAugmenter, ImageIter)
from .record_iter import ImageRecordIter
from .detection import (ImageDetIter, CreateDetAugmenter,
                        DetBorrowAug, DetHorizontalFlipAug,
                        DetRandomCropAug, DetRandomPadAug)

__all__ = ["imdecode", "imresize", "resize_short", "fixed_crop",
           "center_crop", "random_crop", "color_normalize",
           "Augmenter", "ResizeAug", "ForceResizeAug", "CastAug",
           "HorizontalFlipAug", "RandomCropAug", "CenterCropAug",
           "ColorNormalizeAug", "BrightnessJitterAug",
           "CreateAugmenter", "ImageIter", "ImageRecordIter",
           "ImageDetIter", "CreateDetAugmenter", "DetBorrowAug",
           "DetHorizontalFlipAug", "DetRandomCropAug",
           "DetRandomPadAug"]
