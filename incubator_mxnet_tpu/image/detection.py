"""Detection image pipeline (ref: python/mxnet/image/detection.py —
ImageDetIter:624 and the DetAug* family).

Label wire format matches the reference (detection.py:714-728): a flat
float vector ``[header_width, obj_width, <extra header...>,
obj0..objN]`` where each object is ``[cls_id, xmin, ymin, xmax, ymax,
...]`` with coordinates normalized to [0, 1].  The iterator emits a
fixed-shape (batch, max_objs, obj_width) label tensor padded with -1
rows — static shapes so the SSD target/loss step compiles once.

Augmenters transform (image HWC uint8/float, boxes (N, obj_width))
pairs so geometry stays consistent with the boxes.
"""
import random as pyrandom

import numpy as np

from ..io.io import DataBatch, DataDesc
from ..ndarray import array as nd_array
from .image import ImageIter, imresize

__all__ = ["ImageDetIter", "CreateDetAugmenter", "DetBorrowAug",
           "DetHorizontalFlipAug", "DetRandomCropAug",
           "DetRandomPadAug"]


def _parse_det_label(raw):
    """Flat label vector -> (N, obj_width) object array
    (ref: detection.py _check_valid_label/_estimate_label_shape)."""
    raw = np.asarray(raw, np.float32).ravel()
    if raw.size < 2:
        raise ValueError("detection label must start with "
                         "[header_width, obj_width]")
    header_width = int(raw[0])
    obj_width = int(raw[1])
    if header_width < 2 or obj_width < 5:
        raise ValueError(
            f"invalid detection header {raw[:2]} (need header>=2, "
            f"obj_width>=5)")
    body = raw[header_width:]
    if body.size % obj_width != 0:
        raise ValueError(
            f"label body of {body.size} not divisible by obj_width "
            f"{obj_width}")
    return body.reshape(-1, obj_width)


class DetBorrowAug:
    """Wrap an image-only augmenter (color jitter, cast...) for use in
    a detection pipeline (ref: detection.py DetBorrowAug)."""

    def __init__(self, augmenter):
        self.augmenter = augmenter

    def __call__(self, img, label):
        return self.augmenter(img), label


class DetHorizontalFlipAug:
    """Mirror the image and the x-coordinates (ref: detection.py
    DetHorizontalFlipAug)."""

    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, img, label):
        if pyrandom.random() < self.p:
            img = img[:, ::-1]
            label = label.copy()
            xmin = label[:, 1].copy()
            label[:, 1] = 1.0 - label[:, 3]
            label[:, 3] = 1.0 - xmin
        return img, label


class DetRandomCropAug:
    """IoU-constrained random crop (ref: detection.py
    DetRandomCropAug): sample a crop whose overlap with at least one
    object satisfies min_object_covered; keep objects whose centers
    fall inside; re-normalize coordinates to the crop."""

    def __init__(self, min_object_covered=0.3,
                 aspect_ratio_range=(0.75, 1.33),
                 area_range=(0.3, 1.0), max_attempts=25, p=1.0):
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.p = p

    def _coverage(self, crop, boxes):
        cx0, cy0, cx1, cy1 = crop
        ix0 = np.maximum(boxes[:, 1], cx0)
        iy0 = np.maximum(boxes[:, 2], cy0)
        ix1 = np.minimum(boxes[:, 3], cx1)
        iy1 = np.minimum(boxes[:, 4], cy1)
        inter = np.clip(ix1 - ix0, 0, None) * \
            np.clip(iy1 - iy0, 0, None)
        area = (boxes[:, 3] - boxes[:, 1]) * \
            (boxes[:, 4] - boxes[:, 2])
        return inter / np.maximum(area, 1e-12)

    def __call__(self, img, label):
        if label.shape[0] == 0 or pyrandom.random() >= self.p:
            return img, label
        h, w = img.shape[:2]
        for _ in range(self.max_attempts):
            scale = pyrandom.uniform(*self.area_range)
            ratio = pyrandom.uniform(*self.aspect_ratio_range)
            cw = min(1.0, np.sqrt(scale * ratio))
            ch = min(1.0, np.sqrt(scale / ratio))
            cx0 = pyrandom.uniform(0, 1 - cw)
            cy0 = pyrandom.uniform(0, 1 - ch)
            crop = (cx0, cy0, cx0 + cw, cy0 + ch)
            cov = self._coverage(crop, label)
            centers_x = (label[:, 1] + label[:, 3]) / 2
            centers_y = (label[:, 2] + label[:, 4]) / 2
            inside = ((centers_x > crop[0]) & (centers_x < crop[2]) &
                      (centers_y > crop[1]) & (centers_y < crop[3]))
            # acceptance requires a SURVIVING box meeting the
            # coverage bar (not merely any box), and slivers that
            # are mostly outside the crop are dropped with it
            keep = inside & (cov >= min(self.min_object_covered,
                                        0.25))
            if not (inside & (cov >= self.min_object_covered)).any():
                continue
            new = label[keep].copy()
            new[:, 1] = np.clip((new[:, 1] - crop[0]) / cw, 0, 1)
            new[:, 3] = np.clip((new[:, 3] - crop[0]) / cw, 0, 1)
            new[:, 2] = np.clip((new[:, 2] - crop[1]) / ch, 0, 1)
            new[:, 4] = np.clip((new[:, 4] - crop[1]) / ch, 0, 1)
            x0 = int(crop[0] * w)
            y0 = int(crop[1] * h)
            x1 = max(x0 + 1, int(crop[2] * w))
            y1 = max(y0 + 1, int(crop[3] * h))
            return img[y0:y1, x0:x1], new
        return img, label


class DetRandomPadAug:
    """Zoom-out: place the image on a larger canvas (ref:
    detection.py DetRandomPadAug)."""

    def __init__(self, area_range=(1.0, 4.0), fill=127, p=0.5):
        self.area_range = area_range
        self.fill = fill
        self.p = p

    def __call__(self, img, label):
        if pyrandom.random() >= self.p:
            return img, label
        h, w = img.shape[:2]
        scale = pyrandom.uniform(*self.area_range)
        side = np.sqrt(scale)
        nh, nw = int(h * side), int(w * side)
        y0 = pyrandom.randint(0, nh - h)
        x0 = pyrandom.randint(0, nw - w)
        canvas = np.full((nh, nw) + img.shape[2:], self.fill,
                         img.dtype)
        canvas[y0:y0 + h, x0:x0 + w] = img
        label = label.copy()
        label[:, 1] = (label[:, 1] * w + x0) / nw
        label[:, 3] = (label[:, 3] * w + x0) / nw
        label[:, 2] = (label[:, 2] * h + y0) / nh
        label[:, 4] = (label[:, 4] * h + y0) / nh
        return canvas, label


class _DetResizeAug:
    """Force resize to the network input (geometry-free for
    normalized boxes)."""

    def __init__(self, width, height):
        self.width = width
        self.height = height

    def __call__(self, img, label):
        return np.asarray(imresize(img, self.width, self.height)), \
            label


def CreateDetAugmenter(data_shape, resize=True, rand_crop=0.0,
                       rand_pad=0.0, rand_mirror=False, mean=None,
                       std=None, min_object_covered=0.3,
                       area_range=(0.3, 3.0)):
    """Standard detection pipeline (ref: detection.py
    CreateDetAugmenter): [crop] -> [pad] -> resize -> [mirror] ->
    normalize."""
    augs = []
    if rand_crop > 0:
        augs.append(DetRandomCropAug(
            min_object_covered=min_object_covered,
            area_range=(area_range[0], min(1.0, area_range[1])),
            p=rand_crop))
    if rand_pad > 0:
        augs.append(DetRandomPadAug(
            area_range=(1.0, max(1.0, area_range[1])), p=rand_pad))
    if resize:
        augs.append(_DetResizeAug(data_shape[2], data_shape[1]))
    if rand_mirror:
        augs.append(DetHorizontalFlipAug(0.5))
    if mean is not None or std is not None:
        mean = np.asarray(mean if mean is not None else 0.0,
                          np.float32)
        std = np.asarray(std if std is not None else 1.0, np.float32)
        augs.append(DetBorrowAug(
            lambda im: (im.astype(np.float32) - mean) / std))
    return augs


class ImageDetIter(ImageIter):
    """Detection iterator over .rec/.lst (ref: detection.py
    ImageDetIter:624): yields data (B, C, H, W) and label
    (B, max_objs, obj_width) padded with -1 rows."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root="", shuffle=False,
                 aug_list=None, data_name="data", label_name="label",
                 max_objects=None, **kwargs):
        super().__init__(batch_size, data_shape,
                         path_imgrec=path_imgrec,
                         path_imglist=path_imglist,
                         path_root=path_root, shuffle=shuffle,
                         aug_list=[],    # image augs replaced by det
                         data_name=data_name, label_name=label_name,
                         **kwargs)
        self.det_auglist = aug_list if aug_list is not None else \
            CreateDetAugmenter(data_shape)
        self._max_objs, self._obj_width = self._estimate_label_shape(
            max_objects)
        self.provide_label = [DataDesc(
            label_name,
            (batch_size, self._max_objs, self._obj_width))]

    def _estimate_label_shape(self, max_objects):
        """(max objects, obj width) for the padded label.

        With ``max_objects`` given, only the first sample is read (for
        obj_width) — the runtime overflow check in :meth:`next` is the
        safety net, so a large .rec file pays no extra full pass.
        Without it, scan the WHOLE dataset's labels (no image decode):
        a partial window would make a crowded late sample overflow the
        padded label mid-epoch (ref: detection.py
        _estimate_label_shape)."""
        max_objs, obj_width = 1, 5
        while True:
            sample = self._next_sample(decode=False)
            if sample is None:
                break
            objs = _parse_det_label(sample[0])
            max_objs = max(max_objs, objs.shape[0])
            obj_width = max(obj_width, objs.shape[1])
            if max_objects is not None:
                break       # first sample fixes obj_width; cap given
        self.reset()
        if max_objects is not None:
            # floor, not exact cap: an overcrowded first sample (which
            # was parsed anyway) must widen the padding rather than
            # fail mid-epoch in next()
            max_objs = max(max_objs, int(max_objects))
        return max_objs, obj_width

    def next(self):
        c, h, w = self.data_shape
        batch_data = np.zeros((self.batch_size, c, h, w), np.float32)
        batch_label = np.full(
            (self.batch_size, self._max_objs, self._obj_width), -1.0,
            np.float32)
        i = 0
        while i < self.batch_size:
            sample = self._next_sample()
            if sample is None:
                break
            raw, img = sample
            objs = _parse_det_label(raw)
            img = np.asarray(img)
            for aug in self.det_auglist:
                img, objs = aug(img, objs)
            if img.shape[:2] != (h, w):
                img = np.asarray(imresize(img, w, h))
            img = img.astype(np.float32)
            batch_data[i] = np.transpose(np.atleast_3d(img),
                                         (2, 0, 1))[:c]
            if objs.shape[1] != self._obj_width:
                raise ValueError(
                    f"sample has obj_width {objs.shape[1]} but the "
                    f"dataset was estimated at {self._obj_width}; "
                    "object width must be uniform")
            if objs.shape[0] > self._max_objs:
                raise ValueError(
                    f"sample has {objs.shape[0]} objects > padded "
                    f"capacity {self._max_objs}; pass "
                    "max_objects=<dataset max> to ImageDetIter")
            n = objs.shape[0]
            if n:
                batch_label[i, :n] = objs
            i += 1
        if i == 0:
            raise StopIteration
        return DataBatch([nd_array(batch_data)],
                         [nd_array(batch_label)],
                         pad=self.batch_size - i,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)
