"""ctypes wrapper for the native JPEG batch decoder (ref role:
src/io/iter_image_recordio_2.cc decode threads; see
src/imgdec/imgdec.cc).  Self-builds like the recordio backend; falls
back cleanly (``available() == False``) when g++/libjpeg are absent —
callers then use the PIL path."""
import ctypes
import os
import subprocess

import numpy as np

__all__ = ["available", "decode_batch"]

_LIB = None
_TRIED = False


def _build(src, so):
    """Compile to a temp file and os.rename into place: the rename is
    atomic on the same filesystem, so a concurrent process (multi-
    process launch, pytest-xdist) can never dlopen a half-written .so
    and two builders cannot corrupt each other (advisor r4)."""
    os.makedirs(os.path.dirname(so), exist_ok=True)
    tmp = f"{so}.tmp.{os.getpid()}"
    try:
        subprocess.run(
            ["g++", "-O2", "-fPIC", "-std=c++17", "-shared",
             "-o", tmp, src, "-ljpeg", "-lpthread"],
            check=True, capture_output=True, timeout=180)
        os.rename(tmp, so)
        return True
    except Exception:
        try:
            os.remove(tmp)
        except OSError:
            pass
        return False


def _native_lib():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # ABI-versioned filename: bumping the suffix on an ABI change makes
    # a stale cached build simply not found, instead of relying on a
    # same-path reload (glibc dedups dlopen by pathname, so re-loading
    # a rebuilt .so at the SAME path returns the old mapping)
    # v3: fork-safe thread pool (pthread_atfork re-arm) for the
    # multi-process data service's forked decode workers
    so = os.path.join(here, "lib", "libmxtpu_imgdec.v3.so")
    src = os.path.join(os.path.dirname(here), "src", "imgdec",
                       "imgdec.cc")
    if not os.path.exists(so):
        if not (os.path.exists(src) and _build(src, so)):
            return None
    try:
        lib = ctypes.CDLL(so)
        lib.imgdec_last_error.restype = ctypes.c_char_p
        lib.imgdec_batch_err.restype = ctypes.c_int
        lib.imgdec_batch_err.argtypes = [
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_float), ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int]
        _LIB = lib
    except (OSError, AttributeError):
        # AttributeError: symbol missing (a foreign/corrupt .so at the
        # versioned path) — degrade to the PIL fallback, never crash
        return None
    return _LIB


def available():
    return _native_lib() is not None


def decode_batch(raws, out_hw, resize_short=0, mirror=None,
                 mean=None, std=None, nthreads=8, out=None):
    """Decode a list of JPEG byte strings into (n, 3, H, W) float32.

    mirror: optional per-image bool array; mean/std: optional
    3-vectors applied as (px - mean) / std.  Raises on any decode
    failure (fail loudly: a corrupt record must not train as zeros).
    """
    lib = _native_lib()
    if lib is None:
        raise RuntimeError("native image decoder unavailable")
    n = len(raws)
    oh, ow = out_hw
    if out is None:
        out = np.empty((n, 3, oh, ow), np.float32)
    bufs = (ctypes.c_void_p * n)(
        *[ctypes.cast(ctypes.c_char_p(r), ctypes.c_void_p)
          for r in raws])
    sizes = (ctypes.c_int64 * n)(*[len(r) for r in raws])
    mir = None
    if mirror is not None:
        mirror = np.ascontiguousarray(mirror, np.uint8)
        mir = mirror.ctypes.data_as(ctypes.c_void_p)
    mvec = svec = None
    if mean is not None:
        mean = np.ascontiguousarray(mean, np.float32)
        mvec = mean.ctypes.data_as(ctypes.c_void_p)
    if std is not None:
        std = np.ascontiguousarray(std, np.float32)
        svec = std.ctypes.data_as(ctypes.c_void_p)
    # per-call error buffer: a concurrent iterator's next batch can't
    # clobber this batch's message (unlike the imgdec_last_error()
    # global)
    err = ctypes.create_string_buffer(512)
    failed = lib.imgdec_batch_err(
        bufs, sizes, n, oh, ow, int(resize_short), mir, mvec, svec,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        int(nthreads), err, len(err))
    if failed:
        raise ValueError(
            f"native decode failed for {failed}/{n} images: "
            f"{err.value.decode(errors='replace')}")
    return out
