"""Image utilities + augmenter zoo + python image iterator (ref:
python/mxnet/image/image.py — imdecode, resize_short, center/random
crop, Augmenter:482 zoo, ImageIter:999).

Host-side work is numpy/PIL (the reference used OpenCV); the decoded
batch lands on device once per batch, NCHW float32 — augmentation
stays off the TPU where it belongs."""
import io as _io
import os
import random as pyrandom

import numpy as np

from ..io.io import DataBatch, DataDesc, DataIter
from ..ndarray.ndarray import NDArray, array as nd_array

__all__ = ["imdecode", "imresize", "resize_short", "fixed_crop",
           "center_crop", "random_crop", "color_normalize",
           "Augmenter", "ResizeAug", "ForceResizeAug", "CastAug",
           "HorizontalFlipAug", "RandomCropAug", "CenterCropAug",
           "ColorNormalizeAug", "BrightnessJitterAug",
           "CreateAugmenter", "ImageIter"]


def imdecode(buf, to_rgb=True, flag=1):
    """Decode an encoded image buffer to HWC uint8 (ref: image.py
    imdecode; native ref: src/io/image_io.cc)."""
    from PIL import Image
    img = Image.open(_io.BytesIO(bytes(buf)))
    if flag == 0:
        img = img.convert("L")
        arr = np.asarray(img)[:, :, None]
    else:
        img = img.convert("RGB")
        arr = np.asarray(img)
        if not to_rgb:
            arr = arr[:, :, ::-1]
    return nd_array(arr)


def _to_np(img):
    return img.asnumpy() if isinstance(img, NDArray) else np.asarray(img)


def imresize(src, w, h, interp=2):
    """Bilinear resize preserving the input dtype (float pixel data
    from CastAug/ColorNormalizeAug must not be truncated to uint8)."""
    from PIL import Image
    arr = _to_np(src)
    if np.issubdtype(arr.dtype, np.floating):
        in_dtype = arr.dtype
        if arr.ndim == 2:
            arr = arr[:, :, None]
        chans = [np.asarray(
            Image.fromarray(arr[:, :, c].astype(np.float32), mode="F")
            .resize((w, h), Image.BILINEAR))
            for c in range(arr.shape[-1])]
        return nd_array(np.stack(chans, axis=-1).astype(in_dtype))
    arr = arr.astype(np.uint8)
    pil = Image.fromarray(arr.squeeze() if arr.shape[-1] == 1 else arr)
    out = np.asarray(pil.resize((w, h), Image.BILINEAR))
    if out.ndim == 2:
        out = out[:, :, None]
    return nd_array(out)


def resize_short(src, size, interp=2):
    """Resize so the shorter side == size (ref: image.py
    resize_short)."""
    h, w = _to_np(src).shape[:2]
    if h > w:
        new_w, new_h = size, int(h * size / w)
    else:
        new_w, new_h = int(w * size / h), size
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    arr = _to_np(src)[y0:y0 + h, x0:x0 + w]
    out = nd_array(arr)
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp)
    return out


def center_crop(src, size, interp=2):
    h, w = _to_np(src).shape[:2]
    new_w, new_h = size
    x0 = max((w - new_w) // 2, 0)
    y0 = max((h - new_h) // 2, 0)
    return fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h),
                      size, interp), (x0, y0, new_w, new_h)


def random_crop(src, size, interp=2):
    h, w = _to_np(src).shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = pyrandom.randint(0, w - new_w)
    y0 = pyrandom.randint(0, h - new_h)
    return fixed_crop(src, x0, y0, new_w, new_h, size, interp), \
        (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    arr = _to_np(src).astype(np.float32)
    arr = arr - np.asarray(mean, np.float32)
    if std is not None:
        arr = arr / np.asarray(std, np.float32)
    return nd_array(arr)


# ---------------------------------------------------------------------------
# augmenters (ref: image.py Augmenter:482; native ref:
# src/io/image_aug_default.cc)
# ---------------------------------------------------------------------------


class Augmenter:
    """Image augmenter base (ref: image.py Augmenter:482)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            return nd_array(_to_np(src)[:, ::-1])
        return src


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness=0.0):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.brightness,
                                       self.brightness)
        return nd_array(np.clip(_to_np(src).astype(np.float32)
                                * alpha, 0, 255))


class CastAug(Augmenter):
    def __call__(self, src):
        return nd_array(_to_np(src).astype(np.float32))


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean, self.std = mean, std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


def CreateAugmenter(data_shape, resize=0, rand_crop=False,
                    rand_mirror=False, mean=None, std=None,
                    brightness=0, **kwargs):
    """Standard augmenter chain (ref: image.py CreateAugmenter)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize))
    crop_size = (data_shape[2], data_shape[1])
    if rand_crop:
        auglist.append(RandomCropAug(crop_size))
    else:
        auglist.append(CenterCropAug(crop_size))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    if brightness:
        auglist.append(BrightnessJitterAug(brightness))
    auglist.append(CastAug())
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None and mean is not False:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


def augment_to_chw(img, auglist):
    """Run the augmenter chain and emit CHW float32 (shared by
    ImageIter and ImageRecordIter so the two pipelines can't drift)."""
    for aug in auglist:
        img = aug(img)
    return _to_np(img).transpose(2, 0, 1)


class ImageIter(DataIter):
    """Python-side image iterator over .rec or a directory + .lst
    (ref: image.py ImageIter:999)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root="",
                 shuffle=False, aug_list=None, data_name="data",
                 label_name="softmax_label", **kwargs):
        super().__init__(batch_size)
        assert path_imgrec or path_imglist
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape)
        self.shuffle = shuffle
        self._recordio = None
        self._imglist = None
        if path_imgrec:
            from .. import recordio as rio
            idx_path = os.path.splitext(path_imgrec)[0] + ".idx"
            if os.path.exists(idx_path):
                self._recordio = rio.MXIndexedRecordIO(
                    idx_path, path_imgrec, "r")
                self._seq = list(self._recordio.keys)
            else:
                if shuffle:
                    raise ValueError(
                        "shuffle=True needs an .idx next to "
                        f"{path_imgrec} for random access "
                        "(generate one with tools/im2rec.py)")
                self._recordio = rio.MXRecordIO(path_imgrec, "r")
                self._seq = None
        else:
            self._imglist = []
            with open(path_imglist) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) < 3:
                        continue
                    labels = [float(x) for x in parts[1:-1]]
                    self._imglist.append(
                        (os.path.join(path_root, parts[-1]), labels))
            self._seq = list(range(len(self._imglist)))
        self.provide_data = [DataDesc(data_name,
                                      (batch_size,) + self.data_shape)]
        lshape = (batch_size,) if label_width == 1 \
            else (batch_size, label_width)
        self.provide_label = [DataDesc(label_name, lshape)]
        self._cursor = 0
        self.reset()

    def reset(self):
        self._cursor = 0
        if self._seq is not None and self.shuffle:
            pyrandom.shuffle(self._seq)
        if self._recordio is not None and self._seq is None:
            self._recordio.reset()

    def _next_sample(self, decode=True):
        """Next (label, image) pair; ``decode=False`` skips the image
        entirely (label-only scans, e.g. detection shape
        estimation)."""
        from .. import recordio as rio
        if self._recordio is not None:
            if self._seq is not None:
                if self._cursor >= len(self._seq):
                    return None
                rec = self._recordio.read_idx(self._seq[self._cursor])
            else:
                rec = self._recordio.read()
                if rec is None:
                    return None
            self._cursor += 1
            header, img_bytes = rio.unpack(rec)
            label = header.label
            return label, imdecode(img_bytes) if decode else None
        if self._cursor >= len(self._seq):
            return None
        path, labels = self._imglist[self._seq[self._cursor]]
        self._cursor += 1
        if not decode:
            return np.asarray(labels, np.float32), None
        with open(path, "rb") as f:
            return np.asarray(labels, np.float32), imdecode(f.read())

    def next(self):
        c, h, w = self.data_shape
        batch_data = np.zeros((self.batch_size, c, h, w), np.float32)
        batch_label = np.zeros((self.batch_size, self.label_width),
                               np.float32)
        i = 0
        while i < self.batch_size:
            sample = self._next_sample()
            if sample is None:
                break
            label, img = sample
            batch_data[i] = augment_to_chw(img, self.auglist)
            lab = np.atleast_1d(np.asarray(label, np.float32))
            batch_label[i] = lab[:self.label_width]
            i += 1
        if i == 0:
            raise StopIteration
        pad = self.batch_size - i
        label_out = batch_label[:, 0] if self.label_width == 1 \
            else batch_label
        return DataBatch([nd_array(batch_data)],
                         [nd_array(label_out)], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)
